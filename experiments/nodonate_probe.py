"""Probe 7: steady-state matmul semantic kernel WITHOUT donation."""
import time

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

A = 4096
B = 8190
rng = np.random.default_rng(0)


def kernel(table, pk, acct_ledger):
    dr_slot = pk[:, 0].astype(jnp.int32)
    cr_slot = pk[:, 1].astype(jnp.int32)
    amt_lo = pk[:, 2]
    flags = pk[:, 4].astype(jnp.uint32)
    ledger = pk[:, 5].astype(jnp.uint32)
    drc = jnp.clip(dr_slot, 0, A - 1)
    crc = jnp.clip(cr_slot, 0, A - 1)
    dr_ledger = acct_ledger[drc]
    r = jnp.zeros(B, jnp.uint32)

    def app(r, cond, c):
        return jnp.where((r == 0) & cond, jnp.uint32(c), r)

    r = app(r, dr_slot < 0, 42)
    r = app(r, cr_slot < 0, 43)
    r = app(r, dr_slot == cr_slot, 12)
    r = app(r, amt_lo == 0, 20)
    r = app(r, ledger == 0, 21)
    r = app(r, acct_ledger[crc] != dr_ledger, 30)
    r = app(r, ledger != dr_ledger, 31)
    ok = r == 0
    is_pending = (flags & 2) != 0
    zero = jnp.uint64(0)
    amt_ok = jnp.where(ok, amt_lo, zero)
    P = jnp.stack(
        [((amt_ok >> jnp.uint64(s)) & jnp.uint64(0xFF)).astype(jnp.float32)
         for s in range(0, 64, 8)],
        axis=-1,
    )
    dcol = jnp.where(is_pending, 0, 1)
    ccol = jnp.where(is_pending, 2, 3)
    md = jax.nn.one_hot(dcol, 4, dtype=jnp.float32)
    mc = jax.nn.one_hot(ccol, 4, dtype=jnp.float32)
    pay = jnp.concatenate(
        [(md[:, :, None] * P[:, None, :]).reshape(B, 32),
         (mc[:, :, None] * P[:, None, :]).reshape(B, 32)],
        axis=0,
    )
    slots = jnp.concatenate([drc, crc])
    onehot = jax.nn.one_hot(slots, A, dtype=jnp.float32)
    acc = jax.lax.dot_general(
        onehot.T, pay, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).reshape(A, 4, 8).astype(jnp.uint64)
    c = acc[:, :, 0]
    d_lo = c & jnp.uint64(0xFF)
    carry = c >> jnp.uint64(8)
    for k in range(1, 8):
        c = acc[:, :, k] + carry
        d_lo = d_lo | ((c & jnp.uint64(0xFF)) << jnp.uint64(8 * k))
        carry = c >> jnp.uint64(8)
    d_hi = carry
    old_lo = table[:, 0::2]
    old_hi = table[:, 1::2]
    new_lo = old_lo + d_lo
    cy = (new_lo < old_lo).astype(jnp.uint64)
    new_hi = old_hi + d_hi + cy
    ov = ((new_hi < old_hi) | ((new_hi == old_hi) & (new_lo < old_lo))).any()
    nt = jnp.stack(
        [new_lo[:, 0], new_hi[:, 0], new_lo[:, 1], new_hi[:, 1],
         new_lo[:, 2], new_hi[:, 2], new_lo[:, 3], new_hi[:, 3]], axis=-1)
    table = jnp.where(ov, table, nt)
    return table, jnp.where(ov, jnp.uint32(0xFFFF), r)


jf = jax.jit(kernel)  # NO donation


def fresh():
    dr = rng.integers(0, 1000, B).astype(np.int64)
    packed = np.zeros((B, 6), np.uint64)
    packed[:, 0] = dr
    packed[:, 1] = (dr + 1) % 1000
    packed[:, 2] = rng.integers(1, 100, B)
    packed[:, 5] = 1
    return packed


acct_ledger = jnp.ones(A, jnp.uint32)
table = jnp.zeros((A, 8), jnp.uint64)
table, res = jf(table, jnp.asarray(fresh()), acct_ledger)
jax.block_until_ready(res)

for W in (4, 16, 64):
    table = jnp.zeros((A, 8), jnp.uint64)
    pend = []
    n = 120
    t0 = time.perf_counter()
    for i in range(n):
        pk = jnp.asarray(fresh())
        table, res = jf(table, pk, acct_ledger)
        res.copy_to_host_async()
        pend.append(res)
        if len(pend) > W:
            np.asarray(pend.pop(0))
    for r_ in pend:
        np.asarray(r_)
    ms = (time.perf_counter() - t0) / n * 1e3
    print(f"no-donate W={W:3d}: {ms:7.2f} ms/batch -> {B/(ms/1e3):,.0f} ev/s")

# sync-each variant (depth 1)
table = jnp.zeros((A, 8), jnp.uint64)
t0 = time.perf_counter()
for i in range(30):
    pk = jnp.asarray(fresh())
    table, res = jf(table, pk, acct_ledger)
    np.asarray(res)
ms = (time.perf_counter() - t0) / 30 * 1e3
print(f"no-donate sync each: {ms:7.2f} ms/batch -> {B/(ms/1e3):,.0f} ev/s")
