"""r5: ablation of _orderfree cost: ladder / accum / summary."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp
from tigerbeetle_tpu.state_machine import device_kernels as dk

A = 1 << 12
B = dk.B
rng = np.random.default_rng(0)
n = B
dr = rng.integers(0, 1000, n)
pk = dk.pack_base(
    n,
    id_lo=np.arange(1, n + 1, dtype=np.uint64), id_hi=np.zeros(n, np.uint64),
    dr_lo=dr.astype(np.uint64) + 1, dr_hi=np.zeros(n, np.uint64),
    cr_lo=(dr.astype(np.uint64) % 1000) + 2, cr_hi=np.zeros(n, np.uint64),
    pend_lo=np.zeros(n, np.uint64), pend_hi=np.zeros(n, np.uint64),
    amount_lo=rng.integers(1, 100, n).astype(np.uint64),
    amount_hi=np.zeros(n, np.uint64),
    flags=np.zeros(n, np.uint32), ledger=np.ones(n, np.uint32),
    code=np.ones(n, np.uint32), timeout=np.zeros(n, np.uint32),
    ts_nonzero=np.zeros(n, bool),
    dr_slot=dr.astype(np.int64), cr_slot=((dr + 1) % 1000).astype(np.int64),
    e_found=np.zeros(n, bool),
)
pkj = jax.device_put(pk)
meta = jnp.ones((A, 2), jnp.uint32)
table0 = jnp.zeros((A, 8), jnp.uint64)
ring0 = jnp.zeros((256, dk.SUMMARY_WORDS), jnp.uint64)


def variant(which):
    def f(table, ring, ring_at, pk, n, ts_base):
        ev = dk._unpack(pk)
        iota = jnp.arange(B, dtype=jnp.int64)
        active = iota < n
        if which in ("full", "noaccum", "nosummary", "ladder_only"):
            r = dk._static_ladder_normal(ev, meta, active)
        else:
            r = jnp.where(active, jnp.uint32(0), jnp.uint32(1))
        ts_i = ts_base + iota.astype(jnp.uint64)
        expires = ts_i + ev["timeout"] * dk.NS_PER_S
        ov_timeout = (ev["timeout"] != 0) & (expires < ts_i)
        r = jnp.where((r == 0) & ov_timeout, jnp.uint32(62), r)
        ok = active & (r == 0)
        if which in ("full", "nosummary", "accum_only"):
            is_pending = (ev["flags"] & dk.F_PENDING) != 0
            dcol = jnp.where(is_pending, 0, 1)
            ccol = jnp.where(is_pending, 2, 3)
            slot_rows = jnp.concatenate([ev["dr_slot"], ev["cr_slot"]])
            col_rows = jnp.concatenate([dcol, ccol])
            amt_lo2 = jnp.concatenate([ev["amt_lo"]] * 2)
            amt_hi2 = jnp.concatenate([ev["amt_hi"]] * 2)
            valid = jnp.concatenate([ok, ok])
            d_lo, d_hi, limb_ov = dk._accum_cols(
                slot_rows, col_rows, amt_lo2, amt_hi2, valid, A, lo_only=True
            )
            table, ov = dk._admit_apply(table, d_lo, d_hi, limb_ov)
        else:
            ov = jnp.bool_(False)
        if which in ("full", "noaccum", "ladder_only"):
            applied_idx = jnp.where(ok, iota, -1)
            last_applied = applied_idx.max()
            fw = jnp.where(ov, jnp.uint64(dk.FLAG_OVERFLOW), jnp.uint64(0))
            s = dk._summary(r, active, fw, last_applied)
            ring = jax.lax.dynamic_update_slice(ring, s[None, :], (ring_at, 0))
        return table, ring

    return jax.jit(f)


for which in ("full", "noaccum", "nosummary", "ladder_only", "accum_only"):
    fn = variant(which)
    t, r = fn(table0, ring0, 0, pkj, n, jnp.uint64(1))
    jax.block_until_ready((t, r))
    K = 32
    t0 = time.perf_counter()
    t2, r2 = table0, ring0
    for k in range(K):
        t2, r2 = fn(t2, r2, k % 256, pkj, n, jnp.uint64(1))
    jax.block_until_ready((t2, r2))
    dt = time.perf_counter() - t0
    print(f"{which:12s}: {dt/K*1e3:6.2f} ms/batch")
