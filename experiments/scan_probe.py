"""r5: (a) engine-pattern dispatch (fresh h2d per G batches) with the
production kernel; (b) lax.scan over G batches in one call."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp
from tigerbeetle_tpu.state_machine import device_kernels as dk

A = 1 << 12
rng = np.random.default_rng(0)
n = dk.B
dr = rng.integers(0, 1000, n)
pk = dk.pack_base(
    n,
    id_lo=np.arange(1, n + 1, dtype=np.uint64), id_hi=np.zeros(n, np.uint64),
    dr_lo=dr.astype(np.uint64) + 1, dr_hi=np.zeros(n, np.uint64),
    cr_lo=(dr.astype(np.uint64) % 1000) + 2, cr_hi=np.zeros(n, np.uint64),
    pend_lo=np.zeros(n, np.uint64), pend_hi=np.zeros(n, np.uint64),
    amount_lo=rng.integers(1, 100, n).astype(np.uint64),
    amount_hi=np.zeros(n, np.uint64),
    flags=np.zeros(n, np.uint32), ledger=np.ones(n, np.uint32),
    code=np.ones(n, np.uint32), timeout=np.zeros(n, np.uint32),
    ts_nonzero=np.zeros(n, bool),
    dr_slot=dr.astype(np.int64), cr_slot=((dr + 1) % 1000).astype(np.int64),
    e_found=np.zeros(n, bool),
)
G = 8
buf = np.tile(pk, (G, 1))
balances = jnp.zeros((A, 8), jnp.uint64)
meta = jnp.ones((A, 2), jnp.uint32)
ring = jnp.zeros((256, dk.SUMMARY_WORDS), jnp.uint64)

# (a) engine pattern: fresh device_put per G dispatches.
kern = dk.orderfree_lo_staged
sup = jax.device_put(buf)
b, r = kern(balances, meta, ring, 0, sup, 0, n, jnp.uint64(1))
jax.block_until_ready(r)
K = 64
t0 = time.perf_counter()
b2, r2 = balances, ring
for k in range(K):
    if k % G == 0:
        sup = jax.device_put(buf)
    b2, r2 = kern(b2, meta, r2, k % 256, sup, k % G, n, jnp.uint64(1))
np.asarray(r2)
dt = time.perf_counter() - t0
print(f"engine-pattern: {dt/K*1e3:.2f} ms/batch -> {n/(dt/K):,.0f} ev/s")

# (b) scan over G batches in one jitted call.
from functools import partial

def scan_g(table, ring, ring_at0, sup, ns, ts_bases):
    def step(carry, xs):
        table, ring = carry
        g, nn, tsb = xs
        pk_g = jax.lax.dynamic_slice(
            sup, (g * dk.B, 0), (dk.B, dk.N_COLS)
        )
        table, ring = dk._orderfree(
            table, meta, ring, ring_at0 + g, pk_g, nn, tsb, lo_only=True
        )
        return (table, ring), None

    (table, ring), _ = jax.lax.scan(
        step, (table, ring),
        (jnp.arange(G), ns, ts_bases),
    )
    return table, ring

jscan = jax.jit(scan_g)
ns = jnp.full(G, n)
tsb = jnp.arange(G, dtype=jnp.uint64)
sup = jax.device_put(buf)
b, r = jscan(balances, ring, 0, sup, ns, tsb)
jax.block_until_ready(r)
t0 = time.perf_counter()
b2, r2 = balances, ring
for k in range(K // G):
    sup = jax.device_put(buf)
    b2, r2 = jscan(b2, r2, (k * G) % 128, sup, ns, tsb)
np.asarray(r2)
dt = time.perf_counter() - t0
print(f"scan-G={G}:      {dt/K*1e3:.2f} ms/batch -> {n/(dt/K):,.0f} ev/s")
