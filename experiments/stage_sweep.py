"""r5: sweep superbatch depth G (one h2d per G batches) x fetch cadence
R with the production orderfree_lo kernel."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp
from tigerbeetle_tpu.state_machine import device_kernels as dk

A = 1 << 12
rng = np.random.default_rng(0)
n = dk.B
dr = rng.integers(0, 1000, n)
pk = dk.pack_base(
    n,
    id_lo=np.arange(1, n + 1, dtype=np.uint64), id_hi=np.zeros(n, np.uint64),
    dr_lo=dr.astype(np.uint64) + 1, dr_hi=np.zeros(n, np.uint64),
    cr_lo=(dr.astype(np.uint64) % 1000) + 2, cr_hi=np.zeros(n, np.uint64),
    pend_lo=np.zeros(n, np.uint64), pend_hi=np.zeros(n, np.uint64),
    amount_lo=rng.integers(1, 100, n).astype(np.uint64),
    amount_hi=np.zeros(n, np.uint64),
    flags=np.zeros(n, np.uint32), ledger=np.ones(n, np.uint32),
    code=np.ones(n, np.uint32), timeout=np.zeros(n, np.uint32),
    ts_nonzero=np.zeros(n, bool),
    dr_slot=dr.astype(np.int64), cr_slot=((dr + 1) % 1000).astype(np.int64),
    e_found=np.zeros(n, bool),
)
meta = jnp.ones((A, 2), jnp.uint32)
kern = dk.orderfree_lo_staged

for G, R in ((8, 128), (16, 128), (32, 128), (64, 128), (32, 64), (64, 64)):
    buf = np.tile(pk, (G, 1))
    balances = jnp.zeros((A, 8), jnp.uint64)
    ring = jnp.zeros((256, dk.SUMMARY_WORDS), jnp.uint64)
    sup = jax.device_put(buf)
    b, r = kern(balances, meta, ring, 0, sup, 0, n, jnp.uint64(1))
    jax.block_until_ready(r)
    K = 2 * R
    t0 = time.perf_counter()
    b2, r2 = balances, ring
    k = 0
    for i in range(K):
        if i % G == 0:
            sup = jax.device_put(buf)
        b2, r2 = kern(b2, meta, r2, k, sup, i % G, n, jnp.uint64(1))
        k += 1
        if k == R:
            np.asarray(r2)
            k = 0
    if k:
        np.asarray(r2)
    dt = time.perf_counter() - t0
    print(f"G={G:2d} R={R:3d}: {dt/K*1e3:6.2f} ms/batch -> "
          f"{n/(dt/K):,.0f} ev/s")
