"""Compile + time the three device kernels on the real TPU."""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, "/root/repo")

from tigerbeetle_tpu.state_machine import device_kernels as dk

A = 4096
R = 64
rng = np.random.default_rng(0)
Bk = dk.B


def base_pack(n, dr_slot, cr_slot, amt, flags=None, n_cols=dk.N_COLS,
              p_found=None, p_tgt=None):
    z = np.zeros(n, np.uint64)
    ids = np.arange(1, n + 1, dtype=np.uint64)
    dr_s = np.asarray(dr_slot, np.int64)
    cr_s = np.asarray(cr_slot, np.int64)
    return dk.pack_base(
        n, id_lo=ids, id_hi=z,
        dr_lo=np.where(dr_s < 0, 0, dr_s + 100).astype(np.uint64), dr_hi=z,
        cr_lo=np.where(cr_s < 0, 0, cr_s + 100).astype(np.uint64), cr_hi=z,
        pend_lo=z, pend_hi=z,
        amount_lo=np.asarray(amt, np.uint64), amount_hi=z,
        flags=np.zeros(n, np.uint32) if flags is None else np.asarray(
            flags, np.uint32),
        ledger=np.ones(n, np.uint32), code=np.ones(n, np.uint32),
        timeout=np.zeros(n, np.uint32), ts_nonzero=np.zeros(n, bool),
        dr_slot=dr_s, cr_slot=cr_s,
        e_found=np.zeros(n, bool), p_found=p_found, p_tgt=p_tgt,
        n_cols=n_cols,
    )


n = Bk
dr = rng.integers(0, 1000, n).astype(np.int64)
cr = (dr + 1) % 1000
amt = rng.integers(1, 100, n)

table = jnp.zeros((A, 8), jnp.uint64)
meta_np = np.zeros((A, 2), np.uint32)
meta_np[:1000, 1] = 1
meta = jnp.asarray(meta_np)
ring = jnp.zeros((R, dk.SUMMARY_WORDS), jnp.uint64)

for name, fn, mk in (
    ("orderfree", dk.orderfree, lambda: base_pack(n, dr, cr, amt)),
    (
        "linked",
        dk.linked,
        lambda: base_pack(
            n, dr, cr, amt,
            flags=np.where(np.arange(n) % 4 != 3, dk.F_LINKED, 0).astype(
                np.uint32
            ),
        ),
    ),
    (
        "two_phase",
        dk.two_phase,
        lambda: dk.pack_two_phase_ext(
            base_pack(
                n, np.where(np.arange(n) % 2 == 0, dr, -1),
                np.where(np.arange(n) % 2 == 0, cr, -1),
                np.where(np.arange(n) % 2 == 0, amt, 0),
                flags=np.where(
                    np.arange(n) % 2 == 0, dk.F_PENDING, dk.F_POST
                ).astype(np.uint32),
                n_cols=dk.N_COLS_TP,
                p_found=np.zeros(n, bool),
                p_tgt=np.full(n, -1, np.int64),
            ),
            n,
            bits_extra_mask=np.zeros(n, np.uint64),
            p_flags=np.zeros(n, np.uint16), p_code=np.zeros(n, np.uint16),
            p_ledger=np.zeros(n, np.uint32),
            p_dr_slot=np.full(n, -1, np.int64),
            p_cr_slot=np.full(n, -1, np.int64),
            p_amt_lo=np.zeros(n, np.uint64), p_amt_hi=np.zeros(n, np.uint64),
            tgt_ev=np.where(
                np.arange(n) % 2 == 1, np.arange(n) - 1, -1
            ).astype(np.int64),
            dstat_init_ev=np.zeros(n, np.uint32),
        ),
    ),
):
    pk = jnp.asarray(mk())
    t0 = time.perf_counter()
    try:
        t2, r2 = fn(table, meta, ring, 0, pk, n, jnp.uint64(1000))
        jax.block_until_ready(r2)
    except Exception as e:
        print(f"{name}: COMPILE/RUN FAILED: {str(e)[:300]}")
        continue
    compile_s = time.perf_counter() - t0
    s = dk.unpack_summary(np.asarray(r2)[0])
    # pipelined rate with device-resident input
    tbl = table
    t0 = time.perf_counter()
    N = 30
    for i in range(N):
        tbl, r2 = fn(tbl, meta, ring, i % R, pk, n, jnp.uint64(1000 + i * n))
    jax.block_until_ready(r2)
    ms = (time.perf_counter() - t0) / N * 1e3
    print(
        f"{name}: compile {compile_s:.1f}s  {ms:6.2f} ms/batch -> "
        f"{n/(ms/1e3):,.0f} ev/s  n_fail={s['n_fail']} "
        f"precond={s['precond']} iters={s['iters']}"
    )
