"""Characterize the tunneled TPU link: h2d/d2h latency vs size, async
transfer overlap, and compute-only time for the candidate kernel."""
import sys
import time

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

dev = jax.devices()[0]
print("device:", dev, file=sys.stderr)


def timeit(fn, n=10):
    fn()  # warm
    t0 = time.perf_counter()
    for _ in range(n):
        fn()
    return (time.perf_counter() - t0) / n * 1e3


# --- h2d by size (one array per transfer)
for nbytes in (4096, 32 << 10, 256 << 10, 1 << 20, 8 << 20):
    a = np.zeros(nbytes // 8, np.uint64)
    ms = timeit(lambda: jax.block_until_ready(jax.device_put(a, dev)))
    print(f"h2d {nbytes>>10:6d} KiB: {ms:8.2f} ms")

# --- d2h by size
for nbytes in (4096, 32 << 10, 256 << 10, 1 << 20, 8 << 20):
    a = jax.block_until_ready(
        jax.device_put(np.zeros(nbytes // 8, np.uint64), dev)
    )
    ms = timeit(lambda: np.asarray(a))
    print(f"d2h {nbytes>>10:6d} KiB: {ms:8.2f} ms")

# --- d2h with async start then fetch
a = jax.block_until_ready(jax.device_put(np.zeros(4096, np.uint64), dev))
b = jax.block_until_ready(jax.device_put(np.zeros(4096, np.uint64), dev))


def async_pair():
    a.copy_to_host_async()
    b.copy_to_host_async()
    np.asarray(a)
    np.asarray(b)


ms = timeit(async_pair)
print(f"d2h 2x32KiB async-overlap: {ms:8.2f} ms (vs 2x sequential)")

# --- many small d2h in flight at once
arrs = [
    jax.block_until_ready(jax.device_put(np.zeros(4096, np.uint64), dev))
    for _ in range(16)
]


def async_16():
    for x in arrs:
        x.copy_to_host_async()
    for x in arrs:
        np.asarray(x)


ms = timeit(async_16, n=5)
print(f"d2h 16x32KiB async-overlap: {ms:8.2f} ms total -> {ms/16:.2f} ms each")

# --- dispatch+compute only (no fetch): trivial kernel chain
@jax.jit
def bump(t):
    return t + jnp.uint64(1)

t = jax.block_until_ready(jax.device_put(np.zeros((4096, 8), np.uint64), dev))


def chain():
    global t
    for _ in range(10):
        t = bump(t)
    jax.block_until_ready(t)


ms = timeit(chain, n=5)
print(f"10 chained trivial dispatches: {ms:8.2f} ms -> {ms/10:.2f} ms/dispatch")
