"""Probe 9: true d2h cost of COMPUTED arrays by size; ring-buffer
result collection pattern."""
import time

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

A = 4096
B = 8190
rng = np.random.default_rng(0)


# --- true d2h: compute fresh data on device, block, then fetch
@jax.jit
def gen(x, salt):
    return x * salt + jnp.uint64(1)


for size in (4 << 10, 64 << 10, 512 << 10, 4 << 20):
    n = size // 8
    x = jax.block_until_ready(jnp.arange(n, dtype=jnp.uint64))
    outs = []
    for s in range(6):
        y = jax.block_until_ready(gen(x, jnp.uint64(s + 1)))
        t0 = time.perf_counter()
        np.asarray(y)
        outs.append(time.perf_counter() - t0)
    ms = np.median(outs) * 1e3
    print(f"d2h computed {size>>10:5d}KB: {ms:8.2f} ms "
          f"({size/1e6/(ms/1e3):6.1f} MB/s)")


# --- ring-buffer collection: kernel appends results to (K,B) device
# buffer; single fetch every K batches.
@jax.jit
def chain_ring(table, ring, k, x):
    s = x.sum(axis=0)
    table = table + s[None, :2]
    res = x[:, 0].astype(jnp.uint32)
    ring = jax.lax.dynamic_update_slice(ring, res[None, :], (k, 0))
    return table, ring


def fresh():
    return rng.integers(0, 1 << 20, (B, 6)).astype(np.uint64)


for K in (8, 16, 32):
    table = jnp.zeros((A, 2), jnp.uint64)
    ring = jnp.zeros((K, B), jnp.uint32)
    jax.block_until_ready(chain_ring(table, ring, 0, jnp.asarray(fresh())))
    table = jnp.zeros((A, 2), jnp.uint64)
    ring = jnp.zeros((K, B), jnp.uint32)
    N = 96
    t0 = time.perf_counter()
    k = 0
    for i in range(N):
        table, ring = chain_ring_call = chain_ring(
            table, ring, k, jnp.asarray(fresh())
        )
        k += 1
        if k == K:
            np.asarray(ring)  # one fetch for K batches
            k = 0
    if k:
        np.asarray(ring)
    ms = (time.perf_counter() - t0) / N * 1e3
    print(f"ring K={K:3d}: {ms:7.2f} ms/batch -> {B/(ms/1e3):,.0f} ev/s")

# --- ring + async: fetch ring K/2 batches after rotation via second buffer
for K in (16, 32):
    table = jnp.zeros((A, 2), jnp.uint64)
    ring = jnp.zeros((K, B), jnp.uint32)
    jax.block_until_ready(chain_ring(table, ring, 0, jnp.asarray(fresh())))
    table = jnp.zeros((A, 2), jnp.uint64)
    ring = jnp.zeros((K, B), jnp.uint32)
    N = 96
    t0 = time.perf_counter()
    k = 0
    pending_ring = None
    for i in range(N):
        table, ring = chain_ring(table, ring, k, jnp.asarray(fresh()))
        k += 1
        if k == K:
            if pending_ring is not None:
                np.asarray(pending_ring)  # fetch PREVIOUS full ring
            pending_ring = ring
            pending_ring.copy_to_host_async()
            ring = jnp.zeros((K, B), jnp.uint32)
            k = 0
    if pending_ring is not None:
        np.asarray(pending_ring)
    np.asarray(ring)
    ms = (time.perf_counter() - t0) / N * 1e3
    print(f"ring-async K={K:3d}: {ms:7.2f} ms/batch -> {B/(ms/1e3):,.0f} ev/s")
