"""r5: per-tree grid byte attribution for the durable config."""
import os, sys
sys.path.insert(0, "/root/repo")
os.environ.setdefault("BENCH_SMALL", "1")
import numpy as np
import bench
from tigerbeetle_tpu.lsm import tree as tree_mod

by_tree = {}
orig_write_run = tree_mod.Tree._write_run
orig_write_one = tree_mod.Tree._write_one_block

def patch(name, orig):
    def wrapped(self, keys, flags, vals):
        out = orig(self, keys, flags, vals)
        entry = keys.dtype.itemsize + flags.dtype.itemsize + (
            vals.dtype.itemsize if vals.ndim == 1 else vals.shape[1]
        )
        key = (getattr(self, "name", None) or f"tree{self.tree_id}", name)
        by_tree[key] = by_tree.get(key, 0) + len(keys) * entry
        return out
    return wrapped

tree_mod.Tree._write_run = patch("seal", orig_write_run)
tree_mod.Tree._write_one_block = patch("compact", orig_write_one)

N = int(os.environ.get("WA_N", "200000"))
out = bench.run_durable(N)
print({k: v for k, v in out.items() if "bytes" in k or k in ("events_per_sec",)})
total = sum(by_tree.values())
for (tname, phase), b in sorted(by_tree.items(), key=lambda kv: -kv[1]):
    print(f"{tname:24s} {phase:8s} {b/N:8.1f} B/ev  {b/1e6:8.1f} MB")
print(f"{'TOTAL tree writes':33s} {total/N:8.1f} B/ev")
