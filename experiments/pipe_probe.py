"""Probe 3: sustained async dispatch rate, kernel-variant compute cost,
and latency hiding via copy_to_host_async + host-side delay."""
import sys
import time

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

A = 4096
B = 8190
MASK32 = jnp.uint64(0xFFFFFFFF)
dev = jax.devices()[0]


@jax.jit
def trivial(t):
    return t + jnp.uint64(1)


def ladder_only(table, dr_slot, cr_slot, amt_lo, amt_hi, flags, ledger,
                acct_ledger):
    drc = jnp.clip(dr_slot, 0, A - 1)
    crc = jnp.clip(cr_slot, 0, A - 1)
    dr_ledger = acct_ledger[drc]
    cr_ledger = acct_ledger[crc]
    r = jnp.zeros(B, jnp.uint32)

    def app(r, cond, c):
        return jnp.where((r == 0) & cond, jnp.uint32(c), r)

    r = app(r, dr_slot < 0, 42)
    r = app(r, cr_slot < 0, 43)
    r = app(r, dr_slot == cr_slot, 12)
    r = app(r, (amt_lo == 0) & (amt_hi == 0), 20)
    r = app(r, ledger == 0, 21)
    r = app(r, dr_ledger != cr_ledger, 30)
    r = app(r, ledger != dr_ledger, 31)
    return r


def scatter8(table, dr_slot, cr_slot, amt_lo, amt_hi, flags, ledger,
             acct_ledger):
    r = ladder_only(table, dr_slot, cr_slot, amt_lo, amt_hi, flags, ledger,
                    acct_ledger)
    ok = r == 0
    is_pending = (flags & 2) != 0
    drc = jnp.clip(dr_slot, 0, A - 1)
    crc = jnp.clip(cr_slot, 0, A - 1)
    zero = jnp.uint64(0)
    l0 = jnp.where(ok, amt_lo & MASK32, zero)
    l1 = jnp.where(ok, amt_lo >> jnp.uint64(32), zero)
    dcol = jnp.where(is_pending, 0, 1)
    ccol = jnp.where(is_pending, 2, 3)
    acc = jnp.zeros((A, 4, 2), jnp.uint64)
    acc = acc.at[drc, dcol, 0].add(l0, mode="drop")
    acc = acc.at[drc, dcol, 1].add(l1, mode="drop")
    acc = acc.at[crc, ccol, 0].add(l0, mode="drop")
    acc = acc.at[crc, ccol, 1].add(l1, mode="drop")
    c0 = acc[:, :, 0]
    c1 = acc[:, :, 1] + (c0 >> jnp.uint64(32))
    d_lo = (c0 & MASK32) | ((c1 & MASK32) << jnp.uint64(32))
    old_lo = table[:, 0::2]
    new_lo = old_lo + d_lo
    ov = (new_lo < old_lo).any()
    table = jnp.where(ov, table, table.at[:, 0::2].set(new_lo))
    return table, jnp.where(ov, jnp.uint32(0xFFFF), r)


def scatter_vec(table, dr_slot, cr_slot, amt_lo, amt_hi, flags, ledger,
                acct_ledger):
    """One scatter with vector payload (2B, 4) limbs."""
    r = ladder_only(table, dr_slot, cr_slot, amt_lo, amt_hi, flags, ledger,
                    acct_ledger)
    ok = r == 0
    is_pending = (flags & 2) != 0
    drc = jnp.clip(dr_slot, 0, A - 1)
    crc = jnp.clip(cr_slot, 0, A - 1)
    zero = jnp.uint64(0)
    limbs = jnp.stack(
        [
            jnp.where(ok, amt_lo & MASK32, zero),
            jnp.where(ok, amt_lo >> jnp.uint64(32), zero),
            jnp.where(ok, amt_hi & MASK32, zero),
            jnp.where(ok, amt_hi >> jnp.uint64(32), zero),
        ],
        axis=-1,
    )
    dcol = jnp.where(is_pending, 0, 1)
    ccol = jnp.where(is_pending, 2, 3)
    idx = jnp.concatenate([drc * 4 + dcol, crc * 4 + ccol])
    payload = jnp.concatenate([limbs, limbs])
    acc = jnp.zeros((A * 4, 4), jnp.uint64).at[idx].add(payload)
    c0 = acc[:, 0]
    c1 = acc[:, 1] + (c0 >> jnp.uint64(32))
    c2 = acc[:, 2] + (c1 >> jnp.uint64(32))
    c3 = acc[:, 3] + (c2 >> jnp.uint64(32))
    d_lo = ((c0 & MASK32) | ((c1 & MASK32) << jnp.uint64(32))).reshape(A, 4)
    d_hi = ((c2 & MASK32) | ((c3 & MASK32) << jnp.uint64(32))).reshape(A, 4)
    old_lo = table[:, 0::2]
    old_hi = table[:, 1::2]
    new_lo = old_lo + d_lo
    carry = (new_lo < old_lo).astype(jnp.uint64)
    new_hi = old_hi + d_hi + carry
    ov = ((new_hi < old_hi).any()) | ((c3 >> jnp.uint64(32)) != 0).any()
    nt = jnp.stack(
        [new_lo[:, 0], new_hi[:, 0], new_lo[:, 1], new_hi[:, 1],
         new_lo[:, 2], new_hi[:, 2], new_lo[:, 3], new_hi[:, 3]], axis=-1)
    table = jnp.where(ov, table, nt)
    return table, jnp.where(ov, jnp.uint32(0xFFFF), r)


def sortseg(table, dr_slot, cr_slot, amt_lo, amt_hi, flags, ledger,
            acct_ledger):
    """Sort by (slot,col) key + segmented cumsum + unique scatter."""
    r = ladder_only(table, dr_slot, cr_slot, amt_lo, amt_hi, flags, ledger,
                    acct_ledger)
    ok = r == 0
    is_pending = (flags & 2) != 0
    drc = jnp.clip(dr_slot, 0, A - 1)
    crc = jnp.clip(cr_slot, 0, A - 1)
    zero = jnp.uint64(0)
    dcol = jnp.where(is_pending, 0, 1)
    ccol = jnp.where(is_pending, 2, 3)
    idx = jnp.concatenate([drc * 4 + dcol, crc * 4 + ccol]).astype(jnp.int32)
    l0 = jnp.where(ok, amt_lo & MASK32, zero)
    l1 = jnp.where(ok, amt_lo >> jnp.uint64(32), zero)
    l2 = jnp.where(ok, amt_hi & MASK32, zero)
    l3 = jnp.where(ok, amt_hi >> jnp.uint64(32), zero)
    key, p0, p1, p2, p3 = jax.lax.sort(
        [idx, jnp.concatenate([l0, l0]), jnp.concatenate([l1, l1]),
         jnp.concatenate([l2, l2]), jnp.concatenate([l3, l3])],
        num_keys=1,
    )
    m = key.shape[0]
    seg_end = jnp.concatenate(
        [key[1:] != key[:-1], jnp.ones(1, bool)]
    )
    out = []
    for p in (p0, p1, p2, p3):
        cs = jnp.cumsum(p)
        out.append(cs)
    # segment totals at segment ends: total = cs[end] - cs[prev_end]
    ends = jnp.where(seg_end, jnp.arange(m), -1)
    # scatter unique: use key at ends
    acc = jnp.zeros((A * 4, 4), jnp.uint64)
    prev = [jnp.where(seg_end, c, 0) for c in out]
    # exclusive totals per segment: cs at end minus cs at previous seg end
    # previous seg end cumsum: use segment-start gather
    seg_start = jnp.concatenate([jnp.ones(1, bool), key[1:] != key[:-1]])
    start_idx = jnp.where(seg_start, jnp.arange(m), 0)
    start_idx = jax.lax.associative_scan(jnp.maximum, start_idx)
    sums = [
        c - jnp.take(c, start_idx) + p
        for c, p in zip(out, (p0, p1, p2, p3))
    ]
    for k, s in enumerate(sums):
        acc = acc.at[key, k].set(
            jnp.where(seg_end, s, acc[key, k]), mode="drop",
            unique_indices=False,
        )
    c0, c1, c2, c3 = acc[:, 0], acc[:, 1], acc[:, 2], acc[:, 3]
    c1 = c1 + (c0 >> jnp.uint64(32))
    c2 = c2 + (c1 >> jnp.uint64(32))
    c3 = c3 + (c2 >> jnp.uint64(32))
    d_lo = ((c0 & MASK32) | ((c1 & MASK32) << jnp.uint64(32))).reshape(A, 4)
    d_hi = ((c2 & MASK32) | ((c3 & MASK32) << jnp.uint64(32))).reshape(A, 4)
    old_lo = table[:, 0::2]
    old_hi = table[:, 1::2]
    new_lo = old_lo + d_lo
    carry = (new_lo < old_lo).astype(jnp.uint64)
    new_hi = old_hi + d_hi + carry
    ov = (new_hi < old_hi).any()
    nt = jnp.stack(
        [new_lo[:, 0], new_hi[:, 0], new_lo[:, 1], new_hi[:, 1],
         new_lo[:, 2], new_hi[:, 2], new_lo[:, 3], new_hi[:, 3]], axis=-1)
    table = jnp.where(ov, table, nt)
    return table, jnp.where(ov, jnp.uint32(0xFFFF), r)


rng = np.random.default_rng(0)
dr = rng.integers(0, 1000, B).astype(np.int32)
inputs = dict(
    dr_slot=jnp.asarray(dr),
    cr_slot=jnp.asarray(((dr + 1) % 1000).astype(np.int32)),
    amt_lo=jnp.asarray(rng.integers(1, 100, B, np.uint64)),
    amt_hi=jnp.zeros(B, jnp.uint64),
    flags=jnp.zeros(B, jnp.uint32),
    ledger=jnp.ones(B, jnp.uint32),
)
acct_ledger = jnp.ones(A, jnp.uint32)


def sustained(fn, name, n=100):
    table = jnp.zeros((A, 8), jnp.uint64)
    jf = jax.jit(fn, donate_argnums=(0,))
    table, res = jf(table, acct_ledger=acct_ledger, **inputs)
    jax.block_until_ready(res)
    t0 = time.perf_counter()
    last = None
    for _ in range(n):
        table, last = jf(table, acct_ledger=acct_ledger, **inputs)
    jax.block_until_ready(last)
    ms = (time.perf_counter() - t0) / n * 1e3
    print(f"{name:12s}: {ms:6.2f} ms/batch -> {B/(ms/1e3):,.0f} ev/s")
    return ms


# trivial dispatch rate
t = jnp.zeros((A, 8), jnp.uint64)
jax.block_until_ready(trivial(t))
t0 = time.perf_counter()
for _ in range(200):
    t = trivial(t)
jax.block_until_ready(t)
ms = (time.perf_counter() - t0) / 200 * 1e3
print(f"trivial      : {ms:6.2f} ms/dispatch")

sustained(scatter8, "scatter8(lo)")
sustained(scatter_vec, "scatter_vec")
sustained(sortseg, "sortseg")

# --- latency hiding: dispatch, host work X ms, then fetch
jf = jax.jit(scatter_vec, donate_argnums=(0,))
table = jnp.zeros((A, 8), jnp.uint64)
table, res = jf(table, acct_ledger=acct_ledger, **inputs)
jax.block_until_ready(res)
for delay in (0.0, 0.05, 0.15, 0.3):
    fetches = []
    for _ in range(5):
        table, res = jf(table, acct_ledger=acct_ledger, **inputs)
        res.copy_to_host_async()
        time.sleep(delay)
        f0 = time.perf_counter()
        np.asarray(res)
        fetches.append(time.perf_counter() - f0)
    print(f"fetch after {delay*1e3:5.0f} ms host delay: "
          f"{np.median(fetches)*1e3:7.2f} ms")

# --- deep pipeline with deferred fetches (drain every K batches)
for K in (8, 32, 64):
    table = jnp.zeros((A, 8), jnp.uint64)
    pend = []
    n = 128
    t0 = time.perf_counter()
    for i in range(n):
        table, res = jf(table, acct_ledger=acct_ledger, **inputs)
        res.copy_to_host_async()
        pend.append(res)
        if len(pend) >= K:
            for r_ in pend:
                np.asarray(r_)
            pend.clear()
    for r_ in pend:
        np.asarray(r_)
    ms = (time.perf_counter() - t0) / n * 1e3
    print(f"deferred drain K={K:3d}: {ms:6.2f} ms/batch -> "
          f"{B/(ms/1e3):,.0f} ev/s")
