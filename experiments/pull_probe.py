"""Probe 10: continuous-pull summary ring — the candidate production
pattern.  Kernel appends a SMALL per-batch summary (16 u32) to a device
ring; host keeps exactly one ring fetch in flight; replies materialize
when the covering fetch lands."""
import time

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

A = 4096
B = 8190
rng = np.random.default_rng(0)


# --- d2h concurrency of computed small arrays
@jax.jit
def gen(x, s):
    return x + s


xs = [
    jax.block_until_ready(gen(jnp.arange(512, dtype=jnp.uint64), jnp.uint64(i)))
    for i in range(16)
]
t0 = time.perf_counter()
for x in xs:
    x.copy_to_host_async()
for x in xs:
    np.asarray(x)
tot = (time.perf_counter() - t0) * 1e3
print(f"16 concurrent 4KB d2h: {tot:.1f} ms total ({tot/16:.1f} ms each)")


# --- continuous-pull ring
def chain_ring(table, ring, k, x):
    s = x.sum(axis=0)
    table = table + s[None, :2]
    summary = jnp.concatenate(
        [x[:8, 0].astype(jnp.uint32), x[-8:, 1].astype(jnp.uint32)]
    )
    ring = jax.lax.dynamic_update_slice(ring, summary[None, :], (k, 0))
    return table, ring


jf = jax.jit(chain_ring, static_argnums=())


def fresh():
    return rng.integers(0, 1 << 20, (B, 6)).astype(np.uint64)


for R in (64, 128, 256):
    table = jnp.zeros((A, 2), jnp.uint64)
    ring = jnp.zeros((R, 16), jnp.uint32)
    jax.block_until_ready(jf(table, ring, 0, jnp.asarray(fresh())))
    table = jnp.zeros((A, 2), jnp.uint64)
    ring = jnp.zeros((R, 16), jnp.uint32)
    N = 300
    inflight = None  # (handle, covers_up_to)
    done_up_to = 0
    t0 = time.perf_counter()
    k = 0
    for i in range(N):
        table, ring = jf(table, ring, k % R, jnp.asarray(fresh()))
        k += 1
        if inflight is None:
            ring.copy_to_host_async()
            inflight = (ring, k)
        elif inflight[0].is_ready():
            np.asarray(inflight[0])
            done_up_to = inflight[1]
            ring.copy_to_host_async()
            inflight = (ring, k)
        # backpressure: never let unfetched span exceed ring capacity
        while k - done_up_to >= R:
            np.asarray(inflight[0])
            done_up_to = inflight[1]
            if done_up_to < k:
                ring.copy_to_host_async()
                inflight = (ring, k)
    np.asarray(inflight[0])
    ms = (time.perf_counter() - t0) / N * 1e3
    print(f"continuous-pull R={R:4d}: {ms:7.2f} ms/batch -> "
          f"{B/(ms/1e3):,.0f} ev/s")
