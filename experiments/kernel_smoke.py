"""Smoke-test the device kernels on CPU against hand-computed cases."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
try:
    from jax._src import xla_bridge

    xla_bridge._backend_factories.pop("axon", None)
except (ImportError, AttributeError):
    pass

import numpy as np
import jax.numpy as jnp

from tigerbeetle_tpu.state_machine import device_kernels as dk
from tigerbeetle_tpu.types import CreateTransferResult as CTR

A = 64
Bk = dk.B


def mk_tables(n_acct=8, ledger=1, acct_flags=None):
    table = jnp.zeros((A, 8), jnp.uint64)
    meta = np.zeros((A, 2), np.uint32)
    meta[:n_acct, 1] = ledger
    if acct_flags is not None:
        meta[: len(acct_flags), 0] = acct_flags
    return table, jnp.asarray(meta)


def base_pack(n, dr_slot, cr_slot, amt, flags=None, ids=None, pend=None,
              ledger=None, code=None, timeout=None, n_cols=dk.N_COLS,
              p_found=None, p_tgt=None, e_found=None):
    z = np.zeros(n, np.uint64)
    ids = np.arange(1, n + 1, dtype=np.uint64) if ids is None else ids
    pend = z if pend is None else pend
    dr_s = np.asarray(dr_slot, np.int64)
    cr_s = np.asarray(cr_slot, np.int64)
    return dk.pack_base(
        n,
        id_lo=ids, id_hi=z,
        dr_lo=np.where(dr_s < 0, 0, dr_s + 100).astype(np.uint64), dr_hi=z,
        cr_lo=np.where(cr_s < 0, 0, cr_s + 100).astype(np.uint64), cr_hi=z,
        pend_lo=pend, pend_hi=z,
        amount_lo=np.asarray(amt, np.uint64), amount_hi=z,
        flags=np.zeros(n, np.uint32) if flags is None else np.asarray(flags, np.uint32),
        ledger=np.ones(n, np.uint32) if ledger is None else ledger,
        code=np.ones(n, np.uint32) if code is None else code,
        timeout=np.zeros(n, np.uint32) if timeout is None else timeout,
        ts_nonzero=np.zeros(n, bool),
        dr_slot=np.asarray(dr_slot, np.int64),
        cr_slot=np.asarray(cr_slot, np.int64),
        e_found=np.zeros(n, bool) if e_found is None else e_found,
        p_found=p_found, p_tgt=p_tgt,
        n_cols=n_cols,
    )


ring = jnp.zeros((4, dk.SUMMARY_WORDS), jnp.uint64)

# --- orderfree: 3 ok transfers + 1 bad (same account)
table, meta = mk_tables()
pk = base_pack(4, [0, 1, 2, 3], [1, 2, 3, 3], [10, 20, 30, 40])
t2, r2 = dk.orderfree(table, meta, ring, 0, jnp.asarray(pk), 4,
                      jnp.uint64(1000))
s = dk.unpack_summary(np.asarray(r2)[0])
assert s["n_fail"] == 1 and s["fail_idx"][0] == 3, s
assert s["fail_codes"][0] == CTR.accounts_must_be_different
assert not s["overflow"] and s["last_applied"] == 2
tbl = np.asarray(t2)
assert tbl[0, 2] == 10 and tbl[1, 2] == 20 and tbl[1, 6] == 10
assert tbl[3, 6] == 30 and tbl[3, 2] == 0
print("orderfree ok")

# --- orderfree: pending create
table, meta = mk_tables()
pk = base_pack(2, [0, 1], [1, 2], [5, 7],
               flags=np.array([dk.F_PENDING, 0], np.uint32),
               timeout=np.array([3, 0], np.uint32))
t2, r2 = dk.orderfree(table, meta, ring, 1, jnp.asarray(pk), 2,
                      jnp.uint64(1000))
s = dk.unpack_summary(np.asarray(r2)[1])
assert s["n_fail"] == 0, s
tbl = np.asarray(t2)
assert tbl[0, 0] == 5 and tbl[1, 4] == 5 and tbl[1, 2] == 7
print("orderfree pending ok")

# --- linked: chain of 3 with middle failing statically -> all fail
table, meta = mk_tables()
pk = base_pack(3, [0, 1, 2], [1, 1, 0], [10, 20, 30],
               flags=np.array([dk.F_LINKED, dk.F_LINKED, 0], np.uint32))
t2, r2 = dk.linked(table, meta, ring, 0, jnp.asarray(pk), 3,
                   jnp.uint64(1000))
s = dk.unpack_summary(np.asarray(r2)[0])
assert s["n_fail"] == 3, s
codes = dict(zip(s["fail_idx"].tolist(), s["fail_codes"].tolist()))
assert codes[1] == CTR.accounts_must_be_different
assert codes[0] == CTR.linked_event_failed
assert codes[2] == CTR.linked_event_failed
assert np.asarray(t2).sum() == 0
print("linked static-fail ok")

# --- linked with limit account: acct0 has debits_must_not_exceed_credits,
# funded with 50 credits; chain1 debits 40 (ok), chain2 debits 40 (fails).
table, meta = mk_tables(acct_flags=np.array([2, 0, 0], np.uint32))
table = table.at[0, 6].set(50)  # cpo=50
pk = base_pack(2, [0, 0], [1, 2], [40, 40])
t2, r2 = dk.linked(table, meta, ring, 1, jnp.asarray(pk), 2,
                   jnp.uint64(1000))
s = dk.unpack_summary(np.asarray(r2)[1])
assert s["n_fail"] == 1 and s["fail_idx"][0] == 1, s
assert s["fail_codes"][0] == CTR.exceeds_credits
tbl = np.asarray(t2)
assert tbl[0, 2] == 40 and tbl[1, 6] == 40
print("linked limit ok")

# --- linked: chain rolls back on limit failure
table, meta = mk_tables(acct_flags=np.array([2, 0, 0], np.uint32))
table = table.at[0, 6].set(50)
pk = base_pack(3, [1, 0, 2], [2, 1, 0], [10, 60, 5],
               flags=np.array([dk.F_LINKED, dk.F_LINKED, 0], np.uint32))
t2, r2 = dk.linked(table, meta, ring, 2, jnp.asarray(pk), 3,
                   jnp.uint64(1000))
s = dk.unpack_summary(np.asarray(r2)[2])
assert s["n_fail"] == 3, s
codes = dict(zip(s["fail_idx"].tolist(), s["fail_codes"].tolist()))
assert codes[1] == CTR.exceeds_credits
assert codes[0] == CTR.linked_event_failed
tbl = np.asarray(t2)
assert tbl.sum() == 50, tbl.sum()  # only the funding credit remains
print("linked rollback ok")

# --- two_phase: pending + post pair (in-batch), second post loses
table, meta = mk_tables()
n = 3
ids = np.array([10, 11, 12], np.uint64)
pend = np.array([0, 10, 10], np.uint64)
flags = np.array([dk.F_PENDING, dk.F_POST, dk.F_POST], np.uint32)
pk = base_pack(
    n, [0, -1, -1], [1, -1, -1], [30, 0, 0], flags=flags, ids=ids,
    pend=pend, n_cols=dk.N_COLS_TP,
    p_found=np.zeros(n, bool), p_tgt=np.full(n, -1, np.int64),
)
# in-batch refs: tgt_ev = creator event of pending id (event 0)
pk = dk.pack_two_phase_ext(
    pk, n,
    bits_extra_mask=np.zeros(n, np.uint64),
    p_flags=np.zeros(n, np.uint16), p_code=np.zeros(n, np.uint16),
    p_ledger=np.zeros(n, np.uint32),
    p_dr_slot=np.full(n, -1, np.int64), p_cr_slot=np.full(n, -1, np.int64),
    p_amt_lo=np.zeros(n, np.uint64), p_amt_hi=np.zeros(n, np.uint64),
    tgt_ev=np.array([-1, 0, 0], np.int64),
    dstat_init_ev=np.zeros(n, np.uint32),
)
t2, r2 = dk.two_phase(table, meta, ring, 0, jnp.asarray(pk), n,
                      jnp.uint64(1000))
s = dk.unpack_summary(np.asarray(r2)[0])
assert s["n_fail"] == 1 and s["fail_idx"][0] == 2, s
assert s["fail_codes"][0] == CTR.pending_transfer_already_posted
tbl = np.asarray(t2)
# pending released, post applied: dp back to 0, dpo=30
assert tbl[0, 0] == 0 and tbl[0, 2] == 30 and tbl[1, 4] == 0 and tbl[1, 6] == 30, tbl[:2]
print("two_phase in-batch ok")

# --- two_phase: durable void with partial amount -> different_amount err
table, meta = mk_tables()
table = table.at[0, 0].set(30).at[1, 4].set(30)  # live pending 30
n = 1
pk = base_pack(
    n, [-1], [-1], [10],
    flags=np.array([dk.F_VOID], np.uint32),
    ids=np.array([20], np.uint64), pend=np.array([10], np.uint64),
    n_cols=dk.N_COLS_TP,
    p_found=np.ones(n, bool), p_tgt=np.zeros(n, np.int64),
)
pk = dk.pack_two_phase_ext(
    pk, n, bits_extra_mask=np.zeros(n, np.uint64),
    p_flags=np.full(n, dk.F_PENDING, np.uint16),
    p_code=np.ones(n, np.uint16), p_ledger=np.ones(n, np.uint32),
    p_dr_slot=np.zeros(n, np.int64), p_cr_slot=np.ones(n, np.int64),
    p_amt_lo=np.full(n, 30, np.uint64), p_amt_hi=np.zeros(n, np.uint64),
    tgt_ev=np.full(n, -1, np.int64),
    dstat_init_ev=np.full(n, dk.S_PENDING, np.uint32),
)
t2, r2 = dk.two_phase(table, meta, ring, 1, jnp.asarray(pk), n,
                      jnp.uint64(2000))
s = dk.unpack_summary(np.asarray(r2)[1])
assert s["n_fail"] == 1, s
assert s["fail_codes"][0] == CTR.pending_transfer_has_different_amount, s
print("two_phase durable partial-void ok")

# --- two_phase: durable void full -> releases pending
pk2 = base_pack(
    n, [-1], [-1], [0],
    flags=np.array([dk.F_VOID], np.uint32),
    ids=np.array([21], np.uint64), pend=np.array([10], np.uint64),
    n_cols=dk.N_COLS_TP,
    p_found=np.ones(n, bool), p_tgt=np.zeros(n, np.int64),
)
pk2 = dk.pack_two_phase_ext(
    pk2, n, bits_extra_mask=np.zeros(n, np.uint64),
    p_flags=np.full(n, dk.F_PENDING, np.uint16),
    p_code=np.ones(n, np.uint16), p_ledger=np.ones(n, np.uint32),
    p_dr_slot=np.zeros(n, np.int64), p_cr_slot=np.ones(n, np.int64),
    p_amt_lo=np.full(n, 30, np.uint64), p_amt_hi=np.zeros(n, np.uint64),
    tgt_ev=np.full(n, -1, np.int64),
    dstat_init_ev=np.full(n, dk.S_PENDING, np.uint32),
)
t3, r3 = dk.two_phase(table, meta, ring, 2, jnp.asarray(pk2), n,
                      jnp.uint64(2001))
s = dk.unpack_summary(np.asarray(r3)[2])
assert s["n_fail"] == 0, s
tbl = np.asarray(t3)
assert tbl[0, 0] == 0 and tbl[1, 4] == 0 and tbl[0, 2] == 0, tbl[:2]
print("two_phase durable void ok")

print("ALL SMOKE TESTS PASSED")
