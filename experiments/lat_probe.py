"""Microbenchmark: per-dispatch latency of a vectorized order-free
semantic kernel on the real TPU (tunneled), to size the authority
inversion (VERDICT r3 item 1).

Shapes mirror the bench hot path: B=8190 events, A=4096 accounts.
The candidate kernel does: static-ladder-scale elementwise work,
dense per-(slot,col) delta accumulation, u128 overflow admission
against the live table, conditional apply, and returns packed
results + the new table.
"""
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

print("devices:", jax.devices(), file=sys.stderr)

A = 4096
B = 8190
MASK32 = jnp.uint64(0xFFFFFFFF)


def kernel(table, acct, dr_slot, cr_slot, amt_lo, amt_hi, flags, ledger,
           code, id_zero, id_max, pend_nz, timeout, ts_nonzero):
    # --- static ladder (subset, representative op count)
    dr_ok = dr_slot >= 0
    cr_ok = cr_slot >= 0
    drc = jnp.clip(dr_slot, 0, A - 1)
    crc = jnp.clip(cr_slot, 0, A - 1)
    a_dr = acct[drc]
    a_cr = acct[crc]
    dr_ledger = jnp.where(dr_ok, a_dr[:, 1], 0)
    cr_ledger = jnp.where(cr_ok, a_cr[:, 1], 0)
    amount_zero = (amt_lo == 0) & (amt_hi == 0)
    r = jnp.zeros(B, jnp.uint32)

    def app(r, cond, code_v):
        return jnp.where((r == 0) & cond, jnp.uint32(code_v), r)

    r = app(r, ts_nonzero, 3)
    r = app(r, id_zero, 4)
    r = app(r, id_max, 5)
    r = app(r, ~dr_ok, 42)
    r = app(r, ~cr_ok, 43)
    r = app(r, dr_slot == cr_slot, 12)
    r = app(r, pend_nz, 13)
    r = app(r, timeout != 0, 14)
    r = app(r, amount_zero, 20)
    r = app(r, ledger == 0, 21)
    r = app(r, code == 0, 22)
    r = app(r, dr_ledger != cr_ledger, 30)
    r = app(r, ledger != dr_ledger, 31)
    ok = r == 0
    is_pending = (flags & 2) != 0

    # --- dense delta accumulation as 4x32-bit limbs (exact sums)
    l0 = amt_lo & MASK32
    l1 = amt_lo >> jnp.uint64(32)
    l2 = amt_hi & MASK32
    l3 = amt_hi >> jnp.uint64(32)
    zero = jnp.uint64(0)
    dcol = jnp.where(is_pending, 0, 1)
    ccol = jnp.where(is_pending, 2, 3)
    acc = jnp.zeros((A, 4, 4), jnp.uint64)
    sel = lambda v: jnp.where(ok, v, zero)
    acc = acc.at[drc, dcol, 0].add(sel(l0), mode="drop")
    acc = acc.at[drc, dcol, 1].add(sel(l1), mode="drop")
    acc = acc.at[drc, dcol, 2].add(sel(l2), mode="drop")
    acc = acc.at[drc, dcol, 3].add(sel(l3), mode="drop")
    acc = acc.at[crc, ccol, 0].add(sel(l0), mode="drop")
    acc = acc.at[crc, ccol, 1].add(sel(l1), mode="drop")
    acc = acc.at[crc, ccol, 2].add(sel(l2), mode="drop")
    acc = acc.at[crc, ccol, 3].add(sel(l3), mode="drop")
    c0 = acc[:, :, 0]
    c1 = acc[:, :, 1] + (c0 >> jnp.uint64(32))
    c2 = acc[:, :, 2] + (c1 >> jnp.uint64(32))
    c3 = acc[:, :, 3] + (c2 >> jnp.uint64(32))
    d_lo = (c0 & MASK32) | ((c1 & MASK32) << jnp.uint64(32))
    d_hi = (c2 & MASK32) | ((c3 & MASK32) << jnp.uint64(32))
    limb_ov = (c3 >> jnp.uint64(32)) != 0

    old_lo = table[:, 0::2]
    old_hi = table[:, 1::2]
    new_lo = old_lo + d_lo
    carry = (new_lo < old_lo).astype(jnp.uint64)
    new_hi = old_hi + d_hi + carry
    add_ov = (new_hi < old_hi) | ((new_hi == old_hi) & (new_lo < old_lo))
    # combined totals
    tot_lo = new_lo[:, 0] + new_lo[:, 1]
    tc = (tot_lo < new_lo[:, 0]).astype(jnp.uint64)
    tot_hi = new_hi[:, 0] + new_hi[:, 1] + tc
    dr_tot_ov = (tot_hi < new_hi[:, 0])
    overflow = limb_ov.any() | add_ov.any() | dr_tot_ov.any()

    new_table = jnp.where(
        overflow,
        table,
        jnp.stack(
            [new_lo[:, 0], new_hi[:, 0], new_lo[:, 1], new_hi[:, 1],
             new_lo[:, 2], new_hi[:, 2], new_lo[:, 3], new_hi[:, 3]],
            axis=-1,
        ),
    )
    results = jnp.where(overflow, jnp.uint32(0xFFFFFFFF), r)
    return new_table, results


jk = jax.jit(kernel, donate_argnums=(0,))

rng = np.random.default_rng(0)
table = jnp.zeros((A, 8), jnp.uint64)
acct = jnp.ones((A, 2), jnp.uint32)

def mk_inputs():
    dr = rng.integers(0, 1000, B).astype(np.int32)
    cr = ((dr + 1) % 1000).astype(np.int32)
    return dict(
        dr_slot=jnp.asarray(dr), cr_slot=jnp.asarray(cr),
        amt_lo=jnp.asarray(rng.integers(1, 100, B, np.uint64)),
        amt_hi=jnp.zeros(B, jnp.uint64),
        flags=jnp.zeros(B, jnp.uint32),
        ledger=jnp.ones(B, jnp.uint32),
        code=jnp.ones(B, jnp.uint32),
        id_zero=jnp.zeros(B, bool), id_max=jnp.zeros(B, bool),
        pend_nz=jnp.zeros(B, bool),
        timeout=jnp.zeros(B, jnp.uint64),
        ts_nonzero=jnp.zeros(B, bool),
    )

inp = mk_inputs()
t0 = time.perf_counter()
table, res = jk(table, acct, **inp)
np.asarray(res)
print(f"compile+first: {time.perf_counter()-t0:.3f}s", file=sys.stderr)

# --- synchronous per-call latency (fetch results every call)
N = 30
t0 = time.perf_counter()
for _ in range(N):
    table, res = jk(table, acct, **inp)
    res_np = np.asarray(res)
sync_ms = (time.perf_counter() - t0) / N * 1e3
print(f"sync per-call: {sync_ms:.2f} ms -> {B/(sync_ms/1e3):,.0f} ev/s")

# --- dispatch-only (no result fetch until the end)
t0 = time.perf_counter()
reses = []
for _ in range(N):
    table, res = jk(table, acct, **inp)
    reses.append(res)
jax.block_until_ready(reses[-1])
async_ms = (time.perf_counter() - t0) / N * 1e3
print(f"pipelined per-call: {async_ms:.2f} ms -> {B/(async_ms/1e3):,.0f} ev/s")

# --- host->device transfer cost for the input set alone
t0 = time.perf_counter()
for _ in range(N):
    arrs = [jnp.asarray(np.zeros(B, np.uint64)) for _ in range(8)]
    jax.block_until_ready(arrs)
xfer_ms = (time.perf_counter() - t0) / N * 1e3
print(f"8x u64(B) h2d: {xfer_ms:.2f} ms")

# --- depth-2 software pipeline: fetch res[k-1] after dispatch k
t0 = time.perf_counter()
prev = None
for _ in range(N):
    table, res = jk(table, acct, **inp)
    if prev is not None:
        np.asarray(prev)
    prev = res
np.asarray(prev)
pipe_ms = (time.perf_counter() - t0) / N * 1e3
print(f"depth-2 pipeline per-call: {pipe_ms:.2f} ms -> {B/(pipe_ms/1e3):,.0f} ev/s")
