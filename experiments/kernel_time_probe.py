"""Device time per production semantic kernel (r5): dispatch K kernels
with device-resident inputs, block at the end; per-kernel ms."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp
from tigerbeetle_tpu.state_machine import device_kernels as dk

A = 1 << 12
rng = np.random.default_rng(0)
n = dk.B
dr = rng.integers(0, 1000, n)
pk = dk.pack_base(
    n,
    id_lo=np.arange(1, n + 1, dtype=np.uint64), id_hi=np.zeros(n, np.uint64),
    dr_lo=dr.astype(np.uint64) + 1, dr_hi=np.zeros(n, np.uint64),
    cr_lo=(dr.astype(np.uint64) % 1000) + 2, cr_hi=np.zeros(n, np.uint64),
    pend_lo=np.zeros(n, np.uint64), pend_hi=np.zeros(n, np.uint64),
    amount_lo=rng.integers(1, 100, n).astype(np.uint64),
    amount_hi=np.zeros(n, np.uint64),
    flags=np.zeros(n, np.uint32), ledger=np.ones(n, np.uint32),
    code=np.ones(n, np.uint32), timeout=np.zeros(n, np.uint32),
    ts_nonzero=np.zeros(n, bool),
    dr_slot=dr.astype(np.int64), cr_slot=((dr + 1) % 1000).astype(np.int64),
    e_found=np.zeros(n, bool),
)
G = 8
buf = np.tile(pk, (G, 1))
sup = jax.device_put(buf)
balances = jnp.zeros((A, 8), jnp.uint64)
meta = jnp.ones((A, 2), jnp.uint32)
ring = jnp.zeros((256, dk.SUMMARY_WORDS), jnp.uint64)

for name in ("orderfree_lo_staged", "orderfree_staged", "linked_staged",
             "two_phase_lo_staged"):
    kern = getattr(dk, name)
    ncols = sup.shape[1]
    s = sup
    if name.startswith("two_phase"):
        pk_tp = dk.pack_base(
            n,
            id_lo=np.arange(1, n + 1, dtype=np.uint64), id_hi=np.zeros(n, np.uint64),
            dr_lo=dr.astype(np.uint64) + 1, dr_hi=np.zeros(n, np.uint64),
            cr_lo=(dr.astype(np.uint64) % 1000) + 2, cr_hi=np.zeros(n, np.uint64),
            pend_lo=np.zeros(n, np.uint64), pend_hi=np.zeros(n, np.uint64),
            amount_lo=rng.integers(1, 100, n).astype(np.uint64),
            amount_hi=np.zeros(n, np.uint64),
            flags=np.zeros(n, np.uint32), ledger=np.ones(n, np.uint32),
            code=np.ones(n, np.uint32), timeout=np.zeros(n, np.uint32),
            ts_nonzero=np.zeros(n, bool),
            dr_slot=dr.astype(np.int64), cr_slot=((dr + 1) % 1000).astype(np.int64),
            e_found=np.zeros(n, bool),
            p_found=np.zeros(n, bool), p_tgt=np.full(n, -1, np.int64),
            n_cols=dk.N_COLS_TP,
        )
        s = jax.device_put(np.tile(pk_tp, (G, 1)))
    # warm
    b, r = kern(balances, meta, ring, 0, s, 0, n, jnp.uint64(1))
    jax.block_until_ready(r)
    K = 32
    t0 = time.perf_counter()
    b2, r2 = balances, ring
    for k in range(K):
        b2, r2 = kern(b2, meta, r2, k % 256, s, k % G, n, jnp.uint64(1))
    jax.block_until_ready(r2)
    dt = time.perf_counter() - t0
    print(f"{name}: {dt/K*1e3:.2f} ms/batch -> {n/(dt/K):,.0f} ev/s")
