"""Probe 4: notification-latency structure + MXU matmul admission
kernel + h2d-in-loop cost."""
import sys
import time

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

A = 4096
B = 8190
MASK32 = jnp.uint64(0xFFFFFFFF)


@jax.jit
def bump(t):
    return t + jnp.uint64(1)


t = jax.block_until_ready(jnp.zeros((8,), jnp.uint64))

# --- fine-grained readiness curve
print("readiness curve (single trivial dispatch):")
for delay in (0.0, 0.005, 0.01, 0.02, 0.04, 0.08, 0.12):
    outs = []
    for _ in range(5):
        r = bump(t)
        time.sleep(delay)
        f0 = time.perf_counter()
        jax.block_until_ready(r)
        outs.append(time.perf_counter() - f0)
    print(f"  block after {delay*1e3:5.1f} ms: {np.median(outs)*1e3:7.2f} ms")

# --- is_ready polling
r = bump(t)
t0 = time.perf_counter()
polls = 0
while not r.is_ready():
    polls += 1
    if time.perf_counter() - t0 > 1.0:
        break
    time.sleep(0.002)
print(f"is_ready became true after {1e3*(time.perf_counter()-t0):.1f} ms "
      f"({polls} polls)")

# --- does a subsequent dispatch flush earlier completions?
r1 = bump(t)
time.sleep(0.02)
r2 = bump(t)
t0 = time.perf_counter()
jax.block_until_ready(r1)
print(f"block r1 with r2 dispatched after: {1e3*(time.perf_counter()-t0):.1f} ms")

# --- MXU one-hot matmul admission variant
def matmul_admit(table, dr_slot, cr_slot, amt_lo, amt_hi, flags, ledger,
                 acct_ledger):
    drc = jnp.clip(dr_slot, 0, A - 1)
    crc = jnp.clip(cr_slot, 0, A - 1)
    dr_ledger = acct_ledger[drc]
    r = jnp.zeros(B, jnp.uint32)

    def app(r, cond, c):
        return jnp.where((r == 0) & cond, jnp.uint32(c), r)

    r = app(r, dr_slot < 0, 42)
    r = app(r, cr_slot < 0, 43)
    r = app(r, dr_slot == cr_slot, 12)
    r = app(r, (amt_lo == 0) & (amt_hi == 0), 20)
    r = app(r, ledger == 0, 21)
    r = app(r, acct_ledger[crc] != dr_ledger, 30)
    r = app(r, ledger != dr_ledger, 31)
    ok = r == 0
    is_pending = (flags & 2) != 0

    # payload (2B, 16): 8-bit pieces of amt placed in (col, piece) lanes
    zero = jnp.uint64(0)
    amt_ok_lo = jnp.where(ok, amt_lo, zero)
    amt_ok_hi = jnp.where(ok, amt_hi, zero)
    pieces = []
    for shift in range(0, 64, 8):
        pieces.append(
            ((amt_ok_lo >> jnp.uint64(shift)) & jnp.uint64(0xFF)).astype(
                jnp.float32
            )
        )
    for shift in range(0, 64, 8):
        pieces.append(
            ((amt_ok_hi >> jnp.uint64(shift)) & jnp.uint64(0xFF)).astype(
                jnp.float32
            )
        )
    P = jnp.stack(pieces, axis=-1)  # (B, 16)

    # 4 columns x 16 pieces = 64 payload lanes per event row, but each
    # event only feeds (dcol for dr) and (ccol for cr). Build (2B, 64):
    dcol = jnp.where(is_pending, 0, 1)
    ccol = jnp.where(is_pending, 2, 3)
    colmask_d = jax.nn.one_hot(dcol, 4, dtype=jnp.float32)  # (B,4)
    colmask_c = jax.nn.one_hot(ccol, 4, dtype=jnp.float32)
    pay_d = (colmask_d[:, :, None] * P[:, None, :]).reshape(B, 64)
    pay_c = (colmask_c[:, :, None] * P[:, None, :]).reshape(B, 64)
    payload = jnp.concatenate([pay_d, pay_c], axis=0)  # (2B, 64)

    slots = jnp.concatenate([drc, crc])  # (2B,)
    onehot = jax.nn.one_hot(slots, A, dtype=jnp.bfloat16)  # (2B, A)
    acc = jax.lax.dot_general(
        onehot.astype(jnp.float32).T, payload,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (A, 64)
    acc = acc.reshape(A, 4, 16).astype(jnp.uint64)
    # base-256 recombination with carries into u128 limbs
    c = acc[:, :, 0]
    lo = c & jnp.uint64(0xFF)
    carry = c >> jnp.uint64(8)
    vals = [lo]
    for k in range(1, 16):
        c = acc[:, :, k] + carry
        vals.append(c & jnp.uint64(0xFF))
        carry = c >> jnp.uint64(8)
    d_lo = jnp.zeros((A, 4), jnp.uint64)
    d_hi = jnp.zeros((A, 4), jnp.uint64)
    for k in range(8):
        d_lo = d_lo | (vals[k] << jnp.uint64(8 * k))
    for k in range(8):
        d_hi = d_hi | (vals[8 + k] << jnp.uint64(8 * k))
    limb_ov = carry != 0

    old_lo = table[:, 0::2]
    old_hi = table[:, 1::2]
    new_lo = old_lo + d_lo
    cy = (new_lo < old_lo).astype(jnp.uint64)
    new_hi = old_hi + d_hi + cy
    ov = ((new_hi < old_hi) | ((new_hi == old_hi) & (new_lo < old_lo))).any() \
        | limb_ov.any()
    nt = jnp.stack(
        [new_lo[:, 0], new_hi[:, 0], new_lo[:, 1], new_hi[:, 1],
         new_lo[:, 2], new_hi[:, 2], new_lo[:, 3], new_hi[:, 3]], axis=-1)
    table = jnp.where(ov, table, nt)
    return table, jnp.where(ov, jnp.uint32(0xFFFF), r)


rng = np.random.default_rng(0)
dr = rng.integers(0, 1000, B).astype(np.int32)
inputs_np = dict(
    dr_slot=dr,
    cr_slot=((dr + 1) % 1000).astype(np.int32),
    amt_lo=rng.integers(1, 100, B, np.uint64),
    amt_hi=np.zeros(B, np.uint64),
    flags=np.zeros(B, np.uint32),
    ledger=np.ones(B, np.uint32),
)
inputs = {k: jnp.asarray(v) for k, v in inputs_np.items()}
acct_ledger = jnp.ones(A, jnp.uint32)

jf = jax.jit(matmul_admit, donate_argnums=(0,))
table = jnp.zeros((A, 8), jnp.uint64)
table, res = jf(table, acct_ledger=acct_ledger, **inputs)
jax.block_until_ready(res)
# correctness vs numpy
res_np = np.asarray(res)
assert (res_np == 0).all(), res_np[res_np != 0][:5]
tbl = np.asarray(table)
exp_dpo = np.bincount(dr, weights=inputs_np["amt_lo"].astype(np.float64),
                      minlength=A).astype(np.uint64)
assert (tbl[:, 2] == exp_dpo).all(), "dpo mismatch"
print("matmul_admit exactness ok")

n = 100
t0 = time.perf_counter()
last = None
for _ in range(n):
    table, last = jf(table, acct_ledger=acct_ledger, **inputs)
jax.block_until_ready(last)
ms = (time.perf_counter() - t0) / n * 1e3
print(f"matmul_admit: {ms:6.2f} ms/batch -> {B/(ms/1e3):,.0f} ev/s")

# --- with per-batch h2d of fresh packed inputs
packed = np.zeros((B, 6), np.uint64)
packed[:, 0] = inputs_np["dr_slot"]
packed[:, 1] = inputs_np["cr_slot"]
packed[:, 2] = inputs_np["amt_lo"]
packed[:, 4] = inputs_np["flags"]
packed[:, 5] = inputs_np["ledger"]


def unpack_and_run(table, pk, acct_ledger):
    return matmul_admit(
        table,
        pk[:, 0].astype(jnp.int32), pk[:, 1].astype(jnp.int32),
        pk[:, 2], pk[:, 3],
        pk[:, 4].astype(jnp.uint32), pk[:, 5].astype(jnp.uint32),
        acct_ledger,
    )


jf2 = jax.jit(unpack_and_run, donate_argnums=(0,))
table = jnp.zeros((A, 8), jnp.uint64)
table, res = jf2(table, jnp.asarray(packed), acct_ledger)
jax.block_until_ready(res)
t0 = time.perf_counter()
for _ in range(n):
    pk = jnp.asarray(packed)  # fresh h2d each batch
    table, last = jf2(table, pk, acct_ledger)
jax.block_until_ready(last)
ms = (time.perf_counter() - t0) / n * 1e3
print(f"matmul_admit + h2d: {ms:6.2f} ms/batch -> {B/(ms/1e3):,.0f} ev/s")
