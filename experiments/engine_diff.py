"""Differential check: TpuStateMachine(engine='device') vs CPU oracle
on scaled-down bench configs, running on the CPU backend."""
import os
import sys

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=1"
).strip()

import jax

jax.config.update("jax_platforms", "cpu")
try:
    from jax._src import xla_bridge

    xla_bridge._backend_factories.pop("axon", None)
except (ImportError, AttributeError):
    pass

sys.path.insert(0, "/root/repo")
os.environ["BENCH_SMALL"] = "1"
os.environ["BENCH_BATCH"] = "500"

import numpy as np  # noqa: E402

import bench  # noqa: E402
from tigerbeetle_tpu.state_machine.cpu import CpuStateMachine  # noqa: E402
from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine  # noqa: E402
from tigerbeetle_tpu.testing.harness import SingleNodeHarness  # noqa: E402

N = int(os.environ.get("DIFF_N", "6000"))

for name, gen in bench.CONFIGS.items():
    setup, timed, sizing = gen(N)
    ops = setup + timed
    sm_d = TpuStateMachine(
        account_capacity=sizing[0], transfer_capacity=sizing[1],
        engine="device",
    )
    h_d = SingleNodeHarness(sm_d)
    futs = [h_d.submit_async(op, body) for op, body in ops]
    replies_d = [f.result() for f in futs]

    sm_c = CpuStateMachine()
    h_c = SingleNodeHarness(sm_c)
    replies_c = [h_c.submit(op, body) for op, body in ops]

    bad = None
    for i, (a, b) in enumerate(zip(replies_d, replies_c)):
        if a != b:
            bad = i
            break
    if bad is not None:
        import numpy as np
        from tigerbeetle_tpu import types

        ra = np.frombuffer(replies_d[bad], dtype=types.CREATE_RESULT_DTYPE)
        rb = np.frombuffer(replies_c[bad], dtype=types.CREATE_RESULT_DTYPE)
        print(f"{name}: MISMATCH at op {bad} ({ops[bad][0]!r})")
        print("  device:", ra[:10])
        print("  oracle:", rb[:10])
        sys.exit(1)
    # state digest
    acct_ids = bench.config_account_ids(name)
    tids = np.arange(bench.TID0, bench.TID0 + min(2000, N)).astype(np.uint64)
    dg_d = bench.state_digest(h_d, acct_ids, tids)
    dg_c = bench.state_digest(h_c, acct_ids, tids)
    assert dg_d == dg_c, f"{name}: state digest mismatch"
    eng = sm_d._dev
    print(
        f"{name}: ok  semantic={eng.stat_semantic_events} "
        f"host={sm_d.stat_host_semantic_events} "
        f"fallback_batches={eng.stat_fallback_batches} "
        f"fetches={eng.stat_fetches}"
    )
print("ALL CONFIGS MATCH")
