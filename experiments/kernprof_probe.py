"""Probe 12: isolate kernel compute costs (block each, device-resident
inputs): matmul admission core, summary extraction variants."""
import time

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

A = 4096
B = 8190
MASK8 = jnp.uint64(0xFF)
rng = np.random.default_rng(0)


def core(table, pk, acct_ledger):
    dr_slot = pk[:, 0].astype(jnp.int32)
    cr_slot = pk[:, 1].astype(jnp.int32)
    amt_lo = pk[:, 2]
    flags = pk[:, 4].astype(jnp.uint32)
    ledger = pk[:, 5].astype(jnp.uint32)
    drc = jnp.clip(dr_slot, 0, A - 1)
    crc = jnp.clip(cr_slot, 0, A - 1)
    dr_ledger = acct_ledger[drc]
    r = jnp.zeros(B, jnp.uint32)

    def app(r, cond, c):
        return jnp.where((r == 0) & cond, jnp.uint32(c), r)

    r = app(r, dr_slot < 0, 42)
    r = app(r, cr_slot < 0, 43)
    r = app(r, dr_slot == cr_slot, 12)
    r = app(r, amt_lo == 0, 20)
    r = app(r, ledger == 0, 21)
    r = app(r, acct_ledger[crc] != dr_ledger, 30)
    r = app(r, ledger != dr_ledger, 31)
    ok = r == 0
    is_pending = (flags & 2) != 0
    amt_ok = jnp.where(ok, amt_lo, jnp.uint64(0))
    P = jnp.stack(
        [((amt_ok >> jnp.uint64(s)) & MASK8).astype(jnp.float32)
         for s in range(0, 64, 8)],
        axis=-1,
    )
    dcol = jnp.where(is_pending, 0, 1)
    ccol = jnp.where(is_pending, 2, 3)
    md = jax.nn.one_hot(dcol, 4, dtype=jnp.float32)
    mc = jax.nn.one_hot(ccol, 4, dtype=jnp.float32)
    pay = jnp.concatenate(
        [(md[:, :, None] * P[:, None, :]).reshape(B, 32),
         (mc[:, :, None] * P[:, None, :]).reshape(B, 32)],
        axis=0,
    )
    slots = jnp.concatenate([drc, crc])
    onehot = jax.nn.one_hot(slots, A, dtype=jnp.bfloat16)
    acc = jax.lax.dot_general(
        onehot.T, pay.astype(jnp.bfloat16),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).reshape(A, 4, 8).astype(jnp.uint64)
    c = acc[:, :, 0]
    d_lo = c & MASK8
    carry = c >> jnp.uint64(8)
    for kk in range(1, 8):
        c = acc[:, :, kk] + carry
        d_lo = d_lo | ((c & MASK8) << jnp.uint64(8 * kk))
        carry = c >> jnp.uint64(8)
    d_hi = carry
    old_lo = table[:, 0::2]
    old_hi = table[:, 1::2]
    new_lo = old_lo + d_lo
    cy = (new_lo < old_lo).astype(jnp.uint64)
    new_hi = old_hi + d_hi + cy
    ov = ((new_hi < old_hi) | ((new_hi == old_hi) & (new_lo < old_lo))).any()
    nt = jnp.stack(
        [new_lo[:, 0], new_hi[:, 0], new_lo[:, 1], new_hi[:, 1],
         new_lo[:, 2], new_hi[:, 2], new_lo[:, 3], new_hi[:, 3]], axis=-1)
    table = jnp.where(ov, table, nt)
    return table, r, ov


def summary_argsort(r, ov):
    fail = r != 0
    n_fail = fail.sum().astype(jnp.uint64)
    fi = jnp.where(fail, jnp.arange(B, dtype=jnp.uint32), jnp.uint32(B))
    order = jnp.argsort(fi)[:12]
    ent = (fi[order].astype(jnp.uint64) << jnp.uint64(32)) | r[order].astype(
        jnp.uint64
    )
    return jnp.concatenate(
        [jnp.array([n_fail]), jnp.array([ov.astype(jnp.uint64)]), ent,
         jnp.zeros(2, jnp.uint64)]
    )


def summary_scatter(r, ov):
    fail = r != 0
    n_fail = fail.sum().astype(jnp.uint64)
    pos = jnp.cumsum(fail) - 1  # position among failures
    ent = (jnp.arange(B, dtype=jnp.uint64) << jnp.uint64(32)) | r.astype(
        jnp.uint64
    )
    slots12 = jnp.zeros(12, jnp.uint64).at[
        jnp.where(fail, pos, 12)
    ].set(ent, mode="drop")
    return jnp.concatenate(
        [jnp.array([n_fail]), jnp.array([ov.astype(jnp.uint64)]), slots12,
         jnp.zeros(2, jnp.uint64)]
    )


def mk(variant):
    def f(table, ring, k, pk, acct_ledger):
        table, r, ov = core(table, pk, acct_ledger)
        if variant == "none":
            s = jnp.concatenate(
                [jnp.array([(r != 0).sum().astype(jnp.uint64)]),
                 jnp.array([ov.astype(jnp.uint64)]),
                 jnp.zeros(14, jnp.uint64)]
            )
        elif variant == "argsort":
            s = summary_argsort(r, ov)
        else:
            s = summary_scatter(r, ov)
        ring = jax.lax.dynamic_update_slice(ring, s[None, :], (k, 0))
        return table, ring
    return jax.jit(f)


pk_np = np.zeros((B, 6), np.uint64)
dr = rng.integers(0, 1000, B).astype(np.int64)
pk_np[:, 0] = dr
pk_np[:, 1] = (dr + 1) % 1000
pk_np[:, 2] = rng.integers(1, 100, B)
pk_np[:, 5] = 1
pk_dev = jax.block_until_ready(jnp.asarray(pk_np))
acct_ledger = jnp.ones(A, jnp.uint32)

for variant in ("none", "argsort", "scatter"):
    jf = mk(variant)
    table = jnp.zeros((A, 8), jnp.uint64)
    ring = jnp.zeros((64, 16), jnp.uint64)
    table, ring = jf(table, ring, 0, pk_dev, acct_ledger)
    jax.block_until_ready(ring)
    # block-each latency
    t0 = time.perf_counter()
    for i in range(20):
        table, ring = jf(table, ring, i % 64, pk_dev, acct_ledger)
        jax.block_until_ready(ring)
    ms_sync = (time.perf_counter() - t0) / 20 * 1e3
    # pipelined rate, device-resident input
    t0 = time.perf_counter()
    for i in range(100):
        table, ring = jf(table, ring, i % 64, pk_dev, acct_ledger)
    jax.block_until_ready(ring)
    ms_pipe = (time.perf_counter() - t0) / 100 * 1e3
    print(f"{variant:8s}: sync {ms_sync:7.2f} ms  pipelined {ms_pipe:7.2f} ms")

# pipelined with per-batch h2d again, best variant
jf = mk("scatter")
table = jnp.zeros((A, 8), jnp.uint64)
ring = jnp.zeros((64, 16), jnp.uint64)
datas = [np.ascontiguousarray(pk_np) for _ in range(8)]
t0 = time.perf_counter()
for i in range(100):
    table, ring = jf(table, ring, i % 64, jnp.asarray(datas[i % 8]),
                     acct_ledger)
jax.block_until_ready(ring)
ms = (time.perf_counter() - t0) / 100 * 1e3
print(f"scatter + h2d pipelined: {ms:7.2f} ms/batch -> "
      f"{B/(ms/1e3):,.0f} ev/s")
