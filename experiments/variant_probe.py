"""Probe 8: find the fast dispatch pattern for chained-state kernels
with per-batch h2d."""
import time

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

A = 4096
B = 8190
rng = np.random.default_rng(0)


@jax.jit
def chaink(table, x):
    s = x.sum(axis=0)
    return table + s[None, :2], x[:, 0]


def fresh():
    return rng.integers(0, 1 << 20, (B, 6)).astype(np.uint64)


table0 = jnp.zeros((A, 2), jnp.uint64)
jax.block_until_ready(chaink(table0, jnp.asarray(fresh())))

N = 60

# V1: chain + h2d, no fetch, block end
table = table0
rs = []
t0 = time.perf_counter()
for _ in range(N):
    table, r = chaink(table, jnp.asarray(fresh()))
    rs.append(r)
jax.block_until_ready(rs)
print(f"V1 chain+h2d no-fetch: {(time.perf_counter()-t0)/N*1e3:7.2f} ms")

# V3: block each h2d BEFORE dispatch
table = table0
rs = []
t0 = time.perf_counter()
for _ in range(N):
    pk = jnp.asarray(fresh())
    pk.block_until_ready()
    table, r = chaink(table, pk)
    rs.append(r)
jax.block_until_ready(rs)
print(f"V3 blocked-h2d chain:  {(time.perf_counter()-t0)/N*1e3:7.2f} ms")

# V4: double-buffered h2d (issue k+1, block k, dispatch k)
table = table0
rs = []
nxt = jnp.asarray(fresh())
t0 = time.perf_counter()
for _ in range(N):
    cur = nxt
    nxt = jnp.asarray(fresh())
    cur.block_until_ready()
    table, r = chaink(table, cur)
    rs.append(r)
jax.block_until_ready(rs)
print(f"V4 double-buffer h2d:  {(time.perf_counter()-t0)/N*1e3:7.2f} ms")

# V5: V3 + rolling fetch W=8
table = table0
pend = []
t0 = time.perf_counter()
for _ in range(N):
    pk = jnp.asarray(fresh())
    pk.block_until_ready()
    table, r = chaink(table, pk)
    r.copy_to_host_async()
    pend.append(r)
    if len(pend) > 8:
        np.asarray(pend.pop(0))
for r_ in pend:
    np.asarray(r_)
print(f"V5 blocked-h2d W=8:    {(time.perf_counter()-t0)/N*1e3:7.2f} ms")

# V6: V3 + sync fetch each (depth 1!)
table = table0
t0 = time.perf_counter()
for _ in range(N):
    pk = jnp.asarray(fresh())
    pk.block_until_ready()
    table, r = chaink(table, pk)
    np.asarray(r)
print(f"V6 blocked-h2d sync:   {(time.perf_counter()-t0)/N*1e3:7.2f} ms")
