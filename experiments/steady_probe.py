"""Probe 5: realistic steady-state pipeline — fresh h2d per batch,
rolling result fetch W batches behind, several h2d strategies."""
import sys
import time

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

A = 4096
B = 8190
dev = jax.devices()[0]
MASK32 = jnp.uint64(0xFFFFFFFF)


def kernel(table, pk, acct_ledger):
    dr_slot = pk[:, 0].astype(jnp.int32)
    cr_slot = pk[:, 1].astype(jnp.int32)
    amt_lo = pk[:, 2]
    amt_hi = pk[:, 3]
    flags = pk[:, 4].astype(jnp.uint32)
    ledger = pk[:, 5].astype(jnp.uint32)
    drc = jnp.clip(dr_slot, 0, A - 1)
    crc = jnp.clip(cr_slot, 0, A - 1)
    dr_ledger = acct_ledger[drc]
    r = jnp.zeros(B, jnp.uint32)

    def app(r, cond, c):
        return jnp.where((r == 0) & cond, jnp.uint32(c), r)

    r = app(r, dr_slot < 0, 42)
    r = app(r, cr_slot < 0, 43)
    r = app(r, dr_slot == cr_slot, 12)
    r = app(r, (amt_lo == 0) & (amt_hi == 0), 20)
    r = app(r, ledger == 0, 21)
    r = app(r, acct_ledger[crc] != dr_ledger, 30)
    r = app(r, ledger != dr_ledger, 31)
    ok = r == 0
    is_pending = (flags & 2) != 0
    zero = jnp.uint64(0)
    amt_ok = jnp.where(ok, amt_lo, zero)
    pieces = [
        ((amt_ok >> jnp.uint64(s)) & jnp.uint64(0xFF)).astype(jnp.float32)
        for s in range(0, 64, 8)
    ]
    P = jnp.stack(pieces, axis=-1)  # (B, 8)
    dcol = jnp.where(is_pending, 0, 1)
    ccol = jnp.where(is_pending, 2, 3)
    colmask_d = jax.nn.one_hot(dcol, 4, dtype=jnp.float32)
    colmask_c = jax.nn.one_hot(ccol, 4, dtype=jnp.float32)
    pay = jnp.concatenate(
        [
            (colmask_d[:, :, None] * P[:, None, :]).reshape(B, 32),
            (colmask_c[:, :, None] * P[:, None, :]).reshape(B, 32),
        ],
        axis=0,
    )
    slots = jnp.concatenate([drc, crc])
    onehot = jax.nn.one_hot(slots, A, dtype=jnp.float32)
    acc = jax.lax.dot_general(
        onehot.T, pay, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).reshape(A, 4, 8).astype(jnp.uint64)
    c = acc[:, :, 0]
    valbits = c & jnp.uint64(0xFF)
    carry = c >> jnp.uint64(8)
    d_lo = valbits
    for k in range(1, 8):
        c = acc[:, :, k] + carry
        d_lo = d_lo | ((c & jnp.uint64(0xFF)) << jnp.uint64(8 * k))
        carry = c >> jnp.uint64(8)
    d_hi = carry  # remaining carry beyond 64 bits
    old_lo = table[:, 0::2]
    old_hi = table[:, 1::2]
    new_lo = old_lo + d_lo
    cy = (new_lo < old_lo).astype(jnp.uint64)
    new_hi = old_hi + d_hi + cy
    ov = ((new_hi < old_hi) | ((new_hi == old_hi) & (new_lo < old_lo))).any()
    nt = jnp.stack(
        [new_lo[:, 0], new_hi[:, 0], new_lo[:, 1], new_hi[:, 1],
         new_lo[:, 2], new_hi[:, 2], new_lo[:, 3], new_hi[:, 3]], axis=-1)
    table = jnp.where(ov, table, nt)
    return table, jnp.where(ov, jnp.uint32(0xFFFF), r)


jf = jax.jit(kernel, donate_argnums=(0,))
acct_ledger = jnp.ones(A, jnp.uint32)
rng = np.random.default_rng(0)


def fresh_packed():
    dr = rng.integers(0, 1000, B).astype(np.int64)
    packed = np.zeros((B, 6), np.uint64)
    packed[:, 0] = dr
    packed[:, 1] = (dr + 1) % 1000
    packed[:, 2] = rng.integers(1, 100, B)
    packed[:, 5] = 1
    return packed


def run(name, n, W, h2d):
    table = jnp.zeros((A, 8), jnp.uint64)
    pk0 = h2d(fresh_packed())
    table, res = jf(table, pk0, acct_ledger)
    jax.block_until_ready(res)
    pend = []
    t0 = time.perf_counter()
    for i in range(n):
        pk = h2d(fresh_packed())
        table, res = jf(table, pk, acct_ledger)
        res.copy_to_host_async()
        pend.append(res)
        if len(pend) > W:
            np.asarray(pend.pop(0))
    for r_ in pend:
        np.asarray(r_)
    ms = (time.perf_counter() - t0) / n * 1e3
    print(f"{name:28s} W={W:3d}: {ms:7.2f} ms/batch -> "
          f"{B/(ms/1e3):,.0f} ev/s")


h2d_asarray = lambda a: jnp.asarray(a)
h2d_put = lambda a: jax.device_put(a, dev)
h2d_numpy = lambda a: a  # let jit transfer it

for W in (4, 32):
    run("jnp.asarray", 60, W, h2d_asarray)
for W in (4, 32):
    run("device_put", 60, W, h2d_put)
for W in (4, 32):
    run("raw numpy arg", 60, W, h2d_numpy)

# fresh-data generation cost alone (host)
t0 = time.perf_counter()
for _ in range(60):
    fresh_packed()
print(f"fresh_packed host cost: {(time.perf_counter()-t0)/60*1e3:.2f} ms")
