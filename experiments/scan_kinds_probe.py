"""r5: scan-G16 per-batch device+launch time for each kernel kind."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp
from tigerbeetle_tpu.state_machine import device_kernels as dk

A = 1 << 12
rng = np.random.default_rng(0)
n = dk.B
dr = rng.integers(0, 1000, n)

def mk_pk(flags=None, tp=False):
    kw = dict(
        id_lo=np.arange(1, n + 1, dtype=np.uint64), id_hi=np.zeros(n, np.uint64),
        dr_lo=dr.astype(np.uint64) + 1, dr_hi=np.zeros(n, np.uint64),
        cr_lo=(dr.astype(np.uint64) % 1000) + 2, cr_hi=np.zeros(n, np.uint64),
        pend_lo=np.zeros(n, np.uint64), pend_hi=np.zeros(n, np.uint64),
        amount_lo=rng.integers(1, 100, n).astype(np.uint64),
        amount_hi=np.zeros(n, np.uint64),
        flags=flags if flags is not None else np.zeros(n, np.uint32),
        ledger=np.ones(n, np.uint32),
        code=np.ones(n, np.uint32), timeout=np.zeros(n, np.uint32),
        ts_nonzero=np.zeros(n, bool),
        dr_slot=dr.astype(np.int64), cr_slot=((dr + 1) % 1000).astype(np.int64),
        e_found=np.zeros(n, bool),
    )
    if tp:
        kw.update(p_found=np.zeros(n, bool), p_tgt=np.full(n, -1, np.int64),
                  n_cols=dk.N_COLS_TP)
    return dk.pack_base(n, **kw)

lf = np.zeros(n, np.uint32); lf[:] = 1; lf[3::4] = 0
meta = jnp.ones((A, 2), jnp.uint32)
G = 16
for kind, pk in (("orderfree_lo", mk_pk()), ("linked_small", mk_pk(lf)),
                 ("two_phase_lo", mk_pk(tp=True))):
    scan = dk.scan_kernels[kind][G]
    stack = jax.device_put(np.broadcast_to(pk, (G,) + pk.shape).copy())
    ns = jax.device_put(np.full(G, n, np.int64))
    tsb = jax.device_put(np.arange(G, dtype=np.uint64))
    table = jnp.zeros((A, 8), jnp.uint64)
    ring = jnp.zeros((256, dk.SUMMARY_WORDS), jnp.uint64)
    t, r = scan(table, meta, ring, 0, stack, ns, tsb)
    jax.block_until_ready(r)
    K = 4
    t0 = time.perf_counter()
    t2, r2 = table, ring
    for k in range(K):
        t2, r2 = scan(t2, meta, r2, (k * G) % 128, stack, ns, tsb)
    jax.block_until_ready(r2)
    dt = time.perf_counter() - t0
    per = dt / (K * G)
    print(f"{kind:14s} scan16: {per*1e3:6.2f} ms/batch -> {n/per:,.0f} ev/s")
