"""r5: device time per NON-staged production kernel (engine dispatch
shape) after the shared-one-hot + linked_small refactor."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp
from tigerbeetle_tpu.state_machine import device_kernels as dk

A = 1 << 12
rng = np.random.default_rng(0)
n = dk.B

def mk_pk(flags=None, tp=False):
    dr = rng.integers(0, 1000, n)
    kw = dict(
        id_lo=np.arange(1, n + 1, dtype=np.uint64), id_hi=np.zeros(n, np.uint64),
        dr_lo=dr.astype(np.uint64) + 1, dr_hi=np.zeros(n, np.uint64),
        cr_lo=(dr.astype(np.uint64) % 1000) + 2, cr_hi=np.zeros(n, np.uint64),
        pend_lo=np.zeros(n, np.uint64), pend_hi=np.zeros(n, np.uint64),
        amount_lo=rng.integers(1, 100, n).astype(np.uint64),
        amount_hi=np.zeros(n, np.uint64),
        flags=flags if flags is not None else np.zeros(n, np.uint32),
        ledger=np.ones(n, np.uint32),
        code=np.ones(n, np.uint32), timeout=np.zeros(n, np.uint32),
        ts_nonzero=np.zeros(n, bool),
        dr_slot=dr.astype(np.int64), cr_slot=((dr + 1) % 1000).astype(np.int64),
        e_found=np.zeros(n, bool),
    )
    if tp:
        kw.update(p_found=np.zeros(n, bool), p_tgt=np.full(n, -1, np.int64),
                  n_cols=dk.N_COLS_TP)
    return dk.pack_base(n, **kw)

lf = np.zeros(n, np.uint32)
lf[:] = 1  # linked
lf[3::4] = 0  # chains of 4

cases = [
    ("orderfree_lo", dk.orderfree_lo, mk_pk()),
    ("linked", dk.linked, mk_pk(lf)),
    ("linked_small", dk.linked_small, mk_pk(lf)),
    ("two_phase_lo", dk.two_phase_lo, mk_pk(tp=True)),
]
meta = jnp.ones((A, 2), jnp.uint32)
for name, kern, pk in cases:
    pkj = jax.device_put(pk)
    balances = jnp.zeros((A, 8), jnp.uint64)
    ring = jnp.zeros((256, dk.SUMMARY_WORDS), jnp.uint64)
    b, r = kern(balances, meta, ring, 0, pkj, n, jnp.uint64(1))
    jax.block_until_ready(r)
    K = 32
    t0 = time.perf_counter()
    b2, r2 = balances, ring
    for k in range(K):
        b2, r2 = kern(b2, meta, r2, k % 256, pkj, n, jnp.uint64(1))
    jax.block_until_ready(r2)
    dt = time.perf_counter() - t0
    print(f"{name:14s}: {dt/K*1e3:6.2f} ms/batch -> {n/(dt/K):,.0f} ev/s")
