"""r5: op-level TPU profile of orderfree_lo and linked kernels."""
import glob, gzip, sys
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp
from tigerbeetle_tpu.state_machine import device_kernels as dk

A = 1 << 12
rng = np.random.default_rng(0)
n = dk.B
dr = rng.integers(0, 1000, n)
pk = dk.pack_base(
    n,
    id_lo=np.arange(1, n + 1, dtype=np.uint64), id_hi=np.zeros(n, np.uint64),
    dr_lo=dr.astype(np.uint64) + 1, dr_hi=np.zeros(n, np.uint64),
    cr_lo=(dr.astype(np.uint64) % 1000) + 2, cr_hi=np.zeros(n, np.uint64),
    pend_lo=np.zeros(n, np.uint64), pend_hi=np.zeros(n, np.uint64),
    amount_lo=rng.integers(1, 100, n).astype(np.uint64),
    amount_hi=np.zeros(n, np.uint64),
    flags=np.zeros(n, np.uint32), ledger=np.ones(n, np.uint32),
    code=np.ones(n, np.uint32), timeout=np.zeros(n, np.uint32),
    ts_nonzero=np.zeros(n, bool),
    dr_slot=dr.astype(np.int64), cr_slot=((dr + 1) % 1000).astype(np.int64),
    e_found=np.zeros(n, bool),
)
pkj = jax.device_put(pk)
meta = jnp.ones((A, 2), jnp.uint32)
balances = jnp.zeros((A, 8), jnp.uint64)
ring = jnp.zeros((256, dk.SUMMARY_WORDS), jnp.uint64)
kern = dk.orderfree_lo
b, r = kern(balances, meta, ring, 0, pkj, n, jnp.uint64(1))
jax.block_until_ready(r)

with jax.profiler.trace("/tmp/xprof"):
    b2, r2 = balances, ring
    for k in range(8):
        b2, r2 = kern(b2, meta, r2, k, pkj, n, jnp.uint64(1))
    jax.block_until_ready(r2)
print("trace done")
