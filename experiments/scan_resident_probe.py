"""r5: launch-overhead hypothesis — scan G batches per launch with
RESIDENT inputs; compare per-batch wall vs solo dispatches."""
import sys, time
sys.path.insert(0, "/root/repo")
import numpy as np
import jax
import jax.numpy as jnp
from tigerbeetle_tpu.state_machine import device_kernels as dk

A = 1 << 12
rng = np.random.default_rng(0)
n = dk.B
dr = rng.integers(0, 1000, n)
pk = dk.pack_base(
    n,
    id_lo=np.arange(1, n + 1, dtype=np.uint64), id_hi=np.zeros(n, np.uint64),
    dr_lo=dr.astype(np.uint64) + 1, dr_hi=np.zeros(n, np.uint64),
    cr_lo=(dr.astype(np.uint64) % 1000) + 2, cr_hi=np.zeros(n, np.uint64),
    pend_lo=np.zeros(n, np.uint64), pend_hi=np.zeros(n, np.uint64),
    amount_lo=rng.integers(1, 100, n).astype(np.uint64),
    amount_hi=np.zeros(n, np.uint64),
    flags=np.zeros(n, np.uint32), ledger=np.ones(n, np.uint32),
    code=np.ones(n, np.uint32), timeout=np.zeros(n, np.uint32),
    ts_nonzero=np.zeros(n, bool),
    dr_slot=dr.astype(np.int64), cr_slot=((dr + 1) % 1000).astype(np.int64),
    e_found=np.zeros(n, bool),
)
meta = jnp.ones((A, 2), jnp.uint32)

for G in (8, 16, 32):
    stack = jax.device_put(np.broadcast_to(pk, (G,) + pk.shape).copy())
    ns = jnp.full(G, n, jnp.int32)
    tsb = jnp.arange(G, dtype=jnp.uint64) * jnp.uint64(n)

    def scan_g(table, ring, ring_at0, stack, ns, tsb):
        def step(carry, xs):
            table, ring = carry
            g, nn, t = xs
            table, ring = dk._orderfree(
                table, meta, ring, ring_at0 + g, stack[g], nn, t,
                lo_only=True,
            )
            return (table, ring), None
        (table, ring), _ = jax.lax.scan(
            step, (table, ring), (jnp.arange(G), ns, tsb))
        return table, ring

    jscan = jax.jit(scan_g)
    table = jnp.zeros((A, 8), jnp.uint64)
    ring = jnp.zeros((256, dk.SUMMARY_WORDS), jnp.uint64)
    t, r = jscan(table, ring, 0, stack, ns, tsb)
    jax.block_until_ready(r)
    K = max(2, 64 // G)
    t0 = time.perf_counter()
    t2, r2 = table, ring
    for k in range(K):
        t2, r2 = jscan(t2, r2, (k * G) % 128, stack, ns, tsb)
    jax.block_until_ready(r2)
    dt = time.perf_counter() - t0
    per = dt / (K * G)
    print(f"scan G={G:2d}: {per*1e3:6.2f} ms/batch -> {n/per:,.0f} ev/s")
