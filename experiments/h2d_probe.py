"""Probe 6: why do h2d transfers slow to ~25-50ms inside a dispatch
loop?  Isolate: transfer-only loops, stream business, donation."""
import time

import jax

jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

A = 4096
B = 8190
dev = jax.devices()[0]
rng = np.random.default_rng(0)


def fresh():
    return rng.integers(0, 1 << 60, (B, 6)).astype(np.uint64)


# A. h2d-only loop, fresh data, block only at end
for n in (30,):
    arrs = []
    t0 = time.perf_counter()
    for _ in range(n):
        arrs.append(jnp.asarray(fresh()))
    jax.block_until_ready(arrs)
    ms = (time.perf_counter() - t0) / n * 1e3
    print(f"A h2d-only fresh 400KB: {ms:6.2f} ms each")

# A2. h2d-only, block each
t0 = time.perf_counter()
for _ in range(30):
    jax.block_until_ready(jnp.asarray(fresh()))
ms = (time.perf_counter() - t0) / 30 * 1e3
print(f"A2 h2d-only blocked each: {ms:6.2f} ms each")


# B. h2d + trivial kernel on the same fresh data (no donation)
@jax.jit
def red(x):
    return x.sum(axis=0)


jax.block_until_ready(red(jnp.asarray(fresh())))
outs = []
t0 = time.perf_counter()
for _ in range(30):
    outs.append(red(jnp.asarray(fresh())))
jax.block_until_ready(outs)
ms = (time.perf_counter() - t0) / 30 * 1e3
print(f"B h2d + reduce (no donation): {ms:6.2f} ms each")

# C. h2d + chained donated-table kernel (like production), block each
@jax.jit
def chaink(table, x):
    return table + x.sum(axis=0)[None, :2], x[:, 0]


chainkd = jax.jit(chaink, donate_argnums=(0,))
table = jnp.zeros((A, 2), jnp.uint64)
table, r = chainkd(table, jnp.asarray(fresh()))
jax.block_until_ready(r)
t0 = time.perf_counter()
for _ in range(30):
    table, r = chainkd(table, jnp.asarray(fresh()))
    np.asarray(r)
ms = (time.perf_counter() - t0) / 30 * 1e3
print(f"C h2d + donated chain, sync each: {ms:6.2f} ms each")

# D. h2d + donated chain, never fetch (block end)
table = jnp.zeros((A, 2), jnp.uint64)
rs = []
t0 = time.perf_counter()
for _ in range(30):
    table, r = chainkd(table, jnp.asarray(fresh()))
    rs.append(r)
jax.block_until_ready(rs)
ms = (time.perf_counter() - t0) / 30 * 1e3
print(f"D h2d + donated chain, block end: {ms:6.2f} ms each")

# E. same as D but reuse ONE device-resident input (no h2d)
x0 = jax.block_until_ready(jnp.asarray(fresh()))
table = jnp.zeros((A, 2), jnp.uint64)
rs = []
t0 = time.perf_counter()
for _ in range(30):
    table, r = chainkd(table, x0)
    rs.append(r)
jax.block_until_ready(rs)
ms = (time.perf_counter() - t0) / 30 * 1e3
print(f"E no-h2d donated chain, block end: {ms:6.2f} ms each")

# F. D with a host sleep per iter (is h2d fine when stream drains?)
table = jnp.zeros((A, 2), jnp.uint64)
rs = []
t0 = time.perf_counter()
for _ in range(30):
    table, r = chainkd(table, jnp.asarray(fresh()))
    rs.append(r)
    time.sleep(0.02)
jax.block_until_ready(rs)
ms = (time.perf_counter() - t0) / 30 * 1e3 - 20
print(f"F h2d + donated chain + 20ms sleep: {ms:6.2f} ms each (sleep excluded)")

# G. smaller h2d payloads in the loop
@jax.jit
def redsm(x):
    return x.sum()


jax.block_until_ready(redsm(jnp.asarray(np.zeros(1024, np.uint64))))
for size in (1024, 16384, B * 6):
    outs = []
    data = [rng.integers(0, 1 << 60, size).astype(np.uint64) for _ in range(30)]
    t0 = time.perf_counter()
    for d in data:
        outs.append(redsm(jnp.asarray(d)))
    jax.block_until_ready(outs)
    ms = (time.perf_counter() - t0) / 30 * 1e3
    print(f"G h2d {size*8>>10:5d}KB + tiny reduce: {ms:6.2f} ms each")
