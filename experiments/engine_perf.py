"""Real-TPU throughput of the device-authoritative engine at bench
scale (zipf-shaped workload), across stage/fetch tunings."""
import os
import sys
import time

sys.path.insert(0, "/root/repo")

import numpy as np

N = int(os.environ.get("PERF_N", "500000"))
BATCH = 8190


def main():
    from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine
    from tigerbeetle_tpu.testing.harness import SingleNodeHarness
    from tigerbeetle_tpu.types import Operation
    import bench

    rng = np.random.default_rng(45)
    n_acct = 100
    setup = [(Operation.create_accounts,
              bench.accounts_bytes(range(1, n_acct + 1)))]
    dr = rng.integers(1, n_acct + 1, N, np.uint64)
    timed = bench.batched({
        "ids": np.arange(1, N + 1, dtype=np.uint64),
        "dr": dr,
        "cr": dr % np.uint64(n_acct) + np.uint64(1),
        "amount": rng.integers(1, 100, N, np.uint64),
    })
    warm = bench.batched({
        "ids": np.arange(50_000_000, 50_000_000 + BATCH, dtype=np.uint64),
        "dr": dr[:BATCH], "cr": dr[:BATCH] % np.uint64(n_acct) + np.uint64(1),
        "amount": rng.integers(1, 100, BATCH, np.uint64),
    })

    sm = TpuStateMachine(
        engine="device", account_capacity=1 << 12,
        transfer_capacity=N + 3 * BATCH,
    )
    h = SingleNodeHarness(sm)
    for op, body in setup + warm:
        h.submit(op, body)
    sm.sync()
    eng0 = sm._dev
    eng0.stat_t_h2d = eng0.stat_t_dispatch = 0.0
    eng0.stat_t_fetch = eng0.stat_t_finish = 0.0
    eng0.stat_fetches = 0

    t0 = time.perf_counter()
    futs = [h.submit_async(op, body) for op, body in timed]
    t_submit = time.perf_counter() - t0
    replies = [f.result() for f in futs]
    sm.sync()
    dt = time.perf_counter() - t0
    print(f"  submit loop: {t_submit:.2f}s, resolve: {dt - t_submit:.2f}s")
    failed = sum(len(r) // 8 for r in replies)
    eng = sm._dev
    print(
        f"WINDOW={os.environ.get('TB_DEV_WINDOW', '96')}: "
        f"{N/dt:,.0f} ev/s  ({dt:.2f}s, failed={failed}, "
        f"fetches={eng.stat_fetches}, semantic={eng.stat_semantic_events})"
    )
    print(
        f"  split: h2d={eng.stat_t_h2d:.2f}s dispatch={eng.stat_t_dispatch:.2f}s "
        f"fetch={eng.stat_t_fetch:.2f}s finish={eng.stat_t_finish:.2f}s"
    )


main()
