from tigerbeetle_tpu.testing.harness import SingleNodeHarness, account, transfer

__all__ = ["SingleNodeHarness", "account", "transfer"]
