"""Continuous-fuzzing soak orchestrator (reference: src/scripts/cfo.zig
— the CFO fleet runs seeded VOPR simulators and component fuzzers
around the clock and files whatever falls out).

Runs waves of randomized-parameter VOPR clusters and/or long-round
component fuzzers, one JSONL record per case, and prints a repro
command for every failure:

    python -m tigerbeetle_tpu.testing.soak vopr --n 200 --seed-base 7
    python -m tigerbeetle_tpu.testing.soak fuzz --n 40
    python -m tigerbeetle_tpu.testing.soak all  --n 100 --out soak.jsonl

Every case is fully determined by its printed parameters: a failing
record replays exactly (the VOPR regression tests in
tests/test_vopr.py are pinned soak finds)."""
# tbcheck: allow-file(no-print): soak orchestrator — case records
# and repro commands print to the operator by design.

from __future__ import annotations

import argparse
import json
import random
import sys
import traceback


def _vopr_case(rng: random.Random) -> dict:
    return {
        "seed": rng.randrange(1, 1_000_000_000),
        "packet_loss": rng.uniform(0.0, 0.08),
        "crash_probability": rng.uniform(0.0, 0.035),
        "corruption_probability": rng.choice([0.0, 0.001, 0.005, 0.01]),
        "upgrade_nemesis": rng.random() < 0.3,
        "queries": rng.random() < 0.6,
        "replica_count": rng.choice([3, 3, 3, 5]),
        "standby_count": rng.choice([0, 0, 1]),
        "reconfigure_nemesis": rng.random() < 0.5,
        "partition_probability": rng.choice([0.0, 0.01, 0.02]),
        "requests": rng.choice([60, 120]),
    }


def _run_vopr(case: dict) -> None:
    from tigerbeetle_tpu.testing.vopr import Vopr

    kw = dict(case)
    seed = kw.pop("seed")
    Vopr(seed, **kw).run()


def _fuzz_case(rng: random.Random) -> dict:
    from tigerbeetle_tpu.testing.fuzz import FUZZERS

    return {
        "fuzzer": rng.choice(sorted(FUZZERS)),
        "seed": rng.randrange(1, 1_000_000_000),
        "rounds": rng.choice([500, 2000]),
    }


def _run_fuzz(case: dict) -> None:
    from tigerbeetle_tpu.testing.fuzz import FUZZERS

    FUZZERS[case["fuzzer"]](case["seed"], case["rounds"])


_KINDS = {"vopr": (_vopr_case, _run_vopr), "fuzz": (_fuzz_case, _run_fuzz)}


def main(argv: list[str]) -> int:
    ap = argparse.ArgumentParser(prog="soak")
    ap.add_argument("kind", choices=[*_KINDS, "all"])
    ap.add_argument("--n", type=int, default=100)
    ap.add_argument("--seed-base", type=int, default=0)
    ap.add_argument("--out", default="")
    args = ap.parse_args(argv)

    rng = random.Random(args.seed_base)
    out = open(args.out, "a") if args.out else None
    kinds = list(_KINDS) if args.kind == "all" else [args.kind]
    failures = 0
    for i in range(args.n):
        kind = kinds[i % len(kinds)]
        make, run = _KINDS[kind]
        case = make(rng)
        rec = {"kind": kind, **case}
        try:
            run(case)
            rec["ok"] = True
        # tbcheck: allow(broad-except): the soak fleet's whole job is
        # to record ANY failure as a JSONL repro case and keep going.
        except Exception:
            failures += 1
            rec["ok"] = False
            rec["traceback"] = traceback.format_exc()[-1500:]
            print(f"FAIL {kind} {json.dumps(case)}", file=sys.stderr)
        if out:
            out.write(json.dumps(rec) + "\n")
            out.flush()
        if (i + 1) % 25 == 0:
            print(f"soak: {i + 1}/{args.n}, failures={failures}", flush=True)
    print(f"soak: done, {args.n - failures}/{args.n} ok")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
