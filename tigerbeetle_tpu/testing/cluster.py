"""Deterministic in-process cluster: N replicas + clients, one thread.

The reference tests multi-node behavior without a real cluster by
instantiating every replica and client in one process over a simulated
network/storage/time (reference: src/testing/cluster.zig:56-70,
packet_simulator.zig:10-40).  Same pattern here: a seeded
`PacketSimulator` delivers bus messages with delay/loss/partitions,
`Cluster.step()` advances one tick, and identical seeds give identical
runs — which is also how TPU-vs-CPU state parity is checked
reproducibly.
"""

from __future__ import annotations

import dataclasses
import heapq

import numpy as np

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import qos as qos_mod
from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.testing.hash_log import HashLog
from tigerbeetle_tpu.vsr import replica as vsr_format
from tigerbeetle_tpu.vsr import wire
from tigerbeetle_tpu.vsr.multi import VsrReplica
from tigerbeetle_tpu.vsr.storage import MemoryStorage, ZoneLayout
from tigerbeetle_tpu.vsr.wire import Command, VsrOperation


@dataclasses.dataclass
class PacketOptions:
    """reference: src/testing/packet_simulator.zig:10-40."""

    one_way_delay_min: int = 1
    one_way_delay_max: int = 3
    packet_loss_probability: float = 0.0
    packet_replay_probability: float = 0.0


class PacketSimulator:
    """Seeded delay/loss/replay/partition between endpoints.

    Endpoints: replicas are ints 0..n-1; clients are u128 client ids.
    """

    def __init__(self, options: PacketOptions, seed: int = 0) -> None:
        self.options = options
        self.rng = np.random.default_rng(seed)
        self.now = 0
        self._queue: list[tuple[int, int, object]] = []  # (tick, seq, packet)
        self._seq = 0
        self.partitioned: set = set()  # endpoints cut off from everyone

    def partition(self, *endpoints) -> None:
        self.partitioned.update(endpoints)

    def heal(self, *endpoints) -> None:
        if endpoints:
            self.partitioned.difference_update(endpoints)
        else:
            self.partitioned.clear()

    def submit(self, src, dst, header: np.ndarray, body: bytes) -> None:
        if src in self.partitioned or dst in self.partitioned:
            return
        if self.rng.random() < self.options.packet_loss_probability:
            return
        copies = 1
        if self.rng.random() < self.options.packet_replay_probability:
            copies = 2
        for _ in range(copies):
            delay = int(
                self.rng.integers(
                    self.options.one_way_delay_min,
                    self.options.one_way_delay_max + 1,
                )
            )
            heapq.heappush(
                self._queue,
                (self.now + delay, self._seq, (src, dst, header.copy(), body)),
            )
            self._seq += 1

    def advance(self, deliver) -> None:
        """One tick: pop every packet due now and hand to `deliver`."""
        self.now += 1
        while self._queue and self._queue[0][0] <= self.now:
            _, _, (src, dst, header, body) = heapq.heappop(self._queue)
            if src in self.partitioned or dst in self.partitioned:
                continue
            deliver(dst, header, body)


class _Bus:
    """Per-replica bus endpoint feeding the packet simulator.  `src`
    is the PROCESS index; protocol messages address SLOTS, which the
    slot map (reconfiguration) translates back to processes."""

    def __init__(self, cluster: "Cluster", src) -> None:
        self.cluster = cluster
        self.src = src
        self._slot_map: list[int] | None = None

    def set_slot_map(self, members) -> None:
        self._slot_map = list(members)

    def send(self, dst: int, header: np.ndarray, body: bytes) -> None:
        if self._slot_map is not None and dst < len(self._slot_map):
            dst = self._slot_map[dst]
        self.cluster.network.submit(self.src, dst, header, body)

    def send_client(self, client: int, header: np.ndarray, body: bytes) -> None:
        self.cluster.network.submit(self.src, client, header, body)


class SimClient:
    """Driver-side client session: register, pipelined-one request,
    retransmit on timeout (reference: src/vsr/client.zig:18-120).

    A typed client_busy backs the retransmit cadence off with capped
    exponential delay + deterministic jitter (TB_BUSY_BACKOFF_MS;
    round 16): a shed storm answered by immediate retransmits
    re-offers the same overload and self-amplifies.  One sim tick is
    10 ms (constants.TICK_NS), so the ms knob converts directly; 0
    disables (the legacy immediate-cadence behavior)."""

    RETRY_TICKS = 8

    def __init__(self, cluster: "Cluster", client_id: int) -> None:
        from tigerbeetle_tpu import envcheck

        self.cluster = cluster
        self.id = client_id
        self.request_number = 0
        self.view_guess = 0
        self.reply: bytes | None = None
        self.registered = False
        self.evicted = False
        self.busy_replies = 0  # typed admission sheds received
        self.busy_backoffs = 0  # retransmits delayed by busy backoff
        self._backoff_base_ticks = int(
            round(envcheck.busy_backoff_ms() * 1e6 / cfg.TICK_NS)
        )
        self._busy_streak = 0
        self._backoff_until = -(10**9)
        self._inflight: tuple[np.ndarray, bytes] | None = None
        self._last_sent = -(10**9)
        self.replies: list[bytes] = []
        # Serving-tier attribution (round 19): which tier answered the
        # latest reply — ("primary"|"follower", server id, claimed
        # commit_min).  Primary replies carry no attestation carve-out
        # and report commit_min 0 here.
        self.reply_tier: tuple | None = None
        self.reply_tiers: list[tuple] = []

    # -- wire --

    def on_message(self, header: np.ndarray, body: bytes) -> None:
        if not wire.verify_header(header, body):
            return
        cmd = Command(int(header["command"]))
        if cmd == Command.client_busy:
            # Typed admission shed: NOT fatal — the request was never
            # admitted; the retransmission cadence retries it, backed
            # off exponentially per CONSECUTIVE busy (reset on reply)
            # with deterministic jitter so a fleet of shed clients
            # doesn't re-converge on one retry instant.
            self.busy_replies += 1
            if (
                self._backoff_base_ticks > 0
                and self._inflight is not None
                # A stale busy for an ALREADY-COMPLETED request (one
                # retransmit copy shed, another committed and replied)
                # must not inflate the streak or delay the CURRENT
                # request's cadence.
                and int(header["request"])
                == int(self._inflight[0]["request"])
            ):
                self._busy_streak += 1
                self._backoff_until = (
                    self.cluster.network.now + qos_mod.backoff_delay(
                        self.id, self.request_number, self._busy_streak,
                        self._backoff_base_ticks,
                    )
                )
                self.busy_backoffs += 1
            return
        if cmd == Command.eviction:
            # Fatal for the session (reference clients surface this as
            # a terminal error); recorded, not raised, so a multi-client
            # harness keeps stepping.
            self.evicted = True
            self._inflight = None
            return
        if cmd != Command.reply:
            return
        if self._inflight is None:
            return
        want_request = int(self._inflight[0]["request"])
        if int(header["request"]) != want_request:
            return
        self.view_guess = max(self.view_guess, int(header["view"]))
        if int(self._inflight[0]["operation"]) == int(VsrOperation.register):
            self.registered = True
        self._inflight = None
        self._busy_streak = 0
        self._backoff_until = -(10**9)
        self.reply = body
        self.replies.append(body)
        att = wire.attestation_of(header)
        self.reply_tier = (
            ("primary", int(header["replica"]), 0) if att is None
            else ("follower", int(header["replica"]), att[1])
        )
        self.reply_tiers.append(self.reply_tier)

    def tick(self) -> None:
        if self._inflight is None:
            return
        if self.cluster.network.now < self._backoff_until:
            return  # busy backoff window: hold the retransmit cadence
        if self.cluster.network.now - self._last_sent >= self.RETRY_TICKS:
            self._send(broadcast=True)

    # -- api --

    def busy(self) -> bool:
        return self._inflight is not None

    def register(self) -> None:
        assert not self.busy()
        h = wire.make_header(
            command=Command.request, operation=VsrOperation.register,
            cluster=self.cluster.cluster_id, client=self.id, request=0,
        )
        wire.finalize_header(h, b"")
        self._inflight = (h, b"")
        self._send()

    def request(self, operation: types.Operation, body: bytes, *,
                tenant: int = 0) -> None:
        assert self.registered and not self.busy()
        self.request_number += 1
        import time as _time

        h = wire.make_header(
            command=Command.request, operation=operation,
            cluster=self.cluster.cluster_id, client=self.id,
            request=self.request_number,
            # Explicit tenant stamp (round 16): 0 = derive from the
            # body's leading event (the legacy-client path).
            tenant=tenant,
            # Wire trace context from client submit: the id is a
            # deterministic function of (client, request) so seeded
            # runs stay reproducible; the origin timestamp is real
            # CLOCK_MONOTONIC — observability only, never state.
            trace_id=((self.id << 20) ^ self.request_number)
            & 0xFFFFFFFFFFFFFFFF,
            trace_ts=_time.perf_counter_ns(),
            trace_flags=wire.TRACE_SAMPLED,
        )
        wire.finalize_header(h, body)
        self.reply = None
        self._inflight = (h, body)
        self._send()

    def _send(self, broadcast: bool = False) -> None:
        assert self._inflight is not None
        self._last_sent = self.cluster.network.now
        header, body = self._inflight
        targets = (
            range(self.cluster.replica_count)
            if broadcast
            else [self.view_guess % self.cluster.replica_count]
        )
        for r in targets:
            self.cluster.network.submit(
                self.id, self.cluster.process_of_slot(r), header, body
            )


class SimAof:
    """In-memory twin of vsr.aof.AOF for the deterministic cluster:
    same write/sync surface, bytes visible to tailers the moment they
    are written (page-cache semantics — a real tailer reads unsynced
    appends too), crash() loses a seeded cut of the unsynced suffix
    (possibly mid-record: the torn tail), reopen() models the
    repair-on-open scan (truncate the torn tail, recover last_op) so a
    restarted replica's recovery gap-fill re-appends exactly the
    committed records the crash erased."""

    def __init__(self) -> None:
        self.buffer = bytearray()
        self.synced_len = 0
        self.last_op = 0

    def write(self, header: np.ndarray, body: bytes) -> None:
        self.buffer += header.tobytes() + body
        if int(header["command"]) == int(Command.prepare):
            self.last_op = max(self.last_op, int(header["op"]))

    def sync(self) -> None:
        self.synced_len = len(self.buffer)

    def close(self) -> None:
        pass

    def source(self):
        from tigerbeetle_tpu.vsr.aof import BytesSource

        return BytesSource(self.buffer)

    def crash(self, rng) -> None:
        """Power loss: keep everything synced plus a seeded prefix of
        the unsynced suffix (a torn trailing record when the cut lands
        mid-record)."""
        keep = int(rng.integers(self.synced_len, len(self.buffer) + 1))
        del self.buffer[keep:]

    def reopen(self) -> "SimAof":
        """The AOF(path, repair=True) scan: truncate a torn tail to
        the verified record boundary and recompute last_op, so
        recovery replay knows which committed ops to re-append."""
        from tigerbeetle_tpu.vsr.aof import AofTail

        tail = AofTail(self.source())
        self.last_op = 0
        while True:
            entries = tail.poll(limit=1024)
            if not entries:
                break
            for header, _body in entries:
                if int(header["command"]) == int(Command.prepare):
                    self.last_op = max(self.last_op, int(header["op"]))
        del self.buffer[tail.offset:]
        self.synced_len = min(self.synced_len, len(self.buffer))
        return self

    def corrupt(self, rng) -> int | None:
        """Flip one byte of a seeded already-written sector (the
        latent-corruption nemesis for tailed logs).  Returns the
        offset, or None when the log is empty."""
        if not self.buffer:
            return None
        at = int(rng.integers(len(self.buffer)))
        self.buffer[at] ^= 0xFF
        return at


class Cluster:
    def __init__(self, replica_count: int = 3, *, seed: int = 0,
                 standby_count: int = 0,
                 config: cfg.Config = cfg.TEST_MIN,
                 options: PacketOptions | None = None,
                 state_machine_factory=None,
                 tenant_qos: dict | None = None,
                 aof_replicas: tuple = (),
                 root_ring: int = 0) -> None:
        self.cluster_id = 0xC1
        self.replica_count = replica_count
        self.standby_count = standby_count
        self.config = config
        self.network = PacketSimulator(options or PacketOptions(), seed)
        factory = state_machine_factory or (lambda: CpuStateMachine(config))
        self._factory = factory
        # Multi-tenant QoS (round 16): TenantQos kwargs applied to
        # every replica — including restarts, which build a fresh
        # VsrReplica (a restarted replica silently losing its
        # admission policy would fake isolation coverage in VOPR).
        self.tenant_qos = tenant_qos
        # Follower serving (round 19): replicas in `aof_replicas` keep
        # a SimAof a SimFollower can tail; `root_ring` > 0 enables the
        # per-commit root ring on every replica (the at-op attestation
        # source) and the cluster-owned root history — the ground
        # truth the refuse-not-lie audit compares follower replies
        # against.
        self.aofs: dict[int, SimAof] = {
            i: SimAof() for i in aof_replicas
        }
        self.root_ring_size = root_ring
        self.root_history: dict[int, bytes] = {}
        self.followers: list = []

        self.replicas: list[VsrReplica] = []
        self.storages: list[MemoryStorage] = []
        for i in range(replica_count + standby_count):
            storage = MemoryStorage(
                ZoneLayout(config=config, grid_size=1 << 20), seed=seed + i
            )
            vsr_format.format(storage, self.cluster_id, i, replica_count)
            r = VsrReplica(
                storage, self.cluster_id, factory(), _Bus(self, i),
                replica=i, replica_count=replica_count,
                standby_count=standby_count, aof=self.aofs.get(i),
            )
            self._apply_tenant_qos(r)
            r.hash_log = HashLog()
            r.open()
            if self.root_ring_size:
                r.enable_root_ring(self.root_ring_size)
            self.storages.append(storage)
            self.replicas.append(r)
        # Cluster-owned so logs survive replica restarts.
        self.hash_logs = [r.hash_log for r in self.replicas]
        self.clients: dict[int, SimClient] = {}
        self.realtime = 0
        # Per-replica wall-clock skew in ns (nemesis knob): replica i
        # observes realtime + clock_skew[i].  The synchronized clock
        # (vsr/clock.py) must keep primary timestamps near true time
        # despite this.
        self.clock_skew = [0] * (replica_count + standby_count)

    def _apply_tenant_qos(self, r) -> None:
        if self.tenant_qos is None:
            return
        from tigerbeetle_tpu.qos import TenantQos

        kw = dict(self.tenant_qos)
        r.admit_queue = kw.pop("admit_queue", r.admit_queue)
        r.qos = TenantQos(**kw)

    def process_of_slot(self, slot: int) -> int:
        """Current process filling a protocol slot (reconfiguration
        moves slots between processes).  Routing follows the freshest
        ADOPTED membership — which process answers for a slot NOW —
        not the committed one: a replica that heartbeat-adopted a
        newer epoch but hasn't replayed its ops yet would otherwise
        steer requests at the stale mapping."""
        best_epoch, best = -1, None
        for i, r in enumerate(self.replicas):
            if r.status != "normal":
                continue
            # A nemesis-partitioned replica may hold the freshest
            # adopted membership, but no client can reach it (or any
            # process its mapping names through it): routing by its
            # view would steer requests at a mapping no reachable
            # replica answers.  Skip it; heal/failover restores it.
            if i in self.network.partitioned:
                continue
            members = r.members_adopted or r.members
            epoch = max(r.epoch_adopted, r.epoch)
            if members is not None and epoch > best_epoch:
                best_epoch, best = epoch, members
        if best is not None and slot < len(best):
            return best[slot]
        return slot

    def client(self, client_id: int) -> SimClient:
        # Replica addresses (actives then standbys) occupy
        # [0, replica_count + standby_count) in the packet simulator's
        # flat namespace.
        assert client_id >= len(self.replicas), "client id collides with replica"
        c = SimClient(self, client_id)
        self.clients[client_id] = c
        return c

    def register_endpoint(self, client_id: int, endpoint) -> None:
        """Attach a non-SimClient wire endpoint (anything with
        on_message/tick) under a client id — the sharded router's
        per-shard sessions plug in here.  Replaces any previous holder
        of the id (a new router incarnation re-claims its impersonated
        session ids)."""
        assert client_id >= len(self.replicas)
        self.clients[client_id] = endpoint

    def remove_endpoint(self, client_id: int, endpoint) -> None:
        if self.clients.get(client_id) is endpoint:
            del self.clients[client_id]

    # ------------------------------------------------------------------
    # Nemesis (reference: src/simulator.zig:194-204 crash/restart).

    def crash_replica(self, index: int) -> None:
        """Power-loss crash: unsynced sectors are lost (seeded), the
        process is gone until restart_replica."""
        self.storages[index].crash()
        aof = self.aofs.get(index)
        if aof is not None:
            # The AOF loses a seeded cut of its unsynced suffix with
            # the process — the torn-tail nemesis for tailers.
            aof.crash(self.storages[index]._rng)
        self.network.partition(index)
        self.replicas[index].status = "crashed"

    def restart_replica(self, index: int, state_machine=None, *,
                        release: int | None = None,
                        releases_available: tuple[int, ...] | None = None,
                        ) -> None:
        """Restart; optionally with a different installed binary bundle
        (releases_available) and/or running release — the harness-side
        half of the multiversion upgrade (reference:
        src/vsr/replica.zig:4298 replica_release_execute)."""
        storage = self.storages[index]
        self.network.heal(index)
        old = self.replicas[index]
        avail = releases_available or old.releases_available
        aof = self.aofs.get(index)
        if aof is not None:
            # Repair-on-open: truncate the torn tail, recover last_op
            # — recovery replay gap-fills the committed records the
            # crash erased (vsr/replica.py replay path).
            aof.reopen()
        r = VsrReplica(
            storage, self.cluster_id,
            state_machine or self._factory(), _Bus(self, index),
            replica=index, replica_count=self.replica_count,
            standby_count=self.standby_count, aof=aof,
            release=release if release is not None else old.release,
            releases_available=avail,
        )
        self._apply_tenant_qos(r)
        r.hash_log = self.hash_logs[index]
        r.open()
        if self.root_ring_size:
            r.enable_root_ring(self.root_ring_size)
        # Pre-crash commits beyond the durable checkpoint floor may
        # have been lost with the process and superseded — drop them.
        r.hash_log.prune_above(int(r.superblock.working["commit_min"]))
        self.replicas[index] = r

    # ------------------------------------------------------------------

    def step(self) -> None:
        """One tick: advance time, tick everyone, deliver due packets."""
        self.realtime += cfg.TICK_NS
        for i, r in enumerate(self.replicas):
            if r.status == "crashed":
                continue
            r.realtime = self.realtime + self.clock_skew[i]
            r.tick()
        for c in self.clients.values():
            c.tick()
        for f in self.followers:
            f.tick()
        self.network.advance(self._deliver)
        # Group-commit flush point (deterministic: once per step, in
        # replica order).  A no-op unless a test opted the replica's
        # MemoryStorage into deferred sync.
        for r in self.replicas:
            if r.status != "crashed":
                r.flush_group_commit()
        if self.root_ring_size:
            self._merge_root_history()

    def _merge_root_history(self) -> None:
        """Fold every live replica's root ring into the cluster-owned
        op -> root truth map, asserting cross-replica agreement — the
        ground truth the follower refuse-not-lie audit (and any
        client-side verification) compares attested replies against."""
        merged = getattr(self, "_root_merged", None)
        if merged is None:
            merged = self._root_merged = {}
        for i, r in enumerate(self.replicas):
            if r.root_ring is None or r.status == "crashed":
                continue
            mark = merged.get(i, 0)
            new_mark = mark
            # Ring insertion order is ascending op; walk the fresh
            # suffix only.
            for op in reversed(r.root_ring):
                if op <= mark:
                    break
                root = r.root_ring[op]
                prev = self.root_history.get(op)
                if prev is None:
                    self.root_history[op] = root
                else:
                    assert prev == root, (
                        f"replica {i} state root diverged at op {op}"
                    )
                new_mark = max(new_mark, op)
            merged[i] = new_mark

    def _deliver(self, dst, header: np.ndarray, body: bytes) -> None:
        if isinstance(dst, int) and dst < len(self.replicas):
            # A crashed process receives nothing: in-flight packets to
            # it die with it (processing them would let a zombie
            # journal prepares and send acks from beyond the grave).
            if self.replicas[dst].status == "crashed":
                return
            self.replicas[dst].on_message(header, body)
        else:
            client = self.clients.get(dst)
            if client is not None:
                client.on_message(header, body)

    def run_until(self, cond, max_steps: int = 2000) -> None:
        for _ in range(max_steps):
            if cond():
                return
            self.step()
        raise AssertionError(f"condition not reached in {max_steps} steps")

    def run_request(self, client: SimClient, operation: types.Operation,
                    body: bytes, max_steps: int = 2000) -> bytes:
        client.request(operation, body)
        self.run_until(lambda: not client.busy(), max_steps)
        assert client.reply is not None or client.reply == b""
        return client.reply

    # ------------------------------------------------------------------
    # Checkers (reference: src/testing/cluster/state_checker.zig:27-45).

    def check_linearized(self) -> None:
        """Every pair of replicas agrees on the prepare at every op
        both have committed."""
        for a in range(len(self.replicas)):
            for b in range(a + 1, len(self.replicas)):
                ra, rb = self.replicas[a], self.replicas[b]
                # The checkpoint op itself may never have been
                # journaled (state sync installs state, not prepares):
                # compare strictly above it.
                lo = max(
                    1,
                    max(ra.checkpoint_op, rb.checkpoint_op) + 1,
                    min(ra.commit_min, rb.commit_min)
                    - self.config.journal_slot_count + 1,
                )
                for op in range(lo, min(ra.commit_min, rb.commit_min) + 1):
                    pa = ra.journal.read_prepare(op)
                    pb = rb.journal.read_prepare(op)
                    assert pa is not None and pb is not None, (a, b, op)
                    assert pa[0].tobytes() == pb[0].tobytes(), (a, b, op)

    def check_convergence(self) -> None:
        """All replicas at the same commit must hold identical state.
        On divergence the hash logs name the exact first divergent op
        (reference: src/testing/hash_log.zig)."""
        commits = {r.commit_min for r in self.replicas}
        assert len(commits) == 1, commits
        snaps = {r.sm.snapshot() for r in self.replicas}
        # The commit streams must agree op-for-op (even when the end
        # states happen to match).
        for i, a in enumerate(self.hash_logs):
            for j, b in enumerate(self.hash_logs[i + 1 :], i + 1):
                op = a.first_divergence(b)
                suffix = "" if len(snaps) == 1 else " (states diverged)"
                assert op is None, (
                    f"replicas {i}/{j} diverged first at op {op}{suffix}"
                )
        assert len(snaps) == 1, (
            "state machines diverged after identical commit hashes "
            "(non-deterministic state outside the commit path)"
        )
        # State roots are the cheap always-on rendering of the same
        # convergence claim (commitment.py): every replica at the same
        # commit must report one 16-byte root.  Snapshot equality
        # above makes this mostly redundant — it is asserted anyway so
        # a root computation that diverges between replicas (e.g. an
        # incremental-twin drift on one) fails HERE with the roots in
        # hand, not later at a checkpoint assert.
        roots = {
            r.sm.state_root()
            for r in self.replicas
            if hasattr(r.sm, "state_root")
        }
        assert len(roots) <= 1, (
            f"state roots diverged: {sorted(x.hex() for x in roots)}"
        )

    def settle(self, max_steps: int = 3000) -> None:
        """Run until all replicas have converged on the same commit."""
        def converged():
            if any(c.busy() for c in self.clients.values()):
                return False
            commits = {r.commit_min for r in self.replicas}
            ops = {r.op for r in self.replicas}
            return len(commits) == 1 and len(ops) == 1 and all(
                r.status == "normal" for r in self.replicas
            )

        self.run_until(converged, max_steps)


class SimFollower:
    """Deterministic follower harness: the EXACT FollowerCore the TCP
    FollowerServer runs, driven tick-by-tick over a SimAof's buffer,
    with attestation modeled as direct state_root at-op queries
    against the cluster's replicas (the wire transport is covered by
    the tier-1 TCP smoke; the sim covers the state machine).

    Nemesis surface: `partitioned` stops attestations (the follower
    cannot reach the upstream), `paused` stops replay (lag injection),
    `crash_restart()` rebuilds the core from a fresh state machine and
    offset 0 (crash mid-tail: everything re-derives from the log).
    Every serve() goes through `read()`, which appends the attested
    (root, commit_min) of successful replies to `served` — the
    refuse-not-lie audit replays that list against
    cluster.root_history.
    """

    def __init__(self, cluster: Cluster, upstream: int, *,
                 follower_id: int = 1, staleness_ops: int = 64,
                 attest_every: int = 4,
                 state_machine_factory=None) -> None:
        assert upstream in cluster.aofs, "upstream replica keeps no AOF"
        assert cluster.root_ring_size, "attestation needs the root ring"
        self.cluster = cluster
        self.upstream = upstream
        self.follower_id = follower_id
        self.staleness_ops = staleness_ops
        self.attest_every = attest_every
        self._factory = (
            state_machine_factory
            or (lambda: CpuStateMachine(cluster.config))
        )
        self.partitioned = False
        self.paused = False
        self._ticks = 0
        self._attest_current = False
        self.served: list[tuple[bytes, int]] = []  # (root, commit_min)
        self.refusals: list[int] = []              # FollowerRefuse codes
        self.crashes = 0
        self._new_core()
        cluster.followers.append(self)

    def _new_core(self) -> None:
        from tigerbeetle_tpu.runtime.follower import FollowerCore

        self.core = FollowerCore(
            self.cluster.aofs[self.upstream].source(),
            cluster=self.cluster.cluster_id,
            state_machine=self._factory(),
            follower_id=self.follower_id,
            staleness_ops=self.staleness_ops,
        )

    # -- nemesis --------------------------------------------------------

    def crash_restart(self) -> None:
        """kill -9 mid-tail: all volatile state (replayed state
        machine, attestation progress, resume offset) dies; the
        restarted follower re-derives everything from the log and must
        refuse (unattested) until it re-verifies."""
        self.crashes += 1
        self._new_core()

    # -- drive ----------------------------------------------------------

    def tick(self) -> None:
        if self.paused:
            return
        self._ticks += 1
        self.core.pump()
        if self._ticks % self.attest_every == 0:
            self._attest()

    def _attest(self) -> None:
        """One sessionless state_root query against the upstream
        replica, alternating at-op (verification) with current (lag
        estimate) — the transport-free model of the FollowerServer
        attestation loop."""
        if self.partitioned:
            return
        r = self.cluster.replicas[self.upstream]
        if r.status != "normal":
            return
        self._attest_current = not self._attest_current
        core = self.core
        now_ns = self.cluster.network.now * 10**6  # tick clock
        if self._attest_current or core.commit_min == 0:
            root = r.root_at(r.commit_min)
            if root is None and hasattr(r.sm, "state_root"):
                root = r.sm.state_root()
            if root is not None:
                core.on_attestation(root, r.commit_min, now_ns=now_ns)
        else:
            root = r.root_at(core.commit_min)
            if root is not None:
                core.on_attestation(root, core.commit_min, now_ns=now_ns)
            # Ring miss (op no longer retained): the server answers
            # current instead.
            elif r.commit_min and r.root_at(r.commit_min) is not None:
                core.on_attestation(r.root_at(r.commit_min),
                                    r.commit_min, now_ns=now_ns)

    def read(self, operation, body: bytes):
        """One read attempt; successful replies record their attested
        (root, commit_min) for the audit.  Returns FollowerReply or
        FollowerRefusal."""
        from tigerbeetle_tpu.runtime.follower import FollowerReply

        result = self.core.serve(
            int(operation), body, now_ns=self.cluster.network.now * 10**6
        )
        if isinstance(result, FollowerReply):
            self.served.append((result.root, result.commit_min))
        else:
            self.refusals.append(int(result.reason))
        return result

    # -- audit ----------------------------------------------------------

    def check_never_lied(self) -> None:
        """THE invariant: every (root, commit_min) a reply carried
        matches the cluster's root history at that op — a follower
        under any nemesis may refuse or lag, never attest a state no
        replica committed."""
        for root, op in self.served:
            truth = self.cluster.root_history.get(op)
            assert truth is not None, (
                f"follower served op {op} the cluster never recorded"
            )
            assert truth == root, (
                f"follower LIED at op {op}: served {root.hex()}, "
                f"cluster committed {truth.hex()}"
            )


# ----------------------------------------------------------------------
# Account-sharded multi-cluster harness: N deterministic shard clusters
# behind the sans-IO router core (runtime/router.py), with a
# coordinator-kill nemesis surface and cross-shard money checkers.


class _RouterEndpoint:
    """One wire session (client id) into one shard cluster, driven by
    the sim router transport: explicit request numbers, one op in
    flight at a time (FIFO queue — keeps retransmissions matching the
    shard's single stored reply per session), broadcast retransmission
    on the SimClient cadence."""

    RETRY_TICKS = 8

    def __init__(self, cluster: Cluster, client_id: int) -> None:
        self.cluster = cluster
        self.id = client_id
        self.registered = False
        self.evicted = False
        self._queue: list[dict] = []
        self._current: dict | None = None
        self._last_sent = -(10**9)
        # Coordinator auto-numbering: resumed from the register reply's
        # session-resume hint (+gap), so a new incarnation's numbers
        # land above everything the dead one committed or had in
        # flight.
        self.next_request = 1
        cluster.register_endpoint(client_id, self)
        # Sessions must exist shard-side before any request; queue the
        # (idempotent) register first thing.
        self.send(0, VsrOperation.register, b"",
                  lambda _body: setattr(self, "registered", True))

    def detach(self) -> None:
        self.cluster.remove_endpoint(self.id, self)

    def send(self, request: int, operation, body: bytes, callback,
             trace: tuple[int, int, int] = (0, 0, 0)) -> None:
        self._queue.append({
            "request": request, "operation": operation, "body": body,
            "callback": callback, "trace": trace,
        })
        self._pump()

    def _pump(self) -> None:
        if self._current is not None or not self._queue:
            return
        self._current = self._queue.pop(0)
        if self._current["request"] is None:
            # Coordinator numbering assigned at DEQUEUE time, after
            # the register reply's resume hint has been applied.
            self._current["request"] = self.next_request
            self.next_request += 1
        self._send()

    def _send(self) -> None:
        op = self._current
        self._last_sent = self.cluster.network.now
        h = wire.make_header(
            command=Command.request, operation=int(op["operation"]),
            cluster=self.cluster.cluster_id, client=self.id,
            request=op["request"], trace_id=op["trace"][0],
            trace_ts=op["trace"][1], trace_flags=op["trace"][2],
        )
        wire.finalize_header(h, op["body"])
        for r in range(self.cluster.replica_count):
            self.cluster.network.submit(
                self.id, self.cluster.process_of_slot(r), h, op["body"]
            )

    def on_message(self, header: np.ndarray, body: bytes) -> None:
        if not wire.verify_header(header, body):
            return
        cmd = Command(int(header["command"]))
        if cmd == Command.eviction:
            self.evicted = True
            return
        if cmd != Command.reply or self._current is None:
            return  # client_busy: the retransmit cadence retries
        if int(header["request"]) != self._current["request"]:
            return
        if self._current["request"] == 0 and int(
            self._current["operation"]
        ) == int(VsrOperation.register):
            from tigerbeetle_tpu.runtime.router import COORD_RESUME_GAP

            resume = wire.u128(header, "context")
            if resume:
                # Same fencing gap production uses — the sim must
                # validate the real protocol parameter.
                self.next_request = max(
                    self.next_request, resume + COORD_RESUME_GAP
                )
        cb = self._current["callback"]
        self._current = None
        cb(bytes(body))
        self._pump()

    def tick(self) -> None:
        if self._current is None:
            return
        if self.cluster.network.now - self._last_sent >= self.RETRY_TICKS:
            self._send()


class SimRouter:
    """Deterministic transport for RouterCore over in-process shard
    clusters.  Volatile by construction — kill_router() in the harness
    models a coordinator crash; a new incarnation recovers in-doubt
    transfers purely from shard state."""

    COORD_BASE = 7_000_000

    def __init__(self, sharded: "ShardedCluster", *, incarnation: int = 0,
                 recover: bool = False) -> None:
        from tigerbeetle_tpu import obs
        from tigerbeetle_tpu.obs.flight import FlightRecorder
        from tigerbeetle_tpu.runtime.router import RouterCore

        self.sharded = sharded
        self.incarnation = incarnation
        self.registry = obs.Registry()
        self.core = RouterCore(
            sharded.n_shards, coord_timeout_s=sharded.coord_timeout_s,
            registry=self.registry,
        )
        self.flight = FlightRecorder(process_id=100 + incarnation)
        self.core.flight = self.flight
        self.endpoints: list[_RouterEndpoint] = []
        self._coord: dict[int, _RouterEndpoint] = {}
        self._fwd: dict[tuple[int, int], _RouterEndpoint] = {}
        self._tasks: list[tuple[object, object]] = []
        self._open: set[tuple[int, int]] = set()
        self._register_watch: list[tuple[int, object]] = []
        self.recovery_result: dict | None = None
        self._recovery = None
        if recover:
            self._recovery = self.core.recover()
            self._issue(self._recovery.subops)
            self._tasks.append((self._recovery, None))

    def _endpoint(self, cluster_index: int, client_id: int,
                  cache: dict, key) -> _RouterEndpoint:
        ep = cache.get(key)
        if ep is None:
            ep = _RouterEndpoint(self.sharded.shards[cluster_index],
                                 client_id)
            cache[key] = ep
            self.endpoints.append(ep)
        return ep

    def _issue(self, subops) -> None:
        for sub in subops:
            if sub.kind == "root":
                # Sessionless proof-of-state query: in production the
                # shard's server loop answers it outside consensus
                # (runtime/server.py _send_state_root_reply); the sim
                # transport models that by reading the live state
                # machine directly.
                from tigerbeetle_tpu.state_machine import commitment

                shard = self.sharded.shards[sub.shard]
                sm = self.sharded._live_sm(sub.shard)
                root = (
                    sm.state_root()
                    if hasattr(sm, "state_root")
                    else bytes(16)
                )
                commit_min = max(r.commit_min for r in shard.replicas)
                sub.complete(commitment.root_body(root, commit_min))
                continue
            if sub.kind == "fwd":
                ep = self._endpoint(sub.shard, sub.client, self._fwd,
                                    (sub.client, sub.shard))
                request = sub.request
            else:
                # One STABLE coordinator identity across incarnations
                # (request numbers resume via the register reply's
                # hint); request=None → assigned at dequeue.
                ep = self._endpoint(sub.shard, self.COORD_BASE,
                                    self._coord, sub.shard)
                request = None
            ep.send(request, sub.operation, sub.body,
                    (lambda body, s=sub: s.complete(body)), sub.trace)

    def register_client(self, client_id: int, callback) -> None:
        """Ensure the client's impersonated session exists on every
        shard, then call back (the router-side register handshake)."""
        for shard in range(self.sharded.n_shards):
            self._endpoint(shard, client_id, self._fwd,
                           (client_id, shard))
        self._register_watch.append((client_id, callback))

    def submit(self, client_id: int, request: int, operation,
               body: bytes, trace, on_reply) -> None:
        if (client_id, request) in self._open:
            return  # duplicate resubmission to the same incarnation
        self._open.add((client_id, request))
        task = self.core.open_request(client_id, request, operation,
                                      body, trace)
        self._issue(task.subops)
        self._tasks.append((task, (client_id, request, on_reply)))

    @property
    def idle(self) -> bool:
        return not self._tasks and not any(
            ep._current or ep._queue for ep in self.endpoints
        )

    def query_cluster_root(self) -> bytes:
        """The client-facing `state_root` query through the router
        core: per-shard roots fetched via "root" subops (synchronous
        in the sim transport) and folded deterministically.  Returns
        the 24-byte root_body(folded_root, n_shards)."""
        task = self.core.state_root()
        self._issue(task.subops)
        task.pump()
        assert task.done, "sim root subops must complete synchronously"
        return task.result

    def pump(self) -> None:
        done = []
        for entry in self._tasks:
            task, ctx = entry
            issued = task.pump()
            if issued:
                self._issue(issued)
            if task.done:
                done.append(entry)
        for entry in done:
            self._tasks.remove(entry)
            task, ctx = entry
            if ctx is None:
                self.recovery_result = task.result
            else:
                client_id, request, on_reply = ctx
                self._open.discard((client_id, request))
                on_reply(request, task.result)
        if self._register_watch:
            still = []
            for client_id, callback in self._register_watch:
                eps = [self._fwd[(client_id, s)]
                       for s in range(self.sharded.n_shards)]
                if all(ep.registered for ep in eps):
                    callback()
                else:
                    still.append((client_id, callback))
            self._register_watch = still

    def detach(self) -> None:
        for ep in self.endpoints:
            ep.detach()


class RoutedClient:
    """SimClient-compatible facade over the sharded router.  Survives
    coordinator kills: when the harness starts a new router
    incarnation, the in-flight request is resubmitted to it — the
    client-retransmission analog — and the shards' session dedupe plus
    the 2PC's derived-id idempotency make the replay safe."""

    def __init__(self, sharded: "ShardedCluster", client_id: int) -> None:
        self.sharded = sharded
        self.id = client_id
        self.request_number = 0
        self.registered = False
        self.reply: bytes | None = None
        self.replies: list[bytes] = []
        self._register_wanted = False
        self._inflight: tuple | None = None
        sharded.clients.append(self)

    def register(self) -> None:
        self._register_wanted = True
        self.attach()

    def attach(self) -> None:
        """(Re)connect to the current router incarnation."""
        router = self.sharded.router
        if router is None:
            return
        if self._register_wanted and not self.registered:
            router.register_client(self.id, self._on_registered)
        if self._inflight is not None:
            request, operation, body, trace = self._inflight
            router.submit(self.id, request, operation, body, trace,
                          self._on_reply)

    def _on_registered(self) -> None:
        self.registered = True

    def busy(self) -> bool:
        return (self._register_wanted and not self.registered) or (
            self._inflight is not None
        )

    def request(self, operation, body: bytes) -> None:
        assert self.registered and self._inflight is None
        import time as _time

        self.request_number += 1
        trace = (
            ((self.id << 20) ^ self.request_number) & 0xFFFFFFFFFFFFFFFF,
            _time.perf_counter_ns(),
            wire.TRACE_SAMPLED,
        )
        self.reply = None
        self._inflight = (self.request_number, operation, body, trace)
        router = self.sharded.router
        if router is not None:
            router.submit(self.id, self.request_number, operation, body,
                          trace, self._on_reply)

    def _on_reply(self, request: int, body: bytes) -> None:
        if self._inflight is not None and self._inflight[0] == request:
            self._inflight = None
            self.reply = body
            self.replies.append(body)


class ShardedCluster:
    """N deterministic shard clusters + the router, stepped together.

    Every per-shard nemesis of the single-cluster harness applies (via
    `.shards[i]`), plus the coordinator-kill nemesis: kill_router()
    forgets ALL router state mid-protocol; start_router() brings up a
    fresh incarnation that must recover in-doubt cross-shard transfers
    from shard state alone.
    """

    def __init__(self, n_shards: int = 2, *, replica_count: int = 2,
                 seed: int = 0, config: cfg.Config | None = None,
                 options: PacketOptions | None = None,
                 state_machine_factories=None,
                 coord_timeout_s: int = 8,
                 tenant_qos: dict | None = None) -> None:
        import dataclasses as _dc

        self.n_shards = n_shards
        # More session slots than TEST_MIN: each router incarnation
        # registers a coordinator session per shard on top of the
        # impersonated client sessions.
        self.config = config or _dc.replace(cfg.TEST_MIN, clients_max=16)
        self.coord_timeout_s = coord_timeout_s
        self.shards = [
            Cluster(
                replica_count, seed=seed + 7919 * s, config=self.config,
                options=options or PacketOptions(),
                state_machine_factory=(
                    state_machine_factories[s]
                    if state_machine_factories else None
                ),
                tenant_qos=tenant_qos,
            )
            for s in range(n_shards)
        ]
        self.clients: list[RoutedClient] = []
        self.router: SimRouter | None = None
        self.router_kills = 0
        self.start_router(recover=False)

    # -- coordinator lifecycle (the kill nemesis) ----------------------

    def start_router(self, recover: bool | None = None) -> SimRouter:
        assert self.router is None
        if recover is None:
            recover = self.router_kills > 0
        self.router = SimRouter(
            self, incarnation=self.router_kills, recover=recover,
        )
        for c in self.clients:
            c.attach()
        return self.router

    def kill_router(self) -> None:
        """Coordinator crash: every endpoint detaches, all volatile
        2PC state (open requests, stage progress, ensured-ledger cache)
        is gone."""
        assert self.router is not None
        self.router.detach()
        self.router = None
        self.router_kills += 1

    def client(self, client_id: int) -> RoutedClient:
        return RoutedClient(self, client_id)

    # -- stepping ------------------------------------------------------

    def step(self) -> None:
        for shard in self.shards:
            shard.step()
        if self.router is not None:
            self.router.pump()

    def run_until(self, cond, max_steps: int = 4000) -> None:
        for _ in range(max_steps):
            if cond():
                return
            self.step()
        raise AssertionError(f"condition not reached in {max_steps} steps")

    def run_request(self, client: RoutedClient, operation, body: bytes,
                    max_steps: int = 4000) -> bytes:
        client.request(operation, body)
        self.run_until(lambda: not client.busy(), max_steps)
        assert client.reply is not None or client.reply == b""
        return client.reply

    def settle(self, max_steps: int = 8000) -> None:
        def quiet() -> bool:
            if any(c.busy() for c in self.clients):
                return False
            if self.router is not None and not self.router.idle:
                return False
            return all(
                len({r.commit_min for r in s.replicas}) == 1
                and len({r.op for r in s.replicas}) == 1
                and all(r.status == "normal" for r in s.replicas)
                for s in self.shards
            )

        self.run_until(quiet, max_steps)

    # -- checkers ------------------------------------------------------

    def _live_sm(self, shard: int):
        c = self.shards[shard]
        for r in c.replicas:
            if r.status == "normal":
                return r.sm
        return c.replicas[0].sm

    def check_shards(self) -> None:
        """Per-shard hash-log convergence + linearized commit history
        (the single-cluster checkers, per consensus group)."""
        for shard in self.shards:
            shard.check_linearized()
            shard.check_convergence()

    def _balance_sums(self, sm) -> tuple[int, int, int, int]:
        """(debits_pending, credits_pending, debits_posted,
        credits_posted) summed over every account of a state machine
        (CPU or TPU-backed)."""
        from tigerbeetle_tpu.state_machine import CpuStateMachine

        if isinstance(sm, CpuStateMachine):
            dp = sum(a.debits_pending for a in sm.accounts.values())
            cp = sum(a.credits_pending for a in sm.accounts.values())
            dpo = sum(a.debits_posted for a in sm.accounts.values())
            cpo = sum(a.credits_posted for a in sm.accounts.values())
            return dp, cp, dpo, cpo
        n = sm._attrs.count
        lo = sm._mirror.lo[:n].astype(object)
        hi = sm._mirror.hi[:n].astype(object)
        totals = [
            int((lo[:, c] + (hi[:, c] * (1 << 64))).sum()) for c in range(4)
        ]
        return totals[0], totals[2], totals[1], totals[3]

    def cluster_commitment(self) -> bytes:
        """The folded cluster state commitment: per-shard 16-byte
        roots combined with the router's deterministic fold
        (commitment.fold_cluster) — shard index bound into each
        contribution, so shards swapping state moves the root."""
        from tigerbeetle_tpu.state_machine import commitment

        return commitment.fold_cluster(
            [self._live_sm(s).state_root() for s in range(self.n_shards)]
        )

    def check_cluster_commitment(self) -> bytes:
        """Audit point: every replica of every shard agrees on its
        shard root, and the folded cluster commitment is well-defined
        (returned so callers can compare it against the router's
        query-path fold)."""
        for s, shard in enumerate(self.shards):
            roots = {
                r.sm.state_root()
                for r in shard.replicas
                if hasattr(r.sm, "state_root")
            }
            assert len(roots) <= 1, (
                f"shard {s} replicas disagree on state root: "
                f"{sorted(x.hex() for x in roots)}"
            )
        return self.cluster_commitment()

    def check_conservation(self) -> None:
        """Double-entry conservation PER SHARD, at any audit point:
        each shard's state machine is internally double-entry, holds
        included, so total debits == total credits in both columns —
        the 2PC never mints or destroys money inside a shard."""
        for s in range(self.n_shards):
            dp, cp, dpo, cpo = self._balance_sums(self._live_sm(s))
            assert dp == cp, (s, dp, cp)
            assert dpo == cpo, (s, dpo, cpo)

    def cross_status(self, tid: int, dshard: int, cshard: int):
        """(debit_hold_status, credit_hold_status, compensated) for one
        cross-shard transfer, read from live shard state.  Status is a
        TransferPendingStatus or None (hold never created)."""
        ids = types.XShardIds(tid)
        sm_d = self._live_sm(dshard)
        sm_c = self._live_sm(cshard)
        sd = sm_d.pending_status(ids.hold_debit)
        sc = sm_c.pending_status(ids.hold_credit)
        comp = sm_d.transfer_timestamp(ids.comp) is not None
        return sd, sc, comp

    def check_atomicity(self, xfers, final: bool = False,
                        ledgers=(1,)) -> None:
        """Cross-shard conservation of money over the attempted
        cross-shard transfers `xfers` = [(tid, dshard, cshard), ...].

        At EVERY audit point (terminal states are monotone, so this is
        lag-safe even though the two shards are read at different
        commit points): a posted side never coexists with a
        voided/expired other side — no lost money, no double-post.
        The transient posted/pending combination is legal only until
        the coordinator (or its successor) finishes the credit side.

        At quiescence (`final=True`): every transfer is terminal —
        committed on both sides or aborted on both — and the
        settlement accounts net to zero across the cluster."""
        from tigerbeetle_tpu.types import TransferPendingStatus as TPS

        dead = (TPS.voided, TPS.expired)
        for tid, dshard, cshard in xfers:
            sd, sc, comp = self.cross_status(tid, dshard, cshard)
            if comp:
                # Compensated: decided-commit whose credit hold died
                # under it (budget violation, loudly flagged) — money
                # returned to the debitor.
                assert sd == TPS.posted and sc != TPS.posted, (tid, sd, sc)
                continue
            # The credit side can never be posted against a dead
            # debit-side decision: post_credit strictly follows a
            # committed post_debit, and a voided/expired debit hold
            # excludes one.  (Terminal-vs-terminal only — the two
            # shards are read at different commit points, so a
            # transiently lagging non-terminal read is not evidence.
            # The opposite direction — debit posted, credit hold
            # expired — is a legal transient awaiting compensation;
            # `final` requires it resolved.)
            assert not (sc == TPS.posted and sd in dead), (tid, sd, sc)
            assert not (sd == TPS.posted and sc == TPS.voided and final), (
                tid, sd, sc,
            )
            if final:
                assert sd != TPS.pending and sc != TPS.pending, (
                    tid, sd, sc,
                )
                committed = sd == TPS.posted
                assert committed == (sc == TPS.posted), (tid, sd, sc)
        if final:
            # Settlement accounts net to ZERO across the cluster: every
            # committed transfer credits the debit shard's settlement
            # account and debits the credit shard's by the same amount;
            # aborts touch only pending columns, and those are empty at
            # quiescence.
            imbalance = 0
            coord_ids = [types.coord_account_id(lg) for lg in ledgers]
            for s in range(self.n_shards):
                sm = self._live_sm(s)
                for aid in coord_ids:
                    bal = sm.account_balances_raw(aid)
                    if bal is None:
                        continue  # shard never saw a cross-shard leg
                    dp, dpo, cp, cpo = bal
                    assert dp == 0 and cp == 0, (s, aid, dp, cp)
                    imbalance += cpo - dpo
            assert imbalance == 0, imbalance


# ----------------------------------------------------------------------
# Cross-replica trace merging (observability spine, utils/tracer.py).


def merge_traces(trace_paths, out_path: str | None = None,
                 labels=None) -> dict:
    """Stitch per-replica Chrome-trace JSON files (utils/tracer.py
    dumps) into ONE Perfetto-loadable timeline: each input file
    becomes a named process track (`replica<i>`), so a replicated
    drain reads left-to-right across replicas — prepare on the
    primary, journal_write + covering gc sync on every replica,
    prepare_ok on the backups, commit + reply back on the primary.

    Timestamps are comparable because every tracer samples
    CLOCK_MONOTONIC (time.perf_counter_ns), whose epoch is shared by
    all processes on one host — merging traces from different hosts
    would need an offset pass (the vsr/clock.py sync could provide
    one; not needed for single-box clusters).

    Robustness: a missing, empty, truncated, or otherwise unparseable
    per-replica file (a replica killed mid-dump is the common case) is
    SKIPPED with a warning and listed under otherData.skipped — one
    bad file must not void a postmortem merge of the survivors.  Any
    number of inputs merges (>2-replica clusters, flight dumps mixed
    with live tracer dumps).
    """
    import json as _json
    import warnings

    merged_events: list[dict] = []
    dropped_total = 0
    skipped: list[dict] = []
    for i, path in enumerate(trace_paths):
        label = labels[i] if labels else f"replica{i}"
        try:
            with open(path) as f:
                data = _json.load(f)
            if not isinstance(data, dict):
                raise ValueError(f"expected a trace object, got "
                                 f"{type(data).__name__}")
            events = data.get("traceEvents", ())
            if not isinstance(events, list):
                raise ValueError("traceEvents is not a list")
        except (OSError, ValueError) as exc:
            # ValueError covers json.JSONDecodeError (its subclass):
            # empty and truncated files land here too.
            warnings.warn(
                f"merge_traces: skipping {label} ({path}): {exc}",
                stacklevel=2,
            )
            skipped.append({"label": label, "path": str(path),
                            "error": str(exc)})
            continue
        # Re-key pid per input file: every tracer defaults its own
        # process_id, and two replicas that both said pid=0 would
        # otherwise collapse onto one track.
        for ev in events:
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            ev["pid"] = i
            merged_events.append(ev)
        merged_events.append(
            {
                "name": "process_name", "ph": "M", "pid": i, "tid": 0,
                "args": {"name": label},
            }
        )
        other = data.get("otherData", {})
        if isinstance(other, dict):
            try:
                dropped_total += int(other.get("dropped_events", 0))
            except (TypeError, ValueError):
                pass
    merged = {
        "traceEvents": merged_events,
        "otherData": {"dropped_events": dropped_total},
    }
    if skipped:
        merged["otherData"]["skipped"] = skipped
    if out_path:
        with open(out_path, "w") as f:
            _json.dump(merged, f)
    return merged


def trace_demo(out_path: str, *, n_replicas: int = 2, batches: int = 8,
               transfers_per_batch: int = 16, seed: int = 7) -> dict:
    """One-command Perfetto demo (`tigerbeetle-tpu trace-demo`): drive
    a replicated drain through a deterministic n-replica cluster with
    per-replica JSON tracers and group commit live, then merge the
    traces into `out_path` (load it at https://ui.perfetto.dev).  The
    timeline shows prepare -> journal_write -> gc_covering_sync ->
    prepare_ok -> commit -> reply across all replica tracks.

    Returns {"replicas", "ops_committed", "events", "trace_path"}.
    """
    import os
    import tempfile

    from tigerbeetle_tpu.testing.harness import account, pack, transfer
    from tigerbeetle_tpu.utils.tracer import Tracer
    from tigerbeetle_tpu.vsr.storage import MemoryStorage

    # Group commit needs a deferred-sync-capable storage; the sim
    # cluster's MemoryStorage opts in per-class for the demo's scope
    # (the same opt-in tests/test_multi.py uses).
    had = MemoryStorage.supports_deferred_sync
    MemoryStorage.supports_deferred_sync = True
    try:
        cluster = Cluster(replica_count=n_replicas, seed=seed)
        for i, r in enumerate(cluster.replicas):
            r.set_tracer(Tracer("json", process_id=i))
        client = cluster.client(1000)
        client.register()
        cluster.run_until(lambda: client.registered)
        accounts = [account(1), account(2)]
        assert cluster.run_request(
            client, types.Operation.create_accounts, pack(accounts)
        ) == b""
        tid = 100
        for _ in range(batches):
            rows = []
            for _ in range(transfers_per_batch):
                rows.append(
                    transfer(
                        tid, debit_account_id=1, credit_account_id=2,
                        amount=1,
                    )
                )
                tid += 1
            assert cluster.run_request(
                client, types.Operation.create_transfers, pack(rows)
            ) == b""
        cluster.settle()
        tmp = tempfile.mkdtemp(prefix="tb_trace_demo_")
        paths = []
        for i, r in enumerate(cluster.replicas):
            p = os.path.join(tmp, f"replica{i}.json")
            r.tracer.write(p)
            paths.append(p)
        merge_traces(paths, out_path)
        return {
            "replicas": n_replicas,
            "ops_committed": cluster.replicas[0].commit_min,
            "events": batches * transfers_per_batch,
            "per_replica_traces": paths,
            "trace_path": out_path,
        }
    finally:
        MemoryStorage.supports_deferred_sync = had
