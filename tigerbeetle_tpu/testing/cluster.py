"""Deterministic in-process cluster: N replicas + clients, one thread.

The reference tests multi-node behavior without a real cluster by
instantiating every replica and client in one process over a simulated
network/storage/time (reference: src/testing/cluster.zig:56-70,
packet_simulator.zig:10-40).  Same pattern here: a seeded
`PacketSimulator` delivers bus messages with delay/loss/partitions,
`Cluster.step()` advances one tick, and identical seeds give identical
runs — which is also how TPU-vs-CPU state parity is checked
reproducibly.
"""

from __future__ import annotations

import dataclasses
import heapq

import numpy as np

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.testing.hash_log import HashLog
from tigerbeetle_tpu.vsr import replica as vsr_format
from tigerbeetle_tpu.vsr import wire
from tigerbeetle_tpu.vsr.multi import VsrReplica
from tigerbeetle_tpu.vsr.storage import MemoryStorage, ZoneLayout
from tigerbeetle_tpu.vsr.wire import Command, VsrOperation


@dataclasses.dataclass
class PacketOptions:
    """reference: src/testing/packet_simulator.zig:10-40."""

    one_way_delay_min: int = 1
    one_way_delay_max: int = 3
    packet_loss_probability: float = 0.0
    packet_replay_probability: float = 0.0


class PacketSimulator:
    """Seeded delay/loss/replay/partition between endpoints.

    Endpoints: replicas are ints 0..n-1; clients are u128 client ids.
    """

    def __init__(self, options: PacketOptions, seed: int = 0) -> None:
        self.options = options
        self.rng = np.random.default_rng(seed)
        self.now = 0
        self._queue: list[tuple[int, int, object]] = []  # (tick, seq, packet)
        self._seq = 0
        self.partitioned: set = set()  # endpoints cut off from everyone

    def partition(self, *endpoints) -> None:
        self.partitioned.update(endpoints)

    def heal(self, *endpoints) -> None:
        if endpoints:
            self.partitioned.difference_update(endpoints)
        else:
            self.partitioned.clear()

    def submit(self, src, dst, header: np.ndarray, body: bytes) -> None:
        if src in self.partitioned or dst in self.partitioned:
            return
        if self.rng.random() < self.options.packet_loss_probability:
            return
        copies = 1
        if self.rng.random() < self.options.packet_replay_probability:
            copies = 2
        for _ in range(copies):
            delay = int(
                self.rng.integers(
                    self.options.one_way_delay_min,
                    self.options.one_way_delay_max + 1,
                )
            )
            heapq.heappush(
                self._queue,
                (self.now + delay, self._seq, (src, dst, header.copy(), body)),
            )
            self._seq += 1

    def advance(self, deliver) -> None:
        """One tick: pop every packet due now and hand to `deliver`."""
        self.now += 1
        while self._queue and self._queue[0][0] <= self.now:
            _, _, (src, dst, header, body) = heapq.heappop(self._queue)
            if src in self.partitioned or dst in self.partitioned:
                continue
            deliver(dst, header, body)


class _Bus:
    """Per-replica bus endpoint feeding the packet simulator.  `src`
    is the PROCESS index; protocol messages address SLOTS, which the
    slot map (reconfiguration) translates back to processes."""

    def __init__(self, cluster: "Cluster", src) -> None:
        self.cluster = cluster
        self.src = src
        self._slot_map: list[int] | None = None

    def set_slot_map(self, members) -> None:
        self._slot_map = list(members)

    def send(self, dst: int, header: np.ndarray, body: bytes) -> None:
        if self._slot_map is not None and dst < len(self._slot_map):
            dst = self._slot_map[dst]
        self.cluster.network.submit(self.src, dst, header, body)

    def send_client(self, client: int, header: np.ndarray, body: bytes) -> None:
        self.cluster.network.submit(self.src, client, header, body)


class SimClient:
    """Driver-side client session: register, pipelined-one request,
    retransmit on timeout (reference: src/vsr/client.zig:18-120)."""

    RETRY_TICKS = 8

    def __init__(self, cluster: "Cluster", client_id: int) -> None:
        self.cluster = cluster
        self.id = client_id
        self.request_number = 0
        self.view_guess = 0
        self.reply: bytes | None = None
        self.registered = False
        self.evicted = False
        self.busy_replies = 0  # typed admission sheds received
        self._inflight: tuple[np.ndarray, bytes] | None = None
        self._last_sent = -(10**9)
        self.replies: list[bytes] = []

    # -- wire --

    def on_message(self, header: np.ndarray, body: bytes) -> None:
        if not wire.verify_header(header, body):
            return
        cmd = Command(int(header["command"]))
        if cmd == Command.client_busy:
            # Typed admission shed: NOT fatal — the request was never
            # admitted; the retransmission cadence retries it.
            self.busy_replies += 1
            return
        if cmd == Command.eviction:
            # Fatal for the session (reference clients surface this as
            # a terminal error); recorded, not raised, so a multi-client
            # harness keeps stepping.
            self.evicted = True
            self._inflight = None
            return
        if cmd != Command.reply:
            return
        if self._inflight is None:
            return
        want_request = int(self._inflight[0]["request"])
        if int(header["request"]) != want_request:
            return
        self.view_guess = max(self.view_guess, int(header["view"]))
        if int(self._inflight[0]["operation"]) == int(VsrOperation.register):
            self.registered = True
        self._inflight = None
        self.reply = body
        self.replies.append(body)

    def tick(self) -> None:
        if self._inflight is None:
            return
        if self.cluster.network.now - self._last_sent >= self.RETRY_TICKS:
            self._send(broadcast=True)

    # -- api --

    def busy(self) -> bool:
        return self._inflight is not None

    def register(self) -> None:
        assert not self.busy()
        h = wire.make_header(
            command=Command.request, operation=VsrOperation.register,
            cluster=self.cluster.cluster_id, client=self.id, request=0,
        )
        wire.finalize_header(h, b"")
        self._inflight = (h, b"")
        self._send()

    def request(self, operation: types.Operation, body: bytes) -> None:
        assert self.registered and not self.busy()
        self.request_number += 1
        import time as _time

        h = wire.make_header(
            command=Command.request, operation=operation,
            cluster=self.cluster.cluster_id, client=self.id,
            request=self.request_number,
            # Wire trace context from client submit: the id is a
            # deterministic function of (client, request) so seeded
            # runs stay reproducible; the origin timestamp is real
            # CLOCK_MONOTONIC — observability only, never state.
            trace_id=((self.id << 20) ^ self.request_number)
            & 0xFFFFFFFFFFFFFFFF,
            trace_ts=_time.perf_counter_ns(),
            trace_flags=wire.TRACE_SAMPLED,
        )
        wire.finalize_header(h, body)
        self.reply = None
        self._inflight = (h, body)
        self._send()

    def _send(self, broadcast: bool = False) -> None:
        assert self._inflight is not None
        self._last_sent = self.cluster.network.now
        header, body = self._inflight
        targets = (
            range(self.cluster.replica_count)
            if broadcast
            else [self.view_guess % self.cluster.replica_count]
        )
        for r in targets:
            self.cluster.network.submit(
                self.id, self.cluster.process_of_slot(r), header, body
            )


class Cluster:
    def __init__(self, replica_count: int = 3, *, seed: int = 0,
                 standby_count: int = 0,
                 config: cfg.Config = cfg.TEST_MIN,
                 options: PacketOptions | None = None,
                 state_machine_factory=None) -> None:
        self.cluster_id = 0xC1
        self.replica_count = replica_count
        self.standby_count = standby_count
        self.config = config
        self.network = PacketSimulator(options or PacketOptions(), seed)
        factory = state_machine_factory or (lambda: CpuStateMachine(config))
        self._factory = factory

        self.replicas: list[VsrReplica] = []
        self.storages: list[MemoryStorage] = []
        for i in range(replica_count + standby_count):
            storage = MemoryStorage(
                ZoneLayout(config=config, grid_size=1 << 20), seed=seed + i
            )
            vsr_format.format(storage, self.cluster_id, i, replica_count)
            r = VsrReplica(
                storage, self.cluster_id, factory(), _Bus(self, i),
                replica=i, replica_count=replica_count,
                standby_count=standby_count,
            )
            r.hash_log = HashLog()
            r.open()
            self.storages.append(storage)
            self.replicas.append(r)
        # Cluster-owned so logs survive replica restarts.
        self.hash_logs = [r.hash_log for r in self.replicas]
        self.clients: dict[int, SimClient] = {}
        self.realtime = 0
        # Per-replica wall-clock skew in ns (nemesis knob): replica i
        # observes realtime + clock_skew[i].  The synchronized clock
        # (vsr/clock.py) must keep primary timestamps near true time
        # despite this.
        self.clock_skew = [0] * (replica_count + standby_count)

    def process_of_slot(self, slot: int) -> int:
        """Current process filling a protocol slot (reconfiguration
        moves slots between processes).  Routing follows the freshest
        ADOPTED membership — which process answers for a slot NOW —
        not the committed one: a replica that heartbeat-adopted a
        newer epoch but hasn't replayed its ops yet would otherwise
        steer requests at the stale mapping."""
        best_epoch, best = -1, None
        for i, r in enumerate(self.replicas):
            if r.status != "normal":
                continue
            # A nemesis-partitioned replica may hold the freshest
            # adopted membership, but no client can reach it (or any
            # process its mapping names through it): routing by its
            # view would steer requests at a mapping no reachable
            # replica answers.  Skip it; heal/failover restores it.
            if i in self.network.partitioned:
                continue
            members = r.members_adopted or r.members
            epoch = max(r.epoch_adopted, r.epoch)
            if members is not None and epoch > best_epoch:
                best_epoch, best = epoch, members
        if best is not None and slot < len(best):
            return best[slot]
        return slot

    def client(self, client_id: int) -> SimClient:
        # Replica addresses (actives then standbys) occupy
        # [0, replica_count + standby_count) in the packet simulator's
        # flat namespace.
        assert client_id >= len(self.replicas), "client id collides with replica"
        c = SimClient(self, client_id)
        self.clients[client_id] = c
        return c

    # ------------------------------------------------------------------
    # Nemesis (reference: src/simulator.zig:194-204 crash/restart).

    def crash_replica(self, index: int) -> None:
        """Power-loss crash: unsynced sectors are lost (seeded), the
        process is gone until restart_replica."""
        self.storages[index].crash()
        self.network.partition(index)
        self.replicas[index].status = "crashed"

    def restart_replica(self, index: int, state_machine=None, *,
                        release: int | None = None,
                        releases_available: tuple[int, ...] | None = None,
                        ) -> None:
        """Restart; optionally with a different installed binary bundle
        (releases_available) and/or running release — the harness-side
        half of the multiversion upgrade (reference:
        src/vsr/replica.zig:4298 replica_release_execute)."""
        storage = self.storages[index]
        self.network.heal(index)
        old = self.replicas[index]
        avail = releases_available or old.releases_available
        r = VsrReplica(
            storage, self.cluster_id,
            state_machine or self._factory(), _Bus(self, index),
            replica=index, replica_count=self.replica_count,
            standby_count=self.standby_count,
            release=release if release is not None else old.release,
            releases_available=avail,
        )
        r.hash_log = self.hash_logs[index]
        r.open()
        # Pre-crash commits beyond the durable checkpoint floor may
        # have been lost with the process and superseded — drop them.
        r.hash_log.prune_above(int(r.superblock.working["commit_min"]))
        self.replicas[index] = r

    # ------------------------------------------------------------------

    def step(self) -> None:
        """One tick: advance time, tick everyone, deliver due packets."""
        self.realtime += cfg.TICK_NS
        for i, r in enumerate(self.replicas):
            if r.status == "crashed":
                continue
            r.realtime = self.realtime + self.clock_skew[i]
            r.tick()
        for c in self.clients.values():
            c.tick()
        self.network.advance(self._deliver)
        # Group-commit flush point (deterministic: once per step, in
        # replica order).  A no-op unless a test opted the replica's
        # MemoryStorage into deferred sync.
        for r in self.replicas:
            if r.status != "crashed":
                r.flush_group_commit()

    def _deliver(self, dst, header: np.ndarray, body: bytes) -> None:
        if isinstance(dst, int) and dst < len(self.replicas):
            # A crashed process receives nothing: in-flight packets to
            # it die with it (processing them would let a zombie
            # journal prepares and send acks from beyond the grave).
            if self.replicas[dst].status == "crashed":
                return
            self.replicas[dst].on_message(header, body)
        else:
            client = self.clients.get(dst)
            if client is not None:
                client.on_message(header, body)

    def run_until(self, cond, max_steps: int = 2000) -> None:
        for _ in range(max_steps):
            if cond():
                return
            self.step()
        raise AssertionError(f"condition not reached in {max_steps} steps")

    def run_request(self, client: SimClient, operation: types.Operation,
                    body: bytes, max_steps: int = 2000) -> bytes:
        client.request(operation, body)
        self.run_until(lambda: not client.busy(), max_steps)
        assert client.reply is not None or client.reply == b""
        return client.reply

    # ------------------------------------------------------------------
    # Checkers (reference: src/testing/cluster/state_checker.zig:27-45).

    def check_linearized(self) -> None:
        """Every pair of replicas agrees on the prepare at every op
        both have committed."""
        for a in range(len(self.replicas)):
            for b in range(a + 1, len(self.replicas)):
                ra, rb = self.replicas[a], self.replicas[b]
                # The checkpoint op itself may never have been
                # journaled (state sync installs state, not prepares):
                # compare strictly above it.
                lo = max(
                    1,
                    max(ra.checkpoint_op, rb.checkpoint_op) + 1,
                    min(ra.commit_min, rb.commit_min)
                    - self.config.journal_slot_count + 1,
                )
                for op in range(lo, min(ra.commit_min, rb.commit_min) + 1):
                    pa = ra.journal.read_prepare(op)
                    pb = rb.journal.read_prepare(op)
                    assert pa is not None and pb is not None, (a, b, op)
                    assert pa[0].tobytes() == pb[0].tobytes(), (a, b, op)

    def check_convergence(self) -> None:
        """All replicas at the same commit must hold identical state.
        On divergence the hash logs name the exact first divergent op
        (reference: src/testing/hash_log.zig)."""
        commits = {r.commit_min for r in self.replicas}
        assert len(commits) == 1, commits
        snaps = {r.sm.snapshot() for r in self.replicas}
        # The commit streams must agree op-for-op (even when the end
        # states happen to match).
        for i, a in enumerate(self.hash_logs):
            for j, b in enumerate(self.hash_logs[i + 1 :], i + 1):
                op = a.first_divergence(b)
                suffix = "" if len(snaps) == 1 else " (states diverged)"
                assert op is None, (
                    f"replicas {i}/{j} diverged first at op {op}{suffix}"
                )
        assert len(snaps) == 1, (
            "state machines diverged after identical commit hashes "
            "(non-deterministic state outside the commit path)"
        )

    def settle(self, max_steps: int = 3000) -> None:
        """Run until all replicas have converged on the same commit."""
        def converged():
            if any(c.busy() for c in self.clients.values()):
                return False
            commits = {r.commit_min for r in self.replicas}
            ops = {r.op for r in self.replicas}
            return len(commits) == 1 and len(ops) == 1 and all(
                r.status == "normal" for r in self.replicas
            )

        self.run_until(converged, max_steps)


# ----------------------------------------------------------------------
# Cross-replica trace merging (observability spine, utils/tracer.py).


def merge_traces(trace_paths, out_path: str | None = None,
                 labels=None) -> dict:
    """Stitch per-replica Chrome-trace JSON files (utils/tracer.py
    dumps) into ONE Perfetto-loadable timeline: each input file
    becomes a named process track (`replica<i>`), so a replicated
    drain reads left-to-right across replicas — prepare on the
    primary, journal_write + covering gc sync on every replica,
    prepare_ok on the backups, commit + reply back on the primary.

    Timestamps are comparable because every tracer samples
    CLOCK_MONOTONIC (time.perf_counter_ns), whose epoch is shared by
    all processes on one host — merging traces from different hosts
    would need an offset pass (the vsr/clock.py sync could provide
    one; not needed for single-box clusters).

    Robustness: a missing, empty, truncated, or otherwise unparseable
    per-replica file (a replica killed mid-dump is the common case) is
    SKIPPED with a warning and listed under otherData.skipped — one
    bad file must not void a postmortem merge of the survivors.  Any
    number of inputs merges (>2-replica clusters, flight dumps mixed
    with live tracer dumps).
    """
    import json as _json
    import warnings

    merged_events: list[dict] = []
    dropped_total = 0
    skipped: list[dict] = []
    for i, path in enumerate(trace_paths):
        label = labels[i] if labels else f"replica{i}"
        try:
            with open(path) as f:
                data = _json.load(f)
            if not isinstance(data, dict):
                raise ValueError(f"expected a trace object, got "
                                 f"{type(data).__name__}")
            events = data.get("traceEvents", ())
            if not isinstance(events, list):
                raise ValueError("traceEvents is not a list")
        except (OSError, ValueError) as exc:
            # ValueError covers json.JSONDecodeError (its subclass):
            # empty and truncated files land here too.
            warnings.warn(
                f"merge_traces: skipping {label} ({path}): {exc}",
                stacklevel=2,
            )
            skipped.append({"label": label, "path": str(path),
                            "error": str(exc)})
            continue
        # Re-key pid per input file: every tracer defaults its own
        # process_id, and two replicas that both said pid=0 would
        # otherwise collapse onto one track.
        for ev in events:
            if not isinstance(ev, dict):
                continue
            ev = dict(ev)
            ev["pid"] = i
            merged_events.append(ev)
        merged_events.append(
            {
                "name": "process_name", "ph": "M", "pid": i, "tid": 0,
                "args": {"name": label},
            }
        )
        other = data.get("otherData", {})
        if isinstance(other, dict):
            try:
                dropped_total += int(other.get("dropped_events", 0))
            except (TypeError, ValueError):
                pass
    merged = {
        "traceEvents": merged_events,
        "otherData": {"dropped_events": dropped_total},
    }
    if skipped:
        merged["otherData"]["skipped"] = skipped
    if out_path:
        with open(out_path, "w") as f:
            _json.dump(merged, f)
    return merged


def trace_demo(out_path: str, *, n_replicas: int = 2, batches: int = 8,
               transfers_per_batch: int = 16, seed: int = 7) -> dict:
    """One-command Perfetto demo (`tigerbeetle-tpu trace-demo`): drive
    a replicated drain through a deterministic n-replica cluster with
    per-replica JSON tracers and group commit live, then merge the
    traces into `out_path` (load it at https://ui.perfetto.dev).  The
    timeline shows prepare -> journal_write -> gc_covering_sync ->
    prepare_ok -> commit -> reply across all replica tracks.

    Returns {"replicas", "ops_committed", "events", "trace_path"}.
    """
    import os
    import tempfile

    from tigerbeetle_tpu.testing.harness import account, pack, transfer
    from tigerbeetle_tpu.utils.tracer import Tracer
    from tigerbeetle_tpu.vsr.storage import MemoryStorage

    # Group commit needs a deferred-sync-capable storage; the sim
    # cluster's MemoryStorage opts in per-class for the demo's scope
    # (the same opt-in tests/test_multi.py uses).
    had = MemoryStorage.supports_deferred_sync
    MemoryStorage.supports_deferred_sync = True
    try:
        cluster = Cluster(replica_count=n_replicas, seed=seed)
        for i, r in enumerate(cluster.replicas):
            r.set_tracer(Tracer("json", process_id=i))
        client = cluster.client(1000)
        client.register()
        cluster.run_until(lambda: client.registered)
        accounts = [account(1), account(2)]
        assert cluster.run_request(
            client, types.Operation.create_accounts, pack(accounts)
        ) == b""
        tid = 100
        for _ in range(batches):
            rows = []
            for _ in range(transfers_per_batch):
                rows.append(
                    transfer(
                        tid, debit_account_id=1, credit_account_id=2,
                        amount=1,
                    )
                )
                tid += 1
            assert cluster.run_request(
                client, types.Operation.create_transfers, pack(rows)
            ) == b""
        cluster.settle()
        tmp = tempfile.mkdtemp(prefix="tb_trace_demo_")
        paths = []
        for i, r in enumerate(cluster.replicas):
            p = os.path.join(tmp, f"replica{i}.json")
            r.tracer.write(p)
            paths.append(p)
        merge_traces(paths, out_path)
        return {
            "replicas": n_replicas,
            "ops_committed": cluster.replicas[0].commit_min,
            "events": batches * transfers_per_batch,
            "per_replica_traces": paths,
            "trace_path": out_path,
        }
    finally:
        MemoryStorage.supports_deferred_sync = had
