"""VOPR-style deterministic whole-cluster fuzzing.

The reference's VOPR (reference: src/simulator.zig, docs/about/vopr.md)
replaces every nondeterministic component with a seeded fake and then
drives random workload + nemesis events, checking invariants the whole
way.  This build reuses the deterministic cluster (testing/cluster.py)
and layers on:

- Workload: seeded mix of create_accounts / create_transfers (plain,
  pending, post/void, linked chains), with guaranteed-success requests
  tracked for auditing (reference: src/state_machine/workload.zig).
- Nemesis: seeded replica crash (losing unsynced sectors) + restart,
  partitions/heals (reference: src/simulator.zig:194-204).
- Checkers: linearized commit history, state convergence,
  double-entry conservation (sum of debits == sum of credits, posted
  and pending), and restart-replay equivalence (a fresh replica opened
  from a live replica's storage must reach the identical state).
"""

from __future__ import annotations

import numpy as np

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.testing.cluster import Cluster, PacketOptions
from tigerbeetle_tpu.testing.harness import pack, account, transfer
from tigerbeetle_tpu.vsr.multi import VsrReplica


class Workload:
    def __init__(self, seed: int) -> None:
        self.rng = np.random.default_rng(seed)
        self.account_ids: list[int] = []
        self.pending_ids: list[int] = []
        self.next_account = 1
        self.next_transfer = 1_000_000

    def next_request(self) -> tuple[types.Operation, bytes, bool]:
        """-> (operation, body, must_succeed)."""
        roll = self.rng.random()
        if len(self.account_ids) < 4 or roll < 0.08:
            return self._create_accounts()
        if roll < 0.70:
            return self._create_transfers()
        if roll < 0.80 and self.pending_ids:
            return self._post_or_void()
        if roll < 0.90:
            ids = [
                int(v) for v in
                self.rng.choice(self.account_ids, size=min(4, len(self.account_ids)))
            ]
            from tigerbeetle_tpu.testing.harness import ids_bytes

            return types.Operation.lookup_accounts, ids_bytes(ids), True
        return self._create_transfers()

    def _create_accounts(self):
        n = int(self.rng.integers(1, 5))
        rows = []
        for _ in range(n):
            rows.append(account(self.next_account, ledger=1, code=1))
            self.account_ids.append(self.next_account)
            self.next_account += 1
        return types.Operation.create_accounts, pack(rows), True

    def _pick_pair(self) -> tuple[int, int]:
        dr, cr = self.rng.choice(self.account_ids, size=2, replace=False)
        return int(dr), int(cr)

    def _create_transfers(self):
        n = int(self.rng.integers(1, 6))
        rows = []
        linked_open = False
        for k in range(n):
            dr, cr = self._pick_pair()
            flags = 0
            is_pending = self.rng.random() < 0.25
            if is_pending:
                flags |= types.TransferFlags.pending
            # Linked chains (never the last event, so chains close).
            if k < n - 1 and self.rng.random() < 0.2:
                flags |= types.TransferFlags.linked
                linked_open = True
            else:
                linked_open = False
            tid = self.next_transfer
            self.next_transfer += 1
            timeout = int(self.rng.integers(1, 5)) if is_pending and self.rng.random() < 0.3 else 0
            rows.append(
                transfer(tid, debit_account_id=dr, credit_account_id=cr,
                         amount=int(self.rng.integers(1, 100)), flags=flags,
                         timeout=timeout)
            )
            if is_pending and timeout == 0:
                self.pending_ids.append(tid)
        assert not linked_open
        return types.Operation.create_transfers, pack(rows), True

    def _post_or_void(self):
        pid = self.pending_ids.pop(int(self.rng.integers(len(self.pending_ids))))
        void = self.rng.random() < 0.3
        tid = self.next_transfer
        self.next_transfer += 1
        flags = (
            types.TransferFlags.void_pending_transfer if void
            else types.TransferFlags.post_pending_transfer
        )
        # amount=0 means inherit (post) / full (void) — always valid.
        return (
            types.Operation.create_transfers,
            pack([transfer(tid, pending_id=pid, flags=flags)]),
            True,
        )


class Vopr:
    def __init__(self, seed: int, *, replica_count: int = 3,
                 requests: int = 40,
                 packet_loss: float = 0.02,
                 crash_probability: float = 0.01,
                 state_machine_factory=None) -> None:
        self.seed = seed
        self.rng = np.random.default_rng(seed + 1)
        self.cluster = Cluster(
            replica_count=replica_count, seed=seed,
            options=PacketOptions(packet_loss_probability=packet_loss),
            state_machine_factory=state_machine_factory,
        )
        self.workload = Workload(seed + 2)
        self.requests = requests
        self.crash_probability = crash_probability
        self.crashed: set[int] = set()
        self.restart_check_skipped = False

    def run(self) -> None:
        c = self.cluster
        client = c.client(9000 + self.seed)
        client.register()
        c.run_until(lambda: client.registered, max_steps=4000)

        sent = 0
        guard = 0
        pending_audit: tuple[types.Operation, bool] | None = None
        while sent < self.requests or client.busy():
            guard += 1
            assert guard < 200_000, "vopr stalled"
            self._nemesis()
            if not client.busy():
                if pending_audit is not None:
                    self._audit(client, *pending_audit)
                    pending_audit = None
                if sent < self.requests:
                    operation, body, must_succeed = self.workload.next_request()
                    client.request(operation, body)
                    pending_audit = (operation, must_succeed)
                    sent += 1
            c.step()
        if pending_audit is not None:
            self._audit(client, *pending_audit)

        # Heal everything, restart the dead, settle, check.
        c.network.heal()
        for i in sorted(self.crashed):
            c.restart_replica(i)
        self.crashed.clear()
        c.run_until(lambda: not client.busy(), max_steps=20_000)
        c.settle(max_steps=20_000)
        c.check_linearized()
        c.check_convergence()
        self.check_conservation()
        self.check_restart_equivalence()

    def _audit(self, client, operation: types.Operation,
               must_succeed: bool) -> None:
        """Auditor (reference: src/state_machine/auditor.zig): requests
        constructed to be valid must report zero failures."""
        if not must_succeed:
            return
        if operation in (types.Operation.create_accounts,
                         types.Operation.create_transfers):
            results = np.frombuffer(client.reply, types.CREATE_RESULT_DTYPE)
            # Strict: ids are globally unique and sessions dedupe
            # retransmissions by replaying the stored reply, so even
            # `exists` would signal a double execution.
            assert len(results) == 0, (operation, results[:6])

    # -- nemesis --

    def _nemesis(self) -> None:
        c = self.cluster
        # Clock-skew nemesis: wall clocks drift within the Marzullo
        # tolerance (larger skews legitimately stall writes — see
        # test_cluster_divergent_clocks_refuse_writes).
        if self.rng.random() < 0.01:
            i = int(self.rng.integers(c.replica_count))
            c.clock_skew[i] = int(self.rng.integers(-5_000_000, 5_000_000))
        if self.crashed:
            # Restart with probability ~5%/tick so outages are short.
            if self.rng.random() < 0.05:
                i = self.crashed.pop()
                c.restart_replica(i)
            return
        if self.rng.random() < self.crash_probability:
            i = int(self.rng.integers(c.replica_count))
            c.crash_replica(i)
            self.crashed.add(i)

    # -- checkers --

    def check_conservation(self) -> None:
        """Double-entry invariant: total debits == total credits, in
        both posted and pending columns."""
        for r in self.cluster.replicas:
            sm = r.sm
            if isinstance(sm, CpuStateMachine):
                dp = sum(a.debits_pending for a in sm.accounts.values())
                cp = sum(a.credits_pending for a in sm.accounts.values())
                dpo = sum(a.debits_posted for a in sm.accounts.values())
                cpo = sum(a.credits_posted for a in sm.accounts.values())
            else:  # TpuStateMachine: sum the balance-mirror columns
                n = sm._attrs.count
                lo = sm._mirror.lo[:n].astype(object)
                hi = sm._mirror.hi[:n].astype(object)
                totals = [
                    int((lo[:, c] + (hi[:, c] * (1 << 64))).sum())
                    for c in range(4)
                ]
                dp, dpo, cp, cpo = totals
            assert dp == cp, (dp, cp)
            assert dpo == cpo, (dpo, cpo)

    def check_restart_equivalence(self) -> None:
        """Recovery is re-execution: opening a fresh replica over live
        storage must reproduce the live state bit-for-bit.  The run has
        settled, so the live journal tail is the canonical committed
        chain — replay_tail=True executes it deliberately (a normal
        multi-replica open defers the tail to consensus re-commit)."""
        c = self.cluster
        live = c.replicas[0]
        if live.op != live.commit_min:
            # A prepared-but-uncommitted suffix remains (quorum raced
            # the end of the run); tail replay would execute it, so the
            # bit-exact comparison only holds without one.  Recorded so
            # a seed corpus that never exercises this check is visible.
            self.restart_check_skipped = True
            return
        import copy

        # Deep-copy the storage: replay writes reply slots (stamped
        # with the recovered view) and must not mutate live state.
        fresh = VsrReplica(
            copy.deepcopy(c.storages[0]), c.cluster_id, c._factory(),
            live.bus, replica=0, replica_count=c.replica_count,
        )
        fresh.open(replay_tail=True)
        assert fresh.commit_min == live.commit_min
        assert fresh.sm.snapshot() == live.sm.snapshot()
