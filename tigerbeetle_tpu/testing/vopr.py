"""VOPR-style deterministic whole-cluster fuzzing.

The reference's VOPR (reference: src/simulator.zig, docs/about/vopr.md)
replaces every nondeterministic component with a seeded fake and then
drives random workload + nemesis events, checking invariants the whole
way.  This build reuses the deterministic cluster (testing/cluster.py)
and layers on:

- Workload: seeded mix of create_accounts / create_transfers (plain,
  pending, post/void, linked chains), with guaranteed-success requests
  tracked for auditing (reference: src/state_machine/workload.zig).
- Nemesis: seeded replica crash (losing unsynced sectors) + restart,
  partitions/heals (reference: src/simulator.zig:194-204).
- Checkers: linearized commit history, state convergence,
  double-entry conservation (sum of debits == sum of credits, posted
  and pending), and restart-replay equivalence (a fresh replica opened
  from a live replica's storage must reach the identical state).
"""

from __future__ import annotations

import numpy as np

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.state_machine import CpuStateMachine
from tigerbeetle_tpu.testing.cluster import (
    Cluster,
    PacketOptions,
    ShardedCluster,
)
from tigerbeetle_tpu.testing.harness import account, ids_bytes, pack, transfer
from tigerbeetle_tpu.vsr.multi import VsrReplica
from tigerbeetle_tpu.vsr.storage import FsyncCrash
from tigerbeetle_tpu.vsr.wire import VsrOperation


class Workload:
    def __init__(self, seed: int, queries: bool = False) -> None:
        """queries=False is the frozen v1 stream: regression seed
        tests reproduce their original fault interleavings only if
        the RNG consumption stays byte-identical.  queries=True (the
        v2 profile, used by soaks and its own tests) widens the op
        surface with lookup_transfers, AccountFilter queries over the
        committed scan engine, history balances, and balancing
        transfers — cross-replica determinism of every reply is
        enforced by the cluster's hash-log convergence checker."""
        self.rng = np.random.default_rng(seed)
        self.queries = queries
        self.account_ids: list[int] = []
        self.history_ids: list[int] = []
        self.pending_ids: list[int] = []
        self.transfer_ids: list[int] = []
        self.next_account = 1
        self.next_transfer = 1_000_000

    def next_request(self) -> tuple[types.Operation, bytes, bool]:
        """-> (operation, body, must_succeed)."""
        roll = self.rng.random()
        if not self.queries:
            if len(self.account_ids) < 4 or roll < 0.08:
                return self._create_accounts()
            if roll < 0.70:
                return self._create_transfers()
            if roll < 0.80 and self.pending_ids:
                return self._post_or_void()
            if roll < 0.90:
                return self._lookup_accounts()
            return self._create_transfers()
        if len(self.account_ids) < 4 or roll < 0.08:
            return self._create_accounts()
        if roll < 0.58:
            return self._create_transfers()
        if roll < 0.68 and self.pending_ids:
            return self._post_or_void()
        if roll < 0.74:
            return self._lookup_accounts()
        if roll < 0.80 and self.transfer_ids:
            return self._lookup_transfers()
        if roll < 0.88:
            return self._get_account_transfers()
        if roll < 0.94:
            return self._get_account_balances()
        return self._balancing_transfer()

    def _create_accounts(self):
        n = int(self.rng.integers(1, 5))
        rows = []
        for _ in range(n):
            flags = 0
            if self.queries and self.rng.random() < 0.4:
                flags |= types.AccountFlags.history
                self.history_ids.append(self.next_account)
            rows.append(
                account(self.next_account, ledger=1, code=1, flags=flags)
            )
            self.account_ids.append(self.next_account)
            self.next_account += 1
        return types.Operation.create_accounts, pack(rows), True

    def _lookup_accounts(self):
        ids = [
            int(v) for v in
            self.rng.choice(self.account_ids, size=min(4, len(self.account_ids)))
        ]
        return types.Operation.lookup_accounts, ids_bytes(ids), True

    def _lookup_transfers(self):
        ids = [
            int(v) for v in
            self.rng.choice(self.transfer_ids,
                            size=min(4, len(self.transfer_ids)))
        ]
        return types.Operation.lookup_transfers, ids_bytes(ids), True

    def _account_filter(self, account_id: int) -> bytes:
        row = np.zeros(1, dtype=types.ACCOUNT_FILTER_DTYPE)[0]
        types.u128_set(row, "account_id", account_id)
        flags = 0
        if self.rng.random() < 0.8:
            flags |= types.AccountFilterFlags.debits
        if self.rng.random() < 0.8:
            flags |= types.AccountFilterFlags.credits
        if not flags:
            flags = (types.AccountFilterFlags.debits
                     | types.AccountFilterFlags.credits)
        if self.rng.random() < 0.3:
            flags |= types.AccountFilterFlags.reversed
        row["flags"] = flags
        row["limit"] = int(self.rng.choice([1, 3, 50, 8190]))
        return row.tobytes()

    def _get_account_transfers(self):
        aid = int(self.rng.choice(self.account_ids))
        return (
            types.Operation.get_account_transfers,
            self._account_filter(aid),
            True,
        )

    def _get_account_balances(self):
        # Prefer a history-flagged account (rows exist only for
        # those); a non-history target legitimately returns empty and
        # still exercises the committed scan path.
        pool = self.history_ids or self.account_ids
        aid = int(self.rng.choice(pool))
        return (
            types.Operation.get_account_balances,
            self._account_filter(aid),
            True,
        )

    def _balancing_transfer(self):
        dr, cr = self._pick_pair()
        tid = self.next_transfer
        self.next_transfer += 1
        flags = (
            types.TransferFlags.balancing_debit
            # tbcheck: allow(money): seeded-RNG coin flip choosing a
            # flag — the 0.5 is a probability, not an amount.
            if self.rng.random() < 0.5
            else types.TransferFlags.balancing_credit
        )
        # Legitimately fails with exceeds_credits/debits when nothing
        # is transferable — exercised for determinism, not audited.
        return (
            types.Operation.create_transfers,
            pack([transfer(tid, debit_account_id=dr, credit_account_id=cr,
                           amount=int(self.rng.integers(0, 50)),
                           flags=flags)]),
            False,
        )

    def _pick_pair(self) -> tuple[int, int]:
        dr, cr = self.rng.choice(self.account_ids, size=2, replace=False)
        return int(dr), int(cr)

    def _create_transfers(self):
        n = int(self.rng.integers(1, 6))
        rows = []
        linked_open = False
        for k in range(n):
            dr, cr = self._pick_pair()
            flags = 0
            is_pending = self.rng.random() < 0.25
            if is_pending:
                flags |= types.TransferFlags.pending
            # Linked chains (never the last event, so chains close).
            if k < n - 1 and self.rng.random() < 0.2:
                flags |= types.TransferFlags.linked
                linked_open = True
            else:
                linked_open = False
            tid = self.next_transfer
            self.next_transfer += 1
            timeout = int(self.rng.integers(1, 5)) if is_pending and self.rng.random() < 0.3 else 0
            rows.append(
                transfer(tid, debit_account_id=dr, credit_account_id=cr,
                         amount=int(self.rng.integers(1, 100)), flags=flags,
                         timeout=timeout)
            )
            if is_pending and timeout == 0:
                self.pending_ids.append(tid)
            self.transfer_ids.append(tid)
        del self.transfer_ids[:-512]  # bound lookup pool memory
        assert not linked_open
        return types.Operation.create_transfers, pack(rows), True

    def _post_or_void(self):
        pid = self.pending_ids.pop(int(self.rng.integers(len(self.pending_ids))))
        void = self.rng.random() < 0.3
        tid = self.next_transfer
        self.next_transfer += 1
        flags = (
            types.TransferFlags.void_pending_transfer if void
            else types.TransferFlags.post_pending_transfer
        )
        # amount=0 means inherit (post) / full (void) — always valid.
        return (
            types.Operation.create_transfers,
            pack([transfer(tid, pending_id=pid, flags=flags)]),
            True,
        )


def check_conservation(cluster) -> None:
    """Double-entry invariant on every replica: total debits == total
    credits, in both posted and pending columns."""
    for r in cluster.replicas:
        sm = r.sm
        if isinstance(sm, CpuStateMachine):
            dp = sum(a.debits_pending for a in sm.accounts.values())
            cp = sum(a.credits_pending for a in sm.accounts.values())
            dpo = sum(a.debits_posted for a in sm.accounts.values())
            cpo = sum(a.credits_posted for a in sm.accounts.values())
        else:  # TpuStateMachine: sum the balance-mirror columns
            n = sm._attrs.count
            lo = sm._mirror.lo[:n].astype(object)
            hi = sm._mirror.hi[:n].astype(object)
            totals = [
                int((lo[:, c] + (hi[:, c] * (1 << 64))).sum())
                for c in range(4)
            ]
            dp, dpo, cp, cpo = totals
        assert dp == cp, (dp, cp)
        assert dpo == cpo, (dpo, cpo)


class FaultAtlas:
    """Seeded targeting for sector corruption that guarantees >= 1
    intact copy of everything cluster-wide (reference:
    src/testing/storage.zig:58-95 ClusterFaultAtlas): corruption only
    ever hits a fixed minority of replicas (f = (n-1)//2), and locally
    at most one of the four superblock copies."""

    def __init__(self, seed: int, replica_count: int) -> None:
        rng = np.random.default_rng(seed)
        f = (replica_count - 1) // 2
        self.faulty: set[int] = (
            {int(x) for x in rng.choice(replica_count, size=f, replace=False)}
            if f else set()
        )


class Vopr:
    def __init__(self, seed: int, *, replica_count: int = 3,
                 standby_count: int = 0,
                 requests: int = 40,
                 packet_loss: float = 0.02,
                 crash_probability: float = 0.01,
                 corruption_probability: float = 0.0,
                 upgrade_nemesis: bool = False,
                 queries: bool = False,
                 reconfigure_nemesis: bool = False,
                 partition_probability: float = 0.0,
                 device_loss_probability: float = 0.0,
                 state_machine_factory=None) -> None:
        self.seed = seed
        self.rng = np.random.default_rng(seed + 1)
        # Device-loss nemesis (opt-in, like partitions): replicas run
        # the device-authoritative engine behind seeded ChaosLinks
        # (testing/chaos.py), and the nemesis kills/heals those links
        # mid-run.  The degraded-mode lifecycle must keep replies
        # bit-identical across replicas losing their device at
        # DIFFERENT times — enforced by the existing hash-log
        # convergence checker.
        self.device_loss_probability = device_loss_probability
        self._chaos_links: list = []
        if device_loss_probability > 0.0:
            if state_machine_factory is not None:
                # The nemesis can only target links it owns; silently
                # dropping the knob would fake device-loss coverage.
                raise ValueError(
                    "device_loss_probability requires the built-in "
                    "chaos factory; do not also pass "
                    "state_machine_factory"
                )
            from tigerbeetle_tpu.testing.chaos import device_chaos_factory

            state_machine_factory, self._chaos_links = device_chaos_factory(
                seed + 4
            )
        self.cluster = Cluster(
            replica_count=replica_count, seed=seed,
            standby_count=standby_count,
            options=PacketOptions(packet_loss_probability=packet_loss),
            state_machine_factory=state_machine_factory,
        )
        self.workload = Workload(seed + 2, queries=queries)
        self.requests = requests
        self.crash_probability = crash_probability
        self.corruption_probability = corruption_probability
        self.upgrade_nemesis = upgrade_nemesis
        self.reconfigure_nemesis = reconfigure_nemesis and standby_count > 0
        # Opt-in (0.0 keeps pinned seeds' RNG streams byte-identical):
        # unlike a crash, a partitioned process keeps RUNNING — state
        # intact, clock advancing — and rejoins live-but-stale,
        # exercising view-change rejoin paths crashes cannot.
        self.partition_probability = partition_probability
        self._partitioned: set[int] = set()
        self.atlas = FaultAtlas(seed + 3, replica_count)
        self.crashed: set[int] = set()
        self.restart_check_skipped = False
        self.corruptions = 0
        self._sb_corrupt_copy: dict[int, int] = {}

    def run(self) -> None:
        c = self.cluster
        client = c.client(9000 + self.seed)
        client.register()
        c.run_until(lambda: client.registered, max_steps=4000)

        sent = 0
        guard = 0
        pending_audit: tuple[types.Operation, bool] | None = None
        while sent < self.requests or client.busy():
            guard += 1
            assert guard < 200_000, "vopr stalled"
            self._nemesis()
            if not client.busy():
                if pending_audit is not None:
                    self._audit(client, *pending_audit)
                    pending_audit = None
                if sent < self.requests:
                    reconf = (
                        self._propose_reconfigure()
                        if self.reconfigure_nemesis
                        and self.rng.random() < 0.04
                        else None
                    )
                    if reconf is not None:
                        # Membership change rides the normal request
                        # path; a stale-epoch rejection is a legal
                        # outcome under concurrent proposals.
                        client.request(VsrOperation.reconfigure, reconf)
                        pending_audit = (VsrOperation.reconfigure, False)
                    else:
                        operation, body, must_succeed = (
                            self.workload.next_request()
                        )
                        client.request(operation, body)
                        pending_audit = (operation, must_succeed)
                    sent += 1
            c.step()
        if pending_audit is not None:
            self._audit(client, *pending_audit)

        # Heal everything, restart the dead, settle, check.
        for link in self._chaos_links:
            link.heal()
        c.network.heal()
        for i in sorted(self.crashed):
            c.restart_replica(i)
        self.crashed.clear()
        c.run_until(lambda: not client.busy(), max_steps=20_000)
        if self.upgrade_nemesis:
            # Finish any half-rolled upgrade BEFORE requiring
            # convergence: a replica still on the old release cannot
            # execute prepares stamped with the new one (the reference
            # re-execs each process; the harness restarts it).
            for _ in range(4):
                target = max(
                    max(r.release for r in c.replicas),
                    max((r.upgrade_target or 0) for r in c.replicas),
                )
                stale = [
                    i for i, r in enumerate(c.replicas)
                    if r.release < target
                ]
                if not stale:
                    break
                for i in stale:
                    c.restart_replica(
                        i, release=target,
                        releases_available=tuple(range(1, target + 1)),
                    )
                for _ in range(400):
                    c.step()
        c.settle(max_steps=20_000)
        if self.corruption_probability:
            # Surface and heal ALL latent WAL damage before the
            # journal-reading checkers run: production paces scrubbing
            # over minutes; the harness forces full passes (repair may
            # take a couple of request/response rounds).
            for _ in range(6):
                for r in c.replicas:
                    r.wal_scrub_window()
                for _ in range(8 * c.replica_count):
                    c.step()
                if all(not r._wal_scrub_wanted for r in c.replicas):
                    break
            # The extra steps may have committed a pulse mid-stride:
            # re-quiesce before the checkers read cluster state.
            c.settle(max_steps=20_000)
        c.check_linearized()
        c.check_convergence()
        self.check_conservation()
        self.check_restart_equivalence()

    def _audit(self, client, operation: types.Operation,
               must_succeed: bool) -> None:
        """Auditor (reference: src/state_machine/auditor.zig): requests
        constructed to be valid must report zero failures."""
        # A registered client must never be evicted mid-run (sessions
        # are durable state): surface it as the finding, not as a
        # TypeError on the absent reply — for every request, not just
        # must-succeed ones.
        assert not client.evicted, "registered client wrongly evicted"
        if not must_succeed:
            return
        if operation in (types.Operation.create_accounts,
                         types.Operation.create_transfers):
            results = np.frombuffer(client.reply, types.CREATE_RESULT_DTYPE)
            # Strict: ids are globally unique and sessions dedupe
            # retransmissions by replaying the stored reply, so even
            # `exists` would signal a double execution.
            assert len(results) == 0, (operation, results[:6])

    def _propose_reconfigure(self) -> bytes | None:
        """Propose swapping a random active slot with a random standby
        (epoch + 1 over the freshest known membership) — standby
        promotion under the full nemesis suite.  reference:
        src/vsr.zig:273-311 (reconfiguration epochs)."""
        c = self.cluster
        total = c.replica_count + c.standby_count
        best = max(c.replicas, key=lambda r: r.epoch)
        members = list(best.members) if best.members else list(range(total))
        if len(members) != total:
            return None
        a = int(self.rng.integers(c.replica_count))
        s = int(self.rng.integers(c.replica_count, total))
        members[a], members[s] = members[s], members[a]
        return VsrReplica.encode_reconfigure(best.epoch + 1, members)

    # -- nemesis --

    def _nemesis(self) -> None:
        c = self.cluster
        # Clock-skew nemesis: wall clocks drift within the Marzullo
        # tolerance (larger skews legitimately stall writes — see
        # test_cluster_divergent_clocks_refuse_writes).
        if self.rng.random() < 0.01:
            i = int(self.rng.integers(c.replica_count))
            c.clock_skew[i] = int(self.rng.integers(-5_000_000, 5_000_000))
        if self.corruption_probability and (
            self.rng.random() < self.corruption_probability
        ):
            self._corrupt_random_sector()
        if self.upgrade_nemesis:
            self._upgrade_tick()
        if self.device_loss_probability and self._chaos_links:
            downed = [link for link in self._chaos_links if link.down]
            if downed:
                # Heal with ~10%/tick so device outages stay short
                # enough for re-promotion to happen within the run.
                if self.rng.random() < 0.10:
                    for link in downed:
                        link.heal()
            elif self.rng.random() < self.device_loss_probability:
                pick = int(self.rng.integers(len(self._chaos_links)))
                self._chaos_links[pick].kill()
        if self.partition_probability:
            if self._partitioned:
                # Heal with ~4%/tick so isolation windows are short.
                if self.rng.random() < 0.04:
                    c.network.heal(*self._partitioned)
                    self._partitioned.clear()
            elif self.rng.random() < self.partition_probability:
                i = int(self.rng.integers(len(c.replicas)))
                if i not in self.crashed:
                    c.network.partition(i)
                    self._partitioned.add(i)
        if self.crashed:
            # Restart with probability ~5%/tick so outages are short.
            if self.rng.random() < 0.05:
                i = self.crashed.pop()
                c.restart_replica(i)
            return
        if self.rng.random() < self.crash_probability:
            i = int(self.rng.integers(len(c.replicas)))
            c.crash_replica(i)
            self.crashed.add(i)

    def _corrupt_random_sector(self) -> None:
        """Latent-sector-error nemesis over live replicas, targeted by
        the FaultAtlas: WAL prepare slots, WAL header-ring sectors, one
        superblock copy, and live forest grid blocks — every zone with
        an automated recovery path (redundant headers + protocol WAL
        repair, superblock quorum, scrubber block repair)."""
        from tigerbeetle_tpu.vsr.storage import SECTOR_SIZE
        from tigerbeetle_tpu.vsr.superblock import SUPERBLOCK_COPIES

        c = self.cluster
        candidates = [
            i for i in sorted(self.atlas.faulty) if i not in self.crashed
        ]
        if not candidates:
            return
        i = int(self.rng.choice(candidates))
        storage = c.storages[i]
        layout = storage.layout
        replica = c.replicas[i]
        zones = ["wal_prepare", "wal_header", "superblock"]
        if replica.forest is not None and (
            ~replica.forest.grid.free_set.free
        ).any():
            zones.append("grid")
        zone = zones[int(self.rng.integers(len(zones)))]
        if zone == "wal_prepare":
            slot = int(self.rng.integers(layout.config.journal_slot_count))
            offset = layout.prepare_slot_offset(slot)
        elif zone == "wal_header":
            n_sectors = layout.wal_headers_size // SECTOR_SIZE
            offset = (
                layout.wal_headers_offset
                + int(self.rng.integers(n_sectors)) * SECTOR_SIZE
            )
        elif zone == "superblock":
            # At most ONE copy per replica ever corrupts (4-copy
            # quorum stays decidable locally).
            copy = self._sb_corrupt_copy.setdefault(
                i, int(self.rng.integers(SUPERBLOCK_COPIES))
            )
            offset = layout.superblock_offset + copy * (
                layout.superblock_size // SUPERBLOCK_COPIES
            )
        else:
            grid = replica.forest.grid
            allocated = np.flatnonzero(~grid.free_set.free)
            addr = int(self.rng.choice(allocated)) + 1
            offset = grid._offset(addr)
        storage.corrupt_sector(offset)
        self.corruptions += 1

    def _upgrade_tick(self) -> None:
        """Release-upgrade nemesis (reference: src/simulator.zig
        :194-204 restart-with-new-release probabilities): roll replicas
        to advertise release 2, then re-exec each one once the upgrade
        op commits its target."""
        c = self.cluster
        if self.rng.random() < 0.005:
            i = int(self.rng.integers(len(c.replicas)))
            if i not in self.crashed and (
                max(c.replicas[i].releases_available) < 2
            ):
                c.restart_replica(i, releases_available=(1, 2))
        for i, r in enumerate(c.replicas):
            if i in self.crashed:
                continue
            if r.upgrade_target == 2 and r.release == 1 and (
                self.rng.random() < 0.05
            ):
                c.restart_replica(i, release=2, releases_available=(1, 2))

    # -- checkers --

    def check_conservation(self) -> None:
        check_conservation(self.cluster)

    def check_restart_equivalence(self) -> None:
        """Recovery is re-execution: opening a fresh replica over live
        storage must reproduce the live state bit-for-bit.  The run has
        settled, so the live journal tail is the canonical committed
        chain — replay_tail=True executes it deliberately (a normal
        multi-replica open defers the tail to consensus re-commit)."""
        c = self.cluster
        # Corruption targets atlas replicas; restart-replay needs a
        # replica whose local WAL is intact.
        live_index = 0
        if self.corruption_probability:
            live_index = next(
                i for i in range(c.replica_count)
                if i not in self.atlas.faulty
            )
        live = c.replicas[live_index]
        if live.op != live.commit_min:
            # A prepared-but-uncommitted suffix remains (quorum raced
            # the end of the run); tail replay would execute it, so the
            # bit-exact comparison only holds without one.  Recorded so
            # a seed corpus that never exercises this check is visible.
            self.restart_check_skipped = True
            return
        import copy

        # Deep-copy the storage: replay writes reply slots (stamped
        # with the recovered view) and must not mutate live state.
        fresh = VsrReplica(
            copy.deepcopy(c.storages[live_index]), c.cluster_id,
            c._factory(), live.bus, replica=live_index,
            replica_count=c.replica_count,
            release=live.release,
            releases_available=live.releases_available,
        )
        fresh.open(replay_tail=True)
        assert fresh.commit_min == live.commit_min
        assert fresh.sm.snapshot() == live.sm.snapshot()


# ----------------------------------------------------------------------
# Multi-tenant VOPR (round 16): one tenant floods while others
# trickle, with per-tenant QoS live on every replica.


class TenantStream:
    """One tenant's seeded request stream: its own ledger, its own
    account pool, every request constructed-valid (unique ids, no
    balance limits) so any failure row in a reply is a finding."""

    def __init__(self, seed: int, ledger: int, namespace: int) -> None:
        self.rng = np.random.default_rng(seed)
        self.ledger = ledger
        self.account_ids: list[int] = []
        # Per-STREAM id namespaces: several clients may drive the same
        # tenant (the flood), and ids are globally unique.
        self.next_account = namespace * 1_000_000 + 1
        self.next_transfer = namespace * 1_000_000 + 500_000

    def next_request(self) -> tuple[types.Operation, bytes]:
        if len(self.account_ids) < 4 or self.rng.random() < 0.06:
            rows = []
            for _ in range(int(self.rng.integers(2, 5))):
                rows.append(account(self.next_account, ledger=self.ledger))
                self.account_ids.append(self.next_account)
                self.next_account += 1
            return types.Operation.create_accounts, pack(rows)
        rows = []
        for _ in range(int(self.rng.integers(1, 4))):
            dr, cr = self.rng.choice(self.account_ids, size=2,
                                     replace=False)
            rows.append(transfer(
                self.next_transfer, debit_account_id=int(dr),
                credit_account_id=int(cr),
                amount=int(self.rng.integers(1, 100)),
                ledger=self.ledger,
            ))
            self.next_transfer += 1
        return types.Operation.create_transfers, pack(rows)


class MultiTenantVopr:
    """Seeded multi-tenant overload fuzz: a flooding tenant (ledger 1,
    several back-to-back clients) vs trickling tenants (one client
    each, paced), against replicas running per-tenant QoS with a
    deliberately tight admit queue so the flood tenant is SHED —
    hash-log convergence, linearizability, and conservation-of-money
    must hold across the shed/retry/backoff storms, crash/restart and
    packet-loss nemeses included.  Typed busy is load shedding, not
    data loss: every constructed-valid request must eventually commit
    with zero failure rows."""

    def __init__(self, seed: int, *, tenants: int = 3,
                 flood_clients: int = 3, requests: int = 45,
                 replica_count: int = 3, packet_loss: float = 0.01,
                 crash_probability: float = 0.004,
                 trickle_every: int = 12,
                 tenant_queue: int = 2, admit_queue: int = 4,
                 weights: dict | None = None) -> None:
        import dataclasses as _dc

        self.seed = seed
        self.rng = np.random.default_rng(seed + 1)
        self.requests = requests
        self.crash_probability = crash_probability
        self.trickle_every = trickle_every
        self.crashed: set[int] = set()
        self.cluster = Cluster(
            replica_count=replica_count, seed=seed,
            config=_dc.replace(
                cfg.TEST_MIN, clients_max=flood_clients + tenants + 2
            ),
            options=PacketOptions(packet_loss_probability=packet_loss),
            tenant_qos=dict(
                rate=0.0, queue_bound=tenant_queue,
                weights=weights, admit_queue=admit_queue,
            ),
        )
        c = self.cluster
        # Flood tenant: ledger 1, several closed-loop clients driving
        # back-to-back (well past its fair share); trickle tenants:
        # ledgers 2..tenants, one paced client each.
        self.streams: list[tuple] = []  # (client, stream, paced)
        cid = 9000
        ns = 1
        for k in range(flood_clients):
            self.streams.append(
                (c.client(cid), TenantStream(seed + 10 + k, 1, ns), False)
            )
            cid += 1
            ns += 1
        for ledger in range(2, tenants + 1):
            self.streams.append(
                (c.client(cid),
                 TenantStream(seed + 50 + ledger, ledger, ns), True)
            )
            cid += 1
            ns += 1
        self.sheds = 0
        self.busy_replies = 0
        self.busy_backoffs = 0

    def _nemesis(self) -> None:
        c = self.cluster
        if self.crashed:
            if self.rng.random() < 0.05:
                c.restart_replica(self.crashed.pop())
            return
        if self.rng.random() < self.crash_probability:
            i = int(self.rng.integers(len(c.replicas)))
            c.crash_replica(i)
            self.crashed.add(i)

    def run(self) -> None:
        c = self.cluster
        for client, _stream, _paced in self.streams:
            client.register()
        c.run_until(
            lambda: all(cl.registered for cl, _s, _p in self.streams),
            max_steps=8000,
        )
        sent = {id(cl): 0 for cl, _s, _p in self.streams}
        pending: dict[int, types.Operation] = {}
        guard = 0
        while any(
            sent[id(cl)] < self.requests or cl.busy()
            for cl, _s, _p in self.streams
        ):
            guard += 1
            assert guard < 400_000, "multi-tenant vopr stalled"
            self._nemesis()
            for client, stream, paced in self.streams:
                if client.busy():
                    continue
                assert not client.evicted, "tenant client wrongly evicted"
                op = pending.pop(id(client), None)
                if op in (types.Operation.create_accounts,
                          types.Operation.create_transfers):
                    results = np.frombuffer(
                        client.reply, types.CREATE_RESULT_DTYPE
                    )
                    assert len(results) == 0, (
                        "constructed-valid request failed under QoS",
                        op, results[:4],
                    )
                if sent[id(client)] >= self.requests:
                    continue
                if paced and guard % self.trickle_every:
                    continue  # trickle cadence
                op, body = stream.next_request()
                client.request(op, body)
                pending[id(client)] = op
                sent[id(client)] += 1
            c.step()

        # Drain the last replies' audits.
        for client, _stream, _paced in self.streams:
            op = pending.pop(id(client), None)
            if op is not None:
                results = np.frombuffer(
                    client.reply, types.CREATE_RESULT_DTYPE
                )
                assert len(results) == 0, (op, results[:4])

        # Heal, restart the dead, settle, check everything.
        c.network.heal()
        for i in sorted(self.crashed):
            c.restart_replica(i)
        self.crashed.clear()
        c.settle(max_steps=30_000)
        c.check_linearized()
        c.check_convergence()
        check_conservation(c)
        # Shed/backoff accounting (restarts reset replica counters;
        # this is a floor, not a total).
        self.sheds = sum(
            r.qos.sheds for r in c.replicas if r.qos is not None
        )
        self.busy_replies = sum(
            cl.busy_replies for cl, _s, _p in self.streams
        )
        self.busy_backoffs = sum(
            cl.busy_backoffs for cl, _s, _p in self.streams
        )


# ----------------------------------------------------------------------
# Sharded VOPR: the multi-cluster router under the full nemesis mix.


class ShardedWorkload:
    """Seeded request mix over an account-sharded cluster: shard-local
    transfers, CROSS-shard transfers (the 2PC path), local two-phase
    pending/post/void, and lookups.

    Every account is limit-free (no debits/credits_must_not_exceed
    flags) and every transfer id unique, so each well-formed request
    succeeds regardless of the interleaving the router's relaxed
    intra-batch ordering produces — which makes the end state exactly
    reproducible by a single-node oracle replay of the reported-ok
    stream (`oracle_replay`).
    """

    def __init__(self, seed: int, n_shards: int,
                 cross_ratio: float = 0.35, tenants: int = 1) -> None:
        self.rng = np.random.default_rng(seed)
        self.n_shards = n_shards
        self.cross_ratio = cross_ratio
        # Multi-tenant mode (round 16): accounts spread round-robin
        # over `tenants` ledgers; transfer traffic is flood-biased
        # toward ledger 1 (one tenant drives most of the load while
        # the rest trickle).  tenants=1 consumes the RNG stream
        # byte-identically to the frozen v1 profile — the pinned
        # regression seeds (4242/2046/3013) must keep reproducing
        # their original fault interleavings.
        self.tenants = tenants
        self.by_shard: dict[int, list[int]] = {s: [] for s in range(n_shards)}
        self.pools: dict[tuple[int, int], list[int]] = {}  # (shard, ledger)
        self.ledger_of: dict[int, int] = {}
        self.account_ids: list[int] = []
        # Local (same-shard) pending transfers awaiting post/void:
        # (tid, shard, ledger).
        self.pending_local: list[tuple[int, int, int]] = []
        self.next_account = 1
        self.next_transfer = 1_000_000
        # Every attempted cross-shard transfer: (tid, dshard, cshard),
        # with amount/debitor alongside (the oracle needs them to
        # model compensations).
        self.xfers: list[tuple[int, int, int]] = []
        self.xfer_amount: dict[int, int] = {}
        self.xfer_debitor: dict[int, int] = {}

    def _pick_tenant(self) -> int:
        """Flood-biased ledger choice: tenant 1 drives ~70% of the
        traffic, the rest trickle.  No RNG draw in single-tenant mode
        (the frozen stream)."""
        if self.tenants == 1:
            return 1
        if self.rng.random() < 0.7:
            return 1
        return 2 + int(self.rng.integers(self.tenants - 1))

    def _new_accounts(self, n: int):
        rows = []
        for _ in range(n):
            aid = self.next_account
            self.next_account += 1
            # Round-robin ledger assignment (deterministic, no RNG):
            # every tenant's pool fills on every shard.
            ledger = 1 + (aid % self.tenants) if self.tenants > 1 else 1
            rows.append(account(aid, ledger=ledger, code=1))
            self.account_ids.append(aid)
            self.ledger_of[aid] = ledger
            shard = types.shard_of_account(aid, self.n_shards)
            self.by_shard[shard].append(aid)
            self.pools.setdefault((shard, ledger), []).append(aid)
        return types.Operation.create_accounts, pack(rows), "accounts"

    def _pick_local_pair(self, ledger: int = 0) -> tuple[int, int, int]:
        """(debit, credit, shard) on one shard (needs >= 2 accounts
        of `ledger`; 0 = any, the frozen single-tenant path)."""
        if not ledger:
            shards = [s for s, ids in self.by_shard.items() if len(ids) >= 2]
            s = int(self.rng.choice(shards))
            dr, cr = self.rng.choice(self.by_shard[s], size=2, replace=False)
            return int(dr), int(cr), s
        shards = [
            s for s in range(self.n_shards)
            if len(self.pools.get((s, ledger), ())) >= 2
        ]
        s = int(self.rng.choice(shards))
        dr, cr = self.rng.choice(self.pools[(s, ledger)], size=2,
                                 replace=False)
        return int(dr), int(cr), s

    def _pick_cross_pair(self, ledger: int = 0) -> tuple[int, int, int, int]:
        if not ledger:
            shards = [s for s, ids in self.by_shard.items() if ids]
            a, b = self.rng.choice(shards, size=2, replace=False)
            dr = int(self.rng.choice(self.by_shard[int(a)]))
            cr = int(self.rng.choice(self.by_shard[int(b)]))
            return dr, cr, int(a), int(b)
        shards = [
            s for s in range(self.n_shards)
            if self.pools.get((s, ledger))
        ]
        a, b = self.rng.choice(shards, size=2, replace=False)
        dr = int(self.rng.choice(self.pools[(int(a), ledger)]))
        cr = int(self.rng.choice(self.pools[(int(b), ledger)]))
        return dr, cr, int(a), int(b)

    def _ready(self) -> bool:
        if self.tenants == 1:
            return (
                sum(1 for ids in self.by_shard.values() if len(ids) >= 2)
                >= self.n_shards
            )
        # Every tenant needs a local pair somewhere AND presence on
        # two distinct shards (for the cross-shard leg).
        for ledger in range(1, self.tenants + 1):
            if not any(
                len(self.pools.get((s, ledger), ())) >= 2
                for s in range(self.n_shards)
            ):
                return False
            if sum(
                1 for s in range(self.n_shards)
                if self.pools.get((s, ledger))
            ) < 2:
                return False
        return True

    def next_request(self):
        """-> (operation, body, kind); kind in accounts/local/cross/
        post_void/lookup."""
        if not self._ready() or self.rng.random() < 0.06:
            return self._new_accounts(int(self.rng.integers(2, 5)))
        roll = self.rng.random()
        # 0 = frozen single-tenant path (ledger defaults on the rows);
        # >0 = the flood-biased tenant whose pools the pickers filter.
        ledger = self._pick_tenant() if self.tenants > 1 else 0
        if roll < self.cross_ratio:
            dr, cr, ds, cs = self._pick_cross_pair(ledger)
            rows = []
            for _ in range(int(self.rng.integers(1, 4))):
                tid = self.next_transfer
                self.next_transfer += 1
                self.xfers.append((tid, ds, cs))
                amount = int(self.rng.integers(1, 100))
                self.xfer_amount[tid] = amount
                self.xfer_debitor[tid] = dr
                rows.append(transfer(
                    tid, debit_account_id=dr, credit_account_id=cr,
                    amount=amount, ledger=ledger or 1,
                ))
            return types.Operation.create_transfers, pack(rows), "cross"
        if roll < self.cross_ratio + 0.30:
            dr, cr, _s = self._pick_local_pair(ledger)
            rows = []
            for _ in range(int(self.rng.integers(1, 5))):
                tid = self.next_transfer
                self.next_transfer += 1
                rows.append(transfer(
                    tid, debit_account_id=dr, credit_account_id=cr,
                    amount=int(self.rng.integers(1, 100)),
                    ledger=ledger or 1,
                ))
            return types.Operation.create_transfers, pack(rows), "local"
        if roll < self.cross_ratio + 0.42:
            dr, cr, s = self._pick_local_pair(ledger)
            tid = self.next_transfer
            self.next_transfer += 1
            self.pending_local.append((tid, s, ledger or 1))
            return (
                types.Operation.create_transfers,
                pack([transfer(tid, debit_account_id=dr,
                               credit_account_id=cr,
                               amount=int(self.rng.integers(1, 50)),
                               ledger=ledger or 1,
                               flags=types.TransferFlags.pending)]),
                "local",
            )
        if roll < self.cross_ratio + 0.52 and self.pending_local:
            pid, _s, pledger = self.pending_local.pop(
                int(self.rng.integers(len(self.pending_local)))
            )
            tid = self.next_transfer
            self.next_transfer += 1
            void = self.rng.random() < 0.3
            flags = (
                types.TransferFlags.void_pending_transfer if void
                else types.TransferFlags.post_pending_transfer
            )
            return (
                types.Operation.create_transfers,
                pack([transfer(tid, pending_id=pid, ledger=pledger,
                               flags=flags)]),
                "post_void",
            )
        ids = [
            int(v) for v in self.rng.choice(
                self.account_ids, size=min(4, len(self.account_ids))
            )
        ]
        return types.Operation.lookup_accounts, ids_bytes(ids), "lookup"


class ShardedVopr:
    """Deterministic whole-system fuzz of the sharded router: per-shard
    nemeses (replica crash losing unsynced sectors, crash INSIDE a
    covering fsync, partitions, optional device loss) plus the
    coordinator-kill nemesis, with conservation-of-money and 2PC
    atomicity checked at every audit point and an oracle replay at the
    end."""

    AUDIT_EVERY = 41  # steps between mid-run invariant audits

    @property
    def _chaos_links(self) -> list:
        """Flattened per-shard chaos links (factories append lazily)."""
        return [lk for links in self._chaos_link_lists for lk in links]

    def __init__(self, seed: int, *, n_shards: int = 2,
                 replica_count: int = 2, requests: int = 30,
                 packet_loss: float = 0.01,
                 crash_probability: float = 0.004,
                 fsync_crash_probability: float = 0.002,
                 partition_probability: float = 0.004,
                 coordinator_kill_probability: float = 0.004,
                 device_loss_probability: float = 0.0,
                 cross_ratio: float = 0.35,
                 tenants: int = 1,
                 tenant_qos: dict | None = None) -> None:
        self.seed = seed
        self.rng = np.random.default_rng(seed + 1)
        factories = None
        # Per-shard link lists, populated LAZILY by the factories as
        # machines are built — flatten at use time, not here.
        self._chaos_link_lists: list[list] = []
        if device_loss_probability > 0.0:
            from tigerbeetle_tpu.testing.chaos import device_chaos_factory

            factories = []
            for s in range(n_shards):
                factory, links = device_chaos_factory(seed + 40 + s)
                factories.append(factory)
                self._chaos_link_lists.append(links)
        self.cluster = ShardedCluster(
            n_shards, replica_count=replica_count, seed=seed,
            options=PacketOptions(packet_loss_probability=packet_loss),
            state_machine_factories=factories,
            tenant_qos=tenant_qos,
        )
        self.workload = ShardedWorkload(seed + 2, n_shards,
                                        cross_ratio=cross_ratio,
                                        tenants=tenants)
        self.requests = requests
        self.crash_probability = crash_probability
        self.fsync_crash_probability = fsync_crash_probability
        self.partition_probability = partition_probability
        self.coordinator_kill_probability = coordinator_kill_probability
        self.device_loss_probability = device_loss_probability
        self.crashed: set[tuple[int, int]] = set()  # (shard, replica)
        # With no shard nemeses in the mix, a cross-shard abort needs a
        # coordinator kill to be legal; under the full mix, a long
        # stall can legitimately expire a hold.
        self._strict_cross = (
            crash_probability == 0 and fsync_crash_probability == 0
            and partition_probability == 0 and packet_loss == 0
            and device_loss_probability == 0
        )
        self._partitioned: dict[int, set[int]] = {}
        self._fsync_armed: tuple[int, int] | None = None
        self.coordinator_kills = 0
        # Requests whose submit/reply window overlapped a coordinator
        # kill may legally abort with pending_transfer_expired.
        self._kill_epoch = 0
        self.audits = 0
        # The reported-ok logical stream, for the oracle replay:
        # (operation, body, per-row ok mask).
        self.ok_stream: list[tuple[types.Operation, bytes, list[bool]]] = []

    # -- nemesis -------------------------------------------------------

    def _nemesis(self) -> None:
        c = self.cluster
        # Coordinator kill/restart: the defining nemesis of this VOPR.
        if c.router is None:
            if self.rng.random() < 0.08:
                c.start_router()  # recovery runs before/while serving
        elif self.rng.random() < self.coordinator_kill_probability:
            c.kill_router()
            self.coordinator_kills += 1
            self._kill_epoch += 1
        for s, shard in enumerate(c.shards):
            # Partition / heal, per shard.
            parts = self._partitioned.setdefault(s, set())
            if parts:
                if self.rng.random() < 0.05:
                    shard.network.heal(*parts)
                    parts.clear()
            elif self.rng.random() < self.partition_probability:
                i = int(self.rng.integers(len(shard.replicas)))
                if (s, i) not in self.crashed:
                    shard.network.partition(i)
                    parts.add(i)
            # Crash (power loss: unsynced sectors gone) / restart.
            down = [r for (sh, r) in self.crashed if sh == s]
            if down:
                if self.rng.random() < 0.06:
                    i = down[0]
                    shard.restart_replica(i)
                    self.crashed.discard((s, i))
            elif self.rng.random() < self.crash_probability:
                i = int(self.rng.integers(len(shard.replicas)))
                if i not in parts:
                    shard.crash_replica(i)
                    self.crashed.add((s, i))
            # Crash INSIDE a covering fsync (storage fault point).
            if self._fsync_armed is None and not down and (
                self.rng.random() < self.fsync_crash_probability
            ):
                i = int(self.rng.integers(len(shard.replicas)))
                if (s, i) not in self.crashed and i not in parts:
                    shard.storages[i].crash_at_fsync = 1
                    self._fsync_armed = (s, i)
        if self.device_loss_probability and self._chaos_links:
            downed = [lk for lk in self._chaos_links if lk.down]
            if downed:
                if self.rng.random() < 0.10:
                    for lk in downed:
                        lk.heal()
            elif self.rng.random() < self.device_loss_probability:
                pick = int(self.rng.integers(len(self._chaos_links)))
                self._chaos_links[pick].kill()

    def _step(self) -> None:
        try:
            self.cluster.step()
        except FsyncCrash:
            # The armed replica died inside its fsync: finish the crash
            # (its unsynced sectors are gone with it).
            assert self._fsync_armed is not None
            s, i = self._fsync_armed
            self._fsync_armed = None
            self.cluster.shards[s].crash_replica(i)
            self.crashed.add((s, i))

    # -- audits --------------------------------------------------------

    def _audit_point(self) -> None:
        self.audits += 1
        self.cluster.check_conservation()
        self.cluster.check_atomicity(self.workload.xfers)

    def _audit_reply(self, kind: str, body: bytes, reply: bytes,
                     submitted_epoch: int) -> None:
        if kind == "lookup":
            return
        results = np.frombuffer(reply, dtype=types.CREATE_RESULT_DTYPE)
        for r in results:
            code = int(r["result"])
            idx = int(r["index"])
            if kind == "cross" and code == int(
                types.CreateTransferResult.pending_transfer_expired
            ) and (not self._strict_cross
                   or submitted_epoch != (self._kill_epoch, True)):
                # Legal abort: the coordinator died between this
                # transfer's holds and its decision, the request raced
                # a restarted coordinator's still-running in-doubt
                # recovery, or a nemesis stalled the 2PC past the hold
                # timeout.  With every nemesis off (_strict_cross) an
                # abort is only legal when a kill overlapped the
                # request.
                continue
            raise AssertionError(
                f"{kind} request row {idx} failed with "
                f"{types.CreateTransferResult(code).name} "
                f"(kills={self.coordinator_kills})"
            )

    # -- run -----------------------------------------------------------

    def run(self) -> None:
        c = self.cluster
        client = c.client(9000 + self.seed % 100)
        client.register()
        c.run_until(lambda: client.registered, max_steps=6000)

        sent = 0
        guard = 0
        pending_audit = None
        while sent < self.requests or client.busy():
            guard += 1
            assert guard < 400_000, "sharded vopr stalled"
            self._nemesis()
            if not client.busy() and c.router is not None:
                if pending_audit is not None:
                    op, body, kind, epoch = pending_audit
                    self._audit_reply(kind, body, client.reply, epoch)
                    self._record_ok(op, body, kind, client.reply)
                    pending_audit = None
                if sent < self.requests:
                    op, body, kind = self.workload.next_request()
                    client.request(op, body)
                    # Submit context for the audit: the kill epoch AND
                    # whether recovery had already finished — an abort
                    # is only a finding when neither a kill nor a live
                    # recovery overlapped the request.
                    settled = (
                        c.router._recovery is None
                        or c.router.recovery_result is not None
                    )
                    pending_audit = (
                        op, body, kind, (self._kill_epoch, settled)
                    )
                    sent += 1
            self._step()
            if guard % self.AUDIT_EVERY == 0:
                self._audit_point()
        if pending_audit is not None:
            op, body, kind, epoch = pending_audit
            self._audit_reply(kind, body, client.reply, epoch)
            self._record_ok(op, body, kind, client.reply)

        # Heal everything, finish recovery, settle, final checks.
        if self._fsync_armed is not None:
            # Disarm an unfired fault: the quiesce phase below must
            # not crash a replica outside the nemesis loop.
            s, i = self._fsync_armed
            c.shards[s].storages[i].crash_at_fsync = None
            self._fsync_armed = None
        for lk in self._chaos_links:
            lk.heal()
        for s, shard in enumerate(c.shards):
            shard.network.heal()
            self._partitioned.get(s, set()).clear()
        for s, i in sorted(self.crashed):
            c.shards[s].restart_replica(i)
        self.crashed.clear()
        if c.router is None:
            c.start_router()
        c.settle(max_steps=40_000)
        self._audit_point()
        c.check_shards()
        c.check_atomicity(self.workload.xfers, final=True)
        # Final proof-of-state audit: per-shard roots agree across
        # replicas, the folded cluster commitment is well-defined, and
        # the router's query path folds to the same value.
        folded = c.check_cluster_commitment()
        if c.router is not None:
            from tigerbeetle_tpu.state_machine import commitment as _cm

            root, _n = _cm.parse_root_body(c.router.query_cluster_root())
            assert root == folded, (root.hex(), folded.hex())
        self.oracle_compare()

    def _record_ok(self, op, body: bytes, kind: str, reply: bytes) -> None:
        if kind not in ("accounts", "local", "cross", "post_void"):
            return
        dtype = (
            types.ACCOUNT_DTYPE if kind == "accounts"
            else types.TRANSFER_DTYPE
        )
        n = len(body) // dtype.itemsize
        ok = [True] * n
        for r in np.frombuffer(reply, dtype=types.CREATE_RESULT_DTYPE):
            ok[int(r["index"])] = False
        self.ok_stream.append((op, body, ok))

    def oracle_compare(self) -> None:
        """Replay the reported-ok stream through a single-node CPU
        oracle and require every client account's balances to match the
        sharded reality exactly — cross-shard transfers included."""
        from tigerbeetle_tpu.testing.harness import SingleNodeHarness

        oracle = SingleNodeHarness(CpuStateMachine(self.cluster.config))
        for op, body, ok in self.ok_stream:
            dtype = (
                types.ACCOUNT_DTYPE if op == types.Operation.create_accounts
                else types.TRANSFER_DTYPE
            )
            rows = np.frombuffer(body, dtype=dtype)
            keep = [rows[i] for i in range(len(rows)) if ok[i]]
            if not keep:
                continue
            out = oracle.submit(op, pack(keep))
            results = np.frombuffer(out, dtype=types.CREATE_RESULT_DTYPE)
            assert len(results) == 0, (
                "oracle rejected a reported-ok row", op, results[:4],
            )
        # A compensated cross-shard transfer (decided commit whose
        # credit hold died — budget violation) is a reversing entry,
        # not an erasure: the debitor shows the posted debit AND the
        # refunding credit.  Fold those trail entries into the oracle's
        # expectation.
        adjust: dict[int, int] = {}
        compensated = 0
        for tid, ds, cs in self.workload.xfers:
            _sd, _sc, comp = self.cluster.cross_status(tid, ds, cs)
            if comp:
                compensated += 1
                debitor = self.workload.xfer_debitor[tid]
                adjust[debitor] = (
                    adjust.get(debitor, 0) + self.workload.xfer_amount[tid]
                )
        self.compensations = compensated
        for aid in self.workload.account_ids:
            shard = types.shard_of_account(aid, self.cluster.n_shards)
            got = self.cluster._live_sm(shard).account_balances_raw(aid)
            dp, dpo, cp, cpo = oracle.sm.account_balances_raw(aid)
            extra = adjust.get(aid, 0)
            want = (dp, dpo + extra, cp, cpo + extra)
            assert got == want, (aid, shard, got, want)


# ----------------------------------------------------------------------
# Follower nemesis VOPR (round 19): root-attested follower serving
# under crash / torn tail / corruption / partition / lag.


class FollowerVopr:
    """Adversarial proof of the follower robustness contract.

    A 2-replica cluster commits a seeded workload while replica 0's
    SimAof feeds a SimFollower; reads are attempted against the
    follower throughout.  Nemeses (all seeded):

    - follower crash/restart mid-tail (volatile state re-derives from
      the log, serving re-gated on fresh attestation),
    - upstream replica crash — power loss AND crash-INSIDE-fsync
      (storage.crash_at_fsync) — both tearing the AOF's unsynced
      tail, healed by repair-on-open + recovery gap-fill,
    - seeded corruption of a tailed-log byte (latent sector error),
    - partition follower <-> upstream (attestations stop; staleness
      refusals take over),
    - lag injection (replay paused under continued commits).

    THE invariant (check_never_lied): no served reply ever carries a
    (root, commit_min) differing from the cluster's committed root at
    that op — every nemesis may only produce refusals/redirects.
    """

    def __init__(self, seed: int, *, replica_count: int = 2,
                 request_count: int = 120, staleness_ops: int = 24,
                 corruption_probability: float = 0.0015,
                 follower_crash_probability: float = 0.004,
                 partition_probability: float = 0.008,
                 pause_probability: float = 0.008,
                 crash_probability: float = 0.002,
                 fsync_crash_probability: float = 0.001) -> None:
        from tigerbeetle_tpu.testing.cluster import SimFollower

        self.seed = seed
        self.rng = np.random.default_rng(seed ^ 0xF0110)
        self.cluster = Cluster(
            replica_count=replica_count, seed=seed,
            aof_replicas=(0,), root_ring=1 << 20,
        )
        self.follower = SimFollower(
            self.cluster, 0, staleness_ops=staleness_ops,
            attest_every=4,
        )
        self.workload = Workload(seed)
        self.request_count = request_count
        self.corruption_probability = corruption_probability
        self.follower_crash_probability = follower_crash_probability
        self.partition_probability = partition_probability
        self.pause_probability = pause_probability
        self.crash_probability = crash_probability
        self.fsync_crash_probability = fsync_crash_probability
        # Nemesis state/coverage.
        self.crashed: set[int] = set()
        self._fsync_armed: int | None = None
        self.follower_crashes = 0
        self.upstream_crashes = 0
        self.fsync_crashes = 0
        self.corruptions = 0
        self.partitions = 0
        self.pauses = 0
        self.reads_attempted = 0
        self.reads_served = 0
        self.reads_fallback = 0  # refused -> redirected to primary

    # -- nemesis --------------------------------------------------------

    def _nemesis(self) -> None:
        c = self.cluster
        f = self.follower
        rng = self.rng
        # Follower crash/restart mid-tail.
        if rng.random() < self.follower_crash_probability:
            f.crash_restart()
            self.follower_crashes += 1
        # Partition follower <-> upstream.
        if f.partitioned:
            if rng.random() < 0.05:
                f.partitioned = False
        elif rng.random() < self.partition_probability:
            f.partitioned = True
            self.partitions += 1
        # Lag injection: replay paused while commits continue.
        if f.paused:
            if rng.random() < 0.05:
                f.paused = False
        elif rng.random() < self.pause_probability:
            f.paused = True
            self.pauses += 1
        # Seeded corruption of a tailed-log byte.
        if rng.random() < self.corruption_probability:
            if c.aofs[0].corrupt(rng) is not None:
                self.corruptions += 1
        # Upstream crash (power loss, torn AOF tail) / restart.
        if self.crashed:
            if rng.random() < 0.06:
                i = self.crashed.pop()
                c.restart_replica(i)
            return
        if rng.random() < self.crash_probability:
            i = int(rng.integers(len(c.replicas)))
            c.crash_replica(i)
            self.upstream_crashes += i == 0
            self.crashed.add(i)
            return
        # Crash INSIDE a covering fsync (storage fault point): the
        # sharpest torn-tail producer — the process dies with the WAL
        # sync half-applied AND the AOF suffix unsynced.
        if self._fsync_armed is None and (
            rng.random() < self.fsync_crash_probability
        ):
            i = int(rng.integers(len(c.replicas)))
            c.storages[i].crash_at_fsync = 1
            self._fsync_armed = i

    def _step(self) -> None:
        try:
            self.cluster.step()
        except FsyncCrash:
            assert self._fsync_armed is not None
            i = self._fsync_armed
            self._fsync_armed = None
            self.cluster.crash_replica(i)
            self.upstream_crashes += i == 0
            self.fsync_crashes += 1
            self.crashed.add(i)

    # -- reads ----------------------------------------------------------

    def _try_read(self) -> None:
        """One steered read: follower first; a refusal 'redirects' to
        a live replica's state machine (the router fallback, modeled
        transport-free)."""
        from tigerbeetle_tpu.runtime.follower import FollowerReply

        w = self.workload
        if not w.account_ids:
            return
        ids = [
            int(v) for v in self.rng.choice(
                w.account_ids, size=min(4, len(w.account_ids))
            )
        ]
        body = ids_bytes(ids)
        self.reads_attempted += 1
        result = self.follower.read(types.Operation.lookup_accounts, body)
        if isinstance(result, FollowerReply):
            self.reads_served += 1
        else:
            self.reads_fallback += 1

    # -- run -------------------------------------------------------------

    def run(self) -> None:
        c = self.cluster
        client = c.client(0x9F01)
        client.register()
        c.run_until(lambda: not client.busy(), 4000)
        sent = 0
        steps = 0
        while sent < self.request_count:
            steps += 1
            assert steps < 200_000, "follower VOPR stalled"
            self._nemesis()
            if not client.busy() and not client.evicted:
                op, body, _must = self.workload.next_request()
                client.request(op, body)
                sent += 1
            if steps % 7 == 0:
                self._try_read()
            self._step()
        # Quiesce: heal everything, restart the dead, settle.
        if self._fsync_armed is not None:
            c.storages[self._fsync_armed].crash_at_fsync = None
            self._fsync_armed = None
        for i in sorted(self.crashed):
            c.restart_replica(i)
        self.crashed.clear()
        self.follower.partitioned = False
        self.follower.paused = False
        c.network.heal()
        for _ in range(600):
            self._step()
            if not client.busy():
                break
        c.settle(max_steps=8000)
        # Let the follower catch up + re-attest at the quiesced head.
        for _ in range(400):
            self._step()
            if self.follower.core.refuse_reason() is None and (
                self.follower.core.commit_min
                == c.replicas[0].commit_min
            ):
                break

        # THE invariant, unconditionally: refusals allowed, lies never.
        self.follower.check_never_lied()

        core = self.follower.core
        # A follower may end the run un-servable for honest reasons:
        # latched corruption/gap, or a permanently torn tail (e.g.
        # corruption at EOF, or a gap-fill cut short by the
        # checkpoint floor leaves the stream short of its resume
        # offset).  All of those REFUSE; none may lie.
        damaged = core.tail.corrupt or core.gapped or core.poisoned
        stalled = core.commit_min < c.replicas[0].commit_min
        assert not core.poisoned, (
            "deterministic replay of a checksummed log diverged: "
            "poisoned follower without corruption"
        )
        if not damaged and not stalled:
            # Liveness after heal: the follower must serve again, and
            # serve bit-identically to the primary at the same op.
            assert core.refuse_reason() is None, core.refuse_reason()
            assert core.commit_min == c.replicas[0].commit_min
            ids = [int(v) for v in self.workload.account_ids[:8]]
            body = ids_bytes(ids)
            from tigerbeetle_tpu.runtime.follower import FollowerReply

            got = self.follower.read(
                types.Operation.lookup_accounts, body
            )
            assert isinstance(got, FollowerReply), got
            want = c.replicas[0].sm.execute_read(
                types.Operation.lookup_accounts, body
            )
            assert got.body == want, "follower read diverged from primary"
            self.follower.check_never_lied()
