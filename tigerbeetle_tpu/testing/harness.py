"""Single-node commit-pipeline harness + event builders.

Drives a state machine through prepare -> prefetch -> commit the same
way the replica's commit dispatch does (reference:
src/vsr/replica.zig:5746-5844 for timestamping, :3766 for
prefetch_timestamp, :3126-3143 for pulse injection).
"""

from __future__ import annotations

import numpy as np

from tigerbeetle_tpu import types
from tigerbeetle_tpu.types import (
    ACCOUNT_DTYPE,
    TRANSFER_DTYPE,
    CreateAccountResult,
    CreateTransferResult,
    Operation,
)


def account(
    id: int,
    *,
    ledger: int = 1,
    code: int = 1,
    flags: int = 0,
    debits_pending: int = 0,
    debits_posted: int = 0,
    credits_pending: int = 0,
    credits_posted: int = 0,
    user_data_128: int = 0,
    user_data_64: int = 0,
    user_data_32: int = 0,
    reserved: int = 0,
    timestamp: int = 0,
) -> np.ndarray:
    """One Account event row (wire layout)."""
    row = np.zeros(1, dtype=ACCOUNT_DTYPE)[0]
    types.u128_set(row, "id", id)
    types.u128_set(row, "debits_pending", debits_pending)
    types.u128_set(row, "debits_posted", debits_posted)
    types.u128_set(row, "credits_pending", credits_pending)
    types.u128_set(row, "credits_posted", credits_posted)
    types.u128_set(row, "user_data_128", user_data_128)
    row["user_data_64"] = user_data_64
    row["user_data_32"] = user_data_32
    row["reserved"] = reserved
    row["ledger"] = ledger
    row["code"] = code
    row["flags"] = flags
    row["timestamp"] = timestamp
    return row


def transfer(
    id: int,
    *,
    debit_account_id: int = 0,
    credit_account_id: int = 0,
    amount: int = 0,
    pending_id: int = 0,
    user_data_128: int = 0,
    user_data_64: int = 0,
    user_data_32: int = 0,
    timeout: int = 0,
    ledger: int = 1,
    code: int = 1,
    flags: int = 0,
    timestamp: int = 0,
) -> np.ndarray:
    """One Transfer event row (wire layout)."""
    row = np.zeros(1, dtype=TRANSFER_DTYPE)[0]
    types.u128_set(row, "id", id)
    types.u128_set(row, "debit_account_id", debit_account_id)
    types.u128_set(row, "credit_account_id", credit_account_id)
    types.u128_set(row, "amount", amount)
    types.u128_set(row, "pending_id", pending_id)
    types.u128_set(row, "user_data_128", user_data_128)
    row["user_data_64"] = user_data_64
    row["user_data_32"] = user_data_32
    row["timeout"] = timeout
    row["ledger"] = ledger
    row["code"] = code
    row["flags"] = flags
    row["timestamp"] = timestamp
    return row


def pack(rows) -> bytes:
    """Stack event rows into a wire-format batch."""
    if isinstance(rows, np.ndarray) and rows.shape == ():
        rows = [rows]
    if isinstance(rows, (list, tuple)):
        if not rows:
            return b""
        arr = np.stack([np.asarray(r) for r in rows])
    else:
        arr = np.asarray(rows)
    return arr.tobytes()


def ids_bytes(ids: list[int]) -> bytes:
    arr = np.zeros(len(ids), dtype=types.U128_PAIR_DTYPE)
    for i, v in enumerate(ids):
        arr[i]["lo"] = v & types.U64_MAX
        arr[i]["hi"] = v >> 64
    return arr.tobytes()


class SingleNodeHarness:
    """Mimics the primary's prepare/commit loop around a state machine."""

    def __init__(self, state_machine) -> None:
        self.sm = state_machine
        self.op = 0
        self.realtime = 0

    def tick_pulses(self) -> None:
        """Inject pulse operations while the state machine asks for them
        (reference: src/vsr/replica.zig:3126-3143)."""
        while self.sm.pulse_needed():
            before = self.sm.pulse_next_timestamp
            self._run(Operation.pulse, b"")
            # A pulse that found nothing parks pulse_next_timestamp in
            # the future; avoid spinning forever otherwise.
            if self.sm.pulse_next_timestamp == before:
                break

    def _dispatch(self, operation: Operation, input_bytes: bytes):
        """Shared prepare/prefetch/commit prologue; returns the reply
        future (timestamping reference: src/vsr/replica.zig:5762-5772)."""
        self.sm.prepare_timestamp = max(
            max(self.sm.prepare_timestamp, self.sm.commit_timestamp) + 1,
            self.realtime,
        )
        self.sm.prepare(operation, input_bytes)
        timestamp = self.sm.prepare_timestamp
        self.op += 1
        self.sm.prefetch(operation, input_bytes, prefetch_timestamp=timestamp)
        if hasattr(self.sm, "commit_async"):
            return self.sm.commit_async(
                0, self.op, timestamp, operation, input_bytes
            )
        from tigerbeetle_tpu.state_machine.device_engine import ReplyFuture

        return ReplyFuture(
            value=self.sm.commit(0, self.op, timestamp, operation, input_bytes)
        )

    def _run(self, operation: Operation, input_bytes: bytes) -> bytes:
        return self._dispatch(operation, input_bytes).result()

    def submit(
        self, operation: Operation, input_bytes: bytes, *, realtime: int | None = None
    ) -> bytes:
        return self.submit_async(
            operation, input_bytes, realtime=realtime
        ).result()

    def submit_async(
        self, operation: Operation, input_bytes: bytes, *, realtime: int | None = None
    ):
        """Pipelined submission: returns a reply future (resolved
        immediately for state machines without commit_async).  The
        device-engine path materializes replies in submission order at
        ring-fetch boundaries — the same pipelining the reference's
        async client drives (src/clients/c/tb_client/packet.zig)."""
        if realtime is not None:
            self.realtime = realtime
        if operation != Operation.pulse:
            self.tick_pulses()
        return self._dispatch(operation, input_bytes)

    # Convenience wrappers -------------------------------------------------

    def create_accounts(self, rows, **kw) -> list[tuple[int, CreateAccountResult]]:
        out = self.submit(Operation.create_accounts, pack(rows), **kw)
        arr = np.frombuffer(out, dtype=types.CREATE_RESULT_DTYPE)
        return [(int(r["index"]), CreateAccountResult(int(r["result"]))) for r in arr]

    def create_transfers(self, rows, **kw) -> list[tuple[int, CreateTransferResult]]:
        out = self.submit(Operation.create_transfers, pack(rows), **kw)
        arr = np.frombuffer(out, dtype=types.CREATE_RESULT_DTYPE)
        return [(int(r["index"]), CreateTransferResult(int(r["result"]))) for r in arr]

    def lookup_accounts(self, ids: list[int]) -> np.ndarray:
        out = self.submit(Operation.lookup_accounts, ids_bytes(ids))
        return np.frombuffer(out, dtype=ACCOUNT_DTYPE)

    def lookup_transfers(self, ids: list[int]) -> np.ndarray:
        out = self.submit(Operation.lookup_transfers, ids_bytes(ids))
        return np.frombuffer(out, dtype=TRANSFER_DTYPE)
