"""Commit hash log: pinpoint the first divergent op between replicas.

The reference's hash_log records a running hash of consensus-critical
values during a VOPR run so that two runs (or two replicas) that
should be identical can be diffed to the exact divergence point
instead of a failed end-state assertion (reference:
src/testing/hash_log.zig:1-5).

Each replica records, per committed op, a chained digest of
(previous digest, prepare checksum, reply bytes).  Comparing two logs
yields the first op where they differ — the op whose execution
diverged — independent of how much later state drifted.
"""

from __future__ import annotations

import hashlib


class HashLog:
    def __init__(self) -> None:
        self._digests: dict[int, bytes] = {}

    def record(self, op: int, *values: bytes) -> None:
        """Per-op digest (deliberately un-chained: logs legitimately
        have gaps — state sync skips ops, replay is not recorded — and
        chaining would turn a gap into a false divergence)."""
        h = hashlib.sha256(op.to_bytes(8, "little"))
        for v in values:
            h.update(len(v).to_bytes(4, "little"))
            h.update(v)
        self._digests[op] = h.digest()[:16]

    def digest(self, op: int) -> bytes | None:
        return self._digests.get(op)

    def prune_above(self, op: int) -> None:
        """Drop digests > op.  A crash can lose the WAL tail: ops the
        dead process committed beyond the recovered commit point were
        never durable and may be superseded after recovery, so their
        recordings are no longer vouched for."""
        for k in [k for k in self._digests if k > op]:
            del self._digests[k]

    @property
    def max_op(self) -> int:
        return max(self._digests, default=0)

    def first_divergence(self, other: "HashLog") -> int | None:
        """The lowest op both logs recorded with different digests."""
        common = sorted(set(self._digests) & set(other._digests))
        for op in common:
            if self._digests[op] != other._digests[op]:
                return op
        return None
