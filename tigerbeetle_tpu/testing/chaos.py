"""Deterministic device-link chaos injection.

The device-authoritative engine funnels every host<->device crossing
through one seam (device_engine.DeviceLink: "h2d" uploads, "dispatch"
kernel launches, "fetch" d2h reads, "probe" health checks).  ChaosLink
interposes on that seam with a SEEDED fault plan, so CPU-only tests can
drive the full degraded-mode lifecycle — transient-retry, fatal loss,
demote, serve-degraded, re-promote + checksum handshake — with no TPU
and byte-reproducible schedules (the VOPR discipline applied to the
accelerator link; reference: src/testing/storage.zig fault injection).

Fault kinds per crossing:
- transient: raises TransientLinkError once (a retry succeeds);
- fatal: raises FatalLinkError (classification skips the retry budget);
- down: every crossing fails fatally until heal()/auto-heal — a lost
  link, the BENCH_r06 failure mode;
- delay: sleeps a bounded jittered time first (pacing, not failure).
"""

from __future__ import annotations

import time

import numpy as np

from tigerbeetle_tpu.state_machine.device_engine import (
    DeviceLink,
    FatalLinkError,
    TransientLinkError,
)

STAGES = ("h2d", "dispatch", "fetch", "probe")


class ChaosLink(DeviceLink):
    """Fault-injecting DeviceLink shim, seeded and deterministic.

    Probabilistic faults (per crossing, only on stages in `stages`):
    `p_transient`, `p_fatal`, `p_kill` (goes down for `down_for`
    crossings, then auto-heals), `p_delay`/`delay_s`.  Scripted faults:
    `fail_next(stage=..., kind=..., count=...)` queues exact faults for
    the next matching crossings, and `kill()`/`heal()` toggle hard
    loss — both for tests that target one pipeline stage precisely.
    """

    def __init__(
        self,
        seed: int = 0,
        *,
        p_transient: float = 0.0,
        p_fatal: float = 0.0,
        p_kill: float = 0.0,
        down_for: int = 4,
        p_delay: float = 0.0,
        delay_s: float = 0.0,
        stages: tuple[str, ...] = STAGES,
    ) -> None:
        self.rng = np.random.default_rng(seed)
        self.p_transient = p_transient
        self.p_fatal = p_fatal
        self.p_kill = p_kill
        self.down_for = down_for
        self.p_delay = p_delay
        self.delay_s = delay_s
        self.stages = tuple(stages)
        self.down = False
        self._down_left = 0  # crossings left before auto-heal (0: manual)
        self._scripted: list[tuple[str | None, str]] = []
        # Forensics the tests assert on.
        self.crossings = 0
        self.stat_transient = 0
        self.stat_fatal = 0
        self.stat_kills = 0
        self.stat_delays = 0

    # -- fault controls -------------------------------------------------

    def kill(self, *, down_for: int = 0) -> None:
        """Hard link loss; heals after `down_for` crossings (0: only an
        explicit heal() brings it back)."""
        self.down = True
        self._down_left = down_for
        self.stat_kills += 1

    def heal(self) -> None:
        self.down = False
        self._down_left = 0

    def fail_next(
        self,
        stage: str | None = None,
        kind: str = "fatal",
        count: int = 1,
    ) -> None:
        """Queue `count` scripted faults for the next crossings that
        match `stage` (None: any stage).  kind: "transient"/"fatal"."""
        assert kind in ("transient", "fatal"), kind
        assert stage is None or stage in STAGES, stage
        self._scripted.extend([(stage, kind)] * count)

    # -- injection core -------------------------------------------------

    def _raise(self, kind: str, stage: str, why: str) -> None:
        message = f"chaos: {why} ({stage} crossing {self.crossings})"
        if kind == "transient":
            self.stat_transient += 1
            raise TransientLinkError(message)
        self.stat_fatal += 1
        raise FatalLinkError(message)

    def _cross(self, stage: str) -> None:
        self.crossings += 1
        if self.down:
            if self._down_left:
                self._down_left -= 1
                if self._down_left == 0:
                    self.down = False
            self._raise("fatal", stage, "link down")
        for i, (want_stage, kind) in enumerate(self._scripted):
            if want_stage is None or want_stage == stage:
                del self._scripted[i]
                self._raise(kind, stage, f"scripted {kind}")
        if stage not in self.stages:
            return
        # One rng draw per armed fault class, in a FIXED order, so a
        # schedule replays identically for a given seed regardless of
        # which faults fire.
        if self.p_kill and self.rng.random() < self.p_kill:
            self.kill(down_for=self.down_for)
            self._raise("fatal", stage, "link down")
        if self.p_fatal and self.rng.random() < self.p_fatal:
            self._raise("fatal", stage, "injected fatal")
        if self.p_transient and self.rng.random() < self.p_transient:
            self._raise("transient", stage, "injected transient")
        if self.p_delay and self.rng.random() < self.p_delay:
            self.stat_delays += 1
            if self.delay_s > 0:
                time.sleep(self.delay_s * float(self.rng.random()))

    # -- DeviceLink surface ---------------------------------------------

    def device_put(self, array, sharding=None):
        self._cross("h2d")
        return super().device_put(array, sharding)

    def block_until_ready(self, arrays):
        self._cross("h2d")
        return super().block_until_ready(arrays)

    def fetch(self, array) -> np.ndarray:
        self._cross("fetch")
        return super().fetch(array)

    def dispatch(self, fn, *args):
        self._cross("dispatch")
        return super().dispatch(fn, *args)

    def probe(self) -> None:
        self._cross("probe")
        super().probe()


def device_chaos_factory(
    seed: int,
    *,
    account_capacity: int = 1 << 12,
    **chaos_kw,
):
    """-> (state_machine_factory, links) for the cluster/VOPR harness.

    Each machine the factory builds (initial replicas, restarts,
    restart-replay copies) gets its own deterministically-seeded
    ChaosLink, collected in `links` so a nemesis can kill/heal them
    mid-run.  Faults hit replicas at DIFFERENT times, yet the
    degraded-mode lifecycle keeps every reply bit-identical — which the
    cluster's hash-log convergence checker then enforces for free.
    """
    links: list[ChaosLink] = []

    def factory():
        from tigerbeetle_tpu.state_machine.tpu import TpuStateMachine

        link = ChaosLink(seed=seed + 101 * len(links), **chaos_kw)
        links.append(link)
        return TpuStateMachine(
            engine="device",
            account_capacity=account_capacity,
            device_link=link,
        )

    return factory, links
