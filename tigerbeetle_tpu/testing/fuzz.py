"""Per-component fuzzer registry (reference: src/fuzz_tests.zig:24-42).

Each fuzzer is a seeded, self-checking exerciser of one component's
invariants against a simple model.  All register under FUZZERS and run
from one entry point:

    python -m tigerbeetle_tpu.testing.fuzz smoke            # all, brief
    python -m tigerbeetle_tpu.testing.fuzz journal --seed 7 --rounds 200

The smoke tier runs in CI on every test run (tests/test_fuzzers.py);
long runs are for soak sessions, mirroring the reference's CFO fleet
(reference: src/scripts/cfo.zig:1-46).
"""
# tbcheck: allow-file(no-print): fuzzer entry point — progress and
# repro lines print to the terminal/CI log by design.

from __future__ import annotations

import sys

import numpy as np

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu.vsr.storage import MemoryStorage, ZoneLayout


def _layout(grid_size: int = 1 << 20) -> ZoneLayout:
    return ZoneLayout(config=cfg.TEST_MIN, grid_size=grid_size)


# ---------------------------------------------------------------------------
# ewah: encode/decode roundtrip over adversarial bit patterns
# (reference: src/ewah.zig fuzz).


def fuzz_ewah(seed: int, rounds: int) -> None:
    from tigerbeetle_tpu.lsm import ewah

    rng = np.random.default_rng(seed)
    for _ in range(rounds):
        n = int(rng.integers(0, 200))
        style = rng.integers(0, 4)
        if style == 0:
            words = rng.integers(0, 1 << 63, n, np.uint64)
        elif style == 1:
            words = np.zeros(n, np.uint64)
        elif style == 2:
            words = np.full(n, ~np.uint64(0), np.uint64)
        else:
            # Long uniform runs with random literals sprinkled in.
            words = np.zeros(n, np.uint64)
            for _ in range(int(rng.integers(0, 4))):
                if n == 0:
                    break
                at = int(rng.integers(n))
                ln = int(rng.integers(1, n - at + 1))
                words[at : at + ln] = (
                    ~np.uint64(0) if rng.random() < 0.5
                    else np.uint64(rng.integers(1, 1 << 62))
                )
        blob = ewah.encode(words)
        out = ewah.decode(blob, len(words))
        assert np.array_equal(out, words), (seed, style, n)


# ---------------------------------------------------------------------------
# snapshot codec: roundtrip + corruption detection
# (fixed-layout checksummed blobs, utils/snapshot.py).


def fuzz_snapshot(seed: int, rounds: int) -> None:
    from tigerbeetle_tpu.utils import snapshot

    rng = np.random.default_rng(seed)
    dtypes = [np.uint8, np.uint32, np.uint64, np.int64, np.bool_]
    for _ in range(rounds):
        entries = {}
        for k in range(int(rng.integers(1, 8))):
            kind = rng.integers(0, 3)
            name = f"k{k}"
            if kind == 0:
                dt = dtypes[int(rng.integers(len(dtypes)))]
                entries[name] = rng.integers(0, 100, int(rng.integers(0, 50))).astype(dt)
            elif kind == 1:
                entries[name] = rng.bytes(int(rng.integers(0, 100)))
            else:
                entries[name] = int(rng.integers(0, 1 << 60))
        blob = snapshot.encode(entries)
        out = snapshot.decode(blob)
        assert set(out) == set(entries)
        for name, val in entries.items():
            got = out[name]
            if isinstance(val, np.ndarray):
                assert np.array_equal(got, val) and got.dtype == val.dtype
            else:
                assert got == val, (name, got, val)
        # One flipped byte anywhere must be detected, never silently
        # decoded into different data.
        if len(blob) > 0:
            at = int(rng.integers(len(blob)))
            bad = bytearray(blob)
            bad[at] ^= 0xFF
            try:
                out2 = snapshot.decode(bytes(bad))
            except (snapshot.SnapshotError, ValueError, KeyError):
                continue
            # Extremely unlikely benign flip (e.g. padding): contents
            # must still match exactly.
            for name, val in entries.items():
                got = out2[name]
                if isinstance(val, np.ndarray):
                    assert np.array_equal(got, val), "silent corruption"
                else:
                    assert got == val, "silent corruption"


# ---------------------------------------------------------------------------
# free set: reserve/acquire/release protocol vs a model + EWAH
# checkpoint roundtrip (reference: src/vsr/free_set.zig fuzz).


def fuzz_free_set(seed: int, rounds: int) -> None:
    from tigerbeetle_tpu.vsr.free_set import FreeSet

    rng = np.random.default_rng(seed)
    for _ in range(max(1, rounds // 20)):
        n = int(rng.integers(8, 256))
        fs = FreeSet(n)
        acquired: set[int] = set()
        for _ in range(rounds):
            roll = rng.random()
            if roll < 0.5 and fs.count_reservable() > 0:
                want = int(
                    rng.integers(1, min(8, fs.count_reservable()) + 1)
                )
                r = fs.reserve(want)
                took = [fs.acquire(r) for _ in range(int(rng.integers(want + 1)))]
                fs.forfeit(r)
                for a in took:
                    assert a not in acquired, "double allocation"
                    # A quarantined block must never be handed out.
                    assert not fs.quarantine[a - 1], "reused quarantined"
                    acquired.add(a)
            elif acquired and roll < 0.8:
                a = acquired.pop()
                fs.release(a)
            else:
                fs.checkpoint()
                # Freeze: released blocks are free in the encoded blob
                # but quarantined from reuse until the next freeze.
                assert not (fs.quarantine & ~fs.free).any(), seed
                blob = fs.encode()
                back = FreeSet.decode(blob, n)
                assert np.array_equal(back.free, fs.free), seed
        for a in acquired:
            assert not fs.is_free(a)


# ---------------------------------------------------------------------------
# journal: append + torn writes + sector corruption -> recovery
# classification (reference: src/vsr/journal.zig format/recovery fuzz).


def fuzz_journal(seed: int, rounds: int) -> None:
    from tigerbeetle_tpu.vsr import wire
    from tigerbeetle_tpu.vsr.journal import Journal

    rng = np.random.default_rng(seed)
    cluster = 7
    for case in range(max(1, rounds // 10)):
        storage = MemoryStorage(_layout(), seed=seed + case)
        j = Journal(storage, cluster)
        slot_count = j.slot_count
        n_ops = int(rng.integers(1, slot_count))  # no ring wrap: chain stays whole
        parent = 0
        appended: dict[int, bytes] = {}
        for op in range(1, n_ops + 1):
            body = rng.bytes(int(rng.integers(0, 200)))
            h = wire.make_header(
                command=wire.Command.prepare, cluster=cluster, op=op,
                parent=parent,
            )
            wire.finalize_header(h, body)
            parent = wire.u128(h, "checksum")
            j.write_prepare(h, body)
            appended[op] = h.tobytes() + body

        # Latent corruption of random prepare slots (not headers: a
        # corrupt header ring with intact prepare stays recoverable and
        # is covered by state "ok").
        corrupted: set[int] = set()
        for _ in range(int(rng.integers(0, 3))):
            op = int(rng.integers(1, n_ops + 1))
            corrupted.add(op)
            storage.corrupt_sector(
                storage.layout.prepare_slot_offset(j.slot_for_op(op))
            )

        fresh = Journal(storage, cluster)
        rec = fresh.recover(0)
        # Every op below the head that was NOT corrupted must be
        # recovered with byte-identical content; corrupted ops must be
        # classified faulty or truncate the head, never silently served.
        for op in range(1, rec.op_head + 1):
            if op in corrupted:
                assert op in rec.faulty_ops or op not in rec.headers, op
                continue
            if op in rec.headers:
                got = fresh.read_prepare(op)
                assert got is not None, op
                assert (got[0].tobytes() + got[1]) == appended[op], op
        for op in rec.faulty_ops:
            assert op in corrupted, f"op {op} falsely classified faulty"


# ---------------------------------------------------------------------------
# superblock: checkpoint sequences + copy corruption -> quorum open
# (reference: src/vsr/superblock_quorums.zig fuzz).


def fuzz_superblock(seed: int, rounds: int) -> None:
    from tigerbeetle_tpu.vsr.storage import SUPERBLOCK_COPIES
    from tigerbeetle_tpu.vsr.superblock import SuperBlock

    rng = np.random.default_rng(seed)
    for case in range(max(1, rounds // 10)):
        storage = MemoryStorage(_layout(), seed=seed + case)
        sb = SuperBlock(storage, cluster=3)
        sb.format(replica=0, replica_count=1)
        last = (0, 0)
        for _ in range(int(rng.integers(1, 8))):
            commit_min = int(rng.integers(1, 1000))
            sb.checkpoint(
                commit_min=commit_min,
                commit_min_checksum=int(rng.integers(1 << 60)),
                commit_max=commit_min,
                checkpoint_offset=0, checkpoint_size=0,
                checkpoint_checksum=0,
            )
            last = (int(sb.working["sequence"]), commit_min)

        # Corrupt up to COPIES - QUORUM_OPEN copies: open() must still
        # land on the last checkpoint.
        copy_size = storage.layout.superblock_size // SUPERBLOCK_COPIES
        for copy in rng.choice(
            SUPERBLOCK_COPIES, size=int(rng.integers(0, 3)), replace=False
        ):
            storage.corrupt_sector(
                storage.layout.superblock_offset + int(copy) * copy_size
            )
        fresh = SuperBlock(storage, cluster=3)
        h = fresh.open()
        assert (int(h["sequence"]), int(h["commit_min"])) == last, seed


# ---------------------------------------------------------------------------
# lsm tree: put/remove/seal/compact/lookup/scan vs a dict model
# (reference: src/lsm/tree.zig fuzz via forest fuzz).


def fuzz_tree(seed: int, rounds: int) -> None:
    from tigerbeetle_tpu.lsm.runs import pack_u128
    from tigerbeetle_tpu.lsm.tree import Tree
    from tigerbeetle_tpu.vsr.grid import Grid

    rng = np.random.default_rng(seed)
    for case in range(max(1, rounds // 40)):
        storage = MemoryStorage(_layout(grid_size=1 << 22), seed=seed + case)
        grid = Grid(storage, block_size=4096, block_count=1 << 10)
        tree = Tree(grid, "fuzz", value_size=8, memtable_max=64)
        model: dict[bytes, bytes] = {}
        key_space = 500
        for _ in range(rounds):
            roll = rng.random()
            if roll < 0.55:
                n = int(rng.integers(1, 40))
                key_lo = rng.integers(0, key_space, n).astype(np.uint64)
                keys = pack_u128(key_lo, np.zeros(n, np.uint64))
                vals = rng.integers(0, 1 << 62, n).astype(np.uint64)
                tree.put_batch(keys, vals)
                raw = vals.view(np.uint8).reshape(n, 8)
                for i in range(n):
                    model[bytes(keys[i])] = bytes(raw[i])
            elif roll < 0.75:
                n = int(rng.integers(1, 20))
                key_lo = rng.integers(0, key_space, n).astype(np.uint64)
                keys = pack_u128(key_lo, np.zeros(n, np.uint64))
                tree.remove_batch(keys)
                for i in range(n):
                    model.pop(bytes(keys[i]), None)
            elif roll < 0.85:
                tree.seal_memtable()
            else:
                tree.maybe_seal()

            if rng.random() < 0.15:
                # Full batch point-lookup diff.
                probe_lo = rng.integers(0, key_space, 32).astype(np.uint64)
                probe = pack_u128(probe_lo, np.zeros(32, np.uint64))
                found, values = tree.lookup_batch(probe)
                for i in range(len(probe)):
                    k = bytes(probe[i])
                    if k in model:
                        assert found[i], (seed, k)
                        assert bytes(values[i]) == model[k]
                    else:
                        assert not found[i], (seed, k)
        # Final scan over the whole key range matches the model.
        lo = pack_u128(np.zeros(1, np.uint64), np.zeros(1, np.uint64))[0]
        hi = pack_u128(
            np.full(1, ~np.uint64(0)), np.full(1, ~np.uint64(0))
        )[0]
        keys, values = tree.scan_range(bytes(lo), bytes(hi))
        got = {bytes(keys[i]): bytes(values[i]) for i in range(len(keys))}
        assert got == model, (seed, len(got), len(model))


# ---------------------------------------------------------------------------
# manifest log: event stream + compaction + replay vs a model
# (reference: src/lsm/manifest_log.zig fuzz).


def fuzz_manifest_log(seed: int, rounds: int) -> None:
    from tigerbeetle_tpu.lsm.manifest_log import ManifestLog
    from tigerbeetle_tpu.vsr.grid import Grid

    rng = np.random.default_rng(seed)
    for case in range(max(1, rounds // 40)):
        # Grid sized for the workload's compaction peak: live state
        # alone can reach ~350 blocks, and a compacting checkpoint
        # holds the old log blocks (still staged for release) plus the
        # fresh snapshot concurrently.
        storage = MemoryStorage(_layout(grid_size=1 << 24), seed=seed + case)
        grid = Grid(storage, block_size=4096, block_count=1 << 12)
        mlog = ManifestLog(grid)
        model: dict[tuple, list] = {}
        next_run = 0
        addresses: list[int] = []
        for _ in range(rounds):
            roll = rng.random()
            if roll < 0.5:
                tree_id = int(rng.integers(1, 4))
                level = int(rng.integers(0, 3))
                run_id = next_run
                next_run += 1
                blocks = [
                    (int(rng.integers(1, 1000)), int(rng.integers(1, 50)),
                     rng.bytes(16), rng.bytes(16))
                    for _ in range(int(rng.integers(1, 100)))
                ]
                mlog.run_add(tree_id, level, run_id, blocks)
                model[(tree_id, level, run_id)] = blocks
            elif roll < 0.7 and model:
                key = list(model)[int(rng.integers(len(model)))]
                mlog.run_remove(*key)
                del model[key]
            else:
                addresses = mlog.checkpoint()
                # The durable-checkpoint ack that makes staged block
                # releases reusable (production: forest.py:150 at the
                # freeze + the flip's release_quarantine).  Without it
                # every log compaction leaks its released blocks into
                # staging and long runs exhaust the grid.
                grid.free_set.checkpoint()
                grid.free_set.release_quarantine()
        addresses = mlog.checkpoint()
        tail = mlog.tail_bytes()
        replayed = ManifestLog(grid).open(addresses, tail)
        assert replayed == model, (seed, len(replayed), len(model))


FUZZERS = {
    "ewah": fuzz_ewah,
    "snapshot": fuzz_snapshot,
    "free_set": fuzz_free_set,
    "journal": fuzz_journal,
    "superblock": fuzz_superblock,
    "tree": fuzz_tree,
    "manifest_log": fuzz_manifest_log,
}

SMOKE_ROUNDS = 60


def main(argv: list[str]) -> int:
    if not argv or argv[0] in ("-h", "--help"):
        names = " | ".join(["smoke", "all", *FUZZERS])
        print(f"usage: python -m tigerbeetle_tpu.testing.fuzz "
              f"<{names}> [--seed N] [--rounds N]")
        return 2
    name = argv[0]
    seed = 42
    rounds = 400
    args = argv[1:]
    while args:
        if args[0] in ("--seed", "--rounds") and len(args) < 2:
            print(f"{args[0]} requires a value")
            return 2
        if args[0] == "--seed":
            seed = int(args[1])
        elif args[0] == "--rounds":
            rounds = int(args[1])
        else:
            print(f"unknown flag {args[0]}")
            return 2
        args = args[2:]
    if name == "smoke":
        targets, rounds = list(FUZZERS), SMOKE_ROUNDS
    elif name == "all":
        targets = list(FUZZERS)
    elif name in FUZZERS:
        targets = [name]
    else:
        print(f"unknown fuzzer {name!r}; have: {', '.join(FUZZERS)}")
        return 2
    for t in targets:
        FUZZERS[t](seed, rounds)
        print(f"fuzz {t}: ok (seed={seed} rounds={rounds})")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
