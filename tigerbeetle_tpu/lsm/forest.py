"""Forest: owns every groove's trees; open/compact/checkpoint.

reference: src/lsm/forest.zig:31,324,375,547 — the forest opens from
the manifest log, paces compaction, and checkpoints all trees plus the
free set.  Run/block metadata persists through the append-only,
self-compacting manifest LOG in grid blocks (lsm/manifest_log.py;
reference: src/lsm/manifest_log.zig): each checkpoint appends only the
run add/remove events since the last one, so checkpoint cost is
O(delta) even when the forest holds millions of blocks.  The checkpoint
blob carries just the log's block addresses, any unflushed tail
events, per-tree memtable batches, and the free set (snapshot codec —
no pickle anywhere in the durable path).
"""

from __future__ import annotations

import numpy as np

from tigerbeetle_tpu.lsm.groove import Groove
from tigerbeetle_tpu.lsm.manifest_log import ManifestLog
from tigerbeetle_tpu.utils import snapshot as snapcodec
from tigerbeetle_tpu.vsr.free_set import FreeSet
from tigerbeetle_tpu.vsr.grid import Grid
from tigerbeetle_tpu.vsr.storage import Storage


class Forest:
    def __init__(self, storage: Storage, *, block_size: int = 1 << 16,
                 block_count: int = 1 << 12, base_offset: int | None = None,
                 memtable_max: int = 8192,
                 cache_blocks: int | None = None) -> None:
        # The grid cache absorbs compaction's read-back of recently
        # written runs.  The file-backed default (4096 x 64KiB =
        # 256MiB) mirrors the reference's GiB-scale grid cache
        # (src/vsr/grid.zig cache sizing): on this container the OS
        # page cache is evicted under cgroup pressure, so grid preads
        # cost ~5ms of real disk latency without it (profiled: 8s of a
        # 4.1s-budget durable run went to pread).  Memory backends
        # (tests, fuzz clusters) keep a small cache — their reads are
        # already RAM copies, and dozens of in-process replicas must
        # not each pin 256MiB.
        if cache_blocks is None:
            cache_blocks = (
                4096
                if getattr(storage, "supports_async_writeback", False)
                else 256
            )
        self.grid = Grid(
            storage, block_size=block_size, block_count=block_count,
            base_offset=base_offset, cache_blocks=cache_blocks,
        )
        self.memtable_max = memtable_max
        self.grooves: dict[str, Groove] = {}
        self.mlog = ManifestLog(self.grid)
        # tree_id -> Tree, assigned in groove-creation order (stable
        # across restarts because grooves are re-declared identically
        # before open()).
        self._trees: list = []
        self._beat_cursor = 0

    def groove(self, name: str, *, object_size: int,
               index_fields: list[str], index_value_size: int = 1) -> Groove:
        assert name not in self.grooves
        g = Groove(
            self.grid, name, object_size=object_size,
            index_fields=index_fields, memtable_max=self.memtable_max,
            index_value_size=index_value_size,
        )
        self.grooves[name] = g
        for tree in (g.id_tree, g.object_tree, *g.indexes.values()):
            tree.tree_id = len(self._trees)
            tree.mlog = self.mlog
            self._trees.append(tree)
        return g

    def compact(self) -> None:
        for g in self.grooves.values():
            g.maybe_seal()

    def compact_beat(self, block_budget: int = 16) -> int:
        """One beat of paced compaction: advance pending merges by at
        most `block_budget` grid blocks across all trees, round-robin
        from where the last beat stopped (reference:
        src/lsm/forest.zig:846 CompactionPipeline beats).  Driven once
        per commit by the replica — commit-count pacing keeps replicas
        deterministic."""
        used = 0
        n = len(self._trees)
        for k in range(n):
            if used >= block_budget:
                break
            tree = self._trees[(self._beat_cursor + k) % n]
            used += tree.compact_beat(block_budget - used)
        self._beat_cursor = (self._beat_cursor + 1) % max(1, n)
        return used

    def compaction_pending(self) -> bool:
        return any(t.compaction_pending() for t in self._trees)

    def manifest_blob(self) -> bytes:
        """Pure snapshot: log addresses + unflushed tail + memtable
        batches + free set + in-flight merge outputs.  Mutates nothing
        (mid-interval snapshots and the convergence checkers call this
        between checkpoints).

        `orphans`: output blocks of merges still in flight.  The free
        set counts them allocated but no manifest entry references
        them; a restore releases them and the merge restarts from its
        (still-referenced) inputs — which is what lets checkpoints
        proceed WITHOUT draining compaction."""
        orphans = []
        for tree in self._trees:
            if tree._job is not None:
                orphans.extend(b.address for b in tree._job.out_blocks)
        return snapcodec.encode_tree(
            {
                "log_addrs": np.array(self.mlog.blocks, np.uint64),
                "log_tail": self.mlog.tail_bytes(),
                "memtables": {
                    str(t.tree_id): t.memtable_manifest()
                    for t in self._trees
                },
                "free_set": self.grid.free_set.encode(),
                "block_count": self.grid.block_count,
                "orphans": np.array(orphans, np.uint64),
            }
        )

    def checkpoint(self) -> bytes:
        """Seal all memtables (bounds the blob), finish any ACTIVE
        merge jobs, flush+compact the manifest log, release staged
        blocks, and return the checkpoint blob.

        Draining only the in-flight jobs — not every over-full level —
        keeps checkpoints deterministic cluster-wide (no job ever
        crosses a checkpoint, so blobs are state-functions; a crashed
        replica restoring the blob converges with one that kept
        running) while the latency stays bounded: an active job is at
        most one level merge, and the disjoint-range moves that
        dominate the big trees are metadata-only.  Remaining over-full
        levels start their merges in the next interval's beats."""
        for tree in self._trees:
            tree.seal_memtable()
            while tree._job is not None:
                tree.compact_beat(1 << 30)
        # Log flush acquires blocks BEFORE staged releases activate, so
        # blocks referenced by the previous superblock are never
        # overwritten inside this checkpoint's crash window.
        self.mlog.checkpoint()
        self.grid.free_set.checkpoint()
        return self.manifest_blob()

    def open(self, blob: bytes) -> None:
        # Cancel any in-flight merges from the pre-restore state: a
        # stale job would release blocks and log manifest events
        # against the RESTORED free set/manifest (double-free).  Its
        # partially-written output blocks are unreferenced in the
        # restored state and simply get reused.
        for tree in self._trees:
            tree._job = None
        self._beat_cursor = 0
        state = snapcodec.decode_tree(blob)
        self.grid.free_set = FreeSet.decode(
            state["free_set"], state["block_count"]
        )
        # Merge outputs that were in flight at checkpoint time: no
        # manifest entry references them — reclaim (staged; activates
        # at the next checkpoint, so re-crashing re-releases them
        # idempotently from the same blob).
        for addr in state.get("orphans", np.zeros(0, np.uint64)):
            self.grid.free_set.release(int(addr))
        runs = self.mlog.open(
            [int(a) for a in state["log_addrs"]], state["log_tail"]
        )
        per_tree: dict[int, dict] = {}
        for (tree_id, level, run_id), refs in runs.items():
            per_tree.setdefault(tree_id, {})[(level, run_id)] = refs
        memtables = state.get("memtables", {})
        for tree in self._trees:
            tree.restore_runs(per_tree.get(tree.tree_id, {}))
            tree.restore_memtable(memtables.get(str(tree.tree_id), {}))
