"""Forest: owns every groove's trees; open/compact/checkpoint.

reference: src/lsm/forest.zig:31,324,375,547 — the forest opens from
the manifest, paces compaction, and checkpoints all trees plus the
free set.  Manifests serialize through the fixed-layout snapshot codec
(utils/snapshot.py) into the replica's checkpoint blob (recovery
between checkpoints is WAL replay, so the blob is the durable boundary,
reference-equivalent at checkpoint granularity).
"""

from __future__ import annotations

from tigerbeetle_tpu.lsm.groove import Groove
from tigerbeetle_tpu.utils import snapshot as snapcodec
from tigerbeetle_tpu.vsr.free_set import FreeSet
from tigerbeetle_tpu.vsr.grid import Grid
from tigerbeetle_tpu.vsr.storage import Storage


class Forest:
    def __init__(self, storage: Storage, *, block_size: int = 1 << 16,
                 block_count: int = 1 << 12, base_offset: int | None = None,
                 memtable_max: int = 8192) -> None:
        self.grid = Grid(
            storage, block_size=block_size, block_count=block_count,
            base_offset=base_offset,
        )
        self.memtable_max = memtable_max
        self.grooves: dict[str, Groove] = {}

    def groove(self, name: str, *, object_size: int,
               index_fields: list[str], index_value_size: int = 1) -> Groove:
        assert name not in self.grooves
        g = Groove(
            self.grid, name, object_size=object_size,
            index_fields=index_fields, memtable_max=self.memtable_max,
            index_value_size=index_value_size,
        )
        self.grooves[name] = g
        return g

    def compact(self) -> None:
        for g in self.grooves.values():
            g.maybe_seal()

    def manifest_blob(self) -> bytes:
        """Pure snapshot of the forest's manifests + free set (includes
        unsealed memtable batches; mutates nothing)."""
        return snapcodec.encode_tree(
            {
                "grooves": {n: g.manifest() for n, g in self.grooves.items()},
                "free_set": self.grid.free_set.encode(),
                "block_count": self.grid.block_count,
            }
        )

    def checkpoint(self) -> bytes:
        """Seal all memtables, release staged blocks, and return the
        manifest+free-set blob for the superblock-referenced snapshot."""
        for g in self.grooves.values():
            g.id_tree.seal_memtable()
            g.object_tree.seal_memtable()
            for t in g.indexes.values():
                t.seal_memtable()
        self.grid.free_set.checkpoint()
        return self.manifest_blob()

    def open(self, blob: bytes) -> None:
        state = snapcodec.decode_tree(blob)
        self.grid.free_set = FreeSet.decode(
            state["free_set"], state["block_count"]
        )
        for name, manifest in state["grooves"].items():
            self.grooves[name].restore(manifest)
