"""Forest: owns every groove's trees; open/compact/checkpoint.

reference: src/lsm/forest.zig:31,324,375,547 — the forest opens from
the manifest log, paces compaction, and checkpoints all trees plus the
free set.  Run/block metadata persists through the append-only,
self-compacting manifest LOG in grid blocks (lsm/manifest_log.py;
reference: src/lsm/manifest_log.zig): each checkpoint appends only the
run add/remove events since the last one, so checkpoint cost is
O(delta) even when the forest holds millions of blocks.  The checkpoint
blob carries just the log's block addresses, any unflushed tail
events, per-tree memtable batches, and the free set (snapshot codec —
no pickle anywhere in the durable path).
"""

from __future__ import annotations

import numpy as np

from tigerbeetle_tpu.lsm.groove import Groove
from tigerbeetle_tpu.lsm.manifest_log import ManifestLog
from tigerbeetle_tpu.utils import snapshot as snapcodec
from tigerbeetle_tpu.vsr.free_set import FreeSet
from tigerbeetle_tpu.vsr.grid import Grid
from tigerbeetle_tpu.vsr.storage import Storage


class Forest:
    def __init__(self, storage: Storage, *, block_size: int = 1 << 16,
                 block_count: int = 1 << 12, base_offset: int | None = None,
                 memtable_max: int = 8192) -> None:
        self.grid = Grid(
            storage, block_size=block_size, block_count=block_count,
            base_offset=base_offset,
        )
        self.memtable_max = memtable_max
        self.grooves: dict[str, Groove] = {}
        self.mlog = ManifestLog(self.grid)
        # tree_id -> Tree, assigned in groove-creation order (stable
        # across restarts because grooves are re-declared identically
        # before open()).
        self._trees: list = []

    def groove(self, name: str, *, object_size: int,
               index_fields: list[str], index_value_size: int = 1) -> Groove:
        assert name not in self.grooves
        g = Groove(
            self.grid, name, object_size=object_size,
            index_fields=index_fields, memtable_max=self.memtable_max,
            index_value_size=index_value_size,
        )
        self.grooves[name] = g
        for tree in (g.id_tree, g.object_tree, *g.indexes.values()):
            tree.tree_id = len(self._trees)
            tree.mlog = self.mlog
            self._trees.append(tree)
        return g

    def compact(self) -> None:
        for g in self.grooves.values():
            g.maybe_seal()

    def manifest_blob(self) -> bytes:
        """Pure snapshot: log addresses + unflushed tail + memtable
        batches + free set.  Mutates nothing (mid-interval snapshots
        and the convergence checkers call this between checkpoints)."""
        return snapcodec.encode_tree(
            {
                "log_addrs": np.array(self.mlog.blocks, np.uint64),
                "log_tail": self.mlog.tail_bytes(),
                "memtables": {
                    str(t.tree_id): t.memtable_manifest()
                    for t in self._trees
                },
                "free_set": self.grid.free_set.encode(),
                "block_count": self.grid.block_count,
            }
        )

    def checkpoint(self) -> bytes:
        """Seal all memtables, flush+compact the manifest log, release
        staged blocks, and return the checkpoint blob."""
        for tree in self._trees:
            tree.seal_memtable()
        # Log flush acquires blocks BEFORE staged releases activate, so
        # blocks referenced by the previous superblock are never
        # overwritten inside this checkpoint's crash window.
        self.mlog.checkpoint()
        self.grid.free_set.checkpoint()
        return self.manifest_blob()

    def open(self, blob: bytes) -> None:
        state = snapcodec.decode_tree(blob)
        self.grid.free_set = FreeSet.decode(
            state["free_set"], state["block_count"]
        )
        runs = self.mlog.open(
            [int(a) for a in state["log_addrs"]], state["log_tail"]
        )
        per_tree: dict[int, dict] = {}
        for (tree_id, level, run_id), refs in runs.items():
            per_tree.setdefault(tree_id, {})[(level, run_id)] = refs
        memtables = state.get("memtables", {})
        for tree in self._trees:
            tree.restore_runs(per_tree.get(tree.tree_id, {}))
            tree.restore_memtable(memtables.get(str(tree.tree_id), {}))
