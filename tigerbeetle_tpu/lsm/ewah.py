"""EWAH word-aligned hybrid RLE bitset codec.

reference: src/ewah.zig:12-20 — used to compress the free set for
checkpoint persistence.  Encoding: a stream of (marker, literals)
pairs; the marker word packs {uniform_bit: 1, uniform_word_count: 31,
literal_word_count: 32} and is followed by that many literal 64-bit
words.  Vectorized numpy implementation (the reference is scalar Zig).
"""

from __future__ import annotations

import numpy as np


def encode(words: np.ndarray) -> bytes:
    """uint64 word array -> EWAH bytes."""
    words = np.asarray(words, np.uint64)
    out: list[int] = []
    i = 0
    n = len(words)
    ZERO, ONES = np.uint64(0), np.uint64(0xFFFFFFFFFFFFFFFF)
    while i < n:
        # Run of uniform words.
        bit = 1 if words[i] == ONES else 0 if words[i] == ZERO else None
        run = 0
        if bit is not None:
            uniform = ONES if bit else ZERO
            j = i
            while j < n and words[j] == uniform and run < (1 << 31) - 1:
                j += 1
                run += 1
            i = j
        # Literal words until the next uniform run (or end).
        lit_start = i
        while i < n and words[i] != ZERO and words[i] != ONES:
            i += 1
        lits = words[lit_start:i]
        marker = (
            np.uint64(bit or 0)
            | (np.uint64(run) << np.uint64(1))
            | (np.uint64(len(lits)) << np.uint64(32))
        )
        out.append(int(marker))
        out.extend(int(w) for w in lits)
    return np.asarray(out, np.uint64).tobytes()


def decode(data: bytes, word_count: int) -> np.ndarray:
    """EWAH bytes -> uint64 word array of `word_count` words."""
    stream = np.frombuffer(data, np.uint64)
    out = np.zeros(word_count, np.uint64)
    at = 0
    pos = 0
    ONES = np.uint64(0xFFFFFFFFFFFFFFFF)
    while at < len(stream):
        marker = int(stream[at])
        at += 1
        bit = marker & 1
        run = (marker >> 1) & 0x7FFFFFFF
        lit = marker >> 32
        if run:
            if bit:
                out[pos : pos + run] = ONES
            pos += run
        if lit:
            out[pos : pos + lit] = stream[at : at + lit]
            at += lit
            pos += lit
    assert pos == word_count, (pos, word_count)
    return out
