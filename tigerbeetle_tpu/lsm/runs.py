"""u128 key packing for order-preserving numpy sorts.

The reference orders LSM keys numerically (src/lsm/composite_key.zig);
on the host we pack u128 (lo, hi) limb pairs into 16-byte big-endian
void scalars so numpy's memcmp ordering equals numeric u128 ordering
(sort/searchsorted/unique work unchanged). The hot-path id directories
live in utils/hashindex.py; this packing serves the exact-scan path's
id grouping and future on-disk sorted runs.
"""

from __future__ import annotations

import numpy as np

KEY_DTYPE = np.dtype("V16")
_PACK_DTYPE = np.dtype([("hi", ">u8"), ("lo", ">u8")])


def pack_u128(lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    """(lo, hi) uint64 limb arrays -> V16 keys with numeric ordering."""
    s = np.empty(len(lo), dtype=_PACK_DTYPE)
    s["hi"] = hi
    s["lo"] = lo
    return s.view(KEY_DTYPE).reshape(-1)


def key_words(keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """V16 keys -> (word0, word1) native uint64, lexicographic order."""
    w = keys.view(">u8").astype(np.uint64).reshape(-1, 2)
    return w[:, 0], w[:, 1]


def keys_le(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Elementwise a <= b for V16 keys (void dtypes lack ordering
    ufuncs; sort/searchsorted still use memcmp order)."""
    a0, a1 = key_words(a)
    b0, b1 = key_words(b)
    return (a0 < b0) | ((a0 == b0) & (a1 <= b1))
