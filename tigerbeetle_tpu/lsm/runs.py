"""Sorted-run key directory: the host-side analog of the LSM id tree.

The reference maps ids to objects through per-groove LSM trees
(reference: src/lsm/groove.zig:136-176 — IdTree id->timestamp plus
ObjectTree). On the host we need the same mapping (u128 id -> row/slot)
with *vectorized* batch lookup so no per-event Python runs on the hot
path. The structure is deliberately LSM-shaped: each inserted batch is
one sorted run ("immutable memtable"), lookups binary-search every run
newest-first, and runs are merge-compacted once there are too many
(reference analog: src/lsm/compaction.zig level merging).

u128 keys are packed into 16-byte big-endian void scalars so numpy's
memcmp ordering equals numeric u128 ordering (sort/searchsorted work
unchanged).
"""

from __future__ import annotations

import numpy as np

KEY_DTYPE = np.dtype("V16")
_PACK_DTYPE = np.dtype([("hi", ">u8"), ("lo", ">u8")])


def pack_u128(lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    """(lo, hi) uint64 limb arrays -> V16 keys with numeric ordering."""
    s = np.empty(len(lo), dtype=_PACK_DTYPE)
    s["hi"] = hi
    s["lo"] = lo
    return s.view(KEY_DTYPE).reshape(-1)


class SortedRuns:
    """Append-only key -> uint64 value map with vectorized lookup."""

    def __init__(self, compact_at: int = 24) -> None:
        self._runs: list[tuple[np.ndarray, np.ndarray]] = []
        self._compact_at = compact_at
        self.count = 0

    def insert(self, keys: np.ndarray, values: np.ndarray) -> None:
        """Insert one batch (keys must not already exist)."""
        if len(keys) == 0:
            return
        order = np.argsort(keys, kind="stable")
        self._runs.append((keys[order], np.asarray(values, np.uint64)[order]))
        self.count += len(keys)
        if len(self._runs) >= self._compact_at:
            self._compact()

    def _compact(self) -> None:
        keys = np.concatenate([r[0] for r in self._runs])
        values = np.concatenate([r[1] for r in self._runs])
        order = np.argsort(keys, kind="stable")
        self._runs = [(keys[order], values[order])]

    def lookup(self, keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Vectorized get: returns (found bool array, values uint64).

        Newest run wins, though inserts of duplicate keys are illegal
        anyway (the state machine's exists-checks prevent them).
        """
        n = len(keys)
        found = np.zeros(n, dtype=bool)
        values = np.zeros(n, dtype=np.uint64)
        for run_keys, run_values in reversed(self._runs):
            remaining = ~found
            if not remaining.any():
                break
            probe = keys[remaining]
            pos = np.searchsorted(run_keys, probe)
            pos_clipped = np.minimum(pos, len(run_keys) - 1)
            hit = run_keys[pos_clipped] == probe
            idx = np.flatnonzero(remaining)[hit]
            found[idx] = True
            values[idx] = run_values[pos_clipped[hit]]
        return found, values

    def remove(self, keys: np.ndarray) -> None:
        """Delete keys (used only by scoped rollback of create_accounts)."""
        if len(keys) == 0:
            return
        keyset = set(keys.tobytes()[i * 16 : (i + 1) * 16] for i in range(len(keys)))
        new_runs = []
        for run_keys, run_values in self._runs:
            mask = np.array(
                [bytes(k) not in keyset for k in run_keys], dtype=bool
            )
            if mask.all():
                new_runs.append((run_keys, run_values))
            else:
                new_runs.append((run_keys[mask], run_values[mask]))
        self._runs = [r for r in new_runs if len(r[0])]
        self.count -= len(keys)
