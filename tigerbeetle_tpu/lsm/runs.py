"""u128 key packing for order-preserving numpy sorts.

The reference orders LSM keys numerically (src/lsm/composite_key.zig);
on the host we pack u128 (lo, hi) limb pairs into 16-byte big-endian
void scalars so numpy's memcmp ordering equals numeric u128 ordering
(sort/searchsorted/unique work unchanged). The hot-path id directories
live in utils/hashindex.py; this packing serves the exact-scan path's
id grouping and future on-disk sorted runs.
"""

from __future__ import annotations

import numpy as np

KEY_DTYPE = np.dtype("V16")
_PACK_DTYPE = np.dtype([("hi", ">u8"), ("lo", ">u8")])


def pack_u128(lo: np.ndarray, hi: np.ndarray) -> np.ndarray:
    """(lo, hi) uint64 limb arrays -> V16 keys with numeric ordering."""
    s = np.empty(len(lo), dtype=_PACK_DTYPE)
    s["hi"] = hi
    s["lo"] = lo
    return s.view(KEY_DTYPE).reshape(-1)
