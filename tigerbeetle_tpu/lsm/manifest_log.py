"""Append-only, self-compacting manifest log in grid blocks.

reference: src/lsm/manifest_log.zig:1-40 — instead of rewriting every
tree's table list at each checkpoint (O(total runs), which grows with
state), the forest appends only the run add/remove EVENTS since the
last checkpoint, and the log compacts itself (rewrites live state as
fresh snapshot events, releasing old blocks) once dead events
dominate.  The checkpoint blob then carries only the log's block
addresses: O(delta) per checkpoint.

Event wire format (little-endian), one record per run event:
    tree_id  u16
    op       u8   (1 = run_add, 2 = run_remove)
    level    u8
    run_id   u32  (tree-scoped, assigned by Tree in creation order)
    n_blocks u32  (run_add only; 0 for run_remove)
    then n_blocks x block refs:
        addr u64 | count u64 | key_min 16B | key_max 16B

Replay applies events in log order; runs within a level order by
run_id (creation order == newest-last, the merge priority the trees
rely on).
"""

from __future__ import annotations

import struct

import numpy as np

_EV_HEAD = struct.Struct("<HBBII")
_BLOCK_REF = struct.Struct("<QQ16s16s")

OP_ADD = 1
OP_REMOVE = 2
OP_ADD_CONT = 3  # continuation: extends the refs of a prior OP_ADD

class ManifestLog:
    def __init__(self, grid) -> None:
        self.grid = grid
        # A single event record must fit one grid-block payload (4-byte
        # record count + event head + refs); runs with more blocks
        # split into OP_ADD + OP_ADD_CONT records.
        self._refs_per_event = (
            grid.payload_size - 4 - _EV_HEAD.size
        ) // _BLOCK_REF.size
        assert self._refs_per_event >= 1, grid.payload_size
        # Closed log blocks (addresses, oldest first).
        self.blocks: list[int] = []
        # Open tail: encoded event records not yet written to a block.
        self._tail: list[bytes] = []
        # Live-state accounting for the compaction trigger.
        self._events_total = 0
        self._runs_live = 0

    # -- event intake (called by trees through the forest) --------------

    def run_add(self, tree_id: int, level: int, run_id: int, blocks) -> None:
        """blocks: iterable of (addr, count, key_min bytes, key_max)."""
        refs = [
            _BLOCK_REF.pack(addr, count, kmin, kmax)
            for addr, count, kmin, kmax in blocks
        ]
        for at in range(0, max(len(refs), 1), self._refs_per_event):
            chunk = refs[at : at + self._refs_per_event]
            op = OP_ADD if at == 0 else OP_ADD_CONT
            self._tail.append(
                _EV_HEAD.pack(tree_id, op, level, run_id, len(chunk))
                + b"".join(chunk)
            )
            self._events_total += 1
        self._runs_live += 1

    def run_remove(self, tree_id: int, level: int, run_id: int) -> None:
        self._tail.append(_EV_HEAD.pack(tree_id, OP_REMOVE, level, run_id, 0))
        self._events_total += 1
        self._runs_live -= 1

    # -- checkpoint ------------------------------------------------------

    def checkpoint(self) -> list[int]:
        """Flush tail events into grid blocks; compact the whole log
        when dead events outnumber live runs (evaluated BEFORE the
        flush, so a compacting checkpoint never writes the tail twice).
        Returns the block address list to persist in the blob."""
        if self._events_total > 2 * max(self._runs_live, 8):
            self._compact()
        else:
            self._flush_tail()
        return list(self.blocks)

    def _flush_tail(self) -> None:
        if not self._tail:
            return
        payload_max = self.grid.payload_size - 4
        chunks: list[list[bytes]] = [[]]
        size = 0
        for rec in self._tail:
            if size + len(rec) > payload_max:
                chunks.append([])
                size = 0
            chunks[-1].append(rec)
            size += len(rec)
        self._tail = []
        fs = self.grid.free_set
        reservation = fs.reserve(len(chunks))
        for recs in chunks:
            body = b"".join(recs)
            address = fs.acquire(reservation)
            self.grid.write_block(
                address, len(recs).to_bytes(4, "little") + body, block_type=2
            )
            self.blocks.append(address)
        fs.forfeit(reservation)

    def _compact(self) -> None:
        """Rewrite the live state (blocks + unflushed tail) as fresh
        snapshot events and release every old log block (reference:
        manifest_log.zig compacts its own blocks the same way)."""
        state = self._replay(include_tail=True)
        old = self.blocks
        self.blocks = []
        self._tail = []
        self._events_total = 0
        self._runs_live = 0
        for (tree_id, level, run_id), blocks in sorted(state.items()):
            self.run_add(tree_id, level, run_id, blocks)
        self._flush_tail()
        for address in old:
            self.grid.free_set.release(address)

    def tail_bytes(self) -> bytes:
        """Unflushed tail events, encoded like a block payload — the
        PURE mid-interval snapshot carries these alongside the block
        addresses (flushing would mutate the grid)."""
        return len(self._tail).to_bytes(4, "little") + b"".join(self._tail)

    # -- open ------------------------------------------------------------

    def open(self, addresses: list[int], tail: bytes = b"") -> dict:
        """Replay the log (+ an optional unflushed tail from a
        mid-interval snapshot) -> {(tree_id, level, run_id): [block
        refs]}, adopting addresses + tail as the current contents."""
        self.blocks = list(addresses)
        self._tail = []
        if len(tail) > 4:
            n = int.from_bytes(tail[:4], "little")
            at = 4
            for _ in range(n):
                head = _EV_HEAD.unpack_from(tail, at)
                size = _EV_HEAD.size + head[4] * _BLOCK_REF.size
                self._tail.append(tail[at : at + size])
                at += size
        state, n_events = self._replay(include_tail=True, count_events=True)
        self._events_total = n_events
        self._runs_live = len(state)
        return state

    def _replay(self, include_tail: bool = False, count_events: bool = False):
        state: dict = {}
        n_events = 0

        def apply(payload: bytes) -> None:
            nonlocal n_events
            n = int.from_bytes(payload[:4], "little")
            at = 4
            for _ in range(n):
                tree_id, op, level, run_id, n_blocks = _EV_HEAD.unpack_from(
                    payload, at
                )
                at += _EV_HEAD.size
                n_events += 1
                if op in (OP_ADD, OP_ADD_CONT):
                    refs = []
                    for _b in range(n_blocks):
                        addr, count, kmin, kmax = _BLOCK_REF.unpack_from(
                            payload, at
                        )
                        at += _BLOCK_REF.size
                        refs.append((addr, count, kmin, kmax))
                    key = (tree_id, level, run_id)
                    if op == OP_ADD:
                        state[key] = refs
                    else:
                        state[key].extend(refs)
                elif op == OP_REMOVE:
                    state.pop((tree_id, level, run_id), None)
                else:
                    raise ValueError(f"manifest log: unknown op {op}")

        for address in self.blocks:
            apply(self.grid.read_block(address))
        if include_tail:
            apply(self.tail_bytes())
        if count_events:
            return state, n_events
        return state
