"""LSM tree: memtable + leveled sorted runs in grid blocks.

reference: src/lsm/tree.zig:69-253 (mutable/immutable memtable + 7
on-disk levels, growth factor 8 — src/config.zig:156-157),
src/lsm/table.zig (sorted tables in grid blocks), compaction merging a
level into the next (src/lsm/compaction.zig:1-32).

Host-idiomatic re-design: runs are columnar numpy batches (V16 keys in
big-endian pack order so memcmp == numeric u128 order, fixed-size
values, tombstone flags), serialized one chunk per grid block with
per-block key fences for binary search.  All operations are batch
-vectorized (searchsorted over fences + block payloads) — there is no
per-key Python in lookups.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tigerbeetle_tpu.lsm.runs import KEY_DTYPE, keys_le, pack_u128
from tigerbeetle_tpu.vsr.grid import Grid

LEVELS = 7          # reference: src/config.zig lsm_levels
GROWTH = 8          # reference: src/config.zig lsm_growth_factor


def _entry_size(value_size: int) -> int:
    return 16 + 1 + value_size  # key + flags + value


# Sparse-value block encoding (write-amplification lever, VERDICT r4
# #5): values are split into 8-byte groups and only NONZERO groups are
# written, prefixed by a per-row u32 presence mask.  Wire objects are
# mostly-zero (reserved user_data, zeroed reconstructible fields, high
# u128 limbs), so this halves the dominant object-tree seal bytes; the
# worst case costs 4 bytes/row.  Block header bit 31 of the count word
# marks encoded payloads, so raw blocks (older files, non-sparse
# trees) keep parsing.
_SPARSE_FLAG = 0x8000_0000


def _entry_size_sparse(value_size: int) -> int:
    return 16 + 1 + 4 + value_size  # worst case: all groups nonzero


@dataclasses.dataclass
class RunBlock:
    address: int
    count: int
    key_min: bytes  # first key in block
    key_max: bytes  # last key in block


@dataclasses.dataclass
class Run:
    blocks: list[RunBlock]
    id: int = 0  # tree-scoped creation counter (manifest-log identity)

    @property
    def count(self) -> int:
        return sum(b.count for b in self.blocks)

    @property
    def key_min(self) -> bytes:
        return self.blocks[0].key_min

    @property
    def key_max(self) -> bytes:
        return self.blocks[-1].key_max


class Tree:
    def __init__(self, grid: Grid, name: str, *, value_size: int = 8,
                 memtable_max: int = 8192, sparse_values: bool = False) -> None:
        self.grid = grid
        self.name = name
        self.value_size = value_size
        self.value_dtype = np.dtype(f"V{value_size}")
        self.memtable_max = memtable_max
        self.sparse_values = sparse_values and value_size % 8 == 0
        if self.sparse_values:
            assert value_size // 8 <= 32, "sparse mask is u32 (32 groups)"
        # Manifest-log wiring (set by the forest): run add/remove
        # events append to the shared log instead of full-manifest
        # rewrites (reference: src/lsm/manifest_log.zig).
        self.tree_id = 0
        self.mlog = None
        self._next_run_id = 0
        # Memtable: list of individually-sorted columnar batches
        # (keys KEY_DTYPE, flags u8, values (n, value_size) u8), newest
        # LAST.  Vectorized throughout — one put_batch is one argsort,
        # no per-key Python (the spill path feeds 8k-row batches from
        # the commit hot path).
        self.memtable: list[tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        self.memtable_count = 0
        # levels[i] = runs, newest last.
        self.levels: list[list[Run]] = [[] for _ in range(LEVELS)]
        # At most one resumable merge in flight per tree.
        self._job: "CompactionJob | None" = None

    # ------------------------------------------------------------------
    # Writes.

    def _push_batch(self, keys: np.ndarray, flags: np.ndarray,
                    values: np.ndarray) -> None:
        if len(keys) == 0:
            return
        # Strictly-increasing input (spill streams keyed by row number
        # / timestamp) skips the sort AND the dedupe — void-dtype
        # argsort is the hot cost of the LSM ingest path.
        if len(keys) == 1 or not keys_le(keys[1:], keys[:-1]).any():
            self.memtable.append((keys, flags, values))
            self.memtable_count += len(keys)
            return
        # Stable sort + keep the LAST write per duplicate key within
        # the batch (dict-overwrite semantics).
        order = np.argsort(keys, kind="stable")
        keys = keys[order]
        flags = flags[order]
        values = values[order]
        keep = np.ones(len(keys), bool)
        keep[:-1] = keys[:-1] != keys[1:]
        if not keep.all():
            keys, flags, values = keys[keep], flags[keep], values[keep]
        self.memtable.append((keys, flags, values))
        self.memtable_count += len(keys)

    def put_batch(self, keys: np.ndarray, values: np.ndarray) -> None:
        values = np.ascontiguousarray(values).view(np.uint8).reshape(
            len(keys), -1
        )
        assert values.shape[1] == self.value_size, (
            f"{self.name}: value width {values.shape[1]} != "
            f"value_size {self.value_size}"
        )
        self._push_batch(
            np.asarray(keys, KEY_DTYPE), np.zeros(len(keys), np.uint8), values
        )

    def remove_batch(self, keys: np.ndarray) -> None:
        self._push_batch(
            np.asarray(keys, KEY_DTYPE),
            np.ones(len(keys), np.uint8),
            np.zeros((len(keys), self.value_size), np.uint8),
        )

    def put(self, key_hi: int, key_lo: int, value: bytes | int) -> None:
        key = pack_u128(
            np.array([key_lo], np.uint64), np.array([key_hi], np.uint64)
        )
        if isinstance(value, int):
            value = value.to_bytes(self.value_size, "little")
        self._push_batch(
            key, np.zeros(1, np.uint8),
            np.frombuffer(value, np.uint8).reshape(1, -1),
        )

    # ------------------------------------------------------------------
    # Reads.

    def lookup_batch(self, keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """-> (found bool[n], values (n, value_size) uint8).

        Newest wins: memtable, then level 0 runs newest-first, then
        deeper levels.  Tombstones report not-found.
        """
        n = len(keys)
        found = np.zeros(n, bool)
        resolved = np.zeros(n, bool)
        values = np.zeros((n, self.value_size), np.uint8)

        for bkeys, bflags, bvals in reversed(self.memtable):
            todo = np.flatnonzero(~resolved)
            if len(todo) == 0:
                break
            sub = keys[todo]
            pos = np.searchsorted(bkeys, sub)
            pos_c = np.minimum(pos, len(bkeys) - 1)
            hit = bkeys[pos_c] == sub
            hi = todo[hit]
            p = pos_c[hit]
            resolved[hi] = True
            live = bflags[p] == 0
            found[hi[live]] = True
            values[hi[live]] = bvals[p[live]]

        for run in self._runs_newest_first():
            todo = np.flatnonzero(~resolved)
            if len(todo) == 0:
                break
            self._run_lookup(run, keys, todo, found, resolved, values)
        return found, values

    def _runs_newest_first(self):
        for level in range(LEVELS):
            for run in reversed(self.levels[level]):
                yield run

    def _run_lookup(self, run: Run, keys, todo, found, resolved, values):
        fences = np.array([b.key_min for b in run.blocks], KEY_DTYPE)
        maxes = np.array([b.key_max for b in run.blocks], KEY_DTYPE)
        sub = keys[todo]
        # Candidate block per key: rightmost block whose min <= key.
        bi = np.searchsorted(fences, sub, side="right") - 1
        in_range = (bi >= 0) & keys_le(sub, maxes[np.clip(bi, 0, None)])
        for block_index in np.unique(bi[in_range]):
            mask = in_range & (bi == block_index)
            idx = todo[mask]
            bkeys, bflags, bvalues = self._read_run_block(
                run.blocks[block_index]
            )
            pos = np.searchsorted(bkeys, keys[idx])
            pos_c = np.minimum(pos, len(bkeys) - 1)
            hit = bkeys[pos_c] == keys[idx]
            hi = idx[hit]
            p = pos_c[hit]
            resolved[hi] = True
            live = bflags[p] == 0
            found[hi[live]] = True
            values[hi[live]] = bvalues[p[live]]

    def _read_run_block(self, block: RunBlock):
        payload = self.grid.read_block(block.address)
        word = int.from_bytes(payload[:4], "little")
        count = word & ~_SPARSE_FLAG
        at = 4
        keys = np.frombuffer(payload[at : at + 16 * count], KEY_DTYPE)
        at += 16 * count
        flags = np.frombuffer(payload[at : at + count], np.uint8)
        at += count
        if not word & _SPARSE_FLAG:
            vals = np.frombuffer(
                payload[at : at + count * self.value_size], np.uint8
            ).reshape(count, self.value_size)
            return keys, flags, vals
        g = self.value_size // 8
        bits = np.frombuffer(payload[at : at + 4 * count], "<u4")
        at += 4 * count
        mask = (bits[:, None] >> np.arange(g, dtype=np.uint32)) & 1
        mask = mask.astype(bool)
        nnz = int(mask.sum())
        v64 = np.zeros((count, g), "<u8")
        v64[mask] = np.frombuffer(payload[at : at + 8 * nnz], "<u8")
        return keys, flags, v64.view(np.uint8).reshape(count, self.value_size)

    # ------------------------------------------------------------------
    # Range scans (ascending).  Returns merged (keys, values), newest
    # wins, tombstones dropped.

    def scan_range(self, key_min: bytes, key_max: bytes) -> tuple[np.ndarray, np.ndarray]:
        streams = []
        kmin = np.frombuffer(key_min, KEY_DTYPE)
        kmax = np.frombuffer(key_max, KEY_DTYPE)
        for bkeys, bflags, bvals in reversed(self.memtable):
            lo = np.searchsorted(bkeys, kmin)[0]
            hi = np.searchsorted(bkeys, kmax, side="right")[0]
            if lo < hi:
                streams.append((bkeys[lo:hi], bflags[lo:hi], bvals[lo:hi]))
        for run in self._runs_newest_first():
            if run.key_max < key_min or run.key_min > key_max:
                continue
            parts = []
            for block in run.blocks:
                if block.key_max < key_min or block.key_min > key_max:
                    continue
                bkeys, bflags, bvals = self._read_run_block(block)
                lo = np.searchsorted(bkeys, np.array([key_min], KEY_DTYPE))[0]
                hi = np.searchsorted(
                    bkeys, np.array([key_max], KEY_DTYPE), side="right"
                )[0]
                parts.append((bkeys[lo:hi], bflags[lo:hi], bvals[lo:hi]))
            if parts:
                streams.append(
                    tuple(np.concatenate([p[j] for p in parts]) for j in range(3))
                )
        return k_way_merge(streams, self.value_size)

    # ------------------------------------------------------------------
    # Memtable seal + compaction.

    def maybe_seal(self) -> None:
        if self.memtable_count >= self.memtable_max:
            self.seal_memtable()

    def seal_memtable(self) -> None:
        """Seal the memtable into a level-0 run.  Compaction debt this
        creates is NOT paid here — beats (compact_beat) amortize it
        across commits, and compact_drain() settles the rest at
        checkpoint (reference: src/lsm/compaction.zig:1-32 paces the
        same debt across the beats of a bar)."""
        if not self.memtable:
            return
        # Newest batch first: k_way_merge keeps the newest version.
        keys, flags, vals = k_way_merge_flags(
            list(reversed(self.memtable)), self.value_size
        )
        self.memtable.clear()
        self.memtable_count = 0
        run = self._new_run(keys, flags, vals, level=0)
        self.levels[0].append(run)

    def _new_run(self, keys, flags, vals, *, level: int) -> Run:
        run = self._write_run(keys, flags, vals)
        run.id = self._next_run_id
        self._next_run_id += 1
        if self.mlog is not None:
            self.mlog.run_add(
                self.tree_id, level, run.id,
                [
                    (b.address, b.count, b.key_min, b.key_max)
                    for b in run.blocks
                ],
            )
        return run

    def _block_payload(self, k, f, v) -> bytes:
        if not self.sparse_values:
            return (
                len(k).to_bytes(4, "little")
                + k.tobytes() + f.tobytes() + v.tobytes()
            )
        n = len(k)
        g = self.value_size // 8
        v64 = np.ascontiguousarray(v).view("<u8").reshape(n, g)
        mask = v64 != 0
        bits = mask @ (np.uint32(1) << np.arange(g, dtype=np.uint32))
        return (
            (n | _SPARSE_FLAG).to_bytes(4, "little")
            + k.tobytes() + f.tobytes()
            + bits.astype("<u4").tobytes() + v64[mask].tobytes()
        )

    def _per_block(self) -> int:
        entry = (
            _entry_size_sparse(self.value_size)
            if self.sparse_values
            else _entry_size(self.value_size)
        )
        return (self.grid.payload_size - 4) // entry

    def _write_run(self, keys, flags, vals) -> Run:
        per_block = self._per_block()
        blocks = []
        fs = self.grid.free_set
        n = len(keys)
        n_blocks = (n + per_block - 1) // per_block
        reservation = fs.reserve(n_blocks)
        for at in range(0, n, per_block):
            k = keys[at : at + per_block]
            f = flags[at : at + per_block]
            v = vals[at : at + per_block]
            payload = self._block_payload(k, f, v)
            address = fs.acquire(reservation)
            self.grid.write_block(address, payload)
            blocks.append(
                RunBlock(
                    address=address, count=len(k),
                    key_min=k[0].tobytes(), key_max=k[-1].tobytes(),
                )
            )
        fs.forfeit(reservation)
        return Run(blocks=blocks)

    def _level_run_max(self, level: int) -> int:
        """Constant run cap per level IS the geometric invariant here:
        a level-L run is the merge of ~GROWTH level-(L-1) runs, so run
        SIZE grows by GROWTH per level and a cap of GROWTH runs gives
        each level ~GROWTH^L capacity (reference: src/config.zig
        lsm_growth_factor; table-count-based in the reference because
        its tables are fixed-size — ours are not)."""
        del level
        return GROWTH

    # -- paced compaction -------------------------------------------------
    #
    # A merge of level L into L+1 reads both levels and rewrites them —
    # done synchronously it is a latency cliff that grows with state.
    # Instead an over-full level opens a resumable CompactionJob that
    # advances a bounded number of grid blocks per beat; the replica
    # beats every commit and drains at checkpoint
    # (reference: src/lsm/compaction.zig:1-32, forest.zig:846
    # CompactionPipeline).

    def _over_full_level(self) -> int | None:
        for level in range(LEVELS - 1):
            if len(self.levels[level]) > self._level_run_max(level):
                return level
        return None

    def compaction_pending(self) -> bool:
        return self._job is not None or self._over_full_level() is not None

    def compact_beat(self, block_budget: int) -> int:
        """Advance compaction by at most `block_budget` grid blocks
        (read + written); returns blocks actually used.  Deterministic:
        driven by commit count, never wall clock, so replicas stay
        byte-identical."""
        used = 0
        while used < block_budget:
            if self._job is None:
                level = self._over_full_level()
                if level is None:
                    break
                self._job = CompactionJob(self, level)
            used += self._job.step(block_budget - used)
            if self._job.done:
                self._job = None
        return used

    def compact_drain(self) -> None:
        """Checkpoint barrier: settle every pending merge (the free
        set and manifest log must not reference half-built runs in a
        checkpoint)."""
        while self.compaction_pending():
            self.compact_beat(1 << 30)

    # Whole-batch compatibility shim (tests, standalone harnesses).
    def compact(self) -> None:
        self.compact_drain()

    def _read_run_all(self, run: Run):
        parts = [self._read_run_block(b) for b in run.blocks]
        return tuple(np.concatenate([p[j] for p in parts]) for j in range(3))

    def _write_one_block(self, keys, flags, vals) -> RunBlock:
        """Write a single run block (incremental output of a paced
        merge; _write_run covers the whole-run seal path)."""
        fs = self.grid.free_set
        reservation = fs.reserve(1)
        address = fs.acquire(reservation)
        fs.forfeit(reservation)
        payload = self._block_payload(keys, flags, vals)
        self.grid.write_block(address, payload)
        return RunBlock(
            address=address, count=len(keys),
            key_min=keys[0].tobytes(), key_max=keys[-1].tobytes(),
        )

    def _release_run(self, run: Run) -> None:
        for block in run.blocks:
            self.grid.free_set.release(block.address)

    # ------------------------------------------------------------------
    # Manifest (persisted inside the checkpoint blob).

    def memtable_manifest(self) -> dict:
        """Memtable batches only — run/block state lives in the
        manifest log (lsm/manifest_log.py), not here."""
        man = {}
        if self.memtable:
            man["mt_keys"] = np.concatenate([b[0] for b in self.memtable])
            man["mt_flags"] = np.concatenate([b[1] for b in self.memtable])
            man["mt_vals"] = np.concatenate([b[2] for b in self.memtable])
            man["mt_lens"] = np.array(
                [len(b[0]) for b in self.memtable], np.uint64
            )
        return man

    def restore_memtable(self, manifest: dict) -> None:
        self.memtable = []
        self.memtable_count = 0
        if "mt_lens" in manifest and len(manifest["mt_lens"]):
            keys = np.asarray(manifest["mt_keys"]).astype(KEY_DTYPE, copy=False)
            flags = np.asarray(manifest["mt_flags"])
            vals = np.asarray(manifest["mt_vals"])
            at = 0
            for n in manifest["mt_lens"]:
                n = int(n)
                self.memtable.append(
                    (keys[at : at + n], flags[at : at + n], vals[at : at + n])
                )
                at += n
            self.memtable_count = at

    def restore_runs(self, runs: dict) -> None:
        """runs: {(level, run_id): [(addr, count, kmin, kmax), ...]}
        from the manifest-log replay.  Run order within a level is
        run_id order (creation order == newest last)."""
        self.levels = [[] for _ in range(LEVELS)]
        next_id = 0
        for (level, run_id), refs in sorted(runs.items(), key=lambda kv: (kv[0][0], kv[0][1])):
            blocks = [
                RunBlock(
                    address=int(addr), count=int(count),
                    key_min=bytes(kmin), key_max=bytes(kmax),
                )
                for addr, count, kmin, kmax in refs
            ]
            self.levels[level].append(Run(blocks=blocks, id=run_id))
            next_id = max(next_id, run_id + 1)
        self._next_run_id = next_id


class _JobInput:
    """Cursor over one input run's blocks (newest-precedence order is
    the inputs list order, not anything here)."""

    __slots__ = ("run", "block", "keys", "flags", "vals", "offset")

    def __init__(self, run: Run) -> None:
        self.run = run
        self.block = 0
        self.keys = None
        self.flags = None
        self.vals = None
        self.offset = 0

    @property
    def exhausted(self) -> bool:
        return self.keys is None and self.block >= len(self.run.blocks)


class CompactionJob:
    """Resumable merge of level L (+ level L+1) into one level-(L+1)
    run, advanced a bounded number of blocks at a time.

    Visibility: input runs stay in `tree.levels` (reads keep working)
    until the final step, which atomically swaps them for the output
    run and records the change in the manifest log.  A crash mid-job
    loses only unreferenced output blocks — the last checkpoint's free
    set never saw them (checkpoints drain jobs first).

    Chunk correctness: each step merges all entries with key <= bound,
    where bound = min over loaded blocks of that block's key_max.  Any
    entry <= bound must live in its input's CURRENT block (later
    blocks start above their predecessor's key_max >= bound), so
    newest-wins dedupe within the chunk is globally correct.
    """

    def __init__(self, tree: Tree, level: int) -> None:
        self.tree = tree
        self.level = level
        # Snapshot the input run lists: new seals arriving at level 0
        # during the job are NOT part of it.
        self.inputs_a = list(tree.levels[level])
        self.inputs_b = list(tree.levels[level + 1])
        # Newest first across both levels for merge precedence.
        self.inputs = [
            _JobInput(r) for r in reversed(self.inputs_a + self.inputs_b)
        ]
        self.drop_tombstones = level + 1 == LEVELS - 1 or not any(
            tree.levels[i] for i in range(level + 2, LEVELS)
        )
        self.out_blocks: list[RunBlock] = []
        self._buf: list[tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        self._buf_count = 0
        self.done = False

    def _try_move(self) -> bool:
        """Move optimization (reference: src/lsm/compaction.zig
        disjoint-table move): when the input runs cover pairwise
        disjoint key ranges — the common case for trees keyed by
        monotonically increasing values, like the spill object trees'
        row numbers — the merge is pure metadata: the SAME grid blocks
        re-file as one level-(L+1) run, no reads, no rewrites.

        Only the level-L runs move; level L+1 keeps its runs untouched
        (disjointness makes cross-level shadowing impossible).  That
        keeps each move's manifest event O(level-L blocks): re-listing
        an ever-growing merged L+1 run every move would be O(total
        state) metadata per beat — the superlinear drag this bounds."""
        runs = self.inputs_a + self.inputs_b
        ordered = sorted(runs, key=lambda r: r.key_min)
        for prev, cur in zip(ordered, ordered[1:]):
            if not prev.key_max < cur.key_min:
                return False
        tree = self.tree
        level = self.level
        moved = sorted(self.inputs_a, key=lambda r: r.key_min)
        if tree.mlog is not None:
            for run in self.inputs_a:
                tree.mlog.run_remove(tree.tree_id, level, run.id)
        drop = set(id(r) for r in self.inputs_a)
        tree.levels[level] = [
            r for r in tree.levels[level] if id(r) not in drop
        ]
        out = Run(blocks=[b for r in moved for b in r.blocks])
        out.id = tree._next_run_id
        tree._next_run_id += 1
        if tree.mlog is not None:
            tree.mlog.run_add(
                tree.tree_id, level + 1, out.id,
                [
                    (b.address, b.count, b.key_min, b.key_max)
                    for b in out.blocks
                ],
            )
        tree.levels[level + 1].append(out)
        self.done = True
        return True

    def step(self, block_budget: int) -> int:
        if not self.done and not self.out_blocks and not self._buf:
            # First step: a disjoint input set moves instead of merging.
            if self._try_move():
                return 0
        tree = self.tree
        per_block = tree._per_block()
        used = 0
        while used < block_budget and not self.done:
            # Load the current block of every non-exhausted input.
            loaded = []
            for inp in self.inputs:
                if inp.keys is None and inp.block < len(inp.run.blocks):
                    if used >= block_budget:
                        return used
                    inp.keys, inp.flags, inp.vals = tree._read_run_block(
                        inp.run.blocks[inp.block]
                    )
                    inp.offset = 0
                    used += 1
                if inp.keys is not None:
                    loaded.append(inp)
            if not loaded:
                used += self._finalize(per_block)
                return used
            # bytes comparison == key order (big-endian pack).
            bound = np.frombuffer(
                min(inp.keys[-1].tobytes() for inp in loaded), KEY_DTYPE
            )
            chunk = []
            for inp in loaded:
                hi = int(
                    np.searchsorted(
                        inp.keys[inp.offset :], bound, side="right"
                    )[0]
                ) + inp.offset
                if hi > inp.offset:
                    chunk.append(
                        (
                            inp.keys[inp.offset : hi],
                            inp.flags[inp.offset : hi],
                            inp.vals[inp.offset : hi],
                        )
                    )
                inp.offset = hi
                if inp.offset == len(inp.keys):
                    inp.keys = inp.flags = inp.vals = None
                    inp.block += 1
            keys, flags, vals = k_way_merge_flags(chunk, tree.value_size)
            if self.drop_tombstones:
                live = flags == 0
                keys, flags, vals = keys[live], flags[live], vals[live]
            if len(keys):
                self._buf.append((keys, flags, vals))
                self._buf_count += len(keys)
            while self._buf_count >= per_block and used < block_budget:
                used += self._flush_block(per_block)
        return used

    def _pop_buffered(self, count: int):
        keys = np.concatenate([b[0] for b in self._buf])
        flags = np.concatenate([b[1] for b in self._buf])
        vals = np.concatenate([b[2] for b in self._buf])
        take = (keys[:count], flags[:count], vals[:count])
        rest = keys[count:], flags[count:], vals[count:]
        self._buf = [rest] if len(rest[0]) else []
        self._buf_count = len(rest[0])
        return take

    def _flush_block(self, per_block: int) -> int:
        keys, flags, vals = self._pop_buffered(per_block)
        self.out_blocks.append(self.tree._write_one_block(keys, flags, vals))
        return 1

    def _finalize(self, per_block: int) -> int:
        used = 0
        while self._buf_count:
            used += self._flush_block(per_block)
        tree = self.tree
        level = self.level
        if tree.mlog is not None:
            for lvl, runs in ((level, self.inputs_a), (level + 1, self.inputs_b)):
                for run in runs:
                    tree.mlog.run_remove(tree.tree_id, lvl, run.id)
        for run in self.inputs_a + self.inputs_b:
            tree._release_run(run)
        # New seals may have landed at `level` during the job: keep them.
        drop = set(id(r) for r in self.inputs_a + self.inputs_b)
        tree.levels[level] = [
            r for r in tree.levels[level] if id(r) not in drop
        ]
        survivors = [
            r for r in tree.levels[level + 1] if id(r) not in drop
        ]
        if self.out_blocks:
            out = Run(blocks=self.out_blocks)
            out.id = tree._next_run_id
            tree._next_run_id += 1
            if tree.mlog is not None:
                tree.mlog.run_add(
                    tree.tree_id, level + 1, out.id,
                    [
                        (b.address, b.count, b.key_min, b.key_max)
                        for b in out.blocks
                    ],
                )
            tree.levels[level + 1] = [out] + survivors
        else:
            tree.levels[level + 1] = survivors
        self.done = True
        return used


# ----------------------------------------------------------------------
# Merges (reference: src/lsm/k_way_merge.zig, zig_zag_merge.zig).


def k_way_merge_flags(streams, value_size: int):
    """Merge (keys, flags, values) streams, NEWEST FIRST: the first
    stream containing a key wins.  Returns sorted unique arrays with
    tombstones retained.  Inputs are individually sorted+unique (run
    blocks and memtable batches are, by construction), which enables
    two fast paths: a single stream passes through, and streams with
    pairwise-disjoint key ranges concatenate without sorting."""
    streams = [s for s in streams if len(s[0])]
    if not streams:
        return (
            np.zeros(0, KEY_DTYPE), np.zeros(0, np.uint8),
            np.zeros((0, value_size), np.uint8),
        )
    if len(streams) == 1:
        return streams[0]
    ordered = sorted(streams, key=lambda s: s[0][0].tobytes())
    if all(
        ordered[i][0][-1].tobytes() < ordered[i + 1][0][0].tobytes()
        for i in range(len(ordered) - 1)
    ):
        return tuple(
            np.concatenate([s[j] for s in ordered]) for j in range(3)
        )
    # Native streaming merge (native/tb_lsm.inc): the streams are
    # already sorted, so C++ merges in O(n*k) 16-byte compares — far
    # cheaper than the void-dtype argsort over the concatenation the
    # numpy fallback below pays.
    from tigerbeetle_tpu.runtime import fastpath

    merged = fastpath.kway_merge(streams, value_size)
    if merged is not None:
        return merged
    keys = np.concatenate([s[0] for s in streams])
    flags = np.concatenate([s[1] for s in streams])
    vals = np.concatenate([s[2] for s in streams])
    order = np.argsort(keys, kind="stable")  # stable: newer first per key
    keys, flags, vals = keys[order], flags[order], vals[order]
    first = np.ones(len(keys), bool)
    first[1:] = keys[1:] != keys[:-1]
    return keys[first], flags[first], vals[first]


def k_way_merge(streams, value_size: int):
    """As k_way_merge_flags but tombstones dropped (query surface)."""
    keys, flags, vals = k_way_merge_flags(streams, value_size)
    live = flags == 0
    return keys[live], vals[live]


def zig_zag_intersect(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Sorted-key intersection (reference: src/lsm/zig_zag_merge.zig —
    vectorized equivalent of the leapfrog merge)."""
    return np.intersect1d(a.view(KEY_DTYPE), b.view(KEY_DTYPE))
