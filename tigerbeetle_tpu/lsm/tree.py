"""LSM tree: memtable + leveled sorted runs in grid blocks.

reference: src/lsm/tree.zig:69-253 (mutable/immutable memtable + 7
on-disk levels, growth factor 8 — src/config.zig:156-157),
src/lsm/table.zig (sorted tables in grid blocks), compaction merging a
level into the next (src/lsm/compaction.zig:1-32).

Host-idiomatic re-design: runs are columnar numpy batches (V16 keys in
big-endian pack order so memcmp == numeric u128 order, fixed-size
values, tombstone flags), serialized one chunk per grid block with
per-block key fences for binary search.  All operations are batch
-vectorized (searchsorted over fences + block payloads) — there is no
per-key Python in lookups.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tigerbeetle_tpu.lsm.runs import KEY_DTYPE, keys_le, pack_u128
from tigerbeetle_tpu.vsr.grid import Grid

LEVELS = 7          # reference: src/config.zig lsm_levels
GROWTH = 8          # reference: src/config.zig lsm_growth_factor


def _entry_size(value_size: int) -> int:
    return 16 + 1 + value_size  # key + flags + value


@dataclasses.dataclass
class RunBlock:
    address: int
    count: int
    key_min: bytes  # first key in block
    key_max: bytes  # last key in block


@dataclasses.dataclass
class Run:
    blocks: list[RunBlock]
    id: int = 0  # tree-scoped creation counter (manifest-log identity)

    @property
    def count(self) -> int:
        return sum(b.count for b in self.blocks)

    @property
    def key_min(self) -> bytes:
        return self.blocks[0].key_min

    @property
    def key_max(self) -> bytes:
        return self.blocks[-1].key_max


class Tree:
    def __init__(self, grid: Grid, name: str, *, value_size: int = 8,
                 memtable_max: int = 8192) -> None:
        self.grid = grid
        self.name = name
        self.value_size = value_size
        self.value_dtype = np.dtype(f"V{value_size}")
        self.memtable_max = memtable_max
        # Manifest-log wiring (set by the forest): run add/remove
        # events append to the shared log instead of full-manifest
        # rewrites (reference: src/lsm/manifest_log.zig).
        self.tree_id = 0
        self.mlog = None
        self._next_run_id = 0
        # Memtable: list of individually-sorted columnar batches
        # (keys KEY_DTYPE, flags u8, values (n, value_size) u8), newest
        # LAST.  Vectorized throughout — one put_batch is one argsort,
        # no per-key Python (the spill path feeds 8k-row batches from
        # the commit hot path).
        self.memtable: list[tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        self.memtable_count = 0
        # levels[i] = runs, newest last.
        self.levels: list[list[Run]] = [[] for _ in range(LEVELS)]

    # ------------------------------------------------------------------
    # Writes.

    def _push_batch(self, keys: np.ndarray, flags: np.ndarray,
                    values: np.ndarray) -> None:
        if len(keys) == 0:
            return
        # Stable sort + keep the LAST write per duplicate key within
        # the batch (dict-overwrite semantics).
        order = np.argsort(keys, kind="stable")
        keys = keys[order]
        flags = flags[order]
        values = values[order]
        keep = np.ones(len(keys), bool)
        keep[:-1] = keys[:-1] != keys[1:]
        if not keep.all():
            keys, flags, values = keys[keep], flags[keep], values[keep]
        self.memtable.append((keys, flags, values))
        self.memtable_count += len(keys)

    def put_batch(self, keys: np.ndarray, values: np.ndarray) -> None:
        values = np.ascontiguousarray(values).view(np.uint8).reshape(
            len(keys), -1
        )
        assert values.shape[1] == self.value_size, (
            f"{self.name}: value width {values.shape[1]} != "
            f"value_size {self.value_size}"
        )
        self._push_batch(
            np.asarray(keys, KEY_DTYPE), np.zeros(len(keys), np.uint8), values
        )

    def remove_batch(self, keys: np.ndarray) -> None:
        self._push_batch(
            np.asarray(keys, KEY_DTYPE),
            np.ones(len(keys), np.uint8),
            np.zeros((len(keys), self.value_size), np.uint8),
        )

    def put(self, key_hi: int, key_lo: int, value: bytes | int) -> None:
        key = pack_u128(
            np.array([key_lo], np.uint64), np.array([key_hi], np.uint64)
        )
        if isinstance(value, int):
            value = value.to_bytes(self.value_size, "little")
        self._push_batch(
            key, np.zeros(1, np.uint8),
            np.frombuffer(value, np.uint8).reshape(1, -1),
        )

    # ------------------------------------------------------------------
    # Reads.

    def lookup_batch(self, keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """-> (found bool[n], values (n, value_size) uint8).

        Newest wins: memtable, then level 0 runs newest-first, then
        deeper levels.  Tombstones report not-found.
        """
        n = len(keys)
        found = np.zeros(n, bool)
        resolved = np.zeros(n, bool)
        values = np.zeros((n, self.value_size), np.uint8)

        for bkeys, bflags, bvals in reversed(self.memtable):
            todo = np.flatnonzero(~resolved)
            if len(todo) == 0:
                break
            sub = keys[todo]
            pos = np.searchsorted(bkeys, sub)
            pos_c = np.minimum(pos, len(bkeys) - 1)
            hit = bkeys[pos_c] == sub
            hi = todo[hit]
            p = pos_c[hit]
            resolved[hi] = True
            live = bflags[p] == 0
            found[hi[live]] = True
            values[hi[live]] = bvals[p[live]]

        for run in self._runs_newest_first():
            todo = np.flatnonzero(~resolved)
            if len(todo) == 0:
                break
            self._run_lookup(run, keys, todo, found, resolved, values)
        return found, values

    def _runs_newest_first(self):
        for level in range(LEVELS):
            for run in reversed(self.levels[level]):
                yield run

    def _run_lookup(self, run: Run, keys, todo, found, resolved, values):
        fences = np.array([b.key_min for b in run.blocks], KEY_DTYPE)
        maxes = np.array([b.key_max for b in run.blocks], KEY_DTYPE)
        sub = keys[todo]
        # Candidate block per key: rightmost block whose min <= key.
        bi = np.searchsorted(fences, sub, side="right") - 1
        in_range = (bi >= 0) & keys_le(sub, maxes[np.clip(bi, 0, None)])
        for block_index in np.unique(bi[in_range]):
            mask = in_range & (bi == block_index)
            idx = todo[mask]
            bkeys, bflags, bvalues = self._read_run_block(
                run.blocks[block_index]
            )
            pos = np.searchsorted(bkeys, keys[idx])
            pos_c = np.minimum(pos, len(bkeys) - 1)
            hit = bkeys[pos_c] == keys[idx]
            hi = idx[hit]
            p = pos_c[hit]
            resolved[hi] = True
            live = bflags[p] == 0
            found[hi[live]] = True
            values[hi[live]] = bvalues[p[live]]

    def _read_run_block(self, block: RunBlock):
        payload = self.grid.read_block(block.address)
        count = int.from_bytes(payload[:4], "little")
        at = 4
        keys = np.frombuffer(payload[at : at + 16 * count], KEY_DTYPE)
        at += 16 * count
        flags = np.frombuffer(payload[at : at + count], np.uint8)
        at += count
        vals = np.frombuffer(
            payload[at : at + count * self.value_size], np.uint8
        ).reshape(count, self.value_size)
        return keys, flags, vals

    # ------------------------------------------------------------------
    # Range scans (ascending).  Returns merged (keys, values), newest
    # wins, tombstones dropped.

    def scan_range(self, key_min: bytes, key_max: bytes) -> tuple[np.ndarray, np.ndarray]:
        streams = []
        kmin = np.frombuffer(key_min, KEY_DTYPE)
        kmax = np.frombuffer(key_max, KEY_DTYPE)
        for bkeys, bflags, bvals in reversed(self.memtable):
            lo = np.searchsorted(bkeys, kmin)[0]
            hi = np.searchsorted(bkeys, kmax, side="right")[0]
            if lo < hi:
                streams.append((bkeys[lo:hi], bflags[lo:hi], bvals[lo:hi]))
        for run in self._runs_newest_first():
            if run.key_max < key_min or run.key_min > key_max:
                continue
            parts = []
            for block in run.blocks:
                if block.key_max < key_min or block.key_min > key_max:
                    continue
                bkeys, bflags, bvals = self._read_run_block(block)
                lo = np.searchsorted(bkeys, np.array([key_min], KEY_DTYPE))[0]
                hi = np.searchsorted(
                    bkeys, np.array([key_max], KEY_DTYPE), side="right"
                )[0]
                parts.append((bkeys[lo:hi], bflags[lo:hi], bvals[lo:hi]))
            if parts:
                streams.append(
                    tuple(np.concatenate([p[j] for p in parts]) for j in range(3))
                )
        return k_way_merge(streams, self.value_size)

    # ------------------------------------------------------------------
    # Memtable seal + compaction.

    def maybe_seal(self) -> None:
        if self.memtable_count >= self.memtable_max:
            self.seal_memtable()

    def seal_memtable(self) -> None:
        if not self.memtable:
            return
        # Newest batch first: k_way_merge keeps the newest version.
        keys, flags, vals = k_way_merge_flags(
            list(reversed(self.memtable)), self.value_size
        )
        self.memtable.clear()
        self.memtable_count = 0
        run = self._new_run(keys, flags, vals, level=0)
        self.levels[0].append(run)
        self.compact()

    def _new_run(self, keys, flags, vals, *, level: int) -> Run:
        run = self._write_run(keys, flags, vals)
        run.id = self._next_run_id
        self._next_run_id += 1
        if self.mlog is not None:
            self.mlog.run_add(
                self.tree_id, level, run.id,
                [
                    (b.address, b.count, b.key_min, b.key_max)
                    for b in run.blocks
                ],
            )
        return run

    def _write_run(self, keys, flags, vals) -> Run:
        per_block = (self.grid.payload_size - 4) // _entry_size(self.value_size)
        blocks = []
        fs = self.grid.free_set
        n = len(keys)
        n_blocks = (n + per_block - 1) // per_block
        reservation = fs.reserve(n_blocks)
        for at in range(0, n, per_block):
            k = keys[at : at + per_block]
            f = flags[at : at + per_block]
            v = vals[at : at + per_block]
            payload = (
                len(k).to_bytes(4, "little")
                + k.tobytes() + f.tobytes() + v.tobytes()
            )
            address = fs.acquire(reservation)
            self.grid.write_block(address, payload)
            blocks.append(
                RunBlock(
                    address=address, count=len(k),
                    key_min=k[0].tobytes(), key_max=k[-1].tobytes(),
                )
            )
        fs.forfeit(reservation)
        return Run(blocks=blocks)

    def _level_run_max(self, level: int) -> int:
        return GROWTH if level == 0 else GROWTH

    def compact(self) -> None:
        """Merge any over-full level into the next (whole-level merge;
        the reference merges table-by-table per beat — pacing is a
        throughput refinement, the shape invariant is the same)."""
        for level in range(LEVELS - 1):
            if len(self.levels[level]) <= self._level_run_max(level):
                continue
            merged_streams = []
            # Newest first so k_way_merge keeps the newest version.
            for run in reversed(self.levels[level]):
                merged_streams.append(self._read_run_all(run))
            for run in reversed(self.levels[level + 1]):
                merged_streams.append(self._read_run_all(run))
            drop_tombstones = level + 1 == LEVELS - 1 or not any(
                self.levels[i] for i in range(level + 2, LEVELS)
            )
            keys, flags, vals = k_way_merge_flags(
                merged_streams, self.value_size
            )
            if drop_tombstones:
                live = flags == 0
                keys, flags, vals = keys[live], flags[live], vals[live]
            if self.mlog is not None:
                for lvl in (level, level + 1):
                    for run in self.levels[lvl]:
                        self.mlog.run_remove(self.tree_id, lvl, run.id)
            for run in self.levels[level] + self.levels[level + 1]:
                self._release_run(run)
            self.levels[level] = []
            self.levels[level + 1] = (
                [self._new_run(keys, flags, vals, level=level + 1)]
                if len(keys)
                else []
            )

    def _read_run_all(self, run: Run):
        parts = [self._read_run_block(b) for b in run.blocks]
        return tuple(np.concatenate([p[j] for p in parts]) for j in range(3))

    def _release_run(self, run: Run) -> None:
        for block in run.blocks:
            self.grid.free_set.release(block.address)

    # ------------------------------------------------------------------
    # Manifest (persisted inside the checkpoint blob).

    def memtable_manifest(self) -> dict:
        """Memtable batches only — run/block state lives in the
        manifest log (lsm/manifest_log.py), not here."""
        man = {}
        if self.memtable:
            man["mt_keys"] = np.concatenate([b[0] for b in self.memtable])
            man["mt_flags"] = np.concatenate([b[1] for b in self.memtable])
            man["mt_vals"] = np.concatenate([b[2] for b in self.memtable])
            man["mt_lens"] = np.array(
                [len(b[0]) for b in self.memtable], np.uint64
            )
        return man

    def restore_memtable(self, manifest: dict) -> None:
        self.memtable = []
        self.memtable_count = 0
        if "mt_lens" in manifest and len(manifest["mt_lens"]):
            keys = np.asarray(manifest["mt_keys"]).astype(KEY_DTYPE, copy=False)
            flags = np.asarray(manifest["mt_flags"])
            vals = np.asarray(manifest["mt_vals"])
            at = 0
            for n in manifest["mt_lens"]:
                n = int(n)
                self.memtable.append(
                    (keys[at : at + n], flags[at : at + n], vals[at : at + n])
                )
                at += n
            self.memtable_count = at

    def restore_runs(self, runs: dict) -> None:
        """runs: {(level, run_id): [(addr, count, kmin, kmax), ...]}
        from the manifest-log replay.  Run order within a level is
        run_id order (creation order == newest last)."""
        self.levels = [[] for _ in range(LEVELS)]
        next_id = 0
        for (level, run_id), refs in sorted(runs.items(), key=lambda kv: (kv[0][0], kv[0][1])):
            blocks = [
                RunBlock(
                    address=int(addr), count=int(count),
                    key_min=bytes(kmin), key_max=bytes(kmax),
                )
                for addr, count, kmin, kmax in refs
            ]
            self.levels[level].append(Run(blocks=blocks, id=run_id))
            next_id = max(next_id, run_id + 1)
        self._next_run_id = next_id


# ----------------------------------------------------------------------
# Merges (reference: src/lsm/k_way_merge.zig, zig_zag_merge.zig).


def k_way_merge_flags(streams, value_size: int):
    """Merge (keys, flags, values) streams, NEWEST FIRST: the first
    stream containing a key wins.  Returns sorted unique arrays with
    tombstones retained."""
    if not streams:
        return (
            np.zeros(0, KEY_DTYPE), np.zeros(0, np.uint8),
            np.zeros((0, value_size), np.uint8),
        )
    keys = np.concatenate([s[0] for s in streams])
    flags = np.concatenate([s[1] for s in streams])
    vals = np.concatenate([s[2] for s in streams])
    order = np.argsort(keys, kind="stable")  # stable: newer first per key
    keys, flags, vals = keys[order], flags[order], vals[order]
    first = np.ones(len(keys), bool)
    first[1:] = keys[1:] != keys[:-1]
    return keys[first], flags[first], vals[first]


def k_way_merge(streams, value_size: int):
    """As k_way_merge_flags but tombstones dropped (query surface)."""
    keys, flags, vals = k_way_merge_flags(streams, value_size)
    live = flags == 0
    return keys[live], vals[live]


def zig_zag_intersect(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Sorted-key intersection (reference: src/lsm/zig_zag_merge.zig —
    vectorized equivalent of the leapfrog merge)."""
    return np.intersect1d(a.view(KEY_DTYPE), b.view(KEY_DTYPE))
