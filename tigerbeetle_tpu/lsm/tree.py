"""LSM tree: memtable + leveled sorted runs in grid blocks.

reference: src/lsm/tree.zig:69-253 (mutable/immutable memtable + 7
on-disk levels, growth factor 8 — src/config.zig:156-157),
src/lsm/table.zig (sorted tables in grid blocks), compaction merging a
level into the next (src/lsm/compaction.zig:1-32).

Host-idiomatic re-design: runs are columnar numpy batches (V16 keys in
big-endian pack order so memcmp == numeric u128 order, fixed-size
values, tombstone flags), serialized one chunk per grid block with
per-block key fences for binary search.  All operations are batch
-vectorized (searchsorted over fences + block payloads) — there is no
per-key Python in lookups.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from tigerbeetle_tpu.lsm.runs import KEY_DTYPE, keys_le, pack_u128
from tigerbeetle_tpu.vsr.grid import Grid

LEVELS = 7          # reference: src/config.zig lsm_levels
GROWTH = 8          # reference: src/config.zig lsm_growth_factor


def _entry_size(value_size: int) -> int:
    return 16 + 1 + value_size  # key + flags + value


@dataclasses.dataclass
class RunBlock:
    address: int
    count: int
    key_min: bytes  # first key in block
    key_max: bytes  # last key in block


@dataclasses.dataclass
class Run:
    blocks: list[RunBlock]

    @property
    def count(self) -> int:
        return sum(b.count for b in self.blocks)

    @property
    def key_min(self) -> bytes:
        return self.blocks[0].key_min

    @property
    def key_max(self) -> bytes:
        return self.blocks[-1].key_max


class Tree:
    def __init__(self, grid: Grid, name: str, *, value_size: int = 8,
                 memtable_max: int = 8192) -> None:
        self.grid = grid
        self.name = name
        self.value_size = value_size
        self.value_dtype = np.dtype(f"V{value_size}")
        self.memtable_max = memtable_max
        # Memtable: insertion dict key-bytes -> (flags, value-bytes).
        self.memtable: dict[bytes, tuple[int, bytes]] = {}
        # levels[i] = runs, newest last.
        self.levels: list[list[Run]] = [[] for _ in range(LEVELS)]

    # ------------------------------------------------------------------
    # Writes.

    def put_batch(self, keys: np.ndarray, values: np.ndarray) -> None:
        values = np.ascontiguousarray(values).view(np.uint8).reshape(
            len(keys), -1
        )
        kb = keys.tobytes()
        for i in range(len(keys)):
            self.memtable[kb[16 * i : 16 * i + 16]] = (
                0, values[i].tobytes()
            )

    def remove_batch(self, keys: np.ndarray) -> None:
        kb = keys.tobytes()
        empty = bytes(self.value_size)
        for i in range(len(keys)):
            self.memtable[kb[16 * i : 16 * i + 16]] = (1, empty)

    def put(self, key_hi: int, key_lo: int, value: bytes | int) -> None:
        key = pack_u128(
            np.array([key_lo], np.uint64), np.array([key_hi], np.uint64)
        )
        if isinstance(value, int):
            value = value.to_bytes(self.value_size, "little")
        self.memtable[key.tobytes()] = (0, value)

    # ------------------------------------------------------------------
    # Reads.

    def lookup_batch(self, keys: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """-> (found bool[n], values (n, value_size) uint8).

        Newest wins: memtable, then level 0 runs newest-first, then
        deeper levels.  Tombstones report not-found.
        """
        n = len(keys)
        found = np.zeros(n, bool)
        resolved = np.zeros(n, bool)
        values = np.zeros((n, self.value_size), np.uint8)

        if self.memtable:
            kb = keys.tobytes()
            for i in range(n):
                hit = self.memtable.get(kb[16 * i : 16 * i + 16])
                if hit is not None:
                    resolved[i] = True
                    if hit[0] == 0:
                        found[i] = True
                        values[i] = np.frombuffer(hit[1], np.uint8)

        for run in self._runs_newest_first():
            todo = np.flatnonzero(~resolved)
            if len(todo) == 0:
                break
            self._run_lookup(run, keys, todo, found, resolved, values)
        return found, values

    def _runs_newest_first(self):
        for level in range(LEVELS):
            for run in reversed(self.levels[level]):
                yield run

    def _run_lookup(self, run: Run, keys, todo, found, resolved, values):
        fences = np.array([b.key_min for b in run.blocks], KEY_DTYPE)
        maxes = np.array([b.key_max for b in run.blocks], KEY_DTYPE)
        sub = keys[todo]
        # Candidate block per key: rightmost block whose min <= key.
        bi = np.searchsorted(fences, sub, side="right") - 1
        in_range = (bi >= 0) & keys_le(sub, maxes[np.clip(bi, 0, None)])
        for block_index in np.unique(bi[in_range]):
            mask = in_range & (bi == block_index)
            idx = todo[mask]
            bkeys, bflags, bvalues = self._read_run_block(
                run.blocks[block_index]
            )
            pos = np.searchsorted(bkeys, keys[idx])
            pos_c = np.minimum(pos, len(bkeys) - 1)
            hit = bkeys[pos_c] == keys[idx]
            hi = idx[hit]
            p = pos_c[hit]
            resolved[hi] = True
            live = bflags[p] == 0
            found[hi[live]] = True
            values[hi[live]] = bvalues[p[live]]

    def _read_run_block(self, block: RunBlock):
        payload = self.grid.read_block(block.address)
        count = int.from_bytes(payload[:4], "little")
        at = 4
        keys = np.frombuffer(payload[at : at + 16 * count], KEY_DTYPE)
        at += 16 * count
        flags = np.frombuffer(payload[at : at + count], np.uint8)
        at += count
        vals = np.frombuffer(
            payload[at : at + count * self.value_size], np.uint8
        ).reshape(count, self.value_size)
        return keys, flags, vals

    # ------------------------------------------------------------------
    # Range scans (ascending).  Returns merged (keys, values), newest
    # wins, tombstones dropped.

    def scan_range(self, key_min: bytes, key_max: bytes) -> tuple[np.ndarray, np.ndarray]:
        streams = []
        if self.memtable:
            items = sorted(
                (k, fv) for k, fv in self.memtable.items()
                if key_min <= k <= key_max
            )
            if items:
                keys = np.array([k for k, _ in items], KEY_DTYPE)
                flags = np.array([fv[0] for _, fv in items], np.uint8)
                vals = np.frombuffer(
                    b"".join(fv[1] for _, fv in items), np.uint8
                ).reshape(len(items), self.value_size)
                streams.append((keys, flags, vals))
        for run in self._runs_newest_first():
            if run.key_max < key_min or run.key_min > key_max:
                continue
            parts = []
            for block in run.blocks:
                if block.key_max < key_min or block.key_min > key_max:
                    continue
                bkeys, bflags, bvals = self._read_run_block(block)
                lo = np.searchsorted(bkeys, np.array([key_min], KEY_DTYPE))[0]
                hi = np.searchsorted(
                    bkeys, np.array([key_max], KEY_DTYPE), side="right"
                )[0]
                parts.append((bkeys[lo:hi], bflags[lo:hi], bvals[lo:hi]))
            if parts:
                streams.append(
                    tuple(np.concatenate([p[j] for p in parts]) for j in range(3))
                )
        return k_way_merge(streams, self.value_size)

    # ------------------------------------------------------------------
    # Memtable seal + compaction.

    def maybe_seal(self) -> None:
        if len(self.memtable) >= self.memtable_max:
            self.seal_memtable()

    def seal_memtable(self) -> None:
        if not self.memtable:
            return
        items = sorted(self.memtable.items())
        keys = np.array([k for k, _ in items], KEY_DTYPE)
        flags = np.array([fv[0] for _, fv in items], np.uint8)
        vals = np.frombuffer(
            b"".join(fv[1] for _, fv in items), np.uint8
        ).reshape(len(items), self.value_size)
        self.memtable.clear()
        run = self._write_run(keys, flags, vals)
        self.levels[0].append(run)
        self.compact()

    def _write_run(self, keys, flags, vals) -> Run:
        per_block = (self.grid.payload_size - 4) // _entry_size(self.value_size)
        blocks = []
        fs = self.grid.free_set
        n = len(keys)
        n_blocks = (n + per_block - 1) // per_block
        reservation = fs.reserve(n_blocks)
        for at in range(0, n, per_block):
            k = keys[at : at + per_block]
            f = flags[at : at + per_block]
            v = vals[at : at + per_block]
            payload = (
                len(k).to_bytes(4, "little")
                + k.tobytes() + f.tobytes() + v.tobytes()
            )
            address = fs.acquire(reservation)
            self.grid.write_block(address, payload)
            blocks.append(
                RunBlock(
                    address=address, count=len(k),
                    key_min=k[0].tobytes(), key_max=k[-1].tobytes(),
                )
            )
        fs.forfeit(reservation)
        return Run(blocks=blocks)

    def _level_run_max(self, level: int) -> int:
        return GROWTH if level == 0 else GROWTH

    def compact(self) -> None:
        """Merge any over-full level into the next (whole-level merge;
        the reference merges table-by-table per beat — pacing is a
        throughput refinement, the shape invariant is the same)."""
        for level in range(LEVELS - 1):
            if len(self.levels[level]) <= self._level_run_max(level):
                continue
            merged_streams = []
            # Newest first so k_way_merge keeps the newest version.
            for run in reversed(self.levels[level]):
                merged_streams.append(self._read_run_all(run))
            for run in reversed(self.levels[level + 1]):
                merged_streams.append(self._read_run_all(run))
            drop_tombstones = level + 1 == LEVELS - 1 or not any(
                self.levels[i] for i in range(level + 2, LEVELS)
            )
            keys, flags, vals = k_way_merge_flags(
                merged_streams, self.value_size
            )
            if drop_tombstones:
                live = flags == 0
                keys, flags, vals = keys[live], flags[live], vals[live]
            for run in self.levels[level] + self.levels[level + 1]:
                self._release_run(run)
            self.levels[level] = []
            self.levels[level + 1] = (
                [self._write_run(keys, flags, vals)] if len(keys) else []
            )

    def _read_run_all(self, run: Run):
        parts = [self._read_run_block(b) for b in run.blocks]
        return tuple(np.concatenate([p[j] for p in parts]) for j in range(3))

    def _release_run(self, run: Run) -> None:
        for block in run.blocks:
            self.grid.free_set.release(block.address)

    # ------------------------------------------------------------------
    # Manifest (persisted inside the checkpoint blob).

    def manifest(self) -> dict:
        return {
            "levels": [
                [
                    [(b.address, b.count, b.key_min, b.key_max) for b in run.blocks]
                    for run in level
                ]
                for level in self.levels
            ],
            "memtable": dict(self.memtable),
        }

    def restore(self, manifest: dict) -> None:
        self.levels = [
            [
                Run(blocks=[RunBlock(*t) for t in run])
                for run in level
            ]
            for level in manifest["levels"]
        ]
        self.memtable = dict(manifest["memtable"])


# ----------------------------------------------------------------------
# Merges (reference: src/lsm/k_way_merge.zig, zig_zag_merge.zig).


def k_way_merge_flags(streams, value_size: int):
    """Merge (keys, flags, values) streams, NEWEST FIRST: the first
    stream containing a key wins.  Returns sorted unique arrays with
    tombstones retained."""
    if not streams:
        return (
            np.zeros(0, KEY_DTYPE), np.zeros(0, np.uint8),
            np.zeros((0, value_size), np.uint8),
        )
    keys = np.concatenate([s[0] for s in streams])
    flags = np.concatenate([s[1] for s in streams])
    vals = np.concatenate([s[2] for s in streams])
    order = np.argsort(keys, kind="stable")  # stable: newer first per key
    keys, flags, vals = keys[order], flags[order], vals[order]
    first = np.ones(len(keys), bool)
    first[1:] = keys[1:] != keys[:-1]
    return keys[first], flags[first], vals[first]


def k_way_merge(streams, value_size: int):
    """As k_way_merge_flags but tombstones dropped (query surface)."""
    keys, flags, vals = k_way_merge_flags(streams, value_size)
    live = flags == 0
    return keys[live], vals[live]


def zig_zag_intersect(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """Sorted-key intersection (reference: src/lsm/zig_zag_merge.zig —
    vectorized equivalent of the leapfrog merge)."""
    return np.intersect1d(a.view(KEY_DTYPE), b.view(KEY_DTYPE))
