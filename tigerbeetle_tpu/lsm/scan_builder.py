"""Scan expressions over groove secondary indexes.

The reference's query engine composes per-index range scans into
condition trees — union (OR) via k-way merge, intersection (AND) via
zig-zag merge — then materializes matching objects in timestamp order
with an optional direction and limit (reference: src/lsm/
scan_builder.zig:1-40 condition trees, scan_merge.zig merge_union/
merge_intersection, scan_lookup.zig object materialization,
src/direction.zig).

Host-idiomatic re-design: scans produce sorted uint64 timestamp sets
(the index trees key on (field_value, timestamp), so a prefix range
scan is exactly "timestamps where field == value"); union/intersection
are vectorized set merges instead of iterator trees.  `ScanLookup`
gathers the objects for the final timestamp set from the object tree
in one batch.
"""

from __future__ import annotations

import dataclasses

import numpy as np

U64_MAX = (1 << 64) - 1


@dataclasses.dataclass(frozen=True)
class Scan:
    """A node in a condition tree.  Build with ScanBuilder."""

    kind: str  # "eq" | "union" | "intersect"
    field: str | None = None
    value: int = 0
    children: tuple["Scan", ...] = ()


class ScanBuilder:
    """Builds and evaluates condition trees over one groove
    (reference: src/lsm/scan_builder.zig — scans_max/merge nodes are
    bounded there; here the tree is evaluated recursively with
    whole-set vector merges)."""

    def __init__(self, groove) -> None:
        self.groove = groove

    # -- construction --------------------------------------------------

    def eq(self, field: str, value: int) -> Scan:
        assert field in self.groove.indexes, field
        return Scan("eq", field=field, value=value)

    def union(self, *scans: Scan) -> Scan:
        assert scans
        return scans[0] if len(scans) == 1 else Scan("union", children=scans)

    def intersect(self, *scans: Scan) -> Scan:
        assert scans
        return (
            scans[0] if len(scans) == 1 else Scan("intersect", children=scans)
        )

    # -- evaluation ----------------------------------------------------

    def evaluate(
        self,
        scan: Scan,
        *,
        ts_min: int = 0,
        ts_max: int = U64_MAX,
        reversed: bool = False,
        limit: int | None = None,
        return_values: bool = False,
    ) -> np.ndarray:
        """-> matching timestamps in scan direction, limited.  With
        return_values, the index entries' 8-byte payloads instead (the
        spill grooves' row pointers — monotone with timestamp, so the
        set algebra is identical)."""
        ts = self._eval(scan, ts_min, ts_max, return_values)
        if reversed:
            ts = ts[::-1]
        if limit is not None:
            ts = ts[:limit]
        return np.ascontiguousarray(ts)

    def _eval(
        self, scan: Scan, ts_min: int, ts_max: int, return_values: bool
    ) -> np.ndarray:
        if scan.kind == "eq":
            return self.groove.index_scan(
                scan.field, scan.value, ts_min=ts_min, ts_max=ts_max,
                return_values=return_values,
            )
        parts = [
            self._eval(c, ts_min, ts_max, return_values)
            for c in scan.children
        ]
        if scan.kind == "union":
            out = parts[0]
            for p in parts[1:]:
                out = np.union1d(out, p)
            return out
        if scan.kind == "intersect":
            return self.groove.index_intersect(parts)
        raise AssertionError(scan.kind)  # pragma: no cover


class ScanLookup:
    """Materialize scan results as objects (reference:
    src/lsm/scan_lookup.zig — buffers rows for the state machine's
    reply)."""

    def __init__(self, groove) -> None:
        self.groove = groove

    def fetch(self, timestamps: np.ndarray) -> np.ndarray:
        """-> (n, object_size) uint8 rows, in `timestamps` order.
        Scanned timestamps always resolve (indexes only reference live
        objects after compaction drops tombstoned pairs)."""
        if len(timestamps) == 0:
            return np.zeros((0, self.groove.object_size), np.uint8)
        found, rows = self.groove.get_objects(timestamps)
        assert found.all(), "index referenced a missing object"
        return rows
