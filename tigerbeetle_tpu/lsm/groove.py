"""Groove: the tree bundle for one object type.

reference: src/lsm/groove.zig:136-176 — IdTree (id -> timestamp),
ObjectTree (timestamp -> object), and one secondary index tree per
indexed field, keyed (field_value, timestamp) so a prefix range scan
yields the timestamps of matching objects in time order.
"""

from __future__ import annotations

import numpy as np

from tigerbeetle_tpu.lsm.runs import KEY_DTYPE, pack_u128
from tigerbeetle_tpu.lsm.tree import Tree, zig_zag_intersect
from tigerbeetle_tpu.vsr.grid import Grid


def _ts_keys(timestamps: np.ndarray) -> np.ndarray:
    return pack_u128(
        np.asarray(timestamps, np.uint64),
        np.zeros(len(timestamps), np.uint64),
    )


class Groove:
    def __init__(self, grid: Grid, name: str, *, object_size: int,
                 index_fields: list[str], memtable_max: int = 8192,
                 index_value_size: int = 1) -> None:
        self.name = name
        self.object_size = object_size
        self.id_tree = Tree(
            grid, f"{name}.id", value_size=8, memtable_max=memtable_max
        )
        # Objects are mostly-zero wire images (reserved user_data,
        # zeroed reconstructible fields, high u128 limbs): sparse-value
        # blocks halve the dominant seal/merge write volume.
        self.object_tree = Tree(
            grid, f"{name}.object", value_size=object_size,
            memtable_max=memtable_max, sparse_values=object_size % 8 == 0,
        )
        # index_value_size=8 stores a row/object pointer per index entry
        # (the state machine's spill tier scans indexes straight to
        # object-tree keys); the default 1-byte value is presence-only.
        self.indexes = {
            field: Tree(
                grid, f"{name}.{field}", value_size=index_value_size,
                memtable_max=memtable_max,
            )
            for field in index_fields
        }

    # ------------------------------------------------------------------

    def insert_batch(self, id_lo, id_hi, timestamps, objects: np.ndarray,
                     index_values: dict[str, np.ndarray]) -> None:
        """`objects`: (n, object_size) uint8; `index_values`: field ->
        uint64 array (the indexed field per object)."""
        n = len(timestamps)
        ts = np.asarray(timestamps, np.uint64)
        self.id_tree.put_batch(
            pack_u128(np.asarray(id_lo, np.uint64), np.asarray(id_hi, np.uint64)),
            ts.astype("<u8").view("V8"),
        )
        self.object_tree.put_batch(_ts_keys(ts), objects)
        for field, values in index_values.items():
            keys = pack_u128(ts, np.asarray(values, np.uint64))
            tree = self.indexes[field]
            # Entry payload sized to the tree (presence-only by
            # default; 8-byte row pointers for the spill tier).
            tree.put_batch(keys, np.zeros((n, tree.value_size), np.uint8))
        self.maybe_seal()

    def remove_index_batch(self, field: str, values, timestamps) -> None:
        keys = pack_u128(
            np.asarray(timestamps, np.uint64), np.asarray(values, np.uint64)
        )
        self.indexes[field].remove_batch(keys)

    def lookup_ids(self, id_lo, id_hi) -> tuple[np.ndarray, np.ndarray]:
        """ids -> (found, timestamps)."""
        keys = pack_u128(
            np.asarray(id_lo, np.uint64), np.asarray(id_hi, np.uint64)
        )
        found, values = self.id_tree.lookup_batch(keys)
        return found, values.view("<u8").reshape(-1)

    def get_objects(self, timestamps) -> tuple[np.ndarray, np.ndarray]:
        found, values = self.object_tree.lookup_batch(
            _ts_keys(np.asarray(timestamps, np.uint64))
        )
        return found, values

    def index_scan(self, field: str, value: int, *, ts_min: int = 0,
                   ts_max: int = (1 << 64) - 1,
                   return_values: bool = False) -> np.ndarray:
        """-> matching timestamps, ascending — or, with return_values,
        the index entries' 8-byte payloads (e.g. the spill grooves'
        row pointers, which ascend with timestamp) in the same order."""
        lo = pack_u128(
            np.array([ts_min], np.uint64), np.array([value], np.uint64)
        ).tobytes()
        hi = pack_u128(
            np.array([ts_max], np.uint64), np.array([value], np.uint64)
        ).tobytes()
        keys, vals = self.indexes[field].scan_range(lo, hi)
        if return_values:
            return vals.view("<u8").reshape(-1).astype(np.uint64)
        # Key layout is (hi=value, lo=timestamp) big-endian packed:
        # the low 8 bytes are the big-endian timestamp.
        raw = keys.tobytes()
        ts = np.frombuffer(raw, ">u8").reshape(-1, 2)[:, 1]
        return ts.astype(np.uint64)

    def index_intersect(self, scans: list[np.ndarray]) -> np.ndarray:
        """Zig-zag AND of several index_scan timestamp sets."""
        out = scans[0]
        for s in scans[1:]:
            out = np.intersect1d(out, s)
        return out

    def maybe_seal(self) -> None:
        self.id_tree.maybe_seal()
        self.object_tree.maybe_seal()
        for tree in self.indexes.values():
            tree.maybe_seal()

    # Run/block persistence lives in the forest's manifest log
    # (lsm/manifest_log.py); memtables ride the checkpoint blob via
    # Tree.memtable_manifest/restore_memtable.
