from tigerbeetle_tpu.lsm.runs import pack_u128

__all__ = ["pack_u128"]
