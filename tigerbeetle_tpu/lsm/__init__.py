from tigerbeetle_tpu.lsm.runs import SortedRuns, pack_u128

__all__ = ["SortedRuns", "pack_u128"]
