"""tigerbeetle_tpu: a TPU-native financial-transactions database.

A from-scratch framework with the capabilities of the reference
TigerBeetle (surveyed in SURVEY.md): the double-entry accounting state
machine runs as a JAX/XLA kernel against an HBM-resident account table,
surrounded by a host runtime (WAL, consensus, message bus, clients).
"""

from tigerbeetle_tpu import constants, types

__all__ = ["constants", "types"]
__version__ = "0.1.0"
