"""tigerbeetle_tpu: a TPU-native financial-transactions database.

A from-scratch framework with the capabilities of the reference
TigerBeetle (surveyed in SURVEY.md): the double-entry accounting state
machine runs as a JAX/XLA kernel against an HBM-resident account table,
surrounded by a host runtime (WAL, consensus, message bus, clients).
"""

from tigerbeetle_tpu.jaxenv import force_cpu_jax_if_requested

# Must run before anything can initialize a JAX backend: a wedged
# accelerator tunnel blocks even jnp.zeros(), and the ambient
# sitecustomize overrides the JAX_PLATFORMS env var (see jaxenv.py).
force_cpu_jax_if_requested()

from tigerbeetle_tpu import constants, types

__all__ = ["constants", "types"]
__version__ = "0.1.0"
