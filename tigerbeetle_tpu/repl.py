"""REPL: interactive / --command client (reference: src/repl.zig).

Statement syntax (same shape as the reference's):

    create_accounts id=1 code=10 ledger=700, id=2 code=10 ledger=700;
    create_transfers id=1 debit_account_id=1 credit_account_id=2
        amount=10 ledger=700 code=10 flags=linked|pending;
    lookup_accounts id=1, id=2;
    get_account_transfers account_id=1 limit=10;

Objects are comma-separated; `flags` takes |-separated names.  Output
is JSON-ish, one object per line.
"""
# tbcheck: allow-file(no-print): the REPL's stdout is the user
# conversation.

from __future__ import annotations

import json
import sys

import numpy as np

from tigerbeetle_tpu import types

OPERATIONS = {
    "create_accounts", "create_transfers", "lookup_accounts",
    "lookup_transfers", "get_account_transfers", "get_account_balances",
}

_ACCOUNT_U128 = {"id", "debits_pending", "debits_posted", "credits_pending",
                 "credits_posted", "user_data_128"}
_TRANSFER_U128 = {"id", "debit_account_id", "credit_account_id", "amount",
                  "pending_id", "user_data_128"}

_FLAG_TYPES = {
    "create_accounts": types.AccountFlags,
    "create_transfers": types.TransferFlags,
    "get_account_transfers": types.AccountFilterFlags,
    "get_account_balances": types.AccountFilterFlags,
}


def parse_statement(statement: str) -> tuple[str, list[dict]]:
    statement = statement.strip().rstrip(";").strip()
    if not statement:
        raise ValueError("empty statement")
    parts = statement.split(None, 1)
    operation = parts[0]
    if operation not in OPERATIONS:
        raise ValueError(f"unknown operation {operation!r}")
    objects: list[dict] = []
    rest = parts[1] if len(parts) > 1 else ""
    for chunk in rest.split(","):
        chunk = chunk.strip()
        if not chunk:
            continue
        obj: dict = {}
        for pair in chunk.split():
            key, eq, value = pair.partition("=")
            if not eq:
                raise ValueError(f"expected key=value, got {pair!r}")
            if key == "flags":
                flag_type = _FLAG_TYPES.get(operation)
                if flag_type is None:
                    raise ValueError("flags not valid here")
                bits = 0
                for name in value.split("|"):
                    bits |= int(flag_type[name.strip()])
                obj[key] = bits
            else:
                obj[key] = int(value, 0)
        if obj:
            objects.append(obj)
    return operation, objects


def _row_to_dict(row: np.void, u128_fields: set[str]) -> dict:
    out = {}
    done = set()
    for name in row.dtype.names:
        if name.endswith("_lo"):
            base = name[:-3]
            if base in u128_fields:
                out[base] = types.u128_get(row, base)
                done.add(base)
                continue
        if name.endswith("_hi") and name[:-3] in done:
            continue
        if name == "reserved":
            continue
        value = row[name]
        out[name] = int(value) if np.isscalar(value) or value.shape == () else None
    return out


def execute(client, statement: str) -> list[dict]:
    """Run one statement against a Client; returns printable objects."""
    operation, objects = parse_statement(statement)
    if operation == "create_accounts":
        results = client.create_accounts(objects)
        return [{"index": i, "result": r.name} for i, r in results]
    if operation == "create_transfers":
        results = client.create_transfers(objects)
        return [{"index": i, "result": r.name} for i, r in results]
    if operation in ("lookup_accounts", "lookup_transfers"):
        ids = [obj["id"] for obj in objects]
        rows = (
            client.lookup_accounts(ids) if operation == "lookup_accounts"
            else client.lookup_transfers(ids)
        )
        u128 = _ACCOUNT_U128 if operation == "lookup_accounts" else _TRANSFER_U128
        return [_row_to_dict(r, u128) for r in rows]
    # Query filters take exactly one object.
    if len(objects) != 1:
        raise ValueError(f"{operation} takes exactly one filter object")
    kw = dict(objects[0])
    account_id = kw.pop("account_id")
    if operation == "get_account_transfers":
        rows = client.get_account_transfers(account_id, **kw)
        return [_row_to_dict(r, _TRANSFER_U128) for r in rows]
    rows = client.get_account_balances(account_id, **kw)
    return [
        _row_to_dict(r, {"debits_pending", "debits_posted", "credits_pending",
                         "credits_posted"})
        for r in rows
    ]


def run(client, command: str | None = None,
        stdin=None, stdout=None) -> None:
    stdin = stdin or sys.stdin
    stdout = stdout or sys.stdout

    def run_one(statement: str) -> None:
        statement = statement.strip()
        if not statement:
            return
        try:
            for obj in execute(client, statement):
                print(json.dumps(obj), file=stdout)
            print("ok", file=stdout)
        except (ValueError, KeyError, OSError) as e:
            print(f"error: {e}", file=stdout)

    if command is not None:
        for statement in command.split(";"):
            run_one(statement)
        return
    buffer = ""
    for line in stdin:
        buffer += line
        while ";" in buffer:
            statement, _, buffer = buffer.partition(";")
            run_one(statement)
