from tigerbeetle_tpu.state_machine.cpu import CpuStateMachine

__all__ = ["CpuStateMachine"]
