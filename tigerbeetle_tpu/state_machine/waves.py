"""Conflict-aware wave execution: parallel apply for independent
transfers, exact scan only for true dependencies.

The sequential scan kernel (kernel.py) pays one device step per EVENT
— B steps per batch — even when almost every event touches disjoint
accounts.  This module collapses that to one step per *wave*: a
host-side partitioner (`plan_waves`) builds the batch's conflict graph
and assigns each event a topological LEVEL (one more than the highest
level among earlier events it conflicts with); each level executes as
ONE vectorized device step over its — possibly non-contiguous — index
set (`_wave_step_impl`, the scan body re-expressed over a (K,) event
axis with balance deltas combined by an exact u128 segment-sum
scatter, like kernel_fast._flush_impl), while true serial dependencies
— linked chains — run through the unchanged exact scan at their batch
position (kernel.scan_segment).  A two_phase batch of (pending,
finalize) pairs is exactly TWO waves; a fresh-ids batch is ONE.  The
segment kinds thread one carry, so outputs are bit-identical to the
full scan (enforced by tests/test_waves.py differential fuzz).

What makes two events DEPENDENT (same model as parallel-EVM conflict
graphs — arXiv:2503.04595 — specialized to the reference semantics):

- **id/pending references.**  A second event with the same transfer-id
  value must observe the first's create (exists ladder); a post/void
  whose pending_id names an in-batch id must observe that create and
  its status.  Tracked as compact id-group tokens (tpu.py's exact-path
  grouping): two events conflict when either's id_group or p_group was
  already claimed by the wave.
- **durable two-phase targets.**  Two finalizers of the same durable
  pending race first-wins; the second's verdict depends on the first.
  Tracked by p_tgt (the deduped durable-target index).
- **balance READS.**  Most transfers only *add* to balance columns —
  addition commutes and their result codes read no mutable state, so
  they share a wave even on the same hot account (the deltas sum).
  But balancing_debit/credit clamps and debits/credits_must_not_exceed
  limit checks *read* account balances: such an event conflicts with
  any wave-mate that writes one of its read slots (and its own writes
  conflict with wave-mates' reads).
- **linked chains & history accounts.**  Rollback couples every chain
  member (including the closing event), and an AF.history account's
  per-event snapshot must be sequential-exact (it feeds the history
  groove, while wave snapshots are rewritten to batch finals).
  History events always run in exact scan segments; chain runs whose
  chains are MUTUALLY INDEPENDENT (no pv/history members, ids claimed
  once batch-wide, no slot both touched by two chains and read by
  anyone) run position-stepped as CHAIN WAVES — one lax.scan over
  chain position (`_chain_wave_impl`), ~max_chain_len steps instead
  of one per member, with exact trailing-subtraction rollback —
  and everything else keeps the scan.

Overflow codes are the one read everyone performs implicitly: whether
`amount + dp` overflows u128 depends on prior events.  The executor
keeps them exact with the same superset admission the order-free fast
path uses (mirror.try_apply_adds): amounts are non-negative, so if the
ALL-APPLIED additions to a slot cannot overflow its columns or its
dp+dpo / cp+cpo pairs, no sequential prefix can either, and every
ov_* term is identically false in both orders.  `admission_ok` proves
that bound per touched slot on the host mirror (plus an `extra` term
covering in-flight window batches when the device engine plans
against its lagging mirror); a batch that fails it routes to the scan
path — never a wrong answer, only a slower one.

Two executors share the segment loop (`_execute_plan`): the host
exact path donates its table (run_create_transfers_waves), while the
device engine's window launch dispatches NON-DONATING twins
(run_plan_engine) so its authoritative handle survives mid-batch
retries (device_engine._exec_waves).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field

import numpy as np

import jax
import jax.numpy as jnp

from tigerbeetle_tpu.ops import u128 as w
from tigerbeetle_tpu.state_machine import kernel
from tigerbeetle_tpu.state_machine.kernel import (
    CREATED_FIELDS,
    F_BAL_CR,
    F_BAL_DR,
    F_LINKED,
    F_PENDING,
    F_POST,
    F_VOID,
    NS_PER_S,
    R_ALREADY_POSTED,
    R_ALREADY_VOIDED,
    R_EXCEEDS_CREDITS,
    R_EXCEEDS_DEBITS,
    R_EXCEEDS_PENDING_AMOUNT,
    R_OVERFLOWS_CP,
    R_OVERFLOWS_CPO,
    R_OVERFLOWS_CREDITS,
    R_OVERFLOWS_DEBITS,
    R_OVERFLOWS_DP,
    R_OVERFLOWS_DPO,
    R_OVERFLOWS_TIMEOUT,
    R_PENDING_DIFF_AMOUNT,
    R_PENDING_DIFF_CODE,
    R_PENDING_DIFF_CR,
    R_PENDING_DIFF_DR,
    R_PENDING_DIFF_LEDGER,
    R_PENDING_EXPIRED,
    R_PENDING_NOT_FOUND,
    R_PENDING_NOT_PENDING,
    R_TIMESTAMP_MUST_BE_ZERO,
    S_PENDING,
    S_POSTED,
    S_VOIDED,
    U64_MAX,
    _E_FIELD_MAP,
    _EXISTS_SENTINEL,
    _P_FIELD_MAP,
    _exists_ladder_normal,
    _exists_ladder_post_void,
    _first_nonzero,
    _gather_created,
    _merge,
    AF_CR_LIMIT,
    AF_DR_LIMIT,
    CP_LO, CP_HI, CPO_LO, CPO_HI, DP_LO, DP_HI, DPO_LO, DPO_HI,
)

_MASK32 = jnp.uint64(0xFFFFFFFF)

# Wave/scan segment shape buckets (jit compile cache keys).
_SEG_BUCKETS = (16, 64, 256, 1024, 4096, 8192)

def min_ratio() -> float:
    """Minimum step-count reduction (batch length / executed steps)
    before the wave path beats the plain scan; below it the partition
    degrades toward per-event waves and the scan's single fused
    dispatch wins.  Read live (like mode()) so tests and bench arms
    can toggle TB_WAVES_MIN_RATIO after import."""
    from tigerbeetle_tpu import envcheck

    return envcheck.env_float("TB_WAVES_MIN_RATIO", 2.0, minimum=0.0)


def mode() -> str:
    """TB_WAVES routing mode:

    - unset/"auto": wave plans considered whenever the JAX exact scan
      would otherwise run (native absent), profitability + admission
      gates apply.
    - "0": off — the exact path always runs the B-step scan.
    - "1": force — route every batch to the JAX exact path (bypassing
      the native engine and the order-free/linked/two-phase fast
      paths) and execute the wave plan even when unprofitable.
      Differential-test routing: maximizes wave-executor coverage.
    - "exact": route to the JAX exact path like "1", but keep the
      normal profitability/admission decision (what the scheduler
      would really do there).
    - "scan": route to the JAX exact path, never plan waves — the
      pure sequential scan on identical routing, the honest control
      for wave-vs-scan benchmarks."""
    from tigerbeetle_tpu import envcheck

    return envcheck.env_choice(
        "TB_WAVES", "auto", ("auto", "0", "1", "exact", "scan")
    )


def dev_mode() -> str:
    """TB_DEV_WAVES routing mode for the device engine's window launch
    (independent of TB_WAVES, which governs the host exact path):

    - unset/"auto": window batches that fall off the semantic kernels
      (mixed kinds, conflicting ids, balancing, timeouts, two-phase
      edge shapes) are wave-dispatched against the authoritative HBM
      table when the plan is admitted and profitable; declines keep
      the r7 behavior (drain + exact host path).
    - "0": off — off-kernel batches always drain to the host.
    - "1": force — execute every ADMITTED plan even when unprofitable
      (differential-test routing; admission is never bypassed, it is
      the correctness proof)."""
    from tigerbeetle_tpu import envcheck

    return envcheck.env_choice("TB_DEV_WAVES", "auto", ("auto", "0", "1"))


def spec_mode() -> str:
    """TB_WAVES_SPECULATE routing mode for the device wave dispatcher
    (see envcheck.waves_speculate for the full contract): "auto"/"1"
    speculate behind the residue-cap gate, "0" keeps the pessimistic
    plan-first path, "force" routes every window batch optimistically.
    Read live (like mode()) so tests and bench arms can toggle it
    after import."""
    from tigerbeetle_tpu import envcheck

    return envcheck.waves_speculate()


def spec_residue_cap() -> float:
    """TB_WAVES_SPEC_RESIDUE_CAP, read live (envcheck-validated)."""
    from tigerbeetle_tpu import envcheck

    return envcheck.spec_residue_cap()


def chain_max() -> int:
    """TB_WAVES_CHAIN_MAX: longest chain (in positions) a chain-wave
    segment may carry — longer chains keep the exact scan, whose cost
    is one step per member.  0 disables chain waves entirely.  Read
    live so tests and bench arms can toggle it after import."""
    from tigerbeetle_tpu import envcheck

    return envcheck.env_int(
        "TB_WAVES_CHAIN_MAX", 64, minimum=0, maximum=4096
    )


# ---------------------------------------------------------------------------
# Partitioner.


@dataclass
class WavePlan:
    """Execution plan: ordered segments whose index sets cover [0, n).

    Segment order is the EXECUTION order; a "wave" segment's indices
    need not be contiguous (topological-level scheduling), a "scan"
    segment is always a contiguous chain run executed at its batch
    position, and a "chains" segment is a contiguous run of mutually
    independent linked chains executed position-stepped (one device
    step per chain POSITION — `chain_steps` holds the padded step
    count per segment index).
    """

    n: int
    # (kind, idx): kind "wave" = one parallel step over idx (int
    # array, ascending), kind "scan" = len(idx) exact sequential
    # steps over a contiguous run, kind "chains" = chain_steps[k]
    # position steps over a contiguous run of independent chains.
    segments: list = field(default_factory=list)
    wave_mask: np.ndarray | None = None  # events whose snapshots are
    # rewritten to batch finals (wave + chain-wave events)
    chain_steps: dict = field(default_factory=dict)
    # Host-integer sum of the batch's per-event amount bounds — the
    # admission term a later window batch must count while this one is
    # in flight (set by tpu._plan_wave_execution).
    batch_bound: int = 0

    @property
    def n_waves(self) -> int:
        return sum(1 for k, _ in self.segments if k == "wave")

    @property
    def parallel_events(self) -> int:
        return sum(len(ix) for k, ix in self.segments if k == "wave")

    @property
    def n_steps(self) -> int:
        """Device-step equivalents: 1 per wave, length per scan run,
        padded position count per chain-wave run."""
        total = 0
        for k, (kind, ix) in enumerate(self.segments):
            if kind == "wave":
                total += 1
            elif kind == "chains":
                total += self.chain_steps[k]
            else:
                total += len(ix)
        return total

    @property
    def ratio(self) -> float:
        return self.n / max(1, self.n_steps)

    def profitable(self, ratio_floor: float | None = None) -> bool:
        return self.ratio >= (
            min_ratio() if ratio_floor is None else ratio_floor
        )


# How many wavefront rounds the vectorized level assigner runs before
# handing the region to the Python-walk fallback: profitable plans
# have FEW levels (the ratio gate needs n / steps >= min_ratio), so a
# region still unassigned after this many rounds is serial enough that
# the O(n) walk is the cheaper exact algorithm.
_WAVEFRONT_CAP = 24


def _inb_pv_write_pairs(n: int, meta: dict):
    """(event, slot) pairs for in-batch post/voids: the slot union of
    the id-group each finalizer's pending reference names (the creator
    is whichever group member applied, so the finalizer's static write
    set is the union).  Shared by the partitioner's conflict entries
    and the per-column overflow admission (tpu.py)."""
    inb = meta["inb_pv"]
    empty = (np.zeros(0, np.int64), np.zeros(0, np.int64))
    if not inb.any():
        return empty
    id_group = meta["id_group"]
    ref = np.unique(meta["p_group"][inb])
    member = np.isin(id_group, ref)
    g2 = np.concatenate([id_group[member], id_group[member]])
    s2 = np.concatenate([meta["ev_dr"][member], meta["ev_cr"][member]])
    keep = s2 >= 0
    g2, s2 = g2[keep], s2[keep]
    if len(g2) == 0:
        return empty
    span = int(s2.max()) + 2
    key = np.unique(g2 * span + s2)
    pg, ps = key // span, key % span
    evs = np.flatnonzero(inb)
    lo = np.searchsorted(pg, meta["p_group"][evs], side="left")
    hi = np.searchsorted(pg, meta["p_group"][evs], side="right")
    cnt = hi - lo
    total = int(cnt.sum())
    if total == 0:
        return empty
    out_ev = np.repeat(evs, cnt)
    within = np.arange(total) - np.repeat(np.cumsum(cnt) - cnt, cnt)
    out_slot = ps[np.repeat(lo, cnt) + within]
    return out_ev.astype(np.int64), out_slot.astype(np.int64)


def _levels_walk(lo: int, hi: int, meta: dict, group_slots) -> np.ndarray:
    """Per-event Python walk over region [lo, hi) — the REFERENCE
    level assignment (the vectorized wavefront must agree exactly;
    tests/test_device_waves.py fuzzes the two against each other) and
    the fallback for regions more serial than _WAVEFRONT_CAP levels.

    Level = 1 + max level of every earlier conflicting event: same-id
    claims (exists ladder), pending refs, first-wins finalize targets,
    then balance-slot RAW/WAR (a reader must see exactly the earlier
    writers' adds; later writers must apply after it reads).  Reads
    also serialize against earlier reads — a balancing/limit reader's
    own writes are data-dependent, and the greedy rule this
    generalizes kept reader pairs ordered.
    """
    id_group = meta["id_group"]
    p_group = meta["p_group"]
    p_tgt = meta["p_tgt"]
    writes0, writes1 = meta["writes0"], meta["writes1"]
    reads0, reads1 = meta["reads0"], meta["reads1"]
    inb_pv = meta["inb_pv"]
    group_level: dict[int, int] = {}
    ptgt_level: dict[int, int] = {}
    write_level: dict[int, int] = {}
    read_level: dict[int, int] = {}
    levels = np.zeros(hi - lo, np.int32)
    for e in range(lo, hi):
        g = int(id_group[e])
        pg = int(p_group[e])
        pt = int(p_tgt[e])
        ww = []
        if writes0[e] >= 0:
            ww.append(int(writes0[e]))
        if writes1[e] >= 0:
            ww.append(int(writes1[e]))
        if inb_pv[e]:
            ww.extend(group_slots.get(pg, ()))
        rr = []
        if reads0[e] >= 0:
            rr.append(int(reads0[e]))
        if reads1[e] >= 0:
            rr.append(int(reads1[e]))

        lvl = group_level.get(g, -1) + 1
        if pg >= 0:
            lvl = max(lvl, group_level.get(pg, -1) + 1)
        if pt >= 0:
            lvl = max(lvl, ptgt_level.get(pt, -1) + 1)
        for s in rr:
            lvl = max(
                lvl,
                write_level.get(s, -1) + 1,
                read_level.get(s, -1) + 1,
            )
        for s in ww:
            lvl = max(lvl, read_level.get(s, -1) + 1)

        levels[e - lo] = lvl
        if lvl > group_level.get(g, -1):
            group_level[g] = lvl
        if pg >= 0 and lvl > group_level.get(pg, -1):
            group_level[pg] = lvl
        if pt >= 0 and lvl > ptgt_level.get(pt, -1):
            ptgt_level[pt] = lvl
        for s in ww:
            if lvl > write_level.get(s, -1):
                write_level[s] = lvl
        for s in rr:
            if lvl > read_level.get(s, -1):
                read_level[s] = lvl
    return levels


def _levels_wavefront(
    lo: int, hi: int, meta: dict, inb_ev, inb_slot, cap: int = None
) -> np.ndarray | None:
    """Vectorized level assignment for region [lo, hi): Kahn's
    algorithm by level over the conflict DAG.  At round k every
    still-unassigned event with no unassigned predecessor takes level
    k — which equals the walk's greedy level exactly (a predecessor's
    level is strictly below its successors', so "all predecessors
    assigned" first becomes true at round 1 + max pred level).

    Per round the blocked test is a segmented min over sorted-by-token
    entry arrays: for a serial token (id/pending-group claim,
    first-wins target) only the minimum-index unassigned claimant is
    unblocked; for a balance slot a reader is unblocked only as the
    minimum-index unassigned toucher, a writer when no unassigned
    reader precedes it (commuting writers share a round).  Rounds cost
    O(entries) vectorized; plans worth executing have few levels, so a
    region still unassigned after `cap` rounds returns None and the
    caller uses the O(n) walk.
    """
    if cap is None:
        cap = _WAVEFRONT_CAP
    m = hi - lo
    if m <= 1:
        return np.zeros(m, np.int32)
    rel = np.arange(m, dtype=np.int64)
    # Serial tokens: even ids = id/pending groups, odd = durable
    # first-wins targets (namespaces never collide).
    id_group = meta["id_group"][lo:hi]
    s_tok = [2 * id_group]
    s_ev = [rel]
    pg = meta["p_group"][lo:hi]
    msk = pg >= 0
    s_tok.append(2 * pg[msk])
    s_ev.append(rel[msk])
    pt = meta["p_tgt"][lo:hi]
    msk = pt >= 0
    s_tok.append(2 * pt[msk] + 1)
    s_ev.append(rel[msk])
    ser_tok = np.concatenate(s_tok)
    ser_ev = np.concatenate(s_ev)
    _, ser_tok = np.unique(ser_tok, return_inverse=True)
    n_ser = int(ser_tok.max()) + 1

    # Slot entries: (slot, event, role).
    sl, se, sr = [], [], []
    for name, is_read in (
        ("reads0", True), ("reads1", True),
        ("writes0", False), ("writes1", False),
    ):
        a = meta[name][lo:hi]
        msk = a >= 0
        sl.append(a[msk])
        se.append(rel[msk])
        sr.append(np.full(int(msk.sum()), is_read))
    if len(inb_ev):
        msk = (inb_ev >= lo) & (inb_ev < hi)
        sl.append(inb_slot[msk])
        se.append(inb_ev[msk] - lo)
        sr.append(np.zeros(int(msk.sum()), bool))
    slot = np.concatenate(sl)
    sev = np.concatenate(se)
    sread = np.concatenate(sr)
    have_slots = len(slot) > 0
    if have_slots:
        _, slot = np.unique(slot, return_inverse=True)
        n_slot = int(slot.max()) + 1

    levels = np.full(m, -1, np.int32)
    un = np.ones(m, bool)
    big = np.int64(m)
    for lvl in range(cap):
        blk = np.zeros(m, bool)
        act = un[ser_ev]
        t_min = np.full(n_ser, big, np.int64)
        np.minimum.at(t_min, ser_tok[act], ser_ev[act])
        e_act = ser_ev[act]
        np.logical_or.at(blk, e_act, e_act > t_min[ser_tok[act]])
        if have_slots:
            sact = un[sev]
            a_min = np.full(n_slot, big, np.int64)
            np.minimum.at(a_min, slot[sact], sev[sact])
            r_min = np.full(n_slot, big, np.int64)
            ract = sact & sread
            np.minimum.at(r_min, slot[ract], sev[ract])
            es = sev[sact]
            lim = np.where(
                sread[sact], a_min[slot[sact]], r_min[slot[sact]]
            )
            np.logical_or.at(blk, es, es > lim)
        take = un & ~blk
        if not take.any():
            # The DAG is acyclic (edges point forward), so this is
            # unreachable while events remain — guard anyway.
            return None
        levels[take] = lvl
        un &= ~take
        if not un.any():
            return levels
    return None


def _chain_wave_steps(i: int, j: int, n: int, meta: dict, claims):
    """Chain-wave admission for the chain run [i, j): the padded
    position-step count when the run's chains may execute
    position-stepped, else None (keep the exact scan).

    Requirements — each guards a specific exactness argument:
    - no must-scan members (history snapshots are semantically read)
      and no post/void members (first-wins + rollback un-finalize
      would couple chains);
    - every member's id-group is claimed exactly once batch-wide
      (fresh-or-durable-dup ids, never referenced by another event:
      a rolled-back member's created-record registration can then
      never feed a later exists/pending merge);
    - chains are pairwise independent: a balance slot touched by two
      different chains must have NO reader (commuting adds may share;
      a read coupled to another chain's writes — or its rollback —
      would diverge from the sequential order);
    - the longest chain fits the TB_WAVES_CHAIN_MAX cap, and the
      padded step count actually beats the scan's one step/member.
    """
    cap = chain_max()
    if cap < 2:
        return None
    if meta["chain_serial"][i:j].any() or meta["is_pv"][i:j].any():
        return None
    if (claims[meta["id_group"][i:j]] != 1).any():
        return None
    linked = meta["linked"][i:j]
    m = j - i
    starts = np.empty(m, bool)
    starts[0] = True
    starts[1:] = ~linked[:-1]
    chain_rel = np.cumsum(starts) - 1
    n_chains = int(chain_rel[-1]) + 1
    if n_chains < 2:
        return None
    max_len = int(np.bincount(chain_rel).max())
    if max_len > cap:
        return None
    steps = _bucket_positions(max_len)
    if steps >= m:
        return None
    # Pairwise chain independence over balance slots.
    sl, ch, rd = [], [], []
    for name, is_read in (
        ("reads0", True), ("reads1", True),
        ("writes0", False), ("writes1", False),
    ):
        a = meta[name][i:j]
        msk = a >= 0
        sl.append(a[msk])
        ch.append(chain_rel[msk])
        rd.append(np.full(int(msk.sum()), is_read))
    slot = np.concatenate(sl)
    if len(slot):
        chain_of = np.concatenate(ch)
        isr = np.concatenate(rd)
        order = np.lexsort((chain_of, slot))
        slot, chain_of, isr = slot[order], chain_of[order], isr[order]
        seg_new = np.empty(len(slot), bool)
        seg_new[0] = True
        seg_new[1:] = slot[1:] != slot[:-1]
        seg_id = np.cumsum(seg_new) - 1
        n_seg = int(seg_id[-1]) + 1
        first_chain = chain_of[seg_new][seg_id]
        multi = np.zeros(n_seg, bool)
        np.logical_or.at(multi, seg_id, chain_of != first_chain)
        has_read = np.zeros(n_seg, bool)
        np.logical_or.at(has_read, seg_id, isr)
        if (multi & has_read).any():
            return None
    return steps


def plan_waves(
    n: int, meta: dict, use_walk: bool = False, inb_pairs=None,
    claims=None, group_slots_fn=None,
) -> WavePlan:
    """Partition a batch into wave/chain-wave/scan segments.

    Chain runs (contiguous spans of ``chain_member`` events) are
    barriers at their batch position: runs of mutually independent
    linked chains execute position-stepped as a "chains" segment
    (~max_chain_len device steps — see _chain_wave_steps for the
    admission), everything else stays an exact scan.  The chain-free
    REGIONS between them schedule like a parallel-EVM conflict graph
    (arXiv:2503.04595): each event's *level* is one more than the
    highest level of any earlier in-region event it conflicts with
    (shared id/pending token, first-wins target, or a read-write
    balance-slot overlap), and each level executes as ONE wave —
    commuting adds never conflict, so a two_phase batch of (pending,
    finalize) pairs collapses to exactly two waves.  Level order
    preserves sequential semantics for every conflicting pair;
    non-conflicting events commute, so any interleaving of levels is
    bit-identical to the scan.

    Levels come from the vectorized wavefront (_levels_wavefront,
    sorted-token segmented mins — <100 µs for bench-shaped batches) and
    fall back to the per-event Python walk for regions more serial
    than _WAVEFRONT_CAP levels; ``use_walk=True`` forces the walk —
    the reference algorithm the fuzz pins the wavefront against.

    `meta` comes from resolve.wave_dependency_metadata — see there for
    the field contract; `inb_pairs` lets a caller that already built
    the in-batch finalizer write pairs (_inb_pv_write_pairs — the
    admission in tpu._plan_wave_execution needs them too) pass them
    in instead of recomputing.  Runs once per batch on the host, only
    when the wave path is a routing candidate.

    `claims` / `group_slots_fn` exist for SUBSET planning
    (plan_residue): when `meta` covers only a batch's conflicted
    residue, the chain-wave claims admission and the walk fallback's
    in-batch slot unions must still count the COMMITTED events outside
    the subset — the caller supplies full-batch claim counts and a
    full-batch group->slot-union factory, and the subset-local lazy
    builders are skipped.
    """
    chain_member = meta["chain_member"]
    id_group = meta["id_group"]
    p_group = meta["p_group"]
    p_tgt = meta["p_tgt"]
    reads0, reads1 = meta["reads0"], meta["reads1"]
    inb_pv = meta["inb_pv"]

    # Fast path for the dominant shape (fresh unique ids, no chains, no
    # finalizers, no balance readers): the whole batch is ONE wave —
    # skip level assignment entirely.  The arange test covers the
    # ascending-id encoding (tpu.py's identity grouping) without the
    # O(n log n) unique().
    if (
        not chain_member.any()
        and not inb_pv.any()
        and (reads0 < 0).all()
        and (reads1 < 0).all()
        and (p_tgt < 0).all()
        and (p_group < 0).all()
        and (
            (len(id_group) == n and id_group[0] == 0
             and bool((np.diff(id_group) == 1).all()))
            or len(np.unique(id_group)) == n
        )
    ):
        plan = WavePlan(n, segments=[("wave", np.arange(n))])
        plan.wave_mask = np.ones(n, bool)
        return plan

    inb_ev, inb_slot = (
        inb_pairs if inb_pairs is not None else _inb_pv_write_pairs(n, meta)
    )
    group_slots = None  # walk-fallback slot unions, built lazily

    plan = WavePlan(n)
    wave_mask = np.zeros(n, bool)
    segments = plan.segments

    def walk_group_slots():
        # In-batch pending references resolve to the creating event at
        # run time; statically, the finalizer may write the slots of
        # ANY event sharing that id-group, so its write set is the
        # group's slot union.
        nonlocal group_slots
        if group_slots is None:
            if group_slots_fn is not None:
                group_slots = group_slots_fn()
            else:
                group_slots = {}
                if inb_pv.any():
                    ev_dr, ev_cr = meta["ev_dr"], meta["ev_cr"]
                    for e in range(n):
                        g = int(id_group[e])
                        s = group_slots.setdefault(g, set())
                        if ev_dr[e] >= 0:
                            s.add(int(ev_dr[e]))
                        if ev_cr[e] >= 0:
                            s.add(int(ev_cr[e]))
        return group_slots

    def level_region(lo: int, hi: int) -> None:
        levels = None
        if not use_walk:
            levels = _levels_wavefront(lo, hi, meta, inb_ev, inb_slot)
        if levels is None:
            levels = _levels_walk(lo, hi, meta, walk_group_slots())
        for lvl in range(int(levels.max()) + 1 if hi > lo else 0):
            idx = lo + np.flatnonzero(levels == lvl)
            segments.append(("wave", idx))
            wave_mask[idx] = True

    i = 0
    while i < n:
        if chain_member[i]:
            j = i
            while j < n and chain_member[j]:
                j += 1
            if claims is None:
                span = int(max(id_group.max(), p_group.max())) + 1
                claims = np.bincount(id_group, minlength=span)
                pgv = p_group[p_group >= 0]
                if len(pgv):
                    claims = claims + np.bincount(pgv, minlength=span)
            steps = _chain_wave_steps(i, j, n, meta, claims)
            if steps is not None:
                segments.append(("chains", np.arange(i, j)))
                plan.chain_steps[len(segments) - 1] = steps
                wave_mask[i:j] = True
            else:
                segments.append(("scan", np.arange(i, j)))
            i = j
            continue
        j = i
        while j < n and not chain_member[j]:
            j += 1
        level_region(i, j)
        i = j

    plan.wave_mask = wave_mask
    return plan


def plan_residue(n: int, meta: dict, idx: np.ndarray) -> WavePlan:
    """Wave plan for the conflicted RESIDUE of a speculatively-executed
    batch: the level partition plan_waves builds, restricted to the
    ascending global indices `idx`, with every segment's index set in
    GLOBAL batch coordinates and `wave_mask` a (n,) global mask.

    Soundness of planning the subset in isolation: a committed
    (non-conflicted) event commutes with every residue event — a
    conflict in either direction would have blocked one of them at
    validation — so pre-applying all committed effects is sequentially
    equivalent, and only residue-internal order constraints remain.
    Two full-batch terms still leak into the subset plan and are
    supplied from the full metadata: the chain-wave admission's
    claimed-exactly-once-batch-wide counts (a committed claimant
    outside the subset must still decline the chain wave — its created
    record feeds the member's exists merge, which the chain-wave step
    does not model) and the walk fallback's in-batch finalizer slot
    unions (the committed creator's slots are part of a residue
    finalizer's static write set)."""
    idx = np.asarray(idx, np.int64)
    sub = {
        key: (val[idx] if isinstance(val, np.ndarray) else val)
        for key, val in meta.items()
    }
    inb_ev, inb_slot = _inb_pv_write_pairs(n, meta)
    if len(inb_ev):
        keep = np.isin(inb_ev, idx)
        local = np.searchsorted(idx, inb_ev[keep])
        inb_pairs = (local.astype(np.int64), inb_slot[keep])
    else:
        inb_pairs = (inb_ev, inb_slot)
    claims = None
    if sub["chain_member"].any():
        id_group, p_group = meta["id_group"], meta["p_group"]
        span = int(max(id_group.max(), p_group.max())) + 1
        claims = np.bincount(id_group, minlength=span)
        pgv = p_group[p_group >= 0]
        if len(pgv):
            claims = claims + np.bincount(pgv, minlength=span)

    def group_slots_full():
        out: dict = {}
        ev_dr, ev_cr = meta["ev_dr"], meta["ev_cr"]
        id_group = meta["id_group"]
        for e in range(n):
            s = out.setdefault(int(id_group[e]), set())
            if ev_dr[e] >= 0:
                s.add(int(ev_dr[e]))
            if ev_cr[e] >= 0:
                s.add(int(ev_cr[e]))
        return out

    local_plan = plan_waves(
        len(idx), sub, inb_pairs=inb_pairs, claims=claims,
        group_slots_fn=group_slots_full,
    )
    plan = WavePlan(len(idx))
    mask = np.zeros(n, bool)
    for k, (kind, seg) in enumerate(local_plan.segments):
        gseg = idx[np.asarray(seg)]
        plan.segments.append((kind, gseg))
        if kind == "chains":
            plan.chain_steps[len(plan.segments) - 1] = (
                local_plan.chain_steps[k]
            )
        if kind in ("wave", "chains"):
            mask[gseg] = True
    plan.wave_mask = mask
    return plan


# ---------------------------------------------------------------------------
# Overflow admission (host, against the balance mirror).


def admission_ok(
    mirror_lo: np.ndarray,
    mirror_hi: np.ndarray,
    slots: np.ndarray,
    bound_lo: np.ndarray,
    bound_hi: np.ndarray,
    extra: int = 0,
) -> bool:
    """Per-column superset overflow admission for the whole batch.

    `slots` / `bound_lo` / `bound_hi` are aligned per-CONTRIBUTION
    arrays: each (slot, bound) entry upper-bounds one balance-column
    addition the batch can make at that slot (slot < 0 entries are
    ignored; an event appears once per slot it can add through —
    dr/cr for a create, the target's slot union for a finalizer).

    True when, for every touched slot, (pre dp+dpo) + T and
    (pre cp+cpo) + T provably fit u128, where T = the slot's bound sum
    plus `extra` — a host-integer upper bound on contributions already
    in flight but not yet reflected in the mirror (the device engine's
    window pipelining; zero on the drained host path).  Then every
    per-event ov_* term is false in ANY execution order: amounts are
    non-negative, so each sequential prefix of any column (and either
    pair) is bounded by pre + all-applied additions to that slot, and
    releases only shrink it.  Per-column bounding (instead of the old
    whole-table "any nonzero hi limb declines" rule) admits u128-scale
    balances as long as their remaining headroom covers the batch —
    ROADMAP "Wave-path admission breadth".
    """
    valid = slots >= 0
    if not valid.all():
        slots = slots[valid]
        bound_lo = bound_lo[valid]
        bound_hi = bound_hi[valid]
    if len(slots) == 0:
        return True
    # float64 limb bincounts are exact below 2^53: < 2^21 entries of
    # 32-bit limbs (same bound compact_deltas relies on).
    assert len(slots) < (1 << 21)
    m32 = np.uint64(0xFFFFFFFF)
    top = int(slots.max()) + 1
    acc = [
        np.bincount(slots, limb.astype(np.float64), top).astype(np.uint64)
        for limb in (
            bound_lo & m32, bound_lo >> np.uint64(32),
            bound_hi & m32, bound_hi >> np.uint64(32),
        )
    ]
    c0, c1, c2, c3 = acc
    c1 = c1 + (c0 >> np.uint64(32))
    c2 = c2 + (c1 >> np.uint64(32))
    c3 = c3 + (c2 >> np.uint64(32))
    if ((c3 >> np.uint64(32)) != 0).any():
        return False  # one slot's bound sum alone exceeds u128
    t_lo = (c0 & m32) | ((c1 & m32) << np.uint64(32))
    t_hi = (c2 & m32) | ((c3 & m32) << np.uint64(32))
    touched = np.unique(slots)
    T_lo = t_lo[touched]
    T_hi = t_hi[touched]
    if extra:
        if extra >> 128:
            return False
        e_lo = np.uint64(extra & ((1 << 64) - 1))
        e_hi = np.uint64(extra >> 64)
        nl = T_lo + e_lo
        carry = (nl < T_lo).astype(np.uint64)
        nh = T_hi + e_hi
        ov = nh < T_hi
        nh2 = nh + carry
        if (ov | (nh2 < nh)).any():
            return False
        T_lo, T_hi = nl, nh2
    for a, b in ((0, 1), (2, 3)):
        # pre pair = column a + column b (cannot overflow u128: the
        # engine's own overflow codes maintain the pair invariant —
        # checked anyway, a corrupt mirror must decline, not admit).
        pl = mirror_lo[touched, a] + mirror_lo[touched, b]
        cy = (pl < mirror_lo[touched, a]).astype(np.uint64)
        ph_p = mirror_hi[touched, a] + mirror_hi[touched, b]
        p_ov = ph_p < mirror_hi[touched, a]
        ph = ph_p + cy
        p_ov = p_ov | (ph < ph_p)
        if p_ov.any():
            return False
        sl = pl + T_lo
        s_cy = (sl < pl).astype(np.uint64)
        sh_p = ph + T_hi
        s_ov = sh_p < ph
        s_ov = s_ov | ((sh_p + s_cy) < sh_p)
        if s_ov.any():
            return False
    return True


# ---------------------------------------------------------------------------
# The wave step: the scan body over a (K,) event axis.
#
# Table access goes through a small ops seam so ONE step body serves
# both executors: dense (single device owns the whole (A, 8) table)
# and SPMD (each device owns a row slice of the NamedSharding-sharded
# table inside shard_map — see _sharded_fns).  Everything else in the
# step is event-axis work on replicated arrays, which every device
# computes identically, so the sharded executor's outputs are
# bit-identical to the dense one's by construction.


def _apply_add_sub(table, adds, subs, localize=None):
    """table + segment-summed adds - segment-summed subs, exact u128
    per (row, column) — the ONE copy of the carry/borrow arithmetic
    both table-ops share (the sharded executor's bit-identical
    guarantee depends on it staying single-source).  Each spec is
    (slots, cols, lo, hi, valid) with slots pre-clipped into the
    GLOBAL row range; `localize` maps a spec onto this table's rows
    (identity for the dense whole table)."""
    if localize is None:
        localize = lambda spec: spec  # noqa: E731
    A = table.shape[0]
    t_lo = table[:, 0::2]
    t_hi = table[:, 1::2]
    if adds is not None:
        d_lo, d_hi = _accum_u128(*localize(adds), A)
        n_lo = t_lo + d_lo
        cy = (n_lo < t_lo).astype(jnp.uint64)
        t_lo, t_hi = n_lo, t_hi + d_hi + cy
    if subs is not None:
        s_lo, s_hi = _accum_u128(*localize(subs), A)
        n_lo = t_lo - s_lo
        bw = (t_lo < s_lo).astype(jnp.uint64)
        t_lo, t_hi = n_lo, t_hi - s_hi - bw
    return jnp.stack(
        [t_lo[:, 0], t_hi[:, 0], t_lo[:, 1], t_hi[:, 1],
         t_lo[:, 2], t_hi[:, 2], t_lo[:, 3], t_hi[:, 3]],
        axis=-1,
    )


class _DenseTableOps:
    """Whole-table access: the single-device executor's row gathers
    and u128 segment-sum applies (the pre-seam code verbatim)."""

    @staticmethod
    def nrows(table) -> int:
        return table.shape[0]

    @staticmethod
    def rows(table, slots):
        """(K,) pre-clipped global row indices -> (K, 8) rows."""
        return table[slots]

    @staticmethod
    def apply(table, adds=None, subs=None):
        return _apply_add_sub(table, adds, subs)


class _ShardTableOps:
    """Row-slice access inside a shard_map body over the 1-D ("shard",)
    mesh: reads recombine each row from its single owner
    (sharded.gather_rows — all_gather over ICI + exact sum), writes
    scatter only onto locally-owned rows (no collective at all).  Both
    resolve ownership through sharded.own_rows — the one definition of
    the row layout — and reproduce the dense per-row arithmetic
    exactly: a gathered row IS the owner's row, and a local segment
    sum over the shard's slot range equals the dense sum restricted to
    those rows."""

    def __init__(self, total_rows: int, local_rows: int) -> None:
        self.total_rows = total_rows
        self.local_rows = local_rows

    def nrows(self, table) -> int:
        return self.total_rows

    def rows(self, table, slots):
        from tigerbeetle_tpu.parallel import sharded

        return sharded.gather_rows(table, slots, self.local_rows)

    def _localize(self, spec):
        from tigerbeetle_tpu.parallel import sharded

        slots, cols, lo, hi, valid = spec
        local, rel = sharded.own_rows(slots, self.local_rows)
        return rel, cols, lo, hi, valid & local

    def apply(self, table, adds=None, subs=None):
        return _apply_add_sub(table, adds, subs, localize=self._localize)


_DENSE_OPS = _DenseTableOps()


def _accum_u128(slots_c, cols, amt_lo, amt_hi, valid, A):
    """Exact per-(slot, column) u128 sums via 32-bit-piece scatter-adds
    (duplicate slots accumulate — the segment-sum analogue of
    kernel_fast._flush_impl's unique-scatter).  Piece sums stay below
    lanes * 2^32 < 2^64, so recombination with base-2^32 carries is
    exact.  Invalid lanes contribute zero (their slot may be clip
    garbage; zero is harmless anywhere)."""
    zero = jnp.uint64(0)
    lo = jnp.where(valid, amt_lo, zero)
    hi = jnp.where(valid, amt_hi, zero)
    pieces = [
        lo & _MASK32, lo >> jnp.uint64(32),
        hi & _MASK32, hi >> jnp.uint64(32),
    ]
    acc = [
        jnp.zeros((A, 4), jnp.uint64).at[slots_c, cols].add(p)
        for p in pieces
    ]
    c0, c1, c2, c3 = acc
    c1 = c1 + (c0 >> jnp.uint64(32))
    c2 = c2 + (c1 >> jnp.uint64(32))
    c3 = c3 + (c2 >> jnp.uint64(32))
    d_lo = (c0 & _MASK32) | ((c1 & _MASK32) << jnp.uint64(32))
    d_hi = (c2 & _MASK32) | ((c3 & _MASK32) << jnp.uint64(32))
    return d_lo, d_hi


def _wave_step_impl(carry, ev, n, ts_base, ops=_DENSE_OPS, commit_mask=None):
    """Apply one wave — K mutually independent events — as a single
    vectorized step against the segment carry.

    Line-for-line port of kernel.make_body's event body with the
    (K,) axis vectorized and chain/rollback logic dropped (the
    partitioner never places chain members in waves).  Independence
    guarantees every gather sees pre-wave state equal to its
    sequential value, and the admission precondition makes every ov_*
    term false, so results and records are bit-identical to the scan.

    `ops` is the table-access seam: dense (whole table) by default,
    shard-local inside the SPMD executor — the body itself never
    indexes `carry["balances"]` directly.

    `commit_mask` (speculative executor only) deactivates lanes whose
    events failed conflict validation: a masked lane applies nothing,
    scatters nothing, and leaves its result slot untouched — exactly
    "not yet executed", so the conflicted residue replays later
    against this carry.
    """
    table = carry["balances"]
    created = carry["created"]
    group_creator = carry["group_creator"]
    B = carry["results"].shape[0]
    A = ops.nrows(table)

    i = ev["i"]  # (K,) global indices; padding lanes carry i == B
    active = i < n
    if commit_mask is not None:
        active = active & commit_mask
    flags = ev["flags"]
    is_pv = (flags & (F_POST | F_VOID)) != 0
    ts_i = ts_base + i.astype(jnp.uint64)

    # No chain terms: wave events are never chain members, so the
    # scan's chain_open/chain_broken preconditions are identically 0.
    pre = _first_nonzero((ev["ts_nonzero"], R_TIMESTAMP_MUST_BE_ZERO))
    pre = jnp.where(pre == 0, ev["static_result"], pre)

    # -- Exists resolution via the in-batch id directory.
    e_creator = group_creator[jnp.clip(ev["id_group"], 0, B - 1)]
    e_inb = e_creator >= 0
    e_dur = ev["e_found"]
    e_any = e_inb | e_dur
    e = _merge(~e_inb, _gather_created(created, e_creator, B), ev, _E_FIELD_MAP)

    # ==================== normal create_transfer ====================
    dr_row = ops.rows(table, jnp.clip(ev["dr_slot"], 0, A - 1))
    cr_row = ops.rows(table, jnp.clip(ev["cr_slot"], 0, A - 1))
    dr_dp = (dr_row[:, DP_LO], dr_row[:, DP_HI])
    dr_dpo = (dr_row[:, DPO_LO], dr_row[:, DPO_HI])
    dr_cpo = (dr_row[:, CPO_LO], dr_row[:, CPO_HI])
    cr_dpo = (cr_row[:, DPO_LO], cr_row[:, DPO_HI])
    cr_cp = (cr_row[:, CP_LO], cr_row[:, CP_HI])
    cr_cpo = (cr_row[:, CPO_LO], cr_row[:, CPO_HI])

    exists_rn = _exists_ladder_normal(ev, e)

    is_balancing = (flags & (F_BAL_DR | F_BAL_CR)) != 0
    amount = (ev["amount_lo"], ev["amount_hi"])
    amount = w.select(
        is_balancing & w.is_zero(amount),
        (jnp.full_like(amount[0], U64_MAX), jnp.zeros_like(amount[1])),
        amount,
    )
    dr_balance, _ = w.add(dr_dpo, dr_dp)
    bd_avail = w.sub_sat(dr_cpo, dr_balance)
    amount = w.select((flags & F_BAL_DR) != 0, w.minimum(amount, bd_avail), amount)
    bd_fail = ((flags & F_BAL_DR) != 0) & w.is_zero(amount)

    cr_balance, _ = w.add(cr_cpo, cr_cp)
    bc_avail = w.sub_sat(cr_dpo, cr_balance)
    amount_bc = w.minimum(amount, bc_avail)
    amount = w.select(((flags & F_BAL_CR) != 0) & ~bd_fail, amount_bc, amount)
    bc_fail = ((flags & F_BAL_CR) != 0) & w.is_zero(amount) & ~bd_fail

    is_pending = (flags & F_PENDING) != 0
    _, ov_dp = w.add(amount, dr_dp)
    _, ov_cp = w.add(amount, cr_cp)
    _, ov_dpo = w.add(amount, dr_dpo)
    _, ov_cpo = w.add(amount, cr_cpo)
    dr_total, _ = w.add(dr_dp, dr_dpo)
    _, ov_debits = w.add(amount, dr_total)
    cr_total, _ = w.add(cr_cp, cr_cpo)
    _, ov_credits = w.add(amount, cr_total)

    timeout_ns = ev["timeout"] * NS_PER_S
    ts_plus = ts_i + timeout_ns
    ov_timeout = ts_plus < ts_i

    dr_lhs, _ = w.add(dr_total, amount)
    exceeds_cr = ((ev["dr_flags"] & AF_DR_LIMIT) != 0) & w.gt(dr_lhs, dr_cpo)
    cr_lhs, _ = w.add(cr_total, amount)
    exceeds_dr = ((ev["cr_flags"] & AF_CR_LIMIT) != 0) & w.gt(cr_lhs, cr_dpo)

    rn = _first_nonzero(
        (e_any, _EXISTS_SENTINEL),
        (bd_fail, R_EXCEEDS_CREDITS),
        (bc_fail, R_EXCEEDS_DEBITS),
        (is_pending & ov_dp, R_OVERFLOWS_DP),
        (is_pending & ov_cp, R_OVERFLOWS_CP),
        (ov_dpo, R_OVERFLOWS_DPO),
        (ov_cpo, R_OVERFLOWS_CPO),
        (ov_debits, R_OVERFLOWS_DEBITS),
        (ov_credits, R_OVERFLOWS_CREDITS),
        (ov_timeout, R_OVERFLOWS_TIMEOUT),
        (exceeds_cr, R_EXCEEDS_CREDITS),
        (exceeds_dr, R_EXCEEDS_DEBITS),
    )
    rn = jnp.where(rn == _EXISTS_SENTINEL, exists_rn, rn)

    # ==================== post/void pending transfer ====================
    p_creator = group_creator[jnp.clip(ev["p_group"], 0, B - 1)]
    p_inb = (ev["p_group"] >= 0) & (p_creator >= 0)
    p_dur = ev["p_found"]
    p_any = p_dur | p_inb
    p = _merge(p_dur, _gather_created(created, p_creator, B), ev, _P_FIELD_MAP)
    p_timestamp = jnp.where(
        p_dur,
        ev["p_timestamp"],
        ts_base + jnp.clip(p_creator, 0, B - 1).astype(jnp.uint64),
    )
    p_amount = (p["amount_lo"], p["amount_hi"])

    pv_amount_raw = (ev["amount_lo"], ev["amount_hi"])
    pv_amount = w.select(w.is_zero(pv_amount_raw), p_amount, pv_amount_raw)
    is_void = (flags & F_VOID) != 0

    exists_rp = _exists_ladder_post_void(ev, e, p)

    st = jnp.where(
        p_dur,
        carry["dstat"][jnp.clip(ev["p_tgt"], 0, B - 1)],
        carry["inb_status"][jnp.clip(p_creator, 0, B - 1)],
    )

    rp_pre_insert = _first_nonzero(
        (~p_any, R_PENDING_NOT_FOUND),
        ((p["flags"] & F_PENDING) == 0, R_PENDING_NOT_PENDING),
        (~ev["dr_id_zero"] & (ev["dr_slot"] != p["dr_slot"]), R_PENDING_DIFF_DR),
        (~ev["cr_id_zero"] & (ev["cr_slot"] != p["cr_slot"]), R_PENDING_DIFF_CR),
        ((ev["ledger"] > 0) & (ev["ledger"] != p["ledger"]), R_PENDING_DIFF_LEDGER),
        ((ev["code"] > 0) & (ev["code"] != p["code"]), R_PENDING_DIFF_CODE),
        (w.gt(pv_amount, p_amount), R_EXCEEDS_PENDING_AMOUNT),
        (is_void & w.lt(pv_amount, p_amount), R_PENDING_DIFF_AMOUNT),
        (e_any, _EXISTS_SENTINEL),
        (st == S_POSTED, R_ALREADY_POSTED),
        (st == S_VOIDED, R_ALREADY_VOIDED),
        (st == kernel.S_EXPIRED, R_PENDING_EXPIRED),
    )
    rp_pre_insert = jnp.where(
        rp_pre_insert == _EXISTS_SENTINEL, exists_rp, rp_pre_insert
    )

    p_expires = p_timestamp + p["timeout"] * NS_PER_S
    overdue = (p["timeout"] > 0) & (p_expires <= ts_i)
    rp = jnp.where((rp_pre_insert == 0) & overdue, R_PENDING_EXPIRED, rp_pre_insert)

    # ==================== merge & apply ====================
    dyn_r = jnp.where(is_pv, rp, rn)
    gate = active & (pre == 0)
    r = jnp.where(gate, dyn_r, jnp.where(active, pre, 0))

    pv_inserted = gate & is_pv & (rp_pre_insert == 0)
    normal_applied = gate & ~is_pv & (rn == 0)
    pv_applied = gate & is_pv & (rp == 0)
    inserted = pv_inserted | normal_applied
    applied = pv_applied | normal_applied

    ud128_inherit = is_pv & (ev["ud128_lo"] == 0) & (ev["ud128_hi"] == 0)
    rec = {
        "flags": flags,
        "dr_slot": jnp.where(is_pv, p["dr_slot"], ev["dr_slot"]),
        "cr_slot": jnp.where(is_pv, p["cr_slot"], ev["cr_slot"]),
        "amount_lo": jnp.where(is_pv, pv_amount[0], amount[0]),
        "amount_hi": jnp.where(is_pv, pv_amount[1], amount[1]),
        "pending_lo": ev["pending_lo"],
        "pending_hi": ev["pending_hi"],
        "ud128_lo": jnp.where(ud128_inherit, p["ud128_lo"], ev["ud128_lo"]),
        "ud128_hi": jnp.where(ud128_inherit, p["ud128_hi"], ev["ud128_hi"]),
        "ud64": jnp.where(is_pv & (ev["ud64"] == 0), p["ud64"], ev["ud64"]),
        "ud32": jnp.where(is_pv & (ev["ud32"] == 0), p["ud32"], ev["ud32"]),
        "timeout": jnp.where(is_pv, jnp.uint64(0), ev["timeout"]),
        "ledger": jnp.where(is_pv, p["ledger"], ev["ledger"]),
        "code": jnp.where(is_pv, p["code"], ev["code"]),
    }

    # -- Balance effects as commuting u128 deltas, segment-summed.
    up_dr_slot = jnp.where(is_pv, p["dr_slot"], ev["dr_slot"])
    up_cr_slot = jnp.where(is_pv, p["cr_slot"], ev["cr_slot"])
    safe_dr = jnp.clip(up_dr_slot, 0, A - 1)
    safe_cr = jnp.clip(up_cr_slot, 0, A - 1)

    is_post = (flags & F_POST) != 0
    zi = jnp.zeros_like(i)
    # Add lanes: normal dr (dp|dpo), normal cr (cp|cpo), post dr dpo,
    # post cr cpo.  Sub lanes: pv release dr dp, pv release cr cp.
    add_slots = jnp.concatenate([safe_dr, safe_cr, safe_dr, safe_cr])
    add_cols = jnp.concatenate(
        [
            jnp.where(is_pending, zi, zi + 1),
            jnp.where(is_pending, zi + 2, zi + 3),
            zi + 1,
            zi + 3,
        ]
    )
    add_lo = jnp.concatenate([amount[0], amount[0], pv_amount[0], pv_amount[0]])
    add_hi = jnp.concatenate([amount[1], amount[1], pv_amount[1], pv_amount[1]])
    post_ap = pv_applied & is_post
    add_valid = jnp.concatenate(
        [normal_applied, normal_applied, post_ap, post_ap]
    )
    sub_slots = jnp.concatenate([safe_dr, safe_cr])
    sub_cols = jnp.concatenate([zi, zi + 2])
    sub_lo = jnp.concatenate([p_amount[0], p_amount[0]])
    sub_hi = jnp.concatenate([p_amount[1], p_amount[1]])
    sub_valid = jnp.concatenate([pv_applied, pv_applied])

    table = ops.apply(
        table,
        adds=(add_slots, add_cols, add_lo, add_hi, add_valid),
        subs=(sub_slots, sub_cols, sub_lo, sub_hi, sub_valid),
    )

    # -- Per-event post-apply snapshots (pre-wave row + own deltas).
    # They may miss wave-mates' commuting deltas to the same slot, but
    # wave events' snapshots only feed the mirror and are rewritten
    # with batch finals at finalize (history-account events, whose
    # snapshots are semantically read, never ride waves).
    o_dr = ops.rows(carry["balances"], safe_dr)
    o_cr = ops.rows(carry["balances"], safe_cr)
    o_dr_dp = (o_dr[:, DP_LO], o_dr[:, DP_HI])
    o_dr_dpo = (o_dr[:, DPO_LO], o_dr[:, DPO_HI])
    o_cr_cp = (o_cr[:, CP_LO], o_cr[:, CP_HI])
    o_cr_cpo = (o_cr[:, CPO_LO], o_cr[:, CPO_HI])
    n_dr_dp = w.select(
        is_pv,
        w.sub(o_dr_dp, p_amount)[0],
        w.select(is_pending, w.add(o_dr_dp, amount)[0], o_dr_dp),
    )
    n_dr_dpo = w.select(
        is_pv,
        w.select(is_post, w.add(o_dr_dpo, pv_amount)[0], o_dr_dpo),
        w.select(is_pending, o_dr_dpo, w.add(o_dr_dpo, amount)[0]),
    )
    n_cr_cp = w.select(
        is_pv,
        w.sub(o_cr_cp, p_amount)[0],
        w.select(is_pending, w.add(o_cr_cp, amount)[0], o_cr_cp),
    )
    n_cr_cpo = w.select(
        is_pv,
        w.select(is_post, w.add(o_cr_cpo, pv_amount)[0], o_cr_cpo),
        w.select(is_pending, o_cr_cpo, w.add(o_cr_cpo, amount)[0]),
    )
    new_dr_row = jnp.stack(
        [n_dr_dp[0], n_dr_dp[1], n_dr_dpo[0], n_dr_dpo[1],
         o_dr[:, CP_LO], o_dr[:, CP_HI], o_dr[:, CPO_LO], o_dr[:, CPO_HI]],
        axis=-1,
    )
    new_cr_row = jnp.stack(
        [o_cr[:, DP_LO], o_cr[:, DP_HI], o_cr[:, DPO_LO], o_cr[:, DPO_HI],
         n_cr_cp[0], n_cr_cp[1], n_cr_cpo[0], n_cr_cpo[1]],
        axis=-1,
    )

    # -- Scatter per-event state at own (unique) global indices; OOB
    # padding lanes drop.
    idx_i = jnp.where(active, i, B)
    idx_ins = jnp.where(inserted, i, B)
    created = {
        f: created[f]
        .at[idx_ins]
        .set(rec[f].astype(created[f].dtype), mode="drop")
        for f in CREATED_FIELDS
    }
    created_mask = carry["created_mask"].at[idx_i].set(inserted, mode="drop")
    gidx = jnp.where(inserted, jnp.clip(ev["id_group"], 0, B - 1), B)
    group_creator = group_creator.at[gidx].set(i, mode="drop")

    inb_status = carry["inb_status"].at[idx_i].set(
        jnp.where(normal_applied & is_pending, jnp.uint32(S_PENDING), 0),
        mode="drop",
    )
    new_status = jnp.where(is_post, jnp.uint32(S_POSTED), jnp.uint32(S_VOIDED))
    idx_t = jnp.where(pv_applied & p_dur, jnp.clip(ev["p_tgt"], 0, B - 1), B)
    dstat = carry["dstat"].at[idx_t].set(new_status, mode="drop")
    idx_pc = jnp.where(pv_applied & ~p_dur, jnp.clip(p_creator, 0, B - 1), B)
    inb_status = inb_status.at[idx_pc].set(new_status, mode="drop")

    hist_dr = carry["hist_dr"].at[idx_i].set(new_dr_row, mode="drop")
    hist_cr = carry["hist_cr"].at[idx_i].set(new_cr_row, mode="drop")
    results = carry["results"].at[idx_i].set(r, mode="drop")

    last_applied = jnp.maximum(
        carry["last_applied"], jnp.where(applied, i, -1).max()
    )
    pulse_create = carry["pulse_create"].at[idx_i].set(
        jnp.where(
            normal_applied & is_pending & (ev["timeout"] > 0),
            ts_i + timeout_ns,
            jnp.uint64(0),
        ),
        mode="drop",
    )
    pulse_remove = carry["pulse_remove"].at[idx_i].set(
        jnp.where(pv_applied & (p["timeout"] > 0), p_expires, jnp.uint64(0)),
        mode="drop",
    )

    return dict(
        carry,
        balances=table,
        results=results,
        created_mask=created_mask,
        created=created,
        group_creator=group_creator,
        inb_status=inb_status,
        dstat=dstat,
        hist_dr=hist_dr,
        hist_cr=hist_cr,
        last_applied=last_applied,
        pulse_create=pulse_create,
        pulse_remove=pulse_remove,
    )


_wave_step = jax.jit(_wave_step_impl, donate_argnums=(0,))
# Non-donating twin for the device engine's window launch: the engine
# passes its AUTHORITATIVE table handle into the executor and must be
# able to retry the whole batch from that same handle after a
# transient link fault — donation would invalidate it mid-flight.
_wave_step_keep = jax.jit(_wave_step_impl)


# ---------------------------------------------------------------------------
# Chain-wave step: a contiguous run of mutually independent linked
# chains executed as ONE lax.scan over chain POSITION — step p applies
# the p-th member of every chain as a vectorized lane batch (the
# device linked kernel's fixpoint shape), so a chain-dominated region
# costs ~max_chain_len device steps instead of one per member.


def _chain_wave_impl(carry, ev, n, ts_base, ops=_DENSE_OPS):
    """Execute one "chains" segment against the segment carry.

    `ev` is a dict of (P, C) stacked event arrays — position-major,
    one lane per chain, padding lanes carrying i == B — plus a
    ``chain_open`` bool plane (linked flag on the batch's last event).
    Admission (waves._chain_wave_steps) guarantees: plain creates only
    (no post/void), no history accounts, id-groups claimed exactly
    once batch-wide, and pairwise chain independence over balance
    slots — so each lane's gathers see exactly its own chain's prior
    effects plus commuting cross-chain adds to UNREAD slots, and the
    per-position body below (the _wave_step normal-create path plus
    the scan's chain machinery) reproduces the sequential scan's
    results bit-for-bit.  Chain failure semantics match make_body:
    the failing member keeps its own code, every other member reports
    linked_event_failed (chain_open on an open tail), applied members'
    balance effects are rolled back by an exact trailing subtraction,
    and — like the reference's unscoped pulse bookkeeping —
    pulse_create signals recorded at apply time survive the rollback
    while created_mask/inb_status/group_creator registrations do not.
    """
    B = carry["results"].shape[0]
    A = ops.nrows(carry["balances"])
    C = ev["i"].shape[1]

    def step(state, ev_p):
        cr, alive = state
        table = cr["balances"]
        created = cr["created"]
        group_creator = cr["group_creator"]
        i = ev_p["i"]
        active = i < n
        flags = ev_p["flags"]
        ts_i = ts_base + i.astype(jnp.uint64)

        pre = _first_nonzero(
            (ev_p["chain_open"], kernel.R_LINKED_EVENT_CHAIN_OPEN),
            (~alive, kernel.R_LINKED_EVENT_FAILED),
            (ev_p["ts_nonzero"], R_TIMESTAMP_MUST_BE_ZERO),
        )
        pre = jnp.where(pre == 0, ev_p["static_result"], pre)

        # Exists: id-groups are claimed exactly once batch-wide, so
        # only the durable duplicate can exist — no in-batch creator.
        e_any = ev_p["e_found"]
        e = {
            f: ev_p[nm].astype(created[f].dtype)
            for f, nm in _E_FIELD_MAP.items()
        }
        exists_rn = _exists_ladder_normal(ev_p, e)

        dr_row = ops.rows(table, jnp.clip(ev_p["dr_slot"], 0, A - 1))
        cr_row = ops.rows(table, jnp.clip(ev_p["cr_slot"], 0, A - 1))
        dr_dp = (dr_row[:, DP_LO], dr_row[:, DP_HI])
        dr_dpo = (dr_row[:, DPO_LO], dr_row[:, DPO_HI])
        dr_cpo = (dr_row[:, CPO_LO], dr_row[:, CPO_HI])
        cr_dpo = (cr_row[:, DPO_LO], cr_row[:, DPO_HI])
        cr_cp = (cr_row[:, CP_LO], cr_row[:, CP_HI])
        cr_cpo = (cr_row[:, CPO_LO], cr_row[:, CPO_HI])

        is_balancing = (flags & (F_BAL_DR | F_BAL_CR)) != 0
        amount = (ev_p["amount_lo"], ev_p["amount_hi"])
        amount = w.select(
            is_balancing & w.is_zero(amount),
            (jnp.full_like(amount[0], U64_MAX), jnp.zeros_like(amount[1])),
            amount,
        )
        dr_balance, _ = w.add(dr_dpo, dr_dp)
        bd_avail = w.sub_sat(dr_cpo, dr_balance)
        amount = w.select(
            (flags & F_BAL_DR) != 0, w.minimum(amount, bd_avail), amount
        )
        bd_fail = ((flags & F_BAL_DR) != 0) & w.is_zero(amount)
        cr_balance, _ = w.add(cr_cpo, cr_cp)
        bc_avail = w.sub_sat(cr_dpo, cr_balance)
        amount_bc = w.minimum(amount, bc_avail)
        amount = w.select(
            ((flags & F_BAL_CR) != 0) & ~bd_fail, amount_bc, amount
        )
        bc_fail = ((flags & F_BAL_CR) != 0) & w.is_zero(amount) & ~bd_fail

        is_pending = (flags & F_PENDING) != 0
        _, ov_dp = w.add(amount, dr_dp)
        _, ov_cp = w.add(amount, cr_cp)
        _, ov_dpo = w.add(amount, dr_dpo)
        _, ov_cpo = w.add(amount, cr_cpo)
        dr_total, _ = w.add(dr_dp, dr_dpo)
        _, ov_debits = w.add(amount, dr_total)
        cr_total, _ = w.add(cr_cp, cr_cpo)
        _, ov_credits = w.add(amount, cr_total)
        timeout_ns = ev_p["timeout"] * NS_PER_S
        ts_plus = ts_i + timeout_ns
        ov_timeout = ts_plus < ts_i
        dr_lhs, _ = w.add(dr_total, amount)
        exceeds_cr = ((ev_p["dr_flags"] & AF_DR_LIMIT) != 0) & w.gt(
            dr_lhs, dr_cpo
        )
        cr_lhs, _ = w.add(cr_total, amount)
        exceeds_dr = ((ev_p["cr_flags"] & AF_CR_LIMIT) != 0) & w.gt(
            cr_lhs, cr_dpo
        )

        rn = _first_nonzero(
            (e_any, _EXISTS_SENTINEL),
            (bd_fail, R_EXCEEDS_CREDITS),
            (bc_fail, R_EXCEEDS_DEBITS),
            (is_pending & ov_dp, R_OVERFLOWS_DP),
            (is_pending & ov_cp, R_OVERFLOWS_CP),
            (ov_dpo, R_OVERFLOWS_DPO),
            (ov_cpo, R_OVERFLOWS_CPO),
            (ov_debits, R_OVERFLOWS_DEBITS),
            (ov_credits, R_OVERFLOWS_CREDITS),
            (ov_timeout, R_OVERFLOWS_TIMEOUT),
            (exceeds_cr, R_EXCEEDS_CREDITS),
            (exceeds_dr, R_EXCEEDS_DEBITS),
        )
        rn = jnp.where(rn == _EXISTS_SENTINEL, exists_rn, rn)

        gate = active & (pre == 0)
        r = jnp.where(gate, rn, jnp.where(active, pre, 0))
        applied = gate & (rn == 0)
        fail = active & alive & (r != 0)
        alive = alive & ~fail

        # -- Balance adds (segment-summed; pairwise independence makes
        # same-slot duplicates commuting cross-chain adds).
        safe_dr = jnp.clip(ev_p["dr_slot"], 0, A - 1)
        safe_cr = jnp.clip(ev_p["cr_slot"], 0, A - 1)
        zi = jnp.zeros_like(i)
        add_slots = jnp.concatenate([safe_dr, safe_cr])
        add_cols = jnp.concatenate(
            [
                jnp.where(is_pending, zi, zi + 1),
                jnp.where(is_pending, zi + 2, zi + 3),
            ]
        )
        add_lo = jnp.concatenate([amount[0]] * 2)
        add_hi = jnp.concatenate([amount[1]] * 2)
        valid = jnp.concatenate([applied, applied])
        new_table = ops.apply(
            table, adds=(add_slots, add_cols, add_lo, add_hi, valid)
        )

        # -- Snapshots (pre-row + own delta; rewritten to batch finals
        # at finalize for surviving members, unused for failed ones).
        n_dr_dp = w.select(is_pending, w.add(dr_dp, amount)[0], dr_dp)
        n_dr_dpo = w.select(is_pending, dr_dpo, w.add(dr_dpo, amount)[0])
        n_cr_cp = w.select(is_pending, w.add(cr_cp, amount)[0], cr_cp)
        n_cr_cpo = w.select(is_pending, cr_cpo, w.add(cr_cpo, amount)[0])
        new_dr_row = jnp.stack(
            [n_dr_dp[0], n_dr_dp[1], n_dr_dpo[0], n_dr_dpo[1],
             dr_row[:, CP_LO], dr_row[:, CP_HI],
             dr_row[:, CPO_LO], dr_row[:, CPO_HI]],
            axis=-1,
        )
        new_cr_row = jnp.stack(
            [cr_row[:, DP_LO], cr_row[:, DP_HI],
             cr_row[:, DPO_LO], cr_row[:, DPO_HI],
             n_cr_cp[0], n_cr_cp[1], n_cr_cpo[0], n_cr_cpo[1]],
            axis=-1,
        )

        rec = {
            "flags": flags,
            "dr_slot": ev_p["dr_slot"],
            "cr_slot": ev_p["cr_slot"],
            "amount_lo": amount[0],
            "amount_hi": amount[1],
            "pending_lo": ev_p["pending_lo"],
            "pending_hi": ev_p["pending_hi"],
            "ud128_lo": ev_p["ud128_lo"],
            "ud128_hi": ev_p["ud128_hi"],
            "ud64": ev_p["ud64"],
            "ud32": ev_p["ud32"],
            "timeout": ev_p["timeout"],
            "ledger": ev_p["ledger"],
            "code": ev_p["code"],
        }
        idx_i = jnp.where(active, i, B)
        idx_ins = jnp.where(applied, i, B)
        created = {
            f: created[f]
            .at[idx_ins]
            .set(rec[f].astype(created[f].dtype), mode="drop")
            for f in CREATED_FIELDS
        }
        created_mask = cr["created_mask"].at[idx_i].set(applied, mode="drop")
        gidx = jnp.where(applied, jnp.clip(ev_p["id_group"], 0, B - 1), B)
        group_creator = group_creator.at[gidx].set(i, mode="drop")
        inb_status = cr["inb_status"].at[idx_i].set(
            jnp.where(applied & is_pending, jnp.uint32(S_PENDING), 0),
            mode="drop",
        )
        hist_dr = cr["hist_dr"].at[idx_i].set(new_dr_row, mode="drop")
        hist_cr = cr["hist_cr"].at[idx_i].set(new_cr_row, mode="drop")
        results = cr["results"].at[idx_i].set(r, mode="drop")
        last_applied = jnp.maximum(
            cr["last_applied"], jnp.where(applied, i, -1).max()
        )
        pulse_create = cr["pulse_create"].at[idx_i].set(
            jnp.where(
                applied & is_pending & (ev_p["timeout"] > 0),
                ts_i + timeout_ns,
                jnp.uint64(0),
            ),
            mode="drop",
        )

        cr = dict(
            cr,
            balances=new_table,
            results=results,
            created_mask=created_mask,
            created=created,
            group_creator=group_creator,
            inb_status=inb_status,
            hist_dr=hist_dr,
            hist_cr=hist_cr,
            last_applied=last_applied,
            pulse_create=pulse_create,
        )
        ys = (
            i, r, applied, safe_dr, safe_cr,
            amount[0], amount[1], is_pending,
            jnp.clip(ev_p["id_group"], 0, B - 1),
        )
        return (cr, alive), ys

    alive0 = jnp.ones(C, bool)
    (carry, alive), ys = jax.lax.scan(step, (carry, alive0), ev)
    (ys_i, ys_r, ys_ap, ys_dr, ys_cr,
     ys_alo, ys_ahi, ys_pend, ys_g) = ys

    # -- Chain-failure repair: exact rollback subtraction of every
    # applied member of a failed chain, result/registration rewrite.
    dead = ~alive
    rb = ys_ap & dead[None, :]
    flat = lambda a: a.reshape(-1)  # noqa: E731
    zi = jnp.zeros_like(flat(ys_i))
    sub_slots = jnp.concatenate([flat(ys_dr), flat(ys_cr)])
    pend_f = flat(ys_pend)
    sub_cols = jnp.concatenate(
        [jnp.where(pend_f, zi, zi + 1), jnp.where(pend_f, zi + 2, zi + 3)]
    )
    sub_lo = jnp.concatenate([flat(ys_alo)] * 2)
    sub_hi = jnp.concatenate([flat(ys_ahi)] * 2)
    sub_valid = jnp.concatenate([flat(rb)] * 2)
    table = ops.apply(
        carry["balances"],
        subs=(sub_slots, sub_cols, sub_lo, sub_hi, sub_valid),
    )
    fix = (ys_r == 0) & dead[None, :] & (ys_i < n)
    idxf = jnp.where(fix, ys_i, B).reshape(-1)
    results = carry["results"].at[idxf].set(
        jnp.uint32(kernel.R_LINKED_EVENT_FAILED), mode="drop"
    )
    created_mask = carry["created_mask"].at[idxf].set(False, mode="drop")
    inb_status = carry["inb_status"].at[idxf].set(
        jnp.uint32(0), mode="drop"
    )
    gidxf = jnp.where(fix, ys_g, B).reshape(-1)
    group_creator = carry["group_creator"].at[gidxf].set(
        jnp.int32(-1), mode="drop"
    )
    return dict(
        carry,
        balances=table,
        results=results,
        created_mask=created_mask,
        inb_status=inb_status,
        group_creator=group_creator,
    )


_chain_step = jax.jit(_chain_wave_impl, donate_argnums=(0,))
_chain_step_keep = jax.jit(_chain_wave_impl)


@functools.partial(jax.jit, donate_argnums=(0,))
def _init_carry(balances, dstat_init):
    return kernel.make_carry(balances, dstat_init, dstat_init.shape[0])


@jax.jit
def _init_carry_keep(balances, dstat_init):
    return kernel.make_carry(balances, dstat_init, dstat_init.shape[0])


def _finalize_body(carry, hist_fix, ops=_DENSE_OPS):
    """Pack outputs; rewrite wave events' balance snapshots with the
    BATCH-FINAL rows of their touched slots so the host's last-write-
    wins mirror reconstruction lands on exact finals (a wave event's
    own snapshot misses wave-mates' commuting deltas to the same slot,
    and a chain-wave member cross-chain commuting adds).  `hist_fix`
    is the wave mask (wave + chain-wave events): scan-segment events
    keep their sequential snapshots — history-account events always
    run there, so the history groove only ever sees sequential-exact
    rows."""
    table = carry["balances"]
    A = ops.nrows(table)
    fix = hist_fix & (carry["results"] == 0)
    dr = jnp.clip(carry["created"]["dr_slot"], 0, A - 1)
    cr = jnp.clip(carry["created"]["cr_slot"], 0, A - 1)
    hist_dr = jnp.where(fix[:, None], ops.rows(table, dr), carry["hist_dr"])
    hist_cr = jnp.where(fix[:, None], ops.rows(table, cr), carry["hist_cr"])
    return kernel.finalize_outputs(
        dict(carry, hist_dr=hist_dr, hist_cr=hist_cr)
    )


_finalize_impl = jax.jit(_finalize_body, donate_argnums=(0,))
_finalize_keep = jax.jit(_finalize_body)


# ---------------------------------------------------------------------------
# SPMD executors: the SAME step bodies run inside shard_map over the
# device engine's 1-D ("shard",) row mesh, so a row-sharded multi-chip
# engine executes wave plans in place instead of declining to the host
# drain.  The balance table stays a NamedSharding row slice per device
# end to end; per-step cross-shard row reads recombine over ICI
# (sharded.gather_rows), scatters land only on locally-owned rows, and
# every event-axis output (results, records, snapshots, packed matrix)
# is computed replicated — identically on every device — so admission
# and packed outputs agree across the mesh by determinism, and the
# whole pipeline is bit-identical to the dense executor (enforced by
# the sharded differential fuzz in tests/test_device_waves.py).


def plan_shardable(plan: WavePlan) -> bool:
    """True when every segment has an SPMD executor: "wave" and
    "chains" do; "scan" segments (kernel.make_body's sequential
    machinery) keep single-device scope — a sharded engine declines
    such plans gracefully and drains to the host instead."""
    return all(kind in ("wave", "chains") for kind, _ in plan.segments)


@jax.jit
def _make_rest(dstat_init):
    """The segment carry MINUS the balance table (which the sharded
    executors thread separately, under its own partition spec)."""
    carry = kernel.make_carry(
        jnp.zeros((1, 8), jnp.uint64), dstat_init, dstat_init.shape[0]
    )
    carry.pop("balances")
    return carry


_SHARDED_FNS: dict = {}


def _sharded_fns(mesh, total_rows: int):
    """(wave, chain, finalize) shard_map-wrapped jits for one
    (mesh, table geometry) — cached: the wrappers are shape-polymorphic
    via jit retracing, but the mesh closure is fixed."""
    key = (mesh, total_rows)
    hit = _SHARDED_FNS.get(key)
    if hit is not None:
        return hit
    from jax.sharding import PartitionSpec as P

    from tigerbeetle_tpu.parallel import sharded
    from tigerbeetle_tpu.parallel.sharded import shard_map

    n_shard = mesh.shape["shard"]
    assert total_rows % n_shard == 0, (total_rows, n_shard)
    ops = _ShardTableOps(total_rows, total_rows // n_shard)
    kw = sharded.shard_map_kwargs()
    t_spec = P("shard", None)

    def wave_body(table, rest, ev, n, ts_base):
        out = _wave_step_impl(
            dict(rest, balances=table), ev, n, ts_base, ops=ops
        )
        return out.pop("balances"), out

    def chain_body(table, rest, ev, n, ts_base):
        out = _chain_wave_impl(
            dict(rest, balances=table), ev, n, ts_base, ops=ops
        )
        return out.pop("balances"), out

    def fin_body(table, rest, hist_fix):
        return _finalize_body(
            dict(rest, balances=table), hist_fix, ops=ops
        )

    def wrap(body, n_rep_args):
        return jax.jit(
            shard_map(
                body,
                mesh=mesh,
                in_specs=(t_spec,) + (P(),) * n_rep_args,
                out_specs=(t_spec, P()),
                **kw,
            )
        )

    fns = (wrap(wave_body, 4), wrap(chain_body, 4), wrap(fin_body, 2))
    _SHARDED_FNS[key] = fns
    return fns


def _execute_plan_sharded(
    balances, ev: dict, dstat_init, n: int, ts_base: int, plan: WavePlan,
    hist_fix: np.ndarray, mesh,
):
    """Segment loop over the SPMD executors; the caller proved
    plan_shardable(plan).  Never donates — the engine retries from the
    same authoritative handle after transient link faults, exactly
    like the dense engine path."""
    B = ev["flags"].shape[0]
    wave, chain, fin = _sharded_fns(mesh, balances.shape[0])
    rest = _make_rest(jnp.asarray(np.asarray(dstat_init), jnp.uint32))
    table = balances
    n_j = jnp.int32(n)
    ts_j = jnp.uint64(ts_base)
    for k, (seg_kind, idx) in enumerate(plan.segments):
        if seg_kind == "chains":
            ev_seg = _gather_chain_events(
                ev, idx, plan.chain_steps[k], n, B
            )
            table, rest = chain(table, rest, ev_seg, n_j, ts_j)
            continue
        assert seg_kind == "wave", (
            "scan segments have no SPMD executor (plan_shardable)"
        )
        K = _bucket(len(idx))
        ev_seg = _gather_events(ev, idx, K, B)
        table, rest = wave(table, rest, ev_seg, n_j, ts_j)
    return fin(table, rest, jnp.asarray(hist_fix))


def _bucket(k: int) -> int:
    for b in _SEG_BUCKETS:
        if b >= k:
            return b
    return k


def _bucket_positions(p: int) -> int:
    """Chain-wave position bucket (compile cache key): the next power
    of two >= max chain length, floored at 8 — padding positions carry
    inactive lanes, so a coarse bucket costs compute, not correctness,
    and keeps the (P, C) compile-cache tractable."""
    b = 8
    while b < p:
        b *= 2
    return b


def _gather_events(ev: dict, idx: np.ndarray, K: int, B: int) -> dict:
    """Padded (K,) device gather of the host event arrays at batch
    indices `idx` (ascending, possibly non-contiguous for waves);
    padding lanes get i == B (inactive, and every per-event scatter
    drops OOB)."""
    k = len(idx)
    out = {}
    for name, arr in ev.items():
        buf = np.zeros(K, arr.dtype)
        buf[:k] = arr[idx]
        if name == "i":
            buf[k:] = B
        out[name] = jnp.asarray(buf)
    return out


# Event fields the chain-wave step consumes (the post/void join
# columns never ride a "chains" segment — smaller stacked xs).
_CHAIN_EV_FIELDS = (
    "i", "flags", "ts_nonzero", "static_result",
    "amount_lo", "amount_hi", "pending_lo", "pending_hi",
    "ud128_lo", "ud128_hi", "ud64", "ud32", "timeout", "ledger", "code",
    "dr_slot", "cr_slot", "dr_flags", "cr_flags", "id_group",
    "e_found", "e_flags", "e_dr_slot", "e_cr_slot",
    "e_amount_lo", "e_amount_hi", "e_pending_lo", "e_pending_hi",
    "e_ud128_lo", "e_ud128_hi", "e_ud64", "e_ud32", "e_timeout",
    "e_code",
)


def _gather_chain_events(
    ev: dict, idx: np.ndarray, P: int, n: int, B: int
) -> dict:
    """Stack a chain run's events position-major: (P, C) planes, one
    lane per chain, padding cells carrying i == B (inactive).  Chain
    boundaries re-derive from the linked flags, so the executor and
    the partitioner can never disagree on the layout."""
    flags = ev["flags"][idx]
    linked = (flags & F_LINKED) != 0
    m = len(idx)
    starts = np.empty(m, bool)
    starts[0] = True
    starts[1:] = ~linked[:-1]
    chain_rel = np.cumsum(starts) - 1
    pos = np.arange(m) - np.flatnonzero(starts)[chain_rel]
    C = _bucket(int(chain_rel[-1]) + 1)
    assert int(pos.max()) < P, "chain run exceeds its position bucket"
    mat = np.full((P, C), B, np.int64)
    mat[pos, chain_rel] = idx
    out = {}
    for name in _CHAIN_EV_FIELDS:
        arr = ev[name]
        if name == "i":
            out[name] = jnp.asarray(mat.astype(np.int32))
            continue
        src = np.concatenate([arr, np.zeros(1, arr.dtype)])
        out[name] = jnp.asarray(src[np.minimum(mat, len(arr))])
    open_np = np.zeros((P, C), bool)
    open_np[pos, chain_rel] = linked & (idx == n - 1)
    out["chain_open"] = jnp.asarray(open_np)
    return out


def _execute_plan(
    balances, ev: dict, dstat_init, n: int, ts_base: int, plan: WavePlan,
    hist_fix: np.ndarray, donate: bool,
):
    """Run a batch by the plan's segments in order; returns
    (new_balances, packed outputs) — identical contract to
    kernel.run_create_transfers."""
    B = ev["flags"].shape[0]
    init = _init_carry if donate else _init_carry_keep
    step = _wave_step if donate else _wave_step_keep
    chain = _chain_step if donate else _chain_step_keep
    scan = kernel.scan_segment if donate else kernel.scan_segment_keep
    fin = _finalize_impl if donate else _finalize_keep
    carry = init(balances, jnp.asarray(np.asarray(dstat_init), jnp.uint32))
    id_group_full = jnp.asarray(ev["id_group"])
    n_j = jnp.int32(n)
    ts_j = jnp.uint64(ts_base)
    for k, (seg_kind, idx) in enumerate(plan.segments):
        if seg_kind == "chains":
            ev_seg = _gather_chain_events(
                ev, idx, plan.chain_steps[k], n, B
            )
            carry = chain(carry, ev_seg, n_j, ts_j)
            continue
        K = _bucket(len(idx))
        ev_seg = _gather_events(ev, idx, K, B)
        if seg_kind == "wave":
            carry = step(carry, ev_seg, n_j, ts_j)
        else:
            carry = scan(carry, ev_seg, id_group_full, n_j, ts_j)
    return fin(carry, jnp.asarray(hist_fix))


def run_create_transfers_waves(
    balances, ev: dict, dstat_init, n: int, ts_base: int, plan: WavePlan,
    hist_fix: np.ndarray,
):
    """Execute a batch by the wave plan; same contract and bit-exact
    same outputs as kernel.run_create_transfers.

    `ev` is the HOST-side dict of (B,) numpy arrays per
    kernel.EVENT_FIELDS; `hist_fix` is a (B,) bool mask of events whose
    snapshots should be rewritten with batch finals (wave and
    chain-wave events off history accounts).  The input `balances`
    buffer is DONATED (host exact path: the caller replaces its
    handle).
    """
    return _execute_plan(
        balances, ev, dstat_init, n, ts_base, plan, hist_fix, donate=True
    )


def run_plan_engine(
    balances, ev: dict, dstat_init, n: int, ts_base: int, plan: WavePlan,
    hist_fix: np.ndarray, mesh=None,
):
    """Device-engine entry: execute a window batch's wave plan against
    the AUTHORITATIVE table handle without donating any caller buffer
    — the engine must be able to retry the whole batch from the same
    handle after a transient link fault, and its `self.balances` stays
    valid if execution dies partway (demotion re-uploads from the
    mirror regardless).  Returns (new_balances, packed outputs).

    `mesh` routes a ROW-SHARDED engine's plan through the SPMD
    executors (shard_map over the 1-D "shard" axis): the new balances
    come back under the same NamedSharding row partition the engine
    placed them with, and the packed outputs are replicated.  The
    caller must have checked plan_shardable(plan) first."""
    if mesh is not None:
        return _execute_plan_sharded(
            balances, ev, dstat_init, n, ts_base, plan, hist_fix, mesh
        )
    return _execute_plan(
        balances, ev, dstat_init, n, ts_base, plan, hist_fix, donate=False
    )


# ---------------------------------------------------------------------------
# Optimistic (speculative) execution — round 18.  Invert the wave
# pipeline's order for low-contention batches (the Reddio parallel-EVM
# recipe, arXiv:2503.04595): execute the ENTIRE batch as ONE
# speculative wave step against the authoritative table, detect
# read-write/write-write conflicts ON DEVICE with segmented-min passes
# over the same conflict tokens the partitioner levels by, commit the
# validated events, and replay only the conflicted residue through a
# plan_waves subset plan.  The partitioner leaves the hot path
# entirely: plan only on validation failure.
#
# The PREFIX-COMMIT rule (the subtle part): an event's speculative
# result is committable iff NO earlier event in the batch conflicts
# with it — the wavefront's round-0 unblocked test.  Its gathers then
# saw exactly the sequential pre-state (nothing it depends on ran
# before it), and committable events are pairwise non-conflicting (a
# conflict between two of them would have blocked the later one), so
# committing them as one wave is the wave executor's own exactness
# argument.  An event that merely FOLLOWS a conflicted event commits
# fine when they don't conflict — commuting adds reorder freely — so
# the residue is the conflicted set itself, not a positional suffix.
# The step is NON-DONATING: on validation failure nothing about the
# authoritative handle changed, so "rollback" of the un-committed
# lanes is a no-op by construction (their applies were masked out, not
# undone).


def _spec_conflicts(ev: dict, spec_serial, n, A: int, B: int):
    """Per-lane conflict flags for one speculative step — the
    wavefront's round-0 blocked test (_levels_wavefront) computed on
    device from the event columns alone:

    - serial tokens: only the minimum-index claimant of an id/pending
      group or a durable first-wins target is unblocked;
    - balance slots: a reader is unblocked only as the minimum-index
      toucher of its slot, a writer only when no earlier reader
      touches it (commuting writers share);
    - `spec_serial` force-conflicts events the wave step does not
      model (chain members, history-account events, serialized
      post/voids) — they always replay through the residue plan.

    The in-batch finalizer's WIDENED write set (its target group's
    slot union) needs no entries here: the finalizer shares its
    p_group token with any in-batch creator, so whenever the widened
    writes could matter the finalizer is already blocked, and a
    committed finalizer provably applied nothing to those slots (its
    reference was durable or unresolved).
    """
    i = ev["i"]
    active = i < n
    big = jnp.int32(B)
    flags = ev["flags"]
    is_pv = (flags & (F_POST | F_VOID)) != 0

    # Serial tokens, namespace 1: id-value groups (id_group claims +
    # post/void pending-reference claims share the group space).
    idg = jnp.clip(ev["id_group"], 0, B - 1)
    pg = ev["p_group"]
    pgm = active & (pg >= 0)
    pgc = jnp.clip(pg, 0, B - 1)
    tok_min = jnp.full(B + 1, big, jnp.int32)
    tok_min = tok_min.at[jnp.where(active, idg, B)].min(i)
    tok_min = tok_min.at[jnp.where(pgm, pgc, B)].min(i)
    blk = active & (i > tok_min[idg])
    blk = blk | (pgm & (i > tok_min[pgc]))
    # Namespace 2: durable first-wins finalize targets.
    pt = ev["p_tgt"]
    ptm = active & (pt >= 0)
    ptc = jnp.clip(pt, 0, B - 1)
    pt_min = jnp.full(B + 1, big, jnp.int32).at[
        jnp.where(ptm, ptc, B)
    ].min(i)
    blk = blk | (ptm & (i > pt_min[ptc]))

    # Balance-slot entries (the metadata contract of
    # resolve.wave_dependency_metadata, recomputed from the same
    # columns): reads = balancing clamps + limit checks on own
    # accounts; writes = own dr/cr for creates, the durable target's
    # accounts for found finalizers.
    dr_slot = ev["dr_slot"]
    cr_slot = ev["cr_slot"]
    read_dr = (
        active & ~is_pv & (dr_slot >= 0)
        & (((flags & F_BAL_DR) != 0)
           | ((ev["dr_flags"] & AF_DR_LIMIT) != 0))
    )
    read_cr = (
        active & ~is_pv & (cr_slot >= 0)
        & (((flags & F_BAL_CR) != 0)
           | ((ev["cr_flags"] & AF_CR_LIMIT) != 0))
    )
    pf = ev["p_found"]
    neg = jnp.int32(-1)
    w0 = jnp.where(is_pv, jnp.where(pf, ev["p_dr_slot"], neg), dr_slot)
    w1 = jnp.where(is_pv, jnp.where(pf, ev["p_cr_slot"], neg), cr_slot)
    wm0 = active & (w0 >= 0)
    wm1 = active & (w1 >= 0)
    dr_c = jnp.clip(dr_slot, 0, A - 1)
    cr_c = jnp.clip(cr_slot, 0, A - 1)
    w0_c = jnp.clip(w0, 0, A - 1)
    w1_c = jnp.clip(w1, 0, A - 1)
    a_min = (
        jnp.full(A + 1, big, jnp.int32)
        .at[jnp.where(read_dr, dr_c, A)].min(i)
        .at[jnp.where(read_cr, cr_c, A)].min(i)
        .at[jnp.where(wm0, w0_c, A)].min(i)
        .at[jnp.where(wm1, w1_c, A)].min(i)
    )
    r_min = (
        jnp.full(A + 1, big, jnp.int32)
        .at[jnp.where(read_dr, dr_c, A)].min(i)
        .at[jnp.where(read_cr, cr_c, A)].min(i)
    )
    blk = blk | (read_dr & (i > a_min[dr_c]))
    blk = blk | (read_cr & (i > a_min[cr_c]))
    blk = blk | (wm0 & (i > r_min[w0_c]))
    blk = blk | (wm1 & (i > r_min[w1_c]))
    return blk | (active & spec_serial)


def _spec_exec_impl(balances, ev, dstat_init, spec_serial, n, ts_base):
    """One speculative step: fresh carry -> on-device validation ->
    the wave-step body gated on the validated lanes.  Returns
    (carry, conflicted); the carry holds exactly the committed
    events' effects and registrations — nothing of a conflicted lane
    lands anywhere, so the residue replay resumes from it."""
    B = dstat_init.shape[0]
    A = balances.shape[0]
    conflicted = _spec_conflicts(ev, spec_serial, n, A, B)
    carry = kernel.make_carry(balances, dstat_init, B)
    carry = _wave_step_impl(
        carry, ev, n, ts_base, commit_mask=~conflicted
    )
    return carry, conflicted


_spec_exec = jax.jit(_spec_exec_impl)


def run_speculative_engine(balances, ev: dict, dstat_init, spec_serial,
                           n: int, ts_base: int):
    """Device-engine entry for one speculative step: the WHOLE batch
    as one validated wave against the authoritative table handle,
    never donating any caller buffer (a transient link fault retries
    the entire batch idempotently from the same handle — exactly
    run_plan_engine's contract).  Returns (carry, conflicted): fetch
    `conflicted`, then either finalize_engine (no conflicts — the
    speculation hit) or continue_plan_engine with the residue plan."""
    B = ev["flags"].shape[0]
    K = _bucket(n)
    ev_seg = _gather_events(ev, np.arange(n), K, B)
    ss = np.zeros(K, bool)
    ss[:n] = np.asarray(spec_serial)[:n]
    return _spec_exec(
        balances, ev_seg,
        jnp.asarray(np.asarray(dstat_init), jnp.uint32),
        jnp.asarray(ss), jnp.int32(n), jnp.uint64(ts_base),
    )


def continue_plan_engine(carry, ev: dict, n: int, ts_base: int,
                         plan: WavePlan, hist_fix: np.ndarray):
    """Replay the conflicted residue: thread the speculative step's
    carry — committed events' effects, created-record registrations,
    statuses — through the residue plan's segments (global indices,
    non-donating twins), then finalize.  Returns (new_balances,
    packed outputs), the run_plan_engine contract."""
    B = ev["flags"].shape[0]
    id_group_full = jnp.asarray(ev["id_group"])
    n_j = jnp.int32(n)
    ts_j = jnp.uint64(ts_base)
    for k, (seg_kind, idx) in enumerate(plan.segments):
        if seg_kind == "chains":
            ev_seg = _gather_chain_events(
                ev, idx, plan.chain_steps[k], n, B
            )
            carry = _chain_step_keep(carry, ev_seg, n_j, ts_j)
            continue
        ev_seg = _gather_events(ev, idx, _bucket(len(idx)), B)
        if seg_kind == "wave":
            carry = _wave_step_keep(carry, ev_seg, n_j, ts_j)
        else:
            carry = kernel.scan_segment_keep(
                carry, ev_seg, id_group_full, n_j, ts_j
            )
    return _finalize_keep(carry, jnp.asarray(hist_fix))


def finalize_engine(carry, hist_fix: np.ndarray):
    """Finalize a speculative carry with an empty residue (the hit
    path): pack outputs, rewrite committed events' snapshots to batch
    finals.  Returns (new_balances, packed outputs)."""
    return _finalize_keep(carry, jnp.asarray(hist_fix))


def prewarm(
    A: int, B_buckets=kernel.BATCH_BUCKETS, buckets=_SEG_BUCKETS,
    engine: bool = False, mesh=None, spec: bool = False,
) -> None:
    """Compile the wave step, the chain-wave step, and the paired scan
    segment for the given table geometry OFF the hot path: on the
    tunneled TPU each kernel costs minutes of one-time XLA compile,
    which must not land inside a timed window (device_engine.prewarm
    forwards its "waves" kind here; TB_DEV_PREWARM=waves,... opts in).
    The jits are shape-keyed on BOTH the carry's batch bucket B and
    the segment bucket K, so the default warms every (B, K <= B) pair
    the router can produce — warming only the extremes would leave
    mid-size first-compiles (e.g. two_phase's ~B/2-event waves, bucket
    4096) inside timed windows.  `engine=True` additionally warms the
    non-donating twins the device engine's window launch dispatches
    (separate XLA executables); the chain-wave step warms at its
    smallest position bucket (deeper chains recompile once, off the
    common path).  `mesh` warms the SPMD executors instead — the
    row-sharded engine's wave dispatch path."""
    if mesh is not None:
        _prewarm_sharded(A, mesh, B_buckets, buckets)
        return
    step = _wave_step_keep if engine else _wave_step
    chainf = _chain_step_keep if engine else _chain_step
    scan = kernel.scan_segment_keep if engine else kernel.scan_segment
    fin = _finalize_keep if engine else _finalize_impl
    outs = []
    for B, K, ev, idx, chain_ev in _prewarm_shapes(B_buckets, buckets):
        carry = kernel.make_carry(
            jnp.zeros((A, 8), jnp.uint64), jnp.zeros(B, jnp.uint32), B
        )
        carry = step(
            carry, _gather_events(ev, idx, K, B),
            jnp.int32(0), jnp.uint64(1),
        )
        carry = scan(
            carry, _gather_events(ev, idx, K, B),
            jnp.asarray(ev["id_group"]), jnp.int32(0), jnp.uint64(1),
        )
        if chain_ev is not None:
            carry = chainf(carry, chain_ev, jnp.int32(0), jnp.uint64(1))
        outs.append(fin(carry, jnp.zeros(B, bool)))
        if spec:
            # The speculative executor (engine-only, non-donating) is
            # a separate XLA executable per (B, K): validation +
            # masked wave step — warm it so a speculative launch never
            # first-compiles inside a timed window.
            sc, confl = _spec_exec(
                jnp.zeros((A, 8), jnp.uint64),
                _gather_events(ev, idx, K, B),
                jnp.zeros(B, jnp.uint32), jnp.zeros(K, bool),
                jnp.int32(0), jnp.uint64(1),
            )
            outs.append(confl)
            outs.append(_finalize_keep(sc, jnp.zeros(B, bool)))
    jax.block_until_ready(outs)


def _prewarm_shapes(B_buckets, buckets):
    """Yield (B, K, ev, idx, chain_ev) for every (batch, segment)
    bucket pair the router can produce — the ONE definition of the
    synthetic warm-up shapes, so the dense and sharded prewarm loops
    can never warm different geometries.  `chain_ev` is None when
    chain waves are disabled."""
    for B in B_buckets:
        ev = {
            name: np.zeros(B, np.dtype(dtype))
            for name, dtype in kernel.EVENT_FIELDS
        }
        ev["i"] = np.arange(B, dtype=np.int32)
        for K in buckets:
            if K > max(_SEG_BUCKETS) or _bucket(min(K, B)) != K:
                continue
            idx = np.arange(min(K, B))
            chain_ev = None
            if chain_max() >= 2:
                chain_ev = {
                    name: jnp.zeros((8, K), jnp.asarray(ev[name]).dtype)
                    for name in _CHAIN_EV_FIELDS
                }
                chain_ev["i"] = jnp.full((8, K), B, jnp.int32)
                chain_ev["chain_open"] = jnp.zeros((8, K), bool)
            yield B, K, ev, idx, chain_ev


def _prewarm_sharded(A: int, mesh, B_buckets, buckets) -> None:
    """Compile the SPMD wave/chain/finalize executors for every (B, K)
    bucket pair the router can produce, with the table placed under
    the engine's exact NamedSharding (compile cache keys include input
    shardings) — first compiles must not land inside a timed window."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    wave, chain, fin = _sharded_fns(mesh, A)
    sharding = NamedSharding(mesh, P("shard", None))
    outs = []
    for B, K, ev, idx, chain_ev in _prewarm_shapes(B_buckets, buckets):
        table = jax.device_put(jnp.zeros((A, 8), jnp.uint64), sharding)
        rest = _make_rest(jnp.zeros(B, jnp.uint32))
        table, rest = wave(
            table, rest, _gather_events(ev, idx, K, B),
            jnp.int32(0), jnp.uint64(1),
        )
        if chain_ev is not None:
            table, rest = chain(
                table, rest, chain_ev, jnp.int32(0), jnp.uint64(1)
            )
        outs.append(fin(table, rest, jnp.zeros(B, bool)))
    jax.block_until_ready(outs)


# ---------------------------------------------------------------------------
# Pending wave-record compaction.  A queued "waves" record used to
# retain its full (B,)-padded host event dict until launch (~3 MB at
# B=8192; a 96-batch window ~300 MB of host RAM).  Most columns are
# all-zero, constant, or narrow for common batches, and padding past
# the batch length is zeros by construction — so pending records store
# a lossless columnar encoding and rebuild the padded dict at launch
# (DeviceEngine.submit_waves / _exec_waves).  The engine reports the
# retained bytes as `pending_window_bytes` (bench `device_waves`).

_PER_COLUMN_OVERHEAD = 8  # name/tag bookkeeping, counted honestly


class PackedColumns:
    """Lossless columnar encoding of a dict of (B,) numpy arrays whose
    tails (beyond row `n`) are zeros — except full-length aranges
    ("i"), which re-derive.  Per column: all-zero -> nothing, constant
    -> one scalar, arange -> nothing, bool -> bit-packed, integers ->
    the narrowest dtype that holds the value range."""

    __slots__ = ("n", "B", "cols", "nbytes", "padded_nbytes")

    def __init__(self, cols: dict, n: int) -> None:
        self.n = n
        self.cols = {}
        self.nbytes = 0
        self.padded_nbytes = 0
        B = None
        for name, arr in cols.items():
            arr = np.asarray(arr)
            B = arr.shape[0] if B is None else B
            assert arr.shape == (B,), (name, arr.shape, B)
            self.padded_nbytes += arr.nbytes
            self.cols[name] = enc = self._encode(arr, n)
            payload = enc[2]
            self.nbytes += _PER_COLUMN_OVERHEAD + (
                payload.nbytes if isinstance(payload, np.ndarray) else 8
            )
        self.B = B

    @staticmethod
    def _encode(arr: np.ndarray, n: int):
        dt = arr.dtype
        if dt.kind in "iu" and arr[0] == 0 and bool(
            (np.diff(arr) == 1).all()
        ):
            return (dt, "arange", None)
        head, tail = arr[:n], arr[n:]
        if tail.any():
            # Unexpectedly nonzero padding: store verbatim — the codec
            # must be lossless for ANY input, compact for common ones.
            return (dt, "full", arr.copy())
        if not head.any():
            return (dt, "zero", None)
        if bool((head == head[0]).all()):
            return (dt, "const", head[0])
        if dt.kind == "b":
            return (dt, "bits", np.packbits(head))
        if dt.kind == "u":
            vmax = int(head.max())
            for nt in (np.uint8, np.uint16, np.uint32, np.uint64):
                if vmax <= int(np.iinfo(nt).max):
                    return (dt, "arr", head.astype(nt))
        if dt.kind == "i":
            vmin, vmax = int(head.min()), int(head.max())
            for nt in (np.int8, np.int16, np.int32, np.int64):
                ii = np.iinfo(nt)
                if ii.min <= vmin and vmax <= ii.max:
                    return (dt, "arr", head.astype(nt))
        return (dt, "arr", head.copy())

    def unpack(self) -> dict:
        out = {}
        for name, (dt, tag, payload) in self.cols.items():
            if tag == "arange":
                out[name] = np.arange(self.B, dtype=dt)
                continue
            if tag == "full":
                out[name] = payload.copy()
                continue
            arr = np.zeros(self.B, dt)
            if tag == "const":
                arr[: self.n] = payload
            elif tag == "bits":
                arr[: self.n] = np.unpackbits(
                    payload, count=self.n
                ).astype(bool)
            elif tag == "arr":
                arr[: self.n] = payload.astype(dt)
            out[name] = arr
        return out


def pack_wave_record(ev: dict, dstat_init, hist_fix, n: int) -> PackedColumns:
    """One compact bundle for everything a pending "waves" record must
    retain until launch: the event dict plus the dstat seed and the
    snapshot-rewrite mask (all (B,) columns, same codec)."""
    cols = dict(ev)
    cols["__dstat_init__"] = np.asarray(dstat_init)
    cols["__hist_fix__"] = np.asarray(hist_fix)
    return PackedColumns(cols, n)


def unpack_wave_record(pk: PackedColumns):
    """-> (ev, dstat_init, hist_fix), bit-identical to what was packed."""
    cols = pk.unpack()
    dstat_init = cols.pop("__dstat_init__")
    hist_fix = cols.pop("__hist_fix__")
    return cols, dstat_init, hist_fix


def pack_spec_record(ev: dict, dstat_init, spec_serial, n: int) -> PackedColumns:
    """Sibling codec for a pending SPECULATIVE record (same lossless
    columnar compaction, same admission/recovery treatment as a wave
    record): the event dict plus the dstat seed and the known-serial
    mask the on-device validator force-conflicts.  No hist_fix column
    — the snapshot-rewrite mask depends on the validation outcome and
    is derived at launch."""
    cols = dict(ev)
    cols["__dstat_init__"] = np.asarray(dstat_init)
    serial = np.zeros(len(cols["flags"]), bool)
    serial[:n] = np.asarray(spec_serial)[:n]
    cols["__spec_serial__"] = serial
    return PackedColumns(cols, n)


def unpack_spec_record(pk: PackedColumns):
    """-> (ev, dstat_init, spec_serial), bit-identical to what was
    packed."""
    cols = pk.unpack()
    dstat_init = cols.pop("__dstat_init__")
    spec_serial = cols.pop("__spec_serial__")
    return cols, dstat_init, spec_serial


def touched_slots(ev: dict, n: int | None = None) -> np.ndarray:
    """Balance rows a wave batch can modify — the event dict's own
    dr/cr slots plus the durable pending targets' (post/void writes
    land on the TARGET's accounts; in-batch targets resolve to the
    creator event's slots, already covered).  A superset is fine: the
    incremental-commitment refresh of an unmodified row is a no-op
    (device_engine._commit_update)."""
    parts = []
    for key in ("dr_slot", "cr_slot", "p_dr_slot", "p_cr_slot"):
        col = ev.get(key)
        if col is None:
            continue
        a = np.asarray(col).astype(np.int64).ravel()
        if n is not None:
            a = a[:n]
        parts.append(a[a >= 0])
    if not parts:
        return np.zeros(0, np.int64)
    return np.unique(np.concatenate(parts))
