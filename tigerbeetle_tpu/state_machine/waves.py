"""Conflict-aware wave execution: parallel apply for independent
transfers, exact scan only for true dependencies.

The sequential scan kernel (kernel.py) pays one device step per EVENT
— B steps per batch — even when almost every event touches disjoint
accounts.  This module collapses that to one step per *wave*: a
host-side partitioner (`plan_waves`) builds the batch's conflict graph
and assigns each event a topological LEVEL (one more than the highest
level among earlier events it conflicts with); each level executes as
ONE vectorized device step over its — possibly non-contiguous — index
set (`_wave_step_impl`, the scan body re-expressed over a (K,) event
axis with balance deltas combined by an exact u128 segment-sum
scatter, like kernel_fast._flush_impl), while true serial dependencies
— linked chains — run through the unchanged exact scan at their batch
position (kernel.scan_segment).  A two_phase batch of (pending,
finalize) pairs is exactly TWO waves; a fresh-ids batch is ONE.  The
segment kinds thread one carry, so outputs are bit-identical to the
full scan (enforced by tests/test_waves.py differential fuzz).

What makes two events DEPENDENT (same model as parallel-EVM conflict
graphs — arXiv:2503.04595 — specialized to the reference semantics):

- **id/pending references.**  A second event with the same transfer-id
  value must observe the first's create (exists ladder); a post/void
  whose pending_id names an in-batch id must observe that create and
  its status.  Tracked as compact id-group tokens (tpu.py's exact-path
  grouping): two events conflict when either's id_group or p_group was
  already claimed by the wave.
- **durable two-phase targets.**  Two finalizers of the same durable
  pending race first-wins; the second's verdict depends on the first.
  Tracked by p_tgt (the deduped durable-target index).
- **balance READS.**  Most transfers only *add* to balance columns —
  addition commutes and their result codes read no mutable state, so
  they share a wave even on the same hot account (the deltas sum).
  But balancing_debit/credit clamps and debits/credits_must_not_exceed
  limit checks *read* account balances: such an event conflicts with
  any wave-mate that writes one of its read slots (and its own writes
  conflict with wave-mates' reads).
- **linked chains & history accounts.**  Rollback couples every chain
  member (including the closing event), and an AF.history account's
  per-event snapshot must be sequential-exact (it feeds the history
  groove, while wave snapshots are rewritten to batch finals): both
  run in exact scan segments.

Overflow codes are the one read everyone performs implicitly: whether
`amount + dp` overflows u128 depends on prior events.  The executor
keeps them exact with the same superset admission the order-free fast
path uses (mirror.try_apply_adds): amounts are non-negative, so if the
ALL-APPLIED total of the batch cannot overflow any touched column (or
column pair), no sequential prefix can either, and every ov_* term is
identically false in both orders.  `admission_ok` proves that bound on
the host mirror; a batch that fails it (astronomical balances) routes
to the scan path — never a wrong answer, only a slower one.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass, field

import numpy as np

import jax
import jax.numpy as jnp

from tigerbeetle_tpu.ops import u128 as w
from tigerbeetle_tpu.state_machine import kernel
from tigerbeetle_tpu.state_machine.kernel import (
    CREATED_FIELDS,
    F_BAL_CR,
    F_BAL_DR,
    F_LINKED,
    F_PENDING,
    F_POST,
    F_VOID,
    NS_PER_S,
    R_ALREADY_POSTED,
    R_ALREADY_VOIDED,
    R_EXCEEDS_CREDITS,
    R_EXCEEDS_DEBITS,
    R_EXCEEDS_PENDING_AMOUNT,
    R_OVERFLOWS_CP,
    R_OVERFLOWS_CPO,
    R_OVERFLOWS_CREDITS,
    R_OVERFLOWS_DEBITS,
    R_OVERFLOWS_DP,
    R_OVERFLOWS_DPO,
    R_OVERFLOWS_TIMEOUT,
    R_PENDING_DIFF_AMOUNT,
    R_PENDING_DIFF_CODE,
    R_PENDING_DIFF_CR,
    R_PENDING_DIFF_DR,
    R_PENDING_DIFF_LEDGER,
    R_PENDING_EXPIRED,
    R_PENDING_NOT_FOUND,
    R_PENDING_NOT_PENDING,
    R_TIMESTAMP_MUST_BE_ZERO,
    S_PENDING,
    S_POSTED,
    S_VOIDED,
    U64_MAX,
    _E_FIELD_MAP,
    _EXISTS_SENTINEL,
    _P_FIELD_MAP,
    _exists_ladder_normal,
    _exists_ladder_post_void,
    _first_nonzero,
    _gather_created,
    _merge,
    AF_CR_LIMIT,
    AF_DR_LIMIT,
    CP_LO, CP_HI, CPO_LO, CPO_HI, DP_LO, DP_HI, DPO_LO, DPO_HI,
)

_MASK32 = jnp.uint64(0xFFFFFFFF)

# Wave/scan segment shape buckets (jit compile cache keys).
_SEG_BUCKETS = (16, 64, 256, 1024, 4096, 8192)

def min_ratio() -> float:
    """Minimum step-count reduction (batch length / executed steps)
    before the wave path beats the plain scan; below it the partition
    degrades toward per-event waves and the scan's single fused
    dispatch wins.  Read live (like mode()) so tests and bench arms
    can toggle TB_WAVES_MIN_RATIO after import."""
    from tigerbeetle_tpu import envcheck

    return envcheck.env_float("TB_WAVES_MIN_RATIO", 2.0, minimum=0.0)


def mode() -> str:
    """TB_WAVES routing mode:

    - unset/"auto": wave plans considered whenever the JAX exact scan
      would otherwise run (native absent), profitability + admission
      gates apply.
    - "0": off — the exact path always runs the B-step scan.
    - "1": force — route every batch to the JAX exact path (bypassing
      the native engine and the order-free/linked/two-phase fast
      paths) and execute the wave plan even when unprofitable.
      Differential-test routing: maximizes wave-executor coverage.
    - "exact": route to the JAX exact path like "1", but keep the
      normal profitability/admission decision (what the scheduler
      would really do there).
    - "scan": route to the JAX exact path, never plan waves — the
      pure sequential scan on identical routing, the honest control
      for wave-vs-scan benchmarks."""
    from tigerbeetle_tpu import envcheck

    return envcheck.env_choice(
        "TB_WAVES", "auto", ("auto", "0", "1", "exact", "scan")
    )


# ---------------------------------------------------------------------------
# Partitioner.


@dataclass
class WavePlan:
    """Execution plan: ordered segments whose index sets cover [0, n).

    Segment order is the EXECUTION order; a "wave" segment's indices
    need not be contiguous (topological-level scheduling), while a
    "scan" segment is always a contiguous chain run executed at its
    batch position.
    """

    n: int
    # (kind, idx): kind "wave" = one parallel step over idx (int
    # array, ascending), kind "scan" = len(idx) exact sequential
    # steps over a contiguous run.
    segments: list = field(default_factory=list)
    wave_mask: np.ndarray | None = None  # events executed in wave steps

    @property
    def n_waves(self) -> int:
        return sum(1 for k, _ in self.segments if k == "wave")

    @property
    def parallel_events(self) -> int:
        return sum(len(ix) for k, ix in self.segments if k == "wave")

    @property
    def n_steps(self) -> int:
        """Device-step equivalents: 1 per wave, length per scan run."""
        return sum(
            1 if k == "wave" else len(ix) for k, ix in self.segments
        )

    @property
    def ratio(self) -> float:
        return self.n / max(1, self.n_steps)

    def profitable(self, ratio_floor: float | None = None) -> bool:
        return self.ratio >= (
            min_ratio() if ratio_floor is None else ratio_floor
        )


def plan_waves(n: int, meta: dict) -> WavePlan:
    """Partition a batch into wave/scan segments by topological level.

    Chain runs (contiguous spans of ``chain_member`` events) are
    barriers executed by the exact scan at their batch position.  The
    chain-free REGIONS between them schedule like a parallel-EVM
    conflict graph (arXiv:2503.04595): each event's *level* is one
    more than the highest level of any earlier in-region event it
    conflicts with (shared id/pending token, first-wins target, or a
    read-write balance-slot overlap), and each level executes as ONE
    wave — commuting adds never conflict, so a two_phase batch of
    (pending, finalize) pairs collapses to exactly two waves.  Level
    order preserves sequential semantics for every conflicting pair;
    non-conflicting events commute, so any interleaving of levels is
    bit-identical to the scan.

    `meta` comes from resolve.wave_dependency_metadata — see there for
    the field contract.  O(n) with small-constant dict operations;
    runs once per batch on the host, only when the wave path is a
    routing candidate.
    """
    chain_member = meta["chain_member"]
    id_group = meta["id_group"]
    p_group = meta["p_group"]
    p_tgt = meta["p_tgt"]
    writes0 = meta["writes0"]
    writes1 = meta["writes1"]
    reads0 = meta["reads0"]
    reads1 = meta["reads1"]
    inb_pv = meta["inb_pv"]
    ev_dr = meta["ev_dr"]
    ev_cr = meta["ev_cr"]

    # Fast path for the dominant shape (fresh unique ids, no chains, no
    # finalizers, no balance readers): the whole batch is ONE wave —
    # skip the per-event Python walk entirely.
    if (
        not chain_member.any()
        and not inb_pv.any()
        and (reads0 < 0).all()
        and (reads1 < 0).all()
        and (p_tgt < 0).all()
        and (p_group < 0).all()
        and len(np.unique(id_group)) == n
    ):
        plan = WavePlan(n, segments=[("wave", np.arange(n))])
        plan.wave_mask = np.ones(n, bool)
        return plan

    # In-batch pending references resolve to the creating event at run
    # time; statically, the finalizer may write the slots of ANY event
    # sharing that id-group (the creator is whichever applied), so its
    # write set is the group's slot union.
    group_slots: dict[int, set] = {}
    for e in range(n):
        g = int(id_group[e])
        s = group_slots.setdefault(g, set())
        if ev_dr[e] >= 0:
            s.add(int(ev_dr[e]))
        if ev_cr[e] >= 0:
            s.add(int(ev_cr[e]))

    plan = WavePlan(n)
    wave_mask = np.zeros(n, bool)
    segments = plan.segments

    def level_region(lo: int, hi: int) -> None:
        """Assign conflict-graph levels to [lo, hi) (no chain members)
        and emit one wave segment per level, in level order."""
        group_level: dict[int, int] = {}
        ptgt_level: dict[int, int] = {}
        write_level: dict[int, int] = {}
        read_level: dict[int, int] = {}
        levels = np.zeros(hi - lo, np.int32)
        for e in range(lo, hi):
            g = int(id_group[e])
            pg = int(p_group[e])
            pt = int(p_tgt[e])
            ww = []
            if writes0[e] >= 0:
                ww.append(int(writes0[e]))
            if writes1[e] >= 0:
                ww.append(int(writes1[e]))
            if inb_pv[e]:
                ww.extend(group_slots.get(pg, ()))
            rr = []
            if reads0[e] >= 0:
                rr.append(int(reads0[e]))
            if reads1[e] >= 0:
                rr.append(int(reads1[e]))

            # Level = 1 + max level of every earlier conflicting
            # event: same-id claims (exists ladder), pending refs,
            # first-wins finalize targets, then balance-slot RAW/WAR
            # (a reader must see exactly the earlier writers' adds;
            # later writers must apply after it reads).  Reads also
            # serialize against earlier reads — a balancing/limit
            # reader's own writes are data-dependent, and the greedy
            # rule this generalizes kept reader pairs ordered.
            lvl = group_level.get(g, -1) + 1
            if pg >= 0:
                lvl = max(lvl, group_level.get(pg, -1) + 1)
            if pt >= 0:
                lvl = max(lvl, ptgt_level.get(pt, -1) + 1)
            for s in rr:
                lvl = max(
                    lvl,
                    write_level.get(s, -1) + 1,
                    read_level.get(s, -1) + 1,
                )
            for s in ww:
                lvl = max(lvl, read_level.get(s, -1) + 1)

            levels[e - lo] = lvl
            if lvl > group_level.get(g, -1):
                group_level[g] = lvl
            if pg >= 0 and lvl > group_level.get(pg, -1):
                group_level[pg] = lvl
            if pt >= 0 and lvl > ptgt_level.get(pt, -1):
                ptgt_level[pt] = lvl
            for s in ww:
                if lvl > write_level.get(s, -1):
                    write_level[s] = lvl
            for s in rr:
                if lvl > read_level.get(s, -1):
                    read_level[s] = lvl
        for lvl in range(int(levels.max()) + 1 if hi > lo else 0):
            idx = lo + np.flatnonzero(levels == lvl)
            segments.append(("wave", idx))
            wave_mask[idx] = True

    i = 0
    while i < n:
        if chain_member[i]:
            j = i
            while j < n and chain_member[j]:
                j += 1
            segments.append(("scan", np.arange(i, j)))
            i = j
            continue
        j = i
        while j < n and not chain_member[j]:
            j += 1
        level_region(i, j)
        i = j

    plan.wave_mask = wave_mask
    return plan


# ---------------------------------------------------------------------------
# Overflow admission (host, against the balance mirror).


def admission_ok(
    mirror_lo: np.ndarray,
    mirror_hi: np.ndarray,
    touched: np.ndarray,
    bound_lo: np.ndarray,
    bound_hi: np.ndarray,
) -> bool:
    """Superset overflow admission for the whole batch.

    True when (pre-state + all-applied additions) provably cannot
    overflow any touched u128 column or dp+dpo / cp+cpo pair — then
    every per-event ov_* term is false in ANY execution order (amounts
    are non-negative, so each sequential prefix is bounded by the
    all-applied total).  Conservative: `bound_*` are per-event amount
    upper bounds (balancing zero-amount -> maxInt u64), each charged to
    all four lanes an event can add through.
    """
    touched = touched[touched >= 0]
    if len(touched) and mirror_hi[touched].any():
        return False
    m32 = np.uint64(0xFFFFFFFF)
    s_ll = int((bound_lo & m32).sum(dtype=np.uint64))
    s_lh = int((bound_lo >> np.uint64(32)).sum(dtype=np.uint64))
    s_hl = int((bound_hi & m32).sum(dtype=np.uint64))
    s_hh = int((bound_hi >> np.uint64(32)).sum(dtype=np.uint64))
    total = s_ll + (s_lh << 32) + (s_hl << 64) + (s_hh << 96)
    # x4: dr+cr lanes for the create plus dr+cr for a post's add.
    # Touched cols start < 2^64 (hi limbs all zero), so column and
    # pair sums stay < 2^64 + 2^127 < 2^128.
    return 4 * total < (1 << 126)


# ---------------------------------------------------------------------------
# The wave step: the scan body over a (K,) event axis.


def _accum_u128(slots_c, cols, amt_lo, amt_hi, valid, A):
    """Exact per-(slot, column) u128 sums via 32-bit-piece scatter-adds
    (duplicate slots accumulate — the segment-sum analogue of
    kernel_fast._flush_impl's unique-scatter).  Piece sums stay below
    lanes * 2^32 < 2^64, so recombination with base-2^32 carries is
    exact.  Invalid lanes contribute zero (their slot may be clip
    garbage; zero is harmless anywhere)."""
    zero = jnp.uint64(0)
    lo = jnp.where(valid, amt_lo, zero)
    hi = jnp.where(valid, amt_hi, zero)
    pieces = [
        lo & _MASK32, lo >> jnp.uint64(32),
        hi & _MASK32, hi >> jnp.uint64(32),
    ]
    acc = [
        jnp.zeros((A, 4), jnp.uint64).at[slots_c, cols].add(p)
        for p in pieces
    ]
    c0, c1, c2, c3 = acc
    c1 = c1 + (c0 >> jnp.uint64(32))
    c2 = c2 + (c1 >> jnp.uint64(32))
    c3 = c3 + (c2 >> jnp.uint64(32))
    d_lo = (c0 & _MASK32) | ((c1 & _MASK32) << jnp.uint64(32))
    d_hi = (c2 & _MASK32) | ((c3 & _MASK32) << jnp.uint64(32))
    return d_lo, d_hi


def _wave_step_impl(carry, ev, n, ts_base):
    """Apply one wave — K mutually independent events — as a single
    vectorized step against the segment carry.

    Line-for-line port of kernel.make_body's event body with the
    (K,) axis vectorized and chain/rollback logic dropped (the
    partitioner never places chain members in waves).  Independence
    guarantees every gather sees pre-wave state equal to its
    sequential value, and the admission precondition makes every ov_*
    term false, so results and records are bit-identical to the scan.
    """
    table = carry["balances"]
    created = carry["created"]
    group_creator = carry["group_creator"]
    B = carry["results"].shape[0]
    A = table.shape[0]

    i = ev["i"]  # (K,) global indices; padding lanes carry i == B
    active = i < n
    flags = ev["flags"]
    is_pv = (flags & (F_POST | F_VOID)) != 0
    ts_i = ts_base + i.astype(jnp.uint64)

    # No chain terms: wave events are never chain members, so the
    # scan's chain_open/chain_broken preconditions are identically 0.
    pre = _first_nonzero((ev["ts_nonzero"], R_TIMESTAMP_MUST_BE_ZERO))
    pre = jnp.where(pre == 0, ev["static_result"], pre)

    # -- Exists resolution via the in-batch id directory.
    e_creator = group_creator[jnp.clip(ev["id_group"], 0, B - 1)]
    e_inb = e_creator >= 0
    e_dur = ev["e_found"]
    e_any = e_inb | e_dur
    e = _merge(~e_inb, _gather_created(created, e_creator, B), ev, _E_FIELD_MAP)

    # ==================== normal create_transfer ====================
    dr_row = table[jnp.clip(ev["dr_slot"], 0, A - 1)]
    cr_row = table[jnp.clip(ev["cr_slot"], 0, A - 1)]
    dr_dp = (dr_row[:, DP_LO], dr_row[:, DP_HI])
    dr_dpo = (dr_row[:, DPO_LO], dr_row[:, DPO_HI])
    dr_cpo = (dr_row[:, CPO_LO], dr_row[:, CPO_HI])
    cr_dpo = (cr_row[:, DPO_LO], cr_row[:, DPO_HI])
    cr_cp = (cr_row[:, CP_LO], cr_row[:, CP_HI])
    cr_cpo = (cr_row[:, CPO_LO], cr_row[:, CPO_HI])

    exists_rn = _exists_ladder_normal(ev, e)

    is_balancing = (flags & (F_BAL_DR | F_BAL_CR)) != 0
    amount = (ev["amount_lo"], ev["amount_hi"])
    amount = w.select(
        is_balancing & w.is_zero(amount),
        (jnp.full_like(amount[0], U64_MAX), jnp.zeros_like(amount[1])),
        amount,
    )
    dr_balance, _ = w.add(dr_dpo, dr_dp)
    bd_avail = w.sub_sat(dr_cpo, dr_balance)
    amount = w.select((flags & F_BAL_DR) != 0, w.minimum(amount, bd_avail), amount)
    bd_fail = ((flags & F_BAL_DR) != 0) & w.is_zero(amount)

    cr_balance, _ = w.add(cr_cpo, cr_cp)
    bc_avail = w.sub_sat(cr_dpo, cr_balance)
    amount_bc = w.minimum(amount, bc_avail)
    amount = w.select(((flags & F_BAL_CR) != 0) & ~bd_fail, amount_bc, amount)
    bc_fail = ((flags & F_BAL_CR) != 0) & w.is_zero(amount) & ~bd_fail

    is_pending = (flags & F_PENDING) != 0
    _, ov_dp = w.add(amount, dr_dp)
    _, ov_cp = w.add(amount, cr_cp)
    _, ov_dpo = w.add(amount, dr_dpo)
    _, ov_cpo = w.add(amount, cr_cpo)
    dr_total, _ = w.add(dr_dp, dr_dpo)
    _, ov_debits = w.add(amount, dr_total)
    cr_total, _ = w.add(cr_cp, cr_cpo)
    _, ov_credits = w.add(amount, cr_total)

    timeout_ns = ev["timeout"] * NS_PER_S
    ts_plus = ts_i + timeout_ns
    ov_timeout = ts_plus < ts_i

    dr_lhs, _ = w.add(dr_total, amount)
    exceeds_cr = ((ev["dr_flags"] & AF_DR_LIMIT) != 0) & w.gt(dr_lhs, dr_cpo)
    cr_lhs, _ = w.add(cr_total, amount)
    exceeds_dr = ((ev["cr_flags"] & AF_CR_LIMIT) != 0) & w.gt(cr_lhs, cr_dpo)

    rn = _first_nonzero(
        (e_any, _EXISTS_SENTINEL),
        (bd_fail, R_EXCEEDS_CREDITS),
        (bc_fail, R_EXCEEDS_DEBITS),
        (is_pending & ov_dp, R_OVERFLOWS_DP),
        (is_pending & ov_cp, R_OVERFLOWS_CP),
        (ov_dpo, R_OVERFLOWS_DPO),
        (ov_cpo, R_OVERFLOWS_CPO),
        (ov_debits, R_OVERFLOWS_DEBITS),
        (ov_credits, R_OVERFLOWS_CREDITS),
        (ov_timeout, R_OVERFLOWS_TIMEOUT),
        (exceeds_cr, R_EXCEEDS_CREDITS),
        (exceeds_dr, R_EXCEEDS_DEBITS),
    )
    rn = jnp.where(rn == _EXISTS_SENTINEL, exists_rn, rn)

    # ==================== post/void pending transfer ====================
    p_creator = group_creator[jnp.clip(ev["p_group"], 0, B - 1)]
    p_inb = (ev["p_group"] >= 0) & (p_creator >= 0)
    p_dur = ev["p_found"]
    p_any = p_dur | p_inb
    p = _merge(p_dur, _gather_created(created, p_creator, B), ev, _P_FIELD_MAP)
    p_timestamp = jnp.where(
        p_dur,
        ev["p_timestamp"],
        ts_base + jnp.clip(p_creator, 0, B - 1).astype(jnp.uint64),
    )
    p_amount = (p["amount_lo"], p["amount_hi"])

    pv_amount_raw = (ev["amount_lo"], ev["amount_hi"])
    pv_amount = w.select(w.is_zero(pv_amount_raw), p_amount, pv_amount_raw)
    is_void = (flags & F_VOID) != 0

    exists_rp = _exists_ladder_post_void(ev, e, p)

    st = jnp.where(
        p_dur,
        carry["dstat"][jnp.clip(ev["p_tgt"], 0, B - 1)],
        carry["inb_status"][jnp.clip(p_creator, 0, B - 1)],
    )

    rp_pre_insert = _first_nonzero(
        (~p_any, R_PENDING_NOT_FOUND),
        ((p["flags"] & F_PENDING) == 0, R_PENDING_NOT_PENDING),
        (~ev["dr_id_zero"] & (ev["dr_slot"] != p["dr_slot"]), R_PENDING_DIFF_DR),
        (~ev["cr_id_zero"] & (ev["cr_slot"] != p["cr_slot"]), R_PENDING_DIFF_CR),
        ((ev["ledger"] > 0) & (ev["ledger"] != p["ledger"]), R_PENDING_DIFF_LEDGER),
        ((ev["code"] > 0) & (ev["code"] != p["code"]), R_PENDING_DIFF_CODE),
        (w.gt(pv_amount, p_amount), R_EXCEEDS_PENDING_AMOUNT),
        (is_void & w.lt(pv_amount, p_amount), R_PENDING_DIFF_AMOUNT),
        (e_any, _EXISTS_SENTINEL),
        (st == S_POSTED, R_ALREADY_POSTED),
        (st == S_VOIDED, R_ALREADY_VOIDED),
        (st == kernel.S_EXPIRED, R_PENDING_EXPIRED),
    )
    rp_pre_insert = jnp.where(
        rp_pre_insert == _EXISTS_SENTINEL, exists_rp, rp_pre_insert
    )

    p_expires = p_timestamp + p["timeout"] * NS_PER_S
    overdue = (p["timeout"] > 0) & (p_expires <= ts_i)
    rp = jnp.where((rp_pre_insert == 0) & overdue, R_PENDING_EXPIRED, rp_pre_insert)

    # ==================== merge & apply ====================
    dyn_r = jnp.where(is_pv, rp, rn)
    gate = active & (pre == 0)
    r = jnp.where(gate, dyn_r, jnp.where(active, pre, 0))

    pv_inserted = gate & is_pv & (rp_pre_insert == 0)
    normal_applied = gate & ~is_pv & (rn == 0)
    pv_applied = gate & is_pv & (rp == 0)
    inserted = pv_inserted | normal_applied
    applied = pv_applied | normal_applied

    ud128_inherit = is_pv & (ev["ud128_lo"] == 0) & (ev["ud128_hi"] == 0)
    rec = {
        "flags": flags,
        "dr_slot": jnp.where(is_pv, p["dr_slot"], ev["dr_slot"]),
        "cr_slot": jnp.where(is_pv, p["cr_slot"], ev["cr_slot"]),
        "amount_lo": jnp.where(is_pv, pv_amount[0], amount[0]),
        "amount_hi": jnp.where(is_pv, pv_amount[1], amount[1]),
        "pending_lo": ev["pending_lo"],
        "pending_hi": ev["pending_hi"],
        "ud128_lo": jnp.where(ud128_inherit, p["ud128_lo"], ev["ud128_lo"]),
        "ud128_hi": jnp.where(ud128_inherit, p["ud128_hi"], ev["ud128_hi"]),
        "ud64": jnp.where(is_pv & (ev["ud64"] == 0), p["ud64"], ev["ud64"]),
        "ud32": jnp.where(is_pv & (ev["ud32"] == 0), p["ud32"], ev["ud32"]),
        "timeout": jnp.where(is_pv, jnp.uint64(0), ev["timeout"]),
        "ledger": jnp.where(is_pv, p["ledger"], ev["ledger"]),
        "code": jnp.where(is_pv, p["code"], ev["code"]),
    }

    # -- Balance effects as commuting u128 deltas, segment-summed.
    up_dr_slot = jnp.where(is_pv, p["dr_slot"], ev["dr_slot"])
    up_cr_slot = jnp.where(is_pv, p["cr_slot"], ev["cr_slot"])
    safe_dr = jnp.clip(up_dr_slot, 0, A - 1)
    safe_cr = jnp.clip(up_cr_slot, 0, A - 1)

    is_post = (flags & F_POST) != 0
    zi = jnp.zeros_like(i)
    # Add lanes: normal dr (dp|dpo), normal cr (cp|cpo), post dr dpo,
    # post cr cpo.  Sub lanes: pv release dr dp, pv release cr cp.
    add_slots = jnp.concatenate([safe_dr, safe_cr, safe_dr, safe_cr])
    add_cols = jnp.concatenate(
        [
            jnp.where(is_pending, zi, zi + 1),
            jnp.where(is_pending, zi + 2, zi + 3),
            zi + 1,
            zi + 3,
        ]
    )
    add_lo = jnp.concatenate([amount[0], amount[0], pv_amount[0], pv_amount[0]])
    add_hi = jnp.concatenate([amount[1], amount[1], pv_amount[1], pv_amount[1]])
    post_ap = pv_applied & is_post
    add_valid = jnp.concatenate(
        [normal_applied, normal_applied, post_ap, post_ap]
    )
    sub_slots = jnp.concatenate([safe_dr, safe_cr])
    sub_cols = jnp.concatenate([zi, zi + 2])
    sub_lo = jnp.concatenate([p_amount[0], p_amount[0]])
    sub_hi = jnp.concatenate([p_amount[1], p_amount[1]])
    sub_valid = jnp.concatenate([pv_applied, pv_applied])

    d_lo, d_hi = _accum_u128(add_slots, add_cols, add_lo, add_hi, add_valid, A)
    s_lo, s_hi = _accum_u128(sub_slots, sub_cols, sub_lo, sub_hi, sub_valid, A)

    old_lo = table[:, 0::2]
    old_hi = table[:, 1::2]
    t_lo = old_lo + d_lo
    cy = (t_lo < old_lo).astype(jnp.uint64)
    t_hi = old_hi + d_hi + cy
    n_lo = t_lo - s_lo
    bw = (t_lo < s_lo).astype(jnp.uint64)
    n_hi = t_hi - s_hi - bw
    table = jnp.stack(
        [n_lo[:, 0], n_hi[:, 0], n_lo[:, 1], n_hi[:, 1],
         n_lo[:, 2], n_hi[:, 2], n_lo[:, 3], n_hi[:, 3]],
        axis=-1,
    )

    # -- Per-event post-apply snapshots (pre-wave row + own deltas).
    # They may miss wave-mates' commuting deltas to the same slot, but
    # wave events' snapshots only feed the mirror and are rewritten
    # with batch finals at finalize (history-account events, whose
    # snapshots are semantically read, never ride waves).
    o_dr = carry["balances"][safe_dr]
    o_cr = carry["balances"][safe_cr]
    o_dr_dp = (o_dr[:, DP_LO], o_dr[:, DP_HI])
    o_dr_dpo = (o_dr[:, DPO_LO], o_dr[:, DPO_HI])
    o_cr_cp = (o_cr[:, CP_LO], o_cr[:, CP_HI])
    o_cr_cpo = (o_cr[:, CPO_LO], o_cr[:, CPO_HI])
    n_dr_dp = w.select(
        is_pv,
        w.sub(o_dr_dp, p_amount)[0],
        w.select(is_pending, w.add(o_dr_dp, amount)[0], o_dr_dp),
    )
    n_dr_dpo = w.select(
        is_pv,
        w.select(is_post, w.add(o_dr_dpo, pv_amount)[0], o_dr_dpo),
        w.select(is_pending, o_dr_dpo, w.add(o_dr_dpo, amount)[0]),
    )
    n_cr_cp = w.select(
        is_pv,
        w.sub(o_cr_cp, p_amount)[0],
        w.select(is_pending, w.add(o_cr_cp, amount)[0], o_cr_cp),
    )
    n_cr_cpo = w.select(
        is_pv,
        w.select(is_post, w.add(o_cr_cpo, pv_amount)[0], o_cr_cpo),
        w.select(is_pending, o_cr_cpo, w.add(o_cr_cpo, amount)[0]),
    )
    new_dr_row = jnp.stack(
        [n_dr_dp[0], n_dr_dp[1], n_dr_dpo[0], n_dr_dpo[1],
         o_dr[:, CP_LO], o_dr[:, CP_HI], o_dr[:, CPO_LO], o_dr[:, CPO_HI]],
        axis=-1,
    )
    new_cr_row = jnp.stack(
        [o_cr[:, DP_LO], o_cr[:, DP_HI], o_cr[:, DPO_LO], o_cr[:, DPO_HI],
         n_cr_cp[0], n_cr_cp[1], n_cr_cpo[0], n_cr_cpo[1]],
        axis=-1,
    )

    # -- Scatter per-event state at own (unique) global indices; OOB
    # padding lanes drop.
    idx_i = jnp.where(active, i, B)
    idx_ins = jnp.where(inserted, i, B)
    created = {
        f: created[f]
        .at[idx_ins]
        .set(rec[f].astype(created[f].dtype), mode="drop")
        for f in CREATED_FIELDS
    }
    created_mask = carry["created_mask"].at[idx_i].set(inserted, mode="drop")
    gidx = jnp.where(inserted, jnp.clip(ev["id_group"], 0, B - 1), B)
    group_creator = group_creator.at[gidx].set(i, mode="drop")

    inb_status = carry["inb_status"].at[idx_i].set(
        jnp.where(normal_applied & is_pending, jnp.uint32(S_PENDING), 0),
        mode="drop",
    )
    new_status = jnp.where(is_post, jnp.uint32(S_POSTED), jnp.uint32(S_VOIDED))
    idx_t = jnp.where(pv_applied & p_dur, jnp.clip(ev["p_tgt"], 0, B - 1), B)
    dstat = carry["dstat"].at[idx_t].set(new_status, mode="drop")
    idx_pc = jnp.where(pv_applied & ~p_dur, jnp.clip(p_creator, 0, B - 1), B)
    inb_status = inb_status.at[idx_pc].set(new_status, mode="drop")

    hist_dr = carry["hist_dr"].at[idx_i].set(new_dr_row, mode="drop")
    hist_cr = carry["hist_cr"].at[idx_i].set(new_cr_row, mode="drop")
    results = carry["results"].at[idx_i].set(r, mode="drop")

    last_applied = jnp.maximum(
        carry["last_applied"], jnp.where(applied, i, -1).max()
    )
    pulse_create = carry["pulse_create"].at[idx_i].set(
        jnp.where(
            normal_applied & is_pending & (ev["timeout"] > 0),
            ts_i + timeout_ns,
            jnp.uint64(0),
        ),
        mode="drop",
    )
    pulse_remove = carry["pulse_remove"].at[idx_i].set(
        jnp.where(pv_applied & (p["timeout"] > 0), p_expires, jnp.uint64(0)),
        mode="drop",
    )

    return dict(
        carry,
        balances=table,
        results=results,
        created_mask=created_mask,
        created=created,
        group_creator=group_creator,
        inb_status=inb_status,
        dstat=dstat,
        hist_dr=hist_dr,
        hist_cr=hist_cr,
        last_applied=last_applied,
        pulse_create=pulse_create,
        pulse_remove=pulse_remove,
    )


_wave_step = jax.jit(_wave_step_impl, donate_argnums=(0,))


@functools.partial(jax.jit, donate_argnums=(0,))
def _init_carry(balances, dstat_init):
    return kernel.make_carry(balances, dstat_init, dstat_init.shape[0])


@functools.partial(jax.jit, donate_argnums=(0,))
def _finalize_impl(carry, hist_fix):
    """Pack outputs; rewrite wave events' balance snapshots with the
    BATCH-FINAL rows of their touched slots so the host's last-write-
    wins mirror reconstruction lands on exact finals (a wave event's
    own snapshot misses wave-mates' commuting deltas to the same slot).
    `hist_fix` is the wave mask: scan-segment events keep their
    sequential snapshots — history-account events always run there, so
    the history groove only ever sees sequential-exact rows."""
    table = carry["balances"]
    A = table.shape[0]
    fix = hist_fix & (carry["results"] == 0)
    dr = jnp.clip(carry["created"]["dr_slot"], 0, A - 1)
    cr = jnp.clip(carry["created"]["cr_slot"], 0, A - 1)
    hist_dr = jnp.where(fix[:, None], table[dr], carry["hist_dr"])
    hist_cr = jnp.where(fix[:, None], table[cr], carry["hist_cr"])
    return kernel.finalize_outputs(
        dict(carry, hist_dr=hist_dr, hist_cr=hist_cr)
    )


def _bucket(k: int) -> int:
    for b in _SEG_BUCKETS:
        if b >= k:
            return b
    return k


def _gather_events(ev: dict, idx: np.ndarray, K: int, B: int) -> dict:
    """Padded (K,) device gather of the host event arrays at batch
    indices `idx` (ascending, possibly non-contiguous for waves);
    padding lanes get i == B (inactive, and every per-event scatter
    drops OOB)."""
    k = len(idx)
    out = {}
    for name, arr in ev.items():
        buf = np.zeros(K, arr.dtype)
        buf[:k] = arr[idx]
        if name == "i":
            buf[k:] = B
        out[name] = jnp.asarray(buf)
    return out


def run_create_transfers_waves(
    balances, ev: dict, dstat_init, n: int, ts_base: int, plan: WavePlan,
    hist_fix: np.ndarray,
):
    """Execute a batch by the wave plan; same contract and bit-exact
    same outputs as kernel.run_create_transfers.

    `ev` is the HOST-side dict of (B,) numpy arrays per
    kernel.EVENT_FIELDS; `hist_fix` is a (B,) bool mask of events whose
    snapshots should be rewritten with batch finals (wave events off
    history accounts).
    """
    B = ev["flags"].shape[0]
    carry = _init_carry(
        balances, jnp.asarray(np.asarray(dstat_init), jnp.uint32)
    )
    id_group_full = jnp.asarray(ev["id_group"])
    n_j = jnp.int32(n)
    ts_j = jnp.uint64(ts_base)
    for seg_kind, idx in plan.segments:
        K = _bucket(len(idx))
        ev_seg = _gather_events(ev, idx, K, B)
        if seg_kind == "wave":
            carry = _wave_step(carry, ev_seg, n_j, ts_j)
        else:
            carry = kernel.scan_segment(carry, ev_seg, id_group_full, n_j, ts_j)
    return _finalize_impl(carry, jnp.asarray(hist_fix))


def prewarm(
    A: int, B_buckets=kernel.BATCH_BUCKETS, buckets=_SEG_BUCKETS
) -> None:
    """Compile the wave step (and the paired scan segment) for the
    given table geometry OFF the hot path: on the tunneled TPU each
    kernel costs minutes of one-time XLA compile, which must not land
    inside a timed window (device_engine.prewarm forwards its "waves"
    kind here; TB_DEV_PREWARM=waves,... opts in).  The jits are
    shape-keyed on BOTH the carry's batch bucket B and the segment
    bucket K, so the default warms every (B, K <= B) pair the router
    can produce — warming only the extremes would leave mid-size
    first-compiles (e.g. two_phase's ~B/2-event waves, bucket 4096)
    inside timed windows."""
    outs = []
    for B in B_buckets:
        ev = {
            name: np.zeros(B, np.dtype(dtype))
            for name, dtype in kernel.EVENT_FIELDS
        }
        ev["i"] = np.arange(B, dtype=np.int32)
        for K in buckets:
            if K > max(_SEG_BUCKETS) or _bucket(min(K, B)) != K:
                continue
            carry = kernel.make_carry(
                jnp.zeros((A, 8), jnp.uint64), jnp.zeros(B, jnp.uint32), B
            )
            idx = np.arange(min(K, B))
            carry = _wave_step(
                carry, _gather_events(ev, idx, K, B),
                jnp.int32(0), jnp.uint64(1),
            )
            carry = kernel.scan_segment(
                carry, _gather_events(ev, idx, K, B),
                jnp.asarray(ev["id_group"]), jnp.int32(0), jnp.uint64(1),
            )
            outs.append(_finalize_impl(carry, jnp.zeros(B, bool)))
    jax.block_until_ready(outs)
