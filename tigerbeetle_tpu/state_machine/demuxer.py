"""Reply demultiplexing for logically-batched requests.

The primary may pack several client requests of the same operation
into one prepare (cutting consensus/commit overhead per event); the
reply then contains results for the whole event batch, and each client
must receive only the slice covering its own events, with indexes
rebased to its sub-batch (reference: src/state_machine.zig:122-176
DemuxerType; batching allowed only for create_accounts /
create_transfers — batch_logical_allowed :122-131).

Result layouts are `{index: u32, result: u32}` pairs sorted by index
(the state machine emits failures in event order), so each slice is a
binary-searchable contiguous range.
"""

from __future__ import annotations

import numpy as np

from tigerbeetle_tpu.types import CREATE_RESULT_DTYPE, Operation

# reference: src/state_machine.zig:122-131
BATCH_LOGICAL_ALLOWED = frozenset(
    {Operation.create_accounts, Operation.create_transfers}
)


def batch_logical_allowed(operation: Operation) -> bool:
    return operation in BATCH_LOGICAL_ALLOWED


# Both batchable event types are 128-byte wire records
# (reference: src/tigerbeetle.zig:7-40, :80-111).
EVENT_SIZE = 128

# Batched prepares append this trailer (one record per sub-request) so
# every replica — primary, backup, or WAL replay — demuxes and stores
# per-client replies identically.
TRAILER_DTYPE = np.dtype(
    [
        ("client_lo", "<u8"), ("client_hi", "<u8"),
        ("request", "<u4"), ("count", "<u4"),
    ]
)


def encode_trailer(subs: list[tuple[int, int, int]]) -> bytes:
    """subs: [(client u128, request, event_count)] -> trailer bytes."""
    arr = np.zeros(len(subs), TRAILER_DTYPE)
    for i, (client, request, count) in enumerate(subs):
        arr[i]["client_lo"] = client & 0xFFFFFFFFFFFFFFFF
        arr[i]["client_hi"] = client >> 64
        arr[i]["request"] = request
        arr[i]["count"] = count
    return arr.tobytes()


def decode_trailer(
    body: bytes, n_subs: int
) -> tuple[bytes, list[tuple[int, int, int]]]:
    """-> (events bytes, subs) for a batched prepare body."""
    tsize = n_subs * TRAILER_DTYPE.itemsize
    assert len(body) >= tsize, (len(body), n_subs)
    arr = np.frombuffer(body[len(body) - tsize :], TRAILER_DTYPE)
    subs = [
        (
            int(r["client_lo"]) | (int(r["client_hi"]) << 64),
            int(r["request"]),
            int(r["count"]),
        )
        for r in arr
    ]
    events = body[: len(body) - tsize]
    assert len(events) == sum(s[2] for s in subs) * EVENT_SIZE
    return events, subs


def strip_trailer(body: bytes, subs: list[tuple[int, int, int]]) -> bytes:
    return body[: len(body) - len(subs) * TRAILER_DTYPE.itemsize]


class Demuxer:
    """Splits one batched reply into per-request slices, in order.

    reference: src/state_machine.zig:133-176 — decode() consumes
    monotonically increasing (event_offset, event_count) windows.
    """

    def __init__(self, operation: Operation, reply: bytes) -> None:
        assert batch_logical_allowed(operation), operation
        self._results = np.frombuffer(reply, CREATE_RESULT_DTYPE).copy()
        assert (np.diff(self._results["index"].astype(np.int64)) >= 0).all(), (
            "results must be sorted by index"
        )
        self._consumed = 0  # events consumed so far

    def decode(self, event_offset: int, event_count: int) -> bytes:
        """Results for events [event_offset, event_offset+event_count),
        rebased so the caller sees indexes starting at 0."""
        assert event_offset == self._consumed, (event_offset, self._consumed)
        idx = self._results["index"]
        lo = int(np.searchsorted(idx, event_offset, side="left"))
        hi = int(np.searchsorted(idx, event_offset + event_count, side="left"))
        out = self._results[lo:hi].copy()
        out["index"] -= np.uint32(event_offset)
        self._consumed += event_count
        return out.tobytes()
