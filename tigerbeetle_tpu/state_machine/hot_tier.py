"""Hot/cold account tiering: an HBM-resident hot set over the Zipf head.

Every device path used to be capacity-bound to one HBM-resident table
(kernel_fast.DeviceTable, device_engine.DeviceEngine) while the
reference serves unbounded state from an LSM forest.  Reddio's shape
(arXiv:2503.04595) decouples execution from state residency: compute
the batch's touched-account set up front, prefetch the cold rows into
the device table BEFORE the execution step, and let HBM act as a cache
over the logical table instead of a hard ceiling on it.

This module owns the host-side tier state shared by both engine modes:

- ``HotTier``: the logical<->hot slot maps, LRU admission/eviction over
  a fixed hot-row budget, and the hit/miss/evict/prefetch counters the
  obs layer and bench rows read.
- The shared growth-policy helpers (``grow_zero_host`` /
  ``grow_zero_device``) behind the three previously near-identical
  ``grow()`` implementations (kernel_fast / mirror / device_engine) —
  tiering hooks ONE resize path, not three.
- ``mirror_hot_table8``: the hot-shaped upload/compare image built from
  the host mirror (the COLD TIER: the full logical table always lives
  in BalanceMirror host-side, persisted by the same checkpoint/LSM
  machinery as before — tiering changes which rows the DEVICE holds,
  never where the truth lives).

Protocol invariants (DESIGN.md "Hot/cold account tiering"):

- The hot map only changes against a QUIESCED device pipeline: every
  admission first drains in-flight windows and flushes the write-behind
  lane, so evicted rows are clean by construction (their bytes already
  landed on the mirror through the same lane that wrote them) and every
  packed batch launches under the map it was translated with.
- The 16-byte state root keeps covering the WHOLE logical table:
  the host commitment twin is logical-capacity-shaped and unchanged;
  the device maintains the HOT PARTIAL (per-row hashes bound to
  LOGICAL row ids), and ``fold(hot_partial, cold_partial) == root``
  because the r15 fold is an order-independent per-lane sum
  (commitment.HostCommitment.partial gives the host-side hot partial;
  cold_partial = digest - hot_partial).

``TB_HOT_CAPACITY`` (envcheck.hot_capacity) sizes the hot set; the
default 0 means all-resident — ``from_env`` returns None and every
caller's tiering branch is dead, bit-for-bit today's behavior.
"""

from __future__ import annotations

import numpy as np

from tigerbeetle_tpu import envcheck


def grow_zero_host(array: np.ndarray, capacity: int) -> np.ndarray:
    """Zero-widen a host (rows, ...) array to `capacity` rows.

    Returns the input unchanged when already wide enough.  All-zero
    rows hash to 0 under the commitment formula, so growth through
    this helper can never move a state root.
    """
    if capacity <= len(array):
        return array
    out = np.zeros((capacity,) + array.shape[1:], array.dtype)
    out[: len(array)] = array
    return out


def grow_zero_device(table, capacity: int, sharding, place):
    """Zero-widen a device (rows, C) table to `capacity` rows.

    Dense tables concatenate on-device (async — growth must not
    introduce a host round-trip on the commit path); sharded tables
    reshard through the host via `place` (row boundaries move between
    devices).  `table` may be a host array already fetched by the
    caller (the engine's was-sharded grow path).
    """
    import jax
    import jax.numpy as jnp

    have = table.shape[0]
    if capacity <= have:
        return table
    extra = jnp.zeros((capacity - have,) + table.shape[1:], table.dtype)
    if sharding is None:
        return jnp.concatenate([table, extra])
    return place(jnp.concatenate([jax.device_get(table), extra]))


def mirror_hot_table8(mirror, logical_of: np.ndarray) -> np.ndarray:
    """Hot-shaped (hot_rows, 8) device-layout image of the mirror:
    row i holds logical row logical_of[i], zeros for free hot slots —
    the upload/health-compare image for a TIERED device table (the
    tiered twin of BalanceMirror.table8)."""
    out = np.zeros((len(logical_of), 8), np.uint64)
    occ = logical_of >= 0
    rows = logical_of[occ]
    out[occ, 0::2] = mirror.lo[rows]
    out[occ, 1::2] = mirror.hi[rows]
    return out


def from_env(logical_capacity: int) -> "HotTier | None":
    """Build the tier for a table of `logical_capacity` rows, or None
    when TB_HOT_CAPACITY leaves the table all-resident (0/unset, or a
    budget that already covers every row).  Read at CONSTRUCTION time
    (the envcheck knob discipline), so one bench process can compare
    arms under different env settings."""
    budget = envcheck.hot_capacity()
    if budget <= 0 or budget >= logical_capacity:
        return None
    return HotTier(logical_capacity, budget)


class HotTier:
    """Logical<->hot maps + LRU admission over a fixed hot-row budget.

    Counters are plain host ints (readable in both engine modes with
    zero obs dependency); when the owning state machine binds a
    ``stats`` sink (device_engine.make_tier_stats), mutations also land
    on the machine's metrics registry as dev_tier.* counters.
    """

    def __init__(self, logical_capacity: int, hot_rows: int) -> None:
        assert 0 < hot_rows < logical_capacity
        self.hot_rows = hot_rows
        self.logical_capacity = logical_capacity
        # logical row -> hot slot (-1 = cold).
        self.hot_of = np.full(logical_capacity, -1, np.int64)
        # hot slot -> logical row (-1 = free).
        self.logical_of = np.full(hot_rows, -1, np.int64)
        # LRU stamps: one monotone clock tick per batch keeps victim
        # selection frequency/recency-ordered over the Zipf head.
        self._stamp = np.zeros(hot_rows, np.int64)
        self._clock = 0
        self.hits = 0
        self.misses = 0
        self.evicts = 0
        self.prefetches = 0
        self.prefetch_stall_us = 0.0
        self.stats = None  # optional dev_tier.* registry sink

    # -- planning ------------------------------------------------------

    def plan(self, slots) -> tuple[np.ndarray, np.ndarray]:
        """(unique_logical, missing_logical) of a batch's touched set
        (negative entries — not-found joins — are ignored)."""
        uniq = np.unique(np.asarray(slots, np.int64))
        uniq = uniq[uniq >= 0]
        if len(uniq) == 0:
            return uniq, uniq
        return uniq, uniq[self.hot_of[uniq] < 0]

    def record_use(self, rows: np.ndarray, hits: int, misses: int) -> None:
        """Stamp the batch's (now-resident) rows for LRU and count the
        hit/miss split; one clock tick per batch."""
        self._clock += 1
        hot = self.hot_of[rows]
        self._stamp[hot[hot >= 0]] = self._clock
        self.hits += hits
        self.misses += misses
        if self.stats is not None:
            if hits:
                self.stats["hit"].inc(hits)
            if misses:
                self.stats["miss"].inc(misses)

    # -- admission -----------------------------------------------------

    def admit(self, missing: np.ndarray, protect: np.ndarray,
              partial: bool = False):
        """Assign hot slots to cold `missing` rows, reusing free slots
        first and then evicting the least-recently-used occupants whose
        logical rows are not in `protect` (the batch's own touched
        set).  Returns (admitted_logical, hot_slots, evicted_logical);
        None when the batch cannot fit and partial=False (caller takes
        the host path).  With partial=True a prefix of `missing` is
        admitted and the rest stays cold (host-mode write-behind, where
        the mirror is authoritative and cold deltas are simply
        dropped).  The CALLER holds the pipeline quiesced."""
        need = len(missing)
        free = np.flatnonzero(self.logical_of < 0)
        take_free = free[:need]
        n_evict = need - len(take_free)
        victims = np.zeros(0, np.int64)
        if n_evict > 0:
            occupied = np.flatnonzero(self.logical_of >= 0)
            evictable = occupied[
                ~np.isin(self.logical_of[occupied], protect)
            ]
            if len(evictable) < n_evict:
                if not partial:
                    return None
                n_evict = len(evictable)
            if n_evict > 0:
                order = np.argsort(self._stamp[evictable], kind="stable")
                victims = evictable[order[:n_evict]]
        hot_slots = np.concatenate([take_free, victims])
        admitted = missing[: len(hot_slots)]
        evicted = self.logical_of[victims]
        if len(evicted):
            self.hot_of[evicted] = -1
        self.hot_of[admitted] = hot_slots
        self.logical_of[hot_slots] = admitted
        self._stamp[hot_slots] = self._clock
        self.evicts += len(evicted)
        self.prefetches += 1
        if self.stats is not None:
            if len(evicted):
                self.stats["evict"].inc(len(evicted))
            self.stats["prefetch"].inc()
        return admitted, hot_slots, evicted

    def note_stall(self, seconds: float) -> None:
        """Account one admission barrier's wall time (the drain+flush+
        upload the batch waited on before its device step)."""
        us = seconds * 1e6
        self.prefetch_stall_us += us
        if self.stats is not None:
            self.stats["prefetch_stall_us"].inc(us)
            self.stats["prefetch_us"].observe(us)

    # -- geometry ------------------------------------------------------

    def occupied(self) -> np.ndarray:
        """Logical rows currently resident (any order)."""
        return self.logical_of[self.logical_of >= 0]

    def grow_logical(self, capacity: int) -> None:
        """Widen the logical address space; the hot-row budget is a
        fixed HBM allowance and stays put (that is the point: growth
        of the LOGICAL table no longer implies HBM growth)."""
        self.hot_of = grow_zero_host(self.hot_of, capacity)
        if capacity > self.logical_capacity:
            # grow_zero_host zero-fills; new rows are cold, not slot 0.
            self.hot_of[self.logical_capacity : capacity] = -1
            self.logical_capacity = capacity

    def translate(self, arr: np.ndarray) -> np.ndarray:
        """Hot-space copy of a logical slot array; negative entries
        (not-found joins) pass through unchanged.  Callers prefetch
        first, so mapped entries are never -1."""
        out = np.asarray(arr, np.int64).copy()
        m = out >= 0
        out[m] = self.hot_of[out[m]]
        return out
