"""TPU-backed state machine: host orchestration around the JAX kernel.

Same external interface as ``CpuStateMachine`` (input_valid / prepare /
pulse_needed / prefetch / commit over wire bytes), so the two are
interchangeable under the test harness and diffable bit-for-bit.

State split (see kernel.py header):
- DEVICE: the account *balance* table, (A, 8) uint64 — four u128
  balances as limb pairs. This is the only mutable per-account state
  (reference: src/tigerbeetle.zig:7-29 — every other Account field is
  immutable after create_accounts).
- HOST: id directories (LSM-style sorted runs, vectorized lookup),
  immutable account attributes, the columnar transfer store + pending
  statuses + expires_at index + historical balances. All hot-path host
  work is numpy-vectorized; per-event Python runs only for
  create_accounts (not the benchmark's hot operation) and rare pulse
  bookkeeping.

The commit flow for create_transfers mirrors the reference pipeline
(reference: src/vsr/replica.zig:3746-3847 prefetch->commit):
host static ladder + joins ~ prefetch; kernel scan ~ execute; host
post-processing ~ the groove inserts the reference does inline.
"""

from __future__ import annotations

import time as _time

import numpy as np

import jax
import jax.numpy as jnp

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import envcheck
from tigerbeetle_tpu import types
from tigerbeetle_tpu.lsm import pack_u128
from tigerbeetle_tpu.obs import stat_property as obs_stat_property
from tigerbeetle_tpu.utils import HashIndex, RunIndex
from tigerbeetle_tpu.state_machine import kernel, kernel_fast, resolve, waves
from tigerbeetle_tpu.state_machine.mirror import BalanceMirror, _sub_u128
from tigerbeetle_tpu.state_machine.cpu import CpuStateMachine
from tigerbeetle_tpu.types import (
    ACCOUNT_BALANCE_DTYPE,
    ACCOUNT_DTYPE,
    ACCOUNT_FILTER_DTYPE,
    CREATE_RESULT_DTYPE,
    NS_PER_S,
    TIMESTAMP_MAX,
    TIMESTAMP_MIN,
    TRANSFER_DTYPE,
    U64_MAX,
    U128_MAX,
    AccountFilterFlags,
    AccountFlags,
    CreateAccountResult,
    CreateTransferResult,
    Operation,
    TransferFlags,
    TransferPendingStatus,
)

# Tight device-input gate: amounts must fit u32 (tests shrink this to
# force the wide format on the same stream).
_TIGHT_AMOUNT_LIMIT = 1 << 32

AF = AccountFlags
TF = TransferFlags
CAR = CreateAccountResult
CTR = CreateTransferResult

_BATCH_BUCKETS = kernel.BATCH_BUCKETS

# Columnar transfer-store fields.
_STORE_FIELDS = {
    "id_lo": np.uint64, "id_hi": np.uint64,
    "dr_slot": np.int32, "cr_slot": np.int32,
    "amount_lo": np.uint64, "amount_hi": np.uint64,
    "pending_lo": np.uint64, "pending_hi": np.uint64,
    "ud128_lo": np.uint64, "ud128_hi": np.uint64,
    "ud64": np.uint64, "ud32": np.uint32,
    "timeout": np.uint32, "ledger": np.uint32, "code": np.uint32,
    "flags": np.uint32, "timestamp": np.uint64,
    "status": np.uint8,  # TransferPendingStatus for pending transfers
}

_ATTR_FIELDS = {
    "id_lo": np.uint64, "id_hi": np.uint64,
    "ud128_lo": np.uint64, "ud128_hi": np.uint64,
    "ud64": np.uint64, "ud32": np.uint32,
    "ledger": np.uint32, "code": np.uint32, "flags": np.uint32,
    "timestamp": np.uint64,
}

_HISTORY_FIELDS = {
    "timestamp": np.uint64,
    "dr_id_lo": np.uint64, "dr_id_hi": np.uint64,
    "cr_id_lo": np.uint64, "cr_id_hi": np.uint64,
    "dr_bal": (np.uint64, 8), "cr_bal": (np.uint64, 8),
}


def _amount_bound_total(amount_lo: np.ndarray, amount_hi: np.ndarray) -> int:
    """Exact host-integer sum of (lo, hi) u128 amount bounds via 32-bit
    limb sums (each limb sum < 2^21 * 2^32 < 2^64) — the in-flight
    admission bookkeeping the device engine's wave dispatch keeps."""
    m32 = np.uint64(0xFFFFFFFF)
    return (
        int((amount_lo & m32).sum(dtype=np.uint64))
        + (int((amount_lo >> np.uint64(32)).sum(dtype=np.uint64)) << 32)
        + (int((amount_hi & m32).sum(dtype=np.uint64)) << 64)
        + (int((amount_hi >> np.uint64(32)).sum(dtype=np.uint64)) << 96)
    )


def _zeros_touched(shape, dtype) -> np.ndarray:
    """Zeroed array with pages faulted in up front: appends write into
    fresh pages, and eager sequential touching is ~4x cheaper than
    faulting page-by-page from scattered slice writes."""
    a = np.empty(shape, dtype)
    a.fill(0)
    return a


class Columns:
    """Growable columnar array store with vectorized batch append."""

    def __init__(self, fields: dict, capacity: int = 1024) -> None:
        self._fields = fields
        self.count = 0
        self._cap = capacity
        self._cols = {}
        for name, spec in fields.items():
            if isinstance(spec, tuple):
                dtype, width = spec
                self._cols[name] = _zeros_touched((capacity, width), dtype)
            else:
                self._cols[name] = _zeros_touched(capacity, spec)

    def _ensure(self, extra: int) -> None:
        need = self.count + extra
        if need <= self._cap:
            return
        while self._cap < need:
            self._cap *= 4
        for name, col in self._cols.items():
            shape = (self._cap,) + col.shape[1:]
            new = np.empty(shape, col.dtype)
            new[: self.count] = col[: self.count]
            new[self.count :].fill(0)
            self._cols[name] = new

    def append(self, **arrays) -> np.ndarray:
        n = len(next(iter(arrays.values())))
        self._ensure(n)
        lo, hi = self.count, self.count + n
        for name, arr in arrays.items():
            self._cols[name][lo:hi] = arr
        self.count = hi
        return np.arange(lo, hi)

    def truncate(self, count: int) -> None:
        assert count <= self.count
        self.count = count

    def col(self, name: str) -> np.ndarray:
        return self._cols[name][: self.count]

    def __getitem__(self, name: str) -> np.ndarray:
        return self._cols[name]


class _GlobalCol:
    """Indexing proxy translating GLOBAL rows to the RAM tail or the
    LSM spill tier (read-only below the spill base)."""

    __slots__ = ("_store", "_name")

    def __init__(self, store: "TailStore", name: str) -> None:
        self._store = store
        self._name = name

    def __getitem__(self, rows):
        return self._store.gather(self._name, rows)

    def __setitem__(self, rows, values) -> None:
        base = self._store.base
        if np.isscalar(rows) or isinstance(rows, (int, np.integer)):
            rows = np.array([rows], np.int64)
            values = np.asarray([values])
        else:
            rows = np.asarray(rows)
            values = np.broadcast_to(np.asarray(values), rows.shape)
        in_ram = rows >= base
        if in_ram.any():
            self._store.ram[self._name][
                rows[in_ram] - base + self._store._off
            ] = values[in_ram]
        if not in_ram.all():
            # Spilled objects are immutable EXCEPT the pending status
            # byte, which post/void/expiry finalize in place.
            assert self._name == "status", "write to spilled row"
            self._store.spill.update_status(rows[~in_ram], values[~in_ram])


class TailStore:
    """Columnar store whose rows [0, base) have spilled into an LSM
    groove (state_machine/spill.py) and whose tail [base, count) stays
    in RAM — the hot append path never touches the LSM.

    Global row numbers are stable across spills: the id directories,
    the expiry index, and the native library all keep global rows.
    """

    def __init__(self, fields: dict, capacity: int = 1024) -> None:
        self.ram = Columns(fields, capacity)
        self.base = 0
        # Dead physical rows at the front of `ram` (already spilled):
        # drop_prefix advances this offset in O(1) and compacts only
        # when dead rows dominate — per-beat spills must not pay an
        # O(tail) memmove on the commit path.
        self._off = 0
        self.spill = None  # TransferSpill once a forest is attached

    @property
    def count(self) -> int:
        return self.base + self.ram.count - self._off

    def append(self, **arrays) -> np.ndarray:
        return self.ram.append(**arrays) - self._off + self.base

    def col(self, name: str) -> np.ndarray:
        """Live RAM-tail view; index 0 corresponds to global row
        .base."""
        return self.ram.col(name)[self._off :]

    def tail_count(self) -> int:
        return self.ram.count - self._off

    def __getitem__(self, name: str) -> _GlobalCol:
        return _GlobalCol(self, name)

    def _phys(self, rows):
        return rows - self.base + self._off

    def gather(self, name: str, rows):
        from tigerbeetle_tpu.state_machine import spill as spill_mod

        if np.isscalar(rows) or isinstance(rows, (int, np.integer)):
            if rows >= self.base:
                return self.ram[name][self._phys(rows)]
            obj = self.spill.gather(np.array([rows], np.int64))
            return spill_mod.unpack_objects(obj)[name][0]
        rows = np.asarray(rows)
        if len(rows) == 0 or (self.base == 0 or (rows >= self.base).all()):
            return self.ram[name][self._phys(rows)]
        out = np.empty(len(rows), self.ram[name].dtype)
        in_ram = rows >= self.base
        out[in_ram] = self.ram[name][self._phys(rows[in_ram])]
        cold = ~in_ram
        obj = self.spill.gather(rows[cold])
        out[cold] = spill_mod.unpack_objects(obj)[name]
        return out

    def gather_many(self, names: list[str], rows: np.ndarray) -> dict:
        """One spill fetch for many columns (exact-path joins)."""
        from tigerbeetle_tpu.state_machine import spill as spill_mod

        rows = np.asarray(rows)
        in_ram = rows >= self.base
        if in_ram.all():
            phys = self._phys(rows)
            return {n: self.ram[n][phys] for n in names}
        cold_rows = rows[~in_ram]
        cold = spill_mod.unpack_objects(self.spill.gather(cold_rows))
        phys = np.maximum(self._phys(rows), 0)
        out = {}
        for n in names:
            vals = self.ram[n][phys].copy()
            vals[~in_ram] = cold[n]
            out[n] = vals
        return out

    def drop_prefix(self, n: int) -> None:
        """Advance base after `n` rows spilled (caller already wrote
        them to the groove).  O(1); the physical compaction amortizes."""
        assert n <= self.tail_count()
        self._off += n
        self.base += n
        # Compact when dead >= live: the move cost (live rows) is then
        # bounded by the rows dropped since the last compaction, i.e.
        # amortized O(1) per spilled row.
        if self._off and self._off * 2 >= self.ram.count:
            keep = self.ram.count - self._off
            for _name, colarr in self.ram._cols.items():
                colarr[:keep] = colarr[self._off : self.ram.count]
            self.ram.count = keep
            self._off = 0


def _dir_capacity(entries: int) -> int:
    """Pow2 hash capacity holding `entries` at <=50% load (the hash is
    the RunIndex fallback for non-sequential ids; presizing it keeps
    random-id workloads from rehashing on the commit hot path)."""
    return max(1 << 16, 1 << (2 * max(entries, 1)).bit_length())


def _first_code(shape) -> np.ndarray:
    return np.zeros(shape, np.uint32)


def _apply_code(result: np.ndarray, cond: np.ndarray, code: int) -> None:
    np.copyto(result, np.uint32(code), where=(result == 0) & cond)


class TpuStateMachine:
    """Accounting state machine with a JAX/TPU create_transfers path."""

    def __init__(
        self,
        config: cfg.Config = cfg.PRODUCTION,
        account_capacity: int = 1 << 16,
        transfer_capacity: int = 1 << 16,
        engine: str | None = None,
        prewarm: str | list | None = None,
        device_link=None,
    ) -> None:
        """Capacities follow the reference's static-allocation design:
        all large buffers are sized up front from operator-configured
        limits (reference: docs/DESIGN.md static allocation;
        src/config.zig storage limits), so the steady-state commit path
        never grows or faults fresh pages.

        `engine` selects the create_transfers execution authority:
        - "host" (default): host C++/numpy resolvers compute result
          codes; the device table is a write-behind replica
          (round-3 architecture — lowest latency on this link).
        - "device": result codes are computed ON the TPU by the
          semantic kernels (device_kernels.py) through the pipelined
          DeviceEngine; the host mirror is demoted to bookkeeping,
          recovery, and checkpoint parity.  Replies materialize
          asynchronously (commit_async); commit() drains.
        Override via TB_ENGINE env var.

        `device_link` (device mode only): the DeviceLink the engine
        crosses for every upload/dispatch/fetch — tests pass a seeded
        chaos shim (testing/chaos.py) to exercise the degraded-mode
        lifecycle with no real TPU.
        """
        import os as _os

        self.config = config
        from tigerbeetle_tpu import envcheck as _envcheck

        self.engine = engine or _envcheck.env_str("TB_ENGINE", "host")
        assert self.engine in ("host", "device"), self.engine
        self._device_link = device_link
        self.prepare_timestamp = 0
        self.commit_timestamp = 0
        self.pulse_next_timestamp = TIMESTAMP_MIN

        # Metrics registry (obs/registry.py): every stat_* forensics
        # counter below is a registry handle behind a compatibility
        # property (bench resets still work), the device engine's
        # counters graft in under "dev.", and the owning ReplicaServer
        # attaches the whole tree under "sm." for TB_STATS lines and
        # the `stats` wire scrape.
        from tigerbeetle_tpu import obs

        self.metrics = obs.Registry()
        _c = self.metrics.counter
        self._stats = {
            # Device/host work-split accounting (reported by bench.py):
            # events whose balance effects were admitted order-free and
            # applied via device scatter-adds vs events resolved by the
            # serial exact engine (host); device-SEMANTIC split (result
            # codes computed by a device kernel) vs host.
            "stat_device_events": _c("device_events"),
            "stat_exact_events": _c("exact_events"),
            "stat_host_semantic_events": _c("host_semantic_events"),
            "stat_fallback_events": _c("fallback_events"),
            # Vectorized order-dependent resolution (resolve.py):
            # batches routed + fixpoint iterations spent.
            "stat_linked_batches": _c("linked_batches"),
            "stat_two_phase_batches": _c("two_phase_batches"),
            "stat_resolve_iters": _c("resolve_iters"),
            # Which bookkeeping tail ran (VERDICT r4 #4): the
            # all-success one-C-pass hot tail is ~2x the general tail.
            "stat_hot_tail_batches": _c("hot_tail_batches"),
            "stat_slow_tail_batches": _c("slow_tail_batches"),
            # Conflict-aware wave execution (waves.py) on the JAX
            # exact path: wave batches, device-step equivalents, and
            # the event split (waves_per_batch / wave_parallelism_pct).
            "stat_wave_batches": _c("wave.batches"),
            "stat_wave_steps": _c("wave.steps"),
            "stat_wave_events": _c("wave.events"),
            "stat_wave_parallel_events": _c("wave.parallel_events"),
            # Device-engine wave dispatch (TB_DEV_WAVES): window
            # batches executed as wave plans against the authoritative
            # HBM table, declines, step equivalents, cumulative
            # plan+admission wall time.
            "stat_dev_wave_batches": _c("dev_wave.batches"),
            "stat_dev_wave_declined": _c("dev_wave.declined"),
            "stat_dev_wave_steps": _c("dev_wave.steps"),
            "stat_dev_wave_events": _c("dev_wave.events"),
            "stat_dev_wave_plan_s": _c("dev_wave.plan_s"),
        }
        # Per-batch wave plan wall time (the cumulative counter above
        # hides the tail; the histogram is scrapeable).
        self._h_dev_wave_plan = self.metrics.histogram("dev_wave.plan_us")
        # Per-request anatomy hook (obs/anatomy.py): the owning
        # Replica shares its recorder and stamps the current prepare's
        # trace id before each commit, so commit_async can attribute
        # the device-window dispatch hop to the request's timeline.
        from tigerbeetle_tpu.obs import anatomy as anatomy_mod

        self.anatomy = anatomy_mod.NULL
        self.anatomy_trace = 0

        # Account state. The device table is authoritative; the host
        # mirror serves routing decisions and balance reads without
        # blocking on the device link (see mirror.py / kernel_fast.py).
        self._acct_dir = RunIndex(_dir_capacity(account_capacity))
        self._attrs = Columns(_ATTR_FIELDS, capacity=max(1024, account_capacity))
        self._mirror = BalanceMirror(account_capacity)
        # Incremental state commitment (commitment.py): the host twin
        # rides the mirror — every mirror mutation re-hashes exactly
        # the rows it touched — with meta columns read live from the
        # attribute store (survives native re-pointing + restores).
        # Attached BEFORE the device engine so both sides share it.
        self._commitment = None
        if envcheck.state_commit() == 1:
            from tigerbeetle_tpu.state_machine import (
                commitment as commitment_mod,
            )

            self._commitment = commitment_mod.HostCommitment(
                account_capacity, meta_fn=self._commit_meta_cols
            )
            self._mirror.commitment = self._commitment
        if self.engine == "device":
            from tigerbeetle_tpu.state_machine.device_engine import (
                DeviceEngine,
            )

            self._dev = DeviceEngine(
                account_capacity, self._mirror, link=device_link,
                metrics=self.metrics.scope("dev"),
            )
            # Speculative-execution counters live on the MACHINE
            # registry (dev_wave.spec.*, next to the dev_wave.*
            # routing stats) so the stats scrape and flight postmortem
            # carry them; the engine increments the shared handles.
            from tigerbeetle_tpu.state_machine.device_engine import (
                make_spec_stats,
            )

            self._dev.spec_stats = make_spec_stats(self.metrics)
            self._bind_tier_stats()
            # Off-hot-path warmup of the named kinds' transfer plans +
            # scan compiles (bench passes these per config;
            # construction happens during untimed setup).
            from tigerbeetle_tpu import envcheck as _envcheck

            warm_kinds = prewarm or _envcheck.env_str(
                "TB_DEV_PREWARM", ""
            )
            if warm_kinds:
                self._dev.prewarm(
                    warm_kinds.split(",")
                    if isinstance(warm_kinds, str)
                    else warm_kinds
                )
        else:
            self._dev = kernel_fast.DeviceTable(account_capacity)
            self._dev.mirror = self._mirror
            self._bind_tier_stats()
        # Native C++ fast path (native/tb_fastpath.cpp): wire decode,
        # static ladder, account resolution, duplicate detection and
        # u128 overflow admission run natively; the balance mirror is
        # re-pointed at the native library's memory so both sides share
        # one copy.  Absent a compiler, everything runs in Python.
        self._native = None
        try:
            from tigerbeetle_tpu.runtime import fastpath

            if fastpath.available():
                self._native = fastpath.NativeFastpath(account_capacity)
                self._mirror.lo = self._native.lo
                self._mirror.hi = self._native.hi
        except envcheck.EnvVarError:
            # A typo'd knob (TB_NATIVE_SANITIZE=msan) must fail fast
            # with its named error, not read as "no compiler" — a
            # silently-unsanitized run is exactly the confusion the
            # build forensics exist to prevent.
            raise
        # tbcheck: allow(broad-except): the native fast path is an
        # optional accelerator — ANY load/ctypes/ABI failure must fall
        # back to the pure-Python engines, bit-identically.
        except Exception:
            self._native = None

        # Transfer state.
        self._tdir = RunIndex(_dir_capacity(transfer_capacity))
        self._store = TailStore(
            _STORE_FIELDS, capacity=max(1024, transfer_capacity)
        )
        # expires_at index: (expires_at, row, active).  Rows are GLOBAL
        # store rows; live pendings never spill, so active entries
        # always resolve in the RAM tail.
        self._exp = Columns(
            {"expires_at": np.uint64, "row": np.uint32, "active": np.bool_}
        )
        self._history = Columns(_HISTORY_FIELDS)

        # LSM spill tier (attach_forest): None in standalone mode —
        # everything stays in RAM, as in the benchmark harness.  The
        # replica attaches a Forest so state scales past host RAM.
        self._forest = None
        self._hspill = None

        self._expiry_rows: np.ndarray | None = None
        self._exp_dead = 0

        self._inflight_timeouts = False
        # Declines by reason ("plan" = admission/profitability, "mesh"
        # = unsupported sharding geometry, "shard_plan" = plan shape
        # the SPMD executors don't cover, "degraded" = engine lost the
        # link mid-probe): measured, not guessed — bench reports it.
        # The dict is the bench-resettable window view; cumulative
        # per-reason registry counters ride under dev_wave.decline.*.
        self.stat_dev_wave_decline_reasons: dict = {}

    # Compatibility properties: migrated stat_* counters live in the
    # metrics registry (reads and writes route to handles, so bench's
    # between-arm resets keep working).
    stat_device_events = obs_stat_property("stat_device_events")
    stat_exact_events = obs_stat_property("stat_exact_events")
    stat_host_semantic_events = obs_stat_property("stat_host_semantic_events")
    stat_fallback_events = obs_stat_property("stat_fallback_events")
    stat_linked_batches = obs_stat_property("stat_linked_batches")
    stat_two_phase_batches = obs_stat_property("stat_two_phase_batches")
    stat_resolve_iters = obs_stat_property("stat_resolve_iters")
    stat_hot_tail_batches = obs_stat_property("stat_hot_tail_batches")
    stat_slow_tail_batches = obs_stat_property("stat_slow_tail_batches")
    stat_wave_batches = obs_stat_property("stat_wave_batches")
    stat_wave_steps = obs_stat_property("stat_wave_steps")
    stat_wave_events = obs_stat_property("stat_wave_events")
    stat_wave_parallel_events = obs_stat_property("stat_wave_parallel_events")
    stat_dev_wave_batches = obs_stat_property("stat_dev_wave_batches")
    stat_dev_wave_declined = obs_stat_property("stat_dev_wave_declined")
    stat_dev_wave_steps = obs_stat_property("stat_dev_wave_steps")
    stat_dev_wave_events = obs_stat_property("stat_dev_wave_events")
    stat_dev_wave_plan_s = obs_stat_property("stat_dev_wave_plan_s")

    @property
    def stat_device_semantic_events(self) -> int:
        """Events whose result codes were computed on device."""
        return (
            self._dev.stat_semantic_events if self.engine == "device" else 0
        )

    @property
    def _balances(self):
        """Current device table handle behind a flush barrier."""
        return self._dev.read()

    @_balances.setter
    def _balances(self, value) -> None:
        # write_back gathers hot rows under tiering (plain handle swap
        # all-resident) — never assign self._dev.balances directly.
        self._dev.write_back(value)

    def sync(self) -> None:
        """Drain the write-behind queue and wait for the device."""
        jax.block_until_ready(self._dev.read())

    def _engine_drain(self) -> None:
        if self.engine == "device":
            self._dev.drain()

    def _bind_tier_stats(self) -> None:
        """Bind MACHINE-registry dev_tier.* handles to the hot tier
        (both engine modes; no-op all-resident) — same contract as the
        dev_wave.spec.* binding above."""
        hot = getattr(self._dev, "hot", None)
        if hot is None:
            return
        from tigerbeetle_tpu.state_machine.device_engine import (
            make_tier_stats,
        )

        hot.stats = make_tier_stats(self.metrics)

    def _commit_meta_cols(self, slots: np.ndarray) -> np.ndarray:
        """(k, 2) uint32 account-meta columns (flags, ledger) for the
        state commitment — read live from the attribute store, zeros
        past the live account count (matching the engine's meta
        table, where rolled-back/unused slots are zero)."""
        slots = np.asarray(slots, np.int64)
        out = np.zeros((len(slots), 2), np.uint32)
        m = slots < self._attrs.count
        if m.any():
            out[m, 0] = self._attrs.col("flags")[slots[m]]
            out[m, 1] = self._attrs.col("ledger")[slots[m]]
        return out

    def _commit_touch_accounts(self, n0: int) -> None:
        """Fold accounts created since slot n0 (their meta columns
        just became nonzero) into the host commitment twin.  Device
        engines already refreshed these rows in
        DeviceEngine.add_accounts (via _sync_engine_meta, which runs
        first at both call sites) — re-hashing them here would be an
        idempotent double pay."""
        if self._commitment is None or self._attrs.count <= n0:
            return
        if self.engine == "device":
            return
        self._commitment.refresh(
            np.arange(n0, self._attrs.count, dtype=np.int64), self._mirror
        )

    def state_root(self) -> bytes:
        """16-byte state commitment of the account table (balances +
        meta), current to the last materialized commit: the
        incrementally-maintained twin when TB_STATE_COMMIT=1, a
        from-scratch digest of the same value otherwise.  Read-only —
        never touches the device link (healthy, degraded, and
        recovering engines all agree with the host by contract; the
        scrub/handshake/checkpoint tripwires enforce it)."""
        from tigerbeetle_tpu.state_machine import commitment as cm

        if self._commitment is not None:
            return self._commitment.root_bytes()
        n = self._attrs.count
        bal8 = np.empty((n, 8), np.uint64)
        bal8[:, 0::2] = self._mirror.lo[:n]
        bal8[:, 1::2] = self._mirror.hi[:n]
        meta = self._commit_meta_cols(np.arange(n, dtype=np.int64))
        return cm.root_bytes(cm.table_digest(bal8, meta))

    def verify_device_mirror(self) -> None:
        """Compare the device balance table against the host mirror via
        an order-independent digest; crash loudly on divergence
        (VERDICT r3 #4).  Called from the checkpoint barrier.  In
        degraded mode the mirror IS the authoritative table, so there
        is nothing to compare (and no device work that could be done)
        — the handshake that matters there is re-promotion's
        (device_engine.try_repromote).

        With the incremental commitment live the compare is 32 fetched
        bytes (device maintained digest + from-scratch recompute vs
        the host twin); the full-table fetch runs only to NAME the
        diverged rows in the crash message."""
        from tigerbeetle_tpu.state_machine import device_kernels as dk
        from tigerbeetle_tpu.state_machine.device_engine import (
            DeviceLostError,
        )

        dev = self._dev
        if getattr(dev, "state", None) is not None:
            if dev.state is not types.EngineState.healthy:
                return
            if (
                dev._commit_enabled
                and self._commitment is not None
                and dev.dev_digest is not None
            ):
                from tigerbeetle_tpu.state_machine import commitment as cm

                dev.drain()
                dev.flush()
                if dev.state is not types.EngineState.healthy:
                    return
                try:
                    pair = np.asarray(dev.commit_probe())
                except DeviceLostError as exc:
                    dev._demote(exc)
                    return
                twin = self._commitment.digest
                # Tiered, the device digest is the HOT PARTIAL of the
                # logical root: fold(hot, cold) == twin.digest by the
                # r15 order-independent algebra, so comparing the
                # partial attests the device AND (via twin ==
                # host_scratch below) the whole logical table.
                expected_dev = (
                    self._commitment.partial(dev.hot.occupied())
                    if dev.hot is not None
                    else twin
                )
                # Checkpoint tripwire = the strongest compare: the
                # device's maintained digest, its from-scratch
                # recompute, the incrementally-maintained host twin,
                # AND a from-scratch host digest of the mirror must
                # all agree — so device drift, HBM corruption, twin
                # drift, and out-of-band mirror mutation each die
                # here, four-way-attributed.  (The host pass costs
                # what the old checksum8 compare cost; the CHEAP
                # 16-byte compares are scrub's and the handshake's.)
                n_rows = len(self._mirror.lo)
                bal8 = np.empty((n_rows, 8), np.uint64)
                bal8[:, 0::2] = self._mirror.lo
                bal8[:, 1::2] = self._mirror.hi
                host_scratch = cm.table_digest(
                    bal8,
                    self._commit_meta_cols(
                        np.arange(n_rows, dtype=np.int64)
                    ),
                )
                if (
                    (pair[0] == pair[1]).all()
                    and (pair[1] == expected_dev).all()
                    and (twin == host_scratch).all()
                ):
                    return
                try:
                    rows = dev._localize_divergence()
                    detail = (
                        f"{len(rows)} rows diverged"
                        f" (first: {rows[:8].tolist()})"
                    )
                except DeviceLostError as exc:
                    detail = f"localization fetch failed: {exc!r}"
                raise AssertionError(
                    "device/mirror commitment divergence at checkpoint: "
                    f"{detail}; device(maintained, scratch)={pair.tolist()} "
                    f"twin={twin.tolist()} "
                    f"host_scratch={host_scratch.tolist()}"
                )
            if dev.hot is not None:
                # Tiered without commitment: dev.checksum() answers
                # from the mirror (trivially equal) — compare the
                # hot-shaped device tables against the hot-shaped host
                # images instead.
                dev.drain()
                dev.flush()
                if dev.state is not types.EngineState.healthy:
                    return
                try:
                    dev_sum = dev._device_health_digest()
                except DeviceLostError as exc:
                    dev._demote(exc)
                    return
                host_sum = dev._host_health_digest()
            else:
                dev_sum = dev.checksum()  # drains + flushes internally
                if dev.state is not types.EngineState.healthy:
                    return  # the checksum crossing itself demoted
                host_sum = self._mirror.checksum8(dev.capacity)
        else:
            # Host-engine mode: _dev is a kernel_fast.DeviceTable.
            if dev.hot is not None:
                # Tiered: read() serves the logical table FROM the
                # mirror — compare the actual hot device table against
                # the mirror's hot-shaped image instead.
                from tigerbeetle_tpu.state_machine.hot_tier import (
                    mirror_hot_table8,
                )

                from tigerbeetle_tpu.state_machine.mirror import (
                    digest_columns,
                )

                dev.flush()
                dev_sum = digest_columns(np.asarray(dev.balances))
                host_sum = digest_columns(
                    mirror_hot_table8(self._mirror, dev.hot.logical_of)
                )
            else:
                table = dev.read()
                dev_sum = np.asarray(dk.checksum(table))
                host_sum = self._mirror.checksum8(int(table.shape[0]))
        if not (dev_sum == host_sum).all():
            raise AssertionError(
                "device/mirror balance divergence at checkpoint: "
                f"device={dev_sum.tolist()} host={host_sum.tolist()}"
            )

    # ------------------------------------------------------------------
    # LSM spill tier (replica mode).

    def attach_forest(self, forest) -> None:
        """Wire the LSM forest in: transfers + history grooves back the
        columnar stores so durable state scales past host RAM
        (reference: src/lsm/forest.zig:31, groove.zig:136-176)."""
        from tigerbeetle_tpu.state_machine import spill as spill_mod

        assert self._forest is None
        self._forest = forest
        transfers = forest.groove(
            "transfers",
            object_size=spill_mod.TRANSFER_OBJECT_SIZE,
            index_fields=["dr_slot", "cr_slot"],
            index_value_size=8,
        )
        # Index entries are 25B vs 161B objects; sealing them 8x less
        # often keeps their levels shallow (every index run overlaps —
        # (slot, ts) keys never move-optimize), cutting merge rewrite
        # volume on the commit path.
        for tree in transfers.indexes.values():
            tree.memtable_max *= 8
        # Object rows arrive one 8k spill beat at a time; sealing every
        # beat makes level-0 churn (and the GROWTH-way merge cascade)
        # the dominant durable-path cost.  4x fewer, 4x larger runs cut
        # the per-event seal+merge work at ~5MB of memtable RAM.
        transfers.object_tree.memtable_max *= 4
        history = forest.groove(
            "account_history",
            object_size=spill_mod.HISTORY_OBJECT_SIZE,
            index_fields=[],
        )
        self._store.spill = spill_mod.TransferSpill(
            transfers, attrs_fn=lambda: self._attrs
        )
        self._hspill = spill_mod.HistorySpill(history)

    def spill_beat(
        self, max_rows: int = 8192, keep_min: int | None = None
    ) -> int:
        """Paced spill: move at most `max_rows` of the OLDEST RAM-tail
        rows into the LSM tier, keeping the most recent `keep_min` hot
        in RAM.  Called once per commit by the replica, so the spill
        cost (and the compaction debt it creates) amortizes across the
        interval instead of landing inside the checkpoint
        (reference: src/lsm/compaction.zig — data enters the LSM per
        beat, not per checkpoint).  Deterministic: state-dependent
        only."""
        if self._forest is None:
            return 0
        if keep_min is None:
            keep_min = max(self.config.spill_keep_rows, 16_384)
        st = self._store
        if st.tail_count() <= keep_min:
            return 0
        take = min(max_rows, st.tail_count() - keep_min)
        rows = np.arange(st.base, st.base + take, dtype=np.int64)
        cols = {name: st.col(name)[:take] for name in _STORE_FIELDS}
        st.spill.spill(rows, cols, self._attrs)
        st.drop_prefix(take)
        # History spills at checkpoint only (checkpoint_spill): its
        # rows are append-only and bounded per interval, and a per-beat
        # prefix rebuild would cost more copying than it saves.
        return take

    def checkpoint_spill(self) -> None:
        """Move the whole RAM tail into the LSM tier — including live
        pendings, whose status byte stays mutable through
        TransferSpill.update_status (a stuck pending must not pin every
        later row in RAM).  Called by the replica at checkpoint —
        deterministic across replicas (state-dependent only), keeping
        checkpoint snapshots O(RAM tail), not O(history)
        (reference: src/vsr/replica.zig:3886-4039 checkpoint_data)."""
        if self._forest is None:
            return
        st = self._store
        # Retain the hot tail across checkpoints when configured: the
        # snapshot blob carries it, so checkpoint cost is O(one beat's
        # residue) instead of O(interval).
        limit = max(0, st.tail_count() - self.config.spill_keep_rows)
        if limit > 0:
            rows = np.arange(st.base, st.base + limit, dtype=np.int64)
            cols = {
                name: st.col(name)[:limit] for name in _STORE_FIELDS
            }
            st.spill.spill(rows, cols, self._attrs)
            st.drop_prefix(limit)
        # History is append-only: spill everything.
        h = self._history
        if h.count:
            self._hspill.spill(
                {name: h.col(name) for name in _HISTORY_FIELDS}
            )
            h.truncate(0)
        self._forest.checkpoint()

    # ------------------------------------------------------------------
    # Introspection helpers shared with CpuStateMachine.

    def _transfer_row(self, id_value: int) -> int | None:
        found, row = self._tdir.lookup(
            np.array([id_value & 0xFFFFFFFFFFFFFFFF], np.uint64),
            np.array([id_value >> 64], np.uint64),
        )
        return int(row[0]) if found[0] else None

    def transfer_timestamp(self, id_value: int) -> int | None:
        row = self._transfer_row(id_value)
        return None if row is None else int(self._store["timestamp"][row])

    def pending_status(self, id_value: int) -> TransferPendingStatus | None:
        row = self._transfer_row(id_value)
        if row is None:
            return None
        status = int(self._store["status"][row])
        return None if status == 0 else TransferPendingStatus(status)

    @property
    def history_count(self) -> int:
        return self._history.count

    def account_balances_raw(self, id_value: int) -> tuple | None:
        """(debits_pending, debits_posted, credits_pending,
        credits_posted) without going through a commit."""
        slot = self._account_slot(id_value)
        if slot is None:
            return None
        row = np.asarray(self._balances[slot])
        u = lambda i: int(row[i]) | (int(row[i + 1]) << 64)
        return (u(0), u(2), u(4), u(6))

    # ------------------------------------------------------------------
    # Interface plumbing (mirrors CpuStateMachine).

    def input_valid(self, operation: Operation, input_bytes: bytes) -> bool:
        return CpuStateMachine.input_valid(self, operation, input_bytes)

    def prepare(self, operation: Operation, input_bytes: bytes) -> None:
        CpuStateMachine.prepare(self, operation, input_bytes)

    def pulse_needed(self) -> bool:
        # In device mode an in-flight batch may be about to create a
        # timeout-carrying pending, which would pull
        # pulse_next_timestamp earlier — drain before deciding so the
        # pulse schedule matches the oracle exactly.  Timeout batches
        # are routed to the host path anyway, so this only fires when
        # such a batch is genuinely in flight.
        if (
            self.engine == "device"
            and self._inflight_timeouts
            and self._dev.has_inflight()
        ):
            self._engine_drain()
        if self.engine == "device" and not self._dev.has_inflight():
            self._inflight_timeouts = False
        return self.pulse_next_timestamp <= self.prepare_timestamp

    def prefetch(
        self, operation: Operation, input_bytes: bytes, prefetch_timestamp: int
    ) -> None:
        if operation == Operation.pulse:
            self._engine_drain()
            self._expiry_rows = self._scan_expired(prefetch_timestamp)

    def commit(
        self,
        client: int,
        op: int,
        timestamp: int,
        operation: Operation,
        input_bytes: bytes,
    ) -> bytes:
        return self.commit_async(
            client, op, timestamp, operation, input_bytes
        ).result()

    def commit_async(
        self,
        client: int,
        op: int,
        timestamp: int,
        operation: Operation,
        input_bytes: bytes,
    ):
        """Dispatch one committed operation; returns a ReplyFuture.

        In host-engine mode every reply resolves synchronously.  In
        device mode create_transfers batches (and lookup_accounts
        balance gathers) resolve when their summary/gather rides the
        next ring fetch — the pipelined path the benchmark and the
        replica drive (reference: the reference client pipelines
        batches the same way, src/clients/c/tb_client/packet.zig).
        """
        from tigerbeetle_tpu.state_machine.device_engine import ReplyFuture

        assert op != 0
        assert self.input_valid(operation, input_bytes)
        assert timestamp > self.commit_timestamp
        if self.anatomy_trace:
            # The request's device-window hop: when its batch was
            # handed to the engine (window admit / host dispatch).
            self.anatomy.stage(self.anatomy_trace, "device_dispatch")
        if self.engine == "device":
            # Lifecycle tick on EVERY committed operation (not just
            # transfers): re-promotion probes while degraded must fire
            # even when the workload shifts to lookups/creates, and
            # the healthy-mode scrub cadence keeps being evaluated.
            self._dev.tick()
        if operation == Operation.create_transfers:
            if self.engine == "device":
                return self._commit_create_transfers_device(
                    timestamp, input_bytes
                )
            return ReplyFuture(
                value=self._commit_create_transfers(timestamp, input_bytes)
            )
        if operation == Operation.lookup_accounts:
            if self.engine == "device" and self._dev.has_inflight():
                return self._lookup_accounts_device(input_bytes)
            return ReplyFuture(value=self._lookup_accounts(input_bytes))
        if operation == Operation.pulse:
            return ReplyFuture(value=self._commit_expire(timestamp))
        if operation == Operation.create_accounts:
            return ReplyFuture(
                value=self._commit_create_accounts(timestamp, input_bytes)
            )
        # Store-reading queries: exact only against materialized state.
        self._engine_drain()
        if operation == Operation.lookup_transfers:
            return ReplyFuture(value=self._lookup_transfers(input_bytes))
        if operation == Operation.get_account_transfers:
            return ReplyFuture(value=self._get_account_transfers(input_bytes))
        if operation == Operation.get_account_balances:
            return ReplyFuture(value=self._get_account_balances(input_bytes))
        raise AssertionError(operation)

    # ------------------------------------------------------------------
    # Accounts (cold path: per-event, exact oracle semantics).

    def _account_slot(self, id_value: int) -> int | None:
        found, slot = self._acct_dir.lookup(
            np.array([id_value & 0xFFFFFFFFFFFFFFFF], np.uint64),
            np.array([id_value >> 64], np.uint64),
        )
        return int(slot[0]) if found[0] else None

    def _sync_engine_meta(self, n0: int) -> None:
        """Register accounts created since slot n0 with the device
        engine's meta table (device-mode ladder/limit inputs)."""
        if self.engine != "device" or self._attrs.count <= n0:
            return
        slots = np.arange(n0, self._attrs.count, dtype=np.int64)
        self._dev.add_accounts(
            slots,
            self._attrs.col("flags")[n0:],
            self._attrs.col("ledger")[n0:],
        )

    def _commit_create_accounts(self, timestamp: int, input_bytes: bytes) -> bytes:
        events = np.frombuffer(input_bytes, dtype=ACCOUNT_DTYPE)
        n = len(events)
        n0 = self._attrs.count

        reply = self._commit_create_accounts_fast(timestamp, events, n)
        if reply is not None:
            self._sync_engine_meta(n0)
            self._commit_touch_accounts(n0)
            return reply
        results: list[tuple[int, int]] = []

        chain: int | None = None
        chain_broken = False
        # Undo scope for linked chains: slots allocated in the open chain.
        scope_slots: list[int] = []

        committed: list[dict] = []  # attr rows staged this batch

        def exists_ladder(ev: dict, slot: int) -> int:
            a = self._attrs
            if ev["flags"] != int(a["flags"][slot]):
                return CAR.exists_with_different_flags
            if ev["ud128_lo"] != int(a["ud128_lo"][slot]) or ev["ud128_hi"] != int(
                a["ud128_hi"][slot]
            ):
                return CAR.exists_with_different_user_data_128
            if ev["ud64"] != int(a["ud64"][slot]):
                return CAR.exists_with_different_user_data_64
            if ev["ud32"] != int(a["ud32"][slot]):
                return CAR.exists_with_different_user_data_32
            if ev["ledger"] != int(a["ledger"][slot]):
                return CAR.exists_with_different_ledger
            if ev["code"] != int(a["code"][slot]):
                return CAR.exists_with_different_code
            return CAR.exists

        def rollback_scope() -> None:
            if not scope_slots:
                return
            self._acct_dir.remove(
                self._attrs["id_lo"][scope_slots],
                self._attrs["id_hi"][scope_slots],
            )
            if self._native is not None:
                self._native.remove_accounts(
                    self._attrs["id_lo"][scope_slots],
                    self._attrs["id_hi"][scope_slots],
                )
            self._attrs.truncate(min(scope_slots))
            scope_slots.clear()

        for index in range(n):
            row = events[index]
            ev = {
                "id": types.u128_get(row, "id"),
                "flags": int(row["flags"]),
                "ud128_lo": int(row["user_data_128_lo"]),
                "ud128_hi": int(row["user_data_128_hi"]),
                "ud64": int(row["user_data_64"]),
                "ud32": int(row["user_data_32"]),
                "ledger": int(row["ledger"]),
                "code": int(row["code"]),
            }
            linked = bool(ev["flags"] & AF.linked)

            result: int | None = None
            if linked:
                if chain is None:
                    chain = index
                    assert not chain_broken
                    scope_slots.clear()
                if index == n - 1:
                    result = CAR.linked_event_chain_open
            if result is None and chain_broken:
                result = CAR.linked_event_failed
            if result is None and int(row["timestamp"]) != 0:
                result = CAR.timestamp_must_be_zero

            if result is None:
                result = self._create_account_checked(row, ev, exists_ladder)
                if result == CAR.ok:
                    slot = self._attrs.count
                    self._attrs.append(
                        id_lo=np.array([row["id_lo"]]),
                        id_hi=np.array([row["id_hi"]]),
                        ud128_lo=np.array([row["user_data_128_lo"]]),
                        ud128_hi=np.array([row["user_data_128_hi"]]),
                        ud64=np.array([row["user_data_64"]]),
                        ud32=np.array([row["user_data_32"]]),
                        ledger=np.array([row["ledger"]]),
                        code=np.array([row["code"]]),
                        flags=np.array([row["flags"]]),
                        timestamp=np.array([timestamp - n + index + 1], np.uint64),
                    )
                    self._acct_dir.insert(
                        np.array([row["id_lo"]], np.uint64),
                        np.array([row["id_hi"]], np.uint64),
                        np.array([slot], np.uint64),
                    )
                    if self._native is not None:
                        # A capacity rebuild re-registers everything in
                        # _attrs (including this row) — only register
                        # explicitly when no rebuild happened.
                        native = self._native
                        self._ensure_balance_capacity(self._attrs.count)
                        if self._native is native:
                            native.add_accounts(
                                np.array([row["id_lo"]], np.uint64),
                                np.array([row["id_hi"]], np.uint64),
                                np.array([row["flags"]], np.uint32),
                                np.array([row["ledger"]], np.uint32),
                                base_slot=slot,
                            )
                    if chain is not None:
                        scope_slots.append(slot)
                    self.commit_timestamp = timestamp - n + index + 1

            if result != CAR.ok:
                if chain is not None:
                    if not chain_broken:
                        chain_broken = True
                        rollback_scope()
                        for chain_index in range(chain, index):
                            results.append((chain_index, CAR.linked_event_failed))
                results.append((index, int(result)))

            if chain is not None and (
                not linked or result == CAR.linked_event_chain_open
            ):
                scope_slots.clear()
                chain = None
                chain_broken = False

        self._ensure_balance_capacity(self._attrs.count)
        self._sync_engine_meta(n0)
        self._commit_touch_accounts(n0)

        out = np.zeros(len(results), dtype=CREATE_RESULT_DTYPE)
        for i, (index, result) in enumerate(results):
            out[i]["index"] = index
            out[i]["result"] = result
        return out.tobytes()

    def _commit_create_accounts_fast(
        self, timestamp: int, events: np.ndarray, n: int
    ) -> bytes | None:
        """Vectorized all-valid batch: no chains, no failures, no
        existing ids — else None routes to the exact per-event loop."""
        if n == 0:
            return b""
        flags = events["flags"].astype(np.uint32)
        if (flags & np.uint32(AF.linked)).any():
            return None
        id_lo = events["id_lo"].astype(np.uint64)
        id_hi = events["id_hi"].astype(np.uint64)
        invalid = (
            (events["timestamp"] != 0)
            | (events["reserved"] != 0)
            | ((flags & ~np.uint32(0xF)) != 0)
            | ((id_lo == 0) & (id_hi == 0))
            | ((id_lo == np.uint64(U64_MAX)) & (id_hi == np.uint64(U64_MAX)))
            | (
                ((flags & np.uint32(AF.debits_must_not_exceed_credits)) != 0)
                & ((flags & np.uint32(AF.credits_must_not_exceed_debits)) != 0)
            )
            | (events["ledger"] == 0)
            | (events["code"] == 0)
        )
        for field in ("debits_pending", "debits_posted", "credits_pending",
                      "credits_posted"):
            invalid |= (events[f"{field}_lo"] != 0) | (events[f"{field}_hi"] != 0)
        if invalid.any():
            return None
        if n > 1 and not (
            (id_hi[1:] == id_hi[:-1]).all() and (id_lo[1:] > id_lo[:-1]).all()
        ):
            mix = id_lo * np.uint64(0x9E3779B97F4A7C15) + id_hi * np.uint64(
                0xC2B2AE3D27D4EB4F
            )
            if len(np.unique(mix)) != n:
                return None
        found, _ = self._acct_dir.lookup(id_lo, id_hi)
        if found.any():
            return None

        base = self._attrs.count
        ts0 = np.uint64(timestamp - n + 1)
        rows = self._attrs.append(
            id_lo=id_lo, id_hi=id_hi,
            ud128_lo=events["user_data_128_lo"],
            ud128_hi=events["user_data_128_hi"],
            ud64=events["user_data_64"], ud32=events["user_data_32"],
            ledger=events["ledger"], code=events["code"], flags=flags,
            timestamp=ts0 + np.arange(n, dtype=np.uint64),
        )
        assert rows[0] == base
        self._acct_dir.insert(id_lo, id_hi, rows.astype(np.uint64))
        self.commit_timestamp = timestamp
        native = self._native
        self._ensure_balance_capacity(self._attrs.count)
        # A capacity rebuild already re-registered every account.
        if native is not None and self._native is native:
            native.add_accounts(
                id_lo, id_hi, flags, events["ledger"], base_slot=base
            )
        return b""

    def _create_account_checked(self, row, ev, exists_ladder) -> int:
        # reference: src/state_machine.zig:1421-1448
        if int(row["reserved"]) != 0:
            return CAR.reserved_field
        if ev["flags"] & ~0xF:
            return CAR.reserved_flag
        if ev["id"] == 0:
            return CAR.id_must_not_be_zero
        if ev["id"] == U128_MAX:
            return CAR.id_must_not_be_int_max
        if (ev["flags"] & AF.debits_must_not_exceed_credits) and (
            ev["flags"] & AF.credits_must_not_exceed_debits
        ):
            return CAR.flags_are_mutually_exclusive
        for field in ("debits_pending", "debits_posted", "credits_pending", "credits_posted"):
            if types.u128_get(row, field) != 0:
                return getattr(CAR, f"{field}_must_be_zero")
        if ev["ledger"] == 0:
            return CAR.ledger_must_not_be_zero
        if ev["code"] == 0:
            return CAR.code_must_not_be_zero
        slot = self._account_slot(ev["id"])
        if slot is not None:
            return exists_ladder(ev, slot)
        return CAR.ok

    def _ensure_balance_capacity(self, slots: int) -> None:
        # The engine's logical capacity, not the live array shape: a
        # degraded device engine defers widening its HBM tables until
        # re-promotion, but its committed capacity already grew.
        cap = getattr(self._dev, "capacity", None)
        if cap is None:
            cap = self._dev.balances.shape[0]
        if slots <= cap:
            return
        while cap < slots:
            cap *= 2
        self._dev.grow(cap)
        if self._native is not None:
            self._rebuild_native(cap)
        else:
            self._mirror.grow(cap)

    def _rebuild_native(self, capacity: int) -> None:
        """Recreate the native fast path at a new capacity (growth or
        restore): copy balances, re-point the shared mirror, and
        re-register the id directories."""
        from tigerbeetle_tpu.runtime import fastpath

        old_lo, old_hi = self._mirror.lo, self._mirror.hi
        native = fastpath.NativeFastpath(capacity)
        native.lo[: len(old_lo)] = old_lo
        native.hi[: len(old_hi)] = old_hi
        n_acct = self._attrs.count
        if n_acct:
            native.add_accounts(
                self._attrs.col("id_lo"), self._attrs.col("id_hi"),
                self._attrs.col("flags"), self._attrs.col("ledger"),
                base_slot=0,
            )
        if self._store.base:
            from tigerbeetle_tpu.state_machine import spill as spill_mod

            for rows, obj in self._store.spill.iter_objects():
                cols = spill_mod.unpack_objects(obj)
                native.add_transfer_ids(
                    cols["id_lo"], cols["id_hi"], int(rows[0])
                )
        if self._store.tail_count():
            native.add_transfer_ids(
                self._store.col("id_lo"), self._store.col("id_hi"),
                self._store.base,
            )
        self._native = native
        self._mirror.lo = native.lo
        self._mirror.hi = native.hi

    # ------------------------------------------------------------------
    # create_transfers (the hot path).

    # ------------------------------------------------------------------
    # Device-authoritative create_transfers (engine == "device").

    def _commit_create_transfers_device(self, timestamp: int, input_bytes: bytes):
        """Route a batch to a device semantic kernel; host does joins,
        the device computes result codes (VERDICT r3 #1).  Falls back
        to the (drained) host path for shapes outside the kernels'
        classes — the same residual classes the r3 fast paths punted.
        """
        from tigerbeetle_tpu.state_machine import device_kernels as dk
        from tigerbeetle_tpu.state_machine.device_engine import ReplyFuture

        events = np.frombuffer(input_bytes, dtype=TRANSFER_DTYPE)
        n = len(events)
        ts_base = timestamp - n + 1

        def host_path() -> ReplyFuture:
            # Batches the semantic kernels cannot express first try
            # WAVE DISPATCH inside the device window (TB_DEV_WAVES):
            # the wave plan executes against the authoritative HBM
            # table instead of draining the stream to the host mirror.
            # On decline the decode/ladder work is handed to the host
            # path (it is drain-stale-proof: wire bytes + the
            # synchronously-maintained account attrs only), so a
            # persistently declining deployment does not pay it twice.
            fut, decoded = self._try_submit_device_waves(
                events, n, timestamp, input_bytes
            )
            if fut is not None:
                return fut
            self._engine_drain()
            return ReplyFuture(
                value=self._commit_create_transfers(
                    timestamp, input_bytes, decoded=decoded
                )
            )

        # A degraded engine serves every batch through the exact host
        # path (bit-identical replies) until commit_async's lifecycle
        # tick re-promotes it through the checksum handshake.
        if self._dev.state is not types.EngineState.healthy:
            return host_path()

        if n == 0 or n > dk.B:
            return host_path()

        # Forced-optimistic routing (TB_WAVES_SPECULATE=force): every
        # window batch — including shapes the semantic kernels could
        # serve — goes through the speculative wave dispatcher, the
        # differential-fuzz / bench arm that maximizes coverage of the
        # validate-and-residue machinery.
        if waves.spec_mode() == "force":
            return host_path()

        id_lo = np.asarray(events["id_lo"])
        id_hi = np.asarray(events["id_hi"])
        flags16 = np.asarray(events["flags"])
        flags = flags16.astype(np.uint32)
        timeout = events["timeout"].astype(np.uint64)
        amount_hi = np.asarray(events["amount_hi"])

        has_linked = bool((flags16 & np.uint16(TF.linked)).any())
        has_pending = bool((flags16 & np.uint16(TF.pending)).any())
        pv16 = np.uint16(TF.post_pending_transfer | TF.void_pending_transfer)
        has_pv = bool((flags16 & pv16).any())
        has_bal = bool(
            (flags16 & np.uint16(TF.balancing_debit | TF.balancing_credit)).any()
        )

        # Unique-id check (shared with the host router): ascending ids
        # prove uniqueness; else a 64-bit key mix.
        ascending = n == 1 or bool(
            (
                (id_hi[1:] > id_hi[:-1])
                | ((id_hi[1:] == id_hi[:-1]) & (id_lo[1:] > id_lo[:-1]))
            ).all()
        )
        if ascending:
            ids_unique = True
        else:
            mix = id_lo * np.uint64(0x9E3779B97F4A7C15) + id_hi * np.uint64(
                0xC2B2AE3D27D4EB4F
            )
            ids_unique = len(np.unique(mix)) == n
        if not ids_unique or has_bal:
            return host_path()

        # In-flight hazards: this batch's ids (duplicate checks) and —
        # for pv batches — its pending references must not collide
        # with batches whose bookkeeping hasn't materialized yet.  A pv
        # batch also RECORDS its pending-reference keys so a later
        # pipelined finalize of the same durable pending drains instead
        # of reading a stale status join (double-finalize hazard).
        keys = pack_u128(id_lo, id_hi)
        probe = keys
        if has_pv:
            # Only real references: pending_id == 0 means "no
            # reference" and must not alias across batches.
            plo = np.asarray(events["pending_id_lo"])
            phi = np.asarray(events["pending_id_hi"])
            ref = (plo != 0) | (phi != 0)
            probe = np.concatenate([probe, pack_u128(plo[ref], phi[ref])])
        keys_sorted = np.sort(probe) if (has_pv or not ascending) else keys
        if self._dev.inflight_ids_hit(probe):
            self._engine_drain()

        e_found, _e_row = self._tdir.lookup(id_lo, id_hi)
        if e_found.any():
            return host_path()

        # Account joins (slots + flags for routing).
        dr_lo = np.asarray(events["debit_account_id_lo"])
        dr_hi = np.asarray(events["debit_account_id_hi"])
        cr_lo = np.asarray(events["credit_account_id_lo"])
        cr_hi = np.asarray(events["credit_account_id_hi"])
        dr_found, dr_slot_u = self._acct_dir.lookup(dr_lo, dr_hi)
        cr_found, cr_slot_u = self._acct_dir.lookup(cr_lo, cr_hi)
        dr_slot = np.where(dr_found, dr_slot_u.astype(np.int64), -1)
        cr_slot = np.where(cr_found, cr_slot_u.astype(np.int64), -1)
        attrs = self._attrs
        dr_flags = np.where(
            dr_found, attrs["flags"][np.clip(dr_slot, 0, None)], 0
        ).astype(np.uint32)
        cr_flags = np.where(
            cr_found, attrs["flags"][np.clip(cr_slot, 0, None)], 0
        ).astype(np.uint32)
        LIMH = np.uint32(
            AF.debits_must_not_exceed_credits
            | AF.credits_must_not_exceed_debits
            | AF.history
        )
        touch_limit_hist = bool(((dr_flags | cr_flags) & LIMH).any())
        touch_hist = bool(
            ((dr_flags | cr_flags) & np.uint32(AF.history)).any()
        )

        common = dict(
            events=events, n=n, ts_base=ts_base, id_lo=id_lo, id_hi=id_hi,
            dr_lo=dr_lo, dr_hi=dr_hi, cr_lo=cr_lo, cr_hi=cr_hi,
            flags=flags, timeout=timeout, dr_slot=dr_slot, cr_slot=cr_slot,
            keys_sorted=keys_sorted, timestamp=timestamp,
            input_bytes=input_bytes,
        )

        # Each submit path returns None when the batch cannot run on
        # device — under tiering, a touched-account set the hot window
        # cannot hold (tier_prefetch declined) — and the exact host
        # path takes over.
        if not (has_linked or has_pv) and not touch_limit_hist:
            fut = self._submit_device_orderfree(**common)
            return fut if fut is not None else host_path()
        if (
            has_linked
            and not (has_pending or has_pv)
            and not touch_hist
            and not amount_hi.any()
        ):
            fut = self._submit_device_linked(**common)
            return fut if fut is not None else host_path()
        if has_pv and not has_linked and not timeout.any() and not touch_limit_hist:
            fut = self._submit_device_two_phase(**common)
            if fut is not None:
                return fut
        return host_path()

    def _device_pack_base(
        self, n, events, id_lo, id_hi, dr_lo, dr_hi, cr_lo, cr_hi,
        flags, timeout, dr_slot, cr_slot, p_found=None, p_tgt=None,
        n_cols=None,
    ):
        from tigerbeetle_tpu.state_machine import device_kernels as dk

        return dk.pack_base(
            n, id_lo=id_lo, id_hi=id_hi,
            dr_lo=dr_lo, dr_hi=dr_hi, cr_lo=cr_lo, cr_hi=cr_hi,
            pend_lo=np.asarray(events["pending_id_lo"]),
            pend_hi=np.asarray(events["pending_id_hi"]),
            amount_lo=np.asarray(events["amount_lo"]),
            amount_hi=np.asarray(events["amount_hi"]),
            flags=flags, ledger=np.asarray(events["ledger"]),
            code=events["code"].astype(np.uint32),
            timeout=events["timeout"].astype(np.uint32),
            ts_nonzero=np.asarray(events["timestamp"] != 0),
            dr_slot=dr_slot, cr_slot=cr_slot,
            e_found=np.zeros(n, bool),  # router guarantees no dups
            p_found=p_found, p_tgt=p_tgt,
            n_cols=n_cols or dk.N_COLS,
        )

    def _device_fallback(self, timestamp, input_bytes):
        """Exact host re-execution for a flagged batch (engine has
        drained up to the batch before it; mirror is current)."""

        def run() -> bytes:
            self._stats["stat_fallback_events"].inc(
                len(input_bytes) // TRANSFER_DTYPE.itemsize
            )
            self._dev._suppress_enqueue = True
            try:
                return self._commit_create_transfers(timestamp, input_bytes)
            finally:
                self._dev._suppress_enqueue = False

        return run

    def _observe_plan_time(self, t0: float) -> None:
        """Record one wave-routing pass's host wall time (decode,
        joins, admission, and the partitioner whenever it ran)."""
        plan_dt = _time.perf_counter() - t0
        self._stats["stat_dev_wave_plan_s"].inc(plan_dt)
        self._h_dev_wave_plan.observe(plan_dt * 1e6)

    def _dev_wave_decline(self, reason: str) -> None:
        self._stats["stat_dev_wave_declined"].inc()
        # Cumulative per-reason registry counter (scrapeable) + the
        # bench-resettable window dict.
        self.metrics.counter("dev_wave.decline." + reason).inc()
        reasons = self.stat_dev_wave_decline_reasons
        reasons[reason] = reasons.get(reason, 0) + 1

    def _try_submit_device_waves(
        self, events, n, timestamp, input_bytes
    ):
        """Wave-dispatch one window batch that fell off the semantic
        kernels (mixed kinds, conflicting/duplicate ids, balancing,
        timeouts, two-phase edge shapes): host joins + overflow
        admission at submit time, then either OPTIMISTIC submission
        (TB_WAVES_SPECULATE: no plan — the whole batch speculates as
        one device step at launch and only a conflicted residue is
        planned, DeviceEngine._exec_spec) or the pessimistic wave plan
        (segment execution against the authoritative HBM table at
        window launch); exact-path bookkeeping runs from the
        fetched packed outputs at materialization either way.  Returns
        (reply_future, None), or (None, decoded) on decline
        (admission, profitability, TB_DEV_WAVES=0, degraded engine,
        unsupported sharding geometry, plan shapes the SPMD executors
        don't cover, oversize batch) — the caller drains to the host
        exactly as before, reusing the decode dict: the plan is never
        wrong, only occasionally slower.

        ROW-SHARDED engines submit too: the plan executes SPMD over
        the engine's ("shard",) mesh (waves._execute_plan_sharded) as
        long as the capability probe (DeviceEngine.wave_mesh) accepts
        the mesh and the plan carries only wave/chain segments
        (waves.plan_shardable) — anything else declines gracefully,
        counted by reason, never errors.

        Soundness of planning against a LAGGING mirror: the hazard
        probe drains on any id/pending-reference overlap with
        in-flight records (so the host joins here equal their
        post-drain values), and the overflow admission charges every
        in-flight record's amount bound on top of the mirror state
        (DeviceEngine.inflight_bound), so no execution order of the
        window can surface an ov_* code the plan assumed away."""
        dev = self._dev
        dm = waves.dev_mode()
        if dm == "0" or n == 0 or n > _BATCH_BUCKETS[-1]:
            return None, None
        if dev.state is not types.EngineState.healthy:
            return None, None
        if dev.hot is not None:
            # v1 tiering scope cut: wave/speculative event dicts index
            # the table by LOGICAL slot throughout (plan, executors,
            # residue replay) — decline and take the host path.
            self._dev_wave_decline("tier")
            return None, None
        sharded = dev.sharding is not None
        if sharded and dev.wave_mesh() is None:
            self._dev_wave_decline("mesh")
            return None, None
        t0 = _time.perf_counter()
        d = self._decode_static(events, n)
        ts_base = timestamp - n + 1

        # In-flight hazards: this batch's ids (duplicate/exists joins)
        # and real pending references must not collide with records
        # whose bookkeeping hasn't materialized yet.
        keys = pack_u128(d["id_lo"], d["id_hi"])
        probe = keys
        if d["is_pv"].any():
            ref = (d["pend_lo"] != 0) | (d["pend_hi"] != 0)
            probe = np.concatenate(
                [probe, pack_u128(d["pend_lo"][ref], d["pend_hi"][ref])]
            )
        if dev.inflight_ids_hit(probe):
            self._engine_drain()
            if dev.state is not types.EngineState.healthy:
                self._dev_wave_decline("degraded")
                return None, d

        e_found, e_row = self._tdir.lookup(d["id_lo"], d["id_hi"])
        id_lo, id_hi = d["id_lo"], d["id_hi"]
        ascending = n == 1 or bool(
            (
                (id_hi[1:] > id_hi[:-1])
                | ((id_hi[1:] == id_hi[:-1]) & (id_lo[1:] > id_lo[:-1]))
            ).all()
        )
        B = next(b for b in _BATCH_BUCKETS if b >= n)
        j = self._exact_joins(
            n, B, id_lo, id_hi, d["pend_lo"], d["pend_hi"], d["is_pv"],
            ascending, e_found, e_row,
        )
        meta, pv_serial = self._wave_metadata(
            n, d["flags"], d["dr_slot"], d["cr_slot"], d["dr_flags"],
            d["cr_flags"], j["id_group"], j["p_group"], j["p_tgt"],
            j["p_found"], j["gather_p"],
        )

        # Optimistic routing (TB_WAVES_SPECULATE): admitted batches on
        # a dense engine skip the partitioner entirely — the whole
        # batch executes as ONE speculative device step, validated on
        # device, with only the conflicted residue replayed through
        # plan_waves at launch (DeviceEngine._exec_spec).  The
        # residue-cap gate skips batches the host ALREADY knows are
        # residue-dominated (chain members, history events, serialized
        # post/voids) — a guaranteed-loss speculation; "force" takes
        # them anyway (differential/bench routing).
        sm_mode = waves.spec_mode()
        speculate = sm_mode != "0" and not sharded
        if speculate and sm_mode != "force":
            speculate = (
                int(meta["chain_member"].sum())
                <= waves.spec_residue_cap() * n
            )
        # Both cheap pre-admission declines run before the per-column
        # bound accumulation pays for itself.
        if not speculate and self._chain_dominated(
            n, meta, force=(dm == "1")
        ):
            self._observe_plan_time(t0)
            self._dev_wave_decline("plan")
            return None, d
        adm = self._wave_admission(
            n, meta, d["flags"], j["p_found"], j["gather_p"],
            d["is_pv"], d["amount_lo"], d["amount_hi"],
            extra_bound=dev.inflight_bound(),
        )
        if adm is None:
            self._observe_plan_time(t0)
            self._dev_wave_decline("plan")
            return None, d
        inb_pairs, batch_bound = adm
        plan = None
        if not speculate:
            plan = self._grade_plan(
                n, meta, inb_pairs, batch_bound, force=(dm == "1")
            )
        self._observe_plan_time(t0)
        if not speculate:
            if plan is None:
                self._dev_wave_decline("plan")
                return None, d
            if sharded and not waves.plan_shardable(plan):
                # The plan needs a scan segment (history accounts,
                # serial conflict regions) — no SPMD executor covers
                # those, so the sharded engine declines to the drained
                # host path.
                self._dev_wave_decline("shard_plan")
                return None, d

        ev = self._build_scan_events(
            n, B, events, d["flags"], d["static"], d["amount_lo"],
            d["amount_hi"], d["pend_lo"], d["pend_hi"], d["timeout"],
            d["ledger"], d["code"], d["dr_slot"], d["cr_slot"],
            d["dr_flags"], d["cr_flags"], d["dr_zero"], d["cr_zero"],
            e_found, j,
        )
        if d["timeout"].any():
            self._inflight_timeouts = True
        flags, timeout = d["flags"], d["timeout"]
        uniq_rows, dstat_init = j["uniq_rows"], j["dstat_init"]

        def finish(packed_np) -> bytes:
            out = kernel.unpack_outputs(packed_np)
            return self._finish_exact_outputs(
                out, n, ts_base, id_lo, id_hi, flags, timeout,
                uniq_rows, dstat_init, True,
            )

        self.stat_dev_wave_batches += 1
        self.stat_dev_wave_events += n
        if speculate:
            # The in-flight charge is the WHOLE-batch superset — the
            # same bound the wave path charges — never the committed
            # subset: a mid-flight demotion replays the entire batch
            # through the host fallback, and a smaller charge could
            # let a sibling admission over-apply (tests/test_chaos.py
            # pins this window).
            return dev.submit_speculative(
                ev, dstat_init, n, ts_base, meta["chain_member"],
                pv_serial, finish,
                self._device_fallback(timestamp, input_bytes),
                id_keys=np.sort(probe), bound=batch_bound,
            ), None
        self.stat_dev_wave_steps += plan.n_steps
        return dev.submit_waves(
            ev, dstat_init, n, ts_base, plan, _pad(plan.wave_mask, B),
            finish, self._device_fallback(timestamp, input_bytes),
            id_keys=np.sort(probe), bound=plan.batch_bound,
        ), None

    def _tier_translate(self, *slot_arrays):
        """Batch planner front-door for the hot/cold tiering: compute
        the batch's LOGICAL touched-account set up front, prefetch it
        into the device hot window (DeviceEngine.tier_prefetch — rides
        the write-behind lane for eviction), and return each input
        array translated to HOT slots (negative entries pass through).
        Returns None when the batch cannot run on device — the caller
        takes the exact host path.  All-resident: identity."""
        hot = getattr(self._dev, "hot", None)
        if hot is None:
            return slot_arrays
        touched = np.concatenate(
            [np.asarray(a, np.int64).ravel() for a in slot_arrays]
        )
        if not self._dev.tier_prefetch(touched):
            self.metrics.counter("dev_tier.punt").inc()
            return None
        return tuple(
            hot.translate(np.asarray(a, np.int64)) for a in slot_arrays
        )

    def _submit_device_orderfree(
        self, events, n, ts_base, id_lo, id_hi, dr_lo, dr_hi, cr_lo, cr_hi,
        flags, timeout, dr_slot, cr_slot, keys_sorted, timestamp, input_bytes,
    ):
        from tigerbeetle_tpu.state_machine import device_kernels as dk

        # Tiered prefetch + translation happens BEFORE packing; the
        # finish/bookkeeping closures keep the LOGICAL slots (the
        # mirror and attrs are logical-indexed).
        tr = self._tier_translate(dr_slot, cr_slot)
        if tr is None:
            return None
        t_dr_slot, t_cr_slot = tr
        amount_lo = np.asarray(events["amount_lo"])
        amount_hi = np.asarray(events["amount_hi"])
        has_timeout = bool(timeout.any())
        has_hi = bool(amount_hi.any())
        # Tight 20-byte/event input when the batch's exact facts allow
        # (h2d bytes are the device engine's ceiling on this link).
        tight = (
            not has_timeout
            and not has_hi
            and (n == 0 or int(amount_lo.max()) < _TIGHT_AMOUNT_LIMIT)
        )
        if tight:
            pk = dk.pack_tight(
                n, id_lo=id_lo, id_hi=id_hi, dr_lo=dr_lo, dr_hi=dr_hi,
                cr_lo=cr_lo, cr_hi=cr_hi,
                pend_lo=np.asarray(events["pending_id_lo"]),
                pend_hi=np.asarray(events["pending_id_hi"]),
                amount_lo=amount_lo, flags=flags,
                ledger=np.asarray(events["ledger"]),
                code=events["code"].astype(np.uint32),
                ts_nonzero=np.asarray(events["timestamp"] != 0),
                dr_slot=t_dr_slot, cr_slot=t_cr_slot,
            )
        else:
            pk = self._device_pack_base(
                n, events, id_lo, id_hi, dr_lo, dr_hi, cr_lo, cr_hi,
                flags, timeout, t_dr_slot, t_cr_slot,
            )
        if has_timeout:
            self._inflight_timeouts = True
        created = {
            "flags": flags,
            "dr_slot": dr_slot.astype(np.int32),
            "cr_slot": cr_slot.astype(np.int32),
            "amount_lo": amount_lo, "amount_hi": amount_hi,
            "pending_lo": np.asarray(events["pending_id_lo"]),
            "pending_hi": np.asarray(events["pending_id_hi"]),
            "ud128_lo": np.asarray(events["user_data_128_lo"]),
            "ud128_hi": np.asarray(events["user_data_128_hi"]),
            "ud64": np.asarray(events["user_data_64"]),
            "ud32": np.asarray(events["user_data_32"]),
            "timeout": timeout,
            "ledger": np.asarray(events["ledger"]),
            "code": events["code"].astype(np.uint32),
        }

        def finish(summary) -> bytes:
            results = np.zeros(n, np.uint32)
            results[summary["fail_idx"]] = summary["fail_codes"]
            apply_mask = results == 0
            is_pending = (flags & np.uint32(TF.pending)) != 0
            # Mirror bookkeeping doubles as a free admission parity
            # check: the device admitted, so this can never refuse.
            deltas = self._mirror.try_apply_adds(
                dr_slot, cr_slot, amount_lo, amount_hi, is_pending,
                apply_mask,
            )
            assert deltas is not None, "device/mirror admission divergence"
            return self._finish_fast(
                n, ts_base, id_lo, id_hi, flags, timeout, results, created,
                last_applied=summary["last_applied"],
            )

        if tight:
            kind = "orderfree_tight"
        else:
            kind = "orderfree" if has_hi else "orderfree_lo"
        return self._dev.submit(
            kind, pk, n, ts_base, finish,
            self._device_fallback(timestamp, input_bytes),
            id_keys=keys_sorted,
            bound=_amount_bound_total(amount_lo, amount_hi),
        )

    def _submit_device_linked(
        self, events, n, ts_base, id_lo, id_hi, dr_lo, dr_hi, cr_lo, cr_hi,
        flags, timeout, dr_slot, cr_slot, keys_sorted, timestamp, input_bytes,
    ):
        tr = self._tier_translate(dr_slot, cr_slot)
        if tr is None:
            return None
        t_dr_slot, t_cr_slot = tr
        pk = self._device_pack_base(
            n, events, id_lo, id_hi, dr_lo, dr_hi, cr_lo, cr_hi,
            flags, timeout, t_dr_slot, t_cr_slot,
        )
        amount_lo = np.asarray(events["amount_lo"])
        amount_hi = np.asarray(events["amount_hi"])
        created = {
            "flags": flags,
            "dr_slot": dr_slot.astype(np.int32),
            "cr_slot": cr_slot.astype(np.int32),
            "amount_lo": amount_lo, "amount_hi": amount_hi,
            "pending_lo": np.zeros(n, np.uint64),
            "pending_hi": np.zeros(n, np.uint64),
            "ud128_lo": np.asarray(events["user_data_128_lo"]),
            "ud128_hi": np.asarray(events["user_data_128_hi"]),
            "ud64": np.asarray(events["user_data_64"]),
            "ud32": np.asarray(events["user_data_32"]),
            "timeout": timeout,
            "ledger": np.asarray(events["ledger"]),
            "code": events["code"].astype(np.uint32),
        }

        def finish(summary) -> bytes:
            results = np.zeros(n, np.uint32)
            results[summary["fail_idx"]] = summary["fail_codes"]
            self.stat_linked_batches += 1
            self.stat_resolve_iters += summary["iters"]
            deltas = self._mirror.try_apply_adds(
                dr_slot, cr_slot, amount_lo, amount_hi,
                np.zeros(n, bool), results == 0,
            )
            assert deltas is not None, "device/mirror admission divergence"
            return self._finish_fast(
                n, ts_base, id_lo, id_hi, flags, timeout, results, created,
                last_applied=summary["last_applied"],
            )

        # Small-amount specialization: a batch whose total contribution
        # fits i32 runs the one-cumsum-per-prefix fixpoint (the device
        # re-verifies the bound; a wrong pick just falls back exactly).
        kind = (
            "linked_small"
            if int(amount_lo.sum(dtype=np.uint64)) < (1 << 31)
            else "linked"
        )
        return self._dev.submit(
            kind, pk, n, ts_base, finish,
            self._device_fallback(timestamp, input_bytes),
            id_keys=keys_sorted,
            bound=_amount_bound_total(amount_lo, amount_hi),
        )

    def _submit_device_two_phase(
        self, events, n, ts_base, id_lo, id_hi, dr_lo, dr_hi, cr_lo, cr_hi,
        flags, timeout, dr_slot, cr_slot, keys_sorted, timestamp, input_bytes,
    ):
        """Build two-phase join columns and dispatch; None -> host path
        (same residual class the r3 host router punted to the serial
        exact engine)."""
        from tigerbeetle_tpu.state_machine import device_kernels as dk

        pend_lo = np.asarray(events["pending_id_lo"])
        pend_hi = np.asarray(events["pending_id_hi"])
        is_pv = (flags & np.uint32(TF.post_pending_transfer | TF.void_pending_transfer)) != 0

        # In-batch pending references (ids unique -> creator is the
        # unique event with that id).
        id_key = pack_u128(id_lo, id_hi)
        order = np.argsort(id_key, kind="stable")
        sorted_keys = id_key[order]
        pend_key = pack_u128(pend_lo, pend_hi)
        pos = np.searchsorted(sorted_keys, pend_key)
        pos_c = np.minimum(pos, n - 1)
        tgt_ev = np.where(
            is_pv & (sorted_keys[pos_c] == pend_key), order[pos_c], -1
        ).astype(np.int64)
        idx = np.arange(n)
        ib = is_pv & (tgt_ev >= 0) & (tgt_ev < idx)
        # Keep r3 routing parity: an in-batch reference to a
        # non-pending create goes to the serial exact engine.
        if (
            ib
            & ((flags[np.clip(tgt_ev, 0, None)] & np.uint32(TF.pending)) == 0)
        ).any():
            return None

        # Durable pending-target join.
        if is_pv.any():
            p_found, p_row = self._tdir.lookup(pend_lo, pend_hi)
            p_found = p_found & is_pv & ~ib
        else:
            p_found = np.zeros(n, bool)
            p_row = np.zeros(n, np.uint64)
        p_rows_valid = p_row[p_found].astype(np.int64)
        if len(p_rows_valid):
            uniq_rows, first_idx, tgt_inverse = np.unique(
                p_rows_valid, return_index=True, return_inverse=True
            )
            join = self._store.gather_many(
                [
                    "flags", "dr_slot", "cr_slot", "amount_lo", "amount_hi",
                    "ledger", "code", "ud128_lo", "ud128_hi", "ud64", "ud32",
                    "timeout", "status",
                ],
                uniq_rows,
            )
            if (join["timeout"] != 0).any():
                return None
            pj_dr_u = np.clip(join["dr_slot"].astype(np.int64), 0, None)
            pj_cr_u = np.clip(join["cr_slot"].astype(np.int64), 0, None)
            LIMH = np.uint32(
                AF.debits_must_not_exceed_credits
                | AF.credits_must_not_exceed_debits
                | AF.history
            )
            pj_acct_flags = (
                self._attrs["flags"][pj_dr_u] | self._attrs["flags"][pj_cr_u]
            ).astype(np.uint32)
            if (pj_acct_flags & LIMH).any():
                return None
            p_tgt = np.full(n, -1, np.int64)
            p_tgt[p_found] = tgt_inverse
            uniq_status = join["status"].astype(np.uint32)

            def jcol(name, dtype):
                out = np.zeros(n, dtype)
                out[p_found] = join[name][tgt_inverse].astype(dtype)
                return out

        else:
            uniq_rows = np.zeros(0, np.int64)
            uniq_status = np.zeros(0, np.uint32)
            p_tgt = np.full(n, -1, np.int64)

            def jcol(name, dtype):
                return np.zeros(n, dtype)

        pj_dr_slot = jcol("dr_slot", np.int64)
        pj_cr_slot = jcol("cr_slot", np.int64)
        # Tiered prefetch over the batch's WHOLE touched set up front
        # (event accounts + durable pending-target accounts — in-batch
        # targets resolve to event slots already covered).  Only the
        # packed device columns translate; ctx/finish keep LOGICAL
        # slots.  Non-found pj entries keep their 0 default — the
        # kernel reads them only under the p_found bit.
        tr = self._tier_translate(
            dr_slot, cr_slot,
            np.where(p_found, pj_dr_slot, -1),
            np.where(p_found, pj_cr_slot, -1),
        )
        if tr is None:
            return None
        t_dr_slot, t_cr_slot, t_pj_dr, t_pj_cr = tr
        t_pj_dr = np.where(p_found, t_pj_dr, 0)
        t_pj_cr = np.where(p_found, t_pj_cr, 0)
        pk = self._device_pack_base(
            n, events, id_lo, id_hi, dr_lo, dr_hi, cr_lo, cr_hi,
            flags, timeout, t_dr_slot, t_cr_slot,
            p_found=p_found, p_tgt=p_tgt, n_cols=dk.N_COLS_TP,
        )
        # Target account-id equality predicates (host marshaling: u128
        # byte compares against in-batch events or durable attrs).
        tgt_c = np.clip(tgt_ev, 0, None)
        p_drs = np.where(ib, dr_slot[tgt_c], pj_dr_slot)
        p_crs = np.where(ib, cr_slot[tgt_c], pj_cr_slot)
        pd = np.clip(p_drs, 0, None)
        pc = np.clip(p_crs, 0, None)
        p_dr_id_lo = self._attrs["id_lo"][pd]
        p_dr_id_hi = self._attrs["id_hi"][pd]
        p_cr_id_lo = self._attrs["id_lo"][pc]
        p_cr_id_hi = self._attrs["id_hi"][pc]
        t_dr_set = (dr_lo != 0) | (dr_hi != 0)
        t_cr_set = (cr_lo != 0) | (cr_hi != 0)
        dr_eq = (dr_lo == p_dr_id_lo) & (dr_hi == p_dr_id_hi)
        cr_eq = (cr_lo == p_cr_id_lo) & (cr_hi == p_cr_id_hi)
        bits_extra = (
            np.where(t_dr_set, np.uint64(dk.BIT_T_DR_SET), np.uint64(0))
            | np.where(t_cr_set, np.uint64(dk.BIT_T_CR_SET), np.uint64(0))
            | np.where(dr_eq, np.uint64(dk.BIT_DR_EQ_P), np.uint64(0))
            | np.where(cr_eq, np.uint64(dk.BIT_CR_EQ_P), np.uint64(0))
        )
        p_amt_lo_d = jcol("amount_lo", np.uint64)
        p_amt_hi_d = jcol("amount_hi", np.uint64)
        dstat_ev = np.zeros(n, np.uint32)
        if len(uniq_rows):
            dstat_ev[p_found] = uniq_status[p_tgt[p_found]]
        pk = dk.pack_two_phase_ext(
            pk, n, bits_extra_mask=bits_extra,
            p_flags=jcol("flags", np.uint32).astype(np.uint16),
            p_code=jcol("code", np.uint32).astype(np.uint16),
            p_ledger=jcol("ledger", np.uint32),
            p_dr_slot=t_pj_dr, p_cr_slot=t_pj_cr,
            p_amt_lo=p_amt_lo_d, p_amt_hi=p_amt_hi_d,
            tgt_ev=tgt_ev, dstat_init_ev=dstat_ev,
        )
        amount_lo = np.asarray(events["amount_lo"])
        amount_hi = np.asarray(events["amount_hi"])
        p_amt_lo = np.where(ib, amount_lo[tgt_c], p_amt_lo_d)
        p_amt_hi = np.where(ib, amount_hi[tgt_c], p_amt_hi_d)
        ud128_lo = np.asarray(events["user_data_128_lo"])
        ud128_hi = np.asarray(events["user_data_128_hi"])
        ud64 = np.asarray(events["user_data_64"])
        ud32 = np.asarray(events["user_data_32"]).astype(np.uint32)
        ledger_arr = np.asarray(events["ledger"])
        code_arr = events["code"].astype(np.uint32)
        pend_flag = (flags & np.uint32(TF.pending)) != 0
        post = (flags & np.uint32(TF.post_pending_transfer)) != 0

        ctx = dict(
            n=n, ts_base=ts_base, is_pv=is_pv, ib=ib, tgt_ev=tgt_ev,
            p_drs=p_drs, p_crs=p_crs, p_amt_lo=p_amt_lo, p_amt_hi=p_amt_hi,
            p_ud128_lo=np.where(ib, ud128_lo[tgt_c], jcol("ud128_lo", np.uint64)),
            p_ud128_hi=np.where(ib, ud128_hi[tgt_c], jcol("ud128_hi", np.uint64)),
            p_ud64=np.where(ib, ud64[tgt_c], jcol("ud64", np.uint64)),
            p_ud32=np.where(ib, ud32[tgt_c], jcol("ud32", np.uint32)),
            p_ledger=np.where(
                ib, ledger_arr[tgt_c].astype(np.uint32), jcol("ledger", np.uint32)
            ),
            p_code=np.where(ib, code_arr[tgt_c], jcol("code", np.uint32)),
            uniq_rows=uniq_rows, uniq_status=uniq_status, p_tgt=p_tgt,
            pend_flag=pend_flag, post=post,
        )

        def finish(summary) -> bytes:
            return self._finish_device_two_phase(
                summary, events, id_lo, id_hi, flags, timeout,
                amount_lo, amount_hi, pend_lo, pend_hi,
                ud128_lo, ud128_hi, ud64, ud32, ledger_arr, code_arr,
                dr_slot, cr_slot, ctx,
            )

        self.stat_two_phase_batches += 1
        kind = (
            "two_phase_lo"
            if not (amount_hi.any() or p_amt_hi.any())
            else "two_phase"
        )
        # In-flight bound: creates add their amount through two slots
        # (counted once per slot by the wave admission), finalizers at
        # most max(t.amount, pending.amount) — 2x amounts + the joined
        # pending amounts over-covers both.
        bound = 2 * _amount_bound_total(
            amount_lo, amount_hi
        ) + _amount_bound_total(p_amt_lo, p_amt_hi)
        return self._dev.submit(
            kind, pk, n, ts_base, finish,
            self._device_fallback(timestamp, input_bytes),
            id_keys=keys_sorted,
            bound=bound,
        )

    def _finish_device_two_phase(
        self, summary, events, id_lo, id_hi, flags, timeout,
        amount_lo, amount_hi, pend_lo, pend_hi,
        ud128_lo, ud128_hi, ud64, ud32, ledger_arr, code_arr,
        dr_slot, cr_slot, ctx,
    ) -> bytes:
        """Bookkeeping from device codes (mirrors the tail of
        _try_two_phase_fast, with verdicts arriving from the kernel)."""
        n = ctx["n"]
        ts_base = ctx["ts_base"]
        is_pv = ctx["is_pv"]
        results = np.zeros(n, np.uint32)
        results[summary["fail_idx"]] = summary["fail_codes"]
        ok = results == 0
        winner = ok & is_pv
        post = ctx["post"]
        pend_flag = ctx["pend_flag"]
        p_drs, p_crs = ctx["p_drs"], ctx["p_crs"]
        p_amt_lo, p_amt_hi = ctx["p_amt_lo"], ctx["p_amt_hi"]
        t_amt_set = (amount_lo != 0) | (amount_hi != 0)
        res_amt_lo = np.where(is_pv & ~t_amt_set, p_amt_lo, amount_lo)
        res_amt_hi = np.where(is_pv & ~t_amt_set, p_amt_hi, amount_hi)

        # Mirror bookkeeping (device already applied; these asserts are
        # the admission-parity tripwire).
        pend_ok = ok & pend_flag
        plain_ok = ok & ~pend_flag & ~is_pv
        post_win = winner & post
        add_slots = np.concatenate([
            dr_slot[pend_ok], cr_slot[pend_ok],
            dr_slot[plain_ok], cr_slot[plain_ok],
            p_drs[post_win], p_crs[post_win],
        ])
        n_pend = int(pend_ok.sum())
        n_plain = int(plain_ok.sum())
        n_post = int(post_win.sum())
        add_cols = np.concatenate([
            np.zeros(n_pend, np.int64), np.full(n_pend, 2, np.int64),
            np.ones(n_plain, np.int64), np.full(n_plain, 3, np.int64),
            np.ones(n_post, np.int64), np.full(n_post, 3, np.int64),
        ])
        add_lo = np.concatenate([
            amount_lo[pend_ok], amount_lo[pend_ok],
            amount_lo[plain_ok], amount_lo[plain_ok],
            res_amt_lo[post_win], res_amt_lo[post_win],
        ])
        add_hi = np.concatenate([
            amount_hi[pend_ok], amount_hi[pend_ok],
            amount_hi[plain_ok], amount_hi[plain_ok],
            res_amt_hi[post_win], res_amt_hi[post_win],
        ])
        deltas = self._mirror.try_apply_deltas(
            add_slots, add_cols, add_lo, add_hi
        )
        assert deltas is not None, "device/mirror admission divergence"
        n_win = int(winner.sum())
        if n_win:
            sub_slots = np.concatenate([p_drs[winner], p_crs[winner]])
            sub_cols = np.concatenate(
                [np.zeros(n_win, np.int64), np.full(n_win, 2, np.int64)]
            )
            self._mirror.apply_subs(
                sub_slots, sub_cols,
                np.concatenate([p_amt_lo[winner]] * 2),
                np.concatenate([p_amt_hi[winner]] * 2),
            )

        ud128_set = (ud128_lo != 0) | (ud128_hi != 0)
        created = {
            "flags": flags,
            "dr_slot": np.where(is_pv, p_drs, dr_slot).astype(np.int32),
            "cr_slot": np.where(is_pv, p_crs, cr_slot).astype(np.int32),
            "amount_lo": np.where(is_pv, res_amt_lo, amount_lo),
            "amount_hi": np.where(is_pv, res_amt_hi, amount_hi),
            "pending_lo": pend_lo, "pending_hi": pend_hi,
            "ud128_lo": np.where(is_pv & ~ud128_set, ctx["p_ud128_lo"], ud128_lo),
            "ud128_hi": np.where(is_pv & ~ud128_set, ctx["p_ud128_hi"], ud128_hi),
            "ud64": np.where(is_pv & (ud64 == 0), ctx["p_ud64"], ud64),
            "ud32": np.where(is_pv & (ud32 == 0), ctx["p_ud32"], ud32),
            "timeout": np.zeros(n, np.uint64),
            "ledger": np.where(is_pv, ctx["p_ledger"], ledger_arr).astype(np.uint32),
            "code": np.where(is_pv, ctx["p_code"], code_arr).astype(np.uint32),
        }
        inb_status = np.where(
            pend_ok, np.uint32(kernel.S_PENDING), np.uint32(0)
        )
        ib_win = winner & ctx["ib"]
        if ib_win.any():
            inb_status[ctx["tgt_ev"][ib_win]] = np.where(
                post[ib_win],
                np.uint32(kernel.S_POSTED),
                np.uint32(kernel.S_VOIDED),
            )
        uniq_rows = ctx["uniq_rows"]
        uniq_status = ctx["uniq_status"]
        dstat_init = uniq_status.copy()
        dstat = uniq_status.copy()
        dur_win = winner & ~ctx["ib"]
        if dur_win.any():
            dstat[ctx["p_tgt"][dur_win]] = np.where(
                post[dur_win],
                np.uint32(kernel.S_POSTED),
                np.uint32(kernel.S_VOIDED),
            )
        zeros_u64 = np.zeros(n, np.uint64)
        self._post_process_transfers(
            n, ts_base, id_lo, id_hi, flags, timeout,
            results, ok, created, inb_status,
            dstat_init, dstat, uniq_rows,
            np.zeros((n, 8), np.uint64), np.zeros((n, 8), np.uint64),
            summary["last_applied"], zeros_u64, zeros_u64,
            no_history=True,
        )
        fail_idx = np.flatnonzero(results != 0)
        reply = np.zeros(len(fail_idx), dtype=CREATE_RESULT_DTYPE)
        reply["index"] = fail_idx.astype(np.uint32)
        reply["result"] = results[fail_idx]
        return reply.tobytes()

    def _lookup_accounts_device(self, input_bytes: bytes):
        """lookup_accounts with balances gathered from the DEVICE table
        (rides the dispatch stream, so in-flight batches are visible
        without draining) — VERDICT r3 #1d."""
        ids = np.frombuffer(input_bytes, dtype=types.U128_PAIR_DTYPE)
        found, slots = self._acct_dir.lookup(
            ids["lo"].astype(np.uint64), ids["hi"].astype(np.uint64)
        )
        hit = np.flatnonzero(found)
        if len(hit) == 0:
            from tigerbeetle_tpu.state_machine.device_engine import (
                ReplyFuture,
            )

            return ReplyFuture(value=b"")
        slots_hit = slots[hit].astype(np.int64)

        def finish(rows) -> bytes:
            balances = rows[: len(slots_hit)]
            out = np.zeros(len(hit), dtype=ACCOUNT_DTYPE)
            a = self._attrs
            out["id_lo"], out["id_hi"] = a["id_lo"][slots_hit], a["id_hi"][slots_hit]
            out["debits_pending_lo"], out["debits_pending_hi"] = balances[:, 0], balances[:, 1]
            out["debits_posted_lo"], out["debits_posted_hi"] = balances[:, 2], balances[:, 3]
            out["credits_pending_lo"], out["credits_pending_hi"] = balances[:, 4], balances[:, 5]
            out["credits_posted_lo"], out["credits_posted_hi"] = balances[:, 6], balances[:, 7]
            out["user_data_128_lo"] = a["ud128_lo"][slots_hit]
            out["user_data_128_hi"] = a["ud128_hi"][slots_hit]
            out["user_data_64"] = a["ud64"][slots_hit]
            out["user_data_32"] = a["ud32"][slots_hit]
            out["ledger"] = a["ledger"][slots_hit]
            out["code"] = a["code"][slots_hit]
            out["flags"] = a["flags"][slots_hit]
            out["timestamp"] = a["timestamp"][slots_hit]
            return out.tobytes()

        return self._dev.lookup(slots_hit, finish)

    def _commit_create_transfers(
        self, timestamp: int, input_bytes: bytes, decoded: dict | None = None
    ) -> bytes:
        """`decoded`: an already-computed _decode_static dict (the
        wave-dispatch decline path hands its work over; safe to reuse
        across the drain — decode + ladder depend only on the wire
        bytes and the synchronously-maintained account attrs)."""
        events = np.frombuffer(input_bytes, dtype=TRANSFER_DTYPE)
        n = len(events)
        if n == 0:
            return b""
        self.stat_host_semantic_events += n
        ts_base = timestamp - n + 1

        # Native C++ fast path: one call covers decode, static ladder,
        # account resolution, duplicate checks, and overflow admission
        # (native/tb_fastpath.cpp); Python only does the bookkeeping.
        # A None return means fallback — nothing was mutated.
        # TB_WAVES=1/exact/scan bypasses every native/host fast path so
        # the JAX exact path (wave executor or B-step scan) sees the
        # full stream (differential-test + benchmark routing).
        if self._native is not None and waves.mode() not in (
            "1", "exact", "scan"
        ):
            native_out = self._native.commit_transfers(input_bytes, n, ts_base)
            if native_out is not None:
                self.stat_device_events += n
                return self._finish_native_fast(
                    events, n, ts_base, *native_out
                )
            # Order-dependent native resolvers (tb_linked.inc /
            # tb_two_phase.inc): serial C++ over the wire bytes with
            # exact ladders, feeding the same device scatter-add queue.
            nl = self._native.commit_linked(input_bytes, n, ts_base)
            if nl is not None:
                results, dr_slot, cr_slot, deltas, last_applied = nl
                self.stat_device_events += n
                self.stat_linked_batches += 1
                return self._finish_native_fast(
                    events, n, ts_base, results, dr_slot, cr_slot, deltas,
                    last_applied=last_applied,
                )
            reply = self._try_native_two_phase(input_bytes, events, n, ts_base)
            if reply is not None:
                self.stat_device_events += n
                self.stat_two_phase_batches += 1
                return reply

        d = decoded if decoded is not None else self._decode_static(events, n)
        return self._commit_transfers_resolved(
            n, ts_base, events, d["id_lo"], d["id_hi"], d["pend_lo"],
            d["pend_hi"], d["flags"], d["timeout"], d["dr_slot"],
            d["cr_slot"], d["amount_lo"], d["amount_hi"], d["ledger"],
            d["code"], d["static"], d["is_pv"], d["dr_flags"],
            d["cr_flags"], d["dr_zero"], d["cr_zero"],
        )

    def _decode_static(self, events: np.ndarray, n: int) -> dict:
        """Column decode + account resolution + the static precedence
        ladder — everything about a create_transfers batch that is
        independent of balances and durable joins.  Shared by the host
        exact path and the device engine's wave submission
        (_try_submit_device_waves), which must agree byte-for-byte."""
        # Same-width fields stay strided views into the 1 MiB wire
        # buffer (it lives in L2 after the first pass, so elementwise
        # ops on views beat paying a contiguous copy per column);
        # narrower wire fields still widen via astype.
        id_lo = np.asarray(events["id_lo"])
        id_hi = np.asarray(events["id_hi"])
        dr_lo = np.asarray(events["debit_account_id_lo"])
        dr_hi = np.asarray(events["debit_account_id_hi"])
        cr_lo = np.asarray(events["credit_account_id_lo"])
        cr_hi = np.asarray(events["credit_account_id_hi"])
        pend_lo = np.asarray(events["pending_id_lo"])
        pend_hi = np.asarray(events["pending_id_hi"])
        amount_lo = np.asarray(events["amount_lo"])
        amount_hi = np.asarray(events["amount_hi"])
        flags = events["flags"].astype(np.uint32)
        timeout = events["timeout"].astype(np.uint64)
        ledger = np.asarray(events["ledger"])
        code = events["code"].astype(np.uint32)

        is_pv = (flags & (kernel.F_POST | kernel.F_VOID)) != 0

        # Account resolution (immutable within this batch).
        dr_found, dr_slot_u = self._acct_dir.lookup(dr_lo, dr_hi)
        cr_found, cr_slot_u = self._acct_dir.lookup(cr_lo, cr_hi)
        dr_slot = np.where(dr_found, dr_slot_u.astype(np.int64), -1).astype(np.int32)
        cr_slot = np.where(cr_found, cr_slot_u.astype(np.int64), -1).astype(np.int32)
        dr_c = np.clip(dr_slot, 0, None)
        cr_c = np.clip(cr_slot, 0, None)
        attrs = self._attrs
        dr_flags = np.where(dr_found, attrs["flags"][dr_c], 0).astype(np.uint32)
        cr_flags = np.where(cr_found, attrs["flags"][cr_c], 0).astype(np.uint32)
        dr_ledger = np.where(
            dr_found, attrs["ledger"][dr_c], 0
        ).astype(np.uint32)
        cr_ledger = np.where(
            cr_found, attrs["ledger"][cr_c], 0
        ).astype(np.uint32)

        # Elementary predicates, shared by the all-valid short circuit
        # and the precedence ladder.
        id_zero = (id_lo == 0) & (id_hi == 0)
        id_max = (id_lo == np.uint64(U64_MAX)) & (id_hi == np.uint64(U64_MAX))
        reserved = (flags & ~np.uint32(0x3F)) != 0
        dr_zero = (dr_lo == 0) & (dr_hi == 0)
        dr_max = (dr_lo == np.uint64(U64_MAX)) & (dr_hi == np.uint64(U64_MAX))
        cr_zero = (cr_lo == 0) & (cr_hi == 0)
        cr_max = (cr_lo == np.uint64(U64_MAX)) & (cr_hi == np.uint64(U64_MAX))
        same_acct = (dr_lo == cr_lo) & (dr_hi == cr_hi)
        pend_zero = (pend_lo == 0) & (pend_hi == 0)
        not_pending_flag = (flags & kernel.F_PENDING) == 0
        not_balancing = (flags & (kernel.F_BAL_DR | kernel.F_BAL_CR)) == 0
        amount_zero = (amount_lo == 0) & (amount_hi == 0)

        def pack(static):
            return dict(
                id_lo=id_lo, id_hi=id_hi, dr_lo=dr_lo, dr_hi=dr_hi,
                cr_lo=cr_lo, cr_hi=cr_hi, pend_lo=pend_lo,
                pend_hi=pend_hi, amount_lo=amount_lo,
                amount_hi=amount_hi, flags=flags, timeout=timeout,
                ledger=ledger, code=code, is_pv=is_pv, dr_slot=dr_slot,
                cr_slot=cr_slot, dr_flags=dr_flags, cr_flags=cr_flags,
                dr_zero=dr_zero, cr_zero=cr_zero, static=static,
            )

        # Short circuit: the hot path (well-formed plain transfers) hits
        # ZERO ladder codes — one OR-reduction detects that and skips
        # the ~25 masked-copyto cascade entirely.
        if not is_pv.any():
            any_invalid = (
                reserved | id_zero | id_max | dr_zero | dr_max | cr_zero
                | cr_max | same_acct | ~pend_zero | ~dr_found | ~cr_found
                | (not_pending_flag & (timeout != 0))
                | (not_balancing & amount_zero)
                | (ledger == 0) | (code == 0)
                | (dr_ledger != cr_ledger) | (ledger != dr_ledger)
            ).any()
            if not any_invalid:
                return pack(_first_code(n))

        # Static precedence ladder (reference: src/state_machine.zig:
        # 1465-1504 normal, :1614-1624 post/void prefix).
        static = _first_code(n)
        _apply_code(static, reserved, CTR.reserved_flag)
        _apply_code(static, id_zero, CTR.id_must_not_be_zero)
        _apply_code(static, id_max, CTR.id_must_not_be_int_max)

        # Post/void static prefix.
        post = (flags & kernel.F_POST) != 0
        void = (flags & kernel.F_VOID) != 0
        pv_excl = (
            (post & void)
            | (is_pv & ((flags & kernel.F_PENDING) != 0))
            | (is_pv & ((flags & kernel.F_BAL_DR) != 0))
            | (is_pv & ((flags & kernel.F_BAL_CR) != 0))
        )
        pend_max = (pend_lo == np.uint64(U64_MAX)) & (pend_hi == np.uint64(U64_MAX))
        pend_self = (pend_lo == id_lo) & (pend_hi == id_hi)
        _apply_code(static, is_pv & pv_excl, CTR.flags_are_mutually_exclusive)
        _apply_code(static, is_pv & pend_zero, CTR.pending_id_must_not_be_zero)
        _apply_code(static, is_pv & pend_max, CTR.pending_id_must_not_be_int_max)
        _apply_code(static, is_pv & pend_self, CTR.pending_id_must_be_different)
        _apply_code(static, is_pv & (timeout != 0), CTR.timeout_reserved_for_pending_transfer)

        # Normal static ladder.
        nm = ~is_pv
        _apply_code(static, nm & dr_zero, CTR.debit_account_id_must_not_be_zero)
        _apply_code(static, nm & dr_max, CTR.debit_account_id_must_not_be_int_max)
        _apply_code(static, nm & cr_zero, CTR.credit_account_id_must_not_be_zero)
        _apply_code(static, nm & cr_max, CTR.credit_account_id_must_not_be_int_max)
        _apply_code(static, nm & same_acct, CTR.accounts_must_be_different)
        _apply_code(static, nm & ~pend_zero, CTR.pending_id_must_be_zero)
        _apply_code(
            static, nm & not_pending_flag & (timeout != 0),
            CTR.timeout_reserved_for_pending_transfer,
        )
        _apply_code(static, nm & not_balancing & amount_zero, CTR.amount_must_not_be_zero)
        _apply_code(static, nm & (ledger == 0), CTR.ledger_must_not_be_zero)
        _apply_code(static, nm & (code == 0), CTR.code_must_not_be_zero)
        _apply_code(static, nm & ~dr_found, CTR.debit_account_not_found)
        _apply_code(static, nm & ~cr_found, CTR.credit_account_not_found)
        _apply_code(
            static, nm & (dr_ledger != cr_ledger), CTR.accounts_must_have_the_same_ledger
        )
        _apply_code(
            static, nm & (ledger != dr_ledger),
            CTR.transfer_must_have_the_same_ledger_as_accounts,
        )

        return pack(static)

    def _commit_transfers_resolved(
        self, n, ts_base, events, id_lo, id_hi, pend_lo, pend_hi,
        flags, timeout, dr_slot, cr_slot, amount_lo, amount_hi,
        ledger, code, static, is_pv, dr_flags, cr_flags, dr_zero, cr_zero,
    ) -> bytes:
        """Fast-path routing + exact kernel dispatch, after account
        resolution and the static ladder."""
        wave_mode = waves.mode()
        # "1"/"exact"/"scan" all route the batch to the JAX exact
        # dispatch below (skipping the host fast paths); "1" further
        # forces the wave plan past its profitability gate.
        wave_force = wave_mode in ("1", "exact", "scan")
        # The JAX kernel needs shape buckets (compile cache); the native
        # exact engine takes any length — skip the ~50-array padding.
        if self._native is not None and not wave_force:
            B = n
        else:
            B = next(b for b in _BATCH_BUCKETS if b >= n)

        # Durable joins (vectorized hash-index probes).
        e_found, e_row = self._tdir.lookup(id_lo, id_hi)

        # Fast-path routing (see kernel_fast.py preconditions): no
        # order-dependent flags, no in-batch or durable id collisions,
        # no limit/history accounts anywhere in the batch.
        order_free = not (
            flags
            & np.uint32(
                TF.linked
                | TF.post_pending_transfer
                | TF.void_pending_transfer
                | TF.balancing_debit
                | TF.balancing_credit
            )
        ).any()
        # In-batch duplicate-id check: strictly-increasing ids (the
        # common encoder output) prove uniqueness without a sort; else
        # a 64-bit key mix + unique — a hash collision only costs a
        # detour through the exact scan path, which resolves true id
        # groups.  The lexicographic (hi, lo) ascending test is shared
        # with the exact-path grouping shortcut below.
        ascending = n == 1 or bool(
            (
                (id_hi[1:] > id_hi[:-1])
                | ((id_hi[1:] == id_hi[:-1]) & (id_lo[1:] > id_lo[:-1]))
            ).all()
        )
        # The resolver routes exclude only balancing batches (order_free
        # already implies no balancing flags).
        route_candidate = not (
            flags & np.uint32(TF.balancing_debit | TF.balancing_credit)
        ).any()
        if route_candidate:
            ids_unique = ascending
            if not ids_unique:
                id_mix = id_lo * np.uint64(0x9E3779B97F4A7C15) + id_hi * np.uint64(
                    0xC2B2AE3D27D4EB4F
                )
                ids_unique = len(np.unique(id_mix)) == n
        else:
            ids_unique = False
        if order_free and ids_unique and not e_found.any() and not wave_force:
            acct_flags = dr_flags | cr_flags
            if not (
                acct_flags
                & np.uint32(
                    AF.debits_must_not_exceed_credits
                    | AF.credits_must_not_exceed_debits
                    | AF.history
                )
            ).any():
                reply = self._commit_fast(
                    n, ts_base, events, id_lo, id_hi, pend_lo, pend_hi,
                    flags, timeout, dr_slot, cr_slot, amount_lo, amount_hi,
                    ledger, code, static,
                )
                if reply is not None:
                    self.stat_device_events += n
                    return reply

        # Linked-chain / limit-account resolution (resolve.py): plain
        # posted transfers — chains and balance-limit accounts allowed
        # (a chain-free batch is just all chains of length 1) — get
        # exact verdicts from a vectorized fixpoint, then scatter-add
        # apply.  Batches without limits or chains never reach here
        # (the order-free path above took them).
        if (
            ids_unique
            and not wave_force
            and not (
                flags
                & np.uint32(
                    TF.pending
                    | TF.post_pending_transfer
                    | TF.void_pending_transfer
                    | TF.balancing_debit
                    | TF.balancing_credit
                )
            ).any()
            and not e_found.any()
            and not ((dr_flags | cr_flags) & np.uint32(AF.history)).any()
        ):
            reply = self._commit_linked_fast(
                n, ts_base, events, id_lo, id_hi, flags, timeout,
                dr_slot, cr_slot, amount_lo, amount_hi, ledger, code,
                static, dr_flags, cr_flags,
            )
            if reply is not None:
                self.stat_device_events += n
                self.stat_linked_batches += 1
                return reply

        j = self._exact_joins(
            n, B, id_lo, id_hi, pend_lo, pend_hi, is_pv, ascending,
            e_found, e_row,
        )
        unique_ids = j["unique_ids"]
        id_group = j["id_group"]
        p_group = j["p_group"]
        p_found = j["p_found"]
        gather_e = j["gather_e"]
        gather_p = j["gather_p"]
        uniq_rows = j["uniq_rows"]
        uniq_status = j["uniq_status"]
        p_tgt = j["p_tgt"]
        dstat_init = j["dstat_init"]

        # Two-phase resolution (resolve.py): post/void batches whose
        # verdicts are balance-independent resolve in one vectorized
        # pass — pendings, first-wins finalization, scatter-add apply.
        if is_pv.any() and ids_unique and not e_found.any() and not wave_force:
            reply = self._try_two_phase_fast(
                n, ts_base, events, id_lo, id_hi, pend_lo, pend_hi, flags,
                timeout, dr_slot, cr_slot, amount_lo, amount_hi, ledger,
                code, static, is_pv, dr_flags, cr_flags,
                unique_ids, id_group, p_group, p_found, gather_p,
                uniq_rows, p_tgt, uniq_status,
            )
            if reply is not None:
                self.stat_device_events += n
                self.stat_two_phase_batches += 1
                return reply

        ev = self._build_scan_events(
            n, B, events, flags, static, amount_lo, amount_hi,
            pend_lo, pend_hi, timeout, ledger, code, dr_slot, cr_slot,
            dr_flags, cr_flags, dr_zero, cr_zero, e_found, j,
        )

        self.stat_exact_events += n
        if self._native is not None and not wave_force:
            # Serial exact engine in C++ (native/tb_exact.inc): same
            # inputs and packed-output contract as the scan kernel.
            # Sequential semantics are inherently serial (the reference
            # loop is single-core), so the host runs them at memory
            # speed; the shared mirror is mutated in place and the
            # deltas ride the async device queue.
            packed_np, deltas = self._native.commit_exact(
                ev, kernel.EVENT_FIELDS, dstat_init, B, n, ts_base
            )
            self._dev.enqueue(*[d.copy() for d in deltas])
            out = kernel.unpack_outputs(packed_np)
            mirror_from_hist = False  # C++ already updated the mirror
        else:
            # Conflict-aware wave execution (waves.py): when the batch
            # partitions into few mutually-independent waves, run one
            # vectorized device step per wave — chain waves for clean
            # linked runs, and the exact scan only over true conflict
            # groups — instead of the full B-step scan.  Bit-identical
            # outputs (tests/test_waves.py).  A degraded device engine
            # pins this JAX work at the CPU backend: the default
            # backend may be the dead tunneled TPU.
            wave_plan = None
            if wave_mode not in ("0", "scan"):
                wave_plan = self._plan_wave_execution(
                    n, flags, dr_slot, cr_slot, dr_flags, cr_flags,
                    id_group, p_group, p_tgt, p_found, gather_p, is_pv,
                    amount_lo, amount_hi, force=(wave_mode == "1"),
                )
            with self._host_jax_scope():
                if wave_plan is not None:
                    # Wave events' snapshots are rewritten to batch
                    # finals at finalize (history events never ride
                    # waves).
                    new_balances, packed = waves.run_create_transfers_waves(
                        self._balances, ev, dstat_init, n, ts_base,
                        wave_plan, _pad(wave_plan.wave_mask, B),
                    )
                    self.stat_wave_batches += 1
                    self.stat_wave_steps += wave_plan.n_steps
                    self.stat_wave_events += n
                    self.stat_wave_parallel_events += wave_plan.parallel_events
                else:
                    new_balances, packed = kernel.run_create_transfers(
                        self._balances,
                        {k: jnp.asarray(v) for k, v in ev.items()},
                        dstat_init, n, ts_base,
                    )
                self._balances = new_balances

                # ONE device->host transfer for every output: the
                # kernel packs them into a single u64 matrix because
                # the device link is high-latency and per-leaf fetches
                # each pay a full round trip (20x slower on a tunneled
                # TPU).
                out = kernel.unpack_outputs(np.asarray(packed))
            mirror_from_hist = True

        return self._finish_exact_outputs(
            out, n, ts_base, id_lo, id_hi, flags, timeout,
            uniq_rows, dstat_init, mirror_from_hist,
        )

    def _host_jax_scope(self):
        """JAX placement scope for host exact-path execution: pins the
        work at the CPU backend while the device engine is degraded or
        recovering (ROADMAP "Pin degraded-mode host compute") — the
        process default backend may be the dead tunneled TPU, and
        jnp.asarray/jit dispatch would otherwise route there.  A no-op
        (null scope) in host-engine mode and on a healthy engine."""
        import contextlib

        dev = self._dev
        if self.engine == "device" and (
            getattr(dev, "state", None) is not types.EngineState.healthy
            or dev._recovering
        ):
            cpu = dev._cpu_device()
            if cpu is not None:
                return jax.default_device(cpu)
        return contextlib.nullcontext()

    def _finish_exact_outputs(
        self, out, n, ts_base, id_lo, id_hi, flags, timeout,
        uniq_rows, dstat_init, mirror_from_hist,
    ) -> bytes:
        """Exact-path bookkeeping tail from unpacked kernel outputs —
        shared by the synchronous host path and the device engine's
        wave-record finish (which runs it at materialization from the
        fetched packed matrix)."""
        results = out["results"][:n]
        created_mask = out["created_mask"][:n]
        created = {f: out["created"][f][:n] for f in kernel.CREATED_FIELDS}
        inb_status = out["inb_status"][:n]
        dstat = out["dstat"]
        hist_dr = out["hist_dr"][:n]
        hist_cr = out["hist_cr"][:n]

        # Mirror reconstruction: events whose effects persisted
        # (results == 0; rollback rewrote failed-chain members) carry
        # post-apply snapshots of both touched rows. Interleaved in
        # event order, last write wins -> final balances of every
        # touched slot (rolled-back-only slots net to no change).
        ok_idx = np.flatnonzero(results == 0)
        if mirror_from_hist and len(ok_idx):
            slots2 = np.empty(2 * len(ok_idx), np.int64)
            slots2[0::2] = created["dr_slot"][ok_idx]
            slots2[1::2] = created["cr_slot"][ok_idx]
            rows2 = np.empty((2 * len(ok_idx), 8), np.uint64)
            rows2[0::2] = hist_dr[ok_idx]
            rows2[1::2] = hist_cr[ok_idx]
            self._mirror.set_rows8(slots2, rows2)

        self._post_process_transfers(
            n, ts_base, id_lo, id_hi, flags, timeout,
            results, created_mask, created, inb_status,
            dstat_init, dstat, uniq_rows,
            hist_dr, hist_cr,
            int(out["last_applied"]),
            out["pulse_create"][:n],
            out["pulse_remove"][:n],
        )

        # Reply: failures only, in event order.
        fail_idx = np.flatnonzero(results != 0)
        reply = np.zeros(len(fail_idx), dtype=CREATE_RESULT_DTYPE)
        reply["index"] = fail_idx.astype(np.uint32)
        reply["result"] = results[fail_idx]
        return reply.tobytes()

    def _exact_joins(
        self, n, B, id_lo, id_hi, pend_lo, pend_hi, is_pv, ascending,
        e_found, e_row,
    ) -> dict:
        """Exact-path join bundle: compact id groups, in-batch pending
        reference groups, durable duplicate/pending-target gathers and
        the deduped durable-status seed — shared by the host exact
        path and the device engine's wave submission."""
        # Exact-path id groups: one compact index per distinct id value.
        id_key = pack_u128(id_lo, id_hi)
        if ascending:
            # Strictly ascending (the common sequential-id encoding):
            # identity grouping without the unique() sort.
            unique_ids = id_key
            id_group = np.arange(n)
        else:
            unique_ids, id_group = np.unique(id_key, return_inverse=True)
        pend_key = pack_u128(pend_lo, pend_hi)
        pos = np.searchsorted(unique_ids, pend_key)
        pos_c = np.minimum(pos, len(unique_ids) - 1)
        p_group = np.where(
            is_pv & (unique_ids[pos_c] == pend_key), pos_c, -1
        ).astype(np.int32)

        if is_pv.any():
            p_found, p_row = self._tdir.lookup(pend_lo, pend_hi)
            p_found = p_found & is_pv
        else:
            p_found = np.zeros(n, bool)
            p_row = np.zeros(n, np.uint64)
        er = np.clip(e_row, 0, None).astype(np.int64)
        pr = np.clip(p_row, 0, None).astype(np.int64)

        st = self._store

        # Durable joins: ONE batched fetch per referenced row set (the
        # rows may live in the LSM spill tier — per-column gathers
        # would re-read the objects 13 times), skipped entirely when
        # the batch references no durable duplicate/pending rows (the
        # common case for fresh-id batches).
        _JOIN_FIELDS = (
            "flags", "dr_slot", "cr_slot", "amount_lo", "amount_hi",
            "pending_lo", "pending_hi", "ud128_lo", "ud128_hi",
            "ud64", "ud32", "timeout", "ledger", "code", "timestamp",
            "status",
        )

        def _make_gather(found, rows):
            if not found.any():
                empty = {
                    f: np.zeros(n, np.dtype(_STORE_FIELDS[f]))
                    for f in _JOIN_FIELDS
                }
                return lambda col: empty[col]
            idx = np.flatnonzero(found)
            got = st.gather_many(
                list(_JOIN_FIELDS), rows[idx].astype(np.int64)
            )
            full = {}
            for f in _JOIN_FIELDS:
                arr = np.zeros(n, got[f].dtype)
                arr[idx] = got[f]
                full[f] = arr
            return lambda col: full[col]

        gather_e = _make_gather(e_found, er)
        gather_p = _make_gather(p_found, pr)

        # Durable-pending target dedupe + initial statuses (taken from
        # the already-gathered join columns — no second LSM fetch).
        p_rows_valid = p_row[p_found].astype(np.int64)
        if len(p_rows_valid):
            uniq_rows, first_idx, tgt_inverse = np.unique(
                p_rows_valid, return_index=True, return_inverse=True
            )
            rep_event = np.flatnonzero(p_found)[first_idx]
            uniq_status = gather_p("status")[rep_event].astype(np.uint32)
        else:
            uniq_rows = np.zeros(0, np.int64)
            tgt_inverse = np.zeros(0, np.int64)
            uniq_status = np.zeros(0, np.uint32)
        p_tgt = np.full(n, -1, np.int32)
        p_tgt[p_found] = tgt_inverse.astype(np.int32)
        dstat_init = np.zeros(B, np.uint32)
        dstat_init[: len(uniq_rows)] = uniq_status
        return dict(
            unique_ids=unique_ids, id_group=id_group, p_group=p_group,
            p_found=p_found, p_row=p_row, gather_e=gather_e,
            gather_p=gather_p, uniq_rows=uniq_rows,
            uniq_status=uniq_status, p_tgt=p_tgt, dstat_init=dstat_init,
        )

    def _build_scan_events(
        self, n, B, events, flags, static, amount_lo, amount_hi,
        pend_lo, pend_hi, timeout, ledger, code, dr_slot, cr_slot,
        dr_flags, cr_flags, dr_zero, cr_zero, e_found, j,
    ) -> dict:
        """The (B,)-padded host event-array dict per
        kernel.EVENT_FIELDS — the scan/wave executors' input contract,
        shared by the host exact path and the wave submission."""
        gather_e = j["gather_e"]
        gather_p = j["gather_p"]
        return {
            "i": np.arange(B, dtype=np.int32),
            "flags": _pad(flags, B),
            "ts_nonzero": _pad(events["timestamp"] != 0, B),
            "static_result": _pad(static, B),
            "amount_lo": _pad(amount_lo, B), "amount_hi": _pad(amount_hi, B),
            "pending_lo": _pad(pend_lo, B), "pending_hi": _pad(pend_hi, B),
            "ud128_lo": _pad(events["user_data_128_lo"].astype(np.uint64), B),
            "ud128_hi": _pad(events["user_data_128_hi"].astype(np.uint64), B),
            "ud64": _pad(events["user_data_64"].astype(np.uint64), B),
            "ud32": _pad(events["user_data_32"].astype(np.uint32), B),
            "timeout": _pad(timeout, B),
            "ledger": _pad(ledger, B), "code": _pad(code, B),
            "dr_slot": _pad(dr_slot, B), "cr_slot": _pad(cr_slot, B),
            "dr_flags": _pad(dr_flags, B), "cr_flags": _pad(cr_flags, B),
            "dr_id_zero": _pad(dr_zero, B), "cr_id_zero": _pad(cr_zero, B),
            "id_group": _pad(j["id_group"].astype(np.int32), B),
            "p_group": _pad(j["p_group"], B),
            "e_found": _pad(e_found, B),
            "e_flags": _pad(gather_e("flags").astype(np.uint32), B),
            "e_dr_slot": _pad(gather_e("dr_slot").astype(np.int32), B),
            "e_cr_slot": _pad(gather_e("cr_slot").astype(np.int32), B),
            "e_amount_lo": _pad(gather_e("amount_lo").astype(np.uint64), B),
            "e_amount_hi": _pad(gather_e("amount_hi").astype(np.uint64), B),
            "e_pending_lo": _pad(gather_e("pending_lo").astype(np.uint64), B),
            "e_pending_hi": _pad(gather_e("pending_hi").astype(np.uint64), B),
            "e_ud128_lo": _pad(gather_e("ud128_lo").astype(np.uint64), B),
            "e_ud128_hi": _pad(gather_e("ud128_hi").astype(np.uint64), B),
            "e_ud64": _pad(gather_e("ud64").astype(np.uint64), B),
            "e_ud32": _pad(gather_e("ud32").astype(np.uint32), B),
            "e_timeout": _pad(gather_e("timeout").astype(np.uint64), B),
            "e_code": _pad(gather_e("code").astype(np.uint32), B),
            "p_found": _pad(j["p_found"], B),
            "p_flags": _pad(gather_p("flags").astype(np.uint32), B),
            "p_dr_slot": _pad(gather_p("dr_slot").astype(np.int32), B),
            "p_cr_slot": _pad(gather_p("cr_slot").astype(np.int32), B),
            "p_amount_lo": _pad(gather_p("amount_lo").astype(np.uint64), B),
            "p_amount_hi": _pad(gather_p("amount_hi").astype(np.uint64), B),
            "p_ud128_lo": _pad(gather_p("ud128_lo").astype(np.uint64), B),
            "p_ud128_hi": _pad(gather_p("ud128_hi").astype(np.uint64), B),
            "p_ud64": _pad(gather_p("ud64").astype(np.uint64), B),
            "p_ud32": _pad(gather_p("ud32").astype(np.uint32), B),
            "p_timeout": _pad(gather_p("timeout").astype(np.uint64), B),
            "p_ledger": _pad(gather_p("ledger").astype(np.uint32), B),
            "p_code": _pad(gather_p("code").astype(np.uint32), B),
            "p_timestamp": _pad(gather_p("timestamp").astype(np.uint64), B),
            "p_tgt": _pad(j["p_tgt"], B),
        }

    def _plan_wave_execution(
        self, n, flags, dr_slot, cr_slot, dr_flags, cr_flags,
        id_group, p_group, p_tgt, p_found, gather_p, is_pv,
        amount_lo, amount_hi, force: bool = False, extra_bound: int = 0,
    ):
        """Wave routing decision for one exact-path batch: dependency
        metadata (_wave_metadata) -> cheap chain-dominance decline
        (_chain_dominated) -> per-column overflow admission
        (_wave_admission) -> level partition + profitability.
        Returns the plan or None — the scan path — and is always safe
        to decline (never a wrong answer, only a slower one).
        `extra_bound` is the device engine's in-flight contribution
        bound when planning a window batch (the mirror lags
        materialization there); zero on the drained host path."""
        meta, pv_serial = self._wave_metadata(
            n, flags, dr_slot, cr_slot, dr_flags, cr_flags,
            id_group, p_group, p_tgt, p_found, gather_p,
        )
        # Chain-dominance declines on a cheap metadata counter BEFORE
        # the per-column admission pays its bound accumulation.
        if self._chain_dominated(n, meta, force):
            return None
        adm = self._wave_admission(
            n, meta, flags, p_found, gather_p, is_pv,
            amount_lo, amount_hi, extra_bound=extra_bound,
        )
        if adm is None:
            return None
        inb_pairs, batch_bound = adm
        return self._grade_plan(n, meta, inb_pairs, batch_bound, force)

    def _grade_plan(self, n, meta, inb_pairs, batch_bound, force: bool):
        """Partition + profitability + bound attachment — the ONE copy
        shared by the drained host path and the window submission (a
        profitability change made in one and not the other would
        silently diverge the two routings)."""
        plan = waves.plan_waves(n, meta, inb_pairs=inb_pairs)
        if not (force or plan.profitable()):
            return None
        plan.batch_bound = batch_bound
        return plan

    def _wave_metadata(
        self, n, flags, dr_slot, cr_slot, dr_flags, cr_flags,
        id_group, p_group, p_tgt, p_found, gather_p,
    ):
        """Dependency metadata (resolve.py) + the pv_serial routing
        fact, shared by the pessimistic wave path and the speculative
        dispatcher — the cheap first stage every routing gate reads."""
        p_drs = gather_p("dr_slot").astype(np.int64)
        p_crs = gather_p("cr_slot").astype(np.int64)

        # History accounts force per-event-sequential snapshots: their
        # events read their own rows (wave_dependency_metadata), and a
        # post/void whose target could sit on one goes to the scan.
        hist_ev = ((dr_flags | cr_flags) & np.uint32(AF.history)) != 0
        pv_hist = False
        if p_found.any():
            pj = np.unique(
                np.concatenate([p_drs[p_found], p_crs[p_found]])
            )
            pj = pj[pj >= 0]
            pv_hist = bool(
                (self._attrs["flags"][pj] & np.uint32(AF.history)).any()
            )
        pv_serial = bool(hist_ev.any() or pv_hist)
        meta = resolve.wave_dependency_metadata(
            n, flags, dr_slot, cr_slot, dr_flags, cr_flags,
            id_group, p_group, p_tgt, p_found, p_drs, p_crs,
            pv_serial=pv_serial,
        )
        # Stash the durable pending-target slot arrays for the
        # admission stage — already gathered here, and this path's
        # host wall time is exactly what dev_wave.plan_s instruments.
        meta["p_drs"] = p_drs
        meta["p_crs"] = p_crs
        return meta, pv_serial

    def _wave_admission(
        self, n, meta, flags, p_found, gather_p, is_pv,
        amount_lo, amount_hi, extra_bound: int = 0,
    ):
        """Per-column overflow admission against the mirror, shared by
        the pessimistic wave path and the speculative dispatcher
        (which must prove the same overflow superset before executing
        the whole batch optimistically — the ov_* exactness argument
        is order-free, so it covers the one-step speculative apply and
        any residue replay identically).  Returns
        (inb_pairs, batch_bound) or None when the batch lacks provable
        u128 headroom."""
        p_drs = meta["p_drs"]
        p_crs = meta["p_crs"]

        # Per-column overflow admission (waves.admission_ok): per-event
        # amount upper bounds — balancing zero-amount means maxInt u64,
        # post/void apply at most max(t.amount, pending.amount), and an
        # in-batch inherit is bounded by the largest create bound.
        is_balancing = (
            flags & np.uint32(TF.balancing_debit | TF.balancing_credit)
        ) != 0
        amount_zero = (amount_lo == 0) & (amount_hi == 0)
        bound_lo = np.where(
            is_balancing & amount_zero, np.uint64(U64_MAX), amount_lo
        )
        bound_hi = np.where(is_balancing & amount_zero, np.uint64(0), amount_hi)
        p_amt_lo = gather_p("amount_lo").astype(np.uint64)
        p_amt_hi = gather_p("amount_hi").astype(np.uint64)
        p_bigger = is_pv & (
            (p_amt_hi > bound_hi)
            | ((p_amt_hi == bound_hi) & (p_amt_lo > bound_lo))
        )
        bound_lo = np.where(p_bigger, p_amt_lo, bound_lo)
        bound_hi = np.where(p_bigger, p_amt_hi, bound_hi)
        inb_inherit = is_pv & amount_zero & ~p_found
        if inb_inherit.any():
            nm = ~is_pv
            if nm.any():
                mx_hi = bound_hi[nm].max()
                at = bound_hi[nm] == mx_hi
                mx_lo = bound_lo[nm][at].max()
                bound_lo = np.where(inb_inherit, mx_lo, bound_lo)
                bound_hi = np.where(inb_inherit, mx_hi, bound_hi)
        # Per-contribution (slot, bound) pairs: each slot an event can
        # add a balance column through, charged with that event's
        # bound — dr/cr for creates, the durable target's accounts for
        # found finalizers, and the referenced group's slot union for
        # in-batch finalizers (the creator is whichever applied).
        inb_ev, inb_slot = waves._inb_pv_write_pairs(n, meta)
        slots = np.concatenate(
            [meta["ev_dr"], meta["ev_cr"],
             p_drs[p_found], p_crs[p_found], inb_slot]
        )
        bounds_lo = np.concatenate(
            [bound_lo, bound_lo, bound_lo[p_found], bound_lo[p_found],
             bound_lo[inb_ev]]
        )
        bounds_hi = np.concatenate(
            [bound_hi, bound_hi, bound_hi[p_found], bound_hi[p_found],
             bound_hi[inb_ev]]
        )
        # Admission runs BEFORE the per-event partition: the bound
        # arrays are vectorized numpy, so a persistently declining
        # deployment (no u128 headroom left) never pays the plan cost.
        if not waves.admission_ok(
            self._mirror.lo, self._mirror.hi, slots, bounds_lo, bounds_hi,
            extra=extra_bound,
        ):
            return None
        return (inb_ev, inb_slot), _amount_bound_total(bound_lo, bound_hi)

    @staticmethod
    def _chain_dominated(n, meta, force: bool) -> bool:
        """Cheap pre-admission decline: chain members cost one exact
        step each UNLESS they are chain-wave candidates (clean linked
        runs, waves.py) — decline chain-dominated batches before
        paying admission or the partition only when the chains could
        not ride position-stepped anyway."""
        n_chain = int(meta["chain_member"].sum())
        chain_wave_possible = (
            waves.chain_max() >= 2
            and not meta["chain_serial"].any()
            and not (meta["chain_linked"] & meta["is_pv"]).any()
        )
        return (
            not force
            and bool(n_chain)
            and not chain_wave_possible
            and n < waves.min_ratio() * n_chain
        )

    def _try_native_two_phase(
        self, input_bytes, events, n, ts_base
    ) -> bytes | None:
        """Two-phase batch via the native serial resolver
        (native/tb_two_phase.inc).  Python prefetches the durable
        pending targets' columns (they may live in the LSM spill tier)
        and finishes the store/expiry bookkeeping; the resolver owns
        decode, ladders, reference resolution, and balance effects."""
        flags16 = np.asarray(events["flags"])
        pv16 = np.uint16(TF.post_pending_transfer | TF.void_pending_transfer)
        pv_mask = (flags16 & pv16) != 0
        if not pv_mask.any():
            return None
        # Cheap shape gate before paying for the durable join (the
        # native pass-0 would reject these anyway, but only after the
        # tdir lookup + LSM gather below already ran).
        if (flags16 & np.uint16(TF.linked)).any():
            return None
        pv_idx = np.flatnonzero(pv_mask)
        pend_lo = np.asarray(events["pending_id_lo"])[pv_idx]
        pend_hi = np.asarray(events["pending_id_hi"])[pv_idx]
        found, rows = self._tdir.lookup(pend_lo, pend_hi)
        join = None
        if found.any():
            hit = pv_idx[found]
            hit_rows = rows[found].astype(np.int64)
            got = self._store.gather_many(
                [
                    "flags", "dr_slot", "cr_slot", "amount_lo", "amount_hi",
                    "ledger", "code", "ud128_lo", "ud128_hi", "ud64", "ud32",
                    "timeout", "status",
                ],
                hit_rows,
            )
            join = {"row": np.full(n, -1, np.int64)}
            join["row"][hit] = hit_rows
            for f, dt in (
                ("flags", np.uint32), ("dr_slot", np.int32),
                ("cr_slot", np.int32), ("amount_lo", np.uint64),
                ("amount_hi", np.uint64), ("ledger", np.uint32),
                ("code", np.uint32), ("ud128_lo", np.uint64),
                ("ud128_hi", np.uint64), ("ud64", np.uint64),
                ("ud32", np.uint32), ("timeout", np.uint32),
                ("status", np.uint32),
            ):
                arr = np.zeros(n, dt)
                arr[hit] = got[f].astype(dt)
                join[f] = arr
        r = self._native.commit_two_phase(input_bytes, n, ts_base, join)
        if r is None:
            return None
        d = r["deltas"]
        self._dev.enqueue(d[0].copy(), d[1].copy(), d[2].copy(), d[3].copy())
        # Durable finalizations: status byte updates (rows may be
        # spilled; referenced targets are timeout-free by the
        # resolver's contract, so no expiry-index deactivation).
        if len(r["dur_rows"]):
            self._store["status"][r["dur_rows"].copy()] = r[
                "dur_status"
            ].astype(np.uint8)
        flags = flags16.astype(np.uint32)
        timeout = np.asarray(events["timeout"]).astype(np.uint64)
        created = {
            "flags": flags,
            "dr_slot": r["row_dr"], "cr_slot": r["row_cr"],
            "amount_lo": r["amt_lo"], "amount_hi": r["amt_hi"],
            "pending_lo": np.asarray(events["pending_id_lo"]),
            "pending_hi": np.asarray(events["pending_id_hi"]),
            "ud128_lo": r["ud128_lo"], "ud128_hi": r["ud128_hi"],
            "ud64": r["ud64"], "ud32": r["ud32"],
            "timeout": timeout,
            "ledger": r["ledger"], "code": r["code"],
        }
        return self._finish_fast(
            n, ts_base, np.asarray(events["id_lo"]),
            np.asarray(events["id_hi"]), flags, timeout, r["results"],
            created, last_applied=r["last_applied"],
            inb_status=r["inb_status"],
        )

    def _finish_native_fast(
        self, events, n, ts_base, results, dr_slot, cr_slot, deltas,
        last_applied: int | None = None,
    ) -> bytes:
        """Bookkeeping after a native fast-path apply: device enqueue,
        store append, expiry/pulse updates, reply (mirrors
        _commit_fast's tail; results/slots are views into reusable
        native buffers, consumed before the next native call)."""
        dslot, dcol, dlo, dhi = deltas
        # Copies: the device queue holds these past this call, and the
        # native output buffers are reused per batch.
        self._dev.enqueue(
            dslot.copy(), dcol.copy(), dlo.copy(), dhi.copy()
        )

        # Hot tail: every event applied, no timeouts — ONE C pass
        # decodes the wire records straight into the store's column
        # buffers (replacing ~17 strided numpy gathers per batch),
        # then only the id-directory and commit_timestamp remain.
        if (
            not (results != 0).any()
            and not np.asarray(events["timeout"]).any()
        ):
            self.stat_hot_tail_batches += 1
            st = self._store
            st.ram._ensure(n)
            lo = st.ram.count
            from tigerbeetle_tpu.runtime import fastpath as fp_mod

            fp_mod.decode_store(events, n, ts_base, st.ram._cols, lo)
            st.ram._cols["dr_slot"][lo : lo + n] = dr_slot
            st.ram._cols["cr_slot"][lo : lo + n] = cr_slot
            st.ram.count = lo + n
            rows = np.arange(lo, lo + n) - st._off + st.base
            id_lo = st.ram._cols["id_lo"][lo : lo + n]
            id_hi = st.ram._cols["id_hi"][lo : lo + n]
            self._tdir.insert(id_lo, id_hi, rows.astype(np.uint64))
            if self._native is not None:
                self._native.add_transfer_ids(id_lo, id_hi, int(rows[0]))
            self.commit_timestamp = ts_base + n - 1
            return b""

        self.stat_slow_tail_batches += 1
        flags = events["flags"].astype(np.uint32)
        timeout = np.asarray(events["timeout"]).astype(np.uint64)
        created = {
            "flags": flags,
            "dr_slot": dr_slot, "cr_slot": cr_slot,
            "amount_lo": np.asarray(events["amount_lo"]),
            "amount_hi": np.asarray(events["amount_hi"]),
            "pending_lo": np.asarray(events["pending_id_lo"]),
            "pending_hi": np.asarray(events["pending_id_hi"]),
            "ud128_lo": np.asarray(events["user_data_128_lo"]),
            "ud128_hi": np.asarray(events["user_data_128_hi"]),
            "ud64": np.asarray(events["user_data_64"]),
            "ud32": np.asarray(events["user_data_32"]),
            "timeout": timeout,
            "ledger": np.asarray(events["ledger"]),
            "code": events["code"].astype(np.uint32),
        }
        return self._finish_fast(
            n, ts_base, np.asarray(events["id_lo"]),
            np.asarray(events["id_hi"]), flags, timeout, results, created,
            last_applied=last_applied,
        )

    def _commit_fast(
        self, n, ts_base, events, id_lo, id_hi, pend_lo, pend_hi,
        flags, timeout, dr_slot, cr_slot, amount_lo, amount_hi, ledger, code,
        static,
    ) -> bytes | None:
        """Parallel scatter-add apply for order-independent batches.

        Returns None when a balance-overflow is possible, in which case
        the caller re-runs the exact scan kernel (a later event may
        legitimately apply after an earlier one fails with an overflow
        code — reference: src/state_machine.zig:1531-1545).
        """
        # Remaining per-event codes are all order-independent here:
        # timestamp_must_be_zero precedes the static ladder (reference:
        # src/state_machine.zig:1251-1256), overflows_timeout depends
        # only on the event's own timestamp.
        results = np.where(
            events["timestamp"] != 0,
            np.uint32(CTR.timestamp_must_be_zero),
            static,
        )
        ts_i = np.uint64(ts_base) + np.arange(n, dtype=np.uint64)
        expires = ts_i + timeout * np.uint64(NS_PER_S)
        ov_timeout = expires < ts_i
        if ov_timeout.any():
            # overflows_timeout ranks BELOW the balance-overflow codes
            # (reference ladder: src/state_machine.zig:1531-1545), and
            # such an event's amount wouldn't reach the mirror's
            # monotone check — only the exact path ranks them right.
            return None
        apply_mask = results == 0
        is_pending = (flags & np.uint32(TF.pending)) != 0

        # Host-mirror admission (monotone-overflow check) + async
        # device enqueue — the hot path never waits on the device.
        deltas = self._mirror.try_apply_adds(
            dr_slot.astype(np.int64), cr_slot.astype(np.int64),
            amount_lo, amount_hi, is_pending, apply_mask,
        )
        if deltas is None:
            return None
        self._dev.enqueue(*deltas, refresh_twin=False)

        created = {
            "flags": flags,
            "dr_slot": dr_slot.astype(np.int32),
            "cr_slot": cr_slot.astype(np.int32),
            "amount_lo": amount_lo, "amount_hi": amount_hi,
            "pending_lo": pend_lo, "pending_hi": pend_hi,
            "ud128_lo": np.asarray(events["user_data_128_lo"]),
            "ud128_hi": np.asarray(events["user_data_128_hi"]),
            "ud64": np.asarray(events["user_data_64"]),
            "ud32": np.asarray(events["user_data_32"]),
            "timeout": timeout,
            "ledger": ledger, "code": code,
        }
        return self._finish_fast(
            n, ts_base, id_lo, id_hi, flags, timeout, results, created
        )

    def _commit_linked_fast(
        self, n, ts_base, events, id_lo, id_hi, flags, timeout,
        dr_slot, cr_slot, amount_lo, amount_hi, ledger, code,
        static, dr_flags, cr_flags,
    ) -> bytes | None:
        """Linked-chain batch via the vectorized fixpoint resolver.

        Preconditions were checked by the router (plain posted
        transfers only, unique fresh ids, no history accounts).  The
        superset overflow admission below proves no overflow result
        code can fire for ANY subset of the batch (deltas are
        non-negative), which reduces the dynamic ladder to the limit
        checks that resolve.linked_resolve models exactly."""
        ts_nonzero = np.asarray(events["timestamp"] != 0)
        # Superset = every event that could conceivably apply (static
        # failures — including account-not-found, so slots here are
        # always valid — never touch balances).
        may_apply = (static == 0) & ~ts_nonzero
        if not may_apply.any():
            pass  # nothing can apply; resolver handles codes
        elif (
            self._mirror.try_apply_adds(
                dr_slot.astype(np.int64), cr_slot.astype(np.int64),
                amount_lo, amount_hi, np.zeros(n, bool), may_apply,
                commit=False,
            )
            is None
        ):
            return None
        r = resolve.linked_resolve(
            static, ts_nonzero, flags, dr_slot, cr_slot,
            amount_lo, amount_hi, dr_flags, cr_flags, self._mirror,
        )
        if r is None:
            return None
        results, last_applied, iters = r
        self.stat_resolve_iters += iters
        deltas = self._mirror.try_apply_adds(
            dr_slot.astype(np.int64), cr_slot.astype(np.int64),
            amount_lo, amount_hi, np.zeros(n, bool), results == 0,
        )
        assert deltas is not None  # subset of the admitted superset
        self._dev.enqueue(*deltas, refresh_twin=False)
        created = {
            "flags": flags,
            "dr_slot": dr_slot.astype(np.int32),
            "cr_slot": cr_slot.astype(np.int32),
            "amount_lo": amount_lo, "amount_hi": amount_hi,
            "pending_lo": np.zeros(n, np.uint64),
            "pending_hi": np.zeros(n, np.uint64),
            "ud128_lo": np.asarray(events["user_data_128_lo"]),
            "ud128_hi": np.asarray(events["user_data_128_hi"]),
            "ud64": np.asarray(events["user_data_64"]),
            "ud32": np.asarray(events["user_data_32"]),
            "timeout": timeout,
            "ledger": ledger, "code": code,
        }
        return self._finish_fast(
            n, ts_base, id_lo, id_hi, flags, timeout, results, created,
            last_applied=last_applied,
        )

    def _try_two_phase_fast(
        self, n, ts_base, events, id_lo, id_hi, pend_lo, pend_hi, flags,
        timeout, dr_slot, cr_slot, amount_lo, amount_hi, ledger, code,
        static, is_pv, dr_flags, cr_flags,
        unique_ids, id_group, p_group, p_found, gather_p,
        uniq_rows, p_tgt, uniq_status,
    ) -> bytes | None:
        """Two-phase batch via the closed-form resolver.

        Remaining preconditions (the router already checked unique
        fresh ids): no linked/balancing flags, zero timeouts
        everywhere (event timeouts AND durable targets'), no limit or
        history flags on any touched account including durable
        targets' accounts, and in-batch pending references that point
        at actual pending creates.  Anything else returns None — the
        serial exact engine owns it."""
        if (
            flags
            & np.uint32(TF.linked | TF.balancing_debit | TF.balancing_credit)
        ).any():
            return None
        if timeout.any():
            return None
        LIMH = np.uint32(
            AF.debits_must_not_exceed_credits
            | AF.credits_must_not_exceed_debits
            | AF.history
        )
        if ((dr_flags | cr_flags) & LIMH).any():
            return None
        attrs = self._attrs
        if p_found.any():
            if (gather_p("timeout") != 0).any():
                return None
            pj_dr = np.clip(gather_p("dr_slot").astype(np.int64), 0, None)
            pj_cr = np.clip(gather_p("cr_slot").astype(np.int64), 0, None)
            pj_flags = np.where(
                p_found,
                attrs["flags"][pj_dr] | attrs["flags"][pj_cr],
                0,
            ).astype(np.uint32)
            if (pj_flags & LIMH).any():
                return None

        # In-batch pending-reference resolution: creator event of each
        # distinct id (ids are unique, so this is a permutation).
        creator = np.empty(len(unique_ids), np.int64)
        creator[id_group] = np.arange(n)
        tgt_ev = np.where(
            p_group >= 0, creator[np.clip(p_group, 0, None)], -1
        )
        idx = np.arange(n)
        ib = is_pv & (tgt_ev >= 0) & (tgt_ev < idx)
        if (
            ib
            & (
                (flags[np.clip(tgt_ev, 0, None)] & np.uint32(TF.pending))
                == 0
            )
        ).any():
            # Reference resolution on a non-pending in-batch row would
            # couple pv verdicts to each other — exact engine decides.
            return None

        ts_nonzero = np.asarray(events["timestamp"] != 0)
        p_join = {
            f: gather_p(f)
            for f in (
                "flags", "dr_slot", "cr_slot", "amount_lo", "amount_hi",
                "ledger", "code", "ud128_lo", "ud128_hi", "ud64", "ud32",
            )
        }
        ud128_lo = np.asarray(events["user_data_128_lo"])
        ud128_hi = np.asarray(events["user_data_128_hi"])
        ud64 = np.asarray(events["user_data_64"])
        ud32 = np.asarray(events["user_data_32"]).astype(np.uint32)
        r = resolve.two_phase_resolve(
            static, ts_nonzero, flags, is_pv,
            np.asarray(events["debit_account_id_lo"]),
            np.asarray(events["debit_account_id_hi"]),
            np.asarray(events["credit_account_id_lo"]),
            np.asarray(events["credit_account_id_hi"]),
            amount_lo, amount_hi,
            ud128_lo, ud128_hi, ud64, ud32,
            np.asarray(events["ledger"]), code,
            tgt_ev, p_found, p_tgt, p_join, uniq_status, attrs,
        )
        if r is None:
            return None

        results = r["results"]
        ok = r["ok"]
        winner = r["winner"]
        post = r["post"]
        pend_flag = r["pend_flag"]
        tgt_c = np.clip(tgt_ev, 0, None)
        in_batch = r["in_batch"]
        # Unified target slots (in-batch event columns or durable join).
        p_drs = np.where(
            in_batch,
            dr_slot[tgt_c].astype(np.int64),
            np.clip(p_join["dr_slot"].astype(np.int64), 0, None),
        )
        p_crs = np.where(
            in_batch,
            cr_slot[tgt_c].astype(np.int64),
            np.clip(p_join["cr_slot"].astype(np.int64), 0, None),
        )

        # --- balance deltas.  Adds are admission-checked atomically;
        # pending releases can never underflow (each live pending's
        # amount is contained in dp/cp by invariant).
        pend_ok = ok & pend_flag
        plain_ok = ok & ~pend_flag & ~is_pv
        post_win = winner & post
        add_slots = np.concatenate([
            dr_slot[pend_ok].astype(np.int64), cr_slot[pend_ok].astype(np.int64),
            dr_slot[plain_ok].astype(np.int64), cr_slot[plain_ok].astype(np.int64),
            p_drs[post_win], p_crs[post_win],
        ])
        n_pend = int(pend_ok.sum())
        n_plain = int(plain_ok.sum())
        n_post = int(post_win.sum())
        add_cols = np.concatenate([
            np.zeros(n_pend, np.int64), np.full(n_pend, 2, np.int64),
            np.ones(n_plain, np.int64), np.full(n_plain, 3, np.int64),
            np.ones(n_post, np.int64), np.full(n_post, 3, np.int64),
        ])
        add_lo = np.concatenate([
            amount_lo[pend_ok], amount_lo[pend_ok],
            amount_lo[plain_ok], amount_lo[plain_ok],
            r["res_amt_lo"][post_win], r["res_amt_lo"][post_win],
        ])
        add_hi = np.concatenate([
            amount_hi[pend_ok], amount_hi[pend_ok],
            amount_hi[plain_ok], amount_hi[plain_ok],
            r["res_amt_hi"][post_win], r["res_amt_hi"][post_win],
        ])
        deltas = self._mirror.try_apply_deltas(
            add_slots, add_cols, add_lo, add_hi
        )
        if deltas is None:
            return None  # overflow codes in play — exact engine decides
        n_win = int(winner.sum())
        sub_slots = np.concatenate([p_drs[winner], p_crs[winner]])
        sub_cols = np.concatenate(
            [np.zeros(n_win, np.int64), np.full(n_win, 2, np.int64)]
        )
        sub_lo = np.concatenate([r["p_amt_lo"][winner]] * 2)
        sub_hi = np.concatenate([r["p_amt_hi"][winner]] * 2)
        if n_win:
            self._mirror.apply_subs(sub_slots, sub_cols, sub_lo, sub_hi)
            zero = np.zeros(2 * n_win, np.uint64)
            neg_lo, neg_hi, _ = _sub_u128(zero, zero, sub_lo, sub_hi)
            self._dev.enqueue(
                np.concatenate([deltas[0], sub_slots]),
                np.concatenate([deltas[1], sub_cols]),
                np.concatenate([deltas[2], neg_lo]),
                np.concatenate([deltas[3], neg_hi]),
                refresh_twin=False,
            )
        else:
            self._dev.enqueue(*deltas, refresh_twin=False)

        # --- durable store rows (zero-means-inherit resolution for
        # created pv rows; reference: src/state_machine.zig:1697-1720).
        ud128_set = (ud128_lo != 0) | (ud128_hi != 0)
        created = {
            "flags": flags,
            "dr_slot": np.where(is_pv, p_drs, dr_slot.astype(np.int64)).astype(np.int32),
            "cr_slot": np.where(is_pv, p_crs, cr_slot.astype(np.int64)).astype(np.int32),
            "amount_lo": np.where(is_pv, r["res_amt_lo"], amount_lo),
            "amount_hi": np.where(is_pv, r["res_amt_hi"], amount_hi),
            "pending_lo": pend_lo, "pending_hi": pend_hi,
            "ud128_lo": np.where(is_pv & ~ud128_set, r["p_ud128_lo"], ud128_lo),
            "ud128_hi": np.where(is_pv & ~ud128_set, r["p_ud128_hi"], ud128_hi),
            "ud64": np.where(is_pv & (ud64 == 0), r["p_ud64"], ud64),
            "ud32": np.where(is_pv & (ud32 == 0), r["p_ud32"], ud32),
            "timeout": np.zeros(n, np.uint64),
            "ledger": np.where(
                is_pv, r["p_ledger"], np.asarray(events["ledger"])
            ).astype(np.uint32),
            "code": np.where(is_pv, r["p_code"], code).astype(np.uint32),
        }
        inb_status = np.where(
            pend_ok, np.uint32(kernel.S_PENDING), np.uint32(0)
        )
        ib_win = winner & in_batch
        if ib_win.any():
            inb_status[tgt_ev[ib_win]] = np.where(
                post[ib_win],
                np.uint32(kernel.S_POSTED),
                np.uint32(kernel.S_VOIDED),
            )
        dstat_init = uniq_status.copy()
        dstat = uniq_status.copy()
        dur_win = winner & r["durable"]
        if dur_win.any():
            dstat[p_tgt[dur_win]] = np.where(
                post[dur_win],
                np.uint32(kernel.S_POSTED),
                np.uint32(kernel.S_VOIDED),
            )
        zeros_u64 = np.zeros(n, np.uint64)
        self._post_process_transfers(
            n, ts_base, id_lo, id_hi, flags, timeout,
            results, ok, created, inb_status,
            dstat_init, dstat, uniq_rows,
            np.zeros((n, 8), np.uint64), np.zeros((n, 8), np.uint64),
            r["last_applied"], zeros_u64, zeros_u64,
            no_history=True,
        )
        fail_idx = np.flatnonzero(results != 0)
        reply = np.zeros(len(fail_idx), dtype=CREATE_RESULT_DTYPE)
        reply["index"] = fail_idx.astype(np.uint32)
        reply["result"] = results[fail_idx]
        return reply.tobytes()

    def _finish_fast(
        self, n, ts_base, id_lo, id_hi, flags, timeout, results, created,
        last_applied: int | None = None,
        inb_status: np.ndarray | None = None,
    ) -> bytes:
        """Shared fast-path tail (native and Python admission paths):
        expiry/pulse signals, store bookkeeping, failure reply.  Must
        stay one implementation — every fast path\'s durable state
        depends on it being identical.  `inb_status` overrides the
        default created-pending statuses when the caller finalized
        pendings within the batch (two-phase resolver)."""
        apply_mask = results == 0
        is_pending = (flags & np.uint32(TF.pending)) != 0
        ts_i = np.uint64(ts_base) + np.arange(n, dtype=np.uint64)
        expires = ts_i + timeout * np.uint64(NS_PER_S)
        if inb_status is None:
            inb_status = np.where(
                apply_mask & is_pending,
                np.uint32(kernel.S_PENDING),
                np.uint32(0),
            )
        if last_applied is None:
            applied_idx = np.flatnonzero(apply_mask)
            last_applied = int(applied_idx[-1]) if len(applied_idx) else -1
        pulse_create = np.where(
            apply_mask & is_pending & (timeout > 0), expires, np.uint64(0)
        )

        self._post_process_transfers(
            n, ts_base, id_lo, id_hi, flags, timeout,
            results, apply_mask, created, inb_status,
            np.zeros(0, np.uint32), np.zeros(0, np.uint32),
            np.zeros(0, np.int64),
            np.zeros((n, 8), np.uint64), np.zeros((n, 8), np.uint64),
            last_applied, pulse_create, np.zeros(n, np.uint64),
            no_history=True,
        )

        fail_idx = np.flatnonzero(results != 0)
        reply = np.zeros(len(fail_idx), dtype=CREATE_RESULT_DTYPE)
        reply["index"] = fail_idx.astype(np.uint32)
        reply["result"] = results[fail_idx]
        return reply.tobytes()

    def _post_process_transfers(
        self, n, ts_base, id_lo, id_hi, flags, timeout,
        results, created_mask, created, inb_status,
        dstat_init, dstat, uniq_rows,
        hist_dr, hist_cr, last_applied, pulse_create, pulse_remove,
        no_history: bool = False,
    ) -> None:
        ok = results == 0
        # 1. Insert created transfers into the columnar store.  When
        # the whole batch applied (the hot path), index with slices —
        # no per-column fancy-gather copies.
        cm = created_mask
        if cm.all():
            idx = np.arange(n)
            sel = lambda a: a  # noqa: E731
        elif cm.any():
            idx = np.flatnonzero(cm)
            sel = lambda a: a[idx]  # noqa: E731
        else:
            idx = None
        if idx is not None:
            ts = np.uint64(ts_base) + idx.astype(np.uint64)
            rows = self._store.append(
                id_lo=sel(id_lo), id_hi=sel(id_hi),
                dr_slot=sel(created["dr_slot"]), cr_slot=sel(created["cr_slot"]),
                amount_lo=sel(created["amount_lo"]), amount_hi=sel(created["amount_hi"]),
                pending_lo=sel(created["pending_lo"]), pending_hi=sel(created["pending_hi"]),
                ud128_lo=sel(created["ud128_lo"]), ud128_hi=sel(created["ud128_hi"]),
                ud64=sel(created["ud64"]), ud32=sel(created["ud32"]),
                timeout=sel(created["timeout"]).astype(np.uint32, copy=False),
                ledger=sel(created["ledger"]), code=sel(created["code"]),
                flags=sel(flags), timestamp=ts,
                status=sel(inb_status).astype(np.uint8),
            )
            self._tdir.insert(sel(id_lo), sel(id_hi), rows.astype(np.uint64))
            if self._native is not None:
                # Keep the native duplicate-id set in lockstep (rows
                # are contiguous, so base_row + i == row).
                self._native.add_transfer_ids(
                    sel(id_lo), sel(id_hi), int(rows[0])
                )
            row_of_event = np.full(n, -1, np.int64)
            row_of_event[idx] = rows
        else:
            row_of_event = np.full(n, -1, np.int64)

        # 2. Durable pending-status updates (+ expires index removal),
        # batched: changed rows may live in the LSM spill tier.
        changed = np.flatnonzero(dstat[: len(uniq_rows)] != dstat_init[: len(uniq_rows)])
        if len(changed):
            ch_rows = uniq_rows[changed]
            self._store["status"][ch_rows] = dstat[changed].astype(np.uint8)
            timeouts = self._store["timeout"][ch_rows]
            for row in ch_rows[np.asarray(timeouts) > 0]:
                self._exp_deactivate(int(row))

        # 3. New expires entries for still-pending in-batch creations.
        pend_created = np.flatnonzero(
            cm & (inb_status == kernel.S_PENDING) & (timeout > 0)
        )
        if len(pend_created):
            exp_rows = row_of_event[pend_created]
            expires = (
                np.uint64(ts_base)
                + pend_created.astype(np.uint64)
                + timeout[pend_created] * np.uint64(NS_PER_S)
            )
            self._exp.append(
                expires_at=expires,
                row=exp_rows.astype(np.uint32),
                active=np.ones(len(exp_rows), bool),
            )
        # In-batch created-then-finished pendings: status already stored;
        # their expires entries were never added (create+remove nets out).

        # 4. pulse_next_timestamp replay from the kernel's apply-time
        # signals — these are recorded pre-rollback, matching the
        # reference's unscoped pulse_next mutations
        # (reference: src/state_machine.zig:1576-1580,1704-1708).
        for k in np.flatnonzero((pulse_create != 0) | (pulse_remove != 0)):
            create_at = int(pulse_create[k])
            remove_at = int(pulse_remove[k])
            if create_at:
                if create_at < self.pulse_next_timestamp:
                    self.pulse_next_timestamp = create_at
            if remove_at:
                if self.pulse_next_timestamp == remove_at:
                    self.pulse_next_timestamp = TIMESTAMP_MIN

        # 5. Historical balances (skipped when the fast-path admission
        # already proved no account in the batch has flags.history).
        applied = cm & ok
        if not no_history and applied.any():
            idx = np.flatnonzero(applied)
            drs = created["dr_slot"][idx]
            crs = created["cr_slot"][idx]
            dr_hist = (self._attrs["flags"][drs] & AF.history) != 0
            cr_hist = (self._attrs["flags"][crs] & AF.history) != 0
            want = dr_hist | cr_hist
            if want.any():
                sel = idx[want]
                drs, crs = drs[want], crs[want]
                dr_hist, cr_hist = dr_hist[want], cr_hist[want]
                zero8 = np.zeros((len(sel), 8), np.uint64)
                self._history.append(
                    timestamp=np.uint64(ts_base) + sel.astype(np.uint64),
                    dr_id_lo=np.where(dr_hist, self._attrs["id_lo"][drs], 0),
                    dr_id_hi=np.where(dr_hist, self._attrs["id_hi"][drs], 0),
                    cr_id_lo=np.where(cr_hist, self._attrs["id_lo"][crs], 0),
                    cr_id_hi=np.where(cr_hist, self._attrs["id_hi"][crs], 0),
                    dr_bal=np.where(dr_hist[:, None], hist_dr[sel], zero8),
                    cr_bal=np.where(cr_hist[:, None], hist_cr[sel], zero8),
                )

        # 6. commit_timestamp advances to the last event that reached
        # the apply point — including chain events later rolled back
        # (reference: src/state_machine.zig:1583; rollback never
        # reverts commit_timestamp).
        if last_applied >= 0:
            self.commit_timestamp = ts_base + last_applied

    def _exp_deactivate(self, row: int) -> None:
        exp_rows = self._exp.col("row")
        active = self._exp.col("active")
        matches = np.flatnonzero((exp_rows == row) & active)
        self._exp["active"][matches] = False
        self._exp_dead += len(matches)
        # Compact once tombstones dominate, keeping scans O(live).
        if self._exp_dead * 2 > self._exp.count and self._exp.count > 64:
            live = np.flatnonzero(self._exp.col("active"))
            cols = {
                name: self._exp.col(name)[live].copy()
                for name in ("expires_at", "row", "active")
            }
            self._exp.truncate(0)
            self._exp.append(**cols)
            self._exp_dead = 0

    # ------------------------------------------------------------------
    # Expiry pulse.

    def _scan_expired(self, expires_at_max: int) -> np.ndarray:
        limit = self.config.batch_max_create_transfers
        active = self._exp.col("active")
        exp_at = self._exp.col("expires_at")
        rows = self._exp.col("row")
        live = np.flatnonzero(active)
        if len(live) == 0:
            self.pulse_next_timestamp = TIMESTAMP_MAX
            return np.zeros(0, np.int64)
        ts = self._store["timestamp"][rows[live]]
        order = np.lexsort((ts, exp_at[live]))
        ordered = live[order]
        ordered_exp = exp_at[live][order]

        due = ordered_exp <= expires_at_max
        due_idx = np.flatnonzero(due)
        if len(due_idx) > limit:
            taken = ordered[due_idx[:limit]]
            # buffer_finished: next pulse rescans from the overflow point
            # (reference: src/state_machine.zig:2136-2140).
            self.pulse_next_timestamp = int(ordered_exp[due_idx[limit]])
        elif len(due_idx) == len(ordered_exp):
            taken = ordered[due_idx]
            self.pulse_next_timestamp = TIMESTAMP_MAX
        else:
            taken = ordered[due_idx]
            self.pulse_next_timestamp = int(ordered_exp[len(due_idx)])
        return rows[taken].astype(np.int64)

    def _commit_expire(self, timestamp: int) -> bytes:
        assert self._expiry_rows is not None
        rows, self._expiry_rows = self._expiry_rows, None
        if len(rows) == 0:
            return b""

        st = self._store
        # Release pending amounts: dp -= amount on the debit side,
        # cp -= amount on the credit side (sums are order-independent;
        # reference: src/state_machine.zig:1874-1929). Mirror applies
        # exactly; the device gets the same deltas as two's-complement
        # modular adds through the write-behind queue.
        slots = np.concatenate([st["dr_slot"][rows], st["cr_slot"][rows]]).astype(
            np.int64
        )
        cols = np.concatenate(
            [np.zeros(len(rows), np.int64), np.full(len(rows), 2, np.int64)]
        )
        amt_lo = np.concatenate([st["amount_lo"][rows]] * 2)
        amt_hi = np.concatenate([st["amount_hi"][rows]] * 2)
        self._mirror.apply_subs(slots, cols, amt_lo, amt_hi)
        zero = np.zeros(len(slots), np.uint64)
        neg_lo, neg_hi, _ = _sub_u128(zero, zero, amt_lo, amt_hi)
        self._dev.enqueue(slots, cols, neg_lo, neg_hi, refresh_twin=False)

        st["status"][rows] = np.uint8(TransferPendingStatus.expired)
        for row in rows:
            self._exp_deactivate(int(row))
        return b""

    # ------------------------------------------------------------------
    # Lookups & queries (cold path).

    def _lookup_accounts(self, input_bytes: bytes) -> bytes:
        ids = np.frombuffer(input_bytes, dtype=types.U128_PAIR_DTYPE)
        found, slots = self._acct_dir.lookup(
            ids["lo"].astype(np.uint64), ids["hi"].astype(np.uint64)
        )
        hit = np.flatnonzero(found)
        out = np.zeros(len(hit), dtype=ACCOUNT_DTYPE)
        if len(hit) == 0:
            return b""
        slots = slots[hit].astype(np.int64)
        balances = self._mirror.rows8(slots)
        a = self._attrs
        out["id_lo"], out["id_hi"] = a["id_lo"][slots], a["id_hi"][slots]
        out["debits_pending_lo"], out["debits_pending_hi"] = balances[:, 0], balances[:, 1]
        out["debits_posted_lo"], out["debits_posted_hi"] = balances[:, 2], balances[:, 3]
        out["credits_pending_lo"], out["credits_pending_hi"] = balances[:, 4], balances[:, 5]
        out["credits_posted_lo"], out["credits_posted_hi"] = balances[:, 6], balances[:, 7]
        out["user_data_128_lo"] = a["ud128_lo"][slots]
        out["user_data_128_hi"] = a["ud128_hi"][slots]
        out["user_data_64"] = a["ud64"][slots]
        out["user_data_32"] = a["ud32"][slots]
        out["ledger"] = a["ledger"][slots]
        out["code"] = a["code"][slots]
        out["flags"] = a["flags"][slots]
        out["timestamp"] = a["timestamp"][slots]
        return out.tobytes()

    def _transfer_rows_to_np(self, rows: np.ndarray) -> np.ndarray:
        st = self._store
        rows = np.asarray(rows, np.int64)
        out = np.zeros(len(rows), dtype=TRANSFER_DTYPE)
        if len(rows) == 0:
            return out
        cols = st.gather_many(
            [
                "id_lo", "id_hi", "dr_slot", "cr_slot", "amount_lo",
                "amount_hi", "pending_lo", "pending_hi", "ud128_lo",
                "ud128_hi", "ud64", "ud32", "timeout", "ledger", "code",
                "flags", "timestamp",
            ],
            rows,
        )
        out["id_lo"], out["id_hi"] = cols["id_lo"], cols["id_hi"]
        dr = cols["dr_slot"].astype(np.int64)
        cr = cols["cr_slot"].astype(np.int64)
        out["debit_account_id_lo"] = self._attrs["id_lo"][dr]
        out["debit_account_id_hi"] = self._attrs["id_hi"][dr]
        out["credit_account_id_lo"] = self._attrs["id_lo"][cr]
        out["credit_account_id_hi"] = self._attrs["id_hi"][cr]
        out["amount_lo"], out["amount_hi"] = cols["amount_lo"], cols["amount_hi"]
        out["pending_id_lo"], out["pending_id_hi"] = cols["pending_lo"], cols["pending_hi"]
        out["user_data_128_lo"] = cols["ud128_lo"]
        out["user_data_128_hi"] = cols["ud128_hi"]
        out["user_data_64"] = cols["ud64"]
        out["user_data_32"] = cols["ud32"]
        out["timeout"] = cols["timeout"]
        out["ledger"] = cols["ledger"]
        out["code"] = cols["code"]
        out["flags"] = cols["flags"]
        out["timestamp"] = cols["timestamp"]
        return out

    def _lookup_transfers(self, input_bytes: bytes) -> bytes:
        ids = np.frombuffer(input_bytes, dtype=types.U128_PAIR_DTYPE)
        found, rows = self._tdir.lookup(
            ids["lo"].astype(np.uint64), ids["hi"].astype(np.uint64)
        )
        hit = rows[found].astype(np.int64)
        return self._transfer_rows_to_np(hit).tobytes()

    def _parse_filter(self, input_bytes: bytes):
        row = np.frombuffer(input_bytes, dtype=ACCOUNT_FILTER_DTYPE)[0]
        return row

    def _filter_rows(self, filter_row) -> np.ndarray | None:
        """Validated filter -> matching store rows in timestamp order.

        reference: src/state_machine.zig:931-996.
        """
        account_id = types.u128_get(filter_row, "account_id")
        ts_min = int(filter_row["timestamp_min"])
        ts_max = int(filter_row["timestamp_max"])
        limit = int(filter_row["limit"])
        fflags = int(filter_row["flags"])
        valid = (
            account_id != 0
            and account_id != U128_MAX
            and ts_min != U64_MAX
            and ts_max != U64_MAX
            and (ts_max == 0 or ts_min <= ts_max)
            and limit != 0
            and (fflags & (AccountFilterFlags.debits | AccountFilterFlags.credits))
            and not (fflags & ~int(AccountFilterFlags._valid_mask))
            and bytes(filter_row["reserved"]) == b"\x00" * 24
        )
        if not valid:
            return None
        slot = self._account_slot(account_id)
        if slot is None:
            return np.zeros(0, np.int64)
        st = self._store
        lo = TIMESTAMP_MIN if ts_min == 0 else ts_min
        hi = TIMESTAMP_MAX if ts_max == 0 else ts_max
        # Spilled rows: the query composes through the ScanBuilder —
        # the same expression engine (eq / union / intersect over the
        # (slot, ts) index trees) the reference routes queries through
        # (reference: src/state_machine.zig:931-996 -> src/lsm/
        # scan_builder.zig:529).  Values mode yields row pointers.
        if st.base:
            from tigerbeetle_tpu.lsm.scan_builder import ScanBuilder

            sb = ScanBuilder(st.spill.groove)
            scans = []
            if fflags & AccountFilterFlags.debits:
                scans.append(sb.eq("dr_slot", slot))
            if fflags & AccountFilterFlags.credits:
                scans.append(sb.eq("cr_slot", slot))
            spilled = sb.evaluate(
                sb.union(*scans), ts_min=lo, ts_max=hi, return_values=True
            ).astype(np.int64)
        else:
            spilled = np.zeros(0, np.int64)
        # RAM tail: vectorized column scan.
        mask = np.zeros(st.tail_count(), bool)
        if fflags & AccountFilterFlags.debits:
            mask |= st.col("dr_slot") == slot
        if fflags & AccountFilterFlags.credits:
            mask |= st.col("cr_slot") == slot
        ts = st.col("timestamp")
        mask &= (ts >= lo) & (ts <= hi)
        tail_rows = np.flatnonzero(mask) + st.base
        # Spilled rows all precede the tail; concat keeps ts order.
        rows = np.concatenate([spilled, tail_rows])
        if fflags & AccountFilterFlags.reversed:
            rows = rows[::-1]
        return rows

    def _get_account_transfers(self, input_bytes: bytes) -> bytes:
        filter_row = self._parse_filter(input_bytes)
        rows = self._filter_rows(filter_row)
        if rows is None:
            return b""
        batch_max = self.config.batch_max(
            ACCOUNT_FILTER_DTYPE.itemsize, TRANSFER_DTYPE.itemsize
        )
        rows = rows[: min(int(filter_row["limit"]), batch_max)]
        return self._transfer_rows_to_np(rows).tobytes()

    def _get_account_balances(self, input_bytes: bytes) -> bytes:
        filter_row = self._parse_filter(input_bytes)
        account_id = types.u128_get(filter_row, "account_id")
        slot = self._account_slot(account_id)
        if slot is None or not (int(self._attrs["flags"][slot]) & AF.history):
            return b""
        rows = self._filter_rows(filter_row)
        if rows is None:
            return b""
        batch_max = self.config.batch_max(
            ACCOUNT_FILTER_DTYPE.itemsize, ACCOUNT_BALANCE_DTYPE.itemsize
        )
        rows = rows[: min(int(filter_row["limit"]), batch_max)]
        # Map transfer timestamps -> history rows (same timestamps;
        # history rows are store-ordered too).  The RAM tail serves
        # recent rows; older rows come from the LSM history groove.
        want_ts = np.asarray(self._store["timestamp"][rows], np.uint64)
        h = self._history
        h_ts = h.col("timestamp")
        id_lo = np.uint64(account_id & 0xFFFFFFFFFFFFFFFF)
        id_hi = np.uint64(account_id >> 64)
        bal = np.zeros((len(rows), 8), np.uint64)
        in_ram = np.zeros(len(rows), bool)
        if len(h_ts):
            pos = np.searchsorted(h_ts, want_ts)
            pos_c = np.minimum(pos, len(h_ts) - 1)
            in_ram = h_ts[pos_c] == want_ts
            pr = pos_c[in_ram]
            is_dr = (h["dr_id_lo"][pr] == id_lo) & (h["dr_id_hi"][pr] == id_hi)
            bal[in_ram] = np.where(
                is_dr[:, None], h["dr_bal"][pr], h["cr_bal"][pr]
            )
        cold = ~in_ram
        if cold.any():
            assert self._hspill is not None, "history row missing"
            found, got = self._hspill.gather_by_ts(want_ts[cold])
            assert found.all(), "history row missing from LSM tier"
            is_dr = (got["dr_id_lo"] == id_lo) & (got["dr_id_hi"] == id_hi)
            bal[cold] = np.where(
                is_dr[:, None], got["dr_bal"], got["cr_bal"]
            )
        out = np.zeros(len(rows), dtype=ACCOUNT_BALANCE_DTYPE)
        out["debits_pending_lo"], out["debits_pending_hi"] = bal[:, 0], bal[:, 1]
        out["debits_posted_lo"], out["debits_posted_hi"] = bal[:, 2], bal[:, 3]
        out["credits_pending_lo"], out["credits_pending_hi"] = bal[:, 4], bal[:, 5]
        out["credits_posted_lo"], out["credits_posted_hi"] = bal[:, 6], bal[:, 7]
        out["timestamp"] = want_ts
        return out.tobytes()


def _pad(arr: np.ndarray, size: int) -> np.ndarray:
    n = len(arr)
    if n == size:
        return np.ascontiguousarray(arr)
    out = np.zeros(size, arr.dtype)
    out[:n] = arr
    return out


# ----------------------------------------------------------------------
# Checkpoint snapshot (consumed by vsr.checkpointing).

def _tpu_snapshot(self) -> bytes:
    """Serialize durable state: columnar stores + the balance mirror
    (which exactly equals the device table after a queue drain —
    kernel_fast.py write-behind contract).  Fixed-layout binary
    encoding (utils/snapshot.py), NOT pickle: checkpoint blobs travel
    via state sync and must be safe to decode from untrusted bytes."""
    from tigerbeetle_tpu.utils import snapshot as snapcodec

    if self.engine == "device":
        self._dev.drain()
    self._dev.flush()  # queue drained; mirror == device content
    # Device<->mirror checksum at the checkpoint barrier (VERDICT r3
    # #4): in device mode the mirror is a demoted parity oracle, so a
    # silent divergence would otherwise surface only on a fallback.
    # Host mode pays a ~100ms fetch on this link, so it verifies only
    # when asked (TB_CKPT_VERIFY=1; tests and VOPR set it).
    from tigerbeetle_tpu import envcheck as _envcheck

    if self.engine == "device" or _envcheck.env_str("TB_CKPT_VERIFY") == "1":
        self.verify_device_mirror()
    count = self._attrs.count
    # prepare_timestamp is primary-only in-memory state, re-derived from
    # commit_timestamp after restore — see cpu.py snapshot note.
    # With a forest attached, the store section holds only the RAM tail
    # (everything older lives in LSM grid blocks referenced by the
    # manifest) — the blob is O(tail + accounts), not O(history).
    state = {
        "commit_timestamp": self.commit_timestamp,
        "pulse_next_timestamp": self.pulse_next_timestamp,
        "exp_dead": self._exp_dead,
        "store_base": self._store.base,
        "attrs": {k: self._attrs.col(k) for k in _ATTR_FIELDS},
        "store": {k: self._store.col(k) for k in _STORE_FIELDS},
        "exp": {k: self._exp.col(k) for k in ("expires_at", "row", "active")},
        "history": {k: self._history.col(k) for k in _HISTORY_FIELDS},
        "mirror_lo": self._mirror.lo[:count],
        "mirror_hi": self._mirror.hi[:count],
    }
    if self._forest is not None:
        state["history_base"] = self._hspill.base
        state["forest"] = self._forest.manifest_blob()
    return snapcodec.encode_tree(state)


def _tpu_restore(self, data: bytes) -> None:
    import jax.numpy as jnp

    from tigerbeetle_tpu.utils import snapshot as snapcodec

    state = snapcodec.decode_tree(data)
    self.commit_timestamp = state["commit_timestamp"]
    self.pulse_next_timestamp = state["pulse_next_timestamp"]
    self._exp_dead = state["exp_dead"]
    self.prepare_timestamp = self.commit_timestamp

    self._attrs = Columns(_ATTR_FIELDS)
    self._attrs.append(**state["attrs"])
    self._store = TailStore(_STORE_FIELDS)
    self._store.append(**state["store"])
    self._exp = Columns(
        {"expires_at": np.uint64, "row": np.uint32, "active": np.bool_}
    )
    self._exp.append(**state["exp"])
    self._history = Columns(_HISTORY_FIELDS)
    self._history.append(**state["history"])

    base = state.get("store_base", 0)
    if "forest" in state:
        from tigerbeetle_tpu.state_machine import spill as spill_mod

        assert self._forest is not None, "snapshot requires a forest"
        # Reopen the LSM tier from its manifest, then re-point the
        # spill handles at the restored grooves.
        self._forest.open(state["forest"])
        self._store.spill = spill_mod.TransferSpill(
            self._forest.grooves["transfers"],
            attrs_fn=lambda: self._attrs,
        )
        self._store.spill.base = base
        self._store.base = base
        self._hspill = spill_mod.HistorySpill(
            self._forest.grooves["account_history"]
        )
        self._hspill.base = state["history_base"]
    else:
        assert base == 0, "spilled snapshot but no forest attached"

    # Rebuild directories (derived state, never serialized).  Spilled
    # ids stream back from the object tree once; sequential-id runs
    # compress to O(1) ranges in the directories.
    n_acct = self._attrs.count
    self._acct_dir = RunIndex(_dir_capacity(n_acct))
    self._acct_dir.insert(
        self._attrs.col("id_lo"), self._attrs.col("id_hi"),
        np.arange(n_acct, dtype=np.uint64),
    )
    self._tdir = RunIndex(_dir_capacity(self._store.count))
    if base:
        from tigerbeetle_tpu.state_machine import spill as spill_mod

        for rows, obj in self._store.spill.iter_objects():
            cols = spill_mod.unpack_objects(obj)
            self._tdir.insert(
                cols["id_lo"], cols["id_hi"], rows.astype(np.uint64)
            )
    self._tdir.insert(
        self._store.col("id_lo"), self._store.col("id_hi"),
        np.arange(base, base + self._store.tail_count(), dtype=np.uint64),
    )

    cap = max(1 << 12, 1 << (n_acct - 1).bit_length() if n_acct else 1)
    self._mirror = BalanceMirror(cap)
    self._mirror.lo[:n_acct] = state["mirror_lo"]
    self._mirror.hi[:n_acct] = state["mirror_hi"]
    if self._native is not None:
        self._rebuild_native(cap)
    if self._commitment is not None:
        # Fresh twin over the restored mirror + attrs: recovery
        # recomputes the commitment from scratch (the replica asserts
        # it against the superblock's recorded state root).
        from tigerbeetle_tpu.state_machine import commitment as commitment_mod

        self._commitment = commitment_mod.HostCommitment(
            cap, meta_fn=self._commit_meta_cols
        )
        self._commitment.rebuild(self._mirror)
        self._mirror.commitment = self._commitment
    if self.engine == "device":
        from tigerbeetle_tpu.state_machine.device_engine import (
            DeviceEngine,
            DeviceLostError,
            make_spec_stats,
        )

        self._dev = DeviceEngine(
            cap, self._mirror, link=self._device_link,
            metrics=self.metrics.scope("dev"),
        )
        # Re-bind the machine-registry dev_wave.spec.* handles — the
        # counters are process-lifetime cumulative across restores.
        self._dev.spec_stats = make_spec_stats(self.metrics)
        self._bind_tier_stats()
        try:
            if self._dev.state is types.EngineState.healthy:
                # Tiered, this uploads the hot-shaped image for the
                # FRESH engine's (empty) hot map — admissions refill
                # the window on demand from the restored mirror.
                self._dev._upload_from_mirror()
        except DeviceLostError as exc:
            # Restore must not die with the link: the mirror restored
            # above is authoritative until re-promotion.
            self._dev._demote(exc)
        if n_acct:
            self._dev.add_accounts(
                np.arange(n_acct, dtype=np.int64),
                self._attrs.col("flags"),
                self._attrs.col("ledger"),
            )
    else:
        self._dev = kernel_fast.DeviceTable(cap)
        self._dev.mirror = self._mirror
        self._bind_tier_stats()
        # write_back gathers hot rows under tiering (identity swap
        # all-resident; _place only applies to device-resident tables).
        full = jnp.asarray(
            self._mirror.rows8(np.arange(cap, dtype=np.int64))
        )
        if self._dev.hot is None:
            full = self._dev._place(full)
        self._dev.write_back(full)
    self._inflight_timeouts = False
    self._expiry_rows = None


TpuStateMachine.snapshot = _tpu_snapshot
TpuStateMachine.restore = _tpu_restore
