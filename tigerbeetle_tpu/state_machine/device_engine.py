"""Device-authoritative execution pipeline for create_transfers.

Owns the authoritative HBM balance table + account-meta table and a
stream of semantic-kernel dispatches (device_kernels.py).  The host
submits packed batches and gets back *reply futures*; result codes are
computed on device, ride the failure-sparse summary ring, and
materialize once per execution window.

Execution model (r5: phase-separated windows)
---------------------------------------------
The tunneled link's physics (experiments/README.md) dictate the shape:
a d2h fetch costs ~105 ms regardless of size, and ANY h2d issued while
kernels are in flight stalls the stream for tens of milliseconds —
measured end-to-end, interleaving per-G-batch uploads with dispatches
runs 4x slower than the kernels themselves (experiments/stage_sweep.py).
So the engine never touches the link while the device is busy:

  submit()  appends the packed batch to a host-side window; NOTHING
            is dispatched until the window fills (TB_DEV_WINDOW).
  rotate    at the window boundary: (1) fetch the summary ring for the
            PREVIOUS window — the fetch drains the stream, leaving the
            device idle; (2) while idle, upload the new window's
            superbatches in one h2d per column layout and pull any
            lookup-gather handles; (3) dispatch every kernel of the new
            window back-to-back — zero in-stream transfers; (4) only
            then run the previous window's host bookkeeping (finish
            callbacks), overlapped with the device crunching the new
            window.

A batch whose summary carries a fallback flag (balance overflow in
play, failure-cap exceeded, precondition violated) triggers exact
recovery BEFORE the next window launches: the host re-executes that
batch through the host engine (``fallback`` callback, which updates
the mirror), re-uploads the corrected table, and re-dispatches every
later in-flight record.  Replies stay exact for ANY input; the flags
only cost latency.

The pipeline also carries the write-behind lane the host exact path
uses (``enqueue``/``flush``, same contract as kernel_fast.DeviceTable)
so host-resolved batches keep the device table current in stream
order, and a device-side ``lookup`` used to serve lookup_accounts
balances from the authoritative table (not the host mirror).
"""

from __future__ import annotations

import os
import time as _time

import numpy as np

import jax
import jax.numpy as jnp

from tigerbeetle_tpu.state_machine import device_kernels as dk

_WINDOW = int(os.environ.get("TB_DEV_WINDOW", "96"))
_RING = int(os.environ.get("TB_DEV_RING", "256"))
assert 2 * _WINDOW <= _RING, "ring must hold two windows of summaries"


class ReplyFuture:
    """Reply bytes that materialize at the batch's window rotation."""

    __slots__ = ("_value", "_engine")

    def __init__(self, engine=None, value: bytes | None = None) -> None:
        self._value = value
        self._engine = engine

    def done(self) -> bool:
        return self._value is not None

    def resolve(self, value: bytes) -> None:
        self._value = value

    def result(self) -> bytes:
        if self._value is None:
            self._engine.drain()
            assert self._value is not None, "drain did not materialize reply"
        return self._value


class _InFlight:
    """One stream entry, in submission order (ordering matters for
    exact fallback recovery): a semantic batch, a lookup gather, or an
    account-meta update."""

    __slots__ = (
        "kind", "pk", "n", "ts_base", "finish", "fallback", "future",
        "ring_at", "id_keys", "handle", "slots", "rows", "meta_args",
    )

    def __init__(self, kind, future, finish, *, pk=None, n=0, ts_base=0,
                 fallback=None, ring_at=-1, id_keys=None, handle=None,
                 slots=None, meta_args=None):
        self.kind = kind
        self.pk = pk
        self.n = n
        self.ts_base = ts_base
        self.finish = finish
        self.fallback = fallback
        self.future = future
        self.ring_at = ring_at
        self.id_keys = id_keys  # sorted u128-packed ids (hazard probes)
        self.handle = handle    # lookup gather output handle
        self.slots = slots      # lookup slots (for re-gather)
        self.rows = None        # lookup rows fetched at rotation
        self.meta_args = meta_args  # (slots, flags, ledger) for "meta"


_KERNELS = {
    "orderfree": dk.orderfree,
    "orderfree_lo": dk.orderfree_lo,
    "orderfree_tight": dk.orderfree_tight,
    "linked": dk.linked,
    "linked_small": dk.linked_small,
    "two_phase": dk.two_phase,
    "two_phase_lo": dk.two_phase_lo,
}
_SEMANTIC_KINDS = tuple(_KERNELS)


class DeviceEngine:
    """Authoritative device tables + windowed semantic dispatch."""

    def __init__(self, capacity: int, mirror) -> None:
        self.capacity = capacity
        self.mirror = mirror  # host bookkeeping copy (recovery + parity)
        self.window = _WINDOW
        # Multi-device: the authoritative tables shard ROW-WISE across
        # every visible device (NamedSharding over a 1-D "shard" mesh);
        # the semantic kernels then run SPMD with XLA-inserted
        # collectives — the same dispatch code path single-chip uses
        # (exercised by __graft_entry__.dryrun_multichip on a virtual
        # CPU mesh).
        self.sharding = None
        devices = jax.devices()
        if len(devices) > 1 and capacity % len(devices) == 0:
            from jax.sharding import Mesh, NamedSharding
            from jax.sharding import PartitionSpec as P

            mesh = Mesh(np.array(devices), ("shard",))
            self.sharding = NamedSharding(mesh, P("shard", None))
        self.balances = self._place(jnp.zeros((capacity, 8), jnp.uint64))
        self.meta = self._place(jnp.zeros((capacity, 2), jnp.uint32))
        self._meta_host = np.zeros((capacity, 2), np.uint32)
        self.ring = jnp.zeros((_RING, dk.SUMMARY_WORDS), jnp.uint64)
        self._ring_at = 0
        # Window pipeline: _pending accumulates host-side; _launched is
        # the window currently executing on device.
        self._pending: list[_InFlight] = []
        self._pending_semantic = 0
        self._launched: list[_InFlight] = []
        # Write-behind lane for host-resolved batches (exact path).
        self._q: list[tuple] = []
        self._queued = 0
        self._suppress_enqueue = False
        # Stats.
        self.stat_semantic_events = 0
        self.stat_fallback_batches = 0
        self.stat_fetches = 0
        # Wall-time split (seconds) for perf forensics.
        self.stat_t_h2d = 0.0
        self.stat_t_dispatch = 0.0
        self.stat_t_fetch = 0.0
        self.stat_t_finish = 0.0

    def _place(self, table):
        if self.sharding is None:
            return table
        return jax.device_put(table, self.sharding)

    def prewarm(self, kinds) -> None:
        """Pay the one-time per-process costs OFF the hot path: the
        tunnel compiles a transfer plan per h2d SHAPE (~1 s each,
        engine trace) and XLA compiles each scan kernel on first call.
        Callers that know their workload (bench configs) name the
        kinds; engine construction happens during untimed setup.

        The pseudo-kind "waves" warms the HOST-fallback wave executor
        (waves.py) against this engine's table geometry: a batch the
        router punts to the host path re-executes there, and with no
        native engine built that means wave/scan kernels whose first
        compile must not land inside a timed window."""
        kinds = list(kinds)
        if "waves" in kinds:
            from tigerbeetle_tpu.state_machine import waves as _waves

            _waves.prewarm(self.capacity)
        kinds = [k for k in kinds if k in _KERNELS]
        if not kinds:
            return
        tiers = sorted({self._tier(1), self._tier(self.window)})
        for ncols, dtype in {dk.PK_SPEC[k] for k in kinds}:
            jax.device_put(np.zeros((dk.B, ncols), dtype))
            for W in tiers:
                jax.device_put(np.zeros((W, dk.B, ncols), dtype))
        # The per-window ns/tsb arrays transfer from host at launch —
        # their transfer plans need warming like the buffers'.
        for W in tiers:
            jax.device_put(np.zeros(W, np.int64))
            jax.device_put(np.zeros(W, np.uint64))
        table = jnp.zeros_like(self.balances)
        meta = jnp.zeros_like(self.meta)
        ring = jnp.zeros_like(self.ring)
        outs = []
        for k in kinds:
            ncols, dtype = dk.PK_SPEC[k]
            pk = jnp.zeros((dk.B, ncols), dtype)
            outs.append(
                _KERNELS[k](table, meta, ring, 0, pk, 0, jnp.uint64(1))
            )
            for W in tiers:
                big = jnp.zeros((W, dk.B, ncols), dtype)
                ns = jnp.zeros(W, jnp.int64)
                tsb = jnp.zeros(W, jnp.uint64)
                for G in dk.SCAN_SIZES:
                    if G > W:
                        continue
                    outs.append(
                        dk.scan_win_kernels[k][G](
                            table, meta, ring, 0, big, 0, ns, tsb
                        )
                    )
        jax.block_until_ready(outs)

    # ------------------------------------------------------------------
    # Account meta maintenance (create_accounts path).  Rides the
    # record stream so updates sequence between the batches around
    # them without forcing a drain.

    def add_accounts(self, slots, acct_flags, acct_ledger) -> None:
        slots = np.asarray(slots, np.int64)
        self._meta_host[slots, 0] = acct_flags
        self._meta_host[slots, 1] = acct_ledger
        self._pending.append(
            _InFlight(
                "meta", None, None,
                meta_args=(
                    slots,
                    np.asarray(acct_flags, np.uint32),
                    np.asarray(acct_ledger, np.uint32),
                ),
            )
        )

    def remove_accounts(self, slots) -> None:
        """Linked create_accounts rollback support."""
        slots = np.asarray(slots, np.int64)
        self._meta_host[slots] = 0
        z = np.zeros(len(slots), np.uint32)
        self._pending.append(
            _InFlight("meta", None, None, meta_args=(slots, z, z))
        )

    def grow(self, capacity: int) -> None:
        if capacity <= self.capacity:
            return
        self.drain()
        self.flush()
        was_sharded = self.sharding is not None
        if was_sharded and capacity % self.sharding.mesh.devices.size != 0:
            self.sharding = None  # re-place replicated from here on
        extra = capacity - self.capacity

        def widen(table, width, dtype):
            # Previously-sharded tables come back through the host (row
            # boundaries move between devices on grow, and a dropped
            # sharding must not leave a committed sharded base behind).
            base = jax.device_get(table) if was_sharded else table
            return self._place(
                jnp.concatenate([base, jnp.zeros((extra, width), dtype)])
            )

        self.balances = widen(self.balances, 8, jnp.uint64)
        self.meta = widen(self.meta, 2, jnp.uint32)
        mh = np.zeros((capacity, 2), np.uint32)
        mh[: self.capacity] = self._meta_host
        self._meta_host = mh
        self.capacity = capacity

    # ------------------------------------------------------------------
    # Semantic dispatch.

    def submit(self, kind, pk, n, ts_base, finish, fallback,
               id_keys=None) -> ReplyFuture:
        """Queue one semantic batch; returns its reply future.

        `finish(summary) -> bytes` runs at materialization (device codes
        -> bookkeeping + reply).  `fallback() -> bytes` re-executes the
        batch exactly on the host engine against the mirror.
        """
        self.flush()  # earlier exact-path deltas must precede us
        fut = ReplyFuture(self)
        rec = _InFlight(
            kind, fut, finish, pk=pk, n=n, ts_base=ts_base,
            fallback=fallback, id_keys=id_keys,
        )
        self._pending.append(rec)
        self._pending_semantic += 1
        if self._pending_semantic >= self.window:
            self._rotate()
        return fut

    def lookup(self, slots, finish) -> ReplyFuture:
        """Device-side balance gather for lookup_accounts: rides the
        record stream, so it sees every earlier batch's effects.
        `finish(rows)` builds the reply from the fetched (k, 8) rows
        at materialization."""
        fut = ReplyFuture(self)
        slots = np.asarray(slots, np.int64)
        rec = _InFlight("lookup", fut, finish, slots=slots)
        self._pending.append(rec)
        return fut

    def _gather(self, slots):
        pad = ((len(slots) + 255) & ~255) or 256
        sl = np.full(pad, -1, np.int64)
        sl[: len(slots)] = slots
        return dk.lookup(self.balances, jnp.asarray(sl))

    # ------------------------------------------------------------------
    # Window launch: one h2d per column layout (device idle at call
    # time), then back-to-back dispatches with no in-stream transfers.

    def _plan_chunks(self, recs):
        """Group records into dispatch units: maximal same-kind
        semantic runs split into scan chunks (largest SCAN_SIZES
        first, exact decomposition — no padding, no wasted ring
        rows), with meta/lookup records as unit boundaries."""
        units = []
        run = []
        for rec in recs:
            if rec.kind in _SEMANTIC_KINDS and (
                not run or run[-1].kind == rec.kind
            ):
                run.append(rec)
                continue
            if run:
                units.extend(self._split_run(run))
                run = []
            if rec.kind in _SEMANTIC_KINDS:
                run.append(rec)
            else:
                units.append((rec.kind, [rec]))
        if run:
            units.extend(self._split_run(run))
        return units

    def _tier(self, rows: int) -> int:
        small = max(1, self.window // 3)
        return small if rows <= small else self.window

    @staticmethod
    def _split_run(run):
        out = []
        at = 0
        for G in dk.SCAN_SIZES:
            while len(run) - at >= G:
                out.append(("scan", run[at : at + G]))
                at += G
        for rec in run[at:]:
            out.append(("solo", [rec]))
        return out

    def _launch(self, recs: list[_InFlight]) -> None:
        """Upload the window's inputs in as FEW transfers as possible
        (after the first kernel runs, every h2d on this tunnel pays a
        large fixed cost — transfer count dominates, r5 measurements),
        block until they land (an in-flight transfer behind queued
        kernels crawls at the serialized in-stream rate), then
        dispatch back-to-back with zero in-stream transfers.
        Same-kind runs go G batches per LAUNCH via lax.scan reading
        from a per-spec window buffer at a row offset (~10 ms launch
        overhead per dispatch vs ~0.8 ms device compute)."""
        if not recs:
            return
        t0 = _time.perf_counter()
        units = self._plan_chunks(recs)
        # One (tier, B, C) buffer + (tier,) ns/tsb per input spec; scan
        # chunks claim contiguous row ranges in plan order.  The tier
        # (buffer row count) rounds the spec's claimed rows up to
        # window/3 or window, so a minority spec in a mixed window does
        # not ship a full window of padding (the link is bytes-bound).
        rows_of: dict[tuple, int] = {}
        for ukind, urecs in units:
            if ukind == "scan":
                spec = dk.PK_SPEC[urecs[0].kind]
                rows_of[spec] = rows_of.get(spec, 0) + len(urecs)
        bufs: dict[tuple, list] = {}  # spec -> [big, ns, tsb, cursor]
        offsets: dict[int, int] = {}
        for i, (ukind, urecs) in enumerate(units):
            if ukind != "scan":
                continue
            spec = dk.PK_SPEC[urecs[0].kind]
            if spec not in bufs:
                ncols, dtype = spec
                tier = self._tier(rows_of[spec])
                bufs[spec] = [
                    np.zeros((tier, dk.B, ncols), dtype),
                    np.zeros(tier, np.int64),
                    np.zeros(tier, np.uint64),
                    0,
                ]
            big, ns, tsb, cur = bufs[spec]
            for g, rec in enumerate(urecs):
                big[cur + g] = rec.pk
                ns[cur + g] = rec.n
                tsb[cur + g] = rec.ts_base
            offsets[i] = cur
            bufs[spec][3] = cur + len(urecs)
        dev_bufs = {
            spec: (
                jax.device_put(big),
                jax.device_put(ns),
                jax.device_put(tsb),
            )
            for spec, (big, ns, tsb, _cur) in bufs.items()
        }
        dev_solo = {
            i: jax.device_put(urecs[0].pk)
            for i, (ukind, urecs) in enumerate(units)
            if ukind == "solo"
        }
        # ONE blocking sync (each blocking call costs a ~100 ms tunnel
        # round trip).
        jax.block_until_ready([list(dev_bufs.values()), list(dev_solo.values())])
        t1 = _time.perf_counter()
        self.stat_t_h2d += t1 - t0
        for i, (ukind, urecs) in enumerate(units):
            if ukind == "meta":
                slots, flags, ledger = urecs[0].meta_args
                self.meta = dk.meta_update(
                    self.meta, jnp.asarray(slots), jnp.asarray(flags),
                    jnp.asarray(ledger),
                )
                continue
            if ukind == "lookup":
                urecs[0].handle = self._gather(urecs[0].slots)
                continue
            if ukind == "solo":
                rec = urecs[0]
                self.balances, self.ring = _KERNELS[rec.kind](
                    self.balances, self.meta, self.ring, self._ring_at,
                    dev_solo[i], rec.n, jnp.uint64(rec.ts_base),
                )
                rec.ring_at = self._ring_at
                self._ring_at = (self._ring_at + 1) % _RING
                continue
            big, ns, tsb = dev_bufs[dk.PK_SPEC[urecs[0].kind]]
            scan_fn = dk.scan_win_kernels[urecs[0].kind][len(urecs)]
            self.balances, self.ring = scan_fn(
                self.balances, self.meta, self.ring, self._ring_at,
                big, offsets[i], ns, tsb,
            )
            for g, rec in enumerate(urecs):
                rec.ring_at = (self._ring_at + g) % _RING
            self._ring_at = (self._ring_at + len(urecs)) % _RING
        self.stat_t_dispatch += _time.perf_counter() - t1

    def _dispatch(self, rec: _InFlight) -> None:
        """Immediate single-batch dispatch (fallback re-dispatch path)."""
        kernel = _KERNELS[rec.kind]
        self.balances, self.ring = kernel(
            self.balances, self.meta, self.ring, self._ring_at,
            jnp.asarray(rec.pk), rec.n, jnp.uint64(rec.ts_base),
        )
        rec.ring_at = self._ring_at
        self._ring_at = (self._ring_at + 1) % _RING

    # ------------------------------------------------------------------
    # Hazard probe: does any probe id match an in-flight batch's ids?

    def inflight_ids_hit(self, keys: np.ndarray) -> bool:
        """keys: u128-packed (V16) id probes, any order."""
        stream = self._launched + self._pending
        if not stream or len(keys) == 0:
            return False
        keys = np.sort(keys)
        # V16 keys order numerically by their bytes; scalar compares go
        # through .tobytes() (numpy void scalars lack ufunc ordering).
        lo = keys[0].tobytes()
        hi = keys[-1].tobytes()
        for rec in stream:
            ik = rec.id_keys
            if ik is None or len(ik) == 0:
                continue
            if hi < ik[0].tobytes() or lo > ik[-1].tobytes():
                continue
            pos = np.searchsorted(ik, keys)
            pos = np.minimum(pos, len(ik) - 1)
            if (ik[pos] == keys).any():
                return True
        return False

    def has_inflight(self) -> bool:
        return bool(self._launched or self._pending)

    # ------------------------------------------------------------------
    # Rotation + materialization.

    def _fetch_ring(self, recs):
        """Ring snapshot + lookup-row pulls for a launched window; the
        fetch drains the device stream (idle on return)."""
        ring_np = None
        t0 = _time.perf_counter()
        if any(r.kind in _SEMANTIC_KINDS for r in recs):
            self.stat_fetches += 1
            ring_np = np.asarray(self.ring)  # THE burst fetch
        for rec in recs:
            if rec.kind == "lookup" and rec.handle is not None:
                rec.rows = np.asarray(rec.handle)
                rec.handle = None
        self.stat_t_fetch += _time.perf_counter() - t0
        return ring_np

    def _window_clean(self, recs, ring_np) -> bool:
        for rec in recs:
            if rec.kind not in _SEMANTIC_KINDS:
                continue
            s = ring_np[rec.ring_at]
            if int(s[1]) & (dk.FLAG_OVERFLOW | dk.FLAG_CAP | dk.FLAG_PRECOND):
                return False
        return True

    def _resolve_clean(self, recs, ring_np) -> None:
        t0 = _time.perf_counter()
        for rec in recs:
            if rec.kind == "meta":
                continue
            if rec.kind == "lookup":
                rec.future.resolve(rec.finish(rec.rows))
                continue
            s = dk.unpack_summary(ring_np[rec.ring_at])
            self.stat_semantic_events += rec.n
            rec.future.resolve(rec.finish(s))
        self.stat_t_finish += _time.perf_counter() - t0

    def _rotate(self) -> None:
        """Window boundary: fetch the launched window's ring, and —
        when it is clean — launch the pending window while the host
        still holds the fetched results, then finish the old window's
        bookkeeping overlapped with the new window's device work."""
        prev, self._launched = self._launched, []
        ring_np = self._fetch_ring(prev) if prev else None
        if prev and (ring_np is None or self._window_clean(prev, ring_np)):
            nxt, self._pending = self._pending, []
            self._pending_semantic = 0
            self._launch(nxt)
            self._launched = nxt
            self._resolve_clean(prev, ring_np)
            return
        if prev:
            # Fallback in the window: serial exact recovery first.
            self._resolve_recovery(prev, ring_np)
        nxt, self._pending = self._pending, []
        self._pending_semantic = 0
        self._launch(nxt)
        self._launched = nxt

    def _resolve_recovery(self, covered, ring_np) -> None:
        """Exact recovery: resolve in order until the flagged batch,
        host re-execute it (mirror becomes current), rebuild the device
        table, re-dispatch everything after it, repeat until done."""
        while covered:
            if ring_np is None:
                ring_np = self._fetch_ring(covered)
            failed_at = None
            for i, rec in enumerate(covered):
                if rec.kind == "meta":
                    continue
                if rec.kind == "lookup":
                    rec.future.resolve(rec.finish(rec.rows))
                    continue
                s = dk.unpack_summary(ring_np[rec.ring_at])
                if s["overflow"] or s["cap_exceeded"] or s["precond"]:
                    failed_at = i
                    self.stat_fallback_batches += 1
                    rec.future.resolve(rec.fallback())
                    break
                self.stat_semantic_events += rec.n
                rec.future.resolve(rec.finish(s))
            if failed_at is None:
                return
            # Mirror reflects every batch up to and including the
            # fallback; rebuild the device table from it and replay
            # the rest in order.
            self._upload_from_mirror()
            covered = covered[failed_at + 1 :]
            for rec in covered:
                if rec.kind == "meta":
                    slots, flags, ledger = rec.meta_args
                    self.meta = dk.meta_update(
                        self.meta, jnp.asarray(slots), jnp.asarray(flags),
                        jnp.asarray(ledger),
                    )
                elif rec.kind == "lookup":
                    rec.handle = self._gather(rec.slots)
                else:
                    self._dispatch(rec)
            ring_np = None

    def _upload_from_mirror(self) -> None:
        table = np.zeros((self.capacity, 8), np.uint64)
        n = min(len(self.mirror.lo), self.capacity)
        table[:n, 0::2] = self.mirror.lo[:n]
        table[:n, 1::2] = self.mirror.hi[:n]
        self.balances = self._place(jnp.asarray(table))

    def drain(self) -> None:
        while self._launched or self._pending:
            self._rotate()

    # ------------------------------------------------------------------
    # Write-behind lane (host exact path) — kernel_fast.DeviceTable API.

    def enqueue(self, slots, cols, add_lo, add_hi) -> None:
        if self._suppress_enqueue or len(slots) == 0:
            return
        # Exact-path deltas only arrive after a drain (the host path
        # drains before running), so they can never overtake queued
        # semantic batches.
        assert self._pending_semantic == 0 and not self._launched, (
            "write-behind enqueue with in-flight semantic batches"
        )
        self._q.append(
            (
                np.asarray(slots, np.int64),
                np.asarray(cols, np.int64),
                np.asarray(add_lo, np.uint64),
                np.asarray(add_hi, np.uint64),
            )
        )
        self._queued += len(slots)

    def flush(self) -> None:
        if not self._queued:
            return
        from tigerbeetle_tpu.state_machine.mirror import compact_deltas

        slots = np.concatenate([e[0] for e in self._q])
        cols = np.concatenate([e[1] for e in self._q])
        a_lo = np.concatenate([e[2] for e in self._q])
        a_hi = np.concatenate([e[3] for e in self._q])
        self._q.clear()
        self._queued = 0
        chunk = (1 << 21) - 1
        if len(slots) > chunk:
            parts = [
                compact_deltas(
                    slots[i : i + chunk], cols[i : i + chunk],
                    a_lo[i : i + chunk], a_hi[i : i + chunk],
                )
                for i in range(0, len(slots), chunk)
            ]
            slots = np.concatenate([p[0] for p in parts])
            cols = np.concatenate([p[1] for p in parts])
            a_lo = np.concatenate([p[2] for p in parts])
            a_hi = np.concatenate([p[3] for p in parts])
        u_slot, u_col, d_lo, d_hi, _ = compact_deltas(slots, cols, a_lo, a_hi)
        at = 0
        CH = 32_768
        while at < len(u_slot):
            take = min(len(u_slot) - at, CH)
            packed = np.empty((4, CH), np.uint64)
            packed[0, :take] = u_slot[at : at + take].astype(np.uint64)
            packed[0, take:] = self.capacity + np.arange(
                CH - take, dtype=np.uint64
            )
            packed[1, :take] = u_col[at : at + take].astype(np.uint64)
            packed[1, take:] = 0
            packed[2, :take] = d_lo[at : at + take]
            packed[2, take:] = 0
            packed[3, :take] = d_hi[at : at + take]
            packed[3, take:] = 0
            self.balances = dk.apply_deltas(self.balances, jnp.asarray(packed))
            at += take
        # Flushed deltas must land before any later queued meta/lookup
        # records are dispatched — but those only dispatch at the next
        # launch, which follows this flush in program order.

    def read(self):
        """Drain barrier + device handle (DeviceTable API compat)."""
        self.drain()
        self.flush()
        return self.balances

    def checksum(self) -> np.ndarray:
        """Device-side table digest (drained + flushed first)."""
        return np.asarray(dk.checksum(self.read()))
