"""Device-authoritative execution pipeline for create_transfers.

Owns the authoritative HBM balance table + account-meta table and a
stream of semantic-kernel dispatches (device_kernels.py).  The host
submits packed batches and gets back *reply futures*; result codes are
computed on device, ride the failure-sparse summary ring, and
materialize when the host fetches the ring — once per burst, because
the tunneled link's downlink costs ~105 ms per fetch regardless of
size (experiments/README.md).

Execution model
---------------
- ``submit(kind, pk, n, ts_base, finish, fallback)`` dispatches one
  kernel against the current table/ring and appends an in-flight
  record.  Dispatches are asynchronous; the device executes them in
  stream order, so every kernel sees exactly the committed-so-far
  state (serial consistency without host round trips).
- When the in-flight window reaches ``fetch_every`` (or on
  ``drain()``), the host fetches the ring snapshot ONCE and
  materializes every covered batch in order: the ``finish`` callback
  turns device codes into bookkeeping + reply bytes.
- A batch whose summary carries a fallback flag (balance overflow in
  play, failure-cap exceeded, precondition violated) triggers exact
  recovery: the host re-executes that batch through the host engine
  (``fallback`` callback, which updates the mirror), re-uploads the
  corrected table, and re-dispatches every later in-flight batch.
  Replies stay exact for ANY input; the flags only cost latency.

The pipeline also carries the write-behind lane the host exact path
uses (``enqueue``/``flush``, same contract as kernel_fast.DeviceTable)
so host-resolved batches keep the device table current in stream
order, and a device-side ``lookup`` used to serve lookup_accounts
balances from the authoritative table (not the host mirror).
"""

from __future__ import annotations

import os

import numpy as np

import jax
import jax.numpy as jnp

from tigerbeetle_tpu.state_machine import device_kernels as dk

_FETCH_EVERY = int(os.environ.get("TB_DEV_FETCH", "96"))
_RING = int(os.environ.get("TB_DEV_RING", "256"))
_STAGE = int(os.environ.get("TB_DEV_STAGE", "16"))


class ReplyFuture:
    """Reply bytes that materialize at the batch's ring fetch."""

    __slots__ = ("_value", "_engine")

    def __init__(self, engine=None, value: bytes | None = None) -> None:
        self._value = value
        self._engine = engine

    def done(self) -> bool:
        return self._value is not None

    def resolve(self, value: bytes) -> None:
        self._value = value

    def result(self) -> bytes:
        if self._value is None:
            self._engine.drain()
            assert self._value is not None, "drain did not materialize reply"
        return self._value


class _InFlight:
    """One stream entry: a dispatched semantic batch or a lookup
    gather, in submission order (ordering matters for exact fallback
    recovery)."""

    __slots__ = (
        "kind", "pk", "n", "ts_base", "finish", "fallback", "future",
        "ring_at", "id_keys", "handle", "slots",
    )

    def __init__(self, kind, future, finish, *, pk=None, n=0, ts_base=0,
                 fallback=None, ring_at=-1, id_keys=None, handle=None,
                 slots=None):
        self.kind = kind
        self.pk = pk
        self.n = n
        self.ts_base = ts_base
        self.finish = finish
        self.fallback = fallback
        self.future = future
        self.ring_at = ring_at
        self.id_keys = id_keys  # sorted u128-packed ids (hazard probes)
        self.handle = handle    # lookup gather output handle
        self.slots = slots      # lookup slots (for re-gather)


class DeviceEngine:
    """Authoritative device tables + semantic dispatch pipeline."""

    def __init__(self, capacity: int, mirror) -> None:
        self.capacity = capacity
        self.mirror = mirror  # host bookkeeping copy (recovery + parity)
        # Multi-device: the authoritative tables shard ROW-WISE across
        # every visible device (NamedSharding over a 1-D "shard" mesh);
        # the semantic kernels then run SPMD with XLA-inserted
        # collectives — the same dispatch code path single-chip uses
        # (exercised by __graft_entry__.dryrun_multichip on a virtual
        # CPU mesh).
        self.sharding = None
        devices = jax.devices()
        if len(devices) > 1 and capacity % len(devices) == 0:
            from jax.sharding import Mesh, NamedSharding
            from jax.sharding import PartitionSpec as P

            mesh = Mesh(np.array(devices), ("shard",))
            self.sharding = NamedSharding(mesh, P("shard", None))
        self.balances = self._place(jnp.zeros((capacity, 8), jnp.uint64))
        self.meta = self._place(jnp.zeros((capacity, 2), jnp.uint32))
        self._meta_host = np.zeros((capacity, 2), np.uint32)
        self.ring = jnp.zeros((_RING, dk.SUMMARY_WORDS), jnp.uint64)
        self._ring_at = 0
        self._stream: list[_InFlight] = []
        self._n_batches = 0
        # Staging: batches accumulate host-side and ship in ONE
        # superbatch h2d per _STAGE batches (in-stream transfers cost
        # ~25 ms each on this link; amortize them).
        self._stage: list[_InFlight] = []
        # Write-behind lane for host-resolved batches (exact path).
        self._q: list[tuple] = []
        self._queued = 0
        self._suppress_enqueue = False
        # Stats.
        self.stat_semantic_events = 0
        self.stat_fallback_batches = 0
        self.stat_fetches = 0

    def _place(self, table):
        if self.sharding is None:
            return table
        return jax.device_put(table, self.sharding)

    # ------------------------------------------------------------------
    # Account meta maintenance (create_accounts path).

    def add_accounts(self, slots, acct_flags, acct_ledger) -> None:
        slots = np.asarray(slots, np.int64)
        self._meta_host[slots, 0] = acct_flags
        self._meta_host[slots, 1] = acct_ledger
        self.meta = dk.meta_update(
            self.meta,
            jnp.asarray(slots),
            jnp.asarray(np.asarray(acct_flags, np.uint32)),
            jnp.asarray(np.asarray(acct_ledger, np.uint32)),
        )

    def remove_accounts(self, slots) -> None:
        """Linked create_accounts rollback support."""
        slots = np.asarray(slots, np.int64)
        self._meta_host[slots] = 0
        z = np.zeros(len(slots), np.uint32)
        self.meta = dk.meta_update(
            self.meta, jnp.asarray(slots), jnp.asarray(z), jnp.asarray(z)
        )

    def grow(self, capacity: int) -> None:
        if capacity <= self.capacity:
            return
        self.drain()
        self.flush()
        was_sharded = self.sharding is not None
        if was_sharded and capacity % self.sharding.mesh.devices.size != 0:
            self.sharding = None  # re-place replicated from here on
        extra = capacity - self.capacity

        def widen(table, width, dtype):
            # Previously-sharded tables come back through the host (row
            # boundaries move between devices on grow, and a dropped
            # sharding must not leave a committed sharded base behind).
            base = jax.device_get(table) if was_sharded else table
            return self._place(
                jnp.concatenate([base, jnp.zeros((extra, width), dtype)])
            )

        self.balances = widen(self.balances, 8, jnp.uint64)
        self.meta = widen(self.meta, 2, jnp.uint32)
        mh = np.zeros((capacity, 2), np.uint32)
        mh[: self.capacity] = self._meta_host
        self._meta_host = mh
        self.capacity = capacity

    # ------------------------------------------------------------------
    # Semantic dispatch.

    def submit(self, kind, pk, n, ts_base, finish, fallback,
               id_keys=None) -> ReplyFuture:
        """Dispatch one semantic batch; returns its reply future.

        `finish(summary) -> bytes` runs at materialization (device codes
        -> bookkeeping + reply).  `fallback() -> bytes` re-executes the
        batch exactly on the host engine against the mirror.
        """
        self.flush()  # earlier exact-path deltas must precede us
        fut = ReplyFuture(self)
        rec = _InFlight(
            kind, fut, finish, pk=pk, n=n, ts_base=ts_base,
            fallback=fallback, id_keys=id_keys,
        )
        self._stage.append(rec)
        self._stream.append(rec)
        self._n_batches += 1
        if len(self._stage) >= _STAGE:
            self._flush_stage()
        if self._n_batches >= _FETCH_EVERY:
            self._materialize()
        return fut

    def _flush_stage(self) -> None:
        """Ship the staged batches' inputs in one superbatch h2d per
        column layout, then dispatch their kernels in stream order."""
        stage, self._stage = self._stage, []
        if not stage:
            return
        # One superbatch transfer per column layout; dispatch then
        # follows STAGE order (cross-layout batches may depend on each
        # other's balance effects).
        supers = {}
        slot_of = {}
        for ncols in (dk.N_COLS, dk.N_COLS_TP):
            group = [r for r in stage if r.pk.shape[1] == ncols]
            if not group:
                continue
            buf = np.zeros((_STAGE * dk.B, ncols), np.uint64)
            for g, rec in enumerate(group):
                buf[g * dk.B : (g + 1) * dk.B] = rec.pk
                slot_of[id(rec)] = g
            supers[ncols] = jax.device_put(buf)
        for rec in stage:
            kernel = {
                "orderfree": dk.orderfree_staged,
                "orderfree_lo": dk.orderfree_lo_staged,
                "linked": dk.linked_staged,
                "two_phase": dk.two_phase_staged,
                "two_phase_lo": dk.two_phase_lo_staged,
            }[rec.kind]
            self.balances, self.ring = kernel(
                self.balances, self.meta, self.ring, self._ring_at,
                supers[rec.pk.shape[1]], slot_of[id(rec)], rec.n,
                jnp.uint64(rec.ts_base),
            )
            rec.ring_at = self._ring_at
            self._ring_at = (self._ring_at + 1) % _RING

    def _dispatch(self, rec: _InFlight) -> None:
        """Immediate single-batch dispatch (fallback re-dispatch path)."""
        kernel = {
            "orderfree": dk.orderfree,
            "orderfree_lo": dk.orderfree_lo,
            "linked": dk.linked,
            "two_phase": dk.two_phase,
            "two_phase_lo": dk.two_phase_lo,
        }[rec.kind]
        self.balances, self.ring = kernel(
            self.balances, self.meta, self.ring, self._ring_at,
            jnp.asarray(rec.pk), rec.n, jnp.uint64(rec.ts_base),
        )
        rec.ring_at = self._ring_at
        self._ring_at = (self._ring_at + 1) % _RING

    def lookup(self, slots, finish) -> ReplyFuture:
        """Device-side balance gather for lookup_accounts: rides the
        dispatch stream, so it sees every in-flight batch's effects.
        `finish(rows)` builds the reply from the fetched (k, 8) rows
        at materialization."""
        self._flush_stage()  # gather must sequence after staged batches
        fut = ReplyFuture(self)
        slots = np.asarray(slots, np.int64)
        rec = _InFlight("lookup", fut, finish, slots=slots)
        rec.handle = self._gather(slots)
        self._stream.append(rec)
        return fut

    def _gather(self, slots):
        pad = ((len(slots) + 255) & ~255) or 256
        sl = np.full(pad, -1, np.int64)
        sl[: len(slots)] = slots
        return dk.lookup(self.balances, jnp.asarray(sl))

    # ------------------------------------------------------------------
    # Hazard probe: does any probe id match an in-flight batch's ids?

    def inflight_ids_hit(self, keys: np.ndarray) -> bool:
        """keys: u128-packed (V16) id probes, any order."""
        if not self._stream or len(keys) == 0:
            return False
        keys = np.sort(keys)
        # V16 keys order numerically by their bytes; scalar compares go
        # through .tobytes() (numpy void scalars lack ufunc ordering).
        lo = keys[0].tobytes()
        hi = keys[-1].tobytes()
        for rec in self._stream:
            ik = rec.id_keys
            if ik is None or len(ik) == 0:
                continue
            if hi < ik[0].tobytes() or lo > ik[-1].tobytes():
                continue
            pos = np.searchsorted(ik, keys)
            pos = np.minimum(pos, len(ik) - 1)
            if (ik[pos] == keys).any():
                return True
        return False

    def has_inflight(self) -> bool:
        return bool(self._stream)

    # ------------------------------------------------------------------
    # Materialization.

    def _materialize(self) -> None:
        """Fetch the ring once; resolve the stream in order.

        On a fallback flag: the host re-executes that batch exactly
        (updating the mirror), the table is rebuilt from the mirror,
        and the REST of the stream — later batches and lookup gathers,
        whose device snapshots included wrong state — is re-dispatched
        in order against the corrected table.  Repeats until the
        stream drains."""
        while self._stream:
            self._flush_stage()
            covered = self._stream
            self._stream = []
            self._n_batches = 0
            if any(rec.kind != "lookup" for rec in covered):
                self.stat_fetches += 1
                ring_np = np.asarray(self.ring)  # THE burst fetch
            failed_at = None
            for i, rec in enumerate(covered):
                if rec.kind == "lookup":
                    rec.future.resolve(rec.finish(np.asarray(rec.handle)))
                    continue
                s = dk.unpack_summary(ring_np[rec.ring_at])
                if s["overflow"] or s["cap_exceeded"] or s["precond"]:
                    failed_at = i
                    self.stat_fallback_batches += 1
                    rec.future.resolve(rec.fallback())
                    break
                self.stat_semantic_events += rec.n
                rec.future.resolve(rec.finish(s))
            if failed_at is None:
                continue
            # Recovery: mirror reflects every batch up to and including
            # the fallback; rebuild the device table from it and replay
            # the rest of the stream in order.
            self._upload_from_mirror()
            for rec in covered[failed_at + 1 :]:
                if rec.kind == "lookup":
                    rec.handle = self._gather(rec.slots)
                else:
                    self._dispatch(rec)
                    self._n_batches += 1
                self._stream.append(rec)

    def _upload_from_mirror(self) -> None:
        table = np.zeros((self.capacity, 8), np.uint64)
        n = min(len(self.mirror.lo), self.capacity)
        table[:n, 0::2] = self.mirror.lo[:n]
        table[:n, 1::2] = self.mirror.hi[:n]
        self.balances = self._place(jnp.asarray(table))

    def drain(self) -> None:
        self._materialize()

    # ------------------------------------------------------------------
    # Write-behind lane (host exact path) — kernel_fast.DeviceTable API.

    def enqueue(self, slots, cols, add_lo, add_hi) -> None:
        if self._suppress_enqueue or len(slots) == 0:
            return
        # Exact-path deltas only arrive after a drain (the host path
        # drains before running), so they can never overtake staged
        # semantic batches.
        assert not self._stage, "write-behind enqueue with staged batches"
        self._q.append(
            (
                np.asarray(slots, np.int64),
                np.asarray(cols, np.int64),
                np.asarray(add_lo, np.uint64),
                np.asarray(add_hi, np.uint64),
            )
        )
        self._queued += len(slots)

    def flush(self) -> None:
        if not self._queued:
            return
        from tigerbeetle_tpu.state_machine.mirror import compact_deltas

        slots = np.concatenate([e[0] for e in self._q])
        cols = np.concatenate([e[1] for e in self._q])
        a_lo = np.concatenate([e[2] for e in self._q])
        a_hi = np.concatenate([e[3] for e in self._q])
        self._q.clear()
        self._queued = 0
        chunk = (1 << 21) - 1
        if len(slots) > chunk:
            parts = [
                compact_deltas(
                    slots[i : i + chunk], cols[i : i + chunk],
                    a_lo[i : i + chunk], a_hi[i : i + chunk],
                )
                for i in range(0, len(slots), chunk)
            ]
            slots = np.concatenate([p[0] for p in parts])
            cols = np.concatenate([p[1] for p in parts])
            a_lo = np.concatenate([p[2] for p in parts])
            a_hi = np.concatenate([p[3] for p in parts])
        u_slot, u_col, d_lo, d_hi, _ = compact_deltas(slots, cols, a_lo, a_hi)
        at = 0
        CH = 32_768
        while at < len(u_slot):
            take = min(len(u_slot) - at, CH)
            packed = np.empty((4, CH), np.uint64)
            packed[0, :take] = u_slot[at : at + take].astype(np.uint64)
            packed[0, take:] = self.capacity + np.arange(
                CH - take, dtype=np.uint64
            )
            packed[1, :take] = u_col[at : at + take].astype(np.uint64)
            packed[1, take:] = 0
            packed[2, :take] = d_lo[at : at + take]
            packed[2, take:] = 0
            packed[3, :take] = d_hi[at : at + take]
            packed[3, take:] = 0
            self.balances = dk.apply_deltas(self.balances, jnp.asarray(packed))
            at += take

    def read(self):
        """Flush barrier + device handle (DeviceTable API compat)."""
        self.drain()
        self.flush()
        return self.balances

    def checksum(self) -> np.ndarray:
        """Device-side table digest (drained + flushed first)."""
        return np.asarray(dk.checksum(self.read()))
