"""Device-authoritative execution pipeline for create_transfers.

Owns the authoritative HBM balance table + account-meta table and a
stream of semantic-kernel dispatches (device_kernels.py).  The host
submits packed batches and gets back *reply futures*; result codes are
computed on device, ride the failure-sparse summary ring, and
materialize once per execution window.

Execution model (r5: phase-separated windows)
---------------------------------------------
The tunneled link's physics (experiments/README.md) dictate the shape:
a d2h fetch costs ~105 ms regardless of size, and ANY h2d issued while
kernels are in flight stalls the stream for tens of milliseconds —
measured end-to-end, interleaving per-G-batch uploads with dispatches
runs 4x slower than the kernels themselves (experiments/stage_sweep.py).
So the engine never touches the link while the device is busy:

  submit()  appends the packed batch to a host-side window; NOTHING
            is dispatched until the window fills (TB_DEV_WINDOW).
  rotate    at the window boundary: (1) fetch the summary ring for the
            PREVIOUS window — the fetch drains the stream, leaving the
            device idle; (2) while idle, upload the new window's
            superbatches in one h2d per column layout and pull any
            lookup-gather handles; (3) dispatch every kernel of the new
            window back-to-back — zero in-stream transfers; (4) only
            then run the previous window's host bookkeeping (finish
            callbacks), overlapped with the device crunching the new
            window.

A batch whose summary carries a fallback flag (balance overflow in
play, failure-cap exceeded, precondition violated) triggers exact
recovery BEFORE the next window launches: the host re-executes that
batch through the host engine (``fallback`` callback, which updates
the mirror), re-uploads the corrected table, and re-dispatches every
later in-flight record.  Replies stay exact for ANY input; the flags
only cost latency.

The pipeline also carries the write-behind lane the host exact path
uses (``enqueue``/``flush``, same contract as kernel_fast.DeviceTable)
so host-resolved batches keep the device table current in stream
order, and a device-side ``lookup`` used to serve lookup_accounts
balances from the authoritative table (not the host mirror).
"""

from __future__ import annotations

import os as _os
import time as _time

import numpy as np

import jax
import jax.numpy as jnp

from tigerbeetle_tpu import envcheck
from tigerbeetle_tpu.obs import stat_property as obs_stat_property
from tigerbeetle_tpu.state_machine import device_kernels as dk
from tigerbeetle_tpu.types import EngineState
from tigerbeetle_tpu.utils import tracer as tracer_mod

_WINDOW = envcheck.env_int("TB_DEV_WINDOW", 96, minimum=1)
_RING = envcheck.env_int("TB_DEV_RING", 256, minimum=2)


def _validate_window_ring(window: int, ring: int) -> None:
    if 2 * window > ring:
        raise envcheck.EnvVarError(
            f"TB_DEV_WINDOW={window} / TB_DEV_RING={ring} invalid: the "
            "summary ring must hold two windows (2*TB_DEV_WINDOW <= "
            "TB_DEV_RING)"
        )


_validate_window_ring(_WINDOW, _RING)

# Link-robustness knobs: bounded retry with exponential backoff on
# every link crossing, a health-probe cadence for re-promotion out of
# degraded mode, and a checksum-scrub cadence during healthy operation
# (0 disables the scrub).  All read at call time so tests can tighten
# them per engine.
_RETRIES = envcheck.env_int("TB_DEV_RETRIES", 3, minimum=0)
_BACKOFF_MS = envcheck.env_float("TB_DEV_BACKOFF_MS", 5.0, minimum=0.0)
_BACKOFF_CAP_MS = envcheck.env_float(
    "TB_DEV_BACKOFF_CAP_MS", 200.0, minimum=0.0
)
_PROBE_EVERY = envcheck.env_int("TB_DEV_PROBE_EVERY", 8, minimum=1)
# r15: the healthy-mode scrub is a 16-byte incremental-digest compare
# (state_machine/commitment.py) instead of a full-table digest pass,
# so the default cadence drops from 256 to every TB_DEV_PROBE_EVERY
# fetches (the full-fetch compare survives only as the divergence-
# localization fallback).  On the tunneled link each scrub still pays
# one d2h crossing's latency — dev.scrub.cheap_us/fallback_us record
# the real split for the next chip session to retune against.
# The tight default only makes sense for the CHEAP scrub: an engine
# with the commitment disabled (TB_STATE_COMMIT=0) still pays the
# legacy full-digest compare per scrub, so it keeps the legacy 256
# unless the operator set the cadence explicitly (per-engine choice
# in __init__).
_SCRUB_EVERY_SET = envcheck.env_is_set("TB_DEV_SCRUB_EVERY")
_SCRUB_EVERY = envcheck.env_int("TB_DEV_SCRUB_EVERY", _PROBE_EVERY, minimum=0)
_SCRUB_EVERY_LEGACY = 256
# Maximum deterministic per-engine offset applied to the scrub cadence
# so every engine's TB_DEV_SCRUB_EVERY-th fetch doesn't land on the
# same ring rotation (each scrub costs a ~105 ms checksum fetch on the
# tunneled link; ROADMAP "Scrub/probe cadence tuning").  -1 = auto
# (an eighth of the cadence).
_SCRUB_JITTER = envcheck.env_int("TB_DEV_SCRUB_JITTER", -1, minimum=-1)


def _validate_scrub_jitter(every: int, jitter: int) -> None:
    if every and jitter >= every:
        raise envcheck.EnvVarError(
            f"TB_DEV_SCRUB_JITTER={jitter} / TB_DEV_SCRUB_EVERY={every} "
            "invalid: the jitter offset must stay below the scrub "
            "cadence (TB_DEV_SCRUB_JITTER < TB_DEV_SCRUB_EVERY)"
        )


_validate_scrub_jitter(_SCRUB_EVERY, _SCRUB_JITTER)

# Per-process engine construction ordinal: the default scrub-jitter
# seed mixes it in so same-capacity engines sharing the link (the
# normal fleet configuration) still derive DIFFERENT offsets —
# deterministic for a fixed construction order, which is what replay
# needs.
_ENGINE_SEQ = 0


def _scrub_jitter_cap(every: int, jitter: int) -> int:
    """Effective jitter bound: the explicit knob, or auto = every//8."""
    if jitter >= 0:
        return jitter
    return every // 8 if every else 0


class LinkError(RuntimeError):
    """A device-link crossing failed (base for injected faults)."""


class TransientLinkError(LinkError):
    """Retryable: the crossing may succeed if reissued."""


class FatalLinkError(LinkError):
    """Not retryable: the link (or device state behind it) is gone."""


class DeviceLostError(RuntimeError):
    """The device link is lost: a crossing failed fatally or exhausted
    its retry budget.  Raised to callers only when no exact host
    answer exists (a stranded future after ``close()``); everywhere
    else the engine catches it and demotes to the host path."""

    def __init__(self, stage: str, cause: object = None) -> None:
        self.stage = stage
        self.cause = cause
        detail = f": {cause}" if cause is not None else ""
        super().__init__(f"device lost at {stage}{detail}")


# Link-error taxonomy: message markers -> classification, FIRST MATCH
# WINS in declaration order.  JAX/PJRT surface gRPC-style status names
# in their messages; the transient rows are statuses a reissued
# crossing can outlive (backpressure, tunnel flaps, deadline races),
# the fatal rows are states no retry fixes (bad program, lost buffers,
# corrupt device state).  The table is DECLARATIVE so future markers
# harvested from real tunnel flakes are added as one measured row —
# tests/test_device_engine.py asserts the classification of every
# entry (ROADMAP "Real-link error taxonomy").
LINK_ERROR_MARKERS = (
    ("RESOURCE_EXHAUSTED", "transient"),
    ("UNAVAILABLE", "transient"),
    ("DEADLINE_EXCEEDED", "transient"),
    ("ABORTED", "transient"),
    ("CANCELLED", "transient"),
    ("temporarily", "transient"),
    ("INVALID_ARGUMENT", "fatal"),
    ("FAILED_PRECONDITION", "fatal"),
    ("NOT_FOUND", "fatal"),
    ("UNIMPLEMENTED", "fatal"),
    ("INTERNAL", "fatal"),
    ("DATA_LOSS", "fatal"),
)


def classify_link_error(exc: BaseException) -> str:
    """-> "transient" (retry may succeed) or "fatal" (demote)."""
    if isinstance(exc, TransientLinkError):
        return "transient"
    if isinstance(exc, (FatalLinkError, DeviceLostError)):
        return "fatal"
    msg = str(exc)
    for marker, kind in LINK_ERROR_MARKERS:
        if marker in msg:
            return kind
    return "fatal"


class DeviceLink:
    """Every host<->device crossing the engine makes, behind one seam.

    The engine never calls jax transfer/dispatch APIs directly; it
    goes through this object so the chaos harness (testing/chaos.py)
    can interpose a seeded fault-injecting shim, and so retry/
    classification lives in exactly one place (DeviceEngine._retry).
    Stages: "h2d" (uploads), "dispatch" (kernel launches), "fetch"
    (d2h reads), "probe" (health check).
    """

    def device_put(self, array, sharding=None):
        if sharding is not None:
            return jax.device_put(array, sharding)
        return jax.device_put(array)

    def block_until_ready(self, arrays):
        return jax.block_until_ready(arrays)

    def fetch(self, array) -> np.ndarray:
        return np.asarray(array)

    def dispatch(self, fn, *args):
        return fn(*args)

    def probe(self) -> None:
        """Tiny h2d + d2h round trip; raises if the link is dead."""
        echo = self.fetch(self.device_put(np.arange(4, dtype=np.uint64)))
        if int(echo[3]) != 3:
            raise FatalLinkError("probe round trip corrupted")


class ReplyFuture:
    """Reply bytes that materialize at the batch's window rotation.

    A future always terminates: it resolves with exact reply bytes
    (device summary, or host replay after a demotion) or fails with a
    typed error — ``result()`` never strands the caller in an assert
    when the link dies mid-window.
    """

    __slots__ = ("_value", "_engine", "_exc")

    def __init__(self, engine=None, value: bytes | None = None) -> None:
        self._value = value
        self._engine = engine
        self._exc: BaseException | None = None

    def done(self) -> bool:
        return self._value is not None or self._exc is not None

    def resolve(self, value: bytes) -> None:
        self._value = value

    def fail(self, exc: BaseException) -> None:
        self._exc = exc

    def result(self) -> bytes:
        if self._value is None and self._exc is None and (
            self._engine is not None
        ):
            self._engine.drain()
        if self._exc is not None:
            raise self._exc
        if self._value is None:
            raise DeviceLostError(
                "drain", "reply never materialized and no host replay ran"
            )
        return self._value


class _InFlight:
    """One stream entry, in submission order (ordering matters for
    exact fallback recovery): a semantic batch, a wave-dispatched
    batch, a lookup gather, or an account-meta update."""

    __slots__ = (
        "kind", "pk", "n", "ts_base", "finish", "fallback", "future",
        "ring_at", "id_keys", "handle", "slots", "rows", "meta_args",
        "wave_args", "bound", "touched", "hot_slots",
    )

    def __init__(self, kind, future, finish, *, pk=None, n=0, ts_base=0,
                 fallback=None, ring_at=-1, id_keys=None, handle=None,
                 slots=None, meta_args=None, wave_args=None, bound=0,
                 hot_slots=None):
        self.kind = kind
        self.pk = pk
        self.n = n
        self.ts_base = ts_base
        self.finish = finish
        self.fallback = fallback
        self.future = future
        self.ring_at = ring_at
        self.id_keys = id_keys  # sorted u128-packed ids (hazard probes)
        self.handle = handle    # lookup gather / wave packed-output handle
        self.slots = slots      # lookup slots, LOGICAL (host replay reads
                                # them against the mirror)
        self.hot_slots = hot_slots  # tiered device translation of slots
        self.rows = None        # lookup rows / wave outputs fetched at rotation
        self.meta_args = meta_args  # (slots, flags, ledger) for "meta"
        # (waves.PackedColumns, plan): the compact columnar record —
        # NOT the (B,)-padded event dict — rebuilt at launch.
        self.wave_args = wave_args
        # Balance rows this record's execution can modify (wave
        # records fill it at launch) — the incremental-commitment
        # update's input (commitment.py).
        self.touched = None
        # Host-integer bound on the balance additions this record can
        # still contribute (wave admission's in-flight term); released
        # when the record's bookkeeping lands on the mirror.
        self.bound = bound


# Speculative-execution forensics (ISSUE r18): counters named
# dev_wave.spec.* so the owning state machine's registry (and the
# stats-op scrape / flight postmortem built from it) shows them next
# to the dev_wave.* routing stats.  Standalone engines lazily build
# them on their private registry under the same names; the owning
# machine binds machine-registry handles right after construction.
_SPEC_COUNTER_NAMES = (
    "attempts",        # speculative launches dispatched
    "hits",            # batches validated conflict-free (1 device step)
    "plan_skipped",    # partitioner runs avoided (== hits by design)
    "residue_events",  # events replayed through a residue plan
    "steps",           # device-step equivalents incl. residue plans
    "validation_s",    # wall time: speculative dispatch + flags fetch
    "residue_plan_s",  # wall time: plan_residue on misses
)


def make_spec_stats(registry) -> dict:
    st = {
        name: registry.counter("dev_wave.spec." + name)
        for name in _SPEC_COUNTER_NAMES
    }
    st["validation_us"] = registry.histogram("dev_wave.spec.validation_us")
    return st


def make_tier_stats(registry) -> dict:
    """dev_tier.* handles for the hot/cold tiering (hot_tier.py) —
    same owning-machine-binds-handles contract as make_spec_stats."""
    st = {
        name: registry.counter("dev_tier." + name)
        for name in ("hit", "miss", "evict", "prefetch", "prefetch_stall_us")
    }
    st["prefetch_us"] = registry.histogram("dev_tier.prefetch_us")
    return st


_KERNELS = {
    "orderfree": dk.orderfree,
    "orderfree_lo": dk.orderfree_lo,
    "orderfree_tight": dk.orderfree_tight,
    "linked": dk.linked,
    "linked_small": dk.linked_small,
    "two_phase": dk.two_phase,
    "two_phase_lo": dk.two_phase_lo,
}
_SEMANTIC_KINDS = tuple(_KERNELS)

_MASK32_NP = np.uint64(0xFFFFFFFF)


def _tier_set_rows(table, idx, rows):
    """Overwrite table[idx] = rows; padding entries carry DISTINCT
    out-of-range indices (dropped — duplicates would void the
    unique_indices promise even for dropped entries)."""
    return table.at[idx].set(rows, mode="drop", unique_indices=True)


# No donation: the link layer may retry a transiently-failed dispatch,
# which must not find its input buffer already consumed.
_TIER_SET = jax.jit(_tier_set_rows)


def _touched_of_pk(kind: str, pk, n: int) -> np.ndarray:
    """Balance rows a packed semantic batch can modify, extracted from
    the HOST copy of the packed columns (a superset is fine — the
    commitment refresh of an unmodified row is a no-op).  Two-phase
    kernels also write the durable pending target's accounts
    (COL_TP_SLOTS); in-batch targets resolve to the creator event's
    own dr/cr slots, which the batch already covers."""
    pk = np.asarray(pk)
    if kind == "orderfree_tight":
        s = np.concatenate(
            [pk[:n, 1].astype(np.int64), pk[:n, 2].astype(np.int64)]
        ) - 1
        return s[s >= 0]
    w = pk[:n, dk.COL_SLOTS]
    parts = [
        (w & _MASK32_NP).astype(np.int64) - 1,
        (w >> np.uint64(32)).astype(np.int64) - 1,
    ]
    if kind in ("two_phase", "two_phase_lo"):
        w2 = pk[:n, dk.COL_TP_SLOTS]
        parts.append((w2 & _MASK32_NP).astype(np.int64) - 1)
        parts.append((w2 >> np.uint64(32)).astype(np.int64) - 1)
    s = np.concatenate(parts)
    return s[s >= 0]


class DeviceEngine:
    """Authoritative device tables + windowed semantic dispatch."""

    def __init__(self, capacity: int, mirror, link: DeviceLink | None = None,
                 seed: int | None = None, metrics=None) -> None:
        self.capacity = capacity
        self.mirror = mirror  # host bookkeeping copy (recovery + parity)
        # Hot/cold account tiering (hot_tier.py, TB_HOT_CAPACITY): when
        # active, the device tables hold only `hot.hot_rows` rows; the
        # mirror (+ _meta_host) is the full-logical cold tier, and
        # every submit path prefetches its touched-account set into the
        # hot window first (tier_prefetch).  None = all-resident.
        from tigerbeetle_tpu.state_machine import hot_tier as _hot_tier

        self.hot = _hot_tier.from_env(capacity)
        device_rows = capacity if self.hot is None else self.hot.hot_rows
        self.window = _WINDOW
        self.link = link if link is not None else DeviceLink()
        # Lifecycle (types.EngineState): healthy -> degraded on fatal
        # link loss (host mirror becomes authoritative, every
        # outstanding future is replayed exactly on the host) ->
        # repromoting (probe + table re-upload + checksum handshake)
        # -> healthy.
        self.state = EngineState.healthy
        self.last_demotion: str | None = None
        self.last_probe_failure: str | None = None
        self._degraded_submits = 0
        # Healthy-mode scrub cadence, jittered by a deterministic
        # per-engine offset (seeded) so a fleet of engines sharing the
        # link doesn't scrub on the same fetch ordinal — and so the
        # scrub's own ~105 ms fetch doesn't ride the identical ring
        # rotation every cycle.  The offset only ADVANCES the first
        # scrub; the steady-state period stays TB_DEV_SCRUB_EVERY.
        global _ENGINE_SEQ
        _ENGINE_SEQ += 1
        if seed is None:
            seed = capacity + 0x85EBCA6B * _ENGINE_SEQ
        # Commitment on => cheap 16-byte scrubs => the tight default
        # cadence; commitment off (and no explicit operator cadence)
        # => every scrub is the legacy full-digest compare, keep 256.
        self._commit_enabled = envcheck.state_commit() == 1
        self._scrub_every = (
            _SCRUB_EVERY
            if (self._commit_enabled or _SCRUB_EVERY_SET)
            else _SCRUB_EVERY_LEGACY
        )
        cap = _scrub_jitter_cap(self._scrub_every, _SCRUB_JITTER)
        self._scrub_offset = (seed * 0x9E3779B9) % (cap + 1) if cap else 0
        self._last_scrub_fetch = -self._scrub_offset
        self._closed = False
        # Metrics registry handles (obs/registry.py): the owning state
        # machine passes a scoped view of ITS registry ("dev." prefix)
        # so one snapshot covers the whole engine; standalone engines
        # get a private registry.  A restore-recreated engine re-binds
        # the same handles — counters are process-lifetime cumulative.
        # Initialized before the first _place below can retry.
        from tigerbeetle_tpu import obs

        self.metrics = metrics if metrics is not None else obs.Registry()
        # Span/instant tracer (utils/tracer.py): NULL unless the owner
        # shares one — demotions/re-promotions then land as instants
        # on the merged cross-replica timeline.
        self.tracer = tracer_mod.NULL
        _c = self.metrics.counter
        self._stats = {
            "stat_retries": _c("link.retries"),
            "stat_link_errors": _c("link.errors"),
            "stat_semantic_events": _c("semantic_events"),
            "stat_fallback_batches": _c("fallback_batches"),
            "stat_fetches": _c("fetches"),
            # Degraded-mode lifecycle (bench engine_health reports).
            "stat_demotions": _c("demotions"),
            "stat_repromotions": _c("repromotions"),
            "stat_probe_failures": _c("probe_failures"),
            "stat_degraded_events": _c("degraded_events"),
            "stat_scrubs": _c("scrubs"),
            "stat_scrub_heals": _c("scrub_heals"),
            # Incremental state commitment (commitment.py): digest
            # updates dispatched, cheap (16-byte) vs fallback
            # (full-fetch localization) scrub passes, full-table
            # fetches actually paid, and accumulator repairs (tables
            # matched but a digest drifted — should stay 0 forever).
            "stat_commit_updates": _c("commit.updates"),
            "stat_scrub_cheap": _c("commit.scrub_cheap"),
            "stat_scrub_fallback": _c("commit.scrub_fallback"),
            "stat_full_fetches": _c("commit.full_fetches"),
            "stat_commit_repairs": _c("commit.repairs"),
            # Wave-record memory + sharded-execution forensics.
            "stat_wave_window_bytes_peak": _c("wave.window_bytes_peak"),
            "stat_wave_window_padded_peak": _c("wave.window_padded_peak"),
            "stat_wave_sharded": _c("wave.sharded"),
            # Wall-time split (seconds) for perf forensics.
            "stat_t_h2d": _c("t.h2d_s"),
            "stat_t_dispatch": _c("t.dispatch_s"),
            "stat_t_fetch": _c("t.fetch_s"),
            "stat_t_finish": _c("t.finish_s"),
        }
        # Per-stage crossing-latency histograms, hoisted so _retry
        # pays one dict lookup per crossing (no string building; the
        # shared no-op instances when TB_METRICS=0).
        self._link_hists = {
            stage: self.metrics.histogram(f"link.{stage}_us")
            for stage in ("h2d", "dispatch", "fetch", "probe")
        }
        # Cadence first-guesses as pull gauges + measured per-scrub
        # cost (ROADMAP "scrub/probe cadence tuning" carry-over): the
        # next real-link session reads the actual digest-compare cost
        # out of the same scrape that shows the cadence it ran at,
        # instead of re-deriving both from guesses.
        self.metrics.gauge_fn("scrub.every", lambda: self._scrub_every)
        self.metrics.gauge_fn("probe.every", lambda: _PROBE_EVERY)
        self._h_scrub_cost = self.metrics.histogram("scrub.cost_us")
        # Split scrub costs: the 16-byte digest compare vs the
        # full-fetch localization fallback — the next chip session
        # reads both (and the per-step digest-update overhead) off one
        # scrape (ROADMAP "scrub/probe cadence tuning").
        self._h_scrub_cheap = self.metrics.histogram("scrub.cheap_us")
        self._h_scrub_fallback = self.metrics.histogram("scrub.fallback_us")
        self._h_commit_update = self.metrics.histogram("commit.update_us")
        # Multi-device: the authoritative tables shard ROW-WISE across
        # every visible device (NamedSharding over a 1-D "shard" mesh);
        # the semantic kernels then run SPMD with XLA-inserted
        # collectives — the same dispatch code path single-chip uses
        # (exercised by __graft_entry__.dryrun_multichip on a virtual
        # CPU mesh).
        self.sharding = None
        devices = jax.devices()
        if len(devices) > 1 and device_rows % len(devices) == 0:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            from tigerbeetle_tpu.parallel.sharded import make_row_mesh

            self.sharding = NamedSharding(
                make_row_mesh(devices), P("shard", None)
            )
        self._meta_host = np.zeros((capacity, 2), np.uint32)
        self.ring = jnp.zeros((_RING, dk.SUMMARY_WORDS), jnp.uint64)
        self._ring_at = 0
        # Incremental state commitment (commitment.py): a device-side
        # (capacity, 2) per-row-hash array + (2,) u64 fold, updated
        # from just the rows each launch touched, with a bit-identical
        # host twin on the mirror (self._commit_enabled decided with
        # the scrub cadence above).  Standalone engines (unit tests)
        # get a twin keyed to the engine's own meta table; the owning
        # state machine attaches an attrs-backed twin BEFORE
        # constructing the engine.
        self.dev_row_hash = None
        self.dev_digest = None
        if self._commit_enabled and getattr(mirror, "commitment", None) is None:
            from tigerbeetle_tpu.state_machine import commitment as _cm

            mirror.commitment = _cm.HostCommitment(
                capacity, meta_fn=self._twin_meta
            )
        try:
            self.balances = self._place(
                jnp.zeros((device_rows, 8), jnp.uint64)
            )
            self.meta = self._place(jnp.zeros((device_rows, 2), jnp.uint32))
            self._commit_rebuild()
        except DeviceLostError as exc:
            # Born degraded: the link was already dead at construction.
            # Placeholders come from plain jnp (default backend, not the
            # link) so degraded-mode accessors have well-typed handles;
            # re-promotion replaces them from the mirror.
            self.state = EngineState.degraded
            self.last_demotion = repr(exc)
            self.balances = jnp.zeros((device_rows, 8), jnp.uint64)
            self.meta = jnp.zeros((device_rows, 2), jnp.uint32)
        # Window pipeline: _pending accumulates host-side; _launched is
        # the window currently executing on device; _recovering holds a
        # window mid-exact-recovery — detached from _launched so a
        # re-entrant drain (host fallbacks read the table, which
        # drains) cannot re-rotate it, but still owned so a demotion
        # mid-recovery replays its unresolved futures in order.
        self._pending: list[_InFlight] = []
        self._pending_semantic = 0
        self._launched: list[_InFlight] = []
        self._recovering: list[_InFlight] = []
        # Write-behind lane for host-resolved batches (exact path).
        self._q: list[tuple] = []
        self._queued = 0
        self._suppress_enqueue = False
        # Sum of in-flight records' contribution bounds (wave admission
        # accounts for batches the mirror has not materialized yet).
        self._inflight_bound = 0
        # dev_wave.spec.* handles: the owning state machine binds
        # machine-registry counters right after construction (and
        # after restore); standalone engines build them lazily on the
        # private registry at first speculative launch.
        self.spec_stats: dict | None = None
        # Degraded-mode read() cache: (mirror version, capacity) ->
        # CPU-placed (capacity, 8) table handle.
        self._degraded_cache = None
    # Compatibility properties: every stat_* above reads/writes its
    # registry handle (bench/experiment resets included).
    stat_retries = obs_stat_property("stat_retries")
    stat_link_errors = obs_stat_property("stat_link_errors")
    stat_semantic_events = obs_stat_property("stat_semantic_events")
    stat_fallback_batches = obs_stat_property("stat_fallback_batches")
    stat_fetches = obs_stat_property("stat_fetches")
    stat_demotions = obs_stat_property("stat_demotions")
    stat_repromotions = obs_stat_property("stat_repromotions")
    stat_probe_failures = obs_stat_property("stat_probe_failures")
    stat_degraded_events = obs_stat_property("stat_degraded_events")
    stat_scrubs = obs_stat_property("stat_scrubs")
    stat_scrub_heals = obs_stat_property("stat_scrub_heals")
    stat_wave_window_bytes_peak = obs_stat_property(
        "stat_wave_window_bytes_peak"
    )
    stat_wave_window_padded_peak = obs_stat_property(
        "stat_wave_window_padded_peak"
    )
    stat_wave_sharded = obs_stat_property("stat_wave_sharded")
    stat_commit_updates = obs_stat_property("stat_commit_updates")
    stat_scrub_cheap = obs_stat_property("stat_scrub_cheap")
    stat_scrub_fallback = obs_stat_property("stat_scrub_fallback")
    stat_full_fetches = obs_stat_property("stat_full_fetches")
    stat_commit_repairs = obs_stat_property("stat_commit_repairs")
    stat_t_h2d = obs_stat_property("stat_t_h2d")
    stat_t_dispatch = obs_stat_property("stat_t_dispatch")
    stat_t_fetch = obs_stat_property("stat_t_fetch")
    stat_t_finish = obs_stat_property("stat_t_finish")

    # ------------------------------------------------------------------
    # Link crossings: bounded retry + transient/fatal classification.
    # Every h2d upload, kernel dispatch, and d2h fetch funnels through
    # _retry, so a flaky link costs backoff, and a dead one raises ONE
    # typed error (DeviceLostError) that the lifecycle guards catch.

    def _retry(self, fn, stage: str):
        delay_s = _BACKOFF_MS / 1e3
        attempt = 0
        # Per-stage crossing latency — handles hoisted in __init__;
        # the no-op histogram when TB_METRICS=0 (no clock reads).
        hist = self._link_hists.get(stage)
        if hist is None:
            hist = self.metrics.histogram("link." + stage + "_us")
        while True:
            try:
                with hist.time():
                    return fn()
            except Exception as exc:  # noqa: BLE001
                if isinstance(exc, DeviceLostError):
                    raise
                self._stats["stat_link_errors"].inc()
                if (
                    classify_link_error(exc) != "transient"
                    or attempt >= _RETRIES
                ):
                    raise DeviceLostError(stage, exc) from exc
                attempt += 1
                self._stats["stat_retries"].inc()
                if delay_s > 0:
                    _time.sleep(delay_s)
                delay_s = min(delay_s * 2, _BACKOFF_CAP_MS / 1e3)

    def _put(self, array):
        return self._retry(lambda: self.link.device_put(array), "h2d")

    def _run(self, fn, *args):
        return self._retry(lambda: self.link.dispatch(fn, *args), "dispatch")

    def _place(self, table):
        if self.sharding is None:
            sharding = None
        else:
            sharding = self.sharding
        return self._retry(
            lambda: self.link.device_put(table, sharding), "h2d"
        )

    def prewarm(self, kinds) -> None:
        """Pay the one-time per-process costs OFF the hot path: the
        tunnel compiles a transfer plan per h2d SHAPE (~1 s each,
        engine trace) and XLA compiles each scan kernel on first call.
        Callers that know their workload (bench configs) name the
        kinds; engine construction happens during untimed setup.

        The pseudo-kind "waves" warms the HOST-fallback wave executor
        (waves.py) against this engine's table geometry: a batch the
        router punts to the host path re-executes there, and with no
        native engine built that means wave/scan kernels whose first
        compile must not land inside a timed window."""
        if self.state is not EngineState.healthy:
            return
        try:
            self._prewarm_inner(kinds)
        # tbcheck: allow(broad-except): ANY prewarm failure (compile
        # error, tunnel flap, OOM) demotes to the host path via a typed
        # DeviceLostError — degraded service beats dying at setup.
        except Exception as exc:
            self._demote(DeviceLostError("prewarm", exc))

    def _prewarm_inner(self, kinds) -> None:
        kinds = list(kinds)
        if "waves" in kinds:
            from tigerbeetle_tpu.state_machine import waves as _waves

            _waves.prewarm(self.capacity)
            mesh = self.wave_mesh()
            if mesh is not None:
                # Row-sharded engine: the window launch dispatches the
                # SPMD executors — warm those against this mesh so
                # sharded wave dispatch never first-compiles inside a
                # timed window.  (Speculation declines on sharded
                # engines, so no spec warm here.)
                _waves.prewarm(self.capacity, mesh=mesh)
            else:
                # The window launch dispatches the NON-DONATING twins
                # (separate XLA executables) — warm those too so wave
                # dispatch never first-compiles inside a timed window;
                # the speculative executor rides along unless disabled.
                _waves.prewarm(
                    self.capacity, engine=True,
                    spec=_waves.spec_mode() != "0",
                )
        if self._commit_enabled and self.dev_row_hash is not None:
            # Compile the digest-update kernel's smallest slot bucket
            # (every launch dispatches it) off the timed path.  An
            # all-padding slot array contributes nothing, so the
            # warmed dispatch cannot move the digest.
            from tigerbeetle_tpu.state_machine import commitment as _cm

            fns = _cm.device_fns()
            warm_pad = jnp.asarray(_cm.pad_slots(np.zeros(0, np.int64)))
            self._retry(
                lambda: self.link.block_until_ready(
                    self.link.dispatch(
                        fns["update"], self.balances, self.meta,
                        self.dev_row_hash, self.dev_digest,
                        warm_pad, warm_pad,
                    )
                ),
                "dispatch",
            )
        kinds = [k for k in kinds if k in _KERNELS]
        if not kinds:
            return
        tiers = sorted({self._tier(1), self._tier(self.window)})
        for ncols, dtype in {dk.PK_SPEC[k] for k in kinds}:
            self._put(np.zeros((dk.B, ncols), dtype))
            for W in tiers:
                self._put(np.zeros((W, dk.B, ncols), dtype))
        # The per-window ns/tsb arrays transfer from host at launch —
        # their transfer plans need warming like the buffers'.
        for W in tiers:
            self._put(np.zeros(W, np.int64))
            self._put(np.zeros(W, np.uint64))
        table = jnp.zeros_like(self.balances)
        meta = jnp.zeros_like(self.meta)
        ring = jnp.zeros_like(self.ring)
        outs = []
        for k in kinds:
            ncols, dtype = dk.PK_SPEC[k]
            pk = jnp.zeros((dk.B, ncols), dtype)
            outs.append(
                _KERNELS[k](table, meta, ring, 0, pk, 0, jnp.uint64(1))
            )
            for W in tiers:
                big = jnp.zeros((W, dk.B, ncols), dtype)
                ns = jnp.zeros(W, jnp.int64)
                tsb = jnp.zeros(W, jnp.uint64)
                for G in dk.SCAN_SIZES:
                    if G > W:
                        continue
                    outs.append(
                        dk.scan_win_kernels[k][G](
                            table, meta, ring, 0, big, 0, ns, tsb
                        )
                    )
        self._retry(lambda: self.link.block_until_ready(outs), "h2d")

    # ------------------------------------------------------------------
    # Account meta maintenance (create_accounts path).  Rides the
    # record stream so updates sequence between the batches around
    # them without forcing a drain.

    def add_accounts(self, slots, acct_flags, acct_ledger) -> None:
        slots = np.asarray(slots, np.int64)
        self._meta_host[slots, 0] = acct_flags
        self._meta_host[slots, 1] = acct_ledger
        # Meta is part of the committed row content: refresh the host
        # twin (the queued "meta" record folds the device side in at
        # its launch).
        if self.mirror.commitment is not None:
            self.mirror.commitment.refresh(slots, self.mirror)
        if self.state is not EngineState.healthy:
            # The host copy above is authoritative while degraded;
            # re-promotion re-uploads the whole meta table from it.  A
            # queued record would force a doomed launch at next drain.
            return
        self._queue_meta(
            slots,
            np.broadcast_to(
                np.asarray(acct_flags, np.uint32), slots.shape
            ).copy(),
            np.broadcast_to(
                np.asarray(acct_ledger, np.uint32), slots.shape
            ).copy(),
        )

    def remove_accounts(self, slots) -> None:
        """Linked create_accounts rollback support."""
        slots = np.asarray(slots, np.int64)
        self._meta_host[slots] = 0
        if self.mirror.commitment is not None:
            self.mirror.commitment.refresh(slots, self.mirror)
        if self.state is not EngineState.healthy:
            return  # see add_accounts
        z = np.zeros(len(slots), np.uint32)
        self._queue_meta(slots, z, z)

    def _queue_meta(self, slots, flags_u32, ledger_u32) -> None:
        """Queue a device meta update.  Tiered, meta records carry HOT
        slots (the map is stable until the next admission, which drains
        first), and cold rows are dropped — _meta_host stays the
        authority and admission uploads their meta."""
        if self.hot is not None:
            h = self.hot.translate(slots)
            keep = h >= 0
            if not keep.any():
                return
            slots = h[keep]
            flags_u32 = flags_u32[keep]
            ledger_u32 = ledger_u32[keep]
        self._pending.append(
            _InFlight(
                "meta", None, None,
                meta_args=(slots, flags_u32, ledger_u32),
            )
        )

    def grow(self, capacity: int) -> None:
        if capacity <= self.capacity:
            return
        self.drain()
        self.flush()
        old_capacity = self.capacity
        from tigerbeetle_tpu.state_machine.hot_tier import grow_zero_host

        self._meta_host = grow_zero_host(self._meta_host, capacity)
        # Capacity is committed before any link work: a demotion mid-
        # widen serves from the mirror at the NEW capacity, and
        # re-promotion rebuilds both tables from the mirror at it.
        self.capacity = capacity
        if self.hot is not None:
            # Tiered: the device tables keep their fixed hot-row
            # geometry — logical growth widens only the host maps (the
            # new rows are cold-zero, so the hot partial is untouched).
            self.hot.grow_logical(capacity)
            return
        was_sharded = self.sharding is not None
        if was_sharded and capacity % self.sharding.mesh.devices.size != 0:
            self.sharding = None  # re-place replicated from here on
        extra = capacity - old_capacity
        if self.state is not EngineState.healthy:
            return

        def widen(table, width, dtype):
            # Previously-sharded tables come back through the host (row
            # boundaries move between devices on grow, and a dropped
            # sharding must not leave a committed sharded base behind).
            base = (
                self._retry(lambda: self.link.fetch(table), "fetch")
                if was_sharded
                else table
            )
            return self._place(
                self._run(
                    jnp.concatenate, [base, jnp.zeros((extra, width), dtype)]
                )
            )

        try:
            self.balances = widen(self.balances, 8, jnp.uint64)
            self.meta = widen(self.meta, 2, jnp.uint32)
            # Zero rows hash to 0, so the widened digest VALUE is
            # unchanged — but the per-row hash array must match the
            # new geometry (and possibly a dropped sharding): rebuild.
            self._commit_rebuild()
        except DeviceLostError as exc:
            self._demote(exc)

    # ------------------------------------------------------------------
    # Hot/cold tiering (hot_tier.py): the batch planner calls
    # tier_prefetch with a batch's LOGICAL touched-account set BEFORE
    # packing; packed records then carry translated HOT slots.  The hot
    # map only ever changes against a quiesced pipeline (admission
    # drains + flushes first), so every in-flight record executes under
    # the map it was translated with, and eviction is free: after the
    # drain the mirror already holds every finished batch's effects —
    # the write-behind lane IS the dirty write-back path.

    def tier_prefetch(self, slots) -> bool:
        """Make every LOGICAL row in `slots` device-resident (negative
        entries ignored).  Returns False when the batch cannot run on
        device — touched set wider than the hot window, engine not
        healthy, or the link died mid-admission — and the caller takes
        the exact host path."""
        if self.hot is None:
            return True
        import time as _time

        hot = self.hot
        uniq, missing = hot.plan(np.asarray(slots, np.int64))
        if len(missing) == 0:
            hot.record_use(uniq, len(uniq), 0)
            return True
        if len(uniq) > hot.hot_rows:
            return False
        if self.state is not EngineState.healthy:
            return False
        t0 = _time.perf_counter()
        # Quiesce before the map moves (see section comment); the
        # drain can itself demote — re-check before touching the map.
        self.drain()
        self.flush()
        if self.state is not EngineState.healthy:
            return False
        got = hot.admit(missing, protect=uniq)
        if got is None:
            return False
        admitted, hot_slots, _evicted = got
        try:
            self._tier_upload(admitted, hot_slots)
        except DeviceLostError as exc:
            self._demote(exc)
            return False
        hot.record_use(uniq, len(uniq) - len(missing), len(missing))
        hot.note_stall(_time.perf_counter() - t0)
        return True

    def _tier_upload(self, admitted, hot_slots) -> None:
        """Upload admitted rows (balances + meta, straight from the
        cold tier) into their hot slots, and roll the device digest by
        the swap: the commitment "admit" kernel replaces the victim
        slots' row hashes with the host twin's hashes for the admitted
        rows — the digest stays the exact hot partial throughout."""
        if len(admitted) == 0:
            return
        from tigerbeetle_tpu.state_machine import commitment as _cm

        k = len(admitted)
        padded = _cm.pad_slots(np.asarray(hot_slots, np.int64))
        H = self.balances.shape[0]
        idx = np.where(
            padded >= 0, padded, H + np.arange(len(padded), dtype=np.int64)
        )
        bal = np.zeros((len(padded), 8), np.uint64)
        bal[:k] = self.mirror.rows8(admitted)
        meta = np.zeros((len(padded), 2), np.uint32)
        meta[:k] = self._meta_host[admitted]
        idx_j = self._put(idx)
        self.balances = self._run(
            _TIER_SET, self.balances, idx_j, self._put(bal)
        )
        self.meta = self._run(_TIER_SET, self.meta, idx_j, self._put(meta))
        if self._commit_enabled and self.dev_row_hash is not None:
            twin = self.mirror.commitment
            new_lo = np.zeros(len(padded), np.uint64)
            new_hi = np.zeros(len(padded), np.uint64)
            new_lo[:k] = twin.row_lo[admitted]
            new_hi[:k] = twin.row_hi[admitted]
            fns = _cm.device_fns()
            self.dev_row_hash, self.dev_digest = self._run(
                fns["admit"], self.dev_row_hash, self.dev_digest,
                self._put(padded), self._put(new_lo), self._put(new_hi),
            )

    # ------------------------------------------------------------------
    # Semantic dispatch.

    def submit(self, kind, pk, n, ts_base, finish, fallback,
               id_keys=None, bound=0) -> ReplyFuture:
        """Queue one semantic batch; returns its reply future.

        `finish(summary) -> bytes` runs at materialization (device codes
        -> bookkeeping + reply).  `fallback() -> bytes` re-executes the
        batch exactly on the host engine against the mirror.  `bound`
        upper-bounds the balance additions the batch can make (the
        wave path's in-flight admission term).

        In degraded mode the batch never touches the link: it resolves
        immediately through the exact host path (bit-identical reply).
        """
        return self._submit_record(
            n, fallback,
            lambda fut: _InFlight(
                kind, fut, finish, pk=pk, n=n, ts_base=ts_base,
                fallback=fallback, id_keys=id_keys, bound=bound,
            ),
        )

    def submit_waves(self, ev, dstat_init, n, ts_base, plan, hist_fix,
                     finish, fallback, id_keys=None, bound=0) -> ReplyFuture:
        """Queue one WAVE-DISPATCHED batch: a batch the semantic
        kernels cannot express, executed inside the window as the wave
        plan's segments (one device step per wave / chain position —
        waves.run_plan_engine) against the authoritative HBM table
        instead of draining to the host mirror.

        `ev` is the host-side (B,)-array event dict (kernel.py
        contract), `plan` the admitted WavePlan, `hist_fix` the
        snapshot-rewrite mask; `finish(packed_np) -> bytes` runs the
        exact-path bookkeeping from the fetched packed output at
        materialization, `fallback()` the drained host re-execution.
        The caller PROVED admission against mirror + the engine's
        in-flight bound, so the plan is never wrong — a wave record
        has no failure flag and never triggers exact recovery itself.

        The record does NOT retain the (B,)-padded dict: it stores the
        lossless columnar compaction (waves.pack_wave_record) and
        rebuilds the padded arrays at launch — a full pending window
        of wave records holds compact columns, not ~3 MB per batch
        (pending_window_bytes / ROADMAP "Wave-dispatch batch memory").
        """
        from tigerbeetle_tpu.state_machine import waves as _waves

        packed = _waves.pack_wave_record(ev, dstat_init, hist_fix, n)
        return self._submit_wave_like(
            "waves", packed, plan, n, ts_base, finish, fallback,
            id_keys, bound,
        )

    def submit_speculative(self, ev, dstat_init, n, ts_base, spec_serial,
                           pv_serial, finish, fallback, id_keys=None,
                           bound=0) -> ReplyFuture:
        """Queue one SPECULATIVE batch: no wave plan exists yet — at
        launch the ENTIRE batch executes as one validated device step
        (waves.run_speculative_engine) and only a conflicted residue
        replays through plan_waves (waves.plan_residue), so the
        partitioner runs exactly when validation fails.

        Everything else about the record is a wave record: the compact
        columnar codec (waves.pack_spec_record), the hazard-probe id
        keys, exact recovery (no failure flag — admission proved the
        overflow bound, so the fetched packed output always resolves),
        and the degraded-mode host fallback.  `bound` MUST be the
        whole-batch superset the wave path would charge — NOT the
        committed subset: a demotion mid-speculation replays the whole
        batch through the exact host fallback, and a smaller charge
        would let a sibling admission plan against headroom that
        replay then consumes (over-apply).  `pv_serial` records the
        submit-time routing fact (a pending target may sit on a
        history account) the residue planner must reuse."""
        from tigerbeetle_tpu.state_machine import waves as _waves

        packed = _waves.pack_spec_record(ev, dstat_init, spec_serial, n)
        return self._submit_wave_like(
            "spec", packed, bool(pv_serial), n, ts_base, finish,
            fallback, id_keys, bound,
        )

    def _submit_wave_like(self, kind, packed, extra, n, ts_base, finish,
                          fallback, id_keys, bound) -> ReplyFuture:
        """The shared tail of wave/speculative submission: one compact
        record on the stream + the pending-window memory peaks.
        `extra` is the kind's launch payload (the WavePlan for a wave
        record, the pv_serial routing fact for a speculative one)."""
        if self.hot is not None:
            # v1 tiering scope cut: the wave/speculative executors
            # index the table by LOGICAL slot inside their event dicts;
            # the router declines them (dev_wave.decline.tier) before
            # reaching here, so this guard only covers direct engine
            # callers — resolve exactly on the host.
            fut = ReplyFuture(self)
            self.drain()
            self.flush()
            self.stat_fallback_batches += 1
            self._resolve_host_now(fut, fallback)
            return fut
        fut = self._submit_record(
            n, fallback,
            lambda f: _InFlight(
                kind, f, finish, n=n, ts_base=ts_base,
                fallback=fallback, id_keys=id_keys, bound=bound,
                wave_args=(packed, extra),
            ),
        )
        compact, padded = self.pending_window_bytes()
        self.stat_wave_window_bytes_peak = max(
            self.stat_wave_window_bytes_peak, compact
        )
        self.stat_wave_window_padded_peak = max(
            self.stat_wave_window_padded_peak, padded
        )
        return fut

    def _spec_st(self) -> dict:
        st = self.spec_stats
        if st is None:
            st = self.spec_stats = make_spec_stats(self.metrics)
        return st

    def pending_window_bytes(self) -> tuple:
        """(compact, padded) host bytes retained by queued/in-flight
        wave records — what the window actually holds vs what the old
        padded event dicts would have held."""
        compact = padded = 0
        for rec in self._pending + self._launched + self._recovering:
            if rec.kind in ("waves", "spec") and rec.wave_args is not None:
                pk = rec.wave_args[0]
                compact += pk.nbytes
                padded += pk.padded_nbytes
        return compact, padded

    def wave_mesh(self):
        """Capability probe for SPMD wave dispatch: the row mesh when
        this engine's sharded tables support it — a 1-D ("shard",)
        mesh whose shard count divides the capacity — else None.  An
        unsupported mesh makes the router DECLINE wave submission
        (drain + host path, the r7 behavior), never error."""
        if self.sharding is None:
            return None
        mesh = self.sharding.mesh
        if tuple(mesh.axis_names) != ("shard",):
            return None
        if self.capacity % mesh.devices.size != 0:
            return None
        return mesh

    def _submit_record(self, n, fallback, make_rec) -> ReplyFuture:
        """The ONE stream-entry protocol for semantic and wave batches:
        degraded check -> flush (earlier exact-path deltas must
        precede) -> degraded re-check (the flush itself may lose the
        link; a queued record would force a doomed launch) -> enqueue
        + window-rotation trigger."""
        if self.state is not EngineState.healthy:
            fut = ReplyFuture(self)
            self.stat_degraded_events += n
            self._resolve_host_now(fut, fallback)
            return fut
        self.flush()
        if self.state is not EngineState.healthy:
            fut = ReplyFuture(self)
            self.stat_degraded_events += n
            self._resolve_host_now(fut, fallback)
            return fut
        fut = ReplyFuture(self)
        rec = make_rec(fut)
        self._pending.append(rec)
        self._pending_semantic += 1
        self._inflight_bound += rec.bound
        if self._pending_semantic >= self.window:
            try:
                self._rotate()
            except DeviceLostError as exc:
                self._demote(exc)
        return fut

    def inflight_bound(self) -> int:
        """Upper bound on balance additions submitted but not yet
        reflected in the mirror — the wave admission's `extra` term."""
        return self._inflight_bound

    def _release_bound(self, rec: _InFlight) -> None:
        """The record's bookkeeping reached the mirror (finish ran, or
        its host fallback/replay did): its contributions are no longer
        'in flight'.  Idempotent — bound zeroes on first release."""
        if rec.bound:
            self._inflight_bound -= rec.bound
            rec.bound = 0

    def lookup(self, slots, finish) -> ReplyFuture:
        """Device-side balance gather for lookup_accounts: rides the
        record stream, so it sees every earlier batch's effects.
        `finish(rows)` builds the reply from the fetched (k, 8) rows
        at materialization."""
        slots = np.asarray(slots, np.int64)
        if self.state is not EngineState.healthy:
            fut = ReplyFuture(self)
            self._resolve_host_now(
                fut, lambda: finish(self.mirror.rows8(slots))
            )
            return fut
        # Tiered: the gather indexes the hot-shaped device table, so
        # every looked-up row must be resident first.  If the batch
        # can't be made resident, drain + flush and answer from the
        # mirror — exact, since the drain materialized every earlier
        # batch's bookkeeping there.
        if not self.tier_prefetch(slots):
            fut = ReplyFuture(self)
            self.drain()
            self.flush()
            self._resolve_host_now(
                fut, lambda: finish(self.mirror.rows8(slots))
            )
            return fut
        # Earlier host-resolved batches' write-behind deltas must be
        # visible to the gather (found by the wave-dispatch fuzz: a
        # lookup queued behind only meta records — no semantic submit,
        # whose flush would have covered this — read the table without
        # the still-queued exact-path deltas).
        self.flush()
        if self.state is not EngineState.healthy:
            fut = ReplyFuture(self)
            self._resolve_host_now(
                fut, lambda: finish(self.mirror.rows8(slots))
            )
            return fut
        fut = ReplyFuture(self)
        rec = _InFlight(
            "lookup", fut, finish, slots=slots,
            hot_slots=(
                self.hot.translate(slots) if self.hot is not None else None
            ),
        )
        self._pending.append(rec)
        return fut

    @staticmethod
    def _resolve_host_now(fut: ReplyFuture, produce) -> None:
        try:
            fut.resolve(produce())
        except Exception as exc:  # noqa: BLE001
            # A host-path failure must still terminate the future; the
            # caller sees the real error at result().
            fut.fail(exc)
            raise

    def _gather(self, slots):
        pad = ((len(slots) + 255) & ~255) or 256
        sl = np.full(pad, -1, np.int64)
        sl[: len(slots)] = slots
        return self._run(dk.lookup, self.balances, jnp.asarray(sl))

    # ------------------------------------------------------------------
    # Window launch: one h2d per column layout (device idle at call
    # time), then back-to-back dispatches with no in-stream transfers.

    def _plan_chunks(self, recs):
        """Group records into dispatch units: maximal same-kind
        semantic runs split into scan chunks (largest SCAN_SIZES
        first, exact decomposition — no padding, no wasted ring
        rows), with meta/lookup records as unit boundaries."""
        units = []
        run = []
        for rec in recs:
            if rec.kind in _SEMANTIC_KINDS and (
                not run or run[-1].kind == rec.kind
            ):
                run.append(rec)
                continue
            if run:
                units.extend(self._split_run(run))
                run = []
            if rec.kind in _SEMANTIC_KINDS:
                run.append(rec)
            else:
                units.append((rec.kind, [rec]))
        if run:
            units.extend(self._split_run(run))
        return units

    def _tier(self, rows: int) -> int:
        small = max(1, self.window // 3)
        return small if rows <= small else self.window

    @staticmethod
    def _split_run(run):
        out = []
        at = 0
        for G in dk.SCAN_SIZES:
            while len(run) - at >= G:
                out.append(("scan", run[at : at + G]))
                at += G
        for rec in run[at:]:
            out.append(("solo", [rec]))
        return out

    def _launch(self, recs: list[_InFlight]) -> None:
        """Upload the window's inputs in as FEW transfers as possible
        (after the first kernel runs, every h2d on this tunnel pays a
        large fixed cost — transfer count dominates, r5 measurements),
        block until they land (an in-flight transfer behind queued
        kernels crawls at the serialized in-stream rate), then
        dispatch back-to-back with zero in-stream transfers.
        Same-kind runs go G batches per LAUNCH via lax.scan reading
        from a per-spec window buffer at a row offset (~10 ms launch
        overhead per dispatch vs ~0.8 ms device compute)."""
        if not recs:
            return
        t0 = _time.perf_counter()
        units = self._plan_chunks(recs)
        # One (tier, B, C) buffer + (tier,) ns/tsb per input spec; scan
        # chunks claim contiguous row ranges in plan order.  The tier
        # (buffer row count) rounds the spec's claimed rows up to
        # window/3 or window, so a minority spec in a mixed window does
        # not ship a full window of padding (the link is bytes-bound).
        rows_of: dict[tuple, int] = {}
        for ukind, urecs in units:
            if ukind == "scan":
                spec = dk.PK_SPEC[urecs[0].kind]
                rows_of[spec] = rows_of.get(spec, 0) + len(urecs)
        bufs: dict[tuple, list] = {}  # spec -> [big, ns, tsb, cursor]
        offsets: dict[int, int] = {}
        for i, (ukind, urecs) in enumerate(units):
            if ukind != "scan":
                continue
            spec = dk.PK_SPEC[urecs[0].kind]
            if spec not in bufs:
                ncols, dtype = spec
                tier = self._tier(rows_of[spec])
                bufs[spec] = [
                    np.zeros((tier, dk.B, ncols), dtype),
                    np.zeros(tier, np.int64),
                    np.zeros(tier, np.uint64),
                    0,
                ]
            big, ns, tsb, cur = bufs[spec]
            for g, rec in enumerate(urecs):
                big[cur + g] = rec.pk
                ns[cur + g] = rec.n
                tsb[cur + g] = rec.ts_base
            offsets[i] = cur
            bufs[spec][3] = cur + len(urecs)
        dev_bufs = {
            spec: (
                self._put(big),
                self._put(ns),
                self._put(tsb),
            )
            for spec, (big, ns, tsb, _cur) in bufs.items()
        }
        dev_solo = {
            i: self._put(urecs[0].pk)
            for i, (ukind, urecs) in enumerate(units)
            if ukind == "solo"
        }
        # ONE blocking sync (each blocking call costs a ~100 ms tunnel
        # round trip).
        self._retry(
            lambda: self.link.block_until_ready(
                [list(dev_bufs.values()), list(dev_solo.values())]
            ),
            "h2d",
        )
        t1 = _time.perf_counter()
        self.stat_t_h2d += t1 - t0
        for i, (ukind, urecs) in enumerate(units):
            if ukind == "meta":
                slots, flags, ledger = urecs[0].meta_args
                self.meta = self._run(
                    dk.meta_update,
                    self.meta, jnp.asarray(slots), jnp.asarray(flags),
                    jnp.asarray(ledger),
                )
                continue
            if ukind == "lookup":
                rec0 = urecs[0]
                urecs[0].handle = self._gather(
                    rec0.hot_slots if rec0.hot_slots is not None
                    else rec0.slots
                )
                continue
            if ukind == "waves":
                self._exec_waves(urecs[0])
                continue
            if ukind == "spec":
                self._exec_spec(urecs[0])
                continue
            if ukind == "solo":
                rec = urecs[0]
                self.balances, self.ring = self._run(
                    _KERNELS[rec.kind],
                    self.balances, self.meta, self.ring, self._ring_at,
                    dev_solo[i], rec.n, jnp.uint64(rec.ts_base),
                )
                rec.ring_at = self._ring_at
                self._ring_at = (self._ring_at + 1) % _RING
                continue
            big, ns, tsb = dev_bufs[dk.PK_SPEC[urecs[0].kind]]
            scan_fn = dk.scan_win_kernels[urecs[0].kind][len(urecs)]
            self.balances, self.ring = self._run(
                scan_fn,
                self.balances, self.meta, self.ring, self._ring_at,
                big, offsets[i], ns, tsb,
            )
            for g, rec in enumerate(urecs):
                rec.ring_at = (self._ring_at + g) % _RING
            self._ring_at = (self._ring_at + len(urecs)) % _RING
        self.stat_t_dispatch += _time.perf_counter() - t1
        # Absorb the whole window's touched rows into the on-device
        # commitment: one extra dispatch per launch (commit.update_us).
        if self._commit_enabled:
            touched = self._collect_touched(recs)
            if touched is not None:
                self._commit_update(touched)

    def _dispatch(self, rec: _InFlight) -> None:
        """Immediate single-batch dispatch (fallback re-dispatch path)."""
        self.balances, self.ring = self._run(
            _KERNELS[rec.kind],
            self.balances, self.meta, self.ring, self._ring_at,
            jnp.asarray(rec.pk), rec.n, jnp.uint64(rec.ts_base),
        )
        rec.ring_at = self._ring_at
        self._ring_at = (self._ring_at + 1) % _RING

    def _exec_waves(self, rec: _InFlight) -> None:
        """Execute a wave record's plan against the authoritative
        table.  The WHOLE batch rides one "dispatch" link crossing and
        the executor never donates the engine's table handle
        (waves.run_plan_engine), so a transient fault mid-plan retries
        the entire batch idempotently from the same `self.balances`.
        The packed per-event output handle is fetched at rotation like
        a lookup gather.  On a row-sharded engine the plan runs SPMD
        over the ("shard",) mesh (the router only admitted shardable
        plans there), and the new table comes back under the same
        NamedSharding row partition."""
        from tigerbeetle_tpu.state_machine import waves as _waves

        packed_rec, plan = rec.wave_args
        ev, dstat_init, hist_fix = _waves.unpack_wave_record(packed_rec)
        if self._commit_enabled:
            rec.touched = _waves.touched_slots(ev, rec.n)
        mesh = self.wave_mesh()

        def run():
            return self.link.dispatch(
                _waves.run_plan_engine, self.balances, ev, dstat_init,
                rec.n, rec.ts_base, plan, hist_fix, mesh,
            )

        new_balances, packed = self._retry(run, "dispatch")
        # Counted only AFTER the dispatch succeeded: a fatally-failed
        # SPMD launch that ends up served by host fallback must not
        # report as sharded execution in the forensics.
        if mesh is not None:
            self.stat_wave_sharded += 1
        self.balances = new_balances
        rec.handle = packed

    def _exec_spec(self, rec: _InFlight) -> None:
        """Execute a speculative record: ONE whole-batch device step
        with on-device conflict validation, a small flags fetch (the
        validation sync), then — only on a miss — plan_waves over the
        conflicted residue and a carry-threaded replay.  The executor
        never donates the engine's table handle and `self.balances`
        is reassigned only after the whole closure succeeded, so a
        transient fault anywhere (dispatch, validation fetch, residue
        replay) retries the entire batch idempotently from the same
        authoritative handle — exactly _exec_waves' contract."""
        from tigerbeetle_tpu.state_machine import resolve as _resolve
        from tigerbeetle_tpu.state_machine import waves as _waves

        packed_rec, pv_serial = rec.wave_args
        ev, dstat_init, spec_serial = _waves.unpack_spec_record(packed_rec)
        if self._commit_enabled:
            rec.touched = _waves.touched_slots(ev, rec.n)
        n = rec.n
        B = len(ev["flags"])

        def run():
            t0 = _time.perf_counter()
            carry, confl = self.link.dispatch(
                _waves.run_speculative_engine, self.balances, ev,
                dstat_init, spec_serial, n, rec.ts_base,
            )
            # THE validation sync: a (K,) bool fetch.  Blocking here is
            # the speculation tax — later records in the window read
            # self.balances, so the hit/miss verdict cannot defer to
            # rotation (a miss would leave residue effects unapplied
            # underneath them).
            confl_np = np.asarray(self.link.fetch(confl))[:n]
            val_s = _time.perf_counter() - t0
            residue = np.flatnonzero(confl_np)
            hist = np.zeros(B, bool)
            if len(residue) == 0:
                hist[:n] = True
                out = self.link.dispatch(
                    _waves.finalize_engine, carry, hist
                )
                return out, 0, 1, val_s, 0.0
            t1 = _time.perf_counter()
            meta = _resolve.spec_meta_from_events(ev, n, pv_serial)
            plan = _waves.plan_residue(n, meta, residue)
            plan_s = _time.perf_counter() - t1
            # Snapshot-rewrite mask: committed events rode the wave
            # step (finals), residue wave/chain events likewise; scan
            # residues keep their sequential-exact snapshots.
            hist[:n] = ~confl_np
            hist[:n] |= plan.wave_mask
            out = self.link.dispatch(
                _waves.continue_plan_engine, carry, ev, n, rec.ts_base,
                plan, hist,
            )
            return out, len(residue), 1 + plan.n_steps, val_s, plan_s

        st = self._spec_st()
        st["attempts"].inc()
        (new_balances, packed), residue_n, steps, val_s, plan_s = (
            self._retry(run, "dispatch")
        )
        self.balances = new_balances
        rec.handle = packed
        if residue_n == 0:
            st["hits"].inc()
            st["plan_skipped"].inc()
        else:
            st["residue_events"].inc(residue_n)
            st["residue_plan_s"].inc(plan_s)
        st["steps"].inc(steps)
        st["validation_s"].inc(val_s)
        st["validation_us"].observe(val_s * 1e6)

    # ------------------------------------------------------------------
    # Hazard probe: does any probe id match an in-flight batch's ids?

    def inflight_ids_hit(self, keys: np.ndarray) -> bool:
        """keys: u128-packed (V16) id probes, any order."""
        stream = self._launched + self._pending
        if not stream or len(keys) == 0:
            return False
        keys = np.sort(keys)
        # V16 keys order numerically by their bytes; scalar compares go
        # through .tobytes() (numpy void scalars lack ufunc ordering).
        lo = keys[0].tobytes()
        hi = keys[-1].tobytes()
        for rec in stream:
            ik = rec.id_keys
            if ik is None or len(ik) == 0:
                continue
            if hi < ik[0].tobytes() or lo > ik[-1].tobytes():
                continue
            pos = np.searchsorted(ik, keys)
            pos = np.minimum(pos, len(ik) - 1)
            if (ik[pos] == keys).any():
                return True
        return False

    def has_inflight(self) -> bool:
        return bool(self._launched or self._pending)

    # ------------------------------------------------------------------
    # Rotation + materialization.

    def _fetch_ring(self, recs):
        """Ring snapshot + lookup-row pulls for a launched window; the
        fetch drains the device stream (idle on return)."""
        ring_np = None
        t0 = _time.perf_counter()
        if any(r.kind in _SEMANTIC_KINDS for r in recs):
            self.stat_fetches += 1
            # THE burst fetch.
            ring_np = self._retry(lambda: self.link.fetch(self.ring), "fetch")
        for rec in recs:
            if rec.kind in ("lookup", "waves", "spec") and rec.handle is not None:
                rec.rows = self._retry(
                    lambda h=rec.handle: self.link.fetch(h), "fetch"
                )
                rec.handle = None
        self.stat_t_fetch += _time.perf_counter() - t0
        return ring_np

    def _window_clean(self, recs, ring_np) -> bool:
        for rec in recs:
            if rec.kind not in _SEMANTIC_KINDS:
                continue
            s = ring_np[rec.ring_at]
            if int(s[1]) & (dk.FLAG_OVERFLOW | dk.FLAG_CAP | dk.FLAG_PRECOND):
                return False
        return True

    def _resolve_clean(self, recs, ring_np) -> None:
        t0 = _time.perf_counter()
        for rec in recs:
            if rec.kind == "meta":
                continue
            if rec.kind == "lookup":
                rec.future.resolve(rec.finish(rec.rows))
                continue
            if rec.kind in ("waves", "spec"):
                self.stat_semantic_events += rec.n
                rec.future.resolve(rec.finish(rec.rows))
                self._release_bound(rec)
                continue
            s = dk.unpack_summary(ring_np[rec.ring_at])
            self.stat_semantic_events += rec.n
            rec.future.resolve(rec.finish(s))
            self._release_bound(rec)
        self.stat_t_finish += _time.perf_counter() - t0

    def _rotate(self) -> None:
        """Window boundary: fetch the launched window's ring, and —
        when it is clean — launch the pending window while the host
        still holds the fetched results, then finish the old window's
        bookkeeping overlapped with the new window's device work.

        Raises DeviceLostError on unrecoverable link loss; records are
        reassigned between _launched/_pending only AFTER the crossing
        that covers them succeeded, so the _demote caller always sees
        every unresolved record still in the stream lists, in order.
        """
        prev = self._launched
        ring_np = self._fetch_ring(prev) if prev else None
        if prev and (ring_np is None or self._window_clean(prev, ring_np)):
            nxt = self._pending
            self._launch(nxt)  # may raise: prev + nxt stay tracked
            self._launched = nxt
            self._pending = []
            self._pending_semantic = 0
            self._resolve_clean(prev, ring_np)  # host-only, cannot lose
            return
        if prev:
            # Fallback in the window: serial exact recovery first.
            # Detach prev into the recovery slot: the host fallbacks it
            # runs re-enter drain() via table reads, and a nested
            # rotate must NOT see this window as launched (it would
            # re-resolve it).  On device loss mid-recovery the records
            # stay in _recovering for _demote; on success the slot
            # clears.
            self._launched = []
            self._recovering = prev
            self._resolve_recovery(prev, ring_np)
            self._recovering = []
        self._launched = []
        nxt = self._pending
        self._launch(nxt)  # may raise: nxt still in _pending
        self._launched = nxt
        self._pending = []
        self._pending_semantic = 0

    def _resolve_recovery(self, covered, ring_np) -> None:
        """Exact recovery: resolve in order until the flagged batch,
        host re-execute it (mirror becomes current), rebuild the device
        table, re-dispatch everything after it, repeat until done."""
        while covered:
            if ring_np is None:
                ring_np = self._fetch_ring(covered)
            failed_at = None
            for i, rec in enumerate(covered):
                if rec.kind == "meta":
                    continue
                if rec.kind == "lookup":
                    rec.future.resolve(rec.finish(rec.rows))
                    continue
                if rec.kind in ("waves", "spec"):
                    # Wave/speculative records carry no failure flag:
                    # admission proved the plan exact, so the fetched
                    # packed output (computed against the stream prefix
                    # before any LATER batch's fallback) resolves.
                    self.stat_semantic_events += rec.n
                    rec.future.resolve(rec.finish(rec.rows))
                    self._release_bound(rec)
                    continue
                s = dk.unpack_summary(ring_np[rec.ring_at])
                if s["overflow"] or s["cap_exceeded"] or s["precond"]:
                    failed_at = i
                    self.stat_fallback_batches += 1
                    rec.future.resolve(rec.fallback())
                    self._release_bound(rec)
                    break
                self.stat_semantic_events += rec.n
                rec.future.resolve(rec.finish(s))
                self._release_bound(rec)
            if failed_at is None:
                return
            # Mirror reflects every batch up to and including the
            # fallback; rebuild the device table from it and replay
            # the rest in order.
            self._upload_from_mirror()
            covered = covered[failed_at + 1 :]
            for rec in covered:
                if rec.kind == "meta":
                    slots, flags, ledger = rec.meta_args
                    self.meta = self._run(
                        dk.meta_update,
                        self.meta, jnp.asarray(slots), jnp.asarray(flags),
                        jnp.asarray(ledger),
                    )
                elif rec.kind == "lookup":
                    rec.handle = self._gather(
                        rec.hot_slots if rec.hot_slots is not None
                        else rec.slots
                    )
                elif rec.kind == "waves":
                    self._exec_waves(rec)
                elif rec.kind == "spec":
                    self._exec_spec(rec)
                else:
                    self._dispatch(rec)
            # The re-dispatched suffix mutated the rebuilt table: fold
            # its touched rows back into the commitment.
            if self._commit_enabled and covered:
                touched = self._collect_touched(covered)
                if touched is not None:
                    self._commit_update(touched)
            ring_np = None

    def _mirror_table_np(self) -> np.ndarray:
        """Device-layout (capacity, 8) snapshot of the host mirror."""
        return self.mirror.table8(self.capacity)

    def _mirror_hot_table_np(self) -> np.ndarray:
        """Hot-shaped (hot_rows, 8) host image of the device balance
        table — what the DEVICE table should equal under tiering."""
        from tigerbeetle_tpu.state_machine.hot_tier import mirror_hot_table8

        return mirror_hot_table8(self.mirror, self.hot.logical_of)

    def _meta_hot_np(self) -> np.ndarray:
        """Hot-shaped (hot_rows, 2) host image of the device meta
        table (zeros for free hot slots)."""
        lof = self.hot.logical_of
        out = np.zeros((len(lof), 2), np.uint32)
        occ = np.flatnonzero(lof >= 0)
        out[occ] = self._meta_host[lof[occ]]
        return out

    @staticmethod
    def _cpu_device():
        try:
            return jax.devices("cpu")[0]
        except RuntimeError:
            return None

    def _degraded_table(self):
        """Mirror-built table handle for degraded/recovering reads,
        pinned to the CPU backend — a deployment whose DEFAULT JAX
        backend is the dead tunneled TPU must not re-dispatch degraded
        work at it — and cached behind the mirror's version stamp so
        degraded reads stop rebuilding (capacity, 8) bytes per call
        (ROADMAP "Pin degraded-mode host compute")."""
        key = (self.mirror.version, self.capacity)
        if self._degraded_cache is not None and self._degraded_cache[0] == key:
            handle = self._degraded_cache[1]
            # The host exact path DONATES the table it reads (scan /
            # wave executors): a donated cache entry is dead — rebuild.
            if not handle.is_deleted():
                return handle
        table_np = self._mirror_table_np()
        cpu = self._cpu_device()
        handle = (
            jax.device_put(table_np, cpu)
            if cpu is not None
            else jnp.asarray(table_np)
        )
        self._degraded_cache = (key, handle)
        return handle

    def _device_checksum(self) -> np.ndarray:
        """Round-trip the device-side balance-table digest (the ONE
        checksum crossing verify paths and the health digest share)."""
        return self._retry(
            lambda: self.link.fetch(
                self.link.dispatch(dk.checksum, self.balances)
            ),
            "fetch",
        )

    @staticmethod
    def _meta_digest(meta):
        """4-word digest of the (capacity, 2) account-meta table —
        the shared digest formula (mirror.digest_columns), so it can
        never drift from the balance-table compare."""
        from tigerbeetle_tpu.state_machine.mirror import digest_columns

        return digest_columns(meta)

    def _device_health_digest(self) -> np.ndarray:
        """Balances digest + meta digest from the DEVICE tables — what
        the scrub and the re-promotion handshake compare against the
        host's copy (meta corruption must be as detectable as balance
        corruption: the kernels' ladder verdicts read it)."""
        bal = self._device_checksum()
        meta = self._retry(
            lambda: self.link.fetch(
                self.link.dispatch(self._meta_digest, self.meta)
            ),
            "fetch",
        )
        return np.concatenate([bal, meta])

    def _host_health_digest(self) -> np.ndarray:
        # Tiered, the device tables are hot-shaped: digest the same
        # hot-shaped host images the device should hold (the logical
        # table is attested separately through the commitment fold).
        if self.hot is not None:
            from tigerbeetle_tpu.state_machine.mirror import digest_columns

            return np.concatenate(
                [
                    digest_columns(self._mirror_hot_table_np()),
                    self._meta_digest(self._meta_hot_np()),
                ]
            )
        return np.concatenate(
            [
                self.mirror.checksum8(self.capacity),
                self._meta_digest(self._meta_host),
            ]
        )

    def _upload_from_mirror(self) -> None:
        src = (
            self._mirror_hot_table_np()
            if self.hot is not None
            else self._mirror_table_np()
        )
        self.balances = self._place(jnp.asarray(src))
        # The device table just changed wholesale: re-derive the
        # on-device commitment from scratch (one dispatch — callers
        # are recovery/re-promotion/heal paths, never the hot path).
        # Reads the CURRENT device meta table, so callers that also
        # re-upload meta must do so BEFORE this.
        self._commit_rebuild()

    # ------------------------------------------------------------------
    # Incremental state commitment (state_machine/commitment.py): the
    # device maintains per-row hashes + a 16-byte fold of its
    # balances+meta tables as a by-product of every execution path —
    # each launch/flush/recovery re-dispatch absorbs exactly the rows
    # it touched — and the host twin on mirror.commitment tracks the
    # same value bit-identically.  Scrub and the re-promotion
    # handshake compare 16 bytes; the full-table fetch survives only
    # as _localize_divergence.

    def _twin_meta(self, slots: np.ndarray) -> np.ndarray:
        """Meta columns for a standalone engine's host twin (the
        owning state machine supplies an attrs-backed one instead)."""
        out = np.zeros((len(slots), 2), np.uint32)
        m = slots < len(self._meta_host)
        out[m] = self._meta_host[slots[m]]
        return out

    def _commit_rows(self):
        """Logical-row binding for the commitment kernels: identity
        when all-resident, logical_of tiered.  Free hot slots bind to
        row 0 — their all-zero content hashes to (0, 0) regardless of
        the binding, so the digest is exactly the hot PARTIAL of the
        logical table (fold(hot, cold) == the full root)."""
        if self.hot is None:
            return jnp.arange(self.balances.shape[0], dtype=jnp.uint64)
        lof = self.hot.logical_of
        return jnp.asarray(np.where(lof >= 0, lof, 0).astype(np.uint64))

    def _commit_rebuild(self) -> None:
        """From-scratch device digest (vectorized over the table ON
        DEVICE; on a row-sharded engine GSPMD computes shard-local
        partial folds and all-reduces them over ICI)."""
        if not self._commit_enabled:
            return
        from tigerbeetle_tpu.state_machine import commitment as _cm

        fns = _cm.device_fns()
        self.dev_row_hash, self.dev_digest = self._run(
            fns["rebuild"], self.balances, self.meta, self._commit_rows()
        )

    def _commit_update(self, slots) -> None:
        """Absorb the touched rows of one launch/flush into the
        on-device digest: ONE extra dispatch per window, O(touched).
        `slots` index the DEVICE table (hot slots under tiering)."""
        if not self._commit_enabled or self.dev_row_hash is None:
            return
        slots = np.unique(np.asarray(slots, np.int64))
        slots = slots[(slots >= 0) & (slots < self.balances.shape[0])]
        if len(slots) == 0:
            return
        from tigerbeetle_tpu.state_machine import commitment as _cm

        fns = _cm.device_fns()
        padded = _cm.pad_slots(slots)
        if self.hot is None:
            rows = padded
        else:
            rows = np.where(
                padded >= 0, self.hot.logical_of[np.maximum(padded, 0)], 0
            )
        self.stat_commit_updates += 1
        with self._h_commit_update.time():
            self.dev_row_hash, self.dev_digest = self._run(
                fns["update"], self.balances, self.meta,
                self.dev_row_hash, self.dev_digest,
                jnp.asarray(padded), jnp.asarray(rows),
            )

    def _collect_touched(self, recs) -> np.ndarray | None:
        """Union of balance rows a record list can have modified."""
        touched = []
        for rec in recs:
            if rec.kind == "meta":
                touched.append(rec.meta_args[0])
            elif rec.kind in ("waves", "spec") and rec.touched is not None:
                touched.append(rec.touched)
            elif rec.kind in _SEMANTIC_KINDS:
                touched.append(_touched_of_pk(rec.kind, rec.pk, rec.n))
        if not touched:
            return None
        return np.concatenate(touched)

    def commit_probe(self) -> np.ndarray:
        """(2, 2) u64 [maintained digest, from-scratch digest] from
        the device — one dispatch + one 32-byte fetch.  Caller must
        hold the engine drained/flushed."""
        from tigerbeetle_tpu.state_machine import commitment as _cm

        fns = _cm.device_fns()
        return self._retry(
            lambda: self.link.fetch(
                self.link.dispatch(
                    fns["probe"], self.balances, self.meta,
                    self.dev_digest, self._commit_rows(),
                )
            ),
            "fetch",
        )

    def device_root(self) -> np.ndarray:
        """(2,) u64 maintained device digest (16-byte fetch)."""
        return self._retry(
            lambda: self.link.fetch(self.dev_digest), "fetch"
        )

    def _twin_expected_digest(self) -> np.ndarray:
        """What the host twin says the DEVICE digest should be: the
        full root all-resident, the hot partial under tiering (the
        cold partial is the twin's remainder — fold(hot, cold) stays
        the whole-logical-table root)."""
        twin = self.mirror.commitment
        if self.hot is None:
            return twin.digest
        return twin.partial(self.hot.occupied())

    def _localize_divergence(self) -> np.ndarray:
        """THE full-table-fetch path (counted in commit.full_fetches):
        pull both device tables and name the diverged rows vs the
        host's copies — runs only when a 16-byte compare already
        failed (or the TB_DEV_SCRUB_FALLBACK deep-scrub cadence
        forces it)."""
        self.stat_full_fetches += 1
        bal = self._retry(lambda: self.link.fetch(self.balances), "fetch")
        meta = self._retry(lambda: self.link.fetch(self.meta), "fetch")
        if self.hot is not None:
            # Compare hot-shaped tables, report LOGICAL row ids.
            diverged = (bal != self._mirror_hot_table_np()).any(axis=1) | (
                meta != self._meta_hot_np()
            ).any(axis=1)
            hot_rows = np.flatnonzero(diverged)
            return self.hot.logical_of[hot_rows]
        diverged = (bal != self._mirror_table_np()).any(axis=1) | (
            meta != self._meta_host
        ).any(axis=1)
        return np.flatnonzero(diverged)

    def _heal_from_mirror(self) -> None:
        """Re-upload both tables from the host copies (meta first: the
        commitment rebuild inside _upload_from_mirror hashes it)."""
        meta_src = (
            self._meta_hot_np() if self.hot is not None else self._meta_host
        )
        self.meta = self._place(jnp.asarray(meta_src))
        self._upload_from_mirror()

    def drain(self) -> None:
        # A drain nested inside exact recovery (host fallbacks read the
        # table, which drains) must NOT touch the stream: launching the
        # pending window mid-recovery would execute it out of
        # submission order against a table recovery is about to
        # rebuild, and a nested dirty rotation would clobber the
        # _recovering slot.  The outer recovery finishes the stream.
        while (self._launched or self._pending) and not self._recovering:
            try:
                self._rotate()
            except DeviceLostError as exc:
                self._demote(exc)

    def close(self) -> None:
        """End-of-life barrier: every outstanding future resolves (via
        drain, demoting to exact host replay if the link dies) or
        fails with a typed DeviceLostError — a caller blocked in
        result() is never stranded."""
        try:
            self.drain()
            self.flush()
        # tbcheck: allow(broad-except): end-of-life barrier — when even
        # the host replay fails, every stranded future must still be
        # terminated with a typed DeviceLostError (never a hang).
        except Exception as exc:
            for rec in self._recovering + self._launched + self._pending:
                if rec.future is not None and not rec.future.done():
                    rec.future.fail(DeviceLostError("close", exc))
            self._recovering = []
            self._launched = []
            self._pending = []
            self._pending_semantic = 0
            self._inflight_bound = 0
            self._q.clear()
            self._queued = 0
        self._closed = True

    # ------------------------------------------------------------------
    # Degraded-mode lifecycle: demote on fatal link loss, serve exact
    # replies from the host engine against the mirror, probe + re-upload
    # + checksum handshake to re-promote, and a periodic checksum scrub
    # while healthy.

    def _demote(self, exc: BaseException) -> None:
        """Fatal link loss: the host mirror becomes authoritative.
        Every outstanding future resolves IN SUBMISSION ORDER through
        the exact host path — bit-identical to what the device would
        have replied — and later submits route host-side until a
        re-promotion handshake passes."""
        self.state = EngineState.degraded
        self.stat_demotions += 1
        self.tracer.instant("device_demoted", error=repr(exc)[:200])
        self.last_demotion = repr(exc)
        self._degraded_submits = 0
        # The device commitment is as dead as the table it covers; the
        # host twin stays live (mirror mutations keep refreshing it)
        # and re-promotion rebuilds the device side from the upload.
        self.dev_row_hash = None
        self.dev_digest = None
        outstanding = self._recovering + self._launched + self._pending
        # Clear BEFORE replaying: the host path may drain/read this
        # engine re-entrantly, and must see an empty stream.
        self._recovering = []
        self._launched = []
        self._pending = []
        self._pending_semantic = 0
        # Write-behind deltas exist on the mirror already; the device
        # copy is abandoned (re-promotion re-uploads the whole table).
        self._q.clear()
        self._queued = 0
        for rec in outstanding:
            self._replay_record_on_host(rec)

    def _replay_record_on_host(self, rec: _InFlight) -> None:
        fut = rec.future
        if fut is None or fut.done():
            self._release_bound(rec)
            return
        try:
            if rec.kind == "lookup":
                fut.resolve(rec.finish(self.mirror.rows8(rec.slots)))
            else:
                self.stat_degraded_events += rec.n
                fut.resolve(rec.fallback())
        # tbcheck: allow(broad-except): the host replay itself failed —
        # fail THIS future with the real error and keep terminating the
        # rest of the stream (one bad record must not strand the rest).
        except Exception as exc:
            fut.fail(exc)
        finally:
            self._release_bound(rec)

    def tick(self) -> None:
        """Periodic lifecycle work, called once per committed
        operation by the state machine (tpu.commit_async): in degraded
        mode, a health probe + re-promotion attempt every _PROBE_EVERY
        operations; while healthy, the checksum scrub every
        _SCRUB_EVERY ring fetches."""
        if self.state is EngineState.degraded:
            self._degraded_submits += 1
            if self._degraded_submits >= _PROBE_EVERY:
                self._degraded_submits = 0
                self.try_repromote()
            return
        if (
            self._scrub_every
            and self.state is EngineState.healthy
            and self.stat_fetches
            >= self._last_scrub_fetch + self._scrub_every
        ):
            try:
                self.scrub()
            except DeviceLostError as exc:
                self._demote(exc)

    def try_repromote(self) -> bool:
        """Health probe -> table re-upload from the mirror -> checksum
        handshake.  The device becomes authoritative again ONLY if the
        round-tripped digest matches the mirror's; any failure leaves
        the engine degraded (and counted), never half-promoted."""
        if self.state is EngineState.healthy:
            return True
        if self._closed:
            return False
        self.state = EngineState.repromoting
        try:
            self._retry(self.link.probe, "probe")
            self._heal_from_mirror()  # meta first, commitment rebuilt
            self.ring = jnp.zeros((_RING, dk.SUMMARY_WORDS), jnp.uint64)
            self._ring_at = 0
            if self._commit_enabled and self.mirror.commitment is not None:
                # Cheap handshake: the device's freshly-rebuilt 16-byte
                # root vs the incrementally-maintained host twin — no
                # full-table fetch, no host-side full digest pass.
                # Tiered, the device root is the HOT PARTIAL of the
                # logical table, so compare the twin's matching partial.
                dev_sum = self.device_root()
                host_sum = self._twin_expected_digest()
            else:
                dev_sum = self._device_health_digest()
                host_sum = self._host_health_digest()
            if not (dev_sum == host_sum).all():
                raise FatalLinkError(
                    "re-promotion checksum handshake mismatch: "
                    f"device={dev_sum.tolist()} host={host_sum.tolist()}"
                )
        # tbcheck: allow(broad-except): re-promotion is opportunistic —
        # any failure (probe via the classifying _retry, upload, digest
        # handshake) leaves the engine degraded and counted, never
        # half-promoted; the next tick retries.
        except Exception as exc:
            self.state = EngineState.degraded
            self.stat_probe_failures += 1
            self.last_probe_failure = repr(exc)
            return False
        self.state = EngineState.healthy
        self.stat_repromotions += 1
        self.tracer.instant("device_repromoted")
        return True

    def scrub(self) -> bool:
        """Integrity-compare the device tables against the host while
        idle; heal divergence by re-uploading from the mirror.
        Returns True when the tables already matched.  Raises
        DeviceLostError if the link dies mid-scrub (caller demotes).

        Happy path (commitment enabled): ONE dispatch + one 32-byte
        fetch — the device's maintained digest, its from-scratch
        recompute (catches HBM corruption of rows no step touched),
        and the host twin must all agree.  Only a mismatch (or the
        TB_DEV_SCRUB_FALLBACK deep-scrub cadence) pays the full-table
        fetch, which then NAMES the diverged rows before the heal."""
        if (
            self.state is not EngineState.healthy
            or self.has_inflight()
            or self._queued
        ):
            return True
        self._last_scrub_fetch = self.stat_fetches
        self.stat_scrubs += 1
        cheap = (
            self._commit_enabled
            and self.dev_digest is not None
            and self.mirror.commitment is not None
        )
        with self._h_scrub_cost.time():
            if cheap:
                self.stat_scrub_cheap += 1
                with self._h_scrub_cheap.time():
                    pair = self.commit_probe()
                host = self._twin_expected_digest()
                clean = bool(
                    (pair[0] == pair[1]).all() and (pair[1] == host).all()
                )
                deep_every = envcheck.scrub_fallback_every()
                if clean and not (
                    deep_every and self.stat_scrubs % deep_every == 0
                ):
                    return True
            else:
                clean = bool(
                    (
                        self._device_health_digest()
                        == self._host_health_digest()
                    ).all()
                )
                if clean:
                    return True
            # Divergence localization (the demoted full-fetch path) +
            # heal.  A deep scrub that confirms the cheap verdict
            # returns clean without healing.
            self.stat_scrub_fallback += 1
            with self._h_scrub_fallback.time():
                rows = self._localize_divergence()
            if len(rows) == 0:
                if not clean:
                    # Tables match byte-for-byte yet a digest
                    # disagreed: incremental-accumulator drift.  Must
                    # never happen (fuzz-pinned); repaired loudly so a
                    # wedged digest cannot spam heals forever.
                    self.stat_commit_repairs += 1
                    if self.mirror.commitment is not None:
                        self.mirror.commitment.rebuild(self.mirror)
                    self._commit_rebuild()
                return True
            self.tracer.instant("scrub_divergence", rows=int(len(rows)))
            self.stat_scrub_heals += 1
            self._heal_from_mirror()
        return False

    # ------------------------------------------------------------------
    # Write-behind lane (host exact path) — kernel_fast.DeviceTable API.

    def enqueue(self, slots, cols, add_lo, add_hi,
                refresh_twin: bool = True) -> None:
        if len(slots) == 0:
            return
        # The native fast path mutates the shared mirror arrays in
        # place (its commits don't pass through BalanceMirror methods)
        # but ALWAYS feeds its deltas through here — bump the mutation
        # stamp so the degraded-read cache can never serve stale rows
        # (including suppressed re-execution enqueues, whose mirror
        # mutation already happened natively), and fold the touched
        # rows into the host commitment twin for the same reason.
        # Callers whose deltas came through the mirror's own Python
        # methods (whose _touch already refreshed the twin) pass
        # refresh_twin=False to skip the duplicate hashing.
        self.mirror.version += 1
        if refresh_twin and self.mirror.commitment is not None:
            self.mirror.commitment.refresh(
                np.asarray(slots, np.int64), self.mirror
            )
        if self._suppress_enqueue:
            return
        if self.state is not EngineState.healthy:
            # Degraded: the mirror (already updated by the host path)
            # is authoritative; re-promotion re-uploads the full table.
            return
        # Exact-path deltas only arrive after a drain (the host path
        # drains before running), so they can never overtake queued
        # semantic batches.
        assert self._pending_semantic == 0 and not self._launched, (
            "write-behind enqueue with in-flight semantic batches"
        )
        self._q.append(
            (
                np.asarray(slots, np.int64),
                np.asarray(cols, np.int64),
                np.asarray(add_lo, np.uint64),
                np.asarray(add_hi, np.uint64),
            )
        )
        self._queued += len(slots)

    def flush(self) -> None:
        if not self._queued:
            return
        if self.state is not EngineState.healthy:
            self._q.clear()
            self._queued = 0
            return
        try:
            self._flush_inner()
        except DeviceLostError as exc:
            self._demote(exc)

    def _flush_inner(self) -> None:
        from tigerbeetle_tpu.state_machine.mirror import compact_deltas

        slots = np.concatenate([e[0] for e in self._q])
        cols = np.concatenate([e[1] for e in self._q])
        a_lo = np.concatenate([e[2] for e in self._q])
        a_hi = np.concatenate([e[3] for e in self._q])
        self._q.clear()
        self._queued = 0
        chunk = (1 << 21) - 1
        if len(slots) > chunk:
            parts = [
                compact_deltas(
                    slots[i : i + chunk], cols[i : i + chunk],
                    a_lo[i : i + chunk], a_hi[i : i + chunk],
                )
                for i in range(0, len(slots), chunk)
            ]
            slots = np.concatenate([p[0] for p in parts])
            cols = np.concatenate([p[1] for p in parts])
            a_lo = np.concatenate([p[2] for p in parts])
            a_hi = np.concatenate([p[3] for p in parts])
        u_slot, u_col, d_lo, d_hi, _ = compact_deltas(slots, cols, a_lo, a_hi)
        if self.hot is not None:
            # Exact-path deltas arrive with LOGICAL slots; the device
            # table is hot-shaped.  Cold rows keep their deltas in the
            # mirror only (it already leads for host-resolved batches);
            # they upload whole on admission.
            h = self.hot.hot_of[u_slot]
            keep = h >= 0
            u_slot, u_col = h[keep], u_col[keep]
            d_lo, d_hi = d_lo[keep], d_hi[keep]
        at = 0
        CH = 32_768
        while at < len(u_slot):
            take = min(len(u_slot) - at, CH)
            packed = np.empty((4, CH), np.uint64)
            packed[0, :take] = u_slot[at : at + take].astype(np.uint64)
            packed[0, take:] = self.capacity + np.arange(
                CH - take, dtype=np.uint64
            )
            packed[1, :take] = u_col[at : at + take].astype(np.uint64)
            packed[1, take:] = 0
            packed[2, :take] = d_lo[at : at + take]
            packed[2, take:] = 0
            packed[3, :take] = d_hi[at : at + take]
            packed[3, take:] = 0
            self.balances = self._run(
                dk.apply_deltas, self.balances, jnp.asarray(packed)
            )
            at += take
        # Flushed deltas must land before any later queued meta/lookup
        # records are dispatched — but those only dispatch at the next
        # launch, which follows this flush in program order.
        self._commit_update(u_slot)

    def read(self):
        """Drain barrier + table handle (DeviceTable API compat).  In
        degraded mode the authoritative bytes live in the host mirror;
        callers get a default-backend array built from it (NOT routed
        through the possibly-dead link).  During exact recovery the
        mirror is likewise the truth — it reflects exactly the stream
        prefix before the batch being re-executed, while the device
        table still holds the whole window's kernel effects."""
        if self._recovering:
            return self._degraded_table()
        self.drain()
        self.flush()
        if self.state is not EngineState.healthy:
            return self._degraded_table()
        if self.hot is not None:
            # Tiered: the device holds only hot rows; the full LOGICAL
            # table comes from the mirror, which the drain above made
            # current for every finished batch.
            return self._degraded_table()
        return self.balances

    def write_back(self, value) -> None:
        """Replace the device table from a full LOGICAL table image
        (the owning machine's `_balances` setter).  Tiered, the hot
        rows are gathered out of it and the digest rebuilt — the
        mirror (which the caller updates through the same code path)
        stays the cold-tier authority."""
        if self.hot is None:
            self.balances = value
            return
        lof = self.hot.logical_of
        img = np.asarray(jax.device_get(value))
        hot_np = np.zeros((len(lof), 8), np.uint64)
        occ = np.flatnonzero(lof >= 0)
        hot_np[occ] = img[lof[occ]]
        try:
            self.balances = self._place(jnp.asarray(hot_np))
            self._commit_rebuild()
        except DeviceLostError as exc:
            self._demote(exc)

    def checksum(self) -> np.ndarray:
        """Authoritative-table digest (drained + flushed first): the
        device table while healthy, the mirror (computed host-side,
        no device work at all) while degraded.  Tiered, the digest
        covers the LOGICAL table, so it always comes from the mirror —
        the drain just guaranteed it is current."""
        self.drain()
        self.flush()
        if self.state is not EngineState.healthy or self.hot is not None:
            return self.mirror.checksum8(self.capacity)
        try:
            return self._device_checksum()
        except DeviceLostError as exc:
            self._demote(exc)
            return self.mirror.checksum8(self.capacity)
