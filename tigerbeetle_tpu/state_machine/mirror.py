"""Host-side exact mirror of the account-balance table.

The device (HBM) table is the authoritative balance store, but a
round-trip to it costs ~wire latency, so the commit hot path must never
wait on the device. The host keeps a bit-exact mirror of the four u128
balance columns and uses it for:

- fast-path admission: the monotone-overflow check (see
  kernel_fast.py) runs against the mirror, so no device sync is needed
  to decide fast vs exact-scan routing;
- serving lookup/query balance reads without draining the device queue.

The mirror is maintained by the same deltas the device applies, in the
same commit order, so mirror == device table at every flush boundary
(tests assert this via the device-reading debug API).

Columns are (A, 4) uint64 limb pairs: dp, dpo, cp, cpo — matching the
device layout in kernel.py (reference balance fields:
src/tigerbeetle.zig:8-12).
"""

from __future__ import annotations

import numpy as np

_MASK32 = np.uint64(0xFFFFFFFF)


def _add_u128(a_lo, a_hi, b_lo, b_hi):
    """Vectorized (a + b) mod 2^128 plus overflow flag."""
    lo = a_lo + b_lo
    carry = (lo < a_lo).astype(np.uint64)
    hi_partial = a_hi + b_hi
    ov1 = hi_partial < a_hi
    hi = hi_partial + carry
    ov2 = hi < hi_partial
    return lo, hi, ov1 | ov2


def _sub_u128(a_lo, a_hi, b_lo, b_hi):
    """Vectorized (a - b) mod 2^128 plus borrow flag."""
    lo = a_lo - b_lo
    borrow = (a_lo < b_lo).astype(np.uint64)
    hi = a_hi - b_hi - borrow
    under = (a_hi < b_hi) | ((a_hi == b_hi) & (borrow == 1))
    return lo, hi, under


def digest_columns(table):
    """Order-sensitive digest of a (rows, C) unsigned table: per-column
    u64 sums plus golden-ratio row-mixed sums, 2C words total — the
    same family as device_kernels.checksum.  ONE implementation feeds
    every integrity compare (checkpoint parity, healthy-mode scrub,
    re-promotion handshake, account-meta digest) so the formula cannot
    drift between the host and device sides.  Works on numpy and jnp
    arrays alike (the latter lets the device compute its own digest so
    only 2C words cross the link)."""
    if isinstance(table, np.ndarray):
        xp = np
    else:
        import jax.numpy as xp
    m = table.astype(xp.uint64)
    col_sums = m.sum(axis=0, dtype=xp.uint64)
    rows = xp.arange(m.shape[0], dtype=xp.uint64)[:, None]
    mixed = (
        m * (rows * xp.uint64(0x9E3779B97F4A7C15) + xp.uint64(1))
    ).sum(axis=0, dtype=xp.uint64)
    return xp.concatenate([col_sums, mixed])


def compact_deltas(slots, cols, amt_lo, amt_hi):
    """Group (slot, col, amount) contributions into exact u128 sums.

    Returns (uniq_slots, uniq_cols, sum_lo, sum_hi, limb_overflow).
    Amounts are accumulated as 4x32-bit limbs in uint64 lanes: each
    limb sum stays < 2^32 * count, so scatter-adds cannot wrap for any
    realistic batch, and one carry pass recombines exact sums.
    """
    assert len(slots) < 1 << 21, "limb sums must stay exact in float64"
    key = slots.astype(np.int64) * 4 + cols.astype(np.int64)
    uniq, inv = np.unique(key, return_inverse=True)
    # Exact limb sums via float64 bincount: each 32-bit limb summed
    # over <= 2^21 entries stays < 2^53, so float64 is exact.
    k = len(uniq)
    c0 = np.bincount(inv, (amt_lo & _MASK32).astype(np.float64), k).astype(np.uint64)
    c1_ = np.bincount(inv, (amt_lo >> np.uint64(32)).astype(np.float64), k).astype(
        np.uint64
    )
    c2_ = np.bincount(inv, (amt_hi & _MASK32).astype(np.float64), k).astype(np.uint64)
    c3_ = np.bincount(inv, (amt_hi >> np.uint64(32)).astype(np.float64), k).astype(
        np.uint64
    )
    c1 = c1_ + (c0 >> np.uint64(32))
    c2 = c2_ + (c1 >> np.uint64(32))
    c3 = c3_ + (c2 >> np.uint64(32))
    lo = (c0 & _MASK32) | ((c1 & _MASK32) << np.uint64(32))
    hi = (c2 & _MASK32) | ((c3 & _MASK32) << np.uint64(32))
    overflow = (c3 >> np.uint64(32)) != 0
    return (uniq // 4).astype(np.int64), (uniq % 4).astype(np.int64), lo, hi, overflow


class BalanceMirror:
    """Exact host copy of the (A, 4)-column u128 balance table.

    ``version`` is a cheap monotonic mutation stamp: every mutating
    method bumps it (the native fast path mutates lo/hi in place, so
    DeviceEngine.enqueue — which every native commit feeds — bumps it
    too).  Consumers use it as a cache key, e.g. the degraded-mode
    read() table (device_engine.py) that would otherwise rebuild a
    (capacity, 8) array per call.
    """

    def __init__(self, capacity: int) -> None:
        self.lo = np.zeros((capacity, 4), np.uint64)
        self.hi = np.zeros((capacity, 4), np.uint64)
        self.version = 0
        # Optional incremental state commitment (commitment.py): when
        # attached, every mutating method re-hashes exactly the rows
        # it touched, so the 16-byte state root is always current
        # without a full-table pass.  None = disabled (TB_STATE_COMMIT
        # =0), zero overhead.
        self.commitment = None

    def _touch(self, slots) -> None:
        if self.commitment is not None:
            self.commitment.refresh(slots, self)

    def grow(self, capacity: int) -> None:
        if capacity <= len(self.lo):
            return
        from tigerbeetle_tpu.state_machine.hot_tier import grow_zero_host

        self.lo = grow_zero_host(self.lo, capacity)
        self.hi = grow_zero_host(self.hi, capacity)
        self.version += 1
        # All-zero rows hash to 0: growth never moves the root (the
        # twin widens its per-row hash store lazily on next refresh).

    def rows8(self, slots: np.ndarray) -> np.ndarray:
        """(k, 8) interleaved rows matching the device layout."""
        out = np.empty((len(slots), 8), np.uint64)
        out[:, 0::2] = self.lo[slots]
        out[:, 1::2] = self.hi[slots]
        return out

    def table8(self, capacity: int) -> np.ndarray:
        """Full (capacity, 8) device-layout table (zero-padded past the
        mirror's rows) — the re-upload image for demoted engines."""
        table = np.zeros((capacity, 8), np.uint64)
        n = min(len(self.lo), capacity)
        table[:n, 0::2] = self.lo[:n]
        table[:n, 1::2] = self.hi[:n]
        return table

    def checksum8(self, capacity: int) -> np.ndarray:
        """Host-side digest of the first `capacity` rows in device
        layout, matching device_kernels.checksum word-for-word.  Used
        by the checkpoint parity tripwire, the healthy-mode scrub, and
        the re-promotion handshake."""
        return digest_columns(self.table8(capacity))

    def set_rows8(self, slots: np.ndarray, rows: np.ndarray) -> None:
        """Overwrite rows from (k, 8) device-layout snapshots.

        Duplicate slots resolve to the LAST occurrence (commit order).
        """
        rev = slots[::-1]
        uniq, first = np.unique(rev, return_index=True)
        pick = len(slots) - 1 - first
        self.lo[uniq] = rows[pick][:, 0::2]
        self.hi[uniq] = rows[pick][:, 1::2]
        self.version += 1
        self._touch(uniq)

    def try_apply_adds(
        self, dr_slot, cr_slot, amt_lo, amt_hi, is_pending, mask,
        commit: bool = True,
    ):
        """Fast-path admission + commit.

        Applies non-negative balance additions (pending -> dp/cp,
        posted -> dpo/cpo) iff no touched account's final column sum or
        combined debit/credit total overflows u128. Returns the compact
        (slot, col, delta_lo, delta_hi) arrays to enqueue to the device
        when committed, or None — meaning the caller must take the
        exact scan path (reference overflow codes:
        src/state_machine.zig:1531-1545).

        With commit=False this is a pure admission dry-run: nothing is
        mutated; a non-None return proves that applying ANY SUBSET of
        the masked additions cannot overflow (deltas are non-negative,
        so every prefix state is bounded by the all-applied state) —
        the superset guarantee the linked-batch resolver relies on.
        """
        m = mask
        if not m.any():
            z = np.zeros(0, np.int64)
            return (z, z.copy(), np.zeros(0, np.uint64), np.zeros(0, np.uint64))
        if not m.all():
            dr_slot, cr_slot = dr_slot[m], cr_slot[m]
            amt_lo, amt_hi = amt_lo[m], amt_hi[m]
            is_pending = is_pending[m]

        # Dense limb accumulation via float64 bincount (exact: limbs
        # < 2^32, sums < events * 2^32 << 2^53) — no sort, no concat.
        top = int(max(dr_slot.max(), cr_slot.max())) + 1
        K = top * 4
        idx_dr = dr_slot * 4 + np.where(is_pending, 0, 1)
        idx_cr = cr_slot * 4 + np.where(is_pending, 2, 3)
        mask32 = np.uint64(0xFFFFFFFF)
        acc = np.empty((4, K))
        for i, limb in enumerate(
            (amt_lo & mask32, amt_lo >> np.uint64(32),
             amt_hi & mask32, amt_hi >> np.uint64(32))
        ):
            w = limb.astype(np.float64)
            acc[i] = np.bincount(idx_dr, weights=w, minlength=K)
            acc[i] += np.bincount(idx_cr, weights=w, minlength=K)

        touched_idx = np.flatnonzero(acc.any(axis=0))
        u_slot = (touched_idx >> 2).astype(np.int64)
        u_col = (touched_idx & 3).astype(np.int64)
        limbs = acc[:, touched_idx].astype(np.uint64)
        c0 = limbs[0]
        c1 = limbs[1] + (c0 >> np.uint64(32))
        c2 = limbs[2] + (c1 >> np.uint64(32))
        c3 = limbs[3] + (c2 >> np.uint64(32))
        d_lo = (c0 & mask32) | ((c1 & mask32) << np.uint64(32))
        d_hi = (c2 & mask32) | ((c3 & mask32) << np.uint64(32))
        if ((c3 >> np.uint64(32)) != 0).any():
            return None  # column delta alone exceeds u128
        if not self._admit_commit(u_slot, u_col, d_lo, d_hi, commit):
            return None
        return (u_slot, u_col, d_lo, d_hi)

    def _admit_commit(self, u_slot, u_col, d_lo, d_hi, commit: bool) -> bool:
        """Shared admission tail: per-column u128 overflow + combined
        dp+dpo / cp+cpo totals of every touched account, checked
        against the all-applied upper bound; mutates only when BOTH
        pass and commit=True."""
        old_lo = self.lo[u_slot, u_col]
        old_hi = self.hi[u_slot, u_col]
        new_lo, new_hi, add_ov = _add_u128(old_lo, old_hi, d_lo, d_hi)
        if add_ov.any():
            return False
        touched = np.unique(u_slot)
        cand_lo = self.lo[touched].copy()
        cand_hi = self.hi[touched].copy()
        pos = np.searchsorted(touched, u_slot)
        cand_lo[pos, u_col] = new_lo
        cand_hi[pos, u_col] = new_hi
        _, _, dr_tot_ov = _add_u128(
            cand_lo[:, 0], cand_hi[:, 0], cand_lo[:, 1], cand_hi[:, 1]
        )
        _, _, cr_tot_ov = _add_u128(
            cand_lo[:, 2], cand_hi[:, 2], cand_lo[:, 3], cand_hi[:, 3]
        )
        if dr_tot_ov.any() or cr_tot_ov.any():
            return False
        if commit:
            self.lo[u_slot, u_col] = new_lo
            self.hi[u_slot, u_col] = new_hi
            self.version += 1
            self._touch(touched)
        return True

    def try_apply_deltas(self, slots, cols, amt_lo, amt_hi):
        """General checked addition over explicit (slot, col) targets
        (the two-phase resolver's mixed dp/dpo/cp/cpo adds).  Same
        admission rules as try_apply_adds, checked BEFORE any
        mutation.  Returns compact device deltas or None (caller falls
        back to the exact path, mirror untouched)."""
        if len(slots) == 0:
            z = np.zeros(0, np.int64)
            return (z, z.copy(), np.zeros(0, np.uint64), np.zeros(0, np.uint64))
        u_slot, u_col, d_lo, d_hi, limb_ov = compact_deltas(
            np.asarray(slots, np.int64), np.asarray(cols, np.int64),
            amt_lo, amt_hi,
        )
        if limb_ov.any():
            return None
        if not self._admit_commit(u_slot, u_col, d_lo, d_hi, True):
            return None
        return (u_slot, u_col, d_lo, d_hi)

    def apply_subs(self, slots, cols, amt_lo, amt_hi) -> None:
        """Release amounts (pending expiry): column -= amount, exact."""
        u_slot, u_col, d_lo, d_hi, limb_ov = compact_deltas(
            slots, cols, amt_lo, amt_hi
        )
        assert not limb_ov.any()
        new_lo, new_hi, under = _sub_u128(
            self.lo[u_slot, u_col], self.hi[u_slot, u_col], d_lo, d_hi
        )
        assert not under.any(), "pending release underflow"
        self.lo[u_slot, u_col] = new_lo
        self.hi[u_slot, u_col] = new_hi
        self.version += 1
        self._touch(u_slot)
