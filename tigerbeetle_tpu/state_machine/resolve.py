"""Vectorized resolution of order-dependent create_transfers batches.

Round 2 ran every linked/two-phase batch through the serial exact
engine — correct, but the TPU sat idle on 2 of 5 graded workloads.
This module closes that gap by exploiting the *structure* of the order
dependence instead of serializing around it:

- **Two-phase (post/void) batches** are order-dependent only through
  *references* (a post must see the pending created earlier in the
  batch; two posts racing for one pending resolve first-wins).  With
  no balance limits in play, verdicts never depend on balances at all,
  so the whole batch resolves in closed form: vectorized ladder +
  winner-per-target reduction.  Balance effects are then plain
  scatter-adds (pending adds, finalize releases, posted adds).

- **Linked-chain batches with balance-limit accounts** are
  order-dependent through *balances*: whether event i trips
  `debits_must_not_exceed_credits` depends on which earlier events
  applied, and a failing member rolls back its whole chain.  The
  verdicts form a prefix-closed dependency (event i depends only on
  events < i), so a Jacobi fixpoint over per-account segmented prefix
  sums converges to the exact sequential answer: each iteration
  recomputes every event's limit check from the previous iteration's
  pass/fail guesses, and any fixpoint of the iteration is THE
  sequential outcome (verdict of event 0 is unconditional; inductively
  verdict i is correct once 0..i-1 are).  Iterations needed = depth of
  actual failure interaction, typically a handful.

Both resolvers are exact: every result code, rollback, and balance
effect matches the reference semantics (reference:
src/state_machine.zig:1220-1306 execute, :1462-1741 create_transfer +
post/void) bit-for-bit, enforced by differential fuzz vs the CPU
oracle in tests/test_resolve.py.

The caller (tpu.py) routes a batch here only when the preconditions
hold (see _route notes there); a None return means "not resolvable
here" and falls through to the serial exact engine — never a wrong
answer, only a slower one.
"""

from __future__ import annotations

import numpy as np

from tigerbeetle_tpu.types import (
    AccountFlags,
    CreateTransferResult,
    TransferFlags,
)

AF = AccountFlags
TF = TransferFlags
CTR = CreateTransferResult

_LIM = np.uint32(
    AF.debits_must_not_exceed_credits | AF.credits_must_not_exceed_debits
)

# Pending statuses (reference: src/tigerbeetle.zig:113-125).
S_NONE, S_PENDING, S_POSTED, S_VOIDED, S_EXPIRED = 0, 1, 2, 3, 4

# Bound under which all limit arithmetic provably fits in uint64:
# every initial balance component and the batch amount total must stay
# below 2^61, so dp+dpo+running+amount < 4*2^61 < 2^64.
_U64_SAFE = np.uint64(1) << np.uint64(61)


def _exclusive_prefix(values: np.ndarray) -> np.ndarray:
    """[0, v0, v0+v1, ...] — prefix sums excluding the element itself."""
    out = np.empty(len(values) + 1, values.dtype)
    out[0] = 0
    np.cumsum(values, out=out[1:])
    return out


def linked_resolve(
    static: np.ndarray,
    ts_nonzero: np.ndarray,
    flags: np.ndarray,
    dr_slot: np.ndarray,
    cr_slot: np.ndarray,
    amount_lo: np.ndarray,
    amount_hi: np.ndarray,
    dr_flags: np.ndarray,
    cr_flags: np.ndarray,
    mirror,
    max_iters: int = 64,
):
    """Exact verdicts for a linked-chain batch of plain posted transfers.

    Preconditions (checked by the router in tpu.py): no pending /
    post/void / balancing flags anywhere in the batch, ids unique with
    no durable duplicates, no history-flag accounts.  Limit-flag
    accounts ARE allowed — they're the point.

    Returns (results, last_applied, iterations) or None when the batch
    needs the serial exact engine (u128-scale balances, or fixpoint
    cap exceeded).

    reference: src/state_machine.zig:1220-1306 (chain/rollback loop),
    src/tigerbeetle.zig:31-39 (limit formulas).
    """
    n = len(static)
    assert n > 0
    if amount_hi.any():
        return None

    # --- chain structure (chains are contiguous: a chain is a maximal
    # run of linked-flag events plus the first non-linked event after).
    linked = (flags & np.uint32(TF.linked)) != 0
    start = np.empty(n, bool)
    start[0] = True
    if n > 1:
        start[1:] = ~linked[:-1]
    chain_id = np.cumsum(start) - 1
    chain_start_ev = np.flatnonzero(start)
    chain_last_ev = np.append(chain_start_ev[1:] - 1, n - 1)
    start_of_ev = chain_start_ev[chain_id]

    # Per-event unconditional codes.  Precedence: chain_open (last
    # event only) > timestamp_must_be_zero > static ladder
    # (reference: src/state_machine.zig:1236-1256).
    code0 = np.where(
        ts_nonzero, np.uint32(CTR.timestamp_must_be_zero), static
    ).astype(np.uint32)
    if linked[n - 1]:
        code0[n - 1] = np.uint32(CTR.linked_event_chain_open)
    static_ok = code0 == 0

    # --- limit-check entry lists.  Running balance sums are needed
    # only at accounts carrying a limit flag; events that already
    # failed statically never contribute or view.
    dlim = (dr_flags & np.uint32(AF.debits_must_not_exceed_credits)) != 0
    clim = (cr_flags & np.uint32(AF.credits_must_not_exceed_debits)) != 0
    ent_d = static_ok & ((dr_flags & _LIM) != 0)
    ent_c = static_ok & ((cr_flags & _LIM) != 0)

    ev_d = np.flatnonzero(ent_d)
    ev_c = np.flatnonzero(ent_c)
    n_d = len(ev_d)
    evs = np.concatenate([ev_d, ev_c])
    m = len(evs)

    dr_fail = np.zeros(n, bool)
    cr_fail = np.zeros(n, bool)
    iterations = 0

    if m:
        eslot = np.concatenate([dr_slot[ev_d], cr_slot[ev_c]]).astype(np.int64)
        # uint64-exactness precondition on every touched limited slot.
        lim_slots = np.unique(eslot)
        if mirror.hi[lim_slots].any():
            return None
        if (mirror.lo[lim_slots] >= _U64_SAFE).any():
            return None
        contrib = amount_lo[static_ok]
        if float(contrib.astype(np.float64).sum()) >= float(_U64_SAFE):
            return None

        eamt = np.concatenate([amount_lo[ev_d], amount_lo[ev_c]])
        edeb = np.zeros(m, bool)
        edeb[:n_d] = True
        # (slot, event) sort; keys unique (dr==cr events fail
        # accounts_must_be_different statically, so never enter).
        key = (eslot << np.int64(32)) | evs.astype(np.int64)
        order = np.argsort(key)
        evs, eslot, eamt, edeb, key = (
            evs[order], eslot[order], eamt[order], edeb[order], key[order]
        )
        seg_new = np.empty(m, bool)
        seg_new[0] = True
        seg_new[1:] = eslot[1:] != eslot[:-1]
        seg_first = np.maximum.accumulate(np.where(seg_new, np.arange(m), 0))
        # Boundary position splitting "earlier chains" from "my chain".
        bkey = (eslot << np.int64(32)) | start_of_ev[evs].astype(np.int64)
        bpos = np.searchsorted(key, bkey, side="left")
        jpos = np.arange(m)

        init_dp = mirror.lo[eslot, 0]
        init_dpo = mirror.lo[eslot, 1]
        init_cp = mirror.lo[eslot, 2]
        init_cpo = mirror.lo[eslot, 3]
        view_d = edeb & dlim[evs]
        view_c = ~edeb & clim[evs]
        amt_d = np.where(edeb, eamt, np.uint64(0))
        amt_c = np.where(edeb, np.uint64(0), eamt)

        pass_prev = static_ok.copy()
        fails = ~pass_prev
        F = np.cumsum(fails)
        base = (F - fails)[chain_start_ev]
        applied_prefix = (F - base[chain_id]) == 0
        chain_ok = applied_prefix[chain_last_ev]

        for iterations in range(1, max_iters + 1):
            wce = chain_ok[chain_id][evs]
            wie = applied_prefix[evs]
            Pdc = _exclusive_prefix(np.where(wce, amt_d, np.uint64(0)))
            Pcc = _exclusive_prefix(np.where(wce, amt_c, np.uint64(0)))
            Pdi = _exclusive_prefix(np.where(wie, amt_d, np.uint64(0)))
            Pci = _exclusive_prefix(np.where(wie, amt_c, np.uint64(0)))
            deb_before = (Pdc[bpos] - Pdc[seg_first]) + (Pdi[jpos] - Pdi[bpos])
            cred_before = (Pcc[bpos] - Pcc[seg_first]) + (Pci[jpos] - Pci[bpos])

            # reference: src/tigerbeetle.zig:31-39 — dp+dpo+amount
            # must not exceed cpo (debit side), cp+cpo+amount must not
            # exceed dpo (credit side).  All terms < 2^61 by the
            # precondition, so uint64 arithmetic is exact.
            bad_d = view_d & (
                init_dp + init_dpo + deb_before + eamt
                > init_cpo + cred_before
            )
            bad_c = view_c & (
                init_cp + init_cpo + cred_before + eamt
                > init_dpo + deb_before
            )
            dr_fail[:] = False
            cr_fail[:] = False
            dr_fail[evs[bad_d]] = True
            cr_fail[evs[bad_c]] = True
            pass_ = static_ok & ~dr_fail & ~cr_fail

            fails = ~pass_
            F = np.cumsum(fails)
            base = (F - fails)[chain_start_ev]
            applied_prefix = (F - base[chain_id]) == 0
            chain_ok = applied_prefix[chain_last_ev]
            if (pass_ == pass_prev).all():
                break
            pass_prev = pass_
        else:
            return None  # fixpoint cap exceeded — serial engine decides
        pass_ = pass_prev
    else:
        # No limit accounts touched: verdicts are purely static.
        pass_ = static_ok
        fails = ~pass_
        F = np.cumsum(fails)
        base = (F - fails)[chain_start_ev]
        applied_prefix = (F - base[chain_id]) == 0
        chain_ok = applied_prefix[chain_last_ev]

    # --- result codes.  Within a failed chain, the FIRST failing
    # member carries its own code; everyone else gets
    # linked_event_failed; chain_open sticks to the last batch event
    # even when the chain broke earlier (reference:
    # src/state_machine.zig:1240-1248,1276-1284).
    results = np.zeros(n, np.uint32)
    bad_chain = ~chain_ok
    if bad_chain.any():
        member_bad = bad_chain[chain_id]
        fail_pos = np.where(~pass_, np.arange(n), n)
        first_fail = np.minimum.reduceat(fail_pos, chain_start_ev)
        ff = first_fail[bad_chain]
        assert (ff < n).all()
        results[member_bad] = np.uint32(CTR.linked_event_failed)
        own = np.where(
            code0[ff] != 0,
            code0[ff],
            np.where(
                dr_fail[ff],
                np.uint32(CTR.exceeds_credits),
                np.uint32(CTR.exceeds_debits),
            ),
        )
        results[ff] = own
        if linked[n - 1]:
            results[n - 1] = np.uint32(CTR.linked_event_chain_open)

    applied_any = np.flatnonzero(applied_prefix)
    last_applied = int(applied_any[-1]) if len(applied_any) else -1
    return results, last_applied, iterations


def _u128_gt(a_lo, a_hi, b_lo, b_hi):
    return (a_hi > b_hi) | ((a_hi == b_hi) & (a_lo > b_lo))


def two_phase_resolve(
    static: np.ndarray,
    ts_nonzero: np.ndarray,
    flags: np.ndarray,
    is_pv: np.ndarray,
    # raw event fields
    dr_lo, dr_hi, cr_lo, cr_hi,
    amount_lo, amount_hi,
    ud128_lo, ud128_hi, ud64, ud32,
    ledger, code,
    # in-batch pending-target resolution
    tgt_ev: np.ndarray,      # event index creating the referenced id, -1
    # durable pending-target join (full-n arrays from gather_p)
    p_found: np.ndarray,
    p_tgt: np.ndarray,       # unique durable-target index per event, -1
    p_join: dict,            # gathered columns of the durable target
    dstat_init: np.ndarray,  # status per unique durable target
    attrs,                   # account attribute columns (id lookup)
):
    """Closed-form verdicts for a two-phase batch.

    Preconditions (router): no linked / balancing flags, ids unique
    with no durable duplicates, all event timeouts zero, durable
    targets have timeout zero, in-batch targets carry the pending
    flag, and no touched account (including durable targets' accounts)
    has limit or history flags.  Under those, no verdict depends on
    balance state, so one vectorized pass is exact — the only
    inter-event couplings are "pending must exist before me" (an index
    compare) and "first finalizer wins" (a min-reduce per target).

    Returns None if an unsupported shape sneaks through, else a dict
    with results, resolved pv fields, winner bookkeeping.

    reference: src/state_machine.zig:1608-1741 post_or_void.
    """
    n = len(static)
    pend_flag = (flags & np.uint32(TF.pending)) != 0

    code_out = np.where(
        ts_nonzero, np.uint32(CTR.timestamp_must_be_zero), static
    ).astype(np.uint32)

    # --- pv ladder beyond the static prefix.
    pv = is_pv & (code_out == 0)
    idx = np.arange(n)
    in_batch = pv & (tgt_ev >= 0) & (tgt_ev < idx)
    # In-batch target must itself have been created: pending creates
    # succeed iff their own unconditional code is zero.
    tgt_c = np.clip(tgt_ev, 0, None)
    tgt_created = in_batch & (code_out[tgt_c] == 0)
    durable = pv & p_found & ~in_batch
    found = tgt_created | durable
    _apply(code_out, pv & ~found, CTR.pending_transfer_not_found)

    # not_pending: durable target without the pending flag.  (In-batch
    # non-pending targets are excluded by the router.)
    p_flags = np.where(
        in_batch, flags[tgt_c], p_join["flags"].astype(np.uint32)
    )
    _apply(
        code_out,
        found & ((p_flags & np.uint32(TF.pending)) == 0),
        CTR.pending_transfer_not_pending,
    )

    # Unified target fields (in-batch event columns or durable join).
    def pick(batch_col, join_col):
        return np.where(in_batch, batch_col[tgt_c], join_col)

    pj_dr = np.clip(p_join["dr_slot"].astype(np.int64), 0, None)
    pj_cr = np.clip(p_join["cr_slot"].astype(np.int64), 0, None)
    p_dr_lo = pick(dr_lo, attrs["id_lo"][pj_dr])
    p_dr_hi = pick(dr_hi, attrs["id_hi"][pj_dr])
    p_cr_lo = pick(cr_lo, attrs["id_lo"][pj_cr])
    p_cr_hi = pick(cr_hi, attrs["id_hi"][pj_cr])
    p_amt_lo = pick(amount_lo, p_join["amount_lo"].astype(np.uint64))
    p_amt_hi = pick(amount_hi, p_join["amount_hi"].astype(np.uint64))
    p_ledger = pick(ledger.astype(np.uint32), p_join["ledger"].astype(np.uint32))
    p_code = pick(code, p_join["code"].astype(np.uint32))
    p_ud128_lo = pick(ud128_lo, p_join["ud128_lo"].astype(np.uint64))
    p_ud128_hi = pick(ud128_hi, p_join["ud128_hi"].astype(np.uint64))
    p_ud64 = pick(ud64, p_join["ud64"].astype(np.uint64))
    p_ud32 = pick(ud32, p_join["ud32"].astype(np.uint32))

    # Mismatch ladder (reference: src/state_machine.zig:1647-1664).
    t_dr_set = (dr_lo != 0) | (dr_hi != 0)
    t_cr_set = (cr_lo != 0) | (cr_hi != 0)
    _apply(
        code_out,
        found & t_dr_set & ((dr_lo != p_dr_lo) | (dr_hi != p_dr_hi)),
        CTR.pending_transfer_has_different_debit_account_id,
    )
    _apply(
        code_out,
        found & t_cr_set & ((cr_lo != p_cr_lo) | (cr_hi != p_cr_hi)),
        CTR.pending_transfer_has_different_credit_account_id,
    )
    _apply(
        code_out,
        found & (ledger > 0) & (ledger.astype(np.uint32) != p_ledger),
        CTR.pending_transfer_has_different_ledger,
    )
    _apply(
        code_out,
        found & (code > 0) & (code != p_code),
        CTR.pending_transfer_has_different_code,
    )

    # Amount resolution: zero means inherit (reference: :1666-1671).
    t_amt_set = (amount_lo != 0) | (amount_hi != 0)
    res_amt_lo = np.where(t_amt_set, amount_lo, p_amt_lo)
    res_amt_hi = np.where(t_amt_set, amount_hi, p_amt_hi)
    _apply(
        code_out,
        found & _u128_gt(res_amt_lo, res_amt_hi, p_amt_lo, p_amt_hi),
        CTR.exceeds_pending_transfer_amount,
    )
    void = (flags & np.uint32(TF.void_pending_transfer)) != 0
    _apply(
        code_out,
        found & void & _u128_gt(p_amt_lo, p_amt_hi, res_amt_lo, res_amt_hi),
        CTR.pending_transfer_has_different_amount,
    )

    # Durable targets whose status is already final fail every
    # referencing event with the status code (reference: :1673-1683).
    if len(dstat_init):
        dstat_ev = np.where(
            durable & (p_tgt >= 0), dstat_init[np.clip(p_tgt, 0, None)],
            np.uint32(S_PENDING),
        )
    else:
        dstat_ev = np.full(n, np.uint32(S_PENDING))
    _apply(code_out, durable & (dstat_ev == S_POSTED),
           CTR.pending_transfer_already_posted)
    _apply(code_out, durable & (dstat_ev == S_VOIDED),
           CTR.pending_transfer_already_voided)
    _apply(code_out, durable & (dstat_ev == S_EXPIRED),
           CTR.pending_transfer_expired)

    # --- winner per target: among candidates that passed everything
    # above, the lowest event index finalizes; later ones fail with
    # the winner's status code.
    cand = pv & (code_out == 0)
    post = (flags & np.uint32(TF.post_pending_transfer)) != 0
    winner = np.zeros(n, bool)
    cand_idx = np.flatnonzero(cand)
    if len(cand_idx):
        # Key: in-batch targets by creating event, durable by unique
        # target index (disjoint ranges via sign).
        tkey = np.where(
            in_batch[cand_idx], -(tgt_ev[cand_idx].astype(np.int64) + 1),
            p_tgt[cand_idx].astype(np.int64),
        )
        order = np.lexsort((cand_idx, tkey))
        sk = tkey[order]
        si = cand_idx[order]
        first = np.empty(len(sk), bool)
        first[0] = True
        first[1:] = sk[1:] != sk[:-1]
        winner[si[first]] = True
        if not first.all():
            bounds = np.flatnonzero(first)
            sizes = np.diff(np.append(bounds, len(sk)))
            win_rep = np.repeat(si[first], sizes)
            losers = si[~first]
            win_of_loser = win_rep[~first]
            code_out[losers] = np.where(
                post[win_of_loser],
                np.uint32(CTR.pending_transfer_already_posted),
                np.uint32(CTR.pending_transfer_already_voided),
            )

    ok = code_out == 0
    applied_any = np.flatnonzero(ok)
    last_applied = int(applied_any[-1]) if len(applied_any) else -1

    return {
        "results": code_out,
        "ok": ok,
        "winner": winner,
        "post": post,
        "pend_flag": pend_flag,
        "in_batch": in_batch,
        "durable": durable,
        "tgt_ev": tgt_ev,
        "res_amt_lo": res_amt_lo,
        "res_amt_hi": res_amt_hi,
        "p_amt_lo": p_amt_lo,
        "p_amt_hi": p_amt_hi,
        "p_ledger": p_ledger,
        "p_code": p_code,
        "p_ud128_lo": p_ud128_lo,
        "p_ud128_hi": p_ud128_hi,
        "p_ud64": p_ud64,
        "p_ud32": p_ud32,
        "last_applied": last_applied,
    }


def _apply(code_out: np.ndarray, cond: np.ndarray, code) -> None:
    np.copyto(code_out, np.uint32(code), where=(code_out == 0) & cond)


def spec_meta_from_events(ev: dict, n: int, pv_serial: bool) -> dict:
    """wave_dependency_metadata rebuilt from a (B,)-padded host event
    dict (kernel.EVENT_FIELDS contract) — the speculative dispatcher's
    residue planner runs at window LAUNCH, where the padded arrays are
    all that survives of the submit-time joins (the compact record
    keeps nothing else).  Bit-identical to building the metadata from
    the original join columns: every input below is the same value the
    submit path passed, just padded and round-tripped through the
    columnar codec (lossless)."""
    return wave_dependency_metadata(
        n,
        np.asarray(ev["flags"][:n], np.uint32),
        ev["dr_slot"][:n].astype(np.int64),
        ev["cr_slot"][:n].astype(np.int64),
        np.asarray(ev["dr_flags"][:n], np.uint32),
        np.asarray(ev["cr_flags"][:n], np.uint32),
        ev["id_group"][:n].astype(np.int64),
        ev["p_group"][:n].astype(np.int64),
        ev["p_tgt"][:n].astype(np.int64),
        np.asarray(ev["p_found"][:n], bool),
        ev["p_dr_slot"][:n].astype(np.int64),
        ev["p_cr_slot"][:n].astype(np.int64),
        pv_serial=pv_serial,
    )


def wave_dependency_metadata(
    n: int,
    flags: np.ndarray,
    dr_slot: np.ndarray,
    cr_slot: np.ndarray,
    dr_flags: np.ndarray,
    cr_flags: np.ndarray,
    id_group: np.ndarray,
    p_group: np.ndarray,
    p_tgt: np.ndarray,
    p_found: np.ndarray,
    p_dr_slot: np.ndarray,
    p_cr_slot: np.ndarray,
    pv_serial: bool = False,
) -> dict:
    """Per-event dependency metadata for the wave partitioner
    (waves.plan_waves).  Field contract:

    - ``chain_member``: event must run outside plain waves — a
      linked-chain member (rollback couples the chain, including the
      closing non-linked event), an event on a history-flag account
      (its balance snapshot feeds the history groove and must be
      per-event sequential, while wave snapshots are rewritten to
      batch finals), or any shape the wave step does not model
      (``pv_serial`` forces every post/void there, used when a pending
      target could sit on a history account).  ``chain_linked`` is the
      linked-run component alone and ``chain_serial`` the must-scan
      component (history / pv_serial): a chain run with no serial
      member is a CHAIN-WAVE candidate (waves.py runs its independent
      chains position-stepped instead of member-by-member).
    - ``is_pv``: post/void flag (the chain-wave admission declines
      runs containing finalizers).
    - ``id_group`` / ``p_group`` / ``p_tgt``: the exact-path compact
      reference tokens (tpu.py grouping); two events conflict when one
      claims a token the wave already holds.
    - ``writes0/1``: account slots whose balance columns the event's
      apply ADDS to (normal: its dr/cr; post/void: the durable
      target's accounts), -1 for none.  Commuting adds only conflict
      with READERS.
    - ``reads0/1``: slots whose current balance value the event's
      verdict or applied amount depends on (balancing clamps, limit
      checks, history snapshots), -1 for none.
    - ``inb_pv``: post/void naming an in-batch id — its write set
      statically widens to that id-group's slot union (``ev_dr`` /
      ``ev_cr`` feed the union).
    """
    TFv = np.uint32
    linked = (flags & TFv(TF.linked)) != 0
    is_pv = (
        flags & TFv(TF.post_pending_transfer | TF.void_pending_transfer)
    ) != 0
    # Linked-run membership alone: the chain-wave executor (waves.py)
    # can run these position-stepped when the run is otherwise clean.
    chain_linked = linked.copy()
    if n > 1:
        chain_linked[1:] |= linked[:-1]
    # Events that must run in an exact scan segment REGARDLESS of chain
    # structure: history-account snapshots are semantically read, and
    # pv_serial post/voids may target a history account.
    hist = ((dr_flags | cr_flags) & TFv(AF.history)) != 0
    chain_serial = hist & ~is_pv
    if pv_serial:
        chain_serial = chain_serial | is_pv
    chain_member = chain_linked | chain_serial

    bal_dr = (flags & TFv(TF.balancing_debit)) != 0
    bal_cr = (flags & TFv(TF.balancing_credit)) != 0
    # A balancing clamp reads the flagged side's whole row; a limit
    # flag makes the verdict read that account's row.
    read_dr = (
        (bal_dr | ((dr_flags & TFv(AF.debits_must_not_exceed_credits)) != 0))
        & (dr_slot >= 0) & ~is_pv
    )
    read_cr = (
        (bal_cr | ((cr_flags & TFv(AF.credits_must_not_exceed_debits)) != 0))
        & (cr_slot >= 0) & ~is_pv
    )

    neg = np.int64(-1)
    dr64 = dr_slot.astype(np.int64)
    cr64 = cr_slot.astype(np.int64)
    pdr64 = np.where(p_found, p_dr_slot.astype(np.int64), neg)
    pcr64 = np.where(p_found, p_cr_slot.astype(np.int64), neg)
    writes0 = np.where(is_pv, pdr64, np.where(dr_slot >= 0, dr64, neg))
    writes1 = np.where(is_pv, pcr64, np.where(cr_slot >= 0, cr64, neg))
    reads0 = np.where(read_dr, dr64, neg)
    reads1 = np.where(read_cr, cr64, neg)

    return {
        "chain_member": chain_member,
        "chain_linked": chain_linked,
        "chain_serial": chain_serial,
        "linked": linked,
        "is_pv": is_pv,
        "id_group": np.asarray(id_group, np.int64),
        "p_group": np.asarray(p_group, np.int64),
        "p_tgt": np.asarray(p_tgt, np.int64),
        "writes0": writes0,
        "writes1": writes1,
        "reads0": reads0,
        "reads1": reads1,
        "inb_pv": is_pv & (np.asarray(p_group) >= 0),
        "ev_dr": np.where(dr_slot >= 0, dr64, neg),
        "ev_cr": np.where(cr_slot >= 0, cr64, neg),
    }
