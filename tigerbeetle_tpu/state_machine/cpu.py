"""CPU reference ("oracle") accounting state machine.

Implements the exact commit semantics of the reference state machine
(reference: src/state_machine.zig) on plain Python data structures. This
is the parity oracle the TPU kernel is diffed against bit-for-bit, and
doubles as the executable specification of every result code.

Python ints model u128 exactly (masked where the reference wraps);
grooves are dict-backed with the same secondary indexes the LSM forest
maintains, and scoped rollback mirrors ``scope_open``/``scope_close``
(reference: src/lsm/tree.zig:202-222, src/state_machine.zig:1190-1218).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable

import numpy as np

from tigerbeetle_tpu import constants as cfg
from tigerbeetle_tpu import types
from tigerbeetle_tpu.types import (
    ACCOUNT_BALANCE_DTYPE,
    ACCOUNT_DTYPE,
    ACCOUNT_FILTER_DTYPE,
    CREATE_RESULT_DTYPE,
    NS_PER_S,
    TIMESTAMP_MAX,
    TIMESTAMP_MIN,
    TRANSFER_DTYPE,
    U64_MAX,
    U128_MAX,
    AccountFilterFlags,
    AccountFlags,
    CreateAccountResult,
    CreateTransferResult,
    Operation,
    TransferFlags,
    TransferPendingStatus,
)

AF = AccountFlags
TF = TransferFlags
CAR = CreateAccountResult
CTR = CreateTransferResult


@dataclasses.dataclass(slots=True)
class AccountRec:
    """In-memory Account (reference: src/tigerbeetle.zig:7-29)."""

    id: int = 0
    debits_pending: int = 0
    debits_posted: int = 0
    credits_pending: int = 0
    credits_posted: int = 0
    user_data_128: int = 0
    user_data_64: int = 0
    user_data_32: int = 0
    reserved: int = 0
    ledger: int = 0
    code: int = 0
    flags: int = 0
    timestamp: int = 0

    @classmethod
    def from_np(cls, row: np.void) -> "AccountRec":
        g = types.u128_get
        return cls(
            id=g(row, "id"),
            debits_pending=g(row, "debits_pending"),
            debits_posted=g(row, "debits_posted"),
            credits_pending=g(row, "credits_pending"),
            credits_posted=g(row, "credits_posted"),
            user_data_128=g(row, "user_data_128"),
            user_data_64=int(row["user_data_64"]),
            user_data_32=int(row["user_data_32"]),
            reserved=int(row["reserved"]),
            ledger=int(row["ledger"]),
            code=int(row["code"]),
            flags=int(row["flags"]),
            timestamp=int(row["timestamp"]),
        )

    def to_np(self, row: np.void) -> None:
        s = types.u128_set
        s(row, "id", self.id)
        s(row, "debits_pending", self.debits_pending)
        s(row, "debits_posted", self.debits_posted)
        s(row, "credits_pending", self.credits_pending)
        s(row, "credits_posted", self.credits_posted)
        s(row, "user_data_128", self.user_data_128)
        row["user_data_64"] = self.user_data_64
        row["user_data_32"] = self.user_data_32
        row["reserved"] = self.reserved
        row["ledger"] = self.ledger
        row["code"] = self.code
        row["flags"] = self.flags
        row["timestamp"] = self.timestamp

    def copy(self) -> "AccountRec":
        return dataclasses.replace(self)

    def debits_exceed_credits(self, amount: int) -> bool:
        # reference: src/tigerbeetle.zig:31-34
        return bool(self.flags & AF.debits_must_not_exceed_credits) and (
            self.debits_pending + self.debits_posted + amount > self.credits_posted
        )

    def credits_exceed_debits(self, amount: int) -> bool:
        # reference: src/tigerbeetle.zig:36-39
        return bool(self.flags & AF.credits_must_not_exceed_debits) and (
            self.credits_pending + self.credits_posted + amount > self.debits_posted
        )


@dataclasses.dataclass(slots=True)
class TransferRec:
    """In-memory Transfer (reference: src/tigerbeetle.zig:80-111)."""

    id: int = 0
    debit_account_id: int = 0
    credit_account_id: int = 0
    amount: int = 0
    pending_id: int = 0
    user_data_128: int = 0
    user_data_64: int = 0
    user_data_32: int = 0
    timeout: int = 0
    ledger: int = 0
    code: int = 0
    flags: int = 0
    timestamp: int = 0

    @classmethod
    def from_np(cls, row: np.void) -> "TransferRec":
        g = types.u128_get
        return cls(
            id=g(row, "id"),
            debit_account_id=g(row, "debit_account_id"),
            credit_account_id=g(row, "credit_account_id"),
            amount=g(row, "amount"),
            pending_id=g(row, "pending_id"),
            user_data_128=g(row, "user_data_128"),
            user_data_64=int(row["user_data_64"]),
            user_data_32=int(row["user_data_32"]),
            timeout=int(row["timeout"]),
            ledger=int(row["ledger"]),
            code=int(row["code"]),
            flags=int(row["flags"]),
            timestamp=int(row["timestamp"]),
        )

    def to_np(self, row: np.void) -> None:
        s = types.u128_set
        s(row, "id", self.id)
        s(row, "debit_account_id", self.debit_account_id)
        s(row, "credit_account_id", self.credit_account_id)
        s(row, "amount", self.amount)
        s(row, "pending_id", self.pending_id)
        s(row, "user_data_128", self.user_data_128)
        row["user_data_64"] = self.user_data_64
        row["user_data_32"] = self.user_data_32
        row["timeout"] = self.timeout
        row["ledger"] = self.ledger
        row["code"] = self.code
        row["flags"] = self.flags
        row["timestamp"] = self.timestamp

    def copy(self) -> "TransferRec":
        return dataclasses.replace(self)

    def timeout_ns(self) -> int:
        # reference: src/tigerbeetle.zig:101-104
        return self.timeout * NS_PER_S


@dataclasses.dataclass(slots=True)
class BalanceRec:
    """reference: src/state_machine.zig:296-315 (AccountBalancesGrooveValue)."""

    dr_account_id: int = 0
    dr_debits_pending: int = 0
    dr_debits_posted: int = 0
    dr_credits_pending: int = 0
    dr_credits_posted: int = 0
    cr_account_id: int = 0
    cr_debits_pending: int = 0
    cr_debits_posted: int = 0
    cr_credits_pending: int = 0
    cr_credits_posted: int = 0
    timestamp: int = 0


def sum_overflows(a: int, b: int, limit: int = U128_MAX) -> bool:
    # reference: src/state_machine.zig:2002-2007
    return a + b > limit


class UndoLog:
    """Command-log undo for scoped rollback.

    Every groove mutation made while a scope is open registers an
    inverse closure; ``scope_close(.discard)`` replays them in reverse
    (reference: src/lsm/groove.zig scope machinery).
    """

    def __init__(self) -> None:
        self._entries: list[Callable[[], None]] | None = None

    @property
    def active(self) -> bool:
        return self._entries is not None

    def record(self, inverse: Callable[[], None]) -> None:
        if self._entries is not None:
            self._entries.append(inverse)

    def open(self) -> None:
        assert self._entries is None
        self._entries = []

    def close(self, persist: bool) -> None:
        entries = self._entries
        assert entries is not None
        self._entries = None
        if not persist:
            for inverse in reversed(entries):
                inverse()


class CpuStateMachine:
    """Single-node oracle with the reference's commit-time semantics.

    Interface mirrors ``StateMachineType`` (reference:
    src/state_machine.zig:341-350,543,575,589,1107): ``input_valid``,
    ``prepare``, ``pulse_needed``, ``prefetch`` + ``commit``.
    """

    def __init__(self, config: cfg.Config = cfg.PRODUCTION) -> None:
        self.config = config
        self.prepare_timestamp = 0
        self.commit_timestamp = 0

        # Grooves (reference: src/state_machine.zig:178-324).
        self.accounts: dict[int, AccountRec] = {}
        self.accounts_by_timestamp: dict[int, int] = {}  # timestamp -> id
        self.transfers: dict[int, TransferRec] = {}
        self.transfers_by_timestamp: dict[int, int] = {}  # timestamp -> id
        # Secondary indexes used by queries: account id -> [timestamps].
        # Timestamps are assigned monotonically so appends keep order.
        self.transfers_by_dr: dict[int, list[int]] = {}
        self.transfers_by_cr: dict[int, list[int]] = {}
        # Derived index (reference: src/state_machine.zig:229-238):
        # set of (expires_at, pending_transfer_timestamp).
        self.expires_at_index: set[tuple[int, int]] = set()
        # reference: src/state_machine.zig:259-269
        self.transfers_pending: dict[int, TransferPendingStatus] = {}
        # timestamp -> BalanceRec (reference: src/state_machine.zig:296)
        self.account_balances: dict[int, BalanceRec] = {}

        self._undo = UndoLog()

        # reference: src/state_machine.zig:2058-2063
        self.pulse_next_timestamp = TIMESTAMP_MIN
        # Buffer filled by prefetch(pulse); consumed by commit(pulse).
        self._expiry_buffer: list[TransferRec] | None = None

    # ------------------------------------------------------------------
    # Introspection helpers shared with TpuStateMachine (tests use these
    # instead of reaching into either implementation's internals).

    def transfer_timestamp(self, id_value: int) -> int | None:
        t = self.transfers.get(id_value)
        return None if t is None else t.timestamp

    def pending_status(self, id_value: int) -> TransferPendingStatus | None:
        t = self.transfers.get(id_value)
        if t is None:
            return None
        return self.transfers_pending.get(t.timestamp)

    @property
    def history_count(self) -> int:
        return len(self.account_balances)

    def account_balances_raw(self, id_value: int) -> tuple | None:
        a = self.accounts.get(id_value)
        if a is None:
            return None
        return (a.debits_pending, a.debits_posted, a.credits_pending, a.credits_posted)

    def state_root(self) -> bytes:
        """16-byte state commitment of the account table — the same
        value TpuStateMachine.state_root reports for the same commit
        stream (commitment.py; row index = creation order, which is
        the TPU build's slot assignment).  Recomputed from scratch:
        the oracle optimizes for simplicity, not update cost."""
        from tigerbeetle_tpu.state_machine import commitment as cm

        n = len(self.accounts)
        bal8 = np.zeros((n, 8), np.uint64)
        meta = np.zeros((n, 2), np.uint32)
        mask = (1 << 64) - 1
        for i, a in enumerate(self.accounts.values()):
            for j, v in enumerate(
                (a.debits_pending, a.debits_posted,
                 a.credits_pending, a.credits_posted)
            ):
                bal8[i, 2 * j] = v & mask
                bal8[i, 2 * j + 1] = v >> 64
            meta[i, 0] = a.flags
            meta[i, 1] = a.ledger
        return cm.root_bytes(cm.table_digest(bal8, meta))

    # ------------------------------------------------------------------
    # Groove mutations (undo-aware).

    def _account_insert(self, a: AccountRec) -> None:
        key, ts = a.id, a.timestamp
        self.accounts[key] = a
        self.accounts_by_timestamp[ts] = key
        self._undo.record(
            lambda: (self.accounts.pop(key), self.accounts_by_timestamp.pop(ts))
        )

    def _account_update(self, new: AccountRec) -> None:
        key = new.id
        old = self.accounts[key]
        self.accounts[key] = new
        self._undo.record(lambda: self.accounts.__setitem__(key, old))

    def _transfer_insert(self, t: TransferRec) -> None:
        key, ts = t.id, t.timestamp
        self.transfers[key] = t
        self.transfers_by_timestamp[ts] = key
        self.transfers_by_dr.setdefault(t.debit_account_id, []).append(ts)
        self.transfers_by_cr.setdefault(t.credit_account_id, []).append(ts)

        def undo() -> None:
            self.transfers.pop(key)
            self.transfers_by_timestamp.pop(ts)
            self.transfers_by_dr[t.debit_account_id].pop()
            self.transfers_by_cr[t.credit_account_id].pop()

        self._undo.record(undo)
        # Derived expires_at index (reference: src/state_machine.zig:230-238).
        if (t.flags & TF.pending) and t.timeout > 0:
            self._expires_at_insert(t.timestamp + t.timeout_ns(), ts)

    def _expires_at_insert(self, expires_at: int, ts: int) -> None:
        entry = (expires_at, ts)
        self.expires_at_index.add(entry)
        self._undo.record(lambda: self.expires_at_index.discard(entry))

    def _expires_at_remove(self, expires_at: int, ts: int) -> None:
        entry = (expires_at, ts)
        assert entry in self.expires_at_index
        self.expires_at_index.remove(entry)
        self._undo.record(lambda: self.expires_at_index.add(entry))

    def _pending_insert(self, ts: int, status: TransferPendingStatus) -> None:
        self.transfers_pending[ts] = status
        self._undo.record(lambda: self.transfers_pending.pop(ts))

    def _pending_update(self, ts: int, status: TransferPendingStatus) -> None:
        old = self.transfers_pending[ts]
        assert old == TransferPendingStatus.pending
        assert status not in (TransferPendingStatus.none, TransferPendingStatus.pending)
        self.transfers_pending[ts] = status
        self._undo.record(lambda: self.transfers_pending.__setitem__(ts, old))

    def _balance_insert(self, b: BalanceRec) -> None:
        ts = b.timestamp
        self.account_balances[ts] = b
        self._undo.record(lambda: self.account_balances.pop(ts))

    # ------------------------------------------------------------------
    # Operation plumbing (reference: src/state_machine.zig:543-596).

    def input_valid(self, operation: Operation, input_bytes: bytes) -> bool:
        # reference: src/state_machine.zig:543-572
        if operation == Operation.pulse:
            return len(input_bytes) == 0
        if operation in (Operation.get_account_transfers, Operation.get_account_balances):
            return len(input_bytes) == ACCOUNT_FILTER_DTYPE.itemsize
        event_size = types.EVENT_DTYPE[operation].itemsize
        batch_max = self.config.batch_max(
            event_size, types.RESULT_DTYPE[operation].itemsize
        )
        if len(input_bytes) % event_size != 0:
            return False
        if len(input_bytes) > batch_max * event_size:
            return False
        return True

    def prepare(self, operation: Operation, input_bytes: bytes) -> None:
        # reference: src/state_machine.zig:575-587
        assert self.input_valid(operation, input_bytes)
        if operation in (Operation.create_accounts, Operation.create_transfers):
            event_size = types.EVENT_DTYPE[operation].itemsize
            self.prepare_timestamp += len(input_bytes) // event_size

    def pulse_needed(self) -> bool:
        # reference: src/state_machine.zig:589-596
        return self.pulse_next_timestamp <= self.prepare_timestamp

    def prefetch(
        self, operation: Operation, input_bytes: bytes, prefetch_timestamp: int
    ) -> None:
        """Synchronous equivalent of the async prefetch chain.

        Only the pulse path has observable state here: the expiry scan
        (reference: src/state_machine.zig:1010-1060) snapshots the
        expired-transfer batch and updates ``pulse_next_timestamp``.
        """
        if operation == Operation.pulse:
            assert len(input_bytes) == 0
            self._expiry_buffer = self._scan_expired(prefetch_timestamp)

    def _scan_expired(self, expires_at_max: int) -> list[TransferRec]:
        # reference: src/state_machine.zig:2071-2145 (ExpirePendingTransfers)
        limit = self.config.batch_max_create_transfers
        ordered = sorted(self.expires_at_index)
        results: list[TransferRec] = []
        value_next_expired_at: int | None = None
        buffer_finished = False
        for expires_at, ts in ordered:
            value_next_expired_at = expires_at
            if expires_at <= expires_at_max:
                if len(results) == limit:
                    buffer_finished = True
                    break
                results.append(self.transfers[self.transfers_by_timestamp[ts]])
            else:
                break  # exclude_and_stop (reference: :2162-2165)
        # finish() (reference: src/state_machine.zig:2112-2145)
        if buffer_finished:
            self.pulse_next_timestamp = value_next_expired_at
        else:
            if value_next_expired_at is None or value_next_expired_at <= expires_at_max:
                self.pulse_next_timestamp = TIMESTAMP_MAX
            else:
                self.pulse_next_timestamp = value_next_expired_at
        return results

    # Read-only operations a follower may answer out of band (the
    # shared definition lives in types.READ_OPERATIONS) — every one
    # dispatches to a pure executor below (no timestamp advance, no
    # expiry scan, no mutation), so serving them outside the commit
    # stream cannot perturb replayed state.
    READ_OPERATIONS = types.READ_OPERATIONS

    def execute_read(self, operation: Operation, input_bytes: bytes) -> bytes:
        """Serve a read WITHOUT committing it (round 19, the follower
        read path): byte-identical to what commit() would reply for
        the same operation at the current state, but with zero state
        effects — commit_timestamp, pulse scheduling, and the history
        tables are untouched, so interleaved replay stays bit-exact."""
        operation = Operation(operation)
        assert operation in self.READ_OPERATIONS, operation
        assert self.input_valid(operation, input_bytes)
        if operation == Operation.lookup_accounts:
            return self._execute_lookup_accounts(input_bytes)
        if operation == Operation.lookup_transfers:
            return self._execute_lookup_transfers(input_bytes)
        if operation == Operation.get_account_transfers:
            return self._execute_get_account_transfers(input_bytes)
        return self._execute_get_account_balances(input_bytes)

    def commit(
        self,
        client: int,
        op: int,
        timestamp: int,
        operation: Operation,
        input_bytes: bytes,
    ) -> bytes:
        # reference: src/state_machine.zig:1107-1146
        assert op != 0
        assert self.input_valid(operation, input_bytes)
        assert timestamp > self.commit_timestamp

        if operation == Operation.pulse:
            return self._execute_expire_pending_transfers(timestamp)
        if operation == Operation.create_accounts:
            return self._execute_create(Operation.create_accounts, timestamp, input_bytes)
        if operation == Operation.create_transfers:
            return self._execute_create(Operation.create_transfers, timestamp, input_bytes)
        if operation == Operation.lookup_accounts:
            return self._execute_lookup_accounts(input_bytes)
        if operation == Operation.lookup_transfers:
            return self._execute_lookup_transfers(input_bytes)
        if operation == Operation.get_account_transfers:
            return self._execute_get_account_transfers(input_bytes)
        if operation == Operation.get_account_balances:
            return self._execute_get_account_balances(input_bytes)
        raise AssertionError(operation)

    # ------------------------------------------------------------------
    # execute() — the chain/rollback loop (reference: src/state_machine.zig:1220-1306).

    def _execute_create(
        self, operation: Operation, timestamp: int, input_bytes: bytes
    ) -> bytes:
        dtype = (
            ACCOUNT_DTYPE
            if operation == Operation.create_accounts
            else TRANSFER_DTYPE
        )
        events = np.frombuffer(input_bytes, dtype=dtype)
        n = len(events)
        results: list[tuple[int, int]] = []

        chain: int | None = None
        chain_broken = False

        for index in range(n):
            if operation == Operation.create_accounts:
                event: AccountRec | TransferRec = AccountRec.from_np(events[index])
                linked = bool(event.flags & AF.linked)
            else:
                event = TransferRec.from_np(events[index])
                linked = bool(event.flags & TF.linked)

            result: int | None = None
            if linked:
                if chain is None:
                    chain = index
                    assert not chain_broken
                    self._undo.open()
                if index == n - 1:
                    result = CTR.linked_event_chain_open  # same value for accounts

            if result is None and chain_broken:
                result = CTR.linked_event_failed
            if result is None and event.timestamp != 0:
                result = CTR.timestamp_must_be_zero

            if result is None:
                event.timestamp = timestamp - n + index + 1
                if operation == Operation.create_accounts:
                    result = self._create_account(event)
                else:
                    result = self._create_transfer(event)

            if result != 0:
                if chain is not None:
                    if not chain_broken:
                        chain_broken = True
                        self._undo.close(persist=False)
                        # FIFO error emission for rolled-back events
                        # (reference: src/state_machine.zig:1276-1284).
                        for chain_index in range(chain, index):
                            results.append((chain_index, CTR.linked_event_failed))
                    else:
                        assert result in (
                            CTR.linked_event_failed,
                            CTR.linked_event_chain_open,
                        )
                results.append((index, int(result)))

            if chain is not None and (
                not linked or result == CTR.linked_event_chain_open
            ):
                if not chain_broken:
                    self._undo.close(persist=True)
                chain = None
                chain_broken = False

        assert chain is None
        assert not chain_broken

        out = np.zeros(len(results), dtype=CREATE_RESULT_DTYPE)
        for i, (index, result) in enumerate(results):
            out[i]["index"] = index
            out[i]["result"] = result
        return out.tobytes()

    # ------------------------------------------------------------------
    # create_account (reference: src/state_machine.zig:1421-1459).

    def _create_account(self, a: AccountRec) -> CAR:
        assert a.timestamp > self.commit_timestamp

        if a.reserved != 0:
            return CAR.reserved_field
        if a.flags & ~int(AF._valid_mask):
            return CAR.reserved_flag
        if a.id == 0:
            return CAR.id_must_not_be_zero
        if a.id == U128_MAX:
            return CAR.id_must_not_be_int_max
        if (a.flags & AF.debits_must_not_exceed_credits) and (
            a.flags & AF.credits_must_not_exceed_debits
        ):
            return CAR.flags_are_mutually_exclusive
        if a.debits_pending != 0:
            return CAR.debits_pending_must_be_zero
        if a.debits_posted != 0:
            return CAR.debits_posted_must_be_zero
        if a.credits_pending != 0:
            return CAR.credits_pending_must_be_zero
        if a.credits_posted != 0:
            return CAR.credits_posted_must_be_zero
        if a.ledger == 0:
            return CAR.ledger_must_not_be_zero
        if a.code == 0:
            return CAR.code_must_not_be_zero

        e = self.accounts.get(a.id)
        if e is not None:
            return self._create_account_exists(a, e)

        self._account_insert(a)
        self.commit_timestamp = a.timestamp
        return CAR.ok

    @staticmethod
    def _create_account_exists(a: AccountRec, e: AccountRec) -> CAR:
        # reference: src/state_machine.zig:1450-1460
        assert a.id == e.id
        if a.flags != e.flags:
            return CAR.exists_with_different_flags
        if a.user_data_128 != e.user_data_128:
            return CAR.exists_with_different_user_data_128
        if a.user_data_64 != e.user_data_64:
            return CAR.exists_with_different_user_data_64
        if a.user_data_32 != e.user_data_32:
            return CAR.exists_with_different_user_data_32
        if a.ledger != e.ledger:
            return CAR.exists_with_different_ledger
        if a.code != e.code:
            return CAR.exists_with_different_code
        return CAR.exists

    # ------------------------------------------------------------------
    # create_transfer (reference: src/state_machine.zig:1462-1585).

    def _create_transfer(self, t: TransferRec) -> CTR:
        assert t.timestamp > self.commit_timestamp

        if t.flags & ~int(TF._valid_mask):
            return CTR.reserved_flag
        if t.id == 0:
            return CTR.id_must_not_be_zero
        if t.id == U128_MAX:
            return CTR.id_must_not_be_int_max

        if t.flags & (TF.post_pending_transfer | TF.void_pending_transfer):
            return self._post_or_void_pending_transfer(t)

        if t.debit_account_id == 0:
            return CTR.debit_account_id_must_not_be_zero
        if t.debit_account_id == U128_MAX:
            return CTR.debit_account_id_must_not_be_int_max
        if t.credit_account_id == 0:
            return CTR.credit_account_id_must_not_be_zero
        if t.credit_account_id == U128_MAX:
            return CTR.credit_account_id_must_not_be_int_max
        if t.credit_account_id == t.debit_account_id:
            return CTR.accounts_must_be_different

        if t.pending_id != 0:
            return CTR.pending_id_must_be_zero
        if not (t.flags & TF.pending):
            if t.timeout != 0:
                return CTR.timeout_reserved_for_pending_transfer
        if not (t.flags & (TF.balancing_debit | TF.balancing_credit)):
            if t.amount == 0:
                return CTR.amount_must_not_be_zero

        if t.ledger == 0:
            return CTR.ledger_must_not_be_zero
        if t.code == 0:
            return CTR.code_must_not_be_zero

        dr_account = self.accounts.get(t.debit_account_id)
        if dr_account is None:
            return CTR.debit_account_not_found
        cr_account = self.accounts.get(t.credit_account_id)
        if cr_account is None:
            return CTR.credit_account_not_found
        assert t.timestamp > dr_account.timestamp
        assert t.timestamp > cr_account.timestamp

        if dr_account.ledger != cr_account.ledger:
            return CTR.accounts_must_have_the_same_ledger
        if t.ledger != dr_account.ledger:
            return CTR.transfer_must_have_the_same_ledger_as_accounts

        # Existing transfers must not influence overflow/limit checks
        # (reference: src/state_machine.zig:1506-1507) — note the raw
        # (unclamped) t.amount is compared here.
        e = self.transfers.get(t.id)
        if e is not None:
            return self._create_transfer_exists(t, e)

        # Balancing clamp (reference: src/state_machine.zig:1509-1529).
        amount = t.amount
        if t.flags & (TF.balancing_debit | TF.balancing_credit):
            if amount == 0:
                amount = U64_MAX  # reference uses maxInt(u64) here
        else:
            assert amount != 0
        if t.flags & TF.balancing_debit:
            dr_balance = dr_account.debits_posted + dr_account.debits_pending
            amount = min(amount, max(0, dr_account.credits_posted - dr_balance))
            if amount == 0:
                return CTR.exceeds_credits
        if t.flags & TF.balancing_credit:
            cr_balance = cr_account.credits_posted + cr_account.credits_pending
            amount = min(amount, max(0, cr_account.debits_posted - cr_balance))
            if amount == 0:
                return CTR.exceeds_debits

        # Overflow ladder (reference: src/state_machine.zig:1531-1545).
        if t.flags & TF.pending:
            if sum_overflows(amount, dr_account.debits_pending):
                return CTR.overflows_debits_pending
            if sum_overflows(amount, cr_account.credits_pending):
                return CTR.overflows_credits_pending
        if sum_overflows(amount, dr_account.debits_posted):
            return CTR.overflows_debits_posted
        if sum_overflows(amount, cr_account.credits_posted):
            return CTR.overflows_credits_posted
        if sum_overflows(amount, dr_account.debits_pending + dr_account.debits_posted):
            return CTR.overflows_debits
        if sum_overflows(amount, cr_account.credits_pending + cr_account.credits_posted):
            return CTR.overflows_credits

        if sum_overflows(t.timestamp, t.timeout * NS_PER_S, U64_MAX):
            return CTR.overflows_timeout

        if dr_account.debits_exceed_credits(amount):
            return CTR.exceeds_credits
        if cr_account.credits_exceed_debits(amount):
            return CTR.exceeds_debits

        # Apply (reference: src/state_machine.zig:1549-1585).
        t2 = t.copy()
        t2.amount = amount
        self._transfer_insert(t2)

        dr_new = dr_account.copy()
        cr_new = cr_account.copy()
        if t.flags & TF.pending:
            dr_new.debits_pending += amount
            cr_new.credits_pending += amount
            self._pending_insert(t2.timestamp, TransferPendingStatus.pending)
        else:
            dr_new.debits_posted += amount
            cr_new.credits_posted += amount
        self._account_update(dr_new)
        self._account_update(cr_new)

        self._historical_balance(t2, dr_new, cr_new)

        if t.timeout > 0:
            expires_at = t.timestamp + t.timeout_ns()
            if expires_at < self.pulse_next_timestamp:
                self.pulse_next_timestamp = expires_at

        self.commit_timestamp = t.timestamp
        return CTR.ok

    @staticmethod
    def _create_transfer_exists(t: TransferRec, e: TransferRec) -> CTR:
        # reference: src/state_machine.zig:1587-1606
        assert t.id == e.id
        if t.flags != e.flags:
            return CTR.exists_with_different_flags
        if t.debit_account_id != e.debit_account_id:
            return CTR.exists_with_different_debit_account_id
        if t.credit_account_id != e.credit_account_id:
            return CTR.exists_with_different_credit_account_id
        if t.amount != e.amount:
            return CTR.exists_with_different_amount
        assert t.pending_id == 0 and e.pending_id == 0
        if t.user_data_128 != e.user_data_128:
            return CTR.exists_with_different_user_data_128
        if t.user_data_64 != e.user_data_64:
            return CTR.exists_with_different_user_data_64
        if t.user_data_32 != e.user_data_32:
            return CTR.exists_with_different_user_data_32
        if t.timeout != e.timeout:
            return CTR.exists_with_different_timeout
        assert t.ledger == e.ledger
        if t.code != e.code:
            return CTR.exists_with_different_code
        return CTR.exists

    # ------------------------------------------------------------------
    # Two-phase post/void (reference: src/state_machine.zig:1608-1741).

    def _post_or_void_pending_transfer(self, t: TransferRec) -> CTR:
        assert t.id != 0
        assert t.timestamp > self.commit_timestamp
        post = bool(t.flags & TF.post_pending_transfer)
        void = bool(t.flags & TF.void_pending_transfer)
        assert post or void

        if post and void:
            return CTR.flags_are_mutually_exclusive
        if t.flags & TF.pending:
            return CTR.flags_are_mutually_exclusive
        if t.flags & TF.balancing_debit:
            return CTR.flags_are_mutually_exclusive
        if t.flags & TF.balancing_credit:
            return CTR.flags_are_mutually_exclusive

        if t.pending_id == 0:
            return CTR.pending_id_must_not_be_zero
        if t.pending_id == U128_MAX:
            return CTR.pending_id_must_not_be_int_max
        if t.pending_id == t.id:
            return CTR.pending_id_must_be_different
        if t.timeout != 0:
            return CTR.timeout_reserved_for_pending_transfer

        p = self.transfers.get(t.pending_id)
        if p is None:
            return CTR.pending_transfer_not_found
        assert p.timestamp < t.timestamp
        if not (p.flags & TF.pending):
            return CTR.pending_transfer_not_pending

        dr_account = self.accounts[p.debit_account_id]
        cr_account = self.accounts[p.credit_account_id]
        assert p.amount > 0

        if t.debit_account_id > 0 and t.debit_account_id != p.debit_account_id:
            return CTR.pending_transfer_has_different_debit_account_id
        if t.credit_account_id > 0 and t.credit_account_id != p.credit_account_id:
            return CTR.pending_transfer_has_different_credit_account_id
        if t.ledger > 0 and t.ledger != p.ledger:
            return CTR.pending_transfer_has_different_ledger
        if t.code > 0 and t.code != p.code:
            return CTR.pending_transfer_has_different_code

        amount = t.amount if t.amount > 0 else p.amount
        if amount > p.amount:
            return CTR.exceeds_pending_transfer_amount
        if void and amount < p.amount:
            return CTR.pending_transfer_has_different_amount

        e = self.transfers.get(t.id)
        if e is not None:
            return self._post_or_void_pending_transfer_exists(t, e, p)

        status = self.transfers_pending[p.timestamp]
        if status == TransferPendingStatus.posted:
            return CTR.pending_transfer_already_posted
        if status == TransferPendingStatus.voided:
            return CTR.pending_transfer_already_voided
        if status == TransferPendingStatus.expired:
            assert p.timeout > 0
            assert t.timestamp >= p.timestamp + p.timeout_ns()
            return CTR.pending_transfer_expired
        assert status == TransferPendingStatus.pending

        t2 = TransferRec(
            id=t.id,
            debit_account_id=p.debit_account_id,
            credit_account_id=p.credit_account_id,
            user_data_128=t.user_data_128 if t.user_data_128 > 0 else p.user_data_128,
            user_data_64=t.user_data_64 if t.user_data_64 > 0 else p.user_data_64,
            user_data_32=t.user_data_32 if t.user_data_32 > 0 else p.user_data_32,
            ledger=p.ledger,
            code=p.code,
            pending_id=t.pending_id,
            timeout=0,
            timestamp=t.timestamp,
            flags=t.flags,
            amount=amount,
        )
        self._transfer_insert(t2)

        if p.timeout > 0:
            expires_at = p.timestamp + p.timeout_ns()
            if expires_at <= t.timestamp:
                # QUIRK preserved from the reference: t2 was already
                # inserted above, and this error return leaks it outside
                # a linked chain (reference: src/state_machine.zig:1687-1696).
                return CTR.pending_transfer_expired
            self._expires_at_remove(expires_at, p.timestamp)
            # reference: src/state_machine.zig:1704-1708
            if self.pulse_next_timestamp == expires_at:
                self.pulse_next_timestamp = TIMESTAMP_MIN

        self._pending_update(
            p.timestamp,
            TransferPendingStatus.posted if post else TransferPendingStatus.voided,
        )

        dr_new = dr_account.copy()
        cr_new = cr_account.copy()
        dr_new.debits_pending -= p.amount
        cr_new.credits_pending -= p.amount
        assert dr_new.debits_pending >= 0
        assert cr_new.credits_pending >= 0
        if post:
            assert 0 < amount <= p.amount
            dr_new.debits_posted += amount
            cr_new.credits_posted += amount
        self._account_update(dr_new)
        self._account_update(cr_new)

        self._historical_balance(t2, dr_new, cr_new)

        self.commit_timestamp = t.timestamp
        return CTR.ok

    @staticmethod
    def _post_or_void_pending_transfer_exists(
        t: TransferRec, e: TransferRec, p: TransferRec
    ) -> CTR:
        # reference: src/state_machine.zig:1743-1804
        assert t.id == e.id
        assert t.id != p.id
        assert t.pending_id == p.id

        if t.flags != e.flags:
            return CTR.exists_with_different_flags
        if t.amount == 0:
            if e.amount != p.amount:
                return CTR.exists_with_different_amount
        else:
            if t.amount != e.amount:
                return CTR.exists_with_different_amount
        if t.pending_id != e.pending_id:
            return CTR.exists_with_different_pending_id

        if t.user_data_128 == 0:
            if e.user_data_128 != p.user_data_128:
                return CTR.exists_with_different_user_data_128
        else:
            if t.user_data_128 != e.user_data_128:
                return CTR.exists_with_different_user_data_128
        if t.user_data_64 == 0:
            if e.user_data_64 != p.user_data_64:
                return CTR.exists_with_different_user_data_64
        else:
            if t.user_data_64 != e.user_data_64:
                return CTR.exists_with_different_user_data_64
        if t.user_data_32 == 0:
            if e.user_data_32 != p.user_data_32:
                return CTR.exists_with_different_user_data_32
        else:
            if t.user_data_32 != e.user_data_32:
                return CTR.exists_with_different_user_data_32
        return CTR.exists

    # ------------------------------------------------------------------
    # Historical balances (reference: src/state_machine.zig:1806-1841).

    def _historical_balance(
        self, transfer: TransferRec, dr: AccountRec, cr: AccountRec
    ) -> None:
        assert transfer.timestamp > 0
        assert transfer.debit_account_id == dr.id
        assert transfer.credit_account_id == cr.id
        if (dr.flags & AF.history) or (cr.flags & AF.history):
            b = BalanceRec(timestamp=transfer.timestamp)
            if dr.flags & AF.history:
                b.dr_account_id = dr.id
                b.dr_debits_pending = dr.debits_pending
                b.dr_debits_posted = dr.debits_posted
                b.dr_credits_pending = dr.credits_pending
                b.dr_credits_posted = dr.credits_posted
            if cr.flags & AF.history:
                b.cr_account_id = cr.id
                b.cr_debits_pending = cr.debits_pending
                b.cr_debits_posted = cr.debits_posted
                b.cr_credits_pending = cr.credits_pending
                b.cr_credits_posted = cr.credits_posted
            self._balance_insert(b)

    # ------------------------------------------------------------------
    # Expiry (reference: src/state_machine.zig:1874-1929).

    def _execute_expire_pending_transfers(self, timestamp: int) -> bytes:
        assert self._expiry_buffer is not None
        transfers, self._expiry_buffer = self._expiry_buffer, None

        for expired in transfers:
            assert expired.flags & TF.pending
            assert expired.timeout > 0
            assert expired.amount > 0
            expires_at = expired.timestamp + expired.timeout_ns()
            assert expires_at <= timestamp

            dr_account = self.accounts[expired.debit_account_id]
            cr_account = self.accounts[expired.credit_account_id]
            assert dr_account.debits_pending >= expired.amount
            assert cr_account.credits_pending >= expired.amount

            dr_new = dr_account.copy()
            cr_new = cr_account.copy()
            dr_new.debits_pending -= expired.amount
            cr_new.credits_pending -= expired.amount
            self._account_update(dr_new)
            self._account_update(cr_new)

            assert self.transfers_pending[expired.timestamp] == TransferPendingStatus.pending
            self._pending_update(expired.timestamp, TransferPendingStatus.expired)

            self._expires_at_remove(expires_at, expired.timestamp)

        return b""

    # ------------------------------------------------------------------
    # Lookups (reference: src/state_machine.zig:1309-1344).

    def _execute_lookup_accounts(self, input_bytes: bytes) -> bytes:
        ids = np.frombuffer(input_bytes, dtype=types.U128_PAIR_DTYPE)
        out = np.zeros(len(ids), dtype=ACCOUNT_DTYPE)
        count = 0
        for row in ids:
            account = self.accounts.get(int(row["lo"]) | (int(row["hi"]) << 64))
            if account is not None:
                account.to_np(out[count])
                count += 1
        return out[:count].tobytes()

    def _execute_lookup_transfers(self, input_bytes: bytes) -> bytes:
        ids = np.frombuffer(input_bytes, dtype=types.U128_PAIR_DTYPE)
        out = np.zeros(len(ids), dtype=TRANSFER_DTYPE)
        count = 0
        for row in ids:
            transfer = self.transfers.get(int(row["lo"]) | (int(row["hi"]) << 64))
            if transfer is not None:
                transfer.to_np(out[count])
                count += 1
        return out[:count].tobytes()

    # ------------------------------------------------------------------
    # Index-scan queries (reference: src/state_machine.zig:786-1008,1346-1419).

    def _filter_scan(self, filter_row: np.void) -> list[int] | None:
        """Validated filter -> ordered transfer timestamps, else None.

        reference: src/state_machine.zig:931-996 (get_scan_from_filter).
        """
        account_id = types.u128_get(filter_row, "account_id")
        ts_min = int(filter_row["timestamp_min"])
        ts_max = int(filter_row["timestamp_max"])
        limit = int(filter_row["limit"])
        flags = int(filter_row["flags"])
        reserved = bytes(filter_row["reserved"])

        valid = (
            account_id != 0
            and account_id != U128_MAX
            and ts_min != U64_MAX
            and ts_max != U64_MAX
            and (ts_max == 0 or ts_min <= ts_max)
            and limit != 0
            and (flags & (AccountFilterFlags.debits | AccountFilterFlags.credits))
            and not (flags & ~int(AccountFilterFlags._valid_mask))
            and reserved == b"\x00" * 24
        )
        if not valid:
            return None

        lo = TIMESTAMP_MIN if ts_min == 0 else ts_min
        hi = TIMESTAMP_MAX if ts_max == 0 else ts_max

        timestamps: list[int] = []
        if flags & AccountFilterFlags.debits:
            timestamps += [
                t for t in self.transfers_by_dr.get(account_id, []) if lo <= t <= hi
            ]
        if flags & AccountFilterFlags.credits:
            timestamps += [
                t for t in self.transfers_by_cr.get(account_id, []) if lo <= t <= hi
            ]
        timestamps.sort()
        if flags & AccountFilterFlags.reversed:
            timestamps.reverse()
        return timestamps

    def _execute_get_account_transfers(self, input_bytes: bytes) -> bytes:
        filter_row = np.frombuffer(input_bytes, dtype=ACCOUNT_FILTER_DTYPE)[0]
        timestamps = self._filter_scan(filter_row)
        if timestamps is None:
            return b""
        batch_max = self.config.batch_max(
            ACCOUNT_FILTER_DTYPE.itemsize, TRANSFER_DTYPE.itemsize
        )
        limit = min(int(filter_row["limit"]), batch_max)
        timestamps = timestamps[:limit]
        out = np.zeros(len(timestamps), dtype=TRANSFER_DTYPE)
        for i, ts in enumerate(timestamps):
            self.transfers[self.transfers_by_timestamp[ts]].to_np(out[i])
        return out.tobytes()

    def _execute_get_account_balances(self, input_bytes: bytes) -> bytes:
        filter_row = np.frombuffer(input_bytes, dtype=ACCOUNT_FILTER_DTYPE)[0]
        account_id = types.u128_get(filter_row, "account_id")
        account = self.accounts.get(account_id)
        # reference: src/state_machine.zig:858-902 — account must exist
        # and carry flags.history for the scan to run at all.
        if account is None or not (account.flags & AF.history):
            return b""
        timestamps = self._filter_scan(filter_row)
        if timestamps is None:
            return b""
        batch_max = self.config.batch_max(
            ACCOUNT_FILTER_DTYPE.itemsize, ACCOUNT_BALANCE_DTYPE.itemsize
        )
        limit = min(int(filter_row["limit"]), batch_max)
        timestamps = timestamps[:limit]

        out = np.zeros(len(timestamps), dtype=ACCOUNT_BALANCE_DTYPE)
        count = 0
        for ts in timestamps:
            b = self.account_balances[ts]
            row = out[count]
            if account_id == b.dr_account_id:
                types.u128_set(row, "debits_pending", b.dr_debits_pending)
                types.u128_set(row, "debits_posted", b.dr_debits_posted)
                types.u128_set(row, "credits_pending", b.dr_credits_pending)
                types.u128_set(row, "credits_posted", b.dr_credits_posted)
            elif account_id == b.cr_account_id:
                types.u128_set(row, "debits_pending", b.cr_debits_pending)
                types.u128_set(row, "debits_posted", b.cr_debits_posted)
                types.u128_set(row, "credits_pending", b.cr_credits_pending)
                types.u128_set(row, "credits_posted", b.cr_credits_posted)
            else:
                raise AssertionError("scan returned non-history transfer")
            row["timestamp"] = ts
            count += 1
        return out[:count].tobytes()

    # ------------------------------------------------------------------
    # Checkpoint snapshot (consumed by vsr.checkpointing).

    # prepare_timestamp is primary-only in-memory state (re-derived from
    # commit_timestamp on the next prepare), so it is NOT part of the
    # snapshot — backups never advance it and must still converge.
    # accounts_by_timestamp / transfers_by_timestamp are derived from
    # the row sets and rebuilt on restore.

    def snapshot(self) -> bytes:
        """Serialize all durable state to the fixed-layout binary
        snapshot codec (utils/snapshot.py) — NOT pickle (checkpoint
        blobs travel via state sync; decoding must be safe on
        untrusted bytes and stable across versions).

        Canonical: dict iteration order is commit-replay order, which
        is identical across replicas with identical op streams, and
        the set-backed expiry index is sorted — so equal states give
        byte-equal snapshots (the convergence checkers rely on it).
        """
        from tigerbeetle_tpu.utils import snapshot as snapcodec

        def rows_u8(recs, dtype):
            arr = np.zeros(len(recs), dtype=dtype)
            for i, rec in enumerate(recs):
                rec.to_np(arr[i])
            return arr.view(np.uint8).reshape(len(recs), dtype.itemsize)

        def u128_pairs(values):
            arr = np.zeros((len(values), 2), np.uint64)
            for i, v in enumerate(values):
                arr[i, 0] = v & U64_MAX
                arr[i, 1] = v >> 64
            return arr

        def csr(index: dict[int, list[int]]):
            keys = u128_pairs(list(index))
            lens = np.array([len(v) for v in index.values()], np.uint64)
            flat = np.array(
                [ts for v in index.values() for ts in v], np.uint64
            )
            return {"keys": keys, "lens": lens, "values": flat}

        exp = sorted(self.expires_at_index)
        bal = self.account_balances
        state = {
            "commit_timestamp": self.commit_timestamp,
            "pulse_next_timestamp": self.pulse_next_timestamp,
            "accounts": rows_u8(list(self.accounts.values()), ACCOUNT_DTYPE),
            "transfers": rows_u8(
                list(self.transfers.values()), TRANSFER_DTYPE
            ),
            "by_dr": csr(self.transfers_by_dr),
            "by_cr": csr(self.transfers_by_cr),
            "expires_at": np.array(exp, np.uint64).reshape(len(exp), 2),
            "pending_ts": np.array(list(self.transfers_pending), np.uint64),
            "pending_status": np.array(
                [int(s) for s in self.transfers_pending.values()], np.uint8
            ),
            "balances_ts": np.array(list(bal), np.uint64),
            "balances": {
                f: u128_pairs([getattr(b, f) for b in bal.values()])
                for f in (
                    "dr_account_id", "dr_debits_pending", "dr_debits_posted",
                    "dr_credits_pending", "dr_credits_posted",
                    "cr_account_id", "cr_debits_pending", "cr_debits_posted",
                    "cr_credits_pending", "cr_credits_posted",
                )
            },
        }
        return snapcodec.encode_tree(state)

    def restore(self, data: bytes) -> None:
        from tigerbeetle_tpu.utils import snapshot as snapcodec

        state = snapcodec.decode_tree(data)
        self.commit_timestamp = state["commit_timestamp"]
        self.pulse_next_timestamp = state["pulse_next_timestamp"]

        def recs_of(u8, dtype, cls):
            rows = np.ascontiguousarray(u8).view(dtype).reshape(-1)
            return [cls.from_np(rows[i]) for i in range(len(rows))]

        def uncsr(node) -> dict[int, list[int]]:
            out: dict[int, list[int]] = {}
            at = 0
            for i in range(len(node["keys"])):
                key = int(node["keys"][i, 0]) | (int(node["keys"][i, 1]) << 64)
                n = int(node["lens"][i])
                out[key] = [int(t) for t in node["values"][at : at + n]]
                at += n
            return out

        accounts = recs_of(state["accounts"], ACCOUNT_DTYPE, AccountRec)
        self.accounts = {a.id: a for a in accounts}
        self.accounts_by_timestamp = {a.timestamp: a.id for a in accounts}
        transfers = recs_of(state["transfers"], TRANSFER_DTYPE, TransferRec)
        self.transfers = {t.id: t for t in transfers}
        self.transfers_by_timestamp = {t.timestamp: t.id for t in transfers}
        self.transfers_by_dr = uncsr(state["by_dr"])
        self.transfers_by_cr = uncsr(state["by_cr"])
        self.expires_at_index = {
            (int(r[0]), int(r[1])) for r in state["expires_at"]
        }
        self.transfers_pending = {
            int(ts): TransferPendingStatus(int(s))
            for ts, s in zip(state["pending_ts"], state["pending_status"])
        }
        bal_fields = list(state["balances"])
        self.account_balances = {}
        for i, ts in enumerate(state["balances_ts"]):
            rec = BalanceRec(timestamp=int(ts))
            for f in bal_fields:
                pair = state["balances"][f][i]
                setattr(rec, f, int(pair[0]) | (int(pair[1]) << 64))
            self.account_balances[int(ts)] = rec
        self.prepare_timestamp = self.commit_timestamp
        self._undo = UndoLog()
        self._expiry_buffer = None
